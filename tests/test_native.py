"""Parity tests: the native C++ clustering runtime (native/cluster.cpp via
ctypes) against its scipy/sklearn host fallbacks — same partitions on the
same distance matrices, across random data, tie-free by construction."""

import numpy as np
import pytest

from pyconsensus_tpu import _native

pytestmark = pytest.mark.skipif(_native.load() is None,
                                reason="native library unavailable")


def partitions_equal(a, b) -> bool:
    """Label vectors describe the same partition (up to renaming), with
    noise (-1) matched exactly as a class of singletons-by-flag."""
    a, b = np.asarray(a), np.asarray(b)
    if a.shape != b.shape:
        return False
    if not np.array_equal(a == -1, b == -1):
        return False
    mask = a != -1
    seen = {}
    for x, y in zip(a[mask], b[mask]):
        if x in seen:
            if seen[x] != y:
                return False
        else:
            if y in seen.values():
                return False
            seen[x] = y
    return True


def random_dist(rng, n, dim=6):
    X = rng.random((n, dim))
    d = np.sqrt(((X[:, None, :] - X[None, :, :]) ** 2).sum(-1))
    np.fill_diagonal(d, 0.0)
    return d


class TestAvgLinkage:
    @pytest.mark.parametrize("n", [2, 3, 10, 40])
    @pytest.mark.parametrize("frac", [0.1, 0.4, 0.8])
    def test_matches_scipy(self, rng, n, frac):
        from scipy.cluster.hierarchy import fcluster, linkage
        from scipy.spatial.distance import squareform

        d = random_dist(rng, n)
        t = frac * d.max()
        ours = _native.avg_linkage_labels(d, t)
        Z = linkage(squareform(d, checks=False), method="average")
        ref = fcluster(Z, t=t, criterion="distance")
        assert partitions_equal(ours, ref)

    @pytest.mark.parametrize("seed", range(8))
    def test_matches_scipy_on_tied_discrete_data(self, seed):
        """Report matrices are discrete ({0, 0.5, 1}) so distances are
        heavily tied — the regime where NN-chain tie-breaks (survivor slot =
        larger index, predecessor wins nearest-neighbor ties) must replicate
        scipy exactly or partitions silently diverge."""
        from scipy.cluster.hierarchy import fcluster, linkage
        from scipy.spatial.distance import squareform

        rng = np.random.default_rng(seed)
        for _ in range(25):
            n = int(rng.integers(4, 21))
            X = rng.choice([0.0, 0.5, 1.0],
                           size=(n, int(rng.integers(3, 8))))
            d = np.sqrt(((X[:, None, :] - X[None, :, :]) ** 2).sum(-1))
            np.fill_diagonal(d, 0.0)
            t = float(rng.random()) * (d.max() + 0.1)
            ours = _native.avg_linkage_labels(d, t)
            Z = linkage(squareform(d, checks=False), method="average")
            ref = fcluster(Z, t=t, criterion="distance")
            assert partitions_equal(ours, ref)

    def test_single_point(self):
        labels = _native.avg_linkage_labels(np.zeros((1, 1)), 0.5)
        assert labels.tolist() == [0]

    def test_threshold_extremes(self, rng):
        d = random_dist(rng, 12)
        all_one = _native.avg_linkage_labels(d, d.max() * 10)
        assert len(set(all_one.tolist())) == 1
        all_sep = _native.avg_linkage_labels(d, -1.0)
        assert len(set(all_sep.tolist())) == 12


class TestDBSCAN:
    @pytest.mark.parametrize("n", [3, 15, 50])
    @pytest.mark.parametrize("eps_frac,min_samples", [(0.2, 2), (0.4, 3),
                                                      (0.7, 5)])
    def test_matches_sklearn(self, rng, n, eps_frac, min_samples):
        from sklearn.cluster import DBSCAN

        d = random_dist(rng, n)
        eps = eps_frac * np.median(d[d > 0]) if n > 1 else 0.5
        ours = _native.dbscan_labels(d, eps, min_samples)
        ref = DBSCAN(eps=eps, min_samples=min_samples,
                     metric="precomputed").fit(d).labels_
        assert partitions_equal(ours, ref)

    def test_two_blobs_and_noise(self, rng):
        X = np.concatenate([rng.normal(0.0, 0.05, (10, 3)),
                            rng.normal(5.0, 0.05, (10, 3)),
                            [[2.5, 2.5, 2.5]]])
        d = np.sqrt(((X[:, None, :] - X[None, :, :]) ** 2).sum(-1))
        labels = _native.dbscan_labels(d, 0.5, 3)
        assert labels[-1] == -1                      # lone midpoint = noise
        assert len(set(labels[:10].tolist())) == 1
        assert len(set(labels[10:20].tolist())) == 1
        assert labels[0] != labels[10]


class TestHybridPipelineUsesNative:
    def test_conformity_same_with_and_without_native(self, rng, monkeypatch):
        """The hybrid algorithms give identical conformity vectors through
        the native library and the scipy/sklearn fallbacks."""
        from pyconsensus_tpu.models import clustering as cl

        X = rng.choice([0.0, 0.5, 1.0], size=(14, 6))
        rep = rng.random(14) + 0.1
        rep = rep / rep.sum()

        h_native = cl.hierarchical_conformity(X, rep, 0.9)
        d_native = cl.dbscan_conformity(X, rep, 0.8, 2)

        monkeypatch.setattr(_native, "avg_linkage_labels",
                            lambda *a, **k: None)
        monkeypatch.setattr(_native, "dbscan_labels", lambda *a, **k: None)
        h_fallback = cl.hierarchical_conformity(X, rep, 0.9)
        d_fallback = cl.dbscan_conformity(X, rep, 0.8, 2)

        np.testing.assert_allclose(h_native, h_fallback, rtol=1e-12)
        np.testing.assert_allclose(d_native, d_fallback, rtol=1e-12)
