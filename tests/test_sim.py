"""Monte-Carlo collusion simulator tests (BASELINE.json config 5,
SURVEY.md §3.3)."""

import jax
import numpy as np
import pytest

from pyconsensus_tpu import Oracle
from pyconsensus_tpu.sim import CollusionSimulator, simulate_grid
from pyconsensus_tpu.sim.collusion import generate_reports


class TestGeneration:
    def test_shapes_and_values(self):
        key = jax.random.key(7)
        reports, truth, liar = generate_reports(key, 0.3, 0.1, 15, 8)
        assert reports.shape == (15, 8)
        assert truth.shape == (8,)
        assert liar.shape == (15,)
        assert set(np.unique(np.asarray(reports))) <= {0.0, 1.0}

    def test_no_liars_no_noise_reports_truth(self):
        key = jax.random.key(3)
        reports, truth, liar = generate_reports(key, 0.0, 0.0, 10, 6)
        np.testing.assert_array_equal(np.asarray(reports),
                                      np.tile(np.asarray(truth), (10, 1)))
        assert not np.asarray(liar).any()

    def test_colluding_liars_report_anti_truth(self):
        key = jax.random.key(11)
        reports, truth, liar = generate_reports(key, 0.99, 0.0, 10, 6)
        liar = np.asarray(liar)
        assert liar.any()
        np.testing.assert_array_equal(
            np.asarray(reports)[liar],
            np.tile(1.0 - np.asarray(truth), (liar.sum(), 1)))


class TestSweep:
    @pytest.fixture(scope="class")
    def sweep(self):
        sim = CollusionSimulator(n_reporters=20, n_events=8,
                                 max_iterations=5)
        return sim.run(liar_fractions=[0.0, 0.2, 0.45],
                       variances=[0.0, 0.1], n_trials=20, seed=0)

    def test_shapes(self, sweep):
        assert sweep["correct_rate"].shape == (3, 2, 20)
        assert sweep["mean"]["correct_rate"].shape == (3, 2)

    def test_no_liars_no_noise_perfect(self, sweep):
        assert sweep["mean"]["correct_rate"][0, 0] == pytest.approx(1.0)
        assert sweep["mean"]["liar_rep_share"][0, 0] == 0.0
        assert sweep["mean"]["capture_rate"][0, 0] == 0.0

    def test_oracle_resists_moderate_collusion(self, sweep):
        # 20% colluding liars, no noise: consensus should still be correct
        assert sweep["mean"]["correct_rate"][1, 0] > 0.95

    def test_lie_detection_cuts_liar_reputation(self, sweep):
        # liars' post-resolution rep share is below their population share
        realized = sweep["mean"]["liar_fraction_realized"][1, 0]
        assert sweep["mean"]["liar_rep_share"][1, 0] < 0.8 * realized

    def test_more_liars_worse_outcomes(self, sweep):
        correct = sweep["mean"]["correct_rate"]
        assert correct[2, 0] <= correct[1, 0] + 1e-9
        assert correct[2, 1] <= correct[0, 1] + 1e-9

    def test_deterministic(self):
        sim = CollusionSimulator(n_reporters=10, n_events=5)
        a = sim.run([0.2], [0.1], 10, seed=4)
        b = sim.run([0.2], [0.1], 10, seed=4)
        np.testing.assert_array_equal(a["correct_rate"], b["correct_rate"])

    def test_trial_replay_matches_oracle(self):
        """A trial's metrics must equal running its exact report matrix
        through the public Oracle (numpy backend) — the simulator is the same
        pipeline, just batched."""
        sim = CollusionSimulator(n_reporters=12, n_events=6,
                                 max_iterations=3, pca_method="eigh-cov")
        res = sim.run([0.25], [0.1], 4, seed=9)
        base = jax.random.key(9)
        for t in range(4):
            key = jax.random.fold_in(base, t)  # L=V=1 -> flat index == t
            reports, truth, liar = generate_reports(key, 0.25, 0.1, 12, 6)
            r = Oracle(reports=np.asarray(reports), max_iterations=3,
                       backend="numpy").consensus()
            outcomes = r["events"]["outcomes_final"]
            truth = np.asarray(truth)
            assert res["correct_rate"][0, 0, t] == pytest.approx(
                np.mean(outcomes == truth))
            assert res["liar_rep_share"][0, 0, t] == pytest.approx(
                r["agents"]["smooth_rep"][np.asarray(liar)].sum(), abs=1e-8)

    def test_independent_liars_mode(self):
        res = simulate_grid(liar_fractions=[0.3], variances=[0.05],
                            n_trials=10, seed=2, collude=False,
                            n_reporters=16, n_events=8, max_iterations=3)
        assert res["mean"]["correct_rate"][0, 0] > 0.9

    def test_rejects_hybrid_algorithms(self):
        with pytest.raises(ValueError, match="jit-compatible"):
            CollusionSimulator(algorithm="dbscan")

    def test_10k_trials_single_call(self):
        """Config 5 scale: 10k trials in one batched call (CPU-sized)."""
        sim = CollusionSimulator(n_reporters=10, n_events=5, power_iters=16)
        res = sim.run(np.linspace(0.0, 0.4, 5), [0.0, 0.1, 0.2], 667, seed=1)
        total = np.prod(res["correct_rate"].shape)
        assert total == 5 * 3 * 667  # 10,005 resolutions
        assert np.isfinite(res["correct_rate"]).all()


class TestRoundsSimulator:
    """Multi-round reputation dynamics: lax.scan over rounds x vmap over
    the trial grid, reputation carried between rounds."""

    def test_shapes(self):
        from pyconsensus_tpu.sim import RoundsSimulator
        sim = RoundsSimulator(n_rounds=4, n_reporters=12, n_events=6,
                              max_iterations=2, power_iters=16)
        res = sim.run([0.0, 0.3], [0.1], 5, seed=0)
        assert res["liar_rep_share"].shape == (2, 1, 5, 4)
        assert res["mean"]["liar_rep_share"].shape == (2, 1, 4)
        assert res["n_rounds"] == 4

    def test_sustained_liars_ground_down(self):
        """The repeated-game claim: with reputation carried across rounds,
        a minority of sustained colluders loses reputation round over
        round — the trial-averaged trajectory never rebounds by more than
        trial noise and ends well below its start."""
        from pyconsensus_tpu.sim import RoundsSimulator
        sim = RoundsSimulator(n_rounds=6, n_reporters=20, n_events=10,
                              max_iterations=3, power_iters=32)
        res = sim.run([0.25], [0.05], 20, seed=1)
        traj = res["mean"]["liar_rep_share"][0, 0]       # (6,)
        assert traj[-1] < traj[0]
        assert np.all(np.diff(traj) < 0.02)   # no mid-run rebound
        assert res["mean"]["correct_rate"][0, 0, -1] > 0.9

    def test_zero_liars_uniform(self):
        from pyconsensus_tpu.sim import RoundsSimulator
        sim = RoundsSimulator(n_rounds=3, n_reporters=10, n_events=5,
                              power_iters=16)
        res = sim.run([0.0], [0.0], 4, seed=0)
        np.testing.assert_allclose(res["liar_rep_share"][0, 0], 0.0,
                                   atol=1e-12)
        np.testing.assert_allclose(res["mean"]["correct_rate"][0, 0], 1.0)

    def test_validation(self):
        from pyconsensus_tpu.sim import RoundsSimulator
        with pytest.raises(ValueError, match="n_rounds"):
            RoundsSimulator(n_rounds=0)

    def test_round_trajectory_plot(self, tmp_path):
        matplotlib = pytest.importorskip("matplotlib")
        matplotlib.use("Agg")
        from pyconsensus_tpu.sim import (RoundsSimulator,
                                         plot_round_trajectories)
        sim = RoundsSimulator(n_rounds=3, n_reporters=10, n_events=5,
                              power_iters=16)
        res = sim.run([0.0, 0.2], [0.0], 3, seed=0)
        ax = plot_round_trajectories(res)
        assert len(ax.get_lines()) == 2
        ax.figure.savefig(tmp_path / "rounds.png")
        matplotlib.pyplot.close(ax.figure)
        # single-round result has no round axis -> clear error
        from pyconsensus_tpu.sim import CollusionSimulator
        flat = CollusionSimulator(n_reporters=10, n_events=5,
                                  power_iters=16).run([0.0], [0.0], 2)
        with pytest.raises(ValueError, match="per-round"):
            plot_round_trajectories(flat)


class TestCheckpointedSweep:
    """Fault-tolerant sweep runner: chunked execution must be bit-identical
    to the monolithic run, survive crashes (lost chunks re-run), and shard
    across hosts deterministically."""

    LF = [0.0, 0.2, 0.4]
    VAR = [0.0, 0.2]
    T = 7          # deliberately not a multiple of trials_per_chunk

    def _sim(self):
        return CollusionSimulator(n_reporters=10, n_events=6,
                                  max_iterations=2)

    def test_matches_monolithic_run(self, tmp_path):
        from pyconsensus_tpu.sim import CheckpointedSweep
        sim = self._sim()
        mono = sim.run(self.LF, self.VAR, self.T, seed=3)
        sweep = CheckpointedSweep(sim, self.LF, self.VAR, self.T, seed=3,
                                  checkpoint_dir=tmp_path / "ck",
                                  trials_per_chunk=5)
        assert sweep.run(host_id=0, n_hosts=1) == sweep.n_chunks
        got = sweep.gather()
        for key in ("correct_rate", "capture_rate", "liar_rep_share"):
            np.testing.assert_array_equal(got[key], mono[key], err_msg=key)
            np.testing.assert_array_equal(got["mean"][key],
                                          mono["mean"][key], err_msg=key)

    def test_meshed_simulator_matches_monolithic(self, tmp_path):
        """A mesh= simulator inside CheckpointedSweep shards every
        chunk's trial axis (the shared _dispatch point) — chunk widths
        here are non-multiples of the 8 devices, exercising the pad.

        Contract (docs/ROBUSTNESS.md parity ledger #9, closed by
        re-scoping): SAME-topology dispatch — the replay the crash/resume
        chaos suite leans on — is bit-identical (asserted below by
        re-running the identical meshed sweep). CROSS-topology agreement
        (meshed 8-wide padded chunks vs a monolithic 42-wide unsharded
        dispatch) is to reduction-order ulps only: GSPMD partitioning at
        a different per-device batch width re-tiles within-trial
        reductions, and ~1-ulp leaks in a few lanes were measured
        (1.1e-16 in 3 of 42 liar_rep_share lanes; meshed FULL-width
        dispatch agreed bitwise). The collusion module documents the same
        split."""
        from pyconsensus_tpu.parallel import make_mesh
        from pyconsensus_tpu.sim import CheckpointedSweep
        mono = self._sim().run(self.LF, self.VAR, self.T, seed=3)
        meshed = CollusionSimulator(n_reporters=10, n_events=6,
                                    max_iterations=2,
                                    mesh=make_mesh(batch=8, event=1))
        sweep = CheckpointedSweep(meshed, self.LF, self.VAR, self.T,
                                  seed=3, checkpoint_dir=tmp_path / "ck",
                                  trials_per_chunk=5)
        assert sweep.run(host_id=0, n_hosts=1) == sweep.n_chunks
        got = sweep.gather()
        for key in ("correct_rate", "capture_rate", "liar_rep_share"):
            # cross-topology: reduction-order ulp band, never more
            np.testing.assert_allclose(got[key], mono[key], rtol=4e-16,
                                       atol=5e-16, err_msg=key)
        # same-topology replay (the crash/resume contract): bit-identical
        replay = CheckpointedSweep(meshed, self.LF, self.VAR, self.T,
                                   seed=3, checkpoint_dir=tmp_path / "ck2",
                                   trials_per_chunk=5)
        assert replay.run(host_id=0, n_hosts=1) == replay.n_chunks
        rep = replay.gather()
        for key in ("correct_rate", "capture_rate", "liar_rep_share"):
            np.testing.assert_array_equal(rep[key], got[key], err_msg=key)

    def test_crash_resume(self, tmp_path):
        from pyconsensus_tpu.sim import CheckpointedSweep
        sim = self._sim()
        sweep = CheckpointedSweep(sim, self.LF, self.VAR, self.T, seed=3,
                                  checkpoint_dir=tmp_path / "ck",
                                  trials_per_chunk=5)
        # "crash" after two chunks: compute them, leave the rest
        for c in sweep.pending()[:2]:
            sweep._run_chunk(c)
        with pytest.raises(ValueError, match="incomplete"):
            sweep.gather()
        # a fresh process resumes: only the missing chunks run
        resumed = CheckpointedSweep(sim, self.LF, self.VAR, self.T, seed=3,
                                    checkpoint_dir=tmp_path / "ck",
                                    trials_per_chunk=5)
        assert resumed.run(host_id=0, n_hosts=1) == resumed.n_chunks - 2
        got = resumed.gather()
        mono = sim.run(self.LF, self.VAR, self.T, seed=3)
        np.testing.assert_array_equal(got["correct_rate"],
                                      mono["correct_rate"])

    def test_multi_host_sharding(self, tmp_path):
        from pyconsensus_tpu.sim import CheckpointedSweep
        sim = self._sim()
        sweep = CheckpointedSweep(sim, self.LF, self.VAR, self.T, seed=3,
                                  checkpoint_dir=tmp_path / "ck",
                                  trials_per_chunk=4)
        # three hosts run their round-robin shares (any order / interleaving)
        counts = [sweep.run(host_id=h, n_hosts=3) for h in (2, 0, 1)]
        assert sum(counts) == sweep.n_chunks
        assert sweep.pending() == []
        got = sweep.gather()
        mono = sim.run(self.LF, self.VAR, self.T, seed=3)
        np.testing.assert_array_equal(got["liar_rep_share"],
                                      mono["liar_rep_share"])

    def test_rounds_simulator_trajectories(self, tmp_path):
        from pyconsensus_tpu.sim import CheckpointedSweep, RoundsSimulator
        sim = RoundsSimulator(n_rounds=3, n_reporters=8, n_events=5)
        sweep = CheckpointedSweep(sim, [0.0, 0.3], [0.1], 5, seed=1,
                                  checkpoint_dir=tmp_path / "ck",
                                  trials_per_chunk=3)
        sweep.run(host_id=0, n_hosts=1)
        got = sweep.gather()
        mono = sim.run([0.0, 0.3], [0.1], 5, seed=1)
        assert got["correct_rate"].shape == (2, 1, 5, 3)   # trailing rounds
        assert got["n_rounds"] == 3
        np.testing.assert_array_equal(got["correct_rate"],
                                      mono["correct_rate"])

    def test_manifest_guards_mixed_sweeps(self, tmp_path):
        from pyconsensus_tpu.sim import CheckpointedSweep
        sim = self._sim()
        CheckpointedSweep(sim, self.LF, self.VAR, self.T, seed=3,
                          checkpoint_dir=tmp_path / "ck")
        with pytest.raises(ValueError, match="different sweep"):
            CheckpointedSweep(sim, self.LF, self.VAR, self.T, seed=4,
                              checkpoint_dir=tmp_path / "ck")
        # a differently-configured SIMULATOR must be rejected too — its
        # chunks would concatenate without shape errors and silently mix
        other_sim = CollusionSimulator(n_reporters=10, n_events=6,
                                       max_iterations=3)
        with pytest.raises(ValueError, match="different sweep"):
            CheckpointedSweep(other_sim, self.LF, self.VAR, self.T, seed=3,
                              checkpoint_dir=tmp_path / "ck")

    def test_validation(self, tmp_path):
        from pyconsensus_tpu.sim import CheckpointedSweep
        sweep = CheckpointedSweep(self._sim(), self.LF, self.VAR, self.T,
                                  checkpoint_dir=tmp_path / "ck")
        with pytest.raises(ValueError, match="host_id"):
            sweep.run(host_id=5, n_hosts=2)
        with pytest.raises(ValueError, match="trials_per_chunk"):
            CheckpointedSweep(self._sim(), self.LF, self.VAR, self.T,
                              checkpoint_dir=tmp_path / "ck2",
                              trials_per_chunk=0)
