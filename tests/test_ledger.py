"""Multi-round reputation ledger: carry, checkpoint, resume
(SURVEY.md §5 — checkpoint/resume of the cross-round reputation state)."""

import numpy as np
import pytest

from conftest import collusion_reports
from pyconsensus_tpu import Oracle, ReputationLedger


def make_reports(rng, R=10, E=6, liars=3):
    return collusion_reports(rng, R, E, liars)[0]


class TestLedger:
    def test_carries_reputation_forward(self, rng):
        ledger = ReputationLedger(n_reporters=10, max_iterations=3)
        r1 = ledger.resolve(make_reports(rng))
        rep_after_1 = ledger.reputation.copy()
        np.testing.assert_allclose(rep_after_1,
                                   r1["agents"]["smooth_rep"])
        r2 = ledger.resolve(make_reports(rng))
        # round 2 started from round 1's posterior, not uniform
        np.testing.assert_allclose(r2["agents"]["old_rep"], rep_after_1,
                                   rtol=1e-12)
        assert ledger.round == 2
        assert len(ledger.history) == 2

    def test_liars_lose_reputation_over_rounds(self, rng):
        ledger = ReputationLedger(n_reporters=10, max_iterations=3, alpha=0.3)
        for _ in range(4):
            ledger.resolve(make_reports(rng))
        liar_share = ledger.reputation[-3:].sum()
        honest_share = ledger.reputation[:-3].sum()
        assert liar_share < 0.5 * (3 / 10)     # well below uniform share
        assert honest_share > 0.8

    def test_checkpoint_resume_bitwise(self, rng, tmp_path):
        ledger = ReputationLedger(n_reporters=10, max_iterations=2)
        ledger.resolve(make_reports(rng))
        ledger.resolve(make_reports(rng))
        path = tmp_path / "state.npz"
        ledger.save(path)
        resumed = ReputationLedger.load(path)
        np.testing.assert_array_equal(resumed.reputation, ledger.reputation)
        assert resumed.round == ledger.round
        assert resumed.history == ledger.history
        assert resumed.oracle_kwargs == ledger.oracle_kwargs
        # identical future: same next-round result from both
        nxt = make_reports(rng)
        a = ledger.resolve(nxt)["agents"]["smooth_rep"]
        b = resumed.resolve(nxt)["agents"]["smooth_rep"]
        np.testing.assert_array_equal(a, b)

    def test_orbax_checkpoint_resume_bitwise(self, rng, tmp_path):
        """format='orbax' writes a checkpoint DIRECTORY; load()
        auto-detects it and resumes bit-exactly, like the npz path."""
        pytest.importorskip("orbax.checkpoint")
        ledger = ReputationLedger(n_reporters=10, max_iterations=2)
        ledger.resolve(make_reports(rng))
        path = tmp_path / "ck"
        ledger.save(path, format="orbax")
        assert path.is_dir()
        ledger.save(path, format="orbax")   # re-checkpoint to a fixed path
        resumed = ReputationLedger.load(path)
        np.testing.assert_array_equal(resumed.reputation, ledger.reputation)
        assert resumed.round == ledger.round
        assert resumed.history == ledger.history
        assert resumed.oracle_kwargs == ledger.oracle_kwargs
        nxt = make_reports(rng)
        np.testing.assert_array_equal(
            ledger.resolve(nxt)["agents"]["smooth_rep"],
            resumed.resolve(nxt)["agents"]["smooth_rep"])

    def test_unknown_format_rejected(self, tmp_path):
        ledger = ReputationLedger(n_reporters=4)
        with pytest.raises(ValueError, match="format"):
            ledger.save(tmp_path / "x", format="pickle")

    def test_resolve_matches_manual_chain(self, rng):
        """The ledger is exactly the caller-side carry the reference
        expects: manual Oracle chaining gives identical results."""
        m1, m2 = make_reports(rng), make_reports(rng)
        ledger = ReputationLedger(n_reporters=10, max_iterations=2)
        ledger.resolve(m1)
        lr = ledger.resolve(m2)["agents"]["smooth_rep"]

        o1 = Oracle(reports=m1, max_iterations=2).consensus()
        o2 = Oracle(reports=m2,
                    reputation=o1["agents"]["smooth_rep"],
                    max_iterations=2).consensus()
        np.testing.assert_allclose(lr, o2["agents"]["smooth_rep"], rtol=1e-12)

    def test_validation(self):
        with pytest.raises(ValueError):
            ReputationLedger(n_reporters=5, reputation=np.zeros(5))
        with pytest.raises(ValueError):
            ReputationLedger(n_reporters=5, reputation=np.ones(4))

    def test_jax_backend_rounds(self, rng):
        ledger = ReputationLedger(n_reporters=10, backend="jax",
                                  max_iterations=2)
        ledger.resolve(make_reports(rng))
        out = ledger.resolve(make_reports(rng))
        assert np.isin(np.asarray(out["events"]["outcomes_final"]),
                       [0.0, 0.5, 1.0]).all()
