"""Test configuration: force a deterministic 8-virtual-device CPU platform
(SURVEY.md §4 — multi-chip behavior is tested on a simulated mesh via
``--xla_force_host_platform_device_count``) and float64 so the jax backend can
be compared tightly against the numpy reference. Must run before jax's first
import anywhere in the test session."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# The session environment pins JAX_PLATFORMS to the real accelerator and a
# sitecustomize hook pre-imports jax, so the env var alone is not enough —
# tests must run on the simulated 8-device CPU mesh regardless (SURVEY.md §4),
# forced via jax.config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent XLA compilation cache for the whole test session. Two
# structural costs make the suite compile the SAME programs repeatedly:
# the module-boundary ``jax.clear_caches()`` below (the mmap-count
# bound) forces cross-module recompiles of every shared executable, and
# the subprocess tests (fleet worker processes, CLI smokes, kill -9
# workers) each compile their world from scratch. With the cache dir
# exported — env vars, not jax.config, precisely so child processes
# inherit it — an identical program deserializes the compiled artifact
# instead of recompiling (numerics unchanged: it is the same
# executable), which keeps full-suite wall time safely inside the
# tier-1 870 s budget on a slow 1-CPU host. The 0.5 s floor keeps tiny
# jits out of the cache (disk churn for no win).
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    os.environ["JAX_COMPILATION_CACHE_DIR"] = "/tmp/pyconsensus-xla-cache"
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_map_regions():
    """Release compiled executables between test MODULES.

    The full suite compiles thousands of XLA programs into one process;
    each holds mmap'd regions, and by ~92% of the suite the process sits
    at the kernel's default ``vm.max_map_count`` (65530) — the next
    native allocation then SEGFAULTS inside an XLA worker thread (first
    hit in round 4 when the suite grew past ~550 tests; the crash landed
    in whatever test compiled next, masquerading as a threading bug in
    the sweep). Clearing per module keeps the count bounded (~40k peak)
    at the cost of cross-module recompiles, which are rare — modules
    share few (shape, params) keys."""
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def _static_lock_graph():
    """The static may-hold-before graph, computed once per session —
    the reference the runtime lock witness validates against."""
    from pyconsensus_tpu.analysis.witness import static_lock_graph

    return static_lock_graph()


@pytest.fixture
def lock_witness(_static_lock_graph, tmp_path):
    """Run a test under the runtime lock witness (ISSUE 9): package
    locks constructed during the test are instrumented, and at teardown
    the OBSERVED acquisition order must be acyclic and consistent with
    the static lock-order graph (the dynamic mirror of CL801). On
    violation the witness JSON lands in the test's tmp_path. The
    lock-dense suites (test_fleet.py, test_serve.py) opt in wholesale
    via a module-level autouse fixture."""
    from pyconsensus_tpu.analysis.witness import LockWitness

    w = LockWitness().install()
    try:
        yield w
    finally:
        w.uninstall()
    w.check(static=_static_lock_graph,
            dump_path=tmp_path / "lock_witness.json")


@pytest.fixture(scope="session")
def _static_protocol_graph():
    """The static happens-before graph, computed once per session —
    the reference the runtime protocol witness validates against."""
    from pyconsensus_tpu.analysis.protocol_witness import \
        static_protocol_graph

    return static_protocol_graph()


@pytest.fixture
def protocol_witness(_static_protocol_graph, tmp_path):
    """Run a test under the runtime protocol witness (ISSUE 16): the
    durability-event order of every replicated operation the test
    executes (journal/commit/ship, then ack) must be consistent with
    the static CL901 happens-before graph. On violation the witness
    JSON lands in the test's tmp_path. The durability-dense suites
    (test_transport.py, test_fleet.py) opt in wholesale via a
    module-level autouse fixture — the dynamic mirror of CL901, as
    ``lock_witness`` is of CL801."""
    from pyconsensus_tpu.analysis.protocol_witness import ProtocolWitness

    w = ProtocolWitness().install()
    try:
        yield w
    finally:
        w.uninstall()
    w.check(static=_static_protocol_graph,
            dump_path=tmp_path / "protocol_witness.json")


@pytest.fixture
def digest_witness(tmp_path):
    """Run a test under the runtime digest witness (ISSUE 17): every
    digest the test journals, records, or computes must be
    re-derivable from the durable artifact it claims to describe —
    journaled blocks re-read through the validating log reader, ledger
    history records replayed from the committed checkpoint, and
    ``mechanism_digest`` recomputed under reversed insertion order at
    every call. On violation the witness JSON lands in the test's
    tmp_path. The digest-dense suites (test_fleet.py, test_econ.py)
    opt in wholesale via a module-level autouse fixture — the dynamic
    mirror of Layer 6, as ``protocol_witness`` is of CL901."""
    from pyconsensus_tpu.analysis.determinism_witness import DigestWitness

    w = DigestWitness().install()
    try:
        yield w
    finally:
        w.uninstall()
    w.check(dump_path=tmp_path / "digest_witness.json")


def free_port() -> int:
    """An OS-assigned free TCP port for multi-process coordinator tests."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def worker_env() -> dict:
    """Subprocess environment for multi-process distributed tests: forced
    CPU platform with 2 virtual devices, gloo cross-process collectives,
    and x64 to match this conftest. Set before the interpreter starts —
    a sitecustomize hook may pre-import jax against the real accelerator
    otherwise."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "JAX_ENABLE_X64": "1",
    })
    return env


def collusion_reports(rng, R, E, liars, flip_rate=0.1, na_frac=0.0):
    """Shared synthetic-report builder: an honest majority reporting truth
    with per-entry flip noise, a block of coordinated liars reporting
    anti-truth, optional NaN non-reports. Returns ``(reports, truth)``."""
    truth = rng.choice([0.0, 1.0], size=E)
    reports = np.tile(truth, (R, 1))
    honest = R - liars
    flips = rng.random((honest, E)) < flip_rate
    reports[:honest] = np.abs(reports[:honest] - flips)
    reports[honest:] = 1.0 - truth
    if na_frac > 0.0:
        reports[rng.random((R, E)) < na_frac] = np.nan
    return reports, truth
