"""Observability helpers (SURVEY.md §5 tracing/profiling row):
PhaseTimer accumulation/blocking semantics and the trace() no-op/active
paths."""

import time

import jax.numpy as jnp
import pytest

from pyconsensus_tpu.utils import PhaseTimer, trace


class TestPhaseTimer:
    def test_accumulates_and_counts(self):
        timer = PhaseTimer()
        for _ in range(3):
            with timer.phase("work"):
                time.sleep(0.01)
        with timer.phase("other"):
            pass
        totals = timer.totals()
        assert set(totals) == {"work", "other"}
        assert totals["work"] >= 0.03
        assert timer.means()["work"] == pytest.approx(totals["work"] / 3)

    def test_observe_blocks_on_device_value(self):
        timer = PhaseTimer()
        with timer.phase("matmul"):
            x = jnp.ones((64, 64))
            timer.observe(x @ x)
        assert timer.totals()["matmul"] > 0.0
        assert timer._pending is None          # consumed by the phase exit

    def test_report_sorted_by_total(self):
        timer = PhaseTimer()
        with timer.phase("slow"):
            time.sleep(0.02)
        with timer.phase("fast"):
            pass
        report = timer.report()
        assert report.index("slow") < report.index("fast")
        assert "call(s)" in report


class TestTrace:
    def test_noop_without_dir(self):
        with trace(None):
            x = jnp.ones(4).sum()
        assert float(x) == 4.0

    def test_writes_profile(self, tmp_path):
        with trace(str(tmp_path)):
            jnp.ones((16, 16)).sum().block_until_ready()
        # jax.profiler.trace writes a plugins/profile tree
        produced = list(tmp_path.rglob("*"))
        assert produced, "trace(log_dir) produced no profile output"
