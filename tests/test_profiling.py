"""Observability helpers (SURVEY.md §5 tracing/profiling row):
PhaseTimer accumulation/blocking semantics — now a shim over
pyconsensus_tpu.obs (ISSUE 3) — and the trace() no-op/active paths."""

import time

import jax.numpy as jnp
import pytest

from pyconsensus_tpu import obs
from pyconsensus_tpu.utils import PhaseTimer, trace


class TestPhaseTimer:
    def test_accumulates_and_counts(self):
        timer = PhaseTimer()
        for _ in range(3):
            with timer.phase("work"):
                time.sleep(0.01)
        with timer.phase("other"):
            pass
        totals = timer.totals()
        assert set(totals) == {"work", "other"}
        assert totals["work"] >= 0.03
        assert timer.means()["work"] == pytest.approx(totals["work"] / 3)

    def test_observe_blocks_on_device_value(self):
        timer = PhaseTimer()
        with timer.phase("matmul"):
            x = jnp.ones((64, 64))
            timer.observe(x @ x)
        assert timer.totals()["matmul"] > 0.0
        assert timer._pending == []           # restored at the phase exit

    def test_observe_twice_blocks_both(self):
        """ISSUE 3 satellite regression: the pre-obs implementation kept a
        SINGLE ``_pending`` slot, so the second ``observe`` in one phase
        overwrote the first — only the last value was blocked on and the
        first value's device time was attributed to whatever phase
        happened to block next. ``_pending`` is a list now: every observed
        value must be waited on at phase exit."""

        class Recorder:
            def __init__(self):
                self.blocked = 0

            def block_until_ready(self):
                self.blocked += 1
                return self

        first, second = Recorder(), Recorder()
        timer = PhaseTimer()
        with timer.phase("double"):
            timer.observe(first)
            assert timer._pending == [first]  # not overwritten below
            timer.observe(second)
            assert timer._pending == [first, second]
        assert first.blocked == 1, "first observed value was dropped"
        assert second.blocked == 1
        assert timer._pending == []

    def test_observe_nested_phases_attribute_to_inner(self):
        """Nested phases keep separate pending lists: the inner phase's
        observed value must not leak into (or clobber) the outer's."""

        class Recorder:
            def __init__(self):
                self.blocked = 0

            def block_until_ready(self):
                self.blocked += 1
                return self

        outer_v, inner_v = Recorder(), Recorder()
        timer = PhaseTimer()
        with timer.phase("outer"):
            timer.observe(outer_v)
            with timer.phase("inner"):
                timer.observe(inner_v)
            assert inner_v.blocked == 1       # blocked at INNER exit
            assert timer._pending == [outer_v]
        assert outer_v.blocked == 1

    def test_no_block_flag_skips_blocking(self):
        class Explode:
            def block_until_ready(self):     # pragma: no cover - must not run
                raise AssertionError("block=False must not block")

        timer = PhaseTimer()
        with timer.phase("async", block=False):
            timer.observe(Explode())
        assert timer.totals()["async"] >= 0.0

    def test_totals_accumulate_when_body_raises(self):
        """Original-behavior regression (review catch): totals/counts were
        updated in a finally, so a phase whose body raises still counts —
        a sweep tolerating one failing phase keeps its timing."""
        timer = PhaseTimer()
        with pytest.raises(RuntimeError, match="boom"):
            with timer.phase("failing"):
                raise RuntimeError("boom")
        assert timer.totals()["failing"] >= 0.0
        assert timer.means()["failing"] >= 0.0

    def test_observe_outside_phase_keeps_last_only(self):
        """Outside any phase nothing drains the slot, so it must not
        accumulate (pinning every observed device buffer)."""
        timer = PhaseTimer()
        timer.observe("a")
        timer.observe("b")
        assert timer._pending == ["b"]

    def test_report_sorted_by_total(self):
        timer = PhaseTimer()
        with timer.phase("slow"):
            time.sleep(0.02)
        with timer.phase("fast"):
            pass
        report = timer.report()
        assert report.index("slow") < report.index("fast")
        assert "call(s)" in report

    def test_shim_feeds_tracer_and_registry(self):
        """The compatibility shim is a thin layer over obs: each phase
        shows up as a span (attrs mark the shim) and as a
        pyconsensus_phase_seconds series."""
        before = len(obs.TRACER.spans())
        timer = PhaseTimer()
        with timer.phase("shimmed"):
            pass
        spans = obs.TRACER.spans()
        assert len(spans) == before + 1
        assert spans[-1].name == "shimmed"
        assert spans[-1].attrs.get("timer") == "PhaseTimer"
        hist = obs.REGISTRY.get("pyconsensus_phase_seconds")
        assert hist is not None
        assert hist.value(phase="shimmed")["count"] >= 1
        # shim totals equal the span duration exactly (single source)
        assert timer.totals()["shimmed"] == spans[-1].duration_s


class TestTrace:
    def test_noop_without_dir(self):
        with trace(None):
            x = jnp.ones(4).sum()
        assert float(x) == 4.0

    def test_writes_profile(self, tmp_path):
        with trace(str(tmp_path)):
            jnp.ones((16, 16)).sum().block_until_ready()
        # jax.profiler.trace writes a plugins/profile tree
        produced = list(tmp_path.rglob("*"))
        assert produced, "trace(log_dir) produced no profile output"
