"""Multi-device sharding tests on the simulated 8-device CPU mesh
(SURVEY.md §4: this is how "multi-node" is tested without a TPU pod).
Key property: sharded resolution == single-device resolution."""

import jax
import numpy as np
import pytest

from conftest import collusion_reports
from pyconsensus_tpu import Oracle
from pyconsensus_tpu.models.pipeline import ConsensusParams
from pyconsensus_tpu.parallel import (ShardedOracle, make_mesh,
                                      sharded_consensus)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh(batch=1, event=8)


def make_reports(rng, R=32, E=64, na_frac=0.05):
    return collusion_reports(rng, R, E, liars=6, na_frac=na_frac)[0]


class TestShardedParity:
    @pytest.mark.parametrize("pca_method", ["eigh-gram", "power"])
    def test_sharded_equals_unsharded(self, rng, mesh8, pca_method):
        reports = make_reports(rng)
        unsharded = Oracle(reports=reports, backend="jax", max_iterations=3,
                           pca_method=pca_method).consensus()
        sharded = ShardedOracle(reports=reports, backend="jax",
                                max_iterations=3, pca_method=pca_method,
                                mesh=mesh8).consensus()
        np.testing.assert_array_equal(
            sharded["events"]["outcomes_final"],
            unsharded["events"]["outcomes_final"])
        np.testing.assert_allclose(sharded["agents"]["smooth_rep"],
                                   unsharded["agents"]["smooth_rep"],
                                   atol=1e-8)
        np.testing.assert_allclose(sharded["events"]["certainty"],
                                   unsharded["events"]["certainty"],
                                   atol=1e-8)

    def test_sharded_matches_numpy_reference(self, rng, mesh8):
        """End-to-end: 8-way sharded jax == single-process numpy."""
        reports = make_reports(rng)
        reference = Oracle(reports=reports, backend="numpy",
                           max_iterations=3).consensus()
        sharded = ShardedOracle(reports=reports, backend="jax",
                                max_iterations=3, mesh=mesh8).consensus()
        np.testing.assert_array_equal(
            sharded["events"]["outcomes_final"],
            reference["events"]["outcomes_final"])
        np.testing.assert_allclose(sharded["agents"]["smooth_rep"],
                                   reference["agents"]["smooth_rep"],
                                   atol=1e-8)

    def test_scaled_events_sharded(self, rng, mesh8):
        reports = make_reports(rng, E=16, na_frac=0.0)
        bounds = [None] * 14 + [{"scaled": True, "min": 0.0, "max": 10.0}] * 2
        reports[:, 14:] *= 10.0
        mesh2 = make_mesh(batch=1, event=2)
        unsharded = Oracle(reports=reports, event_bounds=bounds,
                           backend="jax", pca_method="eigh-gram").consensus()
        out = sharded_consensus(reports, event_bounds=bounds, mesh=mesh2,
                                params=ConsensusParams(pca_method="eigh-gram"))
        np.testing.assert_allclose(
            np.asarray(out["outcomes_final"]),
            unsharded["events"]["outcomes_final"], rtol=1e-8)

    def test_scaled_gather_path_single_device(self, rng):
        """On a single-device (event=1) mesh the XLA path keeps the static
        scaled count and medians a gather of just the scaled columns
        (sharded._xla_path_n_scaled); outcomes must match the full-median
        Oracle resolution exactly on binary columns and to float tolerance
        on scaled medians."""
        reports = make_reports(rng, E=16, na_frac=0.1)
        bounds = [None] * 13 + [{"scaled": True, "min": 0.0,
                                 "max": 10.0}] * 3
        reports[:, 13:] = np.abs(reports[:, 13:]) * 10.0
        mesh1 = make_mesh(batch=1, event=1)
        out = sharded_consensus(reports, event_bounds=bounds, mesh=mesh1,
                                params=ConsensusParams(
                                    pca_method="eigh-gram"))
        ref = Oracle(reports=reports, event_bounds=bounds, backend="jax",
                     pca_method="eigh-gram").consensus()
        np.testing.assert_array_equal(
            np.asarray(out["outcomes_adjusted"])[:13],
            ref["events"]["outcomes_adjusted"][:13])
        np.testing.assert_allclose(
            np.asarray(out["outcomes_final"]),
            ref["events"]["outcomes_final"], rtol=1e-8)

    def test_functional_api_device_resident(self, rng, mesh8):
        """sharded_consensus accepts a device array without host round-trip."""
        import jax.numpy as jnp
        reports = jnp.asarray(make_reports(rng, na_frac=0.0))
        out = sharded_consensus(reports, mesh=mesh8,
                                params=ConsensusParams(pca_method="power",
                                                       has_na=False))
        outcomes = np.asarray(out["outcomes_final"])
        assert np.isin(outcomes, [0.0, 0.5, 1.0]).all()

    @pytest.mark.parametrize("algo", ["k-means", "dbscan-jit"])
    def test_jit_clustering_shards(self, rng, mesh8, algo):
        """The jit clustering variants shard over events too: their
        distance contractions reduce over the sharded axis (GSPMD psum),
        and the R-sized label machinery replicates."""
        reports = make_reports(rng)
        kwargs = ({"num_clusters": 3} if algo == "k-means"
                  else {"dbscan_eps": 2.5, "dbscan_min_samples": 3})
        unsharded = Oracle(reports=reports, backend="jax", algorithm=algo,
                           **kwargs).consensus()
        sharded = ShardedOracle(reports=reports, backend="jax",
                                algorithm=algo, mesh=mesh8,
                                **kwargs).consensus()
        np.testing.assert_array_equal(
            sharded["events"]["outcomes_final"],
            unsharded["events"]["outcomes_final"])
        np.testing.assert_allclose(sharded["agents"]["smooth_rep"],
                                   unsharded["agents"]["smooth_rep"],
                                   atol=1e-8)

    @pytest.mark.parametrize("algo,kwargs", [
        ("hierarchical", {"hierarchy_threshold": 1.5}),
        ("dbscan", {"dbscan_eps": 1.0, "dbscan_min_samples": 2}),
    ])
    def test_hybrid_clustering_shards(self, rng, mesh8, algo, kwargs):
        """Round 2: the hybrid host-clustering variants resolve on the mesh
        too — device phases (fill, R×R distances, outcomes, bonuses) run
        event-sharded, only the distances and O(R) vectors cross to host.
        Must equal the unsharded resolution exactly on outcomes."""
        reports = make_reports(rng, na_frac=0.1)
        unsharded = Oracle(reports=reports, backend="jax", algorithm=algo,
                           max_iterations=2, **kwargs).consensus()
        sharded = ShardedOracle(reports=reports, backend="jax",
                                algorithm=algo, mesh=mesh8,
                                max_iterations=2, **kwargs).consensus()
        np.testing.assert_array_equal(
            sharded["events"]["outcomes_final"],
            unsharded["events"]["outcomes_final"])
        np.testing.assert_allclose(sharded["agents"]["smooth_rep"],
                                   unsharded["agents"]["smooth_rep"],
                                   atol=1e-8)
        np.testing.assert_allclose(sharded["events"]["certainty"],
                                   unsharded["events"]["certainty"],
                                   atol=1e-8)
        # functional front-end too
        out = sharded_consensus(
            reports, mesh=mesh8,
            params=ConsensusParams(algorithm=algo, max_iterations=2,
                                   **kwargs))
        np.testing.assert_array_equal(
            np.asarray(out["outcomes_final"]),
            unsharded["events"]["outcomes_final"])

    def test_rejects_numpy_backend(self, rng, mesh8):
        with pytest.raises(ValueError, match="backend"):
            ShardedOracle(reports=make_reports(rng), backend="numpy",
                          mesh=mesh8)


class TestFusedResolution:
    """The NaN-threaded Pallas fast path (ConsensusParams.fused_resolution,
    Pallas interpreter on the CPU test platform) must reproduce the XLA
    light pipeline key-for-key — it replaces the fill/PCA/direction-fix/
    outcome/certainty passes with fused kernels but not their semantics."""

    @pytest.mark.parametrize("R,max_iterations", [(24, 1), (24, 4),
                                                  (23, 1)])
    def test_matches_xla_light_path(self, rng, R, max_iterations):
        """R=23 (prime, no 8-multiple chunk divisor) exercises the resolve
        kernel's zero-rep row-padding path."""
        from pyconsensus_tpu.models.pipeline import (_consensus_core_fused,
                                                     _consensus_core_light)
        import jax.numpy as jnp
        reports = make_reports(rng, R=R, E=7)     # ragged vs 128-col blocks
        R, E = reports.shape
        rep = np.full(R, 1.0 / R)
        args = (jnp.asarray(reports), jnp.asarray(rep),
                jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E))
        base = ConsensusParams(algorithm="sztorc",
                               max_iterations=max_iterations,
                               pca_method="power", power_iters=256,
                               power_tol=-1.0, any_scaled=False, has_na=True)
        ref = _consensus_core_light(*args, base)
        fused = _consensus_core_fused(
            *args, base._replace(fused_resolution=True))
        assert set(fused) == set(ref)
        for key in ref:
            a, b = np.asarray(ref[key]), np.asarray(fused[key])
            if key in ("outcomes_adjusted", "outcomes_final", "na_row",
                       "iterations", "convergence"):
                np.testing.assert_array_equal(a, b, err_msg=key)
            elif key == "first_loading":
                # eigensign is arbitrary between the paths
                np.testing.assert_allclose(np.abs(a), np.abs(b), atol=2e-3,
                                           err_msg=key)
            else:
                np.testing.assert_allclose(a, b, atol=2e-3, err_msg=key)

    def test_matches_xla_light_path_scaled(self, rng):
        """Mixed binary + scaled events: the fused path's gather-and-fix
        median pass must reproduce the XLA light pipeline (same sort-based
        weighted median, tolerance-agreement certainty, un-rescale)."""
        from pyconsensus_tpu.models.pipeline import (_consensus_core_fused,
                                                     _consensus_core_light)
        import jax.numpy as jnp
        reports = make_reports(rng, R=24, E=12, na_frac=0.1)
        R, E = reports.shape
        scaled = np.zeros(E, dtype=bool)
        scaled[[3, 7, 11]] = True
        mins = np.where(scaled, -5.0, 0.0)
        maxs = np.where(scaled, 15.0, 1.0)
        reports[:, scaled] = reports[:, scaled] * 20.0 - 5.0   # into bounds
        rep = np.full(R, 1.0 / R)
        args = (jnp.asarray(reports), jnp.asarray(rep), jnp.asarray(scaled),
                jnp.asarray(mins), jnp.asarray(maxs))
        base = ConsensusParams(algorithm="sztorc", max_iterations=2,
                               pca_method="power", power_iters=256,
                               power_tol=-1.0, any_scaled=True, has_na=True,
                               n_scaled=3)
        ref = _consensus_core_light(*args, base._replace(n_scaled=0))
        fused = _consensus_core_fused(
            *args, base._replace(fused_resolution=True))
        assert set(fused) == set(ref)
        binary = ~scaled
        for key in ref:
            a, b = np.asarray(ref[key]), np.asarray(fused[key])
            if key in ("na_row", "iterations", "convergence"):
                np.testing.assert_array_equal(a, b, err_msg=key)
            elif key in ("outcomes_adjusted", "outcomes_final"):
                # binary outcomes are catch-snapped -> exact; scaled carry
                # float differences from the two fill computations
                np.testing.assert_array_equal(a[binary], b[binary],
                                              err_msg=key)
                np.testing.assert_allclose(a[scaled], b[scaled], atol=2e-3,
                                           err_msg=key)
            elif key == "first_loading":
                np.testing.assert_allclose(np.abs(a), np.abs(b), atol=2e-3,
                                           err_msg=key)
            else:
                np.testing.assert_allclose(a, b, atol=2e-3, err_msg=key)

    @pytest.mark.parametrize("algorithm", ["fixed-variance", "ica"])
    @pytest.mark.parametrize("max_iterations", [1, 3])
    def test_multi_component_matches_xla(self, rng, algorithm,
                                         max_iterations):
        """Round 4 (VERDICT r3 item 2): ica and fixed-variance on the
        fused NaN-threaded path — storage-kernel orthogonal iteration +
        one-sweep batched direction fix — must reproduce the XLA light
        pipeline key-for-key (same convergence rules, same component
        selection, same FastICA loop)."""
        from pyconsensus_tpu.models.pipeline import (_consensus_core_fused,
                                                     _consensus_core_light)
        import jax.numpy as jnp
        reports = make_reports(rng, R=24, E=16, na_frac=0.1)
        R, E = reports.shape
        rep = np.full(R, 1.0 / R)
        args = (jnp.asarray(reports), jnp.asarray(rep),
                jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E))
        base = ConsensusParams(algorithm=algorithm,
                               max_iterations=max_iterations,
                               pca_method="power", any_scaled=False,
                               has_na=True)
        ref = _consensus_core_light(*args, base)
        fused = _consensus_core_fused(
            *args, base._replace(fused_resolution=True))
        assert set(fused) == set(ref)
        assert ("first_loading" in fused) == (algorithm != "ica")
        for key in ref:
            a, b = np.asarray(ref[key]), np.asarray(fused[key])
            if key in ("outcomes_adjusted", "outcomes_final", "na_row",
                       "iterations", "convergence"):
                np.testing.assert_array_equal(a, b, err_msg=key)
            elif key == "first_loading":
                np.testing.assert_allclose(np.abs(a), np.abs(b), atol=2e-3,
                                           err_msg=key)
            else:
                np.testing.assert_allclose(a, b, atol=2e-3, err_msg=key)

    @pytest.mark.parametrize("algorithm", ["fixed-variance", "ica"])
    def test_multi_component_int8_storage(self, rng, algorithm):
        """int8 sentinel storage through the multi-component fused path:
        exact on binary lattices, so catch-snapped outcomes match the
        full-precision fused run exactly."""
        from pyconsensus_tpu.models.pipeline import _consensus_core_fused
        import jax.numpy as jnp
        reports = make_reports(rng, R=24, E=16, na_frac=0.15)
        R, E = reports.shape
        rep = np.full(R, 1.0 / R)
        args = (jnp.asarray(reports), jnp.asarray(rep),
                jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E))
        base = ConsensusParams(algorithm=algorithm, pca_method="power",
                               any_scaled=False, has_na=True,
                               fused_resolution=True)
        full = _consensus_core_fused(*args, base)
        int8 = _consensus_core_fused(*args,
                                     base._replace(storage_dtype="int8"))
        np.testing.assert_array_equal(
            np.asarray(full["outcomes_adjusted"]),
            np.asarray(int8["outcomes_adjusted"]))
        np.testing.assert_allclose(np.asarray(full["smooth_rep"]),
                                   np.asarray(int8["smooth_rep"]),
                                   atol=5e-6)

    def test_pre_encoded_reports_bit_identical(self, rng):
        """Round-5 (VERDICT r4 item 3): a matrix encoded ONCE via
        ``encode_reports`` and fed to the fused pipeline produces
        bit-identical results to the raw float form — the encode
        expression is the same, just hoisted out of the resolution."""
        from pyconsensus_tpu.models.pipeline import (_consensus_core_fused,
                                                     encode_reports)
        import jax
        import jax.numpy as jnp
        reports = make_reports(rng, R=40, E=96, na_frac=0.12)
        R, E = reports.shape
        rep = np.full(R, 1.0 / R)
        args = (jnp.asarray(rep), jnp.zeros(E, dtype=bool),
                jnp.zeros(E), jnp.ones(E))
        for algorithm in ("sztorc", "fixed-variance"):
            p = ConsensusParams(algorithm=algorithm, pca_method="power",
                                any_scaled=False, has_na=True,
                                fused_resolution=True, storage_dtype="int8")
            raw = _consensus_core_fused(jnp.asarray(reports), *args, p)
            enc = jax.jit(encode_reports)(jnp.asarray(reports))
            assert np.asarray(enc).dtype == np.int8
            got = _consensus_core_fused(enc, *args, p)
            assert set(got) == set(raw)
            for key in raw:
                np.testing.assert_array_equal(np.asarray(raw[key]),
                                              np.asarray(got[key]),
                                              err_msg=(algorithm, key))

    def test_pre_encoded_validation_and_decode(self, rng):
        """int8 sentinel input demands storage_dtype='int8' (everywhere),
        the XLA core refuses it outright, decode round-trips, and the
        host front-ends (Oracle, numpy backend) transparently decode."""
        from pyconsensus_tpu.models.pipeline import (
            ConsensusParams as CP, _consensus_core, _consensus_core_fused,
            decode_reports, encode_reports)
        import jax.numpy as jnp
        from pyconsensus_tpu import Oracle
        reports = make_reports(rng, R=16, E=12, na_frac=0.2)
        R, E = reports.shape
        enc = encode_reports(jnp.asarray(reports))
        rest = (jnp.full((R,), 1.0 / R), jnp.zeros(E, dtype=bool),
                jnp.zeros(E), jnp.ones(E))
        with pytest.raises(ValueError, match="pre-encoded"):
            _consensus_core_fused(enc, *rest,
                                  CP(algorithm="sztorc", any_scaled=False,
                                     has_na=True, fused_resolution=True,
                                     storage_dtype="bfloat16"))
        with pytest.raises(ValueError, match="pre-encoded"):
            _consensus_core(enc, *rest,
                            CP(algorithm="sztorc", any_scaled=False,
                               has_na=True))
        dec = np.asarray(decode_reports(np.asarray(enc)))
        assert np.array_equal(np.isnan(dec), np.isnan(reports))
        np.testing.assert_allclose(np.nan_to_num(dec),
                                   np.nan_to_num(reports))
        # Oracle accepts the encoded form on every backend and matches
        # the float-input result exactly (host decode at construction)
        enc_np = np.asarray(enc)
        for backend in ("numpy", "jax"):
            a = Oracle(reports=reports, backend=backend).consensus()
            b = Oracle(reports=enc_np, backend=backend).consensus()
            np.testing.assert_array_equal(
                np.asarray(a["events"]["outcomes_final"], dtype=float),
                np.asarray(b["events"]["outcomes_final"], dtype=float))
            np.testing.assert_allclose(
                np.asarray(a["agents"]["smooth_rep"], dtype=float),
                np.asarray(b["agents"]["smooth_rep"], dtype=float),
                rtol=0, atol=0)

    def test_raw_int8_votes_keep_pre_round5_meaning(self):
        """A plain {0, 1} int8 vote matrix (no -1 sentinel, no encoded-2)
        must behave exactly like the same matrix passed as floats — the
        encoded interpretation only engages when the matrix provably is
        encoded (code-review r5 find: unconditional dtype-sniffing would
        have silently halved every raw int8 '1' vote to 0.5). Since the
        heuristic CANNOT prove this case, deciding it now warns; the
        explicit ``encoded=False`` contract is silent."""
        from pyconsensus_tpu import Oracle
        from pyconsensus_tpu.models.pipeline import looks_encoded
        rng = np.random.default_rng(3)
        raw = (rng.random((20, 12)) < 0.5).astype(np.int8)
        assert not looks_encoded(raw)
        assert looks_encoded(np.array([[0, 2]], dtype=np.int8))
        assert looks_encoded(np.array([[0, -1]], dtype=np.int8))
        for backend in ("numpy", "jax"):
            a = Oracle(reports=raw.astype(np.float64),
                       backend=backend).consensus()
            with pytest.warns(UserWarning, match="ambiguous"):
                b = Oracle(reports=raw, backend=backend).consensus()
            np.testing.assert_array_equal(
                np.asarray(a["events"]["outcomes_final"], dtype=float),
                np.asarray(b["events"]["outcomes_final"], dtype=float))
            np.testing.assert_array_equal(
                np.asarray(a["agents"]["smooth_rep"], dtype=float),
                np.asarray(b["agents"]["smooth_rep"], dtype=float))

    def test_oracle_encoded_flag_contract(self):
        """``Oracle(encoded=...)`` pins the int8 reading explicitly: both
        values run silently, mismatched claims raise, and the flag is
        validated against the matrix (satellite of the Layer-3 PR)."""
        import warnings

        import jax.numpy as jnp

        from pyconsensus_tpu import Oracle
        from pyconsensus_tpu.models.pipeline import encode_reports
        rng = np.random.default_rng(7)
        raw = (rng.random((10, 8)) < 0.5).astype(np.int8)
        src = np.where(rng.random((10, 8)) < 0.15, np.nan,
                       raw.astype(np.float64))
        enc = np.asarray(encode_reports(jnp.asarray(src)))
        with warnings.catch_warnings():
            warnings.simplefilter("error")          # no warning allowed
            o_raw = Oracle(reports=raw, encoded=False)
            o_enc = Oracle(reports=enc, encoded=True)
        np.testing.assert_array_equal(o_raw.reports,
                                      raw.astype(np.float64))
        assert np.array_equal(np.isnan(o_enc.reports), np.isnan(src))
        with pytest.raises(ValueError, match="outside"):
            Oracle(reports=enc, encoded=False)      # sentinel != raw
        with pytest.raises(ValueError, match="int8"):
            Oracle(reports=src, encoded=True)       # float can't be enc

    def test_pre_encoded_placement_preserves_dtype(self):
        """The sharded front-end's report placement must not cast the
        encoded matrix to the compute dtype (that would both quadruple
        the bytes and turn the -1 sentinel into a live value)."""
        import jax.numpy as jnp
        import pyconsensus_tpu.parallel.sharded as sh
        mesh = make_mesh(batch=1, event=1)
        enc = jnp.asarray(np.array([[0, 1, 2, -1]], dtype=np.int8))
        placed = sh._maybe_place_reports(
            enc, sh._input_shardings(mesh, 4)[0], jnp.asarray(0.0).dtype)
        assert placed.dtype == jnp.int8

    def test_multi_component_gate(self, monkeypatch):
        """The single-device fused gate admits ica/fixed-variance wherever
        the ONE-PASS block covariance kernel fits (no width ceiling —
        with that kernel the fused path beat XLA at every measured width
        including the north-star 100k); the separable two-sweep fallback
        keeps the measured _MULTI_FUSED_MAX_E ceiling, so f32 storage at
        100k (one-pass does not fit f32's wider decode/aux) stays on the
        XLA path. The mesh gate stays sztorc-only."""
        import pyconsensus_tpu.parallel.sharded as sh
        monkeypatch.setattr(sh.jax, "default_backend", lambda: "tpu")
        for algo in ("ica", "fixed-variance"):
            p = ConsensusParams(algorithm=algo, any_scaled=False,
                                pca_method="power",
                                storage_dtype="bfloat16")
            assert sh._use_fused_resolution(p, 10_000, 32_768, 1), algo
            # north-star width: open since the one-pass block kernel
            assert sh._use_fused_resolution(p, 10_000, 100_000, 1), algo
            # f32 storage at 100k: one-pass unfit, separable over the
            # ceiling -> XLA path
            assert not sh._use_fused_resolution(
                p._replace(storage_dtype="float32"), 10_000, 100_000,
                1), algo
            # mid-band width where the one-pass covariance kernel fits
            # but the scores/dirfix sweeps' (k+1)-row matmat does NOT
            # (code-review r4 find): those sweeps run unconditionally on
            # the fused path, so the gate must stay closed
            from pyconsensus_tpu.ops.pallas_kernels import (
                cov_block_kernel_fits, matmat_kernels_fit)
            E_mid = 140_000
            assert cov_block_kernel_fits(E_mid, 5, 2)
            assert not matmat_kernels_fit(E_mid, 6, 2)
            assert not sh._use_fused_resolution(p, 10_000, E_mid, 1), algo
            assert not sh._use_fused_resolution(p, 10_000, 32_768, 8), algo
            # auto-storage picks int8 for the all-binary single-device
            # case at every fused-served width, including 100k now
            mesh1 = make_mesh(batch=1, event=1)
            for E in (32_768, 100_000):
                storage, why = sh.resolve_auto_storage(
                    ConsensusParams(algorithm=algo, any_scaled=False,
                                    has_na=True), 10_000, E, mesh1)
                assert storage == "int8", (E, why)

    def test_gate_scaled_fraction(self, monkeypatch):
        """On TPU the gate admits a small static scaled fraction and rejects
        scaled-heavy matrices (and any_scaled without a count)."""
        import pyconsensus_tpu.parallel.sharded as sh
        monkeypatch.setattr(sh.jax, "default_backend", lambda: "tpu")
        p = ConsensusParams(algorithm="sztorc", any_scaled=False,
                            pca_method="power-fused",
                            storage_dtype="float32")   # x64 test env
        assert sh._use_fused_resolution(p, 10_000, 100_000, 1)
        ok = p._replace(any_scaled=True, n_scaled=1000)
        assert sh._use_fused_resolution(ok, 10_000, 100_000, 1)
        # prime R no longer disqualifies: the resolve kernel zero-pads to
        # a tileable row count
        assert sh._use_fused_resolution(p, 10_007, 100_000, 1)
        heavy = p._replace(any_scaled=True, n_scaled=20_000)
        assert not sh._use_fused_resolution(heavy, 10_000, 100_000, 1)
        uncounted = p._replace(any_scaled=True, n_scaled=0)
        assert not sh._use_fused_resolution(uncounted, 10_000, 100_000, 1)

    def test_stale_n_scaled_is_reset(self, rng, monkeypatch):
        """A reused params object carrying n_scaled>0 must not leak into a
        boundsless resolution (the fused gather would then mis-resolve
        binary column 0 as scaled), and the XLA path must not key its jit
        cache on the scaled count."""
        import pyconsensus_tpu.parallel.sharded as sh
        from pyconsensus_tpu.models.pipeline import consensus_light_jit
        seen = []

        def spy(*args):
            seen.append(args[-1])
            return consensus_light_jit(*args)

        monkeypatch.setattr(sh, "consensus_light_jit", spy)
        stale = ConsensusParams(pca_method="power", n_scaled=3)
        sh.sharded_consensus(make_reports(rng), params=stale)  # no bounds
        assert seen[-1].n_scaled == 0
        # bounds given but gate rejects (CPU): n_scaled must also be reset
        reports = make_reports(rng, E=16, na_frac=0.0)
        bounds = [None] * 14 + [{"scaled": True, "min": 0.0, "max": 1.0}] * 2
        sh.sharded_consensus(reports, event_bounds=bounds, params=stale)
        assert seen[-1].n_scaled == 0

    def test_gate_requires_single_tpu(self):
        from pyconsensus_tpu.parallel.sharded import _use_fused_resolution
        p = ConsensusParams(algorithm="sztorc", any_scaled=False,
                            pca_method="power-fused")   # as resolved
        # CPU test platform: never on, regardless of other conditions
        assert not _use_fused_resolution(p, 10_000, 100_000, 1)
        # and the non-sztorc / exact-PCA / scaled / multi-device /
        # untileable-R gates
        assert not _use_fused_resolution(
            p._replace(algorithm="k-means"), 10_000, 100_000, 1)
        # an explicitly requested (or auto-picked, R<=4096) exact eigh must
        # never be silently swapped for power iteration by the fused path
        assert not _use_fused_resolution(
            p._replace(pca_method="eigh-gram"), 10_000, 100_000, 1)
        assert not _use_fused_resolution(
            p._replace(any_scaled=True), 10_000, 100_000, 1)
        assert not _use_fused_resolution(p, 10_000, 100_000, 8)

    def test_multi_component_explicit_power_honored(self):
        """An explicit power-family request on ica/fixed-variance resolves
        to 'power' even where auto routing would pick the exact Gram eigh
        (R <= _GRAM_EIGH_MAX_R) — matching weighted_prin_comps' own rule
        and keeping the multi-component fused gate (int8 storage at small
        R) reachable. Auto still routes small R to the exact eigh."""
        from pyconsensus_tpu.parallel.sharded import _pick_pca_method
        p = ConsensusParams(algorithm="ica", any_scaled=False)
        for req in ("power", "power-fused"):
            got = _pick_pca_method(p._replace(pca_method=req), 1003, 4096)
            assert got == "power", (req, got)
        # ... and explicit EXACT requests are honored symmetrically, even
        # where auto would route to power (R > _GRAM_EIGH_MAX_R) or away
        # from eigh-cov (E > 1024)
        assert _pick_pca_method(p._replace(pca_method="eigh-gram"),
                                5000, 4096) == "eigh-gram"
        assert _pick_pca_method(p._replace(pca_method="eigh-cov"),
                                1003, 4096) == "eigh-cov"
        assert _pick_pca_method(p._replace(pca_method="auto"),
                                1003, 4096) == "eigh-gram"
        assert _pick_pca_method(p._replace(pca_method="auto"),
                                1003, 512) == "eigh-cov"

    def test_vmem_fit_models(self):
        """The scoped-VMEM fit models encode the measured compile failures:
        E=200k f32 and R=20k f32-at-C=128 blow the 16 MB limit; the bench
        shape fits in both dtypes; bigger shapes keep a narrower column
        block or fall back to XLA."""
        from pyconsensus_tpu.ops.pallas_kernels import (_resolve_block_cols,
                                                        fused_pca_fits,
                                                        resolve_kernel_fits)
        assert fused_pca_fits(100_000, 4) and fused_pca_fits(100_000, 2)
        assert not fused_pca_fits(200_000, 4)     # measured OOM
        assert fused_pca_fits(150_000, 2)
        assert resolve_kernel_fits(10_000, 4)
        assert _resolve_block_cols(10_000, 2) == 128
        # R=20k f32: C=128 measured OOM, and narrower blocks are illegal
        # (Pallas requires width % 128 == 0) -> XLA fallback
        assert _resolve_block_cols(20_000, 4) is None
        assert not resolve_kernel_fits(20_000, 4)
        # bf16 at R=20k still fits at C=128
        assert _resolve_block_cols(20_000, 2) == 128

    def test_chunk_picker(self):
        from pyconsensus_tpu.ops.pallas_kernels import _pick_chunk
        assert _pick_chunk(10_000) == 1000
        assert _pick_chunk(16) == 16
        assert _pick_chunk(24) == 24
        assert _pick_chunk(10_007) is None
        assert _pick_chunk(2048) == 1024


class TestMesh:
    def test_make_mesh_shapes(self):
        m = make_mesh(batch=2, event=4)
        assert m.shape == {"batch": 2, "event": 4}
        m = make_mesh(batch=2)
        assert m.shape == {"batch": 2, "event": 4}

    def test_bad_mesh(self):
        with pytest.raises(ValueError):
            make_mesh(batch=3)
        with pytest.raises(ValueError):
            make_mesh(batch=4, event=4)


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge
        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)
        outcomes = np.asarray(out["outcomes_final"])
        assert np.isin(outcomes, [0.0, 0.5, 1.0]).all()

    def test_dryrun_multichip_8(self, capsys):
        import __graft_entry__ as ge
        ge.dryrun_multichip(8)
        assert "OK" in capsys.readouterr().out
