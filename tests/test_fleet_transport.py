"""The fleet contract, parametrized over BOTH transports (ISSUE 15
acceptance): every black-box property the router guarantees — parity,
session stickiness, durability, kill-mid-traffic takeover, structured
shedding — holds identically whether the workers are in-process
``ConsensusService`` instances (``InProcessTransport``, the PR-8
fleet) or real OS processes behind the socket RPC wire
(``SocketTransport``). The white-box fleet internals (declare-lock
races, fence ordering, injected takeover faults) stay in
tests/test_fleet.py against the in-process handles they poke."""

import tempfile
import time

import numpy as np
import pytest

from pyconsensus_tpu.faults import (FailoverInProgressError, InputError,
                                    PlacementError, ServiceOverloadError,
                                    TransportError, WorkerLostError)
from pyconsensus_tpu.serve.failover import DurableSession
from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig
from pyconsensus_tpu.serve.service import ServeConfig

TRANSPORTS = ["inprocess", "socket"]


def make_block(round_idx: int, block_idx: int) -> np.ndarray:
    rng = np.random.default_rng([11, round_idx, block_idx])
    block = rng.choice([0.0, 1.0], size=(10, 4))
    block[rng.random(block.shape) < 0.1] = np.nan
    return block


def retried(fn, attempts=40):
    """The polite fleet client: bounded retry on the retryable
    taxonomy (and raw transport loss before the monitor declares)."""
    last = None
    for _ in range(attempts):
        try:
            return fn()
        except (WorkerLostError, FailoverInProgressError,
                TransportError, OSError) as exc:
            last = exc
            hint = getattr(exc, "context", {})
            time.sleep(float(hint.get("retry_after_s", 0.25) or 0.25))
    raise last


@pytest.fixture(scope="module", params=TRANSPORTS)
def fleet(request):
    """One 2-worker fleet per transport (module-scoped: the socket
    variant's worker processes are the expensive resource)."""
    log_dir = tempfile.mkdtemp(prefix=f"fleet-{request.param}-")
    f = ConsensusFleet(FleetConfig(
        n_workers=2, transport=request.param, log_dir=log_dir,
        monitor=True, heartbeat_timeout_s=1.5,
        heartbeat_interval_s=0.25,
        worker=ServeConfig(warmup=(), batch_window_ms=1.0,
                           pallas_buckets=False))).start()
    f._test_transport = request.param
    yield f
    f.close(drain=False, timeout=10.0)


class TestFrontDoorContract:
    def test_stateless_submit_resolves(self, fleet, rng):
        reports = rng.choice([0.0, 1.0], size=(10, 8))
        res = fleet.submit(reports=reports).result(timeout=120)
        assert set(res) >= {"events", "agents", "iterations"}
        assert np.isin(
            np.asarray(res["events"]["outcomes_adjusted"]),
            [0.0, 0.5, 1.0]).all()

    def test_stateless_deterministic_across_workers(self, fleet, rng):
        """The any-worker-same-bits routing freedom: repeated submits
        of one matrix (spread over the ring) return ONE bit pattern."""
        reports = rng.choice([0.0, 1.0], size=(10, 8))
        futures = [fleet.submit(reports=reports) for _ in range(6)]
        outs = [f.result(timeout=120) for f in futures]
        for out in outs[1:]:
            np.testing.assert_array_equal(
                np.asarray(out["agents"]["smooth_rep"]),
                np.asarray(outs[0]["agents"]["smooth_rep"]))
            np.testing.assert_array_equal(
                np.asarray(out["events"]["outcomes_adjusted"]),
                np.asarray(outs[0]["events"]["outcomes_adjusted"]))

    def test_exactly_one_of_reports_session(self, fleet):
        with pytest.raises(InputError):
            fleet.submit()
        with pytest.raises(InputError):
            fleet.submit(reports=np.zeros((4, 4)), session="x")

    def test_unknown_session_refused(self, fleet):
        with pytest.raises(InputError):
            fleet.submit(session="never-created")


class TestSessionContract:
    def test_session_rounds_bit_identical_to_single_box(self, fleet,
                                                        tmp_path):
        """create/append/resolve through the fleet == the same traffic
        on a lone DurableSession, bit for bit, on either transport."""
        name = f"rounds-{fleet._test_transport}"
        owner = fleet.create_session(name, n_reporters=10)
        assert owner in fleet.workers
        ref = DurableSession.create(tmp_path / "ref", name, 10)
        for k in range(2):
            for j in range(2):
                n = fleet.append(name, make_block(k, j))
                ref.append(make_block(k, j))
                assert n == ref.n_events
            got = fleet.submit(session=name).result(timeout=120)
            want = ref.resolve()
            np.testing.assert_array_equal(
                np.asarray(got["agents"]["smooth_rep"]),
                np.asarray(want["smooth_rep"]), err_msg=f"round {k}")
            np.testing.assert_array_equal(
                np.asarray(got["events"]["outcomes_adjusted"]),
                np.asarray(want["outcomes_adjusted"]),
                err_msg=f"round {k}")

    def test_session_state_routes(self, fleet):
        name = f"state-{fleet._test_transport}"
        fleet.create_session(name, n_reporters=10)
        fleet.append(name, make_block(9, 0))
        st = fleet.session_state(name)
        assert st["session"] == name
        assert st["rounds_resolved"] == 0
        assert st["staged_blocks"] == 1

    def test_duplicate_session_refused(self, fleet):
        name = f"dup-{fleet._test_transport}"
        fleet.create_session(name, n_reporters=10)
        with pytest.raises(InputError):
            fleet.create_session(name, n_reporters=10)

    def test_bad_append_shape_refused_structured(self, fleet):
        name = f"shape-{fleet._test_transport}"
        fleet.create_session(name, n_reporters=10)
        with pytest.raises(InputError):
            fleet.append(name, np.zeros((7, 3)))


class TestKillMidTraffic:
    def test_kill_worker_zero_lost_rounds(self, tmp_path, rng):
        """The chaos contract on BOTH transports: kill the session's
        owner mid-round; the standby adopts the (shipped) log and every
        round resolves bit-identical to the never-killed run."""
        for transport in TRANSPORTS:
            fleet = ConsensusFleet(FleetConfig(
                n_workers=3, transport=transport, monitor=True,
                heartbeat_timeout_s=1.0, heartbeat_interval_s=0.25,
                log_dir=str(tmp_path / f"fleet-{transport}"),
                worker=ServeConfig(warmup=(), batch_window_ms=1.0,
                                   pallas_buckets=False))).start()
            try:
                owner = fleet.create_session("m", n_reporters=10)
                fleet.append("m", make_block(0, 0))
                r0 = fleet.submit(session="m").result(timeout=120)
                fleet.append("m", make_block(1, 0))   # mid-round 1
                fleet.kill_worker(owner)
                st = retried(lambda: fleet.session_state("m"))
                assert st["rounds_resolved"] == 1
                assert st["staged_blocks"] == 1       # journal survived
                assert fleet.owner_of("m") != owner
                r1 = retried(
                    lambda: fleet.submit(session="m").result(120))

                ref = DurableSession.create(
                    tmp_path / f"ref-{transport}", "m", 10)
                for k, got in enumerate((r0, r1)):
                    ref.append(make_block(k, 0))
                    want = ref.resolve()
                    np.testing.assert_array_equal(
                        np.asarray(got["agents"]["smooth_rep"]),
                        np.asarray(want["smooth_rep"]),
                        err_msg=f"{transport} round {k}")
                    np.testing.assert_array_equal(
                        np.asarray(got["events"]["outcomes_adjusted"]),
                        np.asarray(want["outcomes_adjusted"]),
                        err_msg=f"{transport} round {k}")
            finally:
                fleet.close(drain=False, timeout=10.0)

    def test_all_workers_dead_is_placement_error(self, tmp_path):
        for transport in TRANSPORTS:
            fleet = ConsensusFleet(FleetConfig(
                n_workers=1, transport=transport,
                log_dir=str(tmp_path / f"dead-{transport}"),
                worker=ServeConfig(warmup=(),
                                   pallas_buckets=False))).start()
            try:
                fleet.create_session("s", n_reporters=10)
                fleet.kill_worker("w0")
                with pytest.raises((PlacementError, WorkerLostError,
                                    FailoverInProgressError)):
                    retried(lambda: fleet.submit(session="s")
                            .result(10), attempts=3)
            finally:
                fleet.close(drain=False, timeout=10.0)


class TestSheddingContract:
    def test_draining_fleet_sheds_structured(self, tmp_path):
        """After close(), submits shed PYC-coded on both transports
        (never a hang, never a raw socket error)."""
        for transport in TRANSPORTS:
            fleet = ConsensusFleet(FleetConfig(
                n_workers=1, transport=transport,
                log_dir=str(tmp_path / f"drain-{transport}"),
                worker=ServeConfig(warmup=(),
                                   pallas_buckets=False))).start()
            fleet.close(drain=True, timeout=30.0)
            with pytest.raises((ServiceOverloadError, WorkerLostError)):
                fut = fleet.submit(reports=np.zeros((4, 4)) + 1.0)
                fut.result(timeout=30)

    def test_status_shape(self, fleet):
        status = fleet.status()
        assert set(status) >= {"workers", "alive", "alive_slots",
                               "sessions", "failovers"}
        assert status["alive"] == 2
        for w in status["workers"].values():
            assert set(w) == {"alive", "queue_depth"}
