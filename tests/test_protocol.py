"""consensus-lint Layer 5 (ISSUE 16): trigger/no-trigger corpus for the
distributed-protocol rules CL901-CL905 — including the three REAL
orderings the fleet ships (ack-iff-shipped append, commit-then-ship
resolve, unlink-on-failed-fold) — the pragma conventions, the live
package-is-clean invariant, the static happens-before export, the
runtime ProtocolWitness (green over real durable-session operations, a
deliberately reordered mock worker flagged), the error-code docs drift
checker, and the ``--format json`` finding schema."""

import io
import json
import pathlib
import sys
import textwrap

import numpy as np
import pytest

from pyconsensus_tpu.analysis.cli import run as cli_run
from pyconsensus_tpu.analysis.concurrency import _Package
from pyconsensus_tpu.analysis.protocol import (PROTOCOL_RULES, _analyze,
                                               analyze_protocol,
                                               happens_before)
from pyconsensus_tpu.analysis.protocol_witness import (
    ProtocolWitness, ProtocolWitnessViolation, protocol_witnessed,
    static_protocol_graph)
from pyconsensus_tpu.analysis.rules import scan_targets

REPO = pathlib.Path(__file__).resolve().parents[1]


def _write(tmp_path, **files):
    for name, src in files.items():
        (tmp_path / f"{name}.py").write_text(textwrap.dedent(src))


def _proto(tmp_path, **files):
    """Write ``name -> source`` modules and run Layer 5 over the dir
    (a path-restricted scan, as the CLI does for explicit targets)."""
    _write(tmp_path, **files)
    return analyze_protocol(paths=[tmp_path])


def _proto_full(tmp_path, **files):
    """Same corpus, analyzed as a FULL scan — enables the whole-surface
    directions (dead server entries, handle diff, RETRYABLE coverage,
    package-level idempotency) that a path-restricted run holds back."""
    _write(tmp_path, **files)
    pkg = _Package(scan_targets([tmp_path]))
    return _analyze(pkg, None, full_scan=True)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- CL901


class TestDurabilityOrdering:
    def test_ack_before_journal_in_dispatch_handler_triggers(self, tmp_path):
        """The seeded reorder of the acceptance criteria: a worker
        dispatch handler resolves the Future BEFORE the journal write.
        The finding names both events."""
        fs = _proto(tmp_path, w="""
            class Worker:
                def handlers(self):
                    return {"append": self.append}

                def append(self, params):
                    self._fut.set_result(1)
                    self._log.journal_block(params["block"])
                    return {"total": 1}
            """)
        assert "CL901" in _rules(fs)
        f = next(f for f in fs if f.rule == "CL901")
        assert "set_result" in f.message and "journal_block" in f.message

    def test_reply_return_before_ship_in_finally_triggers(self, tmp_path):
        """Returning from a dispatch handler IS the ack — a ship parked
        in a ``finally`` after the return is an ack-before-ship."""
        fs = _proto(tmp_path, w="""
            class Worker:
                def handlers(self):
                    return {"append": self.append}

                def append(self, params):
                    try:
                        self._log.journal_block(params["block"])
                        return {"total": 1}
                    finally:
                        self.shipper.ship_file("s", "r", "p")
            """)
        assert any(f.rule == "CL901" and "ship" in f.message for f in fs)

    def test_ship_before_journal_triggers(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class Worker:
                def reordered(self, block):
                    self.shipper.ship_file("s", "r", "p")
                    self._log.journal_block(block)
            """)
        assert any(f.rule == "CL901" and "ship_file" in f.message
                   and "journal_block" in f.message for f in fs)

    def test_swallowing_handler_on_durability_path_triggers(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class Worker:
                def append(self, block):
                    try:
                        self._log.journal_block(block)
                    except Exception:
                        pass
            """)
        assert any(f.rule == "CL901" and "neither re-raises" in f.message
                   for f in fs)

    def test_real_ordering_ack_iff_shipped_append_is_clean(self, tmp_path):
        """The shipped ordering of FleetWorkerProcess.append: journal
        (the append_id-threaded mutation), then ship, then reply."""
        fs = _proto(tmp_path, w="""
            class Worker:
                def handlers(self):
                    return {"append": self.append}

                def append(self, params):
                    total = self.session.append(
                        params["block"], append_id=params.get("append_id"))
                    self._ship_session(params["name"])
                    return {"total_events": int(total)}

                def _ship_session(self, name):
                    for rel, path in self.pending(name):
                        self.shipper.ship_file(name, rel, path)
            """)
        assert fs == []

    def test_real_ordering_commit_then_ship_resolve_is_clean(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class Worker:
                def handlers(self):
                    return {"resolve": self.resolve}

                def resolve(self, params):
                    self._log.commit_round(self.ledger)
                    self.shipper.ship_file("s", "ledger.npz", "p")
                    return {"ok": True}
            """)
        assert fs == []

    def test_real_ordering_unlink_on_failed_fold_is_clean(self, tmp_path):
        """DurableSession.append's BaseException handler: the journal
        record of a failed fold is withdrawn, then the error re-raised
        — both unlink and raise satisfy the fence discipline."""
        fs = _proto(tmp_path, w="""
            class Session:
                def append(self, block, append_id=None):
                    rec = self._log.journal_block(block, append_id=append_id)
                    try:
                        total = self._fold(block)
                    except BaseException:
                        rec.unlink()
                        raise
                    return total
            """)
        assert fs == []

    def test_fencing_handler_is_clean(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class Session:
                def resolve(self):
                    try:
                        self._log.commit_round(self.ledger)
                    except BaseException as exc:
                        self.session.fence(exc)
                        raise
            """)
        assert fs == []

    def test_handler_inside_handler_is_exempt(self, tmp_path):
        """Best-effort cleanup inside an outer handler (the fence call
        itself wrapped in try/except pass) must not be flagged — the
        real shape of FleetWorkerProcess._ship_session."""
        fs = _proto(tmp_path, w="""
            class Worker:
                def _ship(self, name):
                    try:
                        self.shipper.ship_file(name, "r", "p")
                    except Exception as exc:
                        try:
                            self.sessions.get(name).fence(exc)
                        except Exception:
                            pass
                        raise
            """)
        assert fs == []

    def test_dedupe_fastpath_return_is_clean(self, tmp_path):
        """The idempotent-replay fast path acks WITHOUT journaling —
        an early return must not poison the durable path below it."""
        fs = _proto(tmp_path, w="""
            class Worker:
                def handlers(self):
                    return {"append": self.append}

                def append(self, params):
                    if params["append_id"] in self._seen:
                        return {"total": self._total, "deduped": True}
                    self._log.journal_block(
                        params["block"], append_id=params["append_id"])
                    return {"total": 1}
            """)
        assert fs == []

    def test_pragma_with_rationale_suppresses(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class Worker:
                def handlers(self):
                    return {"append": self.append}

                def append(self, params):
                    self._fut.set_result(1)
                    self._log.journal_block(params["block"])  # consensus-lint: disable=CL901 — corpus: deliberate
                    return {"total": 1}
            """)
        assert [f for f in fs if f.rule == "CL901"] == []


# ------------------------------------------------------------- CL902


class TestRpcSurfaceDrift:
    SERVER = """
        class Server:
            def handlers(self):
                return {"ping": self.ping}

            def ping(self, params):
                return {}
        """

    def test_client_method_without_server_entry_triggers(self, tmp_path):
        fs = _proto(tmp_path, s=self.SERVER, c="""
            class Client:
                def hit(self):
                    return self._ctl.call("pong", {})
            """)
        assert any(f.rule == "CL902" and "'pong'" in f.message
                   for f in fs)

    def test_retry_wrapped_call_counts_as_client_use(self, tmp_path):
        """LogShipper's idiom: retry_call(self._client.call, "ship",
        ...) — the method string is argument two of the wrapper."""
        fs = _proto(tmp_path, s=self.SERVER, c="""
            class Client:
                def hit(self):
                    return retry_call(self._ctl.call, "ping", {},
                                      retries=3, retry_on=(OSError,))
            """)
        assert [f for f in fs if f.rule == "CL902"] == []

    def test_dead_server_entry_full_scan_only(self, tmp_path):
        fs = _proto_full(tmp_path, s=self.SERVER, c="""
            class Client:
                def hit(self):
                    return self._ctl.call("ping", {})
            """, s2="""
            class Extra:
                def handlers(self):
                    return {"stats": self.stats}

                def stats(self, params):
                    return {}
            """)
        assert any(f.rule == "CL902" and "'stats'" in f.message
                   and "no client invocation" in f.message for f in fs)

    def test_handle_surface_diff_full_scan_only(self, tmp_path):
        fs = _proto_full(tmp_path, h="""
            class WorkerBase:
                def submit(self, req):
                    raise NotImplementedError

            class InProc(WorkerBase):
                def submit(self, req):
                    return 1

                def drain(self):
                    return 0

            class Socket(WorkerBase):
                def submit(self, req):
                    return 2
            """)
        assert any(f.rule == "CL902" and "'drain'" in f.message
                   and "Socket" in f.message for f in fs)


# ------------------------------------------------------------- CL903


class TestErrorTaxonomy:
    def test_taxonomy_drift_directions(self, tmp_path):
        fs = _proto_full(tmp_path, e="""
            class ConsensusError(Exception):
                error_code = "PYC000"

                def __init__(self, message="", **context):
                    super().__init__(message)
                    self.context = context

            class GoodError(ConsensusError):
                error_code = "PYC901"

            class OrphanError(ConsensusError):
                error_code = "PYC902"

            class DupError(ConsensusError):
                error_code = "PYC901"

            class FatError(ConsensusError):
                error_code = "PYC903"

                def __init__(self, message, extra):
                    super().__init__(message)
                    self.extra = extra

            ERROR_CODES = {cls.error_code: cls for cls in (
                ConsensusError, GoodError, DupError, FatError,
                GhostError)}
            """)
        msgs = [f.message for f in fs if f.rule == "CL903"]
        assert any("OrphanError" in m and "not in the ERROR_CODES" in m
                   for m in msgs)
        assert any("GhostError" in m and "dead registry entry" in m
                   for m in msgs)
        assert any("'PYC901'" in m and "claimed by both" in m
                   for m in msgs)
        assert any("FatError.__init__" in m and "not marshalable" in m
                   for m in msgs)

    def test_retryable_codes_consistency(self, tmp_path):
        fs = _proto_full(tmp_path, e="""
            class ConsensusError(Exception):
                error_code = "PYC000"

                def __init__(self, message="", **context):
                    self.context = context

            class ShedError(ConsensusError):
                error_code = "PYC901"

            class QuietError(ConsensusError):
                error_code = "PYC902"

            ERROR_CODES = {cls.error_code: cls for cls in (
                ConsensusError, ShedError, QuietError)}

            RETRYABLE_CODES = ("PYC901", "PYC999")

            def shed():
                raise ShedError("full", retry_after_s=0.5)

            def quiet():
                raise QuietError("odd", retry_after_s=1.0)
            """)
        msgs = [f.message for f in fs if f.rule == "CL903"]
        # PYC999: listed retryable, no class carries it
        assert any("'PYC999'" in m and "no scanned taxonomy class" in m
                   for m in msgs)
        # PYC902: raised with an honest hint but not listed retryable
        assert any("PYC902" in m and "not in RETRYABLE_CODES" in m
                   for m in msgs)
        # PYC901 is consistent: listed AND hinted — no finding names it
        assert not any("'PYC901'" in m for m in msgs)


# ------------------------------------------------------------- CL904


class TestIdempotencyCoverage:
    def test_dropped_token_triggers(self, tmp_path):
        fs = _proto(tmp_path, w="""
            def append(block, append_id=None):
                return fold(block)
            """)
        assert any(f.rule == "CL904" and "drops it" in f.message
                   for f in fs)

    def test_forwarded_token_is_clean(self, tmp_path):
        fs = _proto(tmp_path, w="""
            def append(log, block, append_id=None):
                return log.journal_block(block, append_id=append_id)

            def wire_forward(ctl, block, append_id=None):
                return ctl.call("append", {"block": block,
                                           "append_id": append_id})
            """)
        assert [f for f in fs if f.rule == "CL904"] == []

    def test_missing_dedupe_guard_and_seed_full_scan(self, tmp_path):
        fs = _proto_full(tmp_path, w="""
            def append(log, block, append_id=None):
                return log.journal_block(block, append_id=append_id)
            """)
        msgs = [f.message for f in fs if f.rule == "CL904"]
        assert any("membership-tests" in m for m in msgs)
        assert any("seeds a dedupe set" in m for m in msgs)
        assert all(f.path == "protocol:idempotency"
                   for f in fs if f.rule == "CL904")

    def test_guard_and_seed_present_is_clean(self, tmp_path):
        fs = _proto_full(tmp_path, w="""
            def append(log, seen, block, append_id=None):
                if append_id is not None and append_id in seen:
                    return 0
                rec = log.journal_block(block, append_id=append_id)
                seen.add(append_id)
                return rec
            """)
        assert [f for f in fs if f.rule == "CL904"] == []


# ------------------------------------------------------------- CL905


class TestRetryScope:
    def test_retry_on_taxonomy_error_triggers(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class ShedError(RuntimeError):
                error_code = "PYC901"

            def fetch(dial):
                return retry_call(dial, retries=3,
                                  retry_on=(OSError, ShedError))
            """)
        assert any(f.rule == "CL905" and "ShedError" in f.message
                   for f in fs)

    def test_blanket_exception_retry_triggers(self, tmp_path):
        fs = _proto(tmp_path, w="""
            def fetch(dial):
                return retry_call(dial, retries=3, retry_on=(Exception,))
            """)
        assert any(f.rule == "CL905" and "Exception" in f.message
                   for f in fs)

    def test_transient_oserror_retry_is_clean(self, tmp_path):
        fs = _proto(tmp_path, w="""
            def fetch(dial):
                return retry_call(dial, retries=3, retry_on=(OSError,))
            """)
        assert [f for f in fs if f.rule == "CL905"] == []

    def test_retry_after_durability_point_triggers(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class Worker:
                def flush(self, block):
                    self._log.journal_block(block)
                    retry_call(self._send, retries=3, retry_on=(OSError,))
            """)
        assert any(f.rule == "CL905"
                   and "after the durability point" in f.message
                   for f in fs)

    def test_retry_inside_fencing_handler_triggers(self, tmp_path):
        fs = _proto(tmp_path, w="""
            class Worker:
                def risky(self, block):
                    try:
                        self._log.journal_block(block)
                    except Exception as exc:
                        self.session.fence(exc)
                        retry_call(self._send, retry_on=(OSError,))
            """)
        assert any(f.rule == "CL905" and "fencing handler" in f.message
                   for f in fs)


# ---------------------------------------------------- the live package


class TestLivePackage:
    def test_package_is_clean(self):
        """The shipped baseline stays EMPTY: Layer 5 over the installed
        package — every real finding was fixed or pragma'd with
        rationale in place."""
        fs = analyze_protocol()
        assert fs == [], [f.render() for f in fs]

    def test_rules_registered(self):
        assert set(PROTOCOL_RULES) == {"CL901", "CL902", "CL903",
                                       "CL904", "CL905"}
        assert all(sev == "error" for sev, _ in PROTOCOL_RULES.values())

    def test_happens_before_matches_shipped_orderings(self):
        """The static graph must state the three real orderings the
        fleet documents: journal->ship->ack appends, commit(->ship)->ack
        resolves — these orders are what ROBUSTNESS.md promises."""
        ops = happens_before()["ops"]
        assert ops["session.append"]["order"] == ["journal", "ack"]
        assert ops["session.resolve"]["order"] == ["commit", "ack"]
        assert ops["worker.append"]["order"] == ["journal", "ship", "ack"]
        assert ops["worker.submit_session"]["order"] == ["ship", "ack"]
        assert ops["worker.create_session"]["order"] == \
            ["commit", "ship", "ack"]
        for spec in ops.values():
            assert ["journal", "ack"] not in [[b, a]
                                              for a, b in spec["edges"]]


# ------------------------------------------------------------ witness


class TestProtocolWitness:
    def _session(self, root, name="pw", n=6):
        from pyconsensus_tpu.serve.failover import DurableSession

        return DurableSession.create(root, name, n)

    def test_green_over_real_session_ops(self, tmp_path):
        """Real DurableSession append + resolve under the witness:
        observed orders consistent with the static graph."""
        rng = np.random.default_rng(0)
        static = static_protocol_graph()
        with protocol_witnessed(static=static,
                                dump_path=tmp_path / "pw.json") as w:
            s = self._session(tmp_path / "log")
            s.append(rng.choice([0.0, 1.0], size=(6, 4)))
            s.resolve()
        kinds = {r["kind"]: r["events"] for r in w.report()["ops"]}
        assert kinds["session.append"] == ["journal", "ack"]
        assert kinds["session.resolve"] == ["commit", "ack"]

    def test_reordered_mock_worker_is_flagged(self, tmp_path):
        """The regression of the acceptance criteria: a mock worker
        that SHIPS before it journals — the witness must contradict the
        static ``journal -> ship`` edge of worker.append."""
        from pyconsensus_tpu.serve.transport.shipping import (
            LogShipper, ShippingReceiver)

        rng = np.random.default_rng(1)
        static = static_protocol_graph()
        rcv = ShippingReceiver(tmp_path / "shipped").start()
        try:
            s = self._session(tmp_path / "log", name="re")
            s.append(rng.choice([0.0, 1.0], size=(6, 4)))
            stale = sorted((tmp_path / "log" / "re").glob("*.npz"))[0]
            w = ProtocolWitness().install()
            try:
                shipper = LogShipper(rcv.host, rcv.port)
                with w.op("worker.append"):
                    # the reorder: ship a record, THEN journal the next
                    shipper.ship_file("re", stale.name, stale)
                    s.append(rng.choice([0.0, 1.0], size=(6, 4)))
                shipper.close()
            finally:
                w.uninstall()
            with pytest.raises(ProtocolWitnessViolation) as ei:
                w.check(static=static, dump_path=tmp_path / "viol.json")
            assert ei.value.op == "worker.append"
            assert ei.value.edge == ("journal", "ship")
            assert ei.value.events[:2] == ["ship", "journal"]
            dumped = json.loads(
                pathlib.Path(ei.value.dump_path).read_text())
            assert any(r["kind"] == "worker.append"
                       for r in dumped["ops"])
        finally:
            rcv.close()

    def test_failed_op_is_unconstrained(self, tmp_path):
        """An operation that raised never acked — the static order is a
        promise about acks, so a partial event trail must not fail."""
        static = static_protocol_graph()
        w = ProtocolWitness().install()
        try:
            with pytest.raises(RuntimeError):
                with w.op("worker.append"):
                    w._record("ship")     # partial, then death
                    raise RuntimeError("kill -9 stand-in")
        finally:
            w.uninstall()
        rec = w.report()["ops"][0]
        assert rec["ok"] is False and "ack" not in rec["events"]
        w.check(static=static)

    def test_unscoped_events_are_counted_not_judged(self, tmp_path):
        """Durability events with no operation frame open (genesis
        create, direct ReplicationLog use) are counted, not ordered."""
        static = static_protocol_graph()
        with protocol_witnessed(static=static) as w:
            self._session(tmp_path / "log", name="gen")
        assert w.report()["unscoped"].get("commit", 0) >= 1

    def test_uninstall_restores_methods(self):
        from pyconsensus_tpu.serve.failover import DurableSession

        real = DurableSession.append
        w = ProtocolWitness().install()
        assert DurableSession.append is not real
        w.uninstall()
        assert DurableSession.append is real


# ------------------------------------------------ error-code docs pin


class TestErrorDocs:
    def _tool(self):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import check_error_docs
        finally:
            sys.path.pop(0)
        return check_error_docs

    def test_live_tree_in_sync(self):
        undocumented, unregistered, mismatched = self._tool().check()
        assert undocumented == [], undocumented
        assert unregistered == [], unregistered
        assert mismatched == [], mismatched
        assert len(self._tool().collect_registered()) >= 12

    def test_detects_drift_directions(self, tmp_path):
        tool = self._tool()
        errors = tmp_path / "errors.py"
        errors.write_text(textwrap.dedent("""
            class AError(Exception):
                error_code = "PYC901"

            class BError(Exception):
                error_code = "PYC902"

            ERROR_CODES = {cls.error_code: cls for cls in (AError, BError)}
            """))
        catalog = tmp_path / "ROB.md"
        catalog.write_text(
            "| PYC901 | `AError` | `Exception` | fine |\n"
            "| PYC903 | `CError` | `Exception` | ghost row |\n")
        registered = tool.collect_registered(errors)
        documented = tool.collect_documented(catalog)
        assert registered == {"PYC901": "AError", "PYC902": "BError"}
        assert sorted(set(registered) - set(documented)) == ["PYC902"]
        assert sorted(set(documented) - set(registered)) == ["PYC903"]


# --------------------------------------------------- --format json


class TestJsonOutput:
    CORPUS = """
        def fetch(dial):
            return retry_call(dial, retries=3, retry_on=(Exception,))
        """

    def _run(self, args):
        buf = io.StringIO()
        code = cli_run(args, stdout=buf)
        return code, buf.getvalue()

    def test_schema_and_exit_code(self, tmp_path):
        target = tmp_path / "m.py"
        target.write_text(textwrap.dedent(self.CORPUS))
        code, out = self._run(["--format", "json", "--no-baseline",
                               "--select", "CL905", str(target)])
        assert code == 1
        payload = json.loads(out)
        assert payload["schema"] == 1
        assert payload["stale_baseline"] == []
        (row,) = payload["findings"]
        assert set(row) == {"rule", "path", "line", "severity",
                            "message", "snippet", "fingerprint", "state"}
        assert row["rule"] == "CL905" and row["state"] == "new"
        assert row["severity"] == "error" and row["line"] > 0
        assert "retry_call" in row["snippet"]
        # legacy keys unchanged for existing consumers
        assert len(payload["new"]) == 1
        assert payload["baselined"] == 0

    def test_baselined_state_and_exit_zero(self, tmp_path):
        target = tmp_path / "m.py"
        target.write_text(textwrap.dedent(self.CORPUS))
        baseline = tmp_path / "baseline.json"
        code, _ = self._run(["--update-baseline", "--baseline",
                             str(baseline), "--select", "CL905",
                             str(target)])
        assert code == 0
        code, out = self._run(["--format", "json", "--baseline",
                               str(baseline), "--select", "CL905",
                               str(target)])
        assert code == 0
        payload = json.loads(out)
        (row,) = payload["findings"]
        assert row["state"] == "baselined"
        assert payload["new"] == [] and payload["baselined"] == 1

    def test_clean_tree_empty_findings(self, tmp_path):
        target = tmp_path / "m.py"
        target.write_text("def ok():\n    return 1\n")
        code, out = self._run(["--format", "json", "--no-baseline",
                               str(target)])
        assert code == 0
        assert json.loads(out)["findings"] == []
