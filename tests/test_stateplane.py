"""The million-session state plane (ISSUE 20): log compaction, tiered
residency, and live rebalancing.

The chaos matrix this file pins: a SIGKILL (the in-process
``SimulatedCrash`` model — a BaseException that escapes every recovery
``except Exception``) during snapshot write, journal truncation, or
migration, at EVERY fence point, never loses an acknowledged round —
the replay is digest-equal to an uninterrupted reference run. A torn or
corrupt snapshot over an intact journal is refused and rebuilt; a torn
snapshot over a truncated journal is the one unrecoverable local state
and raises the structured PYC303. Cold-vs-hot resolution is bitwise
identical, and LRU eviction respects the durability fence.
"""

import os
import threading

import numpy as np
import pytest

from fleet_worker import N_REPORTERS, make_block
from pyconsensus_tpu import faults, obs
from pyconsensus_tpu.faults import (CheckpointCorruptionError,
                                    FailoverInProgressError, FaultPlan,
                                    InputError, SimulatedCrash,
                                    SnapshotCorruptionError)
from pyconsensus_tpu.serve import (ConsensusFleet, DurableSession,
                                   FleetConfig, MarketSession,
                                   ServeConfig, replay_session)
from pyconsensus_tpu.serve.service import ConsensusService
from pyconsensus_tpu.serve.stateplane import (CompactionPolicy, Compactor,
                                              TieredSessionStore,
                                              load_snapshot, snapshot_hint,
                                              write_snapshot)

BITS_KEYS = ("smooth_rep", "outcomes_final", "outcomes_adjusted",
             "old_rep", "avg_certainty")


@pytest.fixture(autouse=True)
def _under_lock_witness(lock_witness):
    """State-plane tests run under the runtime lock witness (ISSUE 9):
    compactor / tiered-store / migration acquisitions must stay
    consistent with the declared CL801 hierarchy."""
    yield


@pytest.fixture(autouse=True)
def _under_protocol_witness(protocol_witness):
    """And under the protocol witness (ISSUE 16): compaction must not
    reorder any journal/commit/ack edge the CL901 graph declares."""
    yield


def assert_same_bits(got: dict, ref: dict, msg: str = "") -> None:
    for key in BITS_KEYS:
        np.testing.assert_array_equal(
            np.asarray(got[key]), np.asarray(ref[key]),
            err_msg=f"{msg} [{key}]")


def drive(session, rounds=2, blocks=3, resolve_last=False):
    """Deterministic traffic: ``blocks`` appends then a resolve per
    round; the final round's journal is left OPEN (staged but
    unresolved) unless ``resolve_last`` — compaction's target state."""
    results = []
    for k in range(rounds):
        for j in range(blocks):
            session.append(make_block(k, j))
        if k < rounds - 1 or resolve_last:
            results.append(session.resolve())
    return results


def reference_session(tmp_path, name="ref", rounds=2, blocks=3):
    ref = DurableSession.create(str(tmp_path / "refroot"), name,
                                N_REPORTERS)
    results = drive(ref, rounds=rounds, blocks=blocks)
    return ref, results


# -- the snapshot record ----------------------------------------------------


class TestSnapshotRecord:
    def test_round_trip_bit_identical(self, tmp_path):
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        staged = session._log.staged(session.ledger.round)
        path = write_snapshot(session._log, session.ledger.round, staged,
                              {"a1", "a2"}, session.ledger._state_tree())
        snap = load_snapshot(path)
        assert snap["round"] == session.ledger.round
        assert snap["dedupe"] == {"a1", "a2"}
        assert len(snap["blocks"]) == len(staged)
        for (got_b, got_bounds, got_aid), (b, bounds, aid) in zip(
                snap["blocks"], staged):
            np.testing.assert_array_equal(got_b, np.asarray(b))
            assert got_bounds == bounds and got_aid == aid
        np.testing.assert_array_equal(
            snap["ledger"]["reputation"],
            session.ledger._state_tree()["reputation"])

    def test_torn_file_refused_with_hint(self, tmp_path):
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        staged = session._log.staged(session.ledger.round)
        path = write_snapshot(session._log, session.ledger.round, staged,
                              set(), session.ledger._state_tree())
        raw = bytearray(path.read_bytes())
        mid = len(raw) // 2             # a block member's payload: the
        raw[mid:mid + 8] = b"\xff" * 8  # zip directory stays readable
        path.write_bytes(bytes(raw))
        with pytest.raises(CheckpointCorruptionError):
            load_snapshot(path)
        assert snapshot_hint(path) == (session.ledger.round, len(staged))

    def test_unreadable_file_refused_without_hint(self, tmp_path):
        path = tmp_path / "snapshot.npz"
        path.write_bytes(b"not a zip archive at all")
        with pytest.raises(CheckpointCorruptionError, match="unreadable"):
            load_snapshot(path)
        assert snapshot_hint(path) is None


# -- compaction -------------------------------------------------------------


class TestCompaction:
    def test_compact_replay_bit_identical(self, tmp_path):
        """THE contract: snapshot + suffix replays bit-identical to the
        full, never-compacted log — compaction changes bytes on disk,
        never bits in any result."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        report = session.compact()
        assert report["records_removed"] > 0
        assert report["bytes_after"] < report["bytes_before"]

        replayed = replay_session(tmp_path, "s")
        ref, _ = reference_session(tmp_path)
        np.testing.assert_array_equal(replayed.ledger.reputation,
                                      ref.ledger.reputation)
        assert replayed.ledger.round == ref.ledger.round
        assert len(replayed._blocks) == len(ref._blocks)
        replayed.append(make_block(1, 3))
        ref.append(make_block(1, 3))
        assert_same_bits(replayed.resolve(), ref.resolve(),
                         "post-compaction resolve")

    def test_journal_bytes_shrink(self, tmp_path):
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        before = session.journal_bytes()
        session.compact()
        assert session.journal_bytes() < before

    def test_dedupe_survives_compaction(self, tmp_path):
        """A committed round's idempotency tokens used to die with the
        journal GC; the snapshot's cumulative dedupe set is their ONLY
        durable record — a replayed session must still acknowledge a
        retried append without folding it twice."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        session.append(make_block(0, 0), append_id="tok-0")
        session.resolve()
        session.append(make_block(1, 0), append_id="tok-1")
        session.compact()
        replayed = replay_session(tmp_path, "s")
        n_before = len(replayed._blocks)
        replayed.append(make_block(1, 0), append_id="tok-1")   # dup
        assert len(replayed._blocks) == n_before
        replayed.append(make_block(0, 0), append_id="tok-0")   # dup from
        assert len(replayed._blocks) == n_before               # round 0

    def test_crash_between_write_and_truncate(self, tmp_path):
        """SIGKILL after the snapshot landed but before ANY journal
        record was unlinked: replay sees snapshot + a fully duplicate
        prefix and must ignore the stale records — bit-identical."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        plan = FaultPlan(seed=0, rules=[
            {"site": "state.compact", "kind": "crash",
             "occurrences": [0]}])
        with faults.armed(plan):
            with pytest.raises(SimulatedCrash):
                session.compact()
        assert plan.fired == [("state.compact", 0, "crash")]
        self._assert_replay_matches_reference(tmp_path)

    @pytest.mark.parametrize("occurrence", [1, 2])
    def test_crash_mid_truncation(self, tmp_path, occurrence):
        """SIGKILL between unlinks: a PARTIAL duplicate prefix remains
        on disk; the snapshot-aware replay must skip exactly the
        covered records and fold the suffix once."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        plan = FaultPlan(seed=0, rules=[
            {"site": "state.compact", "kind": "crash",
             "occurrences": [occurrence]}])
        with faults.armed(plan):
            with pytest.raises(SimulatedCrash):
                session.compact()
        self._assert_replay_matches_reference(tmp_path)

    def test_torn_snapshot_write_never_truncates(self, tmp_path):
        """A snapshot torn INSIDE its atomic-write window is caught by
        the verify-before-truncate read-back: compact refuses, the
        journal stays whole, replay rebuilds, and the next compact
        replaces the torn file."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        records_before = len(session._log.staged(session.ledger.round))
        refused0 = obs.value("pyconsensus_compactions_total",
                             outcome="refused") or 0
        plan = FaultPlan(seed=0, rules=[
            {"site": "state.snapshot", "kind": "torn_write",
             "occurrences": [0]}])
        with faults.armed(plan):
            with pytest.raises(CheckpointCorruptionError):
                session.compact()
        assert len(session._log.staged(session.ledger.round)) \
            == records_before
        assert (obs.value("pyconsensus_compactions_total",
                          outcome="refused") or 0) > refused0
        # the journal survived, so a clean retry compacts for real and
        # replaces the torn file
        report = replay_session(tmp_path, "s").compact()
        assert report["records_removed"] == records_before
        self._assert_replay_matches_reference(tmp_path)

    def test_crash_inside_snapshot_write(self, tmp_path):
        """SIGKILL inside the snapshot's atomic-write window: the temp
        file dies with the process, no snapshot exists, the journal is
        untouched — replay is the plain full-log replay."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        plan = FaultPlan(seed=0, rules=[
            {"site": "state.snapshot", "kind": "crash",
             "occurrences": [0]}])
        with faults.armed(plan):
            with pytest.raises(SimulatedCrash):
                session.compact()
        assert not session._log.snapshot_path.exists()
        self._assert_replay_matches_reference(tmp_path)

    def test_truncated_journal_with_corrupt_snapshot_is_pyc303(
            self, tmp_path):
        """The one unrecoverable local state: the journal was truncated
        behind a snapshot that then went bad. Refusing with a structured
        PYC303 (naming the missing prefix) is the contract — silently
        replaying the survivors would serve different bits."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        session.compact()
        path = session._log.snapshot_path
        raw = bytearray(path.read_bytes())
        mid = len(raw) // 2
        raw[mid:mid + 8] = b"\xff" * 8
        path.write_bytes(bytes(raw))
        with pytest.raises(SnapshotCorruptionError) as exc_info:
            replay_session(tmp_path, "s")
        assert exc_info.value.error_code == "PYC303"
        assert exc_info.value.context.get("missing_prefix", 0) > 0

    def test_gap_behind_snapshot_is_pyc303(self, tmp_path):
        """Journal records missing BELOW the surviving indices while a
        snapshot file exists: the gap can only be a truncation whose
        snapshot no longer accounts for it — PYC303, not the generic
        contiguity error."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        session.compact()
        session = replay_session(tmp_path, "s")
        session.append(make_block(1, 3))
        session.append(make_block(1, 4))
        # corrupt the snapshot AND delete the covered suffix's first
        # record: survivors start above the snapshot's coverage
        path = session._log.snapshot_path
        raw = bytearray(path.read_bytes())
        raw[len(raw) // 2:len(raw) // 2 + 8] = b"\xff" * 8
        path.write_bytes(bytes(raw))
        entries = session._log._staged_entries(session.ledger.round)
        entries[0][1].unlink()
        with pytest.raises(SnapshotCorruptionError):
            replay_session(tmp_path, "s")

    def test_stale_snapshot_prefix_ignored_after_commit(self, tmp_path):
        """A resolve AFTER a compaction commits the snapshot's round:
        the snapshot is now stale — replay must ignore its block prefix
        (those blocks folded into the committed ledger) while still
        honoring its dedupe set."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        session.compact()
        session = replay_session(tmp_path, "s")
        session.resolve()                       # commits round 1
        session.append(make_block(2, 0), append_id="tok-2")
        replayed = replay_session(tmp_path, "s")
        ref, _ = reference_session(tmp_path, rounds=2)
        ref.resolve()
        ref.append(make_block(2, 0))
        assert_same_bits(replayed.resolve(), ref.resolve(),
                         "stale-snapshot replay")

    @staticmethod
    def _assert_replay_matches_reference(tmp_path):
        replayed = replay_session(tmp_path, "s")
        ref, _ = reference_session(tmp_path)
        np.testing.assert_array_equal(replayed.ledger.reputation,
                                      ref.ledger.reputation)
        assert len(replayed._blocks) == len(ref._blocks)
        replayed.append(make_block(1, 3))
        ref.append(make_block(1, 3))
        assert_same_bits(replayed.resolve(), ref.resolve(),
                         "post-crash replay")


# -- compaction policy + sweeper -------------------------------------------


class TestCompactionPolicy:
    def test_thresholds(self, tmp_path):
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(session)
        assert not CompactionPolicy().enabled()
        assert not CompactionPolicy().due(session)
        assert CompactionPolicy(rounds=1).due(session)
        assert not CompactionPolicy(rounds=10).due(session)
        assert CompactionPolicy(journal_bytes=1).due(session)
        assert not CompactionPolicy(
            journal_bytes=10 ** 9).due(session)
        assert not CompactionPolicy(rounds=1).due(
            MarketSession("m", N_REPORTERS))

    def test_negative_thresholds_refused(self):
        with pytest.raises(InputError):
            CompactionPolicy(rounds=-1)

    def test_sweep_compacts_and_counts(self, tmp_path):
        store = TieredSessionStore(hot_capacity=8)
        for i in range(3):
            s = DurableSession.create(tmp_path, f"s{i}", N_REPORTERS)
            drive(s)
            store.add(s)
        compactor = Compactor(store, CompactionPolicy(rounds=1))
        counts = compactor.sweep()
        assert counts == {"compacted": 3, "skipped": 0, "failed": 0}
        assert obs.value("pyconsensus_session_journal_bytes") \
            is not None
        # nothing due on the second pass (no rounds resolved since)
        assert compactor.sweep() == {"compacted": 0, "skipped": 0,
                                     "failed": 0}

    def test_sweep_skips_fenced_session(self, tmp_path):
        store = TieredSessionStore(hot_capacity=8)
        s = DurableSession.create(tmp_path, "s", N_REPORTERS)
        drive(s)
        s.fence(FailoverInProgressError("migrating", session="s"))
        store.add(s)
        counts = Compactor(store, CompactionPolicy(rounds=1)).sweep()
        assert counts["skipped"] == 1 and counts["compacted"] == 0

    def test_service_lifecycle(self, tmp_path):
        cfg = ServeConfig(warmup=(), hot_sessions=4, compact_rounds=1,
                          compact_interval_s=3600.0)
        service = ConsensusService(cfg)
        service.start(warmup=False)
        try:
            assert isinstance(service.sessions, TieredSessionStore)
            assert service.compactor is not None
        finally:
            service.close(drain=False)
        assert service.compactor is None

    def test_config_validation(self):
        with pytest.raises(InputError):
            ConsensusService(ServeConfig(hot_sessions=-1))
        with pytest.raises(InputError):
            ConsensusService(ServeConfig(compact_rounds=-1))
        with pytest.raises(InputError):
            ConsensusService(ServeConfig(compact_interval_s=0.0))


# -- tiered residency -------------------------------------------------------


class TestTieredStore:
    def _store(self, tmp_path, capacity=2, n=4):
        from pyconsensus_tpu.serve.stateplane import hydrate_session
        store = TieredSessionStore(hot_capacity=capacity)
        store.hydrator = lambda name: hydrate_session(tmp_path, name)
        for i in range(n):
            s = DurableSession.create(tmp_path, f"s{i}", N_REPORTERS)
            s.append(make_block(0, 0))
            store.add(s)
        return store

    def test_lru_eviction_and_owned_accounting(self, tmp_path):
        store = self._store(tmp_path)
        assert len(store.hot_names()) == 2
        assert set(store.names()) == {"s0", "s1", "s2", "s3"}
        assert store.cold_names() == ["s0", "s1"]   # LRU-first

    def test_cold_resolve_bit_identical(self, tmp_path):
        """One hydration brings a cold session back with EXACTLY the
        bits an always-hot session would have produced."""
        store = self._store(tmp_path)
        hydrated0 = obs.value(
            "pyconsensus_sessions_hydrated_total") or 0
        cold = store.get("s0")                  # pays one hydration
        assert (obs.value("pyconsensus_sessions_hydrated_total")
                - hydrated0) == 1
        ref = DurableSession.create(str(tmp_path / "ref"), "r",
                                    N_REPORTERS)
        ref.append(make_block(0, 0))
        assert_same_bits(cold.resolve(), ref.resolve(), "cold resolve")
        # now hot: the second touch pays nothing
        store.get("s0")
        assert (obs.value("pyconsensus_sessions_hydrated_total")
                - hydrated0) == 1

    def test_evicted_object_is_fenced(self, tmp_path):
        """ack-iff-durable, object side: a caller still holding the
        evicted OBJECT must not journal beside the hydrated copy — its
        next mutation is a retryable PYC502."""
        store = TieredSessionStore(hot_capacity=1)
        store.hydrator = lambda name: replay_session(tmp_path, name)
        a = DurableSession.create(tmp_path, "a", N_REPORTERS)
        store.add(a)
        b = DurableSession.create(tmp_path, "b", N_REPORTERS)
        store.add(b)                            # evicts a
        assert store.cold_names() == ["a"]
        with pytest.raises(FailoverInProgressError, match="evicted"):
            a.append(make_block(0, 0))
        fresh = store.get("a")                  # hydrated replacement
        assert fresh is not a
        fresh.append(make_block(0, 0))

    def test_busy_session_not_evicted(self, tmp_path):
        """An in-flight mutation holds the session lock; evicting it
        would break ack-iff-durable — the tier soft-overflows
        instead."""
        store = TieredSessionStore(hot_capacity=1)
        a = DurableSession.create(tmp_path, "a", N_REPORTERS)
        store.add(a)
        # a "mutation in flight": another thread holds a's session lock
        # (holding it on THIS thread would hand the lock witness a
        # session-before-store edge no real code path creates)
        acquired, release = threading.Event(), threading.Event()

        def hold():
            with a._lock:
                acquired.set()
                release.wait(timeout=30.0)

        holder = threading.Thread(target=hold)
        holder.start()
        try:
            assert acquired.wait(timeout=10.0)
            b = DurableSession.create(tmp_path, "b", N_REPORTERS)
            store.add(b)
            # a's in-flight mutation pins it hot; the eviction falls
            # through to the next candidate (b, idle and durable)
            assert store.hot_names() == ["a"]
            assert store.cold_names() == ["b"]
        finally:
            release.set()
            holder.join()

    def test_plain_sessions_pinned_hot(self, tmp_path):
        store = TieredSessionStore(hot_capacity=1)
        store.add(MarketSession("m0", N_REPORTERS))
        store.add(MarketSession("m1", N_REPORTERS))
        assert store.hot_names() == ["m0", "m1"]    # nothing durable
        assert store.cold_names() == []             # to evict to

    def test_cold_get_without_hydrator_refused(self, tmp_path):
        store = self._store(tmp_path)
        store.hydrator = None
        with pytest.raises(InputError, match="no hydrator"):
            store.get("s0")

    def test_duplicate_names_refused_across_tiers(self, tmp_path):
        store = self._store(tmp_path)
        assert "s0" in store.cold_names()
        with pytest.raises(InputError, match="already exists"):
            store.create("s0", N_REPORTERS)
        with pytest.raises(InputError, match="already exists"):
            store.add(MarketSession("s0", N_REPORTERS))

    def test_remove_cold_session(self, tmp_path):
        store = self._store(tmp_path)
        store.remove("s0")                      # cold at this point
        assert "s0" not in store.names()
        with pytest.raises(InputError):
            store.get("s0")

    def test_exactly_one_hydration_under_contention(self, tmp_path):
        store = self._store(tmp_path, capacity=2, n=3)
        assert store.cold_names() == ["s0"]
        hydrated0 = obs.value(
            "pyconsensus_sessions_hydrated_total") or 0
        got, errors = [], []

        def touch():
            try:
                got.append(store.get("s0"))
            except Exception as exc:    # noqa: BLE001 — assert below
                errors.append(exc)

        threads = [threading.Thread(target=touch) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len({id(s) for s in got}) == 1   # one shared object
        assert (obs.value("pyconsensus_sessions_hydrated_total")
                - hydrated0) == 1

    def test_hydration_fault_retries_clean(self, tmp_path):
        """A failed hydration (state.hydrate raise) surfaces to the
        caller; the NEXT getter becomes leader and succeeds — no wedged
        event, no half-hydrated session."""
        store = self._store(tmp_path)
        plan = FaultPlan(seed=0, rules=[
            {"site": "state.hydrate", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            with pytest.raises(OSError):
                store.get("s0")
            session = store.get("s0")           # retried: occurrence 1
        session.append(make_block(1, 0))


# -- live rebalancing -------------------------------------------------------


def tiered_fleet(tmp_path, n=2, **worker_kwargs):
    cfg = FleetConfig(
        n_workers=n, log_dir=str(tmp_path / "log"),
        worker=ServeConfig(warmup=(), batch_window_ms=1.0,
                           **worker_kwargs))
    return ConsensusFleet(cfg)


class TestLiveRebalancing:
    def _seed(self, fleet, names, rounds=1, blocks=2):
        refroot = str(fleet.config.log_dir) + "-ref"
        refs = {}
        for n in names:
            fleet.create_session(n, n_reporters=N_REPORTERS)
            refs[n] = DurableSession.create(refroot, n, N_REPORTERS)
            for k in range(rounds):
                for j in range(blocks):
                    fleet.append(n, make_block(k, j))
                    refs[n].append(make_block(k, j))
        return refs

    def _assert_serves_identical(self, fleet, refs, seed=0):
        for n, ref in sorted(refs.items()):
            block = make_block(90 + seed, 0)
            fleet.append(n, block)
            ref.append(block)
            got = fleet.resolve(session=n)
            want = ref.resolve()
            np.testing.assert_array_equal(
                np.asarray(got["agents"]["smooth_rep"]),
                np.asarray(want["smooth_rep"]), err_msg=n)
            np.testing.assert_array_equal(
                np.asarray(got["events"]["outcomes_final"]),
                np.asarray(want["outcomes_final"]), err_msg=n)

    def test_migrate_session_bit_identical(self, tmp_path):
        with tiered_fleet(tmp_path) as fleet:
            refs = self._seed(fleet, ["mkt"])
            src = fleet.owner_of("mkt")
            target = next(w for w in fleet.workers if w != src)
            rebal0 = obs.value(
                "pyconsensus_sessions_rebalanced_total") or 0
            assert fleet.migrate_session("mkt", target) == target
            assert fleet.owner_of("mkt") == target
            assert (obs.value("pyconsensus_sessions_rebalanced_total")
                    - rebal0) == 1
            self._assert_serves_identical(fleet, refs)

    def test_migrate_to_current_owner_is_noop(self, tmp_path):
        with tiered_fleet(tmp_path) as fleet:
            self._seed(fleet, ["mkt"])
            src = fleet.owner_of("mkt")
            rebal0 = obs.value(
                "pyconsensus_sessions_rebalanced_total") or 0
            assert fleet.migrate_session("mkt", src) == src
            assert (obs.value("pyconsensus_sessions_rebalanced_total")
                    or 0) == rebal0

    def test_migrate_unknown_refused(self, tmp_path):
        with tiered_fleet(tmp_path) as fleet:
            with pytest.raises(InputError, match="unknown"):
                fleet.migrate_session("nope")

    def test_migrate_fault_leaves_source_serving(self, tmp_path):
        """An injected state.migrate failure must NOT strand the
        session: the source re-adopts its own log and keeps serving,
        bits identical — rebalancing can fail, durability cannot."""
        with tiered_fleet(tmp_path) as fleet:
            refs = self._seed(fleet, ["mkt"])
            src = fleet.owner_of("mkt")
            target = next(w for w in fleet.workers if w != src)
            plan = FaultPlan(seed=0, rules=[
                {"site": "state.migrate", "kind": "raise",
                 "occurrences": [0], "args": {"error": "os_error"}}])
            with faults.armed(plan):
                with pytest.raises(OSError):
                    fleet.migrate_session("mkt", target)
            assert fleet.owner_of("mkt") == src
            self._assert_serves_identical(fleet, refs)
            # and a clean retry completes the move
            assert fleet.migrate_session("mkt", target) == target
            self._assert_serves_identical(fleet, refs, seed=1)

    def test_rebalance_to_moves_ring_home_keys(self, tmp_path):
        with tiered_fleet(tmp_path) as fleet:
            names = [f"mkt-{i}" for i in range(8)]
            refs = self._seed(fleet, names)
            new = fleet.add_worker()
            moved = fleet.rebalance_to(new)
            expect = sorted(n for n in names
                            if fleet.ring.owner(n) == new)
            assert sorted(n for n, _src in moved) == expect
            for n in names:
                want = new if fleet.ring.owner(n) == new \
                    else fleet.owner_of(n)
                assert fleet.owner_of(n) == want
            self._assert_serves_identical(fleet, refs)

    def test_rebalance_max_sessions_bounds_burst(self, tmp_path):
        with tiered_fleet(tmp_path) as fleet:
            names = [f"mkt-{i}" for i in range(8)]
            self._seed(fleet, names, blocks=1)
            new = fleet.add_worker()
            full = sorted(n for n in names
                          if fleet.ring.owner(n) == new)
            if len(full) < 2:
                pytest.skip("ring placed too few keys on the new "
                            "worker for a bound to bite")
            moved = fleet.rebalance_to(new, max_sessions=1)
            assert len(moved) == 1

    @pytest.mark.parametrize("occurrence", [0, 1, 2])
    def test_sigkill_mid_drain_strands_nothing(self, tmp_path,
                                               occurrence):
        """The ISSUE 20 regression pin: a SIGKILL landing mid-drain at
        ANY migration fence point must strand nothing — the sessions
        the interrupted drain left behind are moved by the death
        declaration, and every acknowledged round survives."""
        fleet = tiered_fleet(tmp_path, n=3).start(warmup=False)
        try:
            names = [f"mkt-{i}" for i in range(6)]
            refs = self._seed(fleet, names, blocks=1)
            owned: dict = {}
            for n in names:
                owned.setdefault(fleet.owner_of(n), []).append(n)
            # the most-loaded owner reaches the deepest fence point
            victim = max(sorted(owned), key=lambda w: len(owned[w]))
            n_owned = len(owned[victim])
            if n_owned <= occurrence:
                pytest.skip(f"victim owns {n_owned} sessions; fence "
                            f"point {occurrence} unreachable")
            plan = FaultPlan(seed=0, rules=[
                {"site": "state.migrate", "kind": "crash",
                 "occurrences": [occurrence]}])
            with faults.armed(plan):
                with pytest.raises(SimulatedCrash):
                    fleet.drain_worker(victim)
            # the kill: the drain died mid-flight, the worker dies for
            # real — the declaration path must finish the job
            fleet.kill_worker(victim)
            assert all(fleet.owner_of(n) != victim for n in names)
            self._assert_serves_identical(fleet, refs)
        finally:
            fleet.close(drain=False)

    def test_retried_drain_completes_after_fault(self, tmp_path):
        """An interrupted drain leaves the worker ALIVE and serving;
        retrying the drain moves the leftovers and shuts it down."""
        fleet = tiered_fleet(tmp_path, n=3).start(warmup=False)
        try:
            names = [f"mkt-{i}" for i in range(4)]
            refs = self._seed(fleet, names, blocks=1)
            victim = sorted({fleet.owner_of(n) for n in names})[0]
            plan = FaultPlan(seed=0, rules=[
                {"site": "state.migrate", "kind": "raise",
                 "occurrences": [0], "args": {"error": "os_error"}}])
            with faults.armed(plan):
                result = fleet.drain_worker(victim)
            assert not result["drained"]
            assert result.get("stranded")
            result = fleet.drain_worker(victim)
            assert result["drained"]
            assert all(fleet.owner_of(n) != victim for n in names)
            self._assert_serves_identical(fleet, refs)
        finally:
            fleet.close(drain=False)

    def test_tiered_fleet_cold_sessions_serve_identical(self, tmp_path):
        """End to end: a fleet whose workers hold 2 hot sessions while
        owning 6, with per-round compaction — every resolution (hot or
        hydrated, before or after compaction) matches the reference."""
        with tiered_fleet(tmp_path, hot_sessions=2, compact_rounds=1,
                          compact_interval_s=3600.0) as fleet:
            names = [f"mkt-{i}" for i in range(6)]
            refs = self._seed(fleet, names)
            self._assert_serves_identical(fleet, refs)
            for w in fleet.workers.values():
                if w.service.compactor is not None:
                    w.service.compactor.sweep()
            self._assert_serves_identical(fleet, refs, seed=1)
            assert (obs.value("pyconsensus_sessions_hydrated_total")
                    or 0) > 0

    def test_migration_preserves_compacted_state(self, tmp_path):
        """Migrate AFTER a compaction: the adopter replays snapshot +
        suffix and must land on the same bits."""
        with tiered_fleet(tmp_path) as fleet:
            refs = self._seed(fleet, ["mkt"], rounds=2)
            src = fleet.owner_of("mkt")
            w = fleet.workers[src]
            w.service.sessions.get("mkt").compact()
            target = next(n for n in fleet.workers if n != src)
            assert fleet.migrate_session("mkt", target) == target
            self._assert_serves_identical(fleet, refs)
