"""IO subsystem tests: report save/load round-trips, the native CSV parser
vs the numpy fallback, and event-sharded device loading (SURVEY.md §2 — the
reference has no data loader; this is the rebuild's ingestion path)."""

import numpy as np
import pytest

import jax

from pyconsensus_tpu import Oracle, _native
from pyconsensus_tpu.io import (csv_to_npy, load_reports,
                                load_reports_sharded, save_reports)
from pyconsensus_tpu.models.pipeline import ConsensusParams
from pyconsensus_tpu.parallel import make_mesh, sharded_consensus


@pytest.fixture
def matrix(rng):
    m = rng.random((17, 9))
    m[rng.random((17, 9)) < 0.2] = np.nan
    return m


def test_npy_roundtrip(tmp_path, matrix):
    p = save_reports(tmp_path / "r.npy", matrix)
    out = load_reports(p)
    np.testing.assert_array_equal(out, matrix)


def test_npy_mmap(tmp_path, matrix):
    p = save_reports(tmp_path / "r.npy", matrix)
    out = load_reports(p, mmap=True)
    assert isinstance(out, np.memmap)
    np.testing.assert_array_equal(np.asarray(out), matrix)


def test_csv_roundtrip(tmp_path, matrix):
    p = save_reports(tmp_path / "r.csv", matrix)
    out = load_reports(p)
    np.testing.assert_array_equal(out, matrix)   # repr() round-trips floats


def test_csv_native_matches_fallback(tmp_path, matrix):
    p = save_reports(tmp_path / "r.csv", matrix)
    native = _native.csv_read(p)
    if native is None:
        pytest.skip("no compiler for the native loader")
    fallback = np.genfromtxt(p, delimiter=",", filling_values=np.nan,
                             missing_values=("NA",), ndmin=2)
    np.testing.assert_array_equal(native, fallback)


def test_csv_header_and_na_tokens(tmp_path):
    p = tmp_path / "r.csv"
    p.write_text("event_a,event_b,event_c\n"
                 "1.0, 0.5 ,NA\n"
                 "na,0.0,1\n"
                 "\n"
                 "null,NaN,0.25\n")
    out = load_reports(p)
    assert out.shape == (3, 3)
    np.testing.assert_array_equal(
        out, np.array([[1.0, 0.5, np.nan],
                       [np.nan, 0.0, 1.0],
                       [np.nan, np.nan, 0.25]]))


def test_csv_plus_prefixed_numbers(tmp_path):
    """'+'-prefixed floats are valid CSV; the first row must not be
    mistaken for a header because of one."""
    p = tmp_path / "r.csv"
    p.write_text("1,+2.5\n3,4\n")
    out = load_reports(p)
    np.testing.assert_array_equal(out, np.array([[1.0, 2.5], [3.0, 4.0]]))


def test_fallback_header_detection(tmp_path):
    """The numpy fallback must skip a header exactly like the native parser
    (same matrix on machines without a compiler)."""
    from pyconsensus_tpu.io import _csv_header_lines
    p = tmp_path / "r.csv"
    p.write_text("event_a,event_b\n1,NA\n0,1\n")
    assert _csv_header_lines(p) == 1
    arr = np.genfromtxt(p, delimiter=",", skip_header=1,
                        missing_values=("NA",), filling_values=np.nan,
                        ndmin=2)
    native = _native.csv_read(p)
    if native is not None:
        np.testing.assert_array_equal(arr, native)
    p.write_text("1,NA\n0,1\n")
    assert _csv_header_lines(p) == 0
    p.write_text("\n\nNA,na,NULL\n")          # all-NA first line: data
    assert _csv_header_lines(p) == 0


@pytest.mark.xfail(
    strict=False,
    reason="environmental: loader.cpp uses floating-point std::from_chars "
           "(C++17), which this container's libstdc++ 10 does not provide "
           "(gcc shipped FP from_chars in libstdc++ 11) — needs a newer "
           "C++ standard library to build")
def test_make_per_library_targets():
    """Each library builds via its own Makefile target, so one failing to
    compile cannot block the other."""
    import pathlib
    import shutil
    import subprocess
    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("no make/g++ toolchain")
    src = pathlib.Path(__file__).parent.parent / "native"
    for target in ("cluster", "loader"):
        subprocess.run(["make", "-C", str(src), target], check=True,
                       capture_output=True, timeout=120)


def test_fallback_strict_like_native(tmp_path):
    """The pure-Python fallback must REJECT corrupt fields and ragged rows
    exactly like the native parser — never coerce them to NaN (which would
    silently turn corruption into 'non-participation' and make results
    differ between machines with and without a compiler)."""
    from pyconsensus_tpu.io import _csv_read_fallback
    p = tmp_path / "bad.csv"
    p.write_text("1,2,3\n4,bogus,6\n")
    with pytest.raises(ValueError, match="row 1"):
        _csv_read_fallback(p)
    p.write_text("1,2,3\n4,5\n")
    with pytest.raises(ValueError, match="row 1"):
        _csv_read_fallback(p)
    p.write_text("")
    with pytest.raises(ValueError, match="non-empty"):
        _csv_read_fallback(p)
    # and it must ACCEPT the full valid grammar identically: header, NA
    # markers, blank lines, +-prefixed floats
    p.write_text("event_a,event_b\n\n1.0,+2.5\nNA, 0.5 \n")
    out = _csv_read_fallback(p)
    np.testing.assert_array_equal(
        out, np.array([[1.0, 2.5], [np.nan, 0.5]]))
    native = _native.csv_read(p)
    if native is not None:
        np.testing.assert_array_equal(out, native)


def test_csv_ragged_row_rejected(tmp_path):
    if _native.load_loader() is None:
        pytest.skip("no compiler for the native loader")
    p = tmp_path / "bad.csv"
    p.write_text("1,2,3\n4,5\n")
    with pytest.raises(ValueError, match="row 1"):
        _native.csv_read(p)


def test_csv_bad_field_rejected(tmp_path):
    if _native.load_loader() is None:
        pytest.skip("no compiler for the native loader")
    p = tmp_path / "bad.csv"
    p.write_text("1,2,3\n4,bogus,6\n")
    with pytest.raises(ValueError, match="row 1"):
        _native.csv_read(p)


class TestCsvToNpy:
    def test_matches_whole_file_parse(self, tmp_path, matrix):
        """Chunked staging produces the exact matrix the whole-file CSV
        parsers produce, at every chunk size (incl. chunk > rows and a
        ragged final chunk)."""
        p = save_reports(tmp_path / "r.csv", matrix)
        whole = load_reports(p)
        for chunk_rows in (1, 5, 17, 100):
            dst = csv_to_npy(p, tmp_path / f"s{chunk_rows}.npy",
                             chunk_rows=chunk_rows)
            np.testing.assert_array_equal(np.load(dst), whole)

    def test_default_dst_and_header(self, tmp_path):
        p = tmp_path / "r.csv"
        p.write_text("a,b\n1.0,NA\n0.5,0.0\n")
        dst = csv_to_npy(p)
        assert dst == tmp_path / "r.npy"
        out = np.load(dst)
        assert out.shape == (2, 2)
        assert np.isnan(out[0, 1])

    def test_bad_field_cleans_up(self, tmp_path):
        p = tmp_path / "r.csv"
        p.write_text("1.0,2.0\n1.0,bogus\n")
        with pytest.raises(ValueError, match="data row 1"):
            csv_to_npy(p, tmp_path / "out.npy")
        assert not (tmp_path / "out.npy").exists()

    def test_ragged_row_rejected(self, tmp_path):
        p = tmp_path / "r.csv"
        p.write_text("1.0,2.0\n1.0\n")
        with pytest.raises(ValueError, match="data row 1"):
            csv_to_npy(p, tmp_path / "out.npy")

    def test_rejects_non_csv_and_empty(self, tmp_path):
        with pytest.raises(ValueError, match="stages .csv"):
            csv_to_npy(tmp_path / "r.npy")
        p = tmp_path / "empty.csv"
        p.write_text("header_a,header_b\n")
        with pytest.raises(ValueError, match="non-empty"):
            csv_to_npy(p)


def test_streaming_from_csv(tmp_path, rng):
    """streaming_consensus on a .csv source: staged in row chunks, outcomes
    identical to the in-memory resolution, staging file removed."""
    from conftest import collusion_reports
    from pyconsensus_tpu.parallel import streaming_consensus

    reports, _ = collusion_reports(rng, R=14, E=11, liars=4, na_frac=0.1)
    p = save_reports(tmp_path / "big.csv", reports)
    out = streaming_consensus(p, panel_events=4)
    ref = Oracle(reports=reports, backend="jax").consensus()
    np.testing.assert_array_equal(out["outcomes_final"],
                                  ref["events"]["outcomes_final"])
    leftovers = [f for f in tmp_path.iterdir() if "stage" in f.name]
    assert leftovers == []


def test_unknown_suffix(tmp_path, matrix):
    with pytest.raises(ValueError, match="format"):
        save_reports(tmp_path / "r.parquet", matrix)
    with pytest.raises(ValueError, match="format"):
        load_reports(tmp_path / "r.parquet")


def test_sharded_load_matches_dense(tmp_path, rng):
    """The event-sharded loaded array must resolve identically to the dense
    host path — same outcomes, same reputation."""
    R, E = 12, 16
    truth = rng.choice([0.0, 1.0], size=E)
    reports = np.tile(truth, (R, 1))
    reports[rng.random((R, E)) < 0.2] = np.nan
    p = save_reports(tmp_path / "r.npy", reports)

    mesh = make_mesh(batch=1, event=8)
    global_arr = load_reports_sharded(p, mesh)
    assert global_arr.shape == (R, E)
    assert not global_arr.sharding.is_fully_replicated

    params = ConsensusParams(algorithm="sztorc", pca_method="eigh-gram",
                             any_scaled=False, has_na=True)
    sharded = sharded_consensus(global_arr, mesh=mesh, params=params)
    dense = Oracle(reports=reports, backend="jax",
                   pca_method="eigh-gram").consensus()
    np.testing.assert_array_equal(
        np.asarray(sharded["outcomes_final"]),
        dense["events"]["outcomes_final"])
    np.testing.assert_allclose(np.asarray(sharded["smooth_rep"]),
                               dense["agents"]["smooth_rep"], atol=1e-12)


def test_sharded_load_copies_blocks(tmp_path, rng):
    """Each device holds exactly its column block of the source matrix."""
    R, E = 6, 8
    m = rng.random((R, E))
    p = save_reports(tmp_path / "r.npy", m)
    mesh = make_mesh(batch=1, event=8)
    arr = load_reports_sharded(p, mesh)
    for shard in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), m[shard.index])
