"""Kernel-level unit tests: numpy reference semantics and numpy<->jax
agreement (SURVEY.md §4 — method-level tests for the small pure functions)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pyconsensus_tpu.ops import jax_kernels as jk
from pyconsensus_tpu.ops import numpy_kernels as nk


def random_reports(rng, R=12, E=7, na_frac=0.2, scaled_frac=0.3):
    reports = rng.choice([0.0, 0.5, 1.0], size=(R, E))
    scaled = rng.random(E) < scaled_frac
    mins = np.where(scaled, -2.0, 0.0)
    maxs = np.where(scaled, 3.0, 1.0)
    raw_scaled = rng.uniform(-2.0, 3.0, size=(R, E))
    reports = np.where(scaled[None, :], raw_scaled, reports)
    na = rng.random((R, E)) < na_frac
    # keep at least one report per column
    na[rng.integers(0, R), :] = False
    reports = np.where(na, np.nan, reports)
    rep = rng.random(R) + 0.1
    rep = rep / rep.sum()
    return reports, rep, scaled, mins, maxs


class TestCatch:
    def test_boundaries(self):
        tol = 0.1
        assert nk.catch(0.39, tol) == 0.0
        assert nk.catch(0.40, tol) == 0.5   # not strictly below 0.5 - tol
        assert nk.catch(0.5, tol) == 0.5
        assert nk.catch(0.60, tol) == 0.5
        assert nk.catch(0.61, tol) == 1.0

    def test_elementwise_and_jax_match(self):
        xs = np.linspace(-0.2, 1.2, 57)
        for tol in (0.0, 0.1, 0.25):
            a = nk.catch(xs, tol)
            b = np.asarray(jk.catch(jnp.asarray(xs), tol))
            np.testing.assert_array_equal(a, b)


class TestNormalize:
    def test_sums_to_one(self):
        v = np.array([1.0, 2.0, 3.0])
        assert nk.normalize(v).sum() == pytest.approx(1.0)

    def test_negative_sum_orientation(self):
        v = np.array([-3.0, -1.0])   # the set2 orientation case
        out = nk.normalize(v)
        assert out.sum() == pytest.approx(1.0)
        assert (out >= 0).all()

    def test_zero_vector_unchanged(self):
        v = np.zeros(4)
        np.testing.assert_array_equal(nk.normalize(v), v)
        np.testing.assert_array_equal(np.asarray(jk.normalize(jnp.zeros(4))),
                                      np.zeros(4))

    def test_jax_match(self, rng):
        v = rng.normal(size=9)
        np.testing.assert_allclose(np.asarray(jk.normalize(jnp.asarray(v))),
                                   nk.normalize(v), rtol=1e-12)


class TestRescale:
    def test_round_trip(self, rng):
        reports, rep, scaled, mins, maxs = random_reports(rng)
        scaled[:] = True
        mins[:] = -5.0
        maxs[:] = 11.0
        out = nk.rescale(reports, scaled, mins, maxs)
        finite = ~np.isnan(reports)
        assert np.nanmax(out) <= 1.0 + 1e-12 and np.nanmin(out) >= -1e-12
        back = nk.unscale_outcomes(out, scaled, mins, maxs)
        np.testing.assert_allclose(back[finite], reports[finite], rtol=1e-12)

    def test_binary_passthrough_and_nan(self):
        reports = np.array([[0.0, 2.0], [np.nan, 4.0]])
        scaled = np.array([False, True])
        out = nk.rescale(reports, scaled, np.array([0.0, 2.0]),
                         np.array([1.0, 6.0]))
        assert out[0, 0] == 0.0
        assert out[0, 1] == pytest.approx(0.0)
        assert out[1, 1] == pytest.approx(0.5)
        assert np.isnan(out[1, 0])

    def test_jax_match(self, rng):
        reports, rep, scaled, mins, maxs = random_reports(rng)
        a = nk.rescale(reports, scaled, mins, maxs)
        b = np.asarray(jk.rescale(jnp.asarray(reports), jnp.asarray(scaled),
                                  jnp.asarray(mins), jnp.asarray(maxs)))
        np.testing.assert_allclose(a, b, rtol=1e-12, equal_nan=True)


class TestInterpolate:
    def test_weighted_mean_fill_binary_snap(self):
        # column 0: reporters 0,1 report {1, 1} with rep {.5, .25}; missing
        # entry fills with catch(weighted mean)=1. column 1 scaled: raw mean.
        reports = np.array([[1.0, 2.0],
                            [1.0, np.nan],
                            [np.nan, 4.0]])
        rep = np.array([0.5, 0.25, 0.25])
        scaled = np.array([False, True])
        filled = nk.interpolate(reports, rep, scaled, 0.1)
        assert filled[2, 0] == 1.0
        # scaled fill: (0.5*2 + 0.25*4) / 0.75 = 8/3
        assert filled[1, 1] == pytest.approx(8.0 / 3.0)

    def test_ambiguous_fill_snaps_to_half(self):
        reports = np.array([[1.0], [0.0], [np.nan]])
        rep = np.array([0.5, 0.5, 0.0])
        filled = nk.interpolate(reports, rep, np.array([False]), 0.1)
        assert filled[2, 0] == 0.5

    def test_no_nan_passthrough(self, rng):
        reports, rep, scaled, mins, maxs = random_reports(rng, na_frac=0.0)
        filled = nk.interpolate(reports, rep, scaled, 0.1)
        np.testing.assert_array_equal(filled, reports)

    def test_jax_match(self, rng):
        reports, rep, scaled, mins, maxs = random_reports(rng)
        rescaled = nk.rescale(reports, scaled, mins, maxs)
        a = nk.interpolate(rescaled, rep, scaled, 0.1)
        b = np.asarray(jk.interpolate(jnp.asarray(rescaled), jnp.asarray(rep),
                                      jnp.asarray(scaled), 0.1))
        np.testing.assert_allclose(a, b, rtol=1e-12)


class TestWeightedCov:
    def test_against_manual(self, rng):
        X = rng.random((6, 4))
        rep = nk.normalize(rng.random(6) + 0.1)
        cov, dev = nk.weighted_cov(X, rep)
        mu = rep @ X
        np.testing.assert_allclose(dev, X - mu, rtol=1e-12)
        manual = np.zeros((4, 4))
        for i in range(6):
            manual += rep[i] * np.outer(X[i] - mu, X[i] - mu)
        manual /= 1.0 - np.sum(rep ** 2)
        np.testing.assert_allclose(cov, manual, rtol=1e-10)

    def test_jax_match(self, rng):
        X = rng.random((6, 4))
        rep = nk.normalize(rng.random(6) + 0.1)
        cov_np, dev_np = nk.weighted_cov(X, rep)
        cov_j, dev_j = jk.weighted_cov(jnp.asarray(X), jnp.asarray(rep))
        np.testing.assert_allclose(np.asarray(cov_j), cov_np, rtol=1e-10)
        np.testing.assert_allclose(np.asarray(dev_j), dev_np, rtol=1e-12)


def _align_sign(v, ref):
    return v if np.dot(v, ref) >= 0 else -v


class TestWeightedPrinComp:
    def test_loading_is_top_eigvec(self, rng):
        X = rng.random((8, 5))
        rep = nk.normalize(rng.random(8) + 0.1)
        loading, scores = nk.weighted_prin_comp(X, rep)
        cov, dev = nk.weighted_cov(X, rep)
        w, V = np.linalg.eigh(cov)
        top = V[:, -1]
        np.testing.assert_allclose(_align_sign(loading, top), top, rtol=1e-8)
        np.testing.assert_allclose(scores, dev @ loading, rtol=1e-12)

    @pytest.mark.parametrize("method", ["eigh-cov", "eigh-gram", "power"])
    def test_jax_methods_agree_up_to_sign(self, rng, method):
        X = rng.random((10, 6))
        rep = nk.normalize(rng.random(10) + 0.1)
        load_np, _ = nk.weighted_prin_comp(X, rep)
        load_j, scores_j = jk.weighted_prin_comp(jnp.asarray(X),
                                                 jnp.asarray(rep),
                                                 method=method)
        load_j = np.asarray(load_j)
        np.testing.assert_allclose(_align_sign(load_j, load_np), load_np,
                                   rtol=0, atol=5e-6)

    def test_multi_component_explained_variance(self, rng):
        X = rng.random((9, 5))
        rep = nk.normalize(rng.random(9) + 0.1)
        loadings, scores, explained = nk.weighted_prin_comps(X, rep, 3)
        assert explained.shape == (3,)
        assert np.all(np.diff(explained) <= 1e-12)  # descending
        assert explained.sum() <= 1.0 + 1e-9
        lj, sj, ej = jk.weighted_prin_comps(jnp.asarray(X), jnp.asarray(rep), 3)
        np.testing.assert_allclose(np.asarray(ej), explained, atol=1e-8)
        lj, ej2 = np.asarray(lj), np.asarray(ej)
        for c in range(3):
            np.testing.assert_allclose(_align_sign(lj[:, c], loadings[:, c]),
                                       loadings[:, c], atol=1e-6)

    def test_orth_iter_matches_eigh(self, rng):
        """The matrix-free multi-component path (method='power' →
        _top_pcs_orth_iter — the large-R route where the Gram eigh OOMs a
        chip) must reproduce the exact eigh's top-k loadings, explained
        fractions, and scores on a well-separated spectrum."""
        X = rng.random((40, 24))
        # plant separated structure so the top-3 spectrum is decisive
        X[:20] += np.outer(np.ones(20), rng.random(24)) * 2.0
        X[20:30] -= np.outer(np.ones(10), rng.random(24)) * 1.5
        rep = nk.normalize(rng.random(40) + 0.1)
        l_ref, s_ref, e_ref = jk.weighted_prin_comps(jnp.asarray(X),
                                                     jnp.asarray(rep), 3,
                                                     method="eigh-gram")
        l_pw, s_pw, e_pw = jk.weighted_prin_comps(jnp.asarray(X),
                                                  jnp.asarray(rep), 3,
                                                  method="power")
        np.testing.assert_allclose(np.asarray(e_pw), np.asarray(e_ref),
                                   atol=1e-6)
        for c in range(3):
            np.testing.assert_allclose(
                _align_sign(np.asarray(l_pw)[:, c], np.asarray(l_ref)[:, c]),
                np.asarray(l_ref)[:, c], atol=1e-5)
            np.testing.assert_allclose(
                _align_sign(np.asarray(s_pw)[:, c], np.asarray(s_ref)[:, c]),
                np.asarray(s_ref)[:, c], atol=1e-5)

    def test_orth_iter_storage_matches_inmemory(self, rng):
        """The storage-kernel orthogonal iteration (round 4: NaN-threaded
        sentinel storage swept by storage_matmat/storage_rows_matmat) must
        reproduce the in-memory orth-iter path on the equivalent filled
        matrix — identical convergence rules, so f64 storage in interpret
        mode agrees tightly."""
        from pyconsensus_tpu.models.pipeline import _fill_stats

        X = rng.random((40, 24))
        X[:20] += np.outer(np.ones(20), rng.random(24)) * 2.0
        X[20:30] -= np.outer(np.ones(10), rng.random(24)) * 1.5
        X[rng.random((40, 24)) < 0.15] = np.nan
        rep = jnp.asarray(nk.normalize(rng.random(40) + 0.1))
        x, fill, _, _ = _fill_stats(jnp.asarray(X), rep, 0.1, "", None)
        filled = jnp.where(jnp.isnan(x), fill[None, :], x)
        mu = rep @ filled
        l_ref, s_ref, e_ref = jk.weighted_prin_comps(filled, rep, 3,
                                                     method="power")
        l_st, s_st, e_st = jk.weighted_prin_comps_storage(
            x, fill, mu, rep, 3, interpret=True)
        np.testing.assert_allclose(np.asarray(e_st), np.asarray(e_ref),
                                   atol=1e-6)
        for c in range(3):
            np.testing.assert_allclose(
                _align_sign(np.asarray(l_st)[:, c], np.asarray(l_ref)[:, c]),
                np.asarray(l_ref)[:, c], atol=1e-5)
            np.testing.assert_allclose(
                _align_sign(np.asarray(s_st)[:, c], np.asarray(s_ref)[:, c]),
                np.asarray(s_ref)[:, c], atol=1e-5)

    def test_multi_dirfix_storage_matches_per_component(self, rng):
        """The batched one-sweep direction fix must reproduce
        direction_fixed_scores applied per component on the filled
        matrix (same collapsed algebra as the sztorc fused pass, same
        tie-break)."""
        from pyconsensus_tpu.models.pipeline import _fill_stats

        X = rng.random((24, 16))
        X[rng.random((24, 16)) < 0.1] = np.nan
        rep = jnp.asarray(nk.normalize(rng.random(24) + 0.1))
        x, fill, _, _ = _fill_stats(jnp.asarray(X), rep, 0.1, "", None)
        filled = jnp.where(jnp.isnan(x), fill[None, :], x)
        mu = rep @ filled
        _, scores, _ = jk.weighted_prin_comps(filled, rep, 3,
                                              method="eigh-gram")
        batched = jk.multi_dirfix_storage(scores, x, fill, mu, rep,
                                          interpret=True)
        for c in range(3):
            ref = jk.direction_fixed_scores(scores[:, c], filled, rep)
            np.testing.assert_allclose(np.asarray(batched)[:, c],
                                       np.asarray(ref), atol=1e-9,
                                       err_msg=f"component {c}")

    def test_orth_iter_degenerate_zero_cov(self, rng):
        """Identical rows (zero covariance): finite outputs, zero
        explained fractions — the qr-of-zeros guard."""
        X = np.tile(rng.random(12), (16, 1))
        rep = np.full(16, 1 / 16)
        l_pw, s_pw, e_pw = jk.weighted_prin_comps(jnp.asarray(X),
                                                  jnp.asarray(rep), 2,
                                                  method="power")
        assert np.isfinite(np.asarray(l_pw)).all()
        np.testing.assert_allclose(np.asarray(e_pw), 0.0, atol=1e-12)

    def test_power_warm_start(self, rng):
        """Warm-starting the power loop near the dominant eigenvector must
        (a) converge to the same loading and (b) use far fewer sweeps than
        the cold start — the HBM savings the iterative Sztorc loop banks
        by passing each iteration the previous loading. A zero v_init must
        be bitwise identical to the cold start (the scan-carry-init
        contract)."""
        X = rng.random((12, 40))
        # planted rank-1 structure -> decisive eigengap, like collusion
        X[:, :20] += np.outer(rng.random(12) * 2.0, np.ones(20))
        rep = jnp.asarray(nk.normalize(rng.random(12) + 0.1))
        Xj = jnp.asarray(X)
        mu, denom = jk._mu_denom(Xj, rep)

        def apply_cov(v):
            t = rep * (Xj @ v - mu @ v)
            return (Xj.T @ t - mu * jnp.sum(t)) / denom

        cold, cold_iters = jk._power_loop(apply_cov, 40, rep.dtype, 128,
                                          1e-6)
        warm, warm_iters = jk._power_loop(apply_cov, 40, rep.dtype, 128,
                                          1e-6, v_init=cold)
        # both sit within the early-exit band of the true eigenvector
        # (alignment tol 1e-6 ~ loading error O(1e-4) at this eigengap;
        # the warm restart only ever tightens it)
        cov, _ = nk.weighted_cov(X, np.asarray(rep))
        top = np.linalg.eigh(cov)[1][:, -1]
        np.testing.assert_allclose(_align_sign(np.asarray(cold), top), top,
                                   atol=1e-3)
        np.testing.assert_allclose(_align_sign(np.asarray(warm), top), top,
                                   atol=1e-3)
        # the blended seed costs ~1 sweep over a pure warm start (the
        # crossing-hazard insurance) but must still beat the cold start
        assert int(warm_iters) <= 3
        assert int(cold_iters) > int(warm_iters)
        zero, zero_iters = jk._power_loop(apply_cov, 40, rep.dtype, 128,
                                          1e-6, v_init=jnp.zeros((40,)))
        np.testing.assert_array_equal(np.asarray(zero), np.asarray(cold))
        assert int(zero_iters) == int(cold_iters)

    def test_warm_start_escapes_stale_eigenvector(self):
        """The eigenvalue-crossing hazard: a PURE warm start from the
        previous dominant direction is an exact fixed point of the power
        map, so the self-consistency exit would accept it even after the
        spectrum crossed and it became the SECOND eigenvector. The blended
        seed (_power_loop mixes in the ones direction) must escape to the
        new dominant eigenvector instead."""
        E = 16
        # diagonal covariance: dominant axis 0, runner-up axis 1 with a
        # decisive gap; "stale loading" = exact second eigenvector e1
        lam = jnp.asarray([4.0, 2.0] + [0.1] * (E - 2))

        def apply_cov(v):
            return lam * v

        stale = jnp.zeros((E,)).at[1].set(1.0)       # exact fixed point
        loading, iters = jk._power_loop(apply_cov, E, lam.dtype, 256,
                                        1e-9, v_init=stale)
        loading = np.asarray(loading)
        assert abs(loading[0]) > 0.99, (
            f"locked onto stale eigenvector: {loading[:3]}, {int(iters)} "
            f"iters")

    def test_gram_matches_cov_method(self, rng):
        X = rng.random((7, 20))
        rep = nk.normalize(rng.random(7) + 0.1)
        l_cov, s_cov = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                             method="eigh-cov")
        l_gram, s_gram = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                               method="eigh-gram")
        l_cov, l_gram = np.asarray(l_cov), np.asarray(l_gram)
        np.testing.assert_allclose(_align_sign(l_gram, l_cov), l_cov, atol=1e-8)


class TestWeightedMedian:
    def test_simple(self):
        assert nk.weighted_median(np.array([1.0, 2.0, 3.0]),
                                  np.array([1.0, 1.0, 1.0])) == 2.0

    def test_weight_dominant(self):
        assert nk.weighted_median(np.array([1.0, 2.0, 3.0]),
                                  np.array([10.0, 1.0, 1.0])) == 1.0

    def test_exact_half_midpoint(self):
        # cumulative weight hits exactly 0.5 at value 1 -> midpoint with 2
        assert nk.weighted_median(np.array([1.0, 2.0]),
                                  np.array([0.5, 0.5])) == 1.5

    def test_jax_columns_match(self, rng):
        R, E = 11, 6
        values = rng.random((R, E))
        weights = rng.random((R, E))
        present = rng.random((R, E)) < 0.8
        present[0, :] = True
        expected = np.array([
            nk.weighted_median(values[present[:, j], j],
                               weights[present[:, j], j])
            for j in range(E)
        ])
        got = np.asarray(jk.weighted_median_cols(jnp.asarray(values),
                                                 jnp.asarray(weights),
                                                 jnp.asarray(present)))
        np.testing.assert_allclose(got, expected, rtol=1e-12)

    def test_blocked_matches_full_width(self, rng, monkeypatch):
        """Above _MEDIAN_BLOCK columns the median runs as a lax.map over
        column blocks (bounded sort temporaries); results must be bitwise
        identical to the full-width form, including a ragged last block
        and all-absent columns."""
        monkeypatch.setattr(jk, "_MEDIAN_BLOCK", 5)
        R, E = 9, 13                      # 2 full blocks + ragged 3
        values = rng.random((R, E))
        weights = rng.random((R, E))
        present = rng.random((R, E)) < 0.7
        present[:, 4] = False             # all-absent column -> 0.5
        full = np.asarray(jk._weighted_median_cols_block(
            jnp.asarray(values), jnp.asarray(weights), jnp.asarray(present)))
        blocked = np.asarray(jk.weighted_median_cols(
            jnp.asarray(values), jnp.asarray(weights), jnp.asarray(present)))
        np.testing.assert_array_equal(blocked, full)
        assert blocked[4] == 0.5
        # (R,) per-reporter weights (the at-scale form: a broadcast (R, E)
        # operand would be materialized across the block loop) must match
        # the explicit broadcast
        rep = rng.random(R)
        wide = np.asarray(jk.weighted_median_cols(
            jnp.asarray(values),
            jnp.asarray(np.broadcast_to(rep[:, None], (R, E)).copy()),
            jnp.asarray(present)))
        narrow = np.asarray(jk.weighted_median_cols(
            jnp.asarray(values), jnp.asarray(rep), jnp.asarray(present)))
        np.testing.assert_array_equal(narrow, wide)

    def test_exact_half_midpoint_jax(self):
        values = jnp.array([[1.0], [2.0]])
        weights = jnp.array([[0.5], [0.5]])
        present = jnp.ones((2, 1), dtype=bool)
        got = np.asarray(jk.weighted_median_cols(values, weights, present))
        assert got[0] == 1.5


class TestDirectionFix:
    def test_majority_orientation(self):
        # 4 honest (agree), 2 liars: direction fix must give honest reporters
        # the higher adjusted scores once reweighted
        X = np.array([[1.0, 1, 0, 0]] * 4 + [[0.0, 0, 1, 1]] * 2)
        rep = np.full(6, 1 / 6)
        adj = nk.direction_fixed_scores(
            nk.weighted_prin_comp(X, rep)[1], X, rep)
        this_rep = nk.row_reward_weighted(adj, rep)
        assert this_rep[:4].sum() > this_rep[4:].sum()

    def test_jax_match(self, rng):
        X = rng.choice([0.0, 0.5, 1.0], size=(8, 5))
        rep = nk.normalize(rng.random(8) + 0.1)
        _, scores = nk.weighted_prin_comp(X, rep)
        adj_np = nk.direction_fixed_scores(scores, X, rep)
        adj_j = np.asarray(jk.direction_fixed_scores(
            jnp.asarray(scores), jnp.asarray(X), jnp.asarray(rep)))
        np.testing.assert_allclose(adj_j, adj_np, rtol=0, atol=1e-10)


class TestRowRewardSmooth:
    def test_degenerate_unanimous(self):
        rep = np.array([0.25, 0.25, 0.5])
        out = nk.row_reward_weighted(np.zeros(3), rep)
        np.testing.assert_array_equal(out, rep)
        out_j = np.asarray(jk.row_reward_weighted(jnp.zeros(3),
                                                  jnp.asarray(rep)))
        np.testing.assert_array_equal(out_j, rep)

    def test_smooth_blend(self):
        a = np.array([1.0, 0.0])
        b = np.array([0.0, 1.0])
        np.testing.assert_allclose(nk.smooth(a, b, 0.1), [0.1, 0.9])
        np.testing.assert_allclose(np.asarray(jk.smooth(jnp.asarray(a),
                                                        jnp.asarray(b), 0.1)),
                                   [0.1, 0.9])


class TestResolveOutcomes:
    def test_parity_random(self, rng):
        for _ in range(5):
            reports, rep, scaled, mins, maxs = random_reports(rng)
            rescaled = nk.rescale(reports, scaled, mins, maxs)
            filled = nk.interpolate(rescaled, rep, scaled, 0.1)
            raw_np, adj_np = nk.resolve_outcomes(rescaled, filled, rep,
                                                 scaled, 0.1)
            raw_j, adj_j = jk.resolve_outcomes(
                jnp.asarray(~np.isnan(rescaled)), jnp.asarray(filled),
                jnp.asarray(rep), jnp.asarray(scaled), 0.1)
            np.testing.assert_allclose(np.asarray(raw_j), raw_np, rtol=1e-12)
            # binary outcomes catch-snapped -> exact equality
            np.testing.assert_array_equal(np.asarray(adj_j)[~scaled],
                                          adj_np[~scaled])

    def test_static_scaled_gather_bitwise(self, rng):
        """The n_scaled static-gather fast path (median on just the scaled
        columns) must be bitwise identical to the full-width median +
        select — each column's math is self-contained, so gathering can't
        change it. Covers NaN columns, blocked and unblocked widths,
        scaled MAJORITIES (round 4 opened the gate to any n_scaled < E),
        and the guard cases (n_scaled=0, all-scaled, median_block=0)
        falling back to the full path."""
        for trial in range(4):
            reports, rep, scaled, mins, maxs = random_reports(rng)
            if trial == 3:
                # force a scaled MAJORITY with one binary holdout: the
                # widest gather the gate now admits
                scaled = np.ones_like(scaled)
                scaled[0] = False   # binary bounds are [0,1] -> identity rescale
            rescaled = nk.rescale(reports, scaled, mins, maxs)
            filled = nk.interpolate(rescaled, rep, scaled, 0.1)
            present = jnp.asarray(~np.isnan(rescaled))
            n_sc = int(scaled.sum())
            if n_sc == 0 or n_sc == scaled.size:
                continue
            args = (present, jnp.asarray(filled), jnp.asarray(rep),
                    jnp.asarray(scaled), 0.1)
            for block in (1024, 2):
                full = jk.resolve_outcomes(*args, median_block=block)
                fast = jk.resolve_outcomes(*args, median_block=block,
                                           n_scaled=n_sc)
                np.testing.assert_array_equal(np.asarray(fast[0]),
                                              np.asarray(full[0]))
                np.testing.assert_array_equal(np.asarray(fast[1]),
                                              np.asarray(full[1]))
            # guards: unblocked (sharded) mode must ignore n_scaled
            a0 = jk.resolve_outcomes(*args, median_block=0)
            a1 = jk.resolve_outcomes(*args, median_block=0, n_scaled=n_sc)
            np.testing.assert_array_equal(np.asarray(a0[1]),
                                          np.asarray(a1[1]))

    def test_bonuses_parity(self, rng):
        reports, rep, scaled, mins, maxs = random_reports(rng)
        rescaled = nk.rescale(reports, scaled, mins, maxs)
        filled = nk.interpolate(rescaled, rep, scaled, 0.1)
        raw_np, adj_np = nk.resolve_outcomes(rescaled, filled, rep, scaled, 0.1)
        e_np = nk.certainty_and_bonuses(rescaled, filled, rep, adj_np,
                                        scaled, 0.1)
        e_j = jk.certainty_and_bonuses(jnp.asarray(~np.isnan(rescaled)),
                                       jnp.asarray(filled), jnp.asarray(rep),
                                       jnp.asarray(adj_np),
                                       jnp.asarray(scaled), 0.1)
        for key, val in e_np.items():
            np.testing.assert_allclose(np.asarray(e_j[key]), val, rtol=0,
                                       atol=1e-10, err_msg=key)


class TestPallasFused:
    """The Pallas row-panel kernel (ops.pallas_kernels) — interpreter mode on
    the CPU test platform; the compiled path is exercised on real TPU by the
    benchmark and verified there against the XLA matvec path."""

    def test_apply_weighted_cov_matches_reference(self, rng):
        from pyconsensus_tpu.ops.pallas_kernels import apply_weighted_cov
        R, E = 13, 9            # deliberately not multiples of the panel size
        X = jnp.asarray(rng.random((R, E)), jnp.float32)
        rep = jnp.asarray(nk.normalize(rng.random(R) + 0.1), jnp.float32)
        v = jnp.asarray(rng.random(E), jnp.float32)
        mu = rep @ X
        dev = X - mu[None, :]
        ref = np.asarray(dev.T @ (rep * (dev @ v)), np.float64)
        out = np.asarray(apply_weighted_cov(X, mu, rep, v, interpret=True))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_apply_weighted_cov_block_matches_reference(self, rng):
        """The one-pass BLOCK covariance kernel equals the dense centered
        application across all three storage encodings, including the
        NaN/sentinel-threaded forms — the k-column sibling of the test
        above (same algebra as the separable storage_matmat +
        storage_rows_matmat pair it replaces on the single-device
        orth-iter path)."""
        from pyconsensus_tpu.ops.pallas_kernels import (
            apply_weighted_cov_block, cov_block_kernel_fits)
        R, E, k = 13, 9, 3      # deliberately not panel multiples
        assert cov_block_kernel_fits(E, k, 1)
        reports = rng.choice([0.0, 0.5, 1.0], size=(R, E))
        na = rng.random((R, E)) < 0.15
        rep = nk.normalize(rng.random(R) + 0.1)
        fill_np = rng.random(E)
        filled = np.where(na, fill_np[None, :], reports)
        mu = filled.T @ rep
        V = rng.standard_normal((E, k))
        dev = filled - mu[None, :]
        ref = dev.T @ (rep[:, None] * (dev @ V))
        t_ref = dev @ V
        for enc, x in (
                ("int8", jnp.asarray(np.where(na, -1, np.round(reports * 2)),
                                     jnp.int8)),
                ("bf16", jnp.asarray(np.where(na, np.nan, reports),
                                     jnp.bfloat16)),
                ("f32", jnp.asarray(np.where(na, np.nan, reports),
                                    jnp.float32))):
            out, none_t = apply_weighted_cov_block(
                x, jnp.asarray(mu), jnp.asarray(rep), jnp.asarray(V),
                fill=jnp.asarray(fill_np), interpret=True)
            assert none_t is None          # emit_t off: no t output paid
            out, t = apply_weighted_cov_block(
                x, jnp.asarray(mu), jnp.asarray(rep), jnp.asarray(V),
                fill=jnp.asarray(fill_np), interpret=True, emit_t=True)
            tol = 1e-5 if enc == "f32" else 5e-3
            np.testing.assert_allclose(np.asarray(out), ref, rtol=0,
                                       atol=tol * np.max(np.abs(ref)),
                                       err_msg=enc)
            # the folded per-row projections equal (X - 1 mu^T) V
            np.testing.assert_allclose(np.asarray(t), t_ref, rtol=0,
                                       atol=tol * np.max(np.abs(t_ref)),
                                       err_msg=enc + " t")

    def test_fill_stats_pass_matches_xla(self, rng):
        """The round-5 fill-stats kernel (opt-in via
        PYCONSENSUS_FILL_STATS_KERNEL=1 after losing its on-chip A/Bs —
        docs/PERFORMANCE.md r5) must agree with the production XLA
        reduction so it stays re-testable on future hardware."""
        from pyconsensus_tpu.ops.pallas_kernels import (
            fill_stats_kernel_fits, fill_stats_pass)
        R, E = 13, 9            # deliberately not panel multiples
        assert fill_stats_kernel_fits(E, 1)
        reports = rng.choice([0.0, 0.5, 1.0], size=(R, E))
        na = rng.random((R, E)) < 0.2
        rep = nk.normalize(rng.random(R) + 0.1)
        x = jnp.asarray(np.where(na, -1, np.round(reports * 2)), jnp.int8)
        tw, numer = fill_stats_pass(x, jnp.asarray(rep, jnp.float32),
                                    interpret=True)
        w = np.where(na, 0.0, rep[:, None])
        np.testing.assert_allclose(np.asarray(tw), w.sum(axis=0),
                                   rtol=0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(numer),
                                   (np.where(na, 0.0, reports) * w
                                    ).sum(axis=0), rtol=0, atol=1e-6)

    def test_power_fused_loading_matches_eigh(self, rng):
        X = rng.random((12, 8))
        rep = nk.normalize(rng.random(12) + 0.1)
        load_np, scores_np = nk.weighted_prin_comp(X, rep)
        load_j, scores_j = jk.weighted_prin_comp(
            jnp.asarray(X), jnp.asarray(rep), method="power-fused")
        load_j = np.asarray(load_j)
        # f32 kernel arithmetic + machine-eps early exit on a small random
        # matrix (weak eigengap): modest tolerance
        np.testing.assert_allclose(_align_sign(load_j, load_np), load_np,
                                   atol=3e-3)
        s = np.asarray(scores_j)
        np.testing.assert_allclose(_align_sign(s, scores_np), scores_np,
                                   atol=3e-3)

    def test_scores_dirfix_pass_contractions(self, rng):
        """The one-sweep contraction outputs equal their two-pass XLA
        definitions: t = X@loading, q = t^T X, c = colsums, o = rep^T X."""
        from pyconsensus_tpu.ops.pallas_kernels import scores_dirfix_pass
        R, E = 13, 9            # deliberately not panel multiples
        X = rng.random((R, E))
        rep = nk.normalize(rng.random(R) + 0.1)
        loading = rng.random(E)
        t, q, c, o = scores_dirfix_pass(jnp.asarray(X, jnp.float32),
                                        jnp.asarray(rep, jnp.float32),
                                        jnp.asarray(loading, jnp.float32),
                                        interpret=True)
        t_ref = X @ loading
        np.testing.assert_allclose(np.asarray(t), t_ref, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(q), t_ref @ X, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(c), X.sum(axis=0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(o), rep @ X, rtol=1e-5)

    def test_sztorc_fused_matches_two_pass(self, rng):
        """The fused sztorc scoring step (power PCA + one-sweep direction
        fix) agrees with the numpy composition on matrices with a decisive
        collusion direction, and picks the same orientation."""
        honest = np.tile(rng.choice([0.0, 1.0], size=(1, 12)), (9, 1))
        liars = 1.0 - honest[:3]
        X = np.concatenate([honest, liars])          # 12 reporters
        noise = rng.choice([0.0, 0.5], size=X.shape, p=[0.9, 0.1])
        X = np.abs(X - noise)
        rep = nk.normalize(rng.random(12) + 0.5)
        adj_np = nk.direction_fixed_scores(
            nk.weighted_prin_comp(X, rep)[1], X, rep)
        adj_f, loading = jk.sztorc_scores_power_fused(
            jnp.asarray(X), jnp.asarray(rep), power_iters=256,
            power_tol=-1.0, interpret=True)
        # the PCA eigensign is arbitrary, and the direction fix compensates
        # (it returns the winning orientation in non-negative form either
        # way); the REPUTATION after row_reward_weighted is the clean
        # invariant to compare, independent of which eigensign each
        # backend's solver happened to pick
        rep_np = nk.row_reward_weighted(adj_np, rep)
        rep_f = np.asarray(jk.row_reward_weighted(adj_f, jnp.asarray(rep)))
        np.testing.assert_allclose(rep_f, rep_np, atol=2e-4)
        # honest majority rewarded
        assert rep_f[:9].sum() > rep_f[9:].sum()

    def test_resolve_certainty_fused_parity(self, rng):
        """The one-sweep resolution kernel reproduces resolve_outcomes +
        certainty_and_bonuses on NaN-threaded binary reports, including the
        ragged last column block (E not a multiple of the block width)."""
        from pyconsensus_tpu.ops.pallas_kernels import resolve_certainty_fused
        R, E = 24, 7
        X = rng.choice([0.0, 0.5, 1.0], size=(R, E))
        X[rng.random((R, E)) < 0.2] = np.nan
        rep = nk.normalize(rng.random(R) + 0.1)
        scaled = np.zeros(E, dtype=bool)
        filled = nk.interpolate(X, rep, scaled, 0.1)
        present = ~np.isnan(X)
        raw_np, adj_np = nk.resolve_outcomes(X, filled, rep, scaled, 0.1)
        extras = nk.certainty_and_bonuses(X, filled, rep, adj_np, scaled, 0.1)
        # fill vector: interpolate's rule (rep-weighted present mean,
        # catch-snapped for binary events)
        w = np.where(present, rep[:, None], 0.0)
        tw = w.sum(axis=0)
        numer = (w * np.where(present, X, 0.0)).sum(axis=0)
        fill = nk.catch(np.where(tw > 0, numer / np.maximum(tw, 1e-30), 0.5),
                        0.1)
        raw, adj, cert, pcol, prow, narow = resolve_certainty_fused(
            jnp.asarray(X, jnp.float32), jnp.asarray(rep, jnp.float32),
            jnp.asarray(fill, jnp.float32), jnp.asarray(rep.sum()), 0.1,
            block_cols=4, interpret=True)   # block_cols=4 -> ragged E=7
        np.testing.assert_allclose(np.asarray(raw), raw_np, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(adj), adj_np)
        np.testing.assert_allclose(np.asarray(cert), extras["certainty"],
                                   atol=1e-5)
        np.testing.assert_allclose(1.0 - np.asarray(pcol),
                                   extras["participation_columns"], atol=1e-5)
        total_cert = extras["certainty"].sum()
        np.testing.assert_allclose(
            1.0 - np.asarray(prow) / total_cert,
            extras["participation_rows"], atol=1e-5)
        np.testing.assert_array_equal(np.asarray(narow) > 0,
                                      np.isnan(X).any(axis=1))

    def test_power_early_exit_matches_full_run(self, rng):
        """tol=0 (machine-precision floor) must give the same loading as a
        full fixed-trip run (power_tol=-1 disables the early exit) — the
        exit may only skip sweeps whose per-step improvement is below the
        machine-epsilon floor (residual error O(eps / eigengap))."""
        X = rng.random((10, 6))
        rep = nk.normalize(rng.random(10) + 0.1)
        l_full, _ = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                          method="power", power_iters=500,
                                          power_tol=-1.0)
        l_tol, _ = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                         method="power", power_iters=500,
                                         power_tol=0.0)
        np.testing.assert_allclose(np.asarray(l_tol), np.asarray(l_full),
                                   atol=1e-5)

    def test_power_bf16_matvec_close(self, rng):
        X = rng.random((10, 6))
        rep = nk.normalize(rng.random(10) + 0.1)
        l_f, _ = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                       method="power")
        l_b, _ = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                       method="power",
                                       matvec_dtype="bfloat16")
        l_f, l_b = np.asarray(l_f), np.asarray(l_b)
        np.testing.assert_allclose(_align_sign(l_b, l_f), l_f, atol=2e-2)
