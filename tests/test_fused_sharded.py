"""Parity tests for the shard_map fused path (parallel.fused_sharded):
the multi-device kernel path must agree with the single-device fused
path (the headline pipeline) — outcomes bit-identically (catch-snapped),
reputations to f32-kernel tolerance — across storage dtypes, NA
patterns, iteration counts, and mesh widths, on the 8-virtual-device CPU
mesh with the Pallas kernels in interpret mode.

Parity-ledger #1-7 closure (docs/ROBUSTNESS.md): the 7 long-failing
cases in this file were NOT power-loop reduction noise — a column whose
present-weighted mean sits EXACTLY on the catch boundary (0.6 with the
default 0.1 tolerance under uniform reputation) snapped its FILL
differently per path because XLA's column reductions at different
shapes land one ulp apart. Fixed by the ``CATCH_TIE_ATOL`` boundary
band (numpy/jax/Pallas `catch` kernels — the MEDIAN/DIRFIX tie-band
pattern): knife-edge fills now resolve to the ambiguous 0.5 on every
path, and the original 5e-6 tolerances hold."""

import numpy as np
import jax.numpy as jnp
import pytest

from conftest import collusion_reports
from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                             _consensus_core_fused)
from pyconsensus_tpu.parallel import make_mesh
from pyconsensus_tpu.parallel.fused_sharded import fused_sharded_consensus
from pyconsensus_tpu.parallel.sharded import (_place_inputs,
                                              _resolve_sharded_params)

R, E = 24, 64


def base_params(**kw):
    kw.setdefault("algorithm", "sztorc")
    kw.setdefault("pca_method", "power")
    kw.setdefault("power_iters", 128)
    kw.setdefault("power_tol", 0.0)
    kw.setdefault("any_scaled", False)
    kw.setdefault("has_na", True)
    kw.setdefault("fused_resolution", True)
    return ConsensusParams(**kw)


def run_both(reports, rep, p, n_event=8):
    mesh = make_mesh(batch=1, event=n_event)
    Ecols = reports.shape[1]
    placed = _place_inputs(mesh, reports, rep, np.zeros(Ecols, bool),
                           np.zeros(Ecols), np.ones(Ecols))
    sharded = fused_sharded_consensus(placed[0], placed[1], mesh, p)
    single = _consensus_core_fused(
        jnp.asarray(reports), jnp.asarray(rep), jnp.zeros(Ecols, bool),
        jnp.zeros(Ecols), jnp.ones(Ecols), p)
    return ({k: np.asarray(v) for k, v in sharded.items()},
            {k: np.asarray(v) for k, v in single.items()})


class TestShardFusedParity:
    @pytest.mark.parametrize("storage", ["int8", "bfloat16", ""])
    def test_matches_single_device_fused(self, rng, storage):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.15)
        rep = np.full(R, 1.0 / R)
        sharded, single = run_both(reports, rep,
                                   base_params(storage_dtype=storage))
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        np.testing.assert_array_equal(sharded["na_row"], single["na_row"])
        for key in ("this_rep", "smooth_rep", "certainty",
                    "participation_rows", "participation_columns",
                    "reporter_bonus", "author_bonus", "consensus_reward"):
            np.testing.assert_allclose(sharded[key], single[key],
                                       atol=5e-6, err_msg=key)
        # the loading converges through different reduction orders (and
        # near-tied |max| entries can flip the canonical sign): align by
        # dot-product sign and allow f32-kernel noise
        a, b = sharded["first_loading"], single["first_loading"]
        a = a * np.sign(np.dot(a, b)) if np.dot(a, b) != 0 else a
        np.testing.assert_allclose(a, b, atol=1e-3)

    def test_iterative_loop(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.1)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="int8", max_iterations=5)
        sharded, single = run_both(reports, rep, p)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        assert sharded["iterations"] == single["iterations"]
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-6)

    def test_dense_no_na(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.0)
        rep = np.full(R, 1.0 / R)
        sharded, single = run_both(reports, rep,
                                   base_params(storage_dtype="int8"))
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        assert sharded["percent_na"] == pytest.approx(0.0, abs=1e-12)
        assert not sharded["na_row"].any()

    def test_matvec_dtype_honored(self, rng):
        """ADVICE r3: the mesh path must apply ConsensusParams.matvec_dtype
        like the single-device fused path (narrowed power/scores passes),
        not silently run full-width."""
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.1)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="", matvec_dtype="bfloat16")
        sharded, single = run_both(reports, rep, p)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        # bf16 matvecs: looser than the f32/f64 parity elsewhere
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-3)

    def test_nonuniform_reputation(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.1)
        rep = rng.random(R) + 0.05
        rep = rep / rep.sum()
        sharded, single = run_both(reports, rep,
                                   base_params(storage_dtype="int8"))
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-6)

    @pytest.mark.parametrize("n_event", [2, 4])
    def test_mesh_width_invariance(self, rng, n_event):
        """Same inputs across mesh widths: catch-snapped outcomes must be
        identical (cross-sharding determinism, the race-detection
        analogue)."""
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.15)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="int8")
        sharded, single = run_both(reports, rep, p, n_event=n_event)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])

    def test_batch_event_mesh_composition(self, rng):
        """The dp x sp composition: a batch x event mesh replicates the
        resolution over 'batch' while the kernels shard over 'event' —
        outcomes must stay bit-identical to the single-device path."""
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.15)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="int8")
        mesh = make_mesh(batch=2, event=4)
        placed = _place_inputs(mesh, reports, rep, np.zeros(E, bool),
                               np.zeros(E), np.ones(E))
        sharded = fused_sharded_consensus(placed[0], placed[1], mesh, p)
        single = _consensus_core_fused(
            jnp.asarray(reports), jnp.asarray(rep), jnp.zeros(E, bool),
            jnp.zeros(E), jnp.ones(E), p)
        np.testing.assert_array_equal(
            np.asarray(sharded["outcomes_adjusted"]),
            np.asarray(single["outcomes_adjusted"]))


def scaled_fixture(rng, n_events, scaled_cols, na_frac=0.1):
    """Mixed binary + scaled reports with bounds vectors: the named
    columns carry continuous values in [-5, 15]."""
    reports, _ = collusion_reports(rng, R, n_events, liars=5,
                                   na_frac=na_frac)
    scaled = np.zeros(n_events, dtype=bool)
    scaled[scaled_cols] = True
    mins = np.where(scaled, -5.0, 0.0)
    maxs = np.where(scaled, 15.0, 1.0)
    with np.errstate(invalid="ignore"):
        reports[:, scaled] = reports[:, scaled] * 20.0 - 5.0
    return reports, scaled, mins, maxs


def run_both_scaled(reports, rep, p, scaled, mins, maxs, n_event=8):
    mesh = make_mesh(batch=1, event=n_event)
    placed = _place_inputs(mesh, reports, rep, scaled, mins, maxs)
    sharded = fused_sharded_consensus(placed[0], placed[1], mesh, p,
                                      *placed[2:])
    single = _consensus_core_fused(
        jnp.asarray(reports), jnp.asarray(rep), jnp.asarray(scaled),
        jnp.asarray(mins), jnp.asarray(maxs), p)
    return ({k: np.asarray(v) for k, v in sharded.items()},
            {k: np.asarray(v) for k, v in single.items()})


def assert_scaled_parity(sharded, single, scaled, atol=5e-6):
    binary = ~scaled
    # binary outcomes are catch-snapped -> exact; outcomes_raw (pre-snap
    # weighted means) and scaled medians carry reduction-order float noise
    for key in ("outcomes_adjusted", "outcomes_final"):
        np.testing.assert_array_equal(sharded[key][binary],
                                      single[key][binary], err_msg=key)
    for key in ("outcomes_raw", "outcomes_adjusted", "outcomes_final"):
        np.testing.assert_allclose(sharded[key][scaled],
                                   single[key][scaled], atol=atol,
                                   err_msg=key)
    np.testing.assert_allclose(sharded["outcomes_raw"], single["outcomes_raw"],
                               atol=atol)
    np.testing.assert_array_equal(sharded["na_row"], single["na_row"])
    for key in ("this_rep", "smooth_rep", "certainty",
                "participation_rows", "participation_columns",
                "reporter_bonus", "author_bonus", "consensus_reward",
                "percent_na", "avg_certainty"):
        np.testing.assert_allclose(sharded[key], single[key], atol=atol,
                                   err_msg=key)


class TestShardFusedScaled:
    """Round-4 gate opening (VERDICT r3 item 1): scaled columns on the
    mesh fused path, re-resolved shard-locally — parity against the
    single-device fused path's gather-median."""

    @pytest.mark.parametrize("storage", ["bfloat16", ""])
    def test_scaled_spread_across_shards(self, rng, storage):
        cols = [5, 20, 37, 50, 63]          # one per several shards
        reports, scaled, mins, maxs = scaled_fixture(rng, E, cols)
        rep = np.full(R, 1.0 / R)
        p = base_params(any_scaled=True, n_scaled=len(cols),
                        storage_dtype=storage)
        sharded, single = run_both_scaled(reports, rep, p, scaled, mins,
                                          maxs)
        assert_scaled_parity(sharded, single, scaled)

    def test_scaled_clustered_on_one_shard(self, rng):
        """All scaled columns on shard 0: the other shards' static gather
        capacity exceeds their (zero) scaled count — garbage slots must
        contribute nothing anywhere."""
        cols = [0, 1, 2, 3]
        reports, scaled, mins, maxs = scaled_fixture(rng, E, cols)
        rep = np.full(R, 1.0 / R)
        p = base_params(any_scaled=True, n_scaled=len(cols),
                        storage_dtype="bfloat16")
        sharded, single = run_both_scaled(reports, rep, p, scaled, mins,
                                          maxs)
        assert_scaled_parity(sharded, single, scaled)

    def test_scaled_iterative(self, rng):
        cols = [7, 33, 59]
        reports, scaled, mins, maxs = scaled_fixture(rng, E, cols)
        rep = np.full(R, 1.0 / R)
        p = base_params(any_scaled=True, n_scaled=len(cols),
                        max_iterations=4, storage_dtype="")
        sharded, single = run_both_scaled(reports, rep, p, scaled, mins,
                                          maxs)
        assert sharded["iterations"] == single["iterations"]
        assert_scaled_parity(sharded, single, scaled)


class TestShardFusedPadding:
    """Round-4 gate opening: non-divisible event counts served by masked
    padding — parity against the (unpadded) single-device fused path."""

    @pytest.mark.parametrize("storage", ["int8", "bfloat16", ""])
    @pytest.mark.parametrize("n_events", [60, 41])
    def test_nondivisible_binary(self, rng, storage, n_events):
        # E=41 on the 8-way mesh leaves the last shard ENTIRELY padding
        reports, _ = collusion_reports(rng, R, n_events, liars=5,
                                       na_frac=0.15)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype=storage)
        sharded, single = run_both(reports, rep, p)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        np.testing.assert_array_equal(sharded["na_row"], single["na_row"])
        assert sharded["outcomes_final"].shape == (n_events,)
        assert sharded["certainty"].shape == (n_events,)
        for key in ("this_rep", "smooth_rep", "certainty",
                    "participation_rows", "participation_columns",
                    "reporter_bonus", "author_bonus", "consensus_reward",
                    "percent_na", "avg_certainty"):
            np.testing.assert_allclose(sharded[key], single[key],
                                       atol=5e-6, err_msg=key)

    def test_nondivisible_iterative(self, rng):
        reports, _ = collusion_reports(rng, R, 60, liars=5, na_frac=0.1)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="int8", max_iterations=5)
        sharded, single = run_both(reports, rep, p)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        assert sharded["iterations"] == single["iterations"]
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-6)

    def test_nondivisible_with_scaled(self, rng):
        """Both gates at once: E=61 (pad 3) with scaled columns,
        including one in the ragged tail region."""
        cols = [4, 31, 58]
        reports, scaled, mins, maxs = scaled_fixture(rng, 61, cols)
        rep = np.full(R, 1.0 / R)
        p = base_params(any_scaled=True, n_scaled=len(cols),
                        storage_dtype="bfloat16")
        sharded, single = run_both_scaled(reports, rep, p, scaled, mins,
                                          maxs)
        assert_scaled_parity(sharded, single, scaled)


class TestUnevenPlacement:
    def test_place_event_bounds_nondivisible(self):
        """place_event_bounds must survive event counts the mesh cannot
        divide (replicated fallback, like _place_inputs) — code-review r4
        found the raw P('event') placement crashing here."""
        from pyconsensus_tpu.parallel import (make_mesh,
                                              place_event_bounds,
                                              sharded_consensus)

        mesh = make_mesh(batch=1, event=8)
        bounds = [None] * 59 + [{"scaled": True, "min": 0.0, "max": 10.0}] * 2
        placed = place_event_bounds(bounds, 61, mesh)
        assert placed.n_scaled == 2 and placed.any_scaled
        rng = np.random.default_rng(3)
        reports = rng.choice([0.0, 1.0], size=(16, 61))
        reports[:, 59:] = rng.random((16, 2)) * 10.0
        out = sharded_consensus(reports, event_bounds=placed, mesh=mesh)
        assert np.asarray(out["outcomes_final"]).shape == (61,)


class TestShardFusedGates:
    def test_scaled_without_bounds_rejected(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5)
        mesh = make_mesh(batch=1, event=8)
        placed = _place_inputs(mesh, reports, np.full(R, 1.0 / R),
                               np.zeros(E, bool), np.zeros(E), np.ones(E))
        with pytest.raises(ValueError, match="event vectors"):
            fused_sharded_consensus(placed[0], placed[1], mesh,
                                    base_params(any_scaled=True, n_scaled=2))

    def test_wrong_algorithm_rejected(self, rng):
        """Direct callers passing non-sztorc params must fail loudly, not
        silently get sztorc results (ADVICE r3)."""
        reports, _ = collusion_reports(rng, R, E, liars=5)
        mesh = make_mesh(batch=1, event=8)
        placed = _place_inputs(mesh, reports, np.full(R, 1.0 / R),
                               np.zeros(E, bool), np.zeros(E), np.ones(E))
        with pytest.raises(ValueError, match="sztorc"):
            fused_sharded_consensus(placed[0], placed[1], mesh,
                                    base_params(algorithm="ica"))
        with pytest.raises(ValueError, match="power-family"):
            fused_sharded_consensus(placed[0], placed[1], mesh,
                                    base_params(pca_method="eigh-gram"))

    def test_int8_scaled_rejected(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5)
        mesh = make_mesh(batch=1, event=8)
        placed = _place_inputs(mesh, reports, np.full(R, 1.0 / R),
                               np.zeros(E, bool), np.zeros(E), np.ones(E))
        with pytest.raises(ValueError, match="int8"):
            fused_sharded_consensus(
                placed[0], placed[1], mesh,
                base_params(any_scaled=True, n_scaled=2,
                            storage_dtype="int8"))

    def test_resolver_closes_gate_off_tpu(self):
        """On the CPU test platform the fused gate stays closed (backend
        check), and a multi-device power-fused request must downgrade to
        the XLA matvecs rather than leak a black-box Pallas call into
        GSPMD."""
        mesh = make_mesh(batch=1, event=8)
        p = _resolve_sharded_params(
            base_params(pca_method="power-fused", fused_resolution=False),
            10_000, 4096, mesh)
        assert not p.fused_resolution
        assert p.pca_method == "power"

    def test_gate_conditions_for_mesh(self, monkeypatch):
        """With the backend forced to report 'tpu': the round-4 mesh gate
        serves non-divisible event counts (padding) and scaled minorities
        (shard-local gather), the auto-storage rule picks int8 on the
        mesh, and int8 + scaled still refuses loudly."""
        from pyconsensus_tpu.parallel import resolve_auto_storage, sharded

        monkeypatch.setattr(sharded.jax, "default_backend", lambda: "tpu")
        mesh = make_mesh(batch=1, event=8)
        # int8 storage: under the x64 test config the default itemsize is
        # 8, which legitimately fails resolve_kernel_fits at R=10k
        p = base_params(pca_method="power-fused", fused_resolution=False,
                        storage_dtype="int8")
        resolved = _resolve_sharded_params(p, 10_000, 4096, mesh)
        assert resolved.fused_resolution
        storage, why = resolve_auto_storage(
            ConsensusParams(algorithm="sztorc", any_scaled=False,
                            has_na=True), 10_000, 4096, mesh)
        assert storage == "int8", why
        # indivisible E no longer closes the mesh gate (padding) — int8
        # stays on the fused path
        assert _resolve_sharded_params(p, 10_000, 4097,
                                       mesh).fused_resolution
        # int8 + scaled is semantically impossible (continuous rescaled
        # values on a half-unit lattice) — loud refusal at resolve time
        with pytest.raises(ValueError, match="int8"):
            _resolve_sharded_params(
                p._replace(any_scaled=True, n_scaled=8), 10_000, 4096,
                mesh)
        # a scaled MINORITY now rides the fused mesh path (shard-local
        # gather-median); a scaled-heavy config still takes the XLA path.
        # bfloat16 storage: the x64 default itemsize (8) legitimately
        # fails the VMEM fit at R=10k, which would shadow the scaled rule
        clean = p._replace(storage_dtype="bfloat16")
        assert _resolve_sharded_params(
            clean._replace(any_scaled=True, n_scaled=8), 10_000, 4096,
            mesh).fused_resolution
        assert not _resolve_sharded_params(
            clean._replace(any_scaled=True, n_scaled=2048), 10_000, 4096,
            mesh).fused_resolution
        # ... and non-divisible E composes with the scaled minority
        assert _resolve_sharded_params(
            clean._replace(any_scaled=True, n_scaled=8), 10_000, 4097,
            mesh).fused_resolution


class TestBatchEventMeshGate:
    """The fused gate must size and trigger on the EVENT axis width, not
    the device count: a batch x event mesh shards columns only over
    'event', and a pure-batch mesh has no event sharding for the kernels
    to ride at all."""

    def test_batch_event_mesh_sizes_on_event_axis(self, monkeypatch):
        from pyconsensus_tpu.parallel import sharded

        monkeypatch.setattr(sharded.jax, "default_backend", lambda: "tpu")
        p = base_params(pca_method="power-fused", fused_resolution=False,
                        storage_dtype="int8")
        mesh = make_mesh(batch=2, event=4)
        # E divisible by the EVENT axis (4) but not by the device count
        # (8): the gate must accept — per-shard width is E/4
        resolved = _resolve_sharded_params(p, 1000, 4 * 501, mesh)
        assert resolved.fused_resolution

    def test_pure_batch_mesh_never_fused(self, monkeypatch):
        from pyconsensus_tpu.parallel import sharded

        monkeypatch.setattr(sharded.jax, "default_backend", lambda: "tpu")
        p = base_params(pca_method="power-fused", fused_resolution=False)
        mesh = make_mesh(batch=8, event=1)
        resolved = _resolve_sharded_params(p, 1000, 4096, mesh)
        assert not resolved.fused_resolution


class TestShardFusedFuzz:
    @pytest.mark.parametrize("trial", range(4))
    def test_random_shapes_and_storage(self, trial):
        """Randomized parity sweep: shapes, NA fractions, storage dtypes,
        reputation skews — outcomes must stay bit-identical to the
        single-device fused path on every draw."""
        rng = np.random.default_rng(100 + trial)
        R_f = int(rng.integers(9, 40))
        E_f = 8 * int(rng.integers(2, 12))       # divisible by the mesh
        storage = rng.choice(["int8", "bfloat16", ""])
        na = float(rng.uniform(0.0, 0.3))
        reports, _ = collusion_reports(rng, R_f, E_f,
                                       liars=max(2, R_f // 4), na_frac=na)
        rep = rng.random(R_f) + 0.02
        rep = rep / rep.sum()
        p = base_params(storage_dtype=str(storage),
                        max_iterations=int(rng.integers(1, 4)))
        sharded, single = run_both(reports, rep, p)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-6)

    @pytest.mark.parametrize("trial", range(4))
    def test_random_nondivisible_and_scaled(self, trial):
        """Round-4 gate fuzz: ARBITRARY event counts (any pad width,
        including entirely-padded trailing shards) composed with random
        scaled-column minorities at random positions — parity against
        the single-device fused path on every draw."""
        rng = np.random.default_rng(500 + trial)
        R_f = int(rng.integers(9, 40))
        E_f = int(rng.integers(17, 95))          # arbitrary width
        n_sc = int(rng.integers(0, max(1, E_f // 8)))
        na = float(rng.uniform(0.0, 0.25))
        reports, _ = collusion_reports(rng, R_f, E_f,
                                       liars=max(2, R_f // 4), na_frac=na)
        rep = rng.random(R_f) + 0.02
        rep = rep / rep.sum()
        if n_sc:
            cols = rng.choice(E_f, size=n_sc, replace=False)
            scaled = np.zeros(E_f, bool)
            scaled[cols] = True
            mins = np.where(scaled, -5.0, 0.0)
            maxs = np.where(scaled, 15.0, 1.0)
            with np.errstate(invalid="ignore"):
                reports[:, scaled] = reports[:, scaled] * 20.0 - 5.0
            p = base_params(any_scaled=True, n_scaled=n_sc,
                            storage_dtype=str(rng.choice(["bfloat16", ""])),
                            max_iterations=int(rng.integers(1, 3)))
            sharded, single = run_both_scaled(reports, rep, p, scaled,
                                              mins, maxs)
            # random draws sit slightly above the curated fixtures'
            # 5e-6 band (different psum orders through the power loop) —
            # binary outcomes stay exact inside assert_scaled_parity
            assert_scaled_parity(sharded, single, scaled, atol=5e-5)
        else:
            p = base_params(
                storage_dtype=str(rng.choice(["int8", "bfloat16", ""])),
                max_iterations=int(rng.integers(1, 3)))
            sharded, single = run_both(reports, rep, p)
            np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                          single["outcomes_adjusted"])
            np.testing.assert_allclose(sharded["smooth_rep"],
                                       single["smooth_rep"], atol=5e-5)
