"""Parity tests for the shard_map fused path (parallel.fused_sharded):
the multi-device kernel path must agree with the single-device fused
path (the headline pipeline) — outcomes bit-identically (catch-snapped),
reputations to f32-kernel tolerance — across storage dtypes, NA
patterns, iteration counts, and mesh widths, on the 8-virtual-device CPU
mesh with the Pallas kernels in interpret mode."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from conftest import collusion_reports
from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                             _consensus_core_fused)
from pyconsensus_tpu.parallel import make_mesh
from pyconsensus_tpu.parallel.fused_sharded import fused_sharded_consensus
from pyconsensus_tpu.parallel.sharded import (_place_inputs,
                                              _resolve_sharded_params)

R, E = 24, 64


def base_params(**kw):
    kw.setdefault("algorithm", "sztorc")
    kw.setdefault("pca_method", "power")
    kw.setdefault("power_iters", 128)
    kw.setdefault("power_tol", 0.0)
    kw.setdefault("any_scaled", False)
    kw.setdefault("has_na", True)
    kw.setdefault("fused_resolution", True)
    return ConsensusParams(**kw)


def run_both(reports, rep, p, n_event=8):
    mesh = make_mesh(batch=1, event=n_event)
    Ecols = reports.shape[1]
    placed = _place_inputs(mesh, reports, rep, np.zeros(Ecols, bool),
                           np.zeros(Ecols), np.ones(Ecols))
    sharded = fused_sharded_consensus(placed[0], placed[1], mesh, p)
    single = _consensus_core_fused(
        jnp.asarray(reports), jnp.asarray(rep), jnp.zeros(Ecols, bool),
        jnp.zeros(Ecols), jnp.ones(Ecols), p)
    return ({k: np.asarray(v) for k, v in sharded.items()},
            {k: np.asarray(v) for k, v in single.items()})


class TestShardFusedParity:
    @pytest.mark.parametrize("storage", ["int8", "bfloat16", ""])
    def test_matches_single_device_fused(self, rng, storage):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.15)
        rep = np.full(R, 1.0 / R)
        sharded, single = run_both(reports, rep,
                                   base_params(storage_dtype=storage))
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        np.testing.assert_array_equal(sharded["na_row"], single["na_row"])
        for key in ("this_rep", "smooth_rep", "certainty",
                    "participation_rows", "participation_columns",
                    "reporter_bonus", "author_bonus", "consensus_reward"):
            np.testing.assert_allclose(sharded[key], single[key],
                                       atol=5e-6, err_msg=key)
        # the loading converges through different reduction orders (and
        # near-tied |max| entries can flip the canonical sign): align by
        # dot-product sign and allow f32-kernel noise
        a, b = sharded["first_loading"], single["first_loading"]
        a = a * np.sign(np.dot(a, b)) if np.dot(a, b) != 0 else a
        np.testing.assert_allclose(a, b, atol=1e-3)

    def test_iterative_loop(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.1)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="int8", max_iterations=5)
        sharded, single = run_both(reports, rep, p)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        assert sharded["iterations"] == single["iterations"]
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-6)

    def test_dense_no_na(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.0)
        rep = np.full(R, 1.0 / R)
        sharded, single = run_both(reports, rep,
                                   base_params(storage_dtype="int8"))
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        assert sharded["percent_na"] == pytest.approx(0.0, abs=1e-12)
        assert not sharded["na_row"].any()

    def test_nonuniform_reputation(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.1)
        rep = rng.random(R) + 0.05
        rep = rep / rep.sum()
        sharded, single = run_both(reports, rep,
                                   base_params(storage_dtype="int8"))
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-6)

    @pytest.mark.parametrize("n_event", [2, 4])
    def test_mesh_width_invariance(self, rng, n_event):
        """Same inputs across mesh widths: catch-snapped outcomes must be
        identical (cross-sharding determinism, the race-detection
        analogue)."""
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.15)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="int8")
        sharded, single = run_both(reports, rep, p, n_event=n_event)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])

    def test_batch_event_mesh_composition(self, rng):
        """The dp x sp composition: a batch x event mesh replicates the
        resolution over 'batch' while the kernels shard over 'event' —
        outcomes must stay bit-identical to the single-device path."""
        reports, _ = collusion_reports(rng, R, E, liars=5, na_frac=0.15)
        rep = np.full(R, 1.0 / R)
        p = base_params(storage_dtype="int8")
        mesh = make_mesh(batch=2, event=4)
        placed = _place_inputs(mesh, reports, rep, np.zeros(E, bool),
                               np.zeros(E), np.ones(E))
        sharded = fused_sharded_consensus(placed[0], placed[1], mesh, p)
        single = _consensus_core_fused(
            jnp.asarray(reports), jnp.asarray(rep), jnp.zeros(E, bool),
            jnp.zeros(E), jnp.ones(E), p)
        np.testing.assert_array_equal(
            np.asarray(sharded["outcomes_adjusted"]),
            np.asarray(single["outcomes_adjusted"]))


class TestShardFusedGates:
    def test_scaled_rejected(self, rng):
        reports, _ = collusion_reports(rng, R, E, liars=5)
        mesh = make_mesh(batch=1, event=8)
        placed = _place_inputs(mesh, reports, np.full(R, 1.0 / R),
                               np.zeros(E, bool), np.zeros(E), np.ones(E))
        with pytest.raises(ValueError, match="binary-only"):
            fused_sharded_consensus(placed[0], placed[1], mesh,
                                    base_params(any_scaled=True, n_scaled=2))

    def test_indivisible_events_rejected(self, rng):
        # raw (unplaced) arrays: the divisibility check fires before any
        # placement — placing an uneven shape would already fail in jax
        reports, _ = collusion_reports(rng, R, 60, liars=5)
        mesh = make_mesh(batch=1, event=8)
        with pytest.raises(ValueError, match="divisible"):
            fused_sharded_consensus(jnp.asarray(reports),
                                    jnp.full((R,), 1.0 / R), mesh,
                                    base_params())

    def test_resolver_closes_gate_off_tpu(self):
        """On the CPU test platform the fused gate stays closed (backend
        check), and a multi-device power-fused request must downgrade to
        the XLA matvecs rather than leak a black-box Pallas call into
        GSPMD."""
        mesh = make_mesh(batch=1, event=8)
        p = _resolve_sharded_params(
            base_params(pca_method="power-fused", fused_resolution=False),
            10_000, 4096, mesh)
        assert not p.fused_resolution
        assert p.pca_method == "power"

    def test_gate_conditions_for_mesh(self, monkeypatch):
        """With the backend forced to report 'tpu', the multi-device gate
        must require divisible events and reject scaled configs, and the
        auto-storage rule must then pick int8 on the mesh."""
        from pyconsensus_tpu.parallel import resolve_auto_storage, sharded

        monkeypatch.setattr(sharded.jax, "default_backend", lambda: "tpu")
        mesh = make_mesh(batch=1, event=8)
        # int8 storage: under the x64 test config the default itemsize is
        # 8, which legitimately fails resolve_kernel_fits at R=10k
        p = base_params(pca_method="power-fused", fused_resolution=False,
                        storage_dtype="int8")
        resolved = _resolve_sharded_params(p, 10_000, 4096, mesh)
        assert resolved.fused_resolution
        storage, why = resolve_auto_storage(
            ConsensusParams(algorithm="sztorc", any_scaled=False,
                            has_na=True), 10_000, 4096, mesh)
        assert storage == "int8", why
        # indivisible E closes the mesh gate — and with int8 storage the
        # resolver must then REFUSE loudly rather than fall through to
        # the XLA path (which stores continuous fills)
        with pytest.raises(ValueError, match="int8"):
            _resolve_sharded_params(p, 10_000, 4097, mesh)
        # scaled events close the mesh gate outright (the gather-and-fix
        # would cross shards) — same loud int8 refusal
        with pytest.raises(ValueError, match="int8"):
            _resolve_sharded_params(
                p._replace(any_scaled=True, n_scaled=8), 10_000, 4096,
                mesh)
        # without int8 the same closures quietly take the XLA path
        clean = p._replace(storage_dtype="")
        assert not _resolve_sharded_params(clean, 10_000, 4097,
                                           mesh).fused_resolution
        assert not _resolve_sharded_params(
            clean._replace(any_scaled=True, n_scaled=8), 10_000, 4097,
            mesh).fused_resolution


class TestBatchEventMeshGate:
    """The fused gate must size and trigger on the EVENT axis width, not
    the device count: a batch x event mesh shards columns only over
    'event', and a pure-batch mesh has no event sharding for the kernels
    to ride at all."""

    def test_batch_event_mesh_sizes_on_event_axis(self, monkeypatch):
        from pyconsensus_tpu.parallel import sharded

        monkeypatch.setattr(sharded.jax, "default_backend", lambda: "tpu")
        p = base_params(pca_method="power-fused", fused_resolution=False,
                        storage_dtype="int8")
        mesh = make_mesh(batch=2, event=4)
        # E divisible by the EVENT axis (4) but not by the device count
        # (8): the gate must accept — per-shard width is E/4
        resolved = _resolve_sharded_params(p, 1000, 4 * 501, mesh)
        assert resolved.fused_resolution

    def test_pure_batch_mesh_never_fused(self, monkeypatch):
        from pyconsensus_tpu.parallel import sharded

        monkeypatch.setattr(sharded.jax, "default_backend", lambda: "tpu")
        p = base_params(pca_method="power-fused", fused_resolution=False)
        mesh = make_mesh(batch=8, event=1)
        resolved = _resolve_sharded_params(p, 1000, 4096, mesh)
        assert not resolved.fused_resolution


class TestShardFusedFuzz:
    @pytest.mark.parametrize("trial", range(4))
    def test_random_shapes_and_storage(self, trial):
        """Randomized parity sweep: shapes, NA fractions, storage dtypes,
        reputation skews — outcomes must stay bit-identical to the
        single-device fused path on every draw."""
        rng = np.random.default_rng(100 + trial)
        R_f = int(rng.integers(9, 40))
        E_f = 8 * int(rng.integers(2, 12))       # divisible by the mesh
        storage = rng.choice(["int8", "bfloat16", ""])
        na = float(rng.uniform(0.0, 0.3))
        reports, _ = collusion_reports(rng, R_f, E_f,
                                       liars=max(2, R_f // 4), na_frac=na)
        rep = rng.random(R_f) + 0.02
        rep = rep / rep.sum()
        p = base_params(storage_dtype=str(storage),
                        max_iterations=int(rng.integers(1, 4)))
        sharded, single = run_both(reports, rep, p)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      single["outcomes_adjusted"])
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   single["smooth_rep"], atol=5e-6)
