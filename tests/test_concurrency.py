"""consensus-lint Layer 4 (ISSUE 9): trigger/no-trigger corpus for the
host-concurrency rules CL801-CL805, the annotation/pragma conventions,
the interprocedural lock flow (cross-module inversion, lambda bodies,
method receivers), the live package-is-clean invariant, the runtime
lock witness (recording, cycle detection, static-graph consistency,
JSON round-trip), the fault-site catalog pins (code + docs), and the
metric-name drift checker."""

import json
import pathlib
import re
import sys
import textwrap
import threading
import time

import pytest

from pyconsensus_tpu.analysis.cli import run as cli_run
from pyconsensus_tpu.analysis.concurrency import (CONCURRENCY_RULES,
                                                  analyze_concurrency,
                                                  lock_order_edges)
from pyconsensus_tpu.analysis import witness as witness_mod
from pyconsensus_tpu.analysis.witness import (LockWitness, WitnessViolation,
                                              load_witness,
                                              static_lock_graph, witnessed)
from pyconsensus_tpu.faults import FAULT_SITES

REPO = pathlib.Path(__file__).resolve().parents[1]


def _conc(tmp_path, **files):
    """Write ``name -> source`` modules and run Layer 4 over the dir."""
    for name, src in files.items():
        (tmp_path / f"{name}.py").write_text(textwrap.dedent(src))
    return analyze_concurrency(paths=[tmp_path])


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- CL801


class TestLockOrderCycles:
    INVERT_A = """
        import threading
        from jmod import Journal

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.j = Journal()

            def alpha(self):
                with self._lock:
                    self.j.write()

            def flush(self):
                with self._lock:
                    pass
        """
    INVERT_B = """
        import threading

        class Journal:
            def __init__(self):
                self._jlock = threading.Lock()

            def write(self):
                with self._jlock:
                    pass

            def beta(self, store):
                with self._jlock:
                    store.flush()
        """

    def test_cross_module_inversion_triggers(self, tmp_path):
        fs = _conc(tmp_path, smod=self.INVERT_A, jmod=self.INVERT_B)
        assert _rules(fs) == ["CL801"]
        (f,) = fs
        assert "Store._lock" in f.message and "Journal._jlock" in f.message
        assert "deadlock" in f.message

    def test_consistent_order_is_clean(self, tmp_path):
        # same shape, but beta respects the store-before-journal order
        clean_b = self.INVERT_B.replace(
            "with self._jlock:\n                    store.flush()",
            "store.flush()")
        fs = _conc(tmp_path, smod=self.INVERT_A, jmod=clean_b)
        assert fs == []

    def test_declared_order_violation_without_cycle(self, tmp_path):
        fs = _conc(tmp_path, decl="""
            import threading

            # consensus-lint: lock-order Worker.a_lock < Worker.b_lock

            class Worker:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def bad(self):
                    with self.b_lock:
                        with self.a_lock:
                            pass
            """)
        assert _rules(fs) == ["CL801"]
        assert "contradicts the declared lock order" in fs[0].message

    def test_declared_order_matching_edge_is_clean(self, tmp_path):
        fs = _conc(tmp_path, decl="""
            import threading

            # consensus-lint: lock-order Worker.a_lock < Worker.b_lock

            class Worker:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def good(self):
                    with self.a_lock:
                        with self.b_lock:
                            pass
            """)
        assert fs == []

    def test_reentrant_same_lock_is_not_a_cycle(self, tmp_path):
        fs = _conc(tmp_path, re="""
            import threading

            class R:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
            """)
        assert fs == []

    def test_suppression_with_rationale(self, tmp_path):
        fs = _conc(tmp_path, decl="""
            import threading

            # consensus-lint: lock-order Worker.a_lock < Worker.b_lock

            class Worker:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def bad(self):
                    with self.b_lock:
                        with self.a_lock:  # consensus-lint: disable=CL801 — drain path: b is private here
                            pass
            """)
        assert fs == []


# ------------------------------------------------------------- CL802


class TestBlockingUnderLock:
    def test_future_result_under_lock(self, tmp_path):
        fs = _conc(tmp_path, disp="""
            import threading

            class Dispatcher:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fut):
                    with self._lock:
                        return fut.result()
            """)
        assert _rules(fs) == ["CL802"]
        assert "future" in fs[0].message

    def test_bounded_timeout_is_exempt(self, tmp_path):
        fs = _conc(tmp_path, disp="""
            import threading

            class Dispatcher:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fut):
                    with self._lock:
                        return fut.result(timeout=1.0)
            """)
        assert fs == []

    def test_result_outside_lock_is_clean(self, tmp_path):
        fs = _conc(tmp_path, disp="""
            import threading

            class Dispatcher:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fut):
                    with self._lock:
                        pending = fut
                    return pending.result()
            """)
        assert fs == []

    def test_sleep_and_queue_handle_dataflow(self, tmp_path):
        fs = _conc(tmp_path, q="""
            import queue
            import threading
            import time

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def nap(self):
                    with self._lock:
                        time.sleep(0.5)

                def drain(self):
                    with self._lock:
                        return self._q.get()

                def drain_bounded(self):
                    with self._lock:
                        return self._q.get(timeout=0.1)
            """)
        assert [f.line for f in fs] == [13, 17]
        assert _rules(fs) == ["CL802"]

    def test_positional_args_are_not_timeouts(self, tmp_path):
        # q.put(item) and q.get(True) carry positional args that are
        # NOT timeouts — both block unboundedly; only the methods'
        # actual timeout slots (or timeout=) bound the wait
        fs = _conc(tmp_path, q="""
            import queue
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue(maxsize=2)

                def feed(self, item):
                    with self._lock:
                        self._q.put(item)

                def poll(self):
                    with self._lock:
                        return self._q.get(True)

                def feed_bounded(self, item):
                    with self._lock:
                        self._q.put(item, True, 0.5)
            """)
        assert _rules(fs) == ["CL802"]
        assert [f.line for f in fs] == [12, 16]

    def test_wait_for_predicate_arg_is_not_a_timeout(self, tmp_path):
        fs = _conc(tmp_path, wf="""
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition()

                def bad(self):
                    with self._lock:
                        self._cond.wait_for(lambda: True)

                def ok(self):
                    with self._lock:
                        self._cond.wait_for(lambda: True, 0.5)
            """)
        assert _rules(fs) == ["CL802"]
        assert [f.line for f in fs] == [11]

    def test_interprocedural_blocking_through_callee(self, tmp_path):
        # the lock is held HERE; the blocking wait lives in the callee —
        # the callee's entry held set carries the caller's lock
        fs = _conc(tmp_path, ip="""
            import threading

            class Gate:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._ev = threading.Event()

                def locked_wait(self):
                    with self._lock:
                        self._park()

                def _park(self):
                    self._ev.wait()
            """)
        assert _rules(fs) == ["CL802"]
        assert fs[0].path == "ip.py"

    def test_condition_wait_on_held_condition_is_the_idiom(self, tmp_path):
        fs = _conc(tmp_path, c="""
            import threading

            class Box:
                def __init__(self):
                    self._cond = threading.Condition()

                def take(self):
                    with self._cond:
                        while True:
                            self._cond.wait()
            """)
        assert fs == []

    def test_lambda_body_lock_flow(self, tmp_path):
        # acquisitions inside a lambda run in the enclosing scope: an
        # inversion seeded through a lambda must still be seen
        fs = _conc(tmp_path, lam="""
            import threading

            class L:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def one(self):
                    with self.a_lock:
                        run = lambda: self.take_b()
                        run()

                def take_b(self):
                    with self.b_lock:
                        pass

                def two(self):
                    with self.b_lock:
                        self.take_a()

                def take_a(self):
                    with self.a_lock:
                        pass
            """)
        assert "CL801" in _rules(fs)

    def test_annotated_receiver_type_lock_flow(self, tmp_path):
        # the receiver's lock resolves through the parameter annotation:
        # Store._lock -> Worker.wlock in one method and the reverse in
        # another is a cross-class inversion
        fs = _conc(tmp_path, recv="""
            import threading

            class Worker:
                def __init__(self):
                    self.wlock = threading.Lock()

                def back(self, store: "Store"):
                    with self.wlock:
                        with store._lock:
                            pass

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()

                def forth(self, w: Worker):
                    with self._lock:
                        with w.wlock:
                            pass
            """)
        assert _rules(fs) == ["CL801"]
        assert "Worker.wlock" in fs[0].message

    def test_acquire_release_linear_tracking(self, tmp_path):
        fs = _conc(tmp_path, ar="""
            import threading
            import time

            class P:
                def __init__(self):
                    self._lock = threading.Lock()

                def work(self):
                    self._lock.acquire()
                    time.sleep(0.1)
                    self._lock.release()
                    time.sleep(0.2)
            """)
        # only the sleep BETWEEN acquire and release is under the lock
        assert _rules(fs) == ["CL802"]
        assert [f.line for f in fs] == [11]

    def test_method_receiver_lock_flow(self, tmp_path):
        # a non-self receiver resolves through the attribute's recorded
        # type: w.declare_lock is a Worker lock on another OBJECT, so
        # holding ours then theirs plus the converse is a real cycle
        fs = _conc(tmp_path, recv="""
            import threading

            class Worker:
                def __init__(self):
                    self.declare_lock = threading.Lock()

                def claim_pair(self, other):
                    with self.declare_lock:
                        with other.declare_lock:
                            pass
            """)
        # same site key for both -> identity-equal, no self edge
        assert fs == []


# ------------------------------------------------------- CL803 / CL804


class TestGuardedBy:
    MIXED = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.mixed = 0

            def inc(self):
                with self._lock:
                    self.n += 1

            def inc2(self):
                with self._lock:
                    self.n += 2

            def rogue(self):
                self.n = 0

            def m1(self):
                with self._lock:
                    self.mixed = 1

            def m2(self):
                other = threading.Lock()
                with other:
                    self.mixed = 2
        """

    def test_majority_guard_and_mixed_sets(self, tmp_path):
        fs = _conc(tmp_path, g=self.MIXED)
        assert _rules(fs) == ["CL803", "CL804"]
        cl803, = [f for f in fs if f.rule == "CL803"]
        assert "Counter.n" in cl803.message
        assert "majority" in cl803.message
        cl804, = [f for f in fs if f.rule == "CL804"]
        assert "Counter.mixed" in cl804.message

    def test_consistent_locking_is_clean(self, tmp_path):
        clean = self.MIXED.replace("def rogue(self):\n                self.n = 0",
                                   "def rogue(self):\n                pass")
        clean = clean.replace(
            "other = threading.Lock()\n                with other:",
            "with self._lock:")
        assert _conc(tmp_path, g=clean) == []

    def test_nested_majority_guard_is_the_best_supported_lock(
            self, tmp_path):
        # both locks clear the strict majority (outer nests inner at 3
        # of 5 writes) but `inner` is held at ALL five — it is the
        # guard, and the two inner-only writes must NOT be flagged
        # against the alphabetically-earlier outer lock
        fs = _conc(tmp_path, nest="""
            import threading

            class M:
                def __init__(self):
                    self.a_outer = threading.Lock()
                    self.b_inner = threading.Lock()
                    self.x = 0

                def w1(self):
                    with self.a_outer:
                        with self.b_inner:
                            self.x = 1
                            self.x = 2
                            self.x = 3

                def w2(self):
                    with self.b_inner:
                        self.x = 4
                        self.x = 5
            """)
        assert fs == []

    def test_guarded_by_annotation_pins_single_write(self, tmp_path):
        # < 2 write sites would normally be under the inference floor;
        # the annotation forces the check anyway
        fs = _conc(tmp_path, a="""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state = "idle"   # guarded-by: _lock

                def set(self):
                    self.state = "hot"
            """)
        assert _rules(fs) == ["CL803"]
        assert "annotated" in fs[0].message

    def test_guarded_by_none_opts_out(self, tmp_path):
        fs = _conc(tmp_path, a="""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0   # guarded-by: none

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    with self._lock:
                        self.n += 2

                def c(self):
                    self.n = 0
            """)
        assert fs == []

    def test_annotation_naming_unknown_lock(self, tmp_path):
        fs = _conc(tmp_path, a="""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0   # guarded-by: _mutex

                def a(self):
                    self.n = 1
            """)
        assert _rules(fs) == ["CL804"]
        assert "_mutex" in fs[0].message

    def test_init_writes_are_construction_time(self, tmp_path):
        fs = _conc(tmp_path, a="""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                    self.n = 1

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    with self._lock:
                        self.n += 2
            """)
        assert fs == []


# ------------------------------------------------------------- CL805


class TestFaultSiteDrift:
    def test_unknown_site_triggers(self, tmp_path):
        fs = _conc(tmp_path, h="""
            from pyconsensus_tpu import faults

            def touch():
                faults.fire("no.such.site")
            """)
        assert _rules(fs) == ["CL805"]
        assert "no.such.site" in fs[0].message

    def test_cataloged_site_is_clean(self, tmp_path):
        fs = _conc(tmp_path, h="""
            from pyconsensus_tpu import faults

            def touch(value):
                faults.fire("serve.enqueue")
                return faults.corrupt("oracle.reports", value)
            """)
        assert fs == []

    def test_catalog_completeness_is_full_scan_only(self, tmp_path):
        # a restricted scan must not demand every cataloged site appear
        fs = _conc(tmp_path, h="""
            def nothing():
                return 1
            """)
        assert fs == []

    def test_every_cataloged_site_has_a_hook_in_the_package(self):
        hook_re = re.compile(r'(?:fire|corrupt)\(\s*"([a-z_.]+)"')
        seen = set()
        for p in (REPO / "pyconsensus_tpu").rglob("*.py"):
            seen.update(hook_re.findall(p.read_text(encoding="utf-8")))
        assert set(FAULT_SITES) <= seen, \
            f"cataloged sites without hooks: {set(FAULT_SITES) - seen}"
        assert seen <= set(FAULT_SITES), \
            f"hook sites missing from the catalog: {seen - set(FAULT_SITES)}"

    def test_robustness_doc_table_matches_catalog(self):
        # the doc-side half of the pin: docs/ROBUSTNESS.md's site table
        # rows name exactly the cataloged sites
        doc = (REPO / "docs" / "ROBUSTNESS.md").read_text(encoding="utf-8")
        rows = set()
        for line in doc.splitlines():
            m = re.match(r"^\|\s*`([a-z_][a-z_.]*)`\s*\|", line.strip())
            if m and "." in m.group(1):
                rows.add(m.group(1))
        doc_sites = {r for r in rows if r in FAULT_SITES or not
                     r.startswith("pyconsensus")}
        assert set(FAULT_SITES) == doc_sites, (
            f"docs/ROBUSTNESS.md site table drift: doc-only "
            f"{doc_sites - set(FAULT_SITES)}, code-only "
            f"{set(FAULT_SITES) - doc_sites}")


# ------------------------------------------------- live package + CLI


def test_package_is_clean():
    """The shipped-baseline-stays-EMPTY invariant for Layer 4: every
    true positive found while building the layer was fixed or carries a
    rationale pragma/annotation in place."""
    assert analyze_concurrency() == []


def test_lock_order_edges_shape():
    g = lock_order_edges()
    assert set(g) == {"locks", "edges"}
    key_re = re.compile(r"^[\w/.-]+\.py:\d+$")
    assert g["locks"], "the package defines locks; the table is empty"
    for key, name in g["locks"].items():
        assert key_re.match(key), key
    lock_keys = set(g["locks"])
    for a, b in g["edges"]:
        assert a in lock_keys and b in lock_keys, (a, b)
    names = set(g["locks"].values())
    # the lock-dense serve tier is represented by its known identities
    assert "MarketSession._lock" in names
    # the per-worker declare lock moved to the shared transport handle
    # base (ISSUE 15) so BOTH transports' handles carry one identity
    assert "WorkerBase.declare_lock" in names


def test_cli_select_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "inv.py"
    bad.write_text(textwrap.dedent("""
        import threading

        class A:
            def __init__(self):
                self.l1 = threading.Lock()
                self.l2 = threading.Lock()

            def one(self):
                with self.l1:
                    with self.l2:
                        pass

            def two(self):
                with self.l2:
                    with self.l1:
                        pass
        """))
    assert cli_run(["--select", "CL801", "--no-baseline", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "CL801" in out
    # --no-concurrency opts the layer out entirely
    assert cli_run(["--select", "CL801", "--no-baseline",
                    "--no-concurrency", str(bad)]) == 0
    # selecting a non-CL80x rule skips the Layer-4 fixpoint's findings
    assert cli_run(["--select", "CL203", "--no-baseline", str(bad)]) == 0


def test_cli_list_rules_shows_layer4(capsys):
    assert cli_run(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "Layer 4 (host concurrency):" in out
    for rid in CONCURRENCY_RULES:
        assert rid in out


# ------------------------------------------------------ runtime witness


@pytest.fixture
def here_witness(monkeypatch):
    """A witness that records locks constructed from THIS test file
    (the package filter is pointed at tests/)."""
    monkeypatch.setattr(witness_mod, "_PKG_DIR",
                        str(pathlib.Path(__file__).resolve().parent))
    w = LockWitness().install()
    yield w
    w.uninstall()


class TestLockWitness:
    def test_records_edges_and_detects_cycle(self, here_witness, tmp_path):
        w = here_witness
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        w.uninstall()
        assert len(w.edges) == 2
        dump = tmp_path / "w" / "witness.json"
        with pytest.raises(WitnessViolation) as ei:
            w.check(dump_path=dump)
        assert ei.value.cycle[0] == ei.value.cycle[-1]
        assert ei.value.dump_path == str(dump)
        # round-trip: the dump carries the full observed relation
        doc = load_witness(dump)
        assert {(e["from"], e["to"]) for e in doc["edges"]} == set(w.edges)
        assert set(doc["locks"]) == set(w.locks)
        for e in doc["edges"]:
            assert e["thread"] == "MainThread"

    def test_union_with_static_graph_detects_contradiction(
            self, here_witness):
        w = here_witness
        a = threading.Lock()
        b = threading.Lock()
        with b:          # observed: B -> A only
            with a:
                pass
        w.uninstall()
        (kb, ka), = list(w.edges)
        # no observed cycle on its own...
        w.check()
        # ...but the static graph documents A < B: the union is cyclic
        static = {"locks": {ka: "T.a", kb: "T.b"}, "edges": [[ka, kb]]}
        with pytest.raises(WitnessViolation) as ei:
            w.check(static=static)
        assert "contradicts the static" in str(ei.value)
        assert "T.a" in str(ei.value) and "T.b" in str(ei.value)

    def test_static_only_cycle_is_not_blamed_on_observation(
            self, here_witness):
        # a cycle purely among STATIC edges is CL801's finding; the
        # witness must not raise over runtime behavior that never
        # happened — neither with zero observed edges nor with an
        # observed edge disjoint from the static cycle
        w = here_witness
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        w.uninstall()
        (ka, kb), = list(w.edges)
        static = {"locks": {}, "edges": [["s1", "s2"], ["s2", "s1"],
                                         [ka, kb]]}
        rep = w.check(static=static)
        assert {(e["from"], e["to"]) for e in rep["edges"]} == {(ka, kb)}
        LockWitness().check(static=static)    # zero observed edges

    def test_consistent_run_passes_and_reports(self, here_witness):
        w = here_witness
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        w.uninstall()
        (ka, kb), = list(w.edges)
        rep = w.check(static={"locks": {}, "edges": [[ka, kb]]})
        assert rep["edges"][0]["from"] == ka

    def test_same_creation_site_instances_share_identity(
            self, here_witness):
        # two instances of one class share the defining line — ordering
        # between them is invisible to the static side, so the witness
        # must not fabricate a self-edge either
        w = here_witness

        def make():
            return threading.Lock()

        a, b = make(), make()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        w.uninstall()
        assert w.edges == {}
        w.check()

    def test_condition_wait_releases_held_state(self, here_witness):
        w = here_witness
        cond = threading.Condition()
        other = threading.Lock()
        taken = []

        def waiter():
            with cond:
                cond.wait(timeout=1.0)
            # after the block NOTHING is held: were wait()'s
            # release/re-acquire bookkeeping broken, a leaked cond
            # entry would fabricate a cond -> other edge here
            with other:
                taken.append(True)

        def notifier():
            time.sleep(0.1)
            with cond:
                cond.notify_all()

        t1 = threading.Thread(target=waiter)
        t2 = threading.Thread(target=notifier)
        t1.start(); t2.start(); t1.join(); t2.join()
        w.uninstall()
        assert taken
        assert w.edges == {}
        w.check()

    def test_outside_package_locks_are_untouched(self):
        # default filter: locks built from tests/ are NOT package locks
        w = LockWitness().install()
        try:
            lk = threading.Lock()
            assert not isinstance(lk, witness_mod._WitnessedLock)
        finally:
            w.uninstall()
        assert w.locks == {}

    def test_install_uninstall_restores_threading(self):
        saved = {k: getattr(threading, k) for k in witness_mod._PATCHED}
        w = LockWitness().install()
        assert threading.Lock is not saved["Lock"]
        w.uninstall()
        for k, v in saved.items():
            assert getattr(threading, k) is v

    def test_witnessed_context_manager_raises_on_cycle(
            self, monkeypatch, tmp_path):
        monkeypatch.setattr(witness_mod, "_PKG_DIR",
                            str(pathlib.Path(__file__).resolve().parent))
        with pytest.raises(WitnessViolation):
            with witnessed(dump_path=tmp_path / "w.json"):
                a = threading.Lock()
                b = threading.Lock()
                with a:
                    with b:
                        pass
                with b:
                    with a:
                        pass
        assert (tmp_path / "w.json").exists()

    def test_witness_proxy_is_a_working_lock(self, here_witness):
        lk = threading.Lock()
        assert isinstance(lk, witness_mod._WitnessedLock)
        assert lk.acquire(timeout=0.5)
        assert lk.locked()
        assert not lk.acquire(blocking=False)
        lk.release()
        assert not lk.locked()
        r = threading.RLock()
        with r:
            with r:      # reentrancy forwards
                pass
        # a Condition built over a witnessed RLock exercises the
        # _release_save/_acquire_restore protocol
        cond = threading.Condition(r)
        with cond:
            assert not cond.wait(timeout=0.05)
        # the stdlib-supported Condition(plain Lock) form must keep
        # working while witnessed: the proxy advertises the protocol
        # names, so it must supply the plain-lock shims itself
        cond2 = threading.Condition(lk)
        with cond2:
            assert not cond2.wait(timeout=0.05)
        assert not lk.locked()

    def test_live_serve_primitives_consistent_with_static_graph(self):
        """The runtime mirror on real package code: exercise the serve
        queue/session/admission primitives under the witness and check
        the observed order against the static may-hold-before graph."""
        static = static_lock_graph()
        assert static["locks"] and static["edges"]
        with witnessed(static=static) as w:
            from pyconsensus_tpu.serve.admission import ClusterCapacity
            from pyconsensus_tpu.serve.queue import (RequestQueue,
                                                     ResolveRequest)

            q = RequestQueue(max_depth=4)
            q.put(ResolveRequest(reports=[[1.0]]))
            assert q.take(timeout=1.0) is not None
            cap = ClusterCapacity()
            cap.register("w0", queue_slots=4)
            cap.register("w1", queue_slots=4)
            cap.mark_dead("w0")
        # witnessed() already checked on exit; the queue's condition
        # acquisitions were recorded (package-filtered)
        assert any("queue.py" in k for k in w.locks)


# --------------------------------------------------- metric-name drift


class TestMetricDocDrift:
    def test_live_tree_is_in_sync(self):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import check_metric_docs
        finally:
            sys.path.pop(0)
        undocumented, unemitted, emitted = check_metric_docs.check()
        assert undocumented == [], \
            f"metrics emitted but missing from docs: {undocumented}"
        assert unemitted == [], \
            f"docs catalog rows with no emitting code: {unemitted}"
        assert len(emitted) > 30     # the registry is heavily used

    def test_detects_both_drift_directions(self, tmp_path):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import check_metric_docs
        finally:
            sys.path.pop(0)
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "m.py").write_text(textwrap.dedent("""
            from pyconsensus_tpu import obs

            def emit():
                obs.counter("pyconsensus_secret_total").inc()
                obs.gauge(
                    "pyconsensus_depth").set(1)
            """))
        catalog = tmp_path / "OBS.md"
        catalog.write_text(
            "| `pyconsensus_depth` | gauge | documented |\n"
            "| `pyconsensus_ghost_total` | counter | never emitted |\n")
        emitted = check_metric_docs.collect_emitted(pkg)
        documented = check_metric_docs.collect_documented(catalog)
        assert set(emitted) == {"pyconsensus_secret_total",
                                "pyconsensus_depth"}
        assert sorted(set(emitted) - documented) == \
            ["pyconsensus_secret_total"]
        assert sorted(documented - set(emitted)) == \
            ["pyconsensus_ghost_total"]
