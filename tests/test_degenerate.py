"""Degenerate-shape and degenerate-content regression suite: the inputs
where the mechanism's guards (zero-sum normalize, single-reporter
covariance denominator, no-disagreement direction) do the work. Behavior
pinned identically across both backends."""

import numpy as np
import pytest

from pyconsensus_tpu import Oracle

CASES = {
    # (reports, expected outcomes_final)
    "single_reporter": (np.array([[1.0, 0.0, 1.0]]), [1.0, 0.0, 1.0]),
    "single_event": (np.array([[1.0], [1.0], [0.0]]), [1.0]),
    "one_by_one": (np.array([[1.0]]), [1.0]),
    "unanimous": (np.ones((5, 4)), [1.0] * 4),
    "all_half": (np.full((4, 3), 0.5), [0.5] * 3),
    "two_reporters_opposed": (np.array([[1.0, 0.0], [0.0, 1.0]]),
                              [0.5, 0.5]),
}


@pytest.mark.parametrize("name", sorted(CASES))
@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_degenerate_case(name, backend):
    reports, expected = CASES[name]
    r = Oracle(reports=reports, backend=backend,
               max_iterations=2).consensus()
    rep = np.asarray(r["agents"]["smooth_rep"], dtype=float)
    assert np.isfinite(rep).all()
    assert (rep >= -1e-12).all()
    assert rep.sum() == pytest.approx(1.0)
    np.testing.assert_array_equal(
        np.asarray(r["events"]["outcomes_final"], dtype=float), expected)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_unanimous_keeps_reputation(backend):
    """No disagreement direction -> row_reward_weighted's degenerate guard
    returns the prior reputation unchanged (up to the smooth blend)."""
    prior = np.array([0.5, 0.3, 0.2])
    r = Oracle(reports=np.ones((3, 4)), reputation=prior,
               backend=backend).consensus()
    np.testing.assert_allclose(r["agents"]["smooth_rep"], prior, atol=1e-12)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_extreme_reputation_concentration(backend):
    """One reporter holding ~all reputation dictates outcomes."""
    reports = np.array([[1.0, 1.0, 0.0],
                        [0.0, 0.0, 1.0],
                        [0.0, 0.0, 1.0]])
    rep = np.array([1e6, 1.0, 1.0])
    r = Oracle(reports=reports, reputation=rep, backend=backend).consensus()
    np.testing.assert_array_equal(r["events"]["outcomes_final"],
                                  [1.0, 1.0, 0.0])
