"""Degenerate-shape and degenerate-content regression suite: the inputs
where the mechanism's guards (zero-sum normalize, single-reporter
covariance denominator, no-disagreement direction) do the work. Behavior
pinned identically across both backends."""

import numpy as np
import pytest

from pyconsensus_tpu import Oracle

CASES = {
    # (reports, expected outcomes_final)
    "single_reporter": (np.array([[1.0, 0.0, 1.0]]), [1.0, 0.0, 1.0]),
    "single_event": (np.array([[1.0], [1.0], [0.0]]), [1.0]),
    "one_by_one": (np.array([[1.0]]), [1.0]),
    "unanimous": (np.ones((5, 4)), [1.0] * 4),
    "all_half": (np.full((4, 3), 0.5), [0.5] * 3),
    "two_reporters_opposed": (np.array([[1.0, 0.0], [0.0, 1.0]]),
                              [0.5, 0.5]),
}


@pytest.mark.parametrize("name", sorted(CASES))
@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_degenerate_case(name, backend):
    reports, expected = CASES[name]
    r = Oracle(reports=reports, backend=backend,
               max_iterations=2).consensus()
    rep = np.asarray(r["agents"]["smooth_rep"], dtype=float)
    assert np.isfinite(rep).all()
    assert (rep >= -1e-12).all()
    assert rep.sum() == pytest.approx(1.0)
    np.testing.assert_array_equal(
        np.asarray(r["events"]["outcomes_final"], dtype=float), expected)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_all_nan_scaled_column_gather_path(backend):
    """An entirely-absent scaled event through the static-gather median
    (Oracle wires ``n_scaled`` whenever scaled columns are a minority):
    zero participation weight must fall back to the reputation-weighted
    fill mean, identically on both backends and equal to the full-width
    median path."""
    reports = np.array([[1.0, 0.0, 1.0, np.nan],
                        [1.0, 0.0, 1.0, np.nan],
                        [1.0, 0.0, 0.0, np.nan],
                        [0.0, 1.0, 1.0, np.nan]])
    bounds = [None, None, None, {"scaled": True, "min": 2.0, "max": 10.0}]
    o = Oracle(reports=reports, event_bounds=bounds, backend=backend)
    if backend == "jax":
        assert o.params.n_scaled == 1     # the gather path is actually on
    r = o.consensus()
    out = np.asarray(r["events"]["outcomes_final"], dtype=float)
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[:3], [1.0, 0.0, 1.0])
    assert 2.0 <= out[3] <= 10.0
    if backend == "jax":
        # bitwise equal to the full-width median (n_scaled=0) resolution
        full = Oracle(reports=reports, event_bounds=bounds, backend="jax")
        full.params = full.params._replace(n_scaled=0)
        np.testing.assert_array_equal(
            out, np.asarray(full.consensus()["events"]["outcomes_final"]))


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_unanimous_keeps_reputation(backend):
    """No disagreement direction -> row_reward_weighted's degenerate guard
    returns the prior reputation unchanged (up to the smooth blend)."""
    prior = np.array([0.5, 0.3, 0.2])
    r = Oracle(reports=np.ones((3, 4)), reputation=prior,
               backend=backend).consensus()
    np.testing.assert_allclose(r["agents"]["smooth_rep"], prior, atol=1e-12)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_extreme_reputation_concentration(backend):
    """One reporter holding ~all reputation dictates outcomes."""
    reports = np.array([[1.0, 1.0, 0.0],
                        [0.0, 0.0, 1.0],
                        [0.0, 0.0, 1.0]])
    rep = np.array([1e6, 1.0, 1.0])
    r = Oracle(reports=reports, reputation=rep, backend=backend).consensus()
    np.testing.assert_array_equal(r["events"]["outcomes_final"],
                                  [1.0, 1.0, 0.0])


def test_none_entries_are_missing_reports():
    """Reference compat: Python ``None`` in a reports list coerces to NaN
    (non-participation), like the reference's masked-array input."""
    r = Oracle(reports=[[1.0, None, 0.0], [1.0, 1.0, 0.0],
                        [0.0, 1.0, 1.0]]).consensus()
    np.testing.assert_array_equal(r["events"]["outcomes_final"],
                                  [1.0, 1.0, 0.0])
    assert bool(r["agents"]["na_row"][0])


def test_streaming_degenerate_shapes():
    """Single-column, single-panel, and panel-larger-than-E inputs all
    stream correctly."""
    from pyconsensus_tpu.parallel import streaming_consensus

    one_col = np.array([[1.0], [1.0], [0.0]])
    out = streaming_consensus(one_col, panel_events=4)
    np.testing.assert_array_equal(out["outcomes_final"], [1.0])
    wide = np.tile([1.0, 0.0, 1.0], (4, 1))
    out = streaming_consensus(wide, panel_events=1)
    np.testing.assert_array_equal(out["outcomes_final"], [1.0, 0.0, 1.0])


def test_checkpointed_sweep_single_trial(tmp_path):
    """A 1-trial, 1-chunk sweep round-trips through checkpoint + gather."""
    from pyconsensus_tpu.sim import CheckpointedSweep, CollusionSimulator

    sim = CollusionSimulator(n_reporters=6, n_events=4)
    sweep = CheckpointedSweep(sim, [0.2], [0.1], 1,
                              checkpoint_dir=tmp_path / "ck")
    assert sweep.n_chunks == 1
    assert sweep.run(host_id=0, n_hosts=1) == 1
    got = sweep.gather()
    assert got["correct_rate"].shape == (1, 1, 1)
