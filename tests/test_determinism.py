"""consensus-lint Layer 6 (ISSUE 17): trigger/no-trigger corpus for the
bit-determinism rules CL1001-CL1004 (unordered iteration, completion
order, host nondeterminism, float accumulation — including the
sanitizers and the interprocedural category threading), the CL1005
compiled-artifact half (scatter-family HLO scan + the StableHLO
double-trace pin over a shipped serve-bucket contract), the live
package-is-clean invariant, the runtime DigestWitness (green over real
durable-session operations, a tampered digest and a reordered fold both
flagged naming the op and BOTH digests), the shuffled-directory
bit-identical replay regression, the lint-rule docs drift checker, and
the ``--format sarif`` output schema."""

import io
import json
import pathlib
import shutil
import sys
import textwrap

import numpy as np
import pytest

from pyconsensus_tpu.analysis.cli import run as cli_run
from pyconsensus_tpu.analysis.contracts import (_builder_stablehlo_pin,
                                                _first_divergence,
                                                nondeterministic_ops)
from pyconsensus_tpu.analysis.determinism import (DETERMINISM_RULES,
                                                  STATIC_DETERMINISM_RULES,
                                                  analyze_determinism)
from pyconsensus_tpu.analysis.determinism_witness import (
    DeterminismWitnessViolation, DigestWitness, _canonical_record_digest,
    digest_witnessed)

REPO = pathlib.Path(__file__).resolve().parents[1]


def _det(tmp_path, **files):
    """Write ``name -> source`` modules and run Layer 6 over the dir."""
    for name, src in files.items():
        (tmp_path / f"{name}.py").write_text(textwrap.dedent(src))
    return analyze_determinism(paths=[tmp_path])


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- CL1001


class TestUnorderedIteration:
    def test_dict_fold_into_digest_triggers(self, tmp_path):
        """The seeded violation of the acceptance criteria: a digest
        folded over dict iteration order. The finding names the sink
        AND the unordered source."""
        fs = _det(tmp_path, m="""
            import hashlib

            def round_digest(votes):
                h = hashlib.sha256()
                for name, vote in votes.items():
                    h.update(f"{name}={vote}".encode())
                return h.hexdigest()
            """)
        assert _rules(fs) == ["CL1001"]
        (f,) = fs
        assert "digest" in f.message and ".items()" in f.message

    def test_sorted_dict_fold_is_clean(self, tmp_path):
        fs = _det(tmp_path, m="""
            import hashlib

            def round_digest(votes):
                h = hashlib.sha256()
                for name, vote in sorted(votes.items()):
                    h.update(f"{name}={vote}".encode())
                return h.hexdigest()
            """)
        assert fs == []

    def test_glob_into_journal_triggers_sorted_is_clean(self, tmp_path):
        """The filesystem-enumeration direction satellite 3 fixed in
        aotcache/sim: readdir order reaching a replication payload."""
        fs = _det(tmp_path, bad="""
            def ship(log, root):
                for p in root.glob("*.npz"):
                    log.journal_block(p.read_bytes())
            """, ok="""
            def ship(log, root):
                for p in sorted(root.glob("*.npz")):
                    log.journal_block(p.read_bytes())
            """)
        assert _rules(fs) == ["CL1001"]
        assert all(f.path.endswith("bad.py") for f in fs)

    def test_set_iteration_into_digest_triggers(self, tmp_path):
        fs = _det(tmp_path, m="""
            import hashlib

            def digest(names):
                h = hashlib.sha256()
                for n in {x.strip() for x in names}:
                    h.update(n.encode())
                return h.hexdigest()
            """)
        assert _rules(fs) == ["CL1001"]

    def test_json_without_sort_keys_triggers_canonical_is_clean(
            self, tmp_path):
        fs = _det(tmp_path, bad="""
            import json

            def artifact(stats):
                rows = [v for v in stats.values()]
                return json.dumps(rows)
            """, ok="""
            import json

            def artifact(stats):
                rows = [v for v in stats.values()]
                return json.dumps(rows, sort_keys=True)
            """)
        assert _rules(fs) == ["CL1001"]
        assert all(f.path.endswith("bad.py") for f in fs)
        assert "sort_keys" in fs[0].message

    def test_interprocedural_category_threads_through_helper(
            self, tmp_path):
        """The helper RETURNS the unordered value; the caller digests
        it. The category must survive the summary round trip."""
        fs = _det(tmp_path, m="""
            import hashlib

            def collect(stats):
                out = []
                for k, v in stats.items():
                    out.append(f"{k}={v}")
                return out

            def digest(stats):
                h = hashlib.sha256()
                for row in collect(stats):
                    h.update(row.encode())
                return h.hexdigest()
            """)
        assert any(f.rule == "CL1001" and "digest" in f.message
                   for f in fs)

    def test_pragma_with_rationale_suppresses(self, tmp_path):
        fs = _det(tmp_path, m="""
            import hashlib

            def round_digest(votes):
                h = hashlib.sha256()
                for name, vote in votes.items():
                    # fixed field set; order never reaches the bytes
                    h.update(name.encode())  # consensus-lint: disable=CL1001
                return h.hexdigest()
            """)
        assert fs == []


# ------------------------------------------------------------- CL1002


class TestCompletionOrder:
    def test_as_completed_fold_into_digest_triggers(self, tmp_path):
        fs = _det(tmp_path, m="""
            import hashlib
            from concurrent.futures import as_completed

            def digest(futures):
                h = hashlib.sha256()
                for fut in as_completed(futures):
                    h.update(fut.result())
                return h.hexdigest()
            """)
        assert _rules(fs) == ["CL1002"]
        assert "as_completed" in fs[0].message

    def test_sequence_keyed_fold_is_clean(self, tmp_path):
        """The fix the rule text prescribes: collect by completion,
        fold by sequence key."""
        fs = _det(tmp_path, m="""
            import hashlib
            from concurrent.futures import as_completed

            def digest(futures):
                pairs = []
                for fut in as_completed(futures):
                    pairs.append((futures[fut], fut.result()))
                h = hashlib.sha256()
                for key, payload in sorted(pairs):
                    h.update(payload)
                return h.hexdigest()
            """)
        assert fs == []


# ------------------------------------------------------------- CL1003


class TestHostNondeterminism:
    def test_wallclock_into_journal_triggers(self, tmp_path):
        fs = _det(tmp_path, m="""
            import time

            def stamp(log, block):
                log.journal_block({"t": time.time(), "block": block})
            """)
        assert _rules(fs) == ["CL1003"]
        assert "time.time()" in fs[0].message

    def test_id_into_digest_triggers(self, tmp_path):
        fs = _det(tmp_path, m="""
            import hashlib

            def digest(obj):
                return hashlib.sha256(str(id(obj)).encode()).hexdigest()
            """)
        assert _rules(fs) == ["CL1003"]

    def test_seeded_rng_is_clean_unseeded_triggers(self, tmp_path):
        fs = _det(tmp_path, bad="""
            import numpy as np

            def record(ledger, result):
                rng = np.random.default_rng()
                ledger.record_round({"jitter": rng.random(), **result})
            """, ok="""
            import numpy as np

            def record(ledger, result, seed):
                rng = np.random.default_rng(seed)
                ledger.record_round({"jitter": rng.random(), **result})
            """)
        assert _rules(fs) == ["CL1003"]
        assert all(f.path.endswith("bad.py") for f in fs)


# ------------------------------------------------------------- CL1004


class TestFloatAccumulation:
    def test_augassign_fold_over_values_triggers(self, tmp_path):
        fs = _det(tmp_path, m="""
            def record(ledger, stakes, result):
                total = 0.0
                for s in stakes.values():
                    total += s
                ledger.record_round({"total": total, **result})
            """)
        assert _rules(fs) == ["CL1004"]
        assert "+=" in fs[0].message

    def test_sum_over_unordered_triggers_sorted_is_clean(self, tmp_path):
        fs = _det(tmp_path, bad="""
            def record(ledger, stakes, result):
                total = sum(stakes.values())
                ledger.record_round({"total": total, **result})
            """, ok="""
            def record(ledger, stakes, result):
                total = sum(sorted(stakes.values()))
                ledger.record_round({"total": total, **result})
            """)
        assert _rules(fs) == ["CL1004"]
        assert all(f.path.endswith("bad.py") for f in fs)


# ------------------------------------------------- registry + package


class TestLayerSurface:
    def test_rules_registered(self):
        assert set(DETERMINISM_RULES) == {"CL1001", "CL1002", "CL1003",
                                          "CL1004", "CL1005"}
        assert all(sev == "error"
                   for sev, _ in DETERMINISM_RULES.values())
        assert STATIC_DETERMINISM_RULES == \
            frozenset({"CL1001", "CL1002", "CL1003", "CL1004"})

    def test_package_is_clean(self):
        """The shipped baseline stays EMPTY: Layer 6 over the installed
        package — every real first-run finding was fixed (ledger aux
        sort, canonical wire encoding, sort_keys artifacts, sorted
        filesystem sweeps) or pragma'd with rationale in place."""
        fs = analyze_determinism()
        assert fs == [], [f.render() for f in fs]


# ------------------------------------------------------------- CL1005


class TestCompiledArtifact:
    SCATTER = ("  %sc.1 = f32[8]{0} scatter(f32[8]{0} %p, s32[2]{0} %i, "
               "f32[2]{0} %u), to_apply=%add")
    SELECT = ("  %ss.1 = f32[4]{0} select-and-scatter(f32[8]{0} %o, "
              "f32[4]{0} %s, f32[] %z), select=%ge, scatter=%add")
    REDUCE_SCATTER = ("  %rs.1 = f32[4]{0} reduce-scatter(f32[8]{0} %p), "
                      "replica_groups={{0,1}}, dimensions={0}")

    def test_scatter_family_flagged(self):
        hlo = "\n".join(["HloModule m", self.SCATTER, self.SELECT])
        hits = nondeterministic_ops(hlo)
        assert len(hits) == 2
        assert any("select-and-scatter" in h for h in hits)

    def test_reduce_scatter_is_not_in_the_family(self):
        """``reduce-scatter`` is a deterministic collective that merely
        contains the substring — the leading-space anchor excludes it."""
        assert nondeterministic_ops(
            "\n".join(["HloModule m", self.REDUCE_SCATTER])) == []

    def test_blessed_list_suppresses(self):
        hlo = "\n".join(["HloModule m", self.SCATTER])
        assert nondeterministic_ops(hlo, blessed=("scatter",)) == []
        assert nondeterministic_ops(hlo, blessed=("select-and-scatter",))

    def test_metadata_mention_ignored(self):
        line = ('  %c.1 = f32[8]{0} copy(f32[8]{0} %p), '
                'metadata={op_name="jit(f)/scatter(x)"}')
        assert nondeterministic_ops("\n".join(["HloModule m", line])) == []

    def test_first_divergence_names_the_line(self):
        msg = _first_divergence("a\nb\nc", "a\nX\nc")
        assert msg.startswith("line 2:") and "'b'" in msg and "'X'" in msg

    def test_stablehlo_pin_green_on_shipped_contract(self):
        """The dynamic half on a real shipped spec: serve_bucket traced
        twice must lower to byte-identical StableHLO."""
        specs = json.loads(
            (REPO / "pyconsensus_tpu" / "analysis" /
             "contracts.json").read_text())["contracts"]
        spec = next(s for s in specs
                    if s["name"] == "serve-bucket-stablehlo-pin")
        assert _builder_stablehlo_pin(spec) == []

    def test_unknown_entry_is_a_loud_cl300(self):
        fs = _builder_stablehlo_pin({"name": "x", "entry": "nope"})
        assert [f.rule for f in fs] == ["CL300"]


# ------------------------------------------------------------ witness


class TestDigestWitness:
    def _session(self, root, name="dw", n=6):
        from pyconsensus_tpu.serve.failover import DurableSession

        return DurableSession.create(root, name, n)

    def _run_rounds(self, w, root, rounds=2):
        rng = np.random.default_rng(0)
        s = self._session(root)
        for _ in range(rounds):
            s.append(rng.choice([0.0, 1.0], size=(6, 4)))
            s.append(rng.choice([0.0, 1.0], size=(6, 4)))
            s.resolve()
        return s

    def test_green_over_real_session_ops(self, tmp_path):
        """Real journal/commit/record traffic: every digest replays
        bit-identical from the durable artifacts at check()."""
        with digest_witnessed(
                dump_path=tmp_path / "dw.json") as w:
            self._run_rounds(w, tmp_path / "log")
        rep = w.check()
        ops = {r["op"] for r in rep["records"]}
        assert {"journal_block", "commit_round",
                "record_round"} <= ops
        assert rep["checked"] >= 3 and rep["recorded"] >= 6

    def test_tampered_commit_digest_is_flagged(self, tmp_path):
        """The divergence direction: corrupt ONE recorded history
        digest — check() must name the op and BOTH digests."""
        w = DigestWitness().install()
        try:
            self._run_rounds(w, tmp_path / "log")
        finally:
            w.uninstall()
        victim = next(r for r in reversed(w.records)
                      if r["op"] == "commit_round")
        real = victim["digests"][0]
        victim["digests"][0] = "0" * 64
        with pytest.raises(DeterminismWitnessViolation) as ei:
            w.check(dump_path=tmp_path / "viol.json")
        assert ei.value.op.startswith("commit_round[")
        assert ei.value.recorded == "0" * 64
        assert ei.value.replayed == real
        assert pathlib.Path(ei.value.dump_path).exists()

    def test_tampered_journal_digest_is_flagged(self, tmp_path):
        w = DigestWitness().install()
        try:
            rng = np.random.default_rng(1)
            s = self._session(tmp_path / "log")
            s.append(rng.choice([0.0, 1.0], size=(6, 4)))
            # no resolve: the staged block survives round GC
        finally:
            w.uninstall()
        victim = next(r for r in w.records
                      if r["op"] == "journal_block")
        victim["digest"] = "f" * 64
        with pytest.raises(DeterminismWitnessViolation) as ei:
            w.check(dump_path=tmp_path / "viol.json")
        assert ei.value.op.startswith("journal_block[")
        assert ei.value.recorded == "f" * 64

    def test_reordered_fold_mock_is_flagged_at_the_call_site(self):
        """The seeded mock of the acceptance criteria: an
        insertion-order-dependent mechanism_digest stand-in must raise
        AT THE CALL under the witness, naming both digests."""
        import hashlib

        def broken(final_reps):
            h = hashlib.sha256()
            for k, v in final_reps.items():   # the reordered fold
                h.update(f"{k}={v}".encode())
            return h.hexdigest()

        w = DigestWitness()
        wrapped = w._wrap_mechanism_digest(broken)
        with pytest.raises(DeterminismWitnessViolation) as ei:
            wrapped({"a": 1.0, "b": 2.0})
        assert ei.value.op == "mechanism_digest"
        assert ei.value.recorded != ei.value.replayed
        assert len(ei.value.recorded) == 64

    def test_real_mechanism_digest_is_order_invariant(self):
        from pyconsensus_tpu.econ import scoreboard

        with digest_witnessed() as w:
            d = scoreboard.mechanism_digest(
                {"m1": np.float64(0.25), "m0": np.float64(0.75)})
        assert len(d) == 64
        assert any(r["op"] == "mechanism_digest" for r in w.records)

    def test_torn_down_artifacts_are_skipped_not_flagged(self, tmp_path):
        """A test that removes its log dir (teardown, corruption tests)
        leaves unreplayable records — skipped, never a violation."""
        w = DigestWitness().install()
        try:
            self._run_rounds(w, tmp_path / "log")
        finally:
            w.uninstall()
        shutil.rmtree(tmp_path / "log")
        rep = w.check()
        assert rep["checked"] == 0 and rep["skipped"] >= 3

    def test_uninstall_restores_surfaces(self):
        from pyconsensus_tpu.econ import scoreboard
        from pyconsensus_tpu.serve.failover import ReplicationLog

        real_j = ReplicationLog.journal_block
        real_m = scoreboard.mechanism_digest
        w = DigestWitness().install()
        assert ReplicationLog.journal_block is not real_j
        assert scoreboard.mechanism_digest is not real_m
        w.uninstall()
        assert ReplicationLog.journal_block is real_j
        assert scoreboard.mechanism_digest is real_m

    def test_canonical_record_digest_is_key_order_free(self):
        a = {"round": 1, "certainty": 0.5}
        b = {"certainty": 0.5, "round": 1}
        assert _canonical_record_digest(a) == _canonical_record_digest(b)


# -------------------------------------- shuffled-directory replay


class TestShuffledDirectoryReplay:
    def test_replay_is_bit_identical_under_shuffled_readdir(
            self, tmp_path):
        """The satellite-3 regression: clone a live log by copying its
        files in a SHUFFLED creation order (perturbing readdir order,
        which tracks directory insertion history) — takeover replay and
        resolve must produce bit-identical outcomes and reputation."""
        from pyconsensus_tpu.serve.failover import (DurableSession,
                                                    replay_session)

        rng = np.random.default_rng(7)
        src = DurableSession.create(tmp_path / "a", "shuf", 6)
        src.append(rng.choice([0.0, 1.0], size=(6, 4)))
        src.append(rng.choice([0.0, 1.0], size=(6, 4)))
        src.resolve()
        src.append(rng.choice([0.0, 1.0], size=(6, 4)))

        files = sorted((tmp_path / "a" / "shuf").rglob("*"))
        order = np.random.default_rng(11).permutation(len(files))
        for i in order:
            f = files[int(i)]
            dst = tmp_path / "b" / "shuf" / f.relative_to(
                tmp_path / "a" / "shuf")
            dst.parent.mkdir(parents=True, exist_ok=True)
            if f.is_file():
                dst.write_bytes(f.read_bytes())

        twin = replay_session(tmp_path / "b", "shuf")
        block = rng.choice([0.0, 1.0], size=(6, 4))
        src.append(block.copy())
        twin.append(block.copy())
        got, want = twin.resolve(), src.resolve()
        np.testing.assert_array_equal(
            np.asarray(got["outcomes_adjusted"]),
            np.asarray(want["outcomes_adjusted"]))
        np.testing.assert_array_equal(
            np.asarray(got["smooth_rep"]),
            np.asarray(want["smooth_rep"]))


# ------------------------------------------------ lint-rule docs pin


class TestLintDocs:
    def _tool(self):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import check_lint_docs
        finally:
            sys.path.pop(0)
        return check_lint_docs

    def test_live_tree_in_sync(self):
        undocumented, unimplemented, sev_drift = self._tool().check()
        assert undocumented == [], undocumented
        assert unimplemented == [], unimplemented
        assert sev_drift == [], sev_drift
        assert len(self._tool().collect_implemented()) >= 30

    def test_detects_drift_directions(self, tmp_path):
        tool = self._tool()
        doc = tmp_path / "SA.md"
        doc.write_text(
            "| CL101 | warning | severity drifted |\n"
            "prose mentioning CL9998 which no table implements\n")
        mentioned, table_sev = tool.collect_documented(doc)
        implemented = tool.collect_implemented()
        assert "CL9998" in mentioned - set(implemented)
        assert table_sev["CL101"] == "warning"
        assert implemented["CL101"] == "error"   # i.e. drift detectable


# --------------------------------------------------- --format sarif


class TestSarifOutput:
    CORPUS = """
        import hashlib

        def round_digest(votes):
            h = hashlib.sha256()
            for name, vote in votes.items():
                h.update(f"{name}={vote}".encode())
            return h.hexdigest()
        """

    def _run(self, args):
        buf = io.StringIO()
        code = cli_run(args, stdout=buf)
        return code, buf.getvalue()

    def test_schema_and_exit_code(self, tmp_path):
        target = tmp_path / "m.py"
        target.write_text(textwrap.dedent(self.CORPUS))
        code, out = self._run(["--format", "sarif", "--no-baseline",
                               "--select", "CL1001", str(target)])
        assert code == 1
        doc = json.loads(out)
        assert doc["version"] == "2.1.0"
        assert "sarif" in doc["$schema"]
        run = doc["runs"][0]
        driver = run["tool"]["driver"]
        assert driver["name"] == "consensus-lint"
        rule_ids = [r["id"] for r in driver["rules"]]
        assert rule_ids == ["CL1001"]
        (res,) = run["results"]
        assert res["ruleId"] == "CL1001"
        assert rule_ids[res["ruleIndex"]] == "CL1001"
        assert res["level"] == "error"
        assert res["baselineState"] == "new"
        assert "consensusLint/v1" in res["partialFingerprints"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("m.py")
        assert loc["region"]["startLine"] >= 1
        assert "unordered-iteration" in res["message"]["text"]

    def test_baselined_state_and_exit_zero(self, tmp_path):
        target = tmp_path / "m.py"
        target.write_text(textwrap.dedent(self.CORPUS))
        baseline = tmp_path / "baseline.json"
        code, _ = self._run(["--update-baseline", "--baseline",
                             str(baseline), "--select", "CL1001",
                             str(target)])
        assert code == 0
        code, out = self._run(["--format", "sarif", "--baseline",
                               str(baseline), "--select", "CL1001",
                               str(target)])
        assert code == 0
        (res,) = json.loads(out)["runs"][0]["results"]
        assert res["baselineState"] == "unchanged"

    def test_clean_tree_empty_results(self, tmp_path):
        target = tmp_path / "m.py"
        target.write_text("def ok():\n    return 1\n")
        code, out = self._run(["--format", "sarif", "--no-baseline",
                               str(target)])
        assert code == 0
        run = json.loads(out)["runs"][0]
        assert run["results"] == [] and run["tool"]["driver"]["rules"] == []

    def test_no_determinism_excludes_layer6(self, tmp_path):
        """The opt-out: the same corpus under --no-determinism exits 0
        with zero findings (CL1005 contract findings are filtered the
        same way — exercised by the cli preserve/in_scope paths)."""
        target = tmp_path / "m.py"
        target.write_text(textwrap.dedent(self.CORPUS))
        code, out = self._run(["--format", "json", "--no-baseline",
                               "--no-determinism", str(target)])
        assert code == 0
        assert json.loads(out)["findings"] == []
