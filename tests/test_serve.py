"""pyconsensus_tpu.serve — micro-batching consensus service (ISSUE 5).

Covers the padded bucket kernel's equivalence contract (snapped
outcomes bit-identical to direct Oracle resolution across every bucket
of the ladder, both backends, binary + scaled; continuous tails within
the documented 1e-9 band; full determinism across batch compositions),
the queue/admission overload semantics (deterministic PYC401 shedding),
the executable cache (LRU, hit/miss metrics, warmup-pinned retraces),
market sessions (incremental statistics bit-identical to the streaming
driver over the same block split), and the fault sites.
"""

import threading

import numpy as np
import pytest

from conftest import collusion_reports
from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.faults import (ERROR_CODES, ConsensusError,
                                    ServiceOverloadError)
from pyconsensus_tpu.serve import (BucketKey, ConsensusService,
                                   LoadGenerator, RequestQueue,
                                   ResolveRequest, ServeConfig,
                                   bucket_path_eligible)

#: the continuous-tail band vs direct resolution (docs/SERVING.md —
#: XLA reduce tilings are shape/fusion-dependent, so only the snapped
#: outcomes are bitwise across compiled graphs; measured <= 3e-10)
SERVE_ATOL = 1e-9


@pytest.fixture(autouse=True)
def _under_lock_witness(lock_witness):
    """Every serve test runs under the runtime lock witness (ISSUE 9):
    batcher/queue/cache/admission/session lock acquisitions are
    recorded and the observed order checked against the static CL801
    graph at teardown."""
    yield

#: result-field accessors compared against direct Oracle resolutions
_EXACT_KEYS = (("events", "outcomes_final"), ("events", "outcomes_adjusted"))
_BAND_KEYS = (("agents", "smooth_rep"), ("agents", "this_rep"),
              ("agents", "reporter_bonus"), ("agents", "relative_part"),
              ("agents", "participation_rows"),
              ("events", "outcomes_raw"), ("events", "certainty"),
              ("events", "consensus_reward"), ("events", "author_bonus"),
              ("events", "participation_columns"))


def _get(result, path):
    section, key = path
    return np.asarray(result[section][key])


def serve_one(reports, bounds=None, cfg=None, backend="jax", **kw):
    with ConsensusService(cfg or ServeConfig()) as svc:
        return svc.submit(reports=reports, event_bounds=bounds,
                          backend=backend, **kw).result(timeout=120)


def assert_serve_parity(got, ref):
    for path in _EXACT_KEYS:
        np.testing.assert_array_equal(_get(got, path), _get(ref, path),
                                      err_msg=str(path))
    assert got["iterations"] == ref["iterations"]
    assert got["convergence"] == ref["convergence"]
    for path in _BAND_KEYS:
        np.testing.assert_allclose(_get(got, path), _get(ref, path),
                                   atol=SERVE_ATOL, rtol=0,
                                   err_msg=str(path))
    assert got["certainty"] == pytest.approx(ref["certainty"],
                                             abs=SERVE_ATOL)
    assert got["participation"] == pytest.approx(ref["participation"],
                                                 abs=SERVE_ATOL)


def scaled_fixture(rng, R, E, n_scaled):
    reports, _ = collusion_reports(rng, R, E, liars=max(2, R // 4),
                                   na_frac=0.12)
    cols = rng.choice(E, n_scaled, replace=False)
    bounds = [None] * E
    for c in cols:
        bounds[c] = {"scaled": True, "min": -5.0, "max": 15.0}
        with np.errstate(invalid="ignore"):
            reports[:, c] = reports[:, c] * 20.0 - 5.0
    return reports, bounds


class TestPaddingEquivalence:
    """The satellite property test: a request resolved through EVERY
    bucket size yields the same answers as a direct Oracle call."""

    #: ladders forcing four different buckets around a 13 x 52 request
    BUCKETS = [(13, 52), (16, 64), (32, 128), (64, 256)]

    def _cfg(self, rb, eb):
        return ServeConfig(row_buckets=(rb,), event_buckets=(eb,),
                           batch_window_ms=0.0)

    @pytest.mark.parametrize("bucket", BUCKETS)
    def test_binary_na_every_bucket(self, rng, bucket):
        reports, _ = collusion_reports(rng, 13, 52, liars=4, na_frac=0.15)
        ref = Oracle(reports=reports, backend="jax",
                     pca_method="power").consensus()
        got = serve_one(reports, cfg=self._cfg(*bucket))
        assert_serve_parity(got, ref)

    @pytest.mark.parametrize("bucket", BUCKETS)
    def test_scaled_every_bucket(self, rng, bucket):
        reports, bounds = scaled_fixture(rng, 13, 52, n_scaled=6)
        ref = Oracle(reports=reports, event_bounds=bounds, backend="jax",
                     pca_method="power").consensus()
        got = serve_one(reports, bounds, cfg=self._cfg(*bucket))
        assert_serve_parity(got, ref)

    @pytest.mark.parametrize("trial", range(4))
    def test_property_random_shapes(self, trial):
        """Random shapes/NA/iterations through the default ladder."""
        rng = np.random.default_rng(4200 + trial)
        R = int(rng.integers(5, 40))
        E = int(rng.integers(8, 130))
        na = float(rng.uniform(0.0, 0.3))
        it = int(rng.integers(1, 5))
        reports, _ = collusion_reports(rng, R, E, liars=max(2, R // 4),
                                       na_frac=na)
        ref = Oracle(reports=reports, backend="jax", pca_method="power",
                     max_iterations=it).consensus()
        got = serve_one(reports, max_iterations=it)
        assert_serve_parity(got, ref)

    def test_numpy_backend_bit_identical(self, rng):
        """The numpy path dispatches the Oracle graph directly — FULL
        bit-identity, both value and aggregate."""
        reports, _ = collusion_reports(rng, 11, 30, liars=3, na_frac=0.2)
        ref = Oracle(reports=reports, backend="numpy").consensus()
        got = serve_one(reports, backend="numpy")
        for path in _EXACT_KEYS + _BAND_KEYS:
            np.testing.assert_array_equal(_get(got, path),
                                          _get(ref, path),
                                          err_msg=str(path))
        assert got["certainty"] == ref["certainty"]

    def test_direct_path_bit_identical(self, rng):
        """A bucket-ineligible algorithm rides the direct path — the
        Oracle graph itself, bit-identical."""
        reports, _ = collusion_reports(rng, 10, 24, liars=3, na_frac=0.1)
        ref = Oracle(reports=reports, backend="jax",
                     algorithm="k-means").consensus()
        got = serve_one(reports, algorithm="k-means")
        np.testing.assert_array_equal(
            _get(got, ("events", "outcomes_final")),
            _get(ref, ("events", "outcomes_final")))
        np.testing.assert_array_equal(_get(got, ("agents", "smooth_rep")),
                                      _get(ref, ("agents", "smooth_rep")))

    def test_quarantine_matches_oracle(self, rng):
        """±Inf rows quarantine at the serve front door exactly like the
        Oracle front door."""
        reports, _ = collusion_reports(rng, 12, 32, liars=3, na_frac=0.1)
        reports[4, 7] = np.inf
        ref = Oracle(reports=reports, backend="jax",
                     pca_method="power").consensus()
        got = serve_one(reports)
        np.testing.assert_array_equal(got["quarantined_rows"],
                                      ref["quarantined_rows"])
        assert_serve_parity(got, ref)


class TestDeterminism:
    """A request's bits never depend on traffic shape or co-batched
    requests (the fixed-capacity executable contract)."""

    def test_same_bits_across_batch_compositions(self, rng):
        reports, _ = collusion_reports(rng, 12, 48, liars=4, na_frac=0.1)
        others = [collusion_reports(np.random.default_rng(50 + i), 12, 48,
                                    liars=4, na_frac=0.1)[0]
                  for i in range(5)]
        cfg = ServeConfig(batch_window_ms=20.0, max_batch=8)
        outs = []
        # solo dispatch
        outs.append(serve_one(reports, cfg=cfg))
        # co-batched with 5 other requests (one dispatch window)
        with ConsensusService(cfg) as svc:
            futs = [svc.submit(reports=m) for m in [reports] + others]
            outs.append(futs[0].result(timeout=120))
        # repeated dispatch in a fresh service
        outs.append(serve_one(reports, cfg=cfg))
        first = outs[0]
        for other in outs[1:]:
            for path in _EXACT_KEYS + _BAND_KEYS:
                np.testing.assert_array_equal(_get(first, path),
                                              _get(other, path),
                                              err_msg=str(path))
            assert other["certainty"] == first["certainty"]

    def test_concurrent_clients_get_their_own_results(self, rng):
        """N interleaved clients, distinct matrices — each future must
        carry ITS request's resolution (lane-routing correctness)."""
        N = 12
        matrices = []
        for i in range(N):
            r = np.random.default_rng(900 + i)
            m, _ = collusion_reports(r, 10 + (i % 3), 40 + 4 * (i % 4),
                                     liars=3, na_frac=0.1)
            matrices.append(m)
        refs = [Oracle(reports=m, backend="jax",
                       pca_method="power").consensus() for m in matrices]
        cfg = ServeConfig(batch_window_ms=5.0)
        with ConsensusService(cfg) as svc:
            futs = [None] * N

            def client(i):
                futs[i] = svc.submit(reports=matrices[i])

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(N)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            results = [f.result(timeout=120) for f in futs]
        for got, ref in zip(results, refs):
            assert_serve_parity(got, ref)


class TestAdmission:
    def test_error_taxonomy(self):
        assert ServiceOverloadError.error_code == "PYC401"
        assert ERROR_CODES["PYC401"] is ServiceOverloadError
        assert issubclass(ServiceOverloadError, ConsensusError)
        assert issubclass(ServiceOverloadError, RuntimeError)

    def test_queue_full_is_deterministic(self):
        q = RequestQueue(max_depth=2)
        q.put(ResolveRequest(reports=np.zeros((2, 2))))
        q.put(ResolveRequest(reports=np.zeros((2, 2))))
        with pytest.raises(ServiceOverloadError) as e:
            q.put(ResolveRequest(reports=np.zeros((2, 2))))
        assert e.value.context["reason"] == "queue_full"
        assert e.value.error_code == "PYC401"

    def test_rate_limit_sheds_over_rate_traffic(self, rng):
        reports, _ = collusion_reports(rng, 8, 24, liars=2, na_frac=0.0)
        cfg = ServeConfig(rate_limit_rps=1e-3, rate_burst=2.0)
        with ConsensusService(cfg) as svc:
            svc.submit(reports=reports).result(timeout=120)
            svc.submit(reports=reports).result(timeout=120)
            with pytest.raises(ServiceOverloadError) as e:
                svc.submit(reports=reports)
        assert e.value.context["reason"] == "rate_limited"
        assert e.value.context["retry_after_s"] > 0

    def test_deadline_shed_not_hang(self, rng):
        """An expired request is shed with PYC401, never served late and
        never hung."""
        reports, _ = collusion_reports(rng, 8, 24, liars=2, na_frac=0.0)
        with ConsensusService(ServeConfig()) as svc:
            fut = svc.submit(reports=reports, deadline_ms=1e-6)
            with pytest.raises(ServiceOverloadError) as e:
                fut.result(timeout=60)
        assert e.value.context["reason"] == "deadline"

    def test_drain_finishes_queued_then_refuses(self, rng):
        reports, _ = collusion_reports(rng, 8, 24, liars=2, na_frac=0.0)
        svc = ConsensusService(ServeConfig()).start()
        futs = [svc.submit(reports=reports) for _ in range(4)]
        svc.close(drain=True)
        for f in futs:
            assert f.result(timeout=60)["convergence"] in (True, False)
        with pytest.raises(ServiceOverloadError) as e:
            svc.submit(reports=reports)
        assert e.value.context["reason"] == "draining"

    def test_validation_errors_are_synchronous(self):
        svc = ConsensusService(ServeConfig())
        with pytest.raises(ValueError):
            svc.submit(reports=np.zeros((0, 3)))
        with pytest.raises(ValueError):
            svc.submit()
        with pytest.raises(ValueError):
            svc.submit(reports=np.zeros((2, 2)), session="x")


class TestCacheAndWarmup:
    def test_warmup_pins_retraces_at_bucket_count(self, rng):
        obs.reset()
        cfg = ServeConfig(warmup=((16, 64), (32, 128)),
                          batch_window_ms=1.0)
        reports, _ = collusion_reports(rng, 12, 48, liars=4, na_frac=0.1)
        big, _ = collusion_reports(rng, 24, 100, liars=6, na_frac=0.1)
        with ConsensusService(cfg) as svc:
            for _ in range(3):
                svc.submit(reports=reports).result(timeout=120)
                svc.submit(reports=big).result(timeout=120)
            assert len(svc.cache) == 2
        assert obs.value("pyconsensus_jit_retraces_total",
                         entry="serve_bucket") == 2
        assert obs.value("pyconsensus_serve_cache_misses_total") == 2
        assert obs.value("pyconsensus_serve_cache_hits_total") >= 6

    def test_lru_eviction(self, rng):
        cfg = ServeConfig(cache_capacity=1, batch_window_ms=0.0)
        small, _ = collusion_reports(rng, 6, 12, liars=2, na_frac=0.1)
        wide, _ = collusion_reports(rng, 6, 20, liars=2, na_frac=0.1)
        before = obs.value("pyconsensus_serve_cache_evictions_total") or 0
        with ConsensusService(cfg) as svc:
            svc.submit(reports=small).result(timeout=120)
            svc.submit(reports=wide).result(timeout=120)
            assert len(svc.cache) == 1
        after = obs.value("pyconsensus_serve_cache_evictions_total")
        assert after - before >= 1

    def test_bucket_key_fields(self):
        from pyconsensus_tpu.models.pipeline import ConsensusParams

        p = ConsensusParams(algorithm="sztorc", pca_method="power")
        key = BucketKey.make(16, 64, 8, p)
        assert (key.rows, key.events, key.batch) == (16, 64, 8)
        assert key.params is p
        assert key == BucketKey.make(16, 64, 8, p)


class TestRouting:
    def test_eligibility_rule(self):
        assert bucket_path_eligible("sztorc", "power", False, True, "")
        assert bucket_path_eligible("sztorc", "auto", True, True,
                                    "bfloat16")
        assert not bucket_path_eligible("ica", "power", False, True, "")
        assert not bucket_path_eligible("sztorc", "eigh-gram", False,
                                        True, "")
        assert not bucket_path_eligible("sztorc", "power", False, True,
                                        "int8")

    def test_oversize_request_takes_direct_path(self, rng):
        """A shape beyond the ladders still resolves (direct path)."""
        cfg = ServeConfig(row_buckets=(8,), event_buckets=(16,),
                          batch_window_ms=0.0)
        reports, _ = collusion_reports(rng, 12, 40, liars=3, na_frac=0.1)
        ref = Oracle(reports=reports, backend="jax").consensus()
        got = serve_one(reports, cfg=cfg)
        np.testing.assert_array_equal(
            _get(got, ("events", "outcomes_final")),
            _get(ref, ("events", "outcomes_final")))

    def test_coalescing_is_measurably_active(self, rng):
        """The acceptance demo: concurrent same-bucket traffic must
        coalesce (mean occupancy > 1)."""
        obs.reset()
        reports, _ = collusion_reports(rng, 12, 48, liars=4, na_frac=0.1)
        cfg = ServeConfig(warmup=((16, 64),), batch_window_ms=10.0)
        with ConsensusService(cfg) as svc:
            futs = [svc.submit(reports=reports) for _ in range(8)]
            for f in futs:
                f.result(timeout=120)
        snap = obs.REGISTRY.snapshot()[
            "pyconsensus_serve_batch_occupancy"]["series"]
        ser = next(iter(snap.values()))
        assert ser["sum"] / ser["count"] > 1.0


class TestSessions:
    def test_incremental_matches_streaming_driver(self, rng):
        """append-accumulated statistics resolve bit-identically to
        streaming_consensus over the same panel split."""
        from pyconsensus_tpu.models.pipeline import ConsensusParams
        from pyconsensus_tpu.parallel import streaming_consensus

        R, width, blocks = 14, 16, 3
        full = np.concatenate(
            [collusion_reports(rng, R, width, liars=4, na_frac=0.1)[0]
             for _ in range(blocks)], axis=1)
        stream = streaming_consensus(
            full, panel_events=width,
            params=ConsensusParams(algorithm="sztorc", max_iterations=1))
        svc = ConsensusService(ServeConfig())
        svc.create_session("m1", n_reporters=R)
        for b in range(blocks):
            svc.append("m1", full[:, b * width:(b + 1) * width])
        got = svc.submit(session="m1").result(timeout=120)
        svc.close(drain=True)
        np.testing.assert_array_equal(
            _get(got, ("events", "outcomes_final")),
            stream["outcomes_final"])
        np.testing.assert_array_equal(_get(got, ("agents", "smooth_rep")),
                                      stream["smooth_rep"])
        np.testing.assert_array_equal(
            _get(got, ("events", "certainty")), stream["certainty"])
        np.testing.assert_array_equal(
            _get(got, ("agents", "reporter_bonus")),
            stream["reporter_bonus"])

    def test_outcomes_match_oracle(self, rng):
        from pyconsensus_tpu.serve import MarketSession

        R = 12
        b1, _ = collusion_reports(rng, R, 10, liars=3, na_frac=0.1)
        b2, _ = collusion_reports(rng, R, 14, liars=3, na_frac=0.1)
        session = MarketSession("m", n_reporters=R)
        session.append(b1)
        session.append(b2)
        flat = session.resolve()
        ref = Oracle(reports=np.concatenate([b1, b2], axis=1),
                     backend="jax").consensus()
        np.testing.assert_array_equal(flat["outcomes_adjusted"],
                                      _get(ref, ("events",
                                                 "outcomes_adjusted")))

    def test_reputation_carries_and_round_closes(self, rng):
        from pyconsensus_tpu.serve import MarketSession

        R = 10
        session = MarketSession("m", n_reporters=R)
        b1, _ = collusion_reports(rng, R, 12, liars=3, na_frac=0.0)
        session.append(b1)
        r1 = session.resolve()
        np.testing.assert_array_equal(session.reputation,
                                      r1["smooth_rep"])
        assert session.n_events == 0          # round closed
        with pytest.raises(ValueError):
            session.resolve()                 # nothing staged
        b2, _ = collusion_reports(rng, R, 12, liars=3, na_frac=0.0)
        session.append(b2)
        r2 = session.resolve()
        # round 2 resolved against the carried reputation
        ref2 = Oracle(reports=b2, reputation=r1["smooth_rep"],
                      backend="jax").consensus()
        np.testing.assert_array_equal(r2["outcomes_adjusted"],
                                      _get(ref2, ("events",
                                                  "outcomes_adjusted")))

    def test_ledger_integration(self, rng, tmp_path):
        from pyconsensus_tpu.ledger import ReputationLedger
        from pyconsensus_tpu.serve import MarketSession

        R = 8
        ledger = ReputationLedger(n_reporters=R)
        session = MarketSession("m", n_reporters=R, ledger=ledger)
        b, _ = collusion_reports(rng, R, 10, liars=2, na_frac=0.1)
        session.append(b)
        session.resolve()
        assert ledger.round == 1
        assert len(ledger.history) == 1
        np.testing.assert_array_equal(ledger.reputation,
                                      session.reputation)
        # checkpoint round-trips the carried state
        ledger.save(tmp_path / "state.npz")
        resumed = ReputationLedger.load(tmp_path / "state.npz")
        np.testing.assert_array_equal(resumed.reputation,
                                      ledger.reputation)
        assert resumed.round == 1

    def test_scaled_blocks(self, rng):
        from pyconsensus_tpu.serve import MarketSession

        R = 12
        block, bounds = scaled_fixture(rng, R, 16, n_scaled=4)
        session = MarketSession("m", n_reporters=R)
        session.append(block, event_bounds=bounds)
        flat = session.resolve()
        ref = Oracle(reports=block, event_bounds=bounds,
                     backend="jax").consensus()
        np.testing.assert_array_equal(
            flat["outcomes_adjusted"][np.asarray(
                [b is None for b in bounds])],
            _get(ref, ("events", "outcomes_adjusted"))[np.asarray(
                [b is None for b in bounds])])

    def test_shape_validation(self):
        from pyconsensus_tpu.serve import MarketSession

        session = MarketSession("m", n_reporters=6)
        with pytest.raises(ValueError):
            session.append(np.zeros((5, 3)))

    def test_direct_fallback_for_iterated_resolve(self, rng):
        """A non-default configuration (max_iterations > 1) assembles
        the staged panel and resolves through Oracle — same carried
        reputation, full algorithm table."""
        from pyconsensus_tpu.serve import MarketSession

        R = 10
        b1, _ = collusion_reports(rng, R, 8, liars=3, na_frac=0.1)
        b2, _ = collusion_reports(rng, R, 8, liars=3, na_frac=0.1)
        session = MarketSession("m", n_reporters=R)
        session.append(b1)
        session.append(b2)
        flat = session.resolve(max_iterations=3)
        ref = Oracle(reports=np.concatenate([b1, b2], axis=1),
                     backend="jax", max_iterations=3).consensus()
        np.testing.assert_array_equal(flat["smooth_rep"],
                                      _get(ref, ("agents", "smooth_rep")))
        assert flat["iterations"] == ref["iterations"]
        np.testing.assert_array_equal(session.reputation,
                                      flat["smooth_rep"])


class TestFaultSites:
    def test_enqueue_site(self, rng):
        from pyconsensus_tpu import faults

        reports, _ = collusion_reports(rng, 8, 16, liars=2, na_frac=0.0)
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "serve.enqueue", "kind": "raise",
             "occurrences": [0]}])
        svc = ConsensusService(ServeConfig())
        with faults.armed(plan):
            with pytest.raises(OSError):
                svc.submit(reports=reports)
        assert plan.fired == [("serve.enqueue", 0, "raise")]

    def test_dispatch_site(self, rng):
        from pyconsensus_tpu import faults

        reports, _ = collusion_reports(rng, 8, 16, liars=2, na_frac=0.0)
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "serve.dispatch", "kind": "raise",
             "occurrences": [0]}])
        with ConsensusService(ServeConfig()) as svc:
            with faults.armed(plan):
                fut = svc.submit(reports=reports)
                with pytest.raises(OSError):
                    fut.result(timeout=60)

    def test_group_failure_resolves_every_future(self, rng):
        """A dispatch failure must surface on EVERY coalesced future —
        never leave group members hanging to their timeouts."""
        from pyconsensus_tpu import faults

        reports, _ = collusion_reports(rng, 8, 16, liars=2, na_frac=0.0)
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "serve.dispatch", "kind": "raise",
             "occurrences": [0]}])
        cfg = ServeConfig(batch_window_ms=30.0)
        with ConsensusService(cfg) as svc:
            with faults.armed(plan):
                futs = [svc.submit(reports=reports) for _ in range(4)]
                outcomes = []
                for f in futs:
                    try:
                        f.result(timeout=30)
                        outcomes.append("ok")
                    except OSError:
                        outcomes.append("err")
        # every coalesced member of the failed dispatch resolved with
        # the error; none hung (the result(timeout=30) would have
        # raised TimeoutError instead of OSError)
        assert outcomes.count("err") >= 1
        assert set(outcomes) <= {"ok", "err"}

    def test_session_append_corruption(self, rng):
        from pyconsensus_tpu import faults
        from pyconsensus_tpu.serve import MarketSession

        plan = faults.FaultPlan(seed=3, rules=[
            {"site": "serve.session_append", "kind": "nan_storm",
             "occurrences": [0], "args": {"fraction": 0.5}}])
        session = MarketSession("m", n_reporters=8)
        block = np.ones((8, 6))
        with faults.armed(plan):
            session.append(block)
        # the staged block was poisoned, the caller's array untouched
        # (read through the staging decode: ISSUE 13 stages lattice-
        # exact blocks as device-resident int8 sentinel arrays)
        staged = MarketSession._staged_host(session._blocks[0])
        assert np.isnan(staged).any()
        assert not np.isnan(block).any()


class TestLoadgen:
    def test_closed_loop_demo(self, rng):
        """The acceptance demo: >= 8 concurrent clients, zero failures,
        coalescing active, retraces pinned at warmed bucket count."""
        obs.reset()
        cfg = ServeConfig(warmup=((16, 64), (32, 128)),
                          batch_window_ms=3.0)
        with ConsensusService(cfg) as svc:
            gen = LoadGenerator(svc, shapes=((12, 48), (24, 100)),
                                na_frac=0.1, seed=5)
            stats = gen.run_closed(n_requests=40, concurrency=8)
        assert stats["failed"] == 0
        assert stats["succeeded"] == 40
        assert stats["throughput_rps"] > 0
        assert stats["latency_p99_ms"] >= stats["latency_p50_ms"]
        snap = obs.REGISTRY.snapshot()[
            "pyconsensus_serve_batch_occupancy"]["series"]
        ser = next(iter(snap.values()))
        assert ser["sum"] / ser["count"] > 1.0
        assert obs.value("pyconsensus_jit_retraces_total",
                         entry="serve_bucket") == 2

    def test_open_loop_sheds_deterministically(self, rng):
        """Over-rate open-loop traffic: every failure is a PYC401 —
        never a hang, never an unclassified error."""
        cfg = ServeConfig(rate_limit_rps=5.0, rate_burst=3.0,
                          batch_window_ms=0.0)
        with ConsensusService(cfg) as svc:
            gen = LoadGenerator(svc, shapes=((8, 24),), na_frac=0.0,
                                seed=2)
            stats = gen.run_open(n_requests=30, rate_rps=400.0)
        assert stats["failed"] > 0
        assert set(stats["errors"]) == {"PYC401"}
        assert stats["succeeded"] + stats["failed"] == 30


class TestServeConfig:
    def test_json_round_trip(self, tmp_path):
        import json

        path = tmp_path / "serve.json"
        path.write_text(json.dumps({
            "row_buckets": [8, 32], "event_buckets": [64],
            "max_batch": 4, "rate_limit_rps": 10.0,
            "warmup": [[8, 64]]}))
        cfg = ServeConfig.load(path)
        assert cfg.row_buckets == (8, 32)
        assert cfg.warmup == ((8, 64),)
        assert cfg.max_batch == 4

    def test_unknown_key_rejected(self, tmp_path):
        import json

        path = tmp_path / "serve.json"
        path.write_text(json.dumps({"no_such_knob": 1}))
        with pytest.raises(ValueError, match="no_such_knob"):
            ServeConfig.load(path)

    def test_unsorted_ladder_rejected(self):
        with pytest.raises(ValueError, match="ascending"):
            ConsensusService(ServeConfig(row_buckets=(32, 8)))
