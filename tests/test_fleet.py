"""Replicated serve fleet (ISSUE 8): consistent-hash placement,
ledger-backed hot-standby failover, fleet admission, client retry, and
kill-a-worker-mid-traffic chaos.

The contract under test, end to end: any worker can die mid-traffic and
every accepted request either resolves with bits identical to a
single-box run, or sheds with a structured PYC-coded error carrying an
honest ``retry_after_s`` — never a silent drop, never corrupted state.
"""

import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from conftest import worker_env
from fleet_worker import BLOCKS_PER_ROUND, N_REPORTERS, make_block
from pyconsensus_tpu import Oracle, ReputationLedger, obs
from pyconsensus_tpu import faults
from pyconsensus_tpu.faults import (ERROR_CODES, CheckpointCorruptionError,
                                    FailoverInProgressError, InputError,
                                    PlacementError, ServiceOverloadError,
                                    WorkerLostError)
from pyconsensus_tpu.serve import (ConsensusFleet, DurableSession,
                                   FleetConfig, HashRing, MarketSession,
                                   ReplicationLog, ServeConfig,
                                   replay_session)
from pyconsensus_tpu.serve.admission import ClusterCapacity
from pyconsensus_tpu.serve.loadgen import (RETRYABLE_CODES, LoadGenerator,
                                           summarize)
from pyconsensus_tpu.serve.queue import ResolveRequest


@pytest.fixture(autouse=True)
def _under_lock_witness(lock_witness):
    """Every fleet test runs under the runtime lock witness (ISSUE 9):
    the observed acquisition order across router/heartbeat/takeover/
    session locks must stay acyclic and consistent with the static
    CL801 graph, or the test fails with the witness JSON dumped."""
    yield


@pytest.fixture(autouse=True)
def _under_protocol_witness(protocol_witness):
    """And under the runtime protocol witness (ISSUE 16): every
    durable-session operation's observed journal/commit/ship/ack order
    must be consistent with the static CL901 happens-before graph."""
    yield


@pytest.fixture(autouse=True)
def _under_digest_witness(digest_witness):
    """And under the runtime digest witness (ISSUE 17): every digest a
    fleet test journals or records must replay bit-identical from the
    durable artifact — the dynamic mirror of Layer 6's bit-determinism
    proof."""
    yield


def small_fleet(tmp_path, n=3, **cfg_kwargs):
    cfg = FleetConfig(
        n_workers=n, log_dir=str(tmp_path / "log"),
        worker=ServeConfig(warmup=(), batch_window_ms=1.0),
        **cfg_kwargs)
    return ConsensusFleet(cfg)


def flat_bits(result):
    """The bit-identity tuple of a flat light result dict."""
    return (np.asarray(result["smooth_rep"]),
            np.asarray(result["outcomes_final"]),
            np.asarray(result["outcomes_adjusted"]),
            int(np.asarray(result["iterations"])),
            np.asarray(result["old_rep"]),
            np.asarray(result["avg_certainty"]))


def assert_same_bits(got, ref, msg=""):
    for a, b in zip(flat_bits(got), flat_bits(ref)):
        np.testing.assert_array_equal(a, b, err_msg=msg)


# -- consistent-hash placement ---------------------------------------------


class TestHashRing:
    KEYS = [f"session-{i}" for i in range(240)]

    def test_deterministic_across_instances(self):
        a = HashRing(["w0", "w1", "w2"])
        b = HashRing(["w2", "w0", "w1"])     # insertion order irrelevant
        assert [a.owner(k) for k in self.KEYS] == \
               [b.owner(k) for k in self.KEYS]

    def test_removal_moves_only_the_dead_workers_keys(self):
        ring = HashRing(["w0", "w1", "w2"])
        before = {k: ring.owner(k) for k in self.KEYS}
        moved = ring.moved_keys(self.KEYS, "w1")
        assert moved == [k for k, o in before.items() if o == "w1"]
        ring.remove("w1")
        after = {k: ring.owner(k) for k in self.KEYS}
        for k in self.KEYS:
            if before[k] != "w1":
                assert after[k] == before[k], k     # stability
            else:
                assert after[k] != "w1"             # redistributed
        assert any(before[k] == "w1" for k in self.KEYS)

    def test_add_back_restores_placement(self):
        ring = HashRing(["w0", "w1", "w2"])
        before = {k: ring.owner(k) for k in self.KEYS}
        ring.remove("w1")
        ring.add("w1")
        assert {k: ring.owner(k) for k in self.KEYS} == before

    def test_distribution_is_roughly_balanced(self):
        ring = HashRing(["w0", "w1", "w2"])
        owners = [ring.owner(k) for k in self.KEYS]
        for w in ("w0", "w1", "w2"):
            assert owners.count(w) >= len(self.KEYS) * 0.15, w

    def test_empty_ring_raises_placement_error(self):
        ring = HashRing()
        with pytest.raises(PlacementError) as ei:
            ring.owner("anything")
        assert ei.value.error_code == "PYC503"
        with pytest.raises(PlacementError):
            ring.preference("anything")

    def test_preference_owner_first_distinct(self):
        ring = HashRing(["w0", "w1", "w2"])
        for k in self.KEYS[:40]:
            pref = ring.preference(k)
            assert pref[0] == ring.owner(k)
            assert sorted(pref) == ["w0", "w1", "w2"]

    def test_remove_unknown_is_noop(self):
        ring = HashRing(["w0"])
        ring.remove("nope")
        assert ring.owner("k") == "w0"

    def test_bad_vnodes_rejected(self):
        with pytest.raises(PlacementError):
            HashRing(vnodes=0)


# -- PYC5xx taxonomy -------------------------------------------------------


class TestFleetTaxonomy:
    def test_codes_registered_and_stable(self):
        assert ERROR_CODES["PYC501"] is WorkerLostError
        assert ERROR_CODES["PYC502"] is FailoverInProgressError
        assert ERROR_CODES["PYC503"] is PlacementError

    @pytest.mark.parametrize("cls", [WorkerLostError,
                                     FailoverInProgressError,
                                     PlacementError])
    def test_double_inheritance_and_context(self, cls):
        exc = cls("boom", retry_after_s=0.5, worker="w1")
        assert isinstance(exc, RuntimeError)
        assert exc.context["worker"] == "w1"
        assert exc.error_code in str(exc)


# -- ledger.verify() (takeover preflight) ----------------------------------


class TestLedgerVerify:
    def _saved(self, tmp_path, rounds=2):
        ledger = ReputationLedger(n_reporters=6, max_iterations=2)
        rng = np.random.default_rng(3)
        for _ in range(rounds):
            ledger.resolve(rng.choice([0.0, 1.0], size=(6, 5)))
        path = tmp_path / "state.npz"
        ledger.save(path)
        return ledger, path

    def test_verify_summary_without_construction(self, tmp_path):
        ledger, path = self._saved(tmp_path)
        raw = path.read_bytes()
        summary = ReputationLedger.verify(path)
        assert summary == {"n_reporters": 6, "round": 2,
                           "rounds_recorded": 2}
        assert path.read_bytes() == raw        # dry run: zero mutation

    def test_torn_final_record_detected(self, tmp_path):
        _, path = self._saved(tmp_path)
        raw = path.read_bytes()
        # a power-loss torn write: the file is cut short mid final
        # record (the npz central directory is gone)
        path.write_bytes(raw[: len(raw) - len(raw) // 3])
        with pytest.raises(CheckpointCorruptionError) as ei:
            ReputationLedger.verify(path)
        assert ei.value.error_code == "PYC301"
        assert path.name in str(ei.value)

    def test_missing_field_named(self, tmp_path):
        _, path = self._saved(tmp_path)
        with np.load(path) as data:
            state = {k: data[k] for k in data.files if k != "round"}
        np.savez(path, **state)
        with pytest.raises(CheckpointCorruptionError,
                           match="'round' is missing"):
            ReputationLedger.verify(path)

    def test_nonfinite_reputation_named(self, tmp_path):
        _, path = self._saved(tmp_path)
        with np.load(path) as data:
            state = {k: data[k] for k in data.files}
        state["reputation"] = np.array([0.5, np.nan, 0.5])
        np.savez(path, **state)
        with pytest.raises(CheckpointCorruptionError,
                           match="non-finite"):
            ReputationLedger.verify(path)

    def test_missing_file_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            ReputationLedger.verify(tmp_path / "absent.npz")


# -- replication log -------------------------------------------------------


class TestReplicationLog:
    def test_journal_round_trip_bitwise(self, tmp_path):
        log = ReplicationLog.create(tmp_path, "s", 4)
        rng = np.random.default_rng(0)
        b0 = rng.random((4, 3))
        b0[0, 1] = np.nan
        bounds = [None, {"scaled": True, "min": 0.0, "max": 10.0}, None]
        log.journal_block(0, 0, b0, bounds)
        b1 = rng.random((4, 2))
        log.journal_block(0, 1, b1, None)
        staged = log.staged(0)
        assert len(staged) == 2
        np.testing.assert_array_equal(staged[0][0], b0)
        assert staged[0][1] == bounds
        np.testing.assert_array_equal(staged[1][0], b1)
        assert staged[1][1] is None

    def test_digest_mismatch_refused(self, tmp_path):
        log = ReplicationLog.create(tmp_path, "s", 4)
        log.journal_block(0, 0, np.ones((4, 3)))
        victim = log._block_path(0, 0)
        raw = bytearray(victim.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        victim.write_bytes(bytes(raw))
        with pytest.raises(CheckpointCorruptionError):
            log.staged(0)

    def test_torn_final_block_detected(self, tmp_path):
        log = ReplicationLog.create(tmp_path, "s", 4)
        log.journal_block(0, 0, np.ones((4, 3)))
        log.journal_block(0, 1, np.zeros((4, 2)))
        victim = log._block_path(0, 1)       # the FINAL journal record
        raw = victim.read_bytes()
        victim.write_bytes(raw[: len(raw) // 2])
        with pytest.raises(CheckpointCorruptionError) as ei:
            log.staged(0)
        assert victim.name in str(ei.value)

    def test_index_gap_refused(self, tmp_path):
        log = ReplicationLog.create(tmp_path, "s", 4)
        log.journal_block(0, 0, np.ones((4, 3)))
        log.journal_block(0, 1, np.ones((4, 3)))
        log._block_path(0, 0).unlink()
        with pytest.raises(CheckpointCorruptionError,
                           match="not contiguous"):
            log.staged(0)

    def test_commit_clears_only_closed_rounds(self, tmp_path):
        log = ReplicationLog.create(tmp_path, "s", 4)
        log.journal_block(0, 0, np.ones((4, 3)))
        log.journal_block(1, 0, np.zeros((4, 3)))   # next round's journal
        ledger = ReputationLedger(4)
        ledger.round = 1
        log.commit_round(ledger)
        assert not log._block_path(0, 0).exists()
        assert log._block_path(1, 0).exists()
        assert log.verify()["staged_blocks"] == 1

    def test_duplicate_create_refused(self, tmp_path):
        ReplicationLog.create(tmp_path, "s", 4)
        with pytest.raises(InputError):
            ReplicationLog.create(tmp_path, "s", 4)

    def test_meta_corruption_named(self, tmp_path):
        log = ReplicationLog.create(tmp_path, "s", 4)
        log.meta_path.write_text("{not json")
        with pytest.raises(CheckpointCorruptionError):
            log.verify()

    def test_verify_refuses_roster_mismatch(self, tmp_path):
        log = ReplicationLog.create(tmp_path, "s", 4)
        ReputationLedger(5).save(log.ledger_path)
        with pytest.raises(CheckpointCorruptionError,
                           match="reporters"):
            log.verify()

    def test_failed_commit_fences_session(self, tmp_path):
        """A resolve whose ledger commit fails must FENCE the session:
        memory is one round ahead of disk, so a later acknowledged
        append would journal under a round index replay discards — an
        acknowledged write the fleet would forget. The fence makes the
        failure loud; the durable log (previous checkpoint + the
        round's journal) still replays the round bit-identically."""
        ref = MarketSession("ref", N_REPORTERS)
        ref.append(make_block(0, 0))
        want = ref.resolve()

        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        session.append(make_block(0, 0))
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "ledger.save", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            with pytest.raises(OSError):
                session.resolve()
        assert plan.fired == [("ledger.save", 0, "raise")]
        with pytest.raises(CheckpointCorruptionError, match="fenced"):
            session.append(make_block(1, 0))
        with pytest.raises(CheckpointCorruptionError, match="fenced"):
            session.resolve()
        standby = replay_session(tmp_path, "s")
        assert_same_bits(standby.resolve(), want,
                         "uncommitted round must replay bit-identical")

    def test_failed_fold_removes_journal_record(self, tmp_path,
                                                monkeypatch):
        """An append whose in-memory fold fails must not leave its
        journal record behind: the caller was told the append never
        happened, so replay must not fold it — a phantom acknowledged
        block would change the standby's bits."""
        import pyconsensus_tpu.serve.session as session_mod

        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        session.append(make_block(0, 0))

        def boom(*args, **kwargs):
            raise RuntimeError("device fell over mid-fold")
        monkeypatch.setattr(session_mod, "_pass1_panel", boom)
        with pytest.raises(RuntimeError):
            session.append(make_block(0, 1))
        monkeypatch.undo()

        standby = replay_session(tmp_path, "s")
        assert len(standby._blocks) == 1     # the phantom never replays
        ref = MarketSession("ref", N_REPORTERS)
        ref.append(make_block(0, 0))
        assert_same_bits(standby.resolve(), ref.resolve(),
                         "failed append must not reach the standby")

    def test_injected_append_corruption_is_durable(self, tmp_path):
        """A ``serve.session_append`` corruption must hit the journal
        and the in-memory fold IDENTICALLY: the standby replays
        whatever the dead worker acknowledged — corrupted traffic
        included — or the bit-identity contract breaks under the exact
        faults the chaos plans inject."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        plan = faults.FaultPlan(seed=3, rules=[
            {"site": "serve.session_append", "kind": "nan_storm",
             "occurrences": [0], "args": {"fraction": 0.5}}])
        with faults.armed(plan):
            session.append(make_block(0, 0))
        # exactly one fire: the seam moved pre-journal, it did not fork
        assert plan.fired == [("serve.session_append", 0, "nan_storm")]
        # read through the staging decode (ISSUE 13: lattice-exact
        # blocks stage as device-resident int8 sentinel arrays)
        assert np.isnan(DurableSession._staged_host(
            session._blocks[0])).any()
        standby = replay_session(tmp_path, "s")
        np.testing.assert_array_equal(
            DurableSession._staged_host(standby._blocks[0]),
            DurableSession._staged_host(session._blocks[0]),
            err_msg="journal and fold diverged under injected corruption")


# -- failover determinism (the kill-point property test) -------------------


N_ROUNDS = 3


def drive(session, ops):
    """Run ``ops`` (a list of ("append", k, j) / ("resolve", k) steps)
    against ``session``; returns the per-round results."""
    results = []
    for op in ops:
        if op[0] == "append":
            session.append(make_block(op[1], op[2]))
        else:
            results.append(session.resolve())
    return results


def all_ops():
    ops = []
    for k in range(N_ROUNDS):
        for j in range(BLOCKS_PER_ROUND):
            ops.append(("append", k, j))
        ops.append(("resolve", k))
    return ops


@pytest.fixture(scope="module")
def reference_rounds():
    """The never-killed single-worker run (plain in-memory session)."""
    session = MarketSession("ref", N_REPORTERS)
    return drive(session, all_ops())


class TestFailoverDeterminism:
    @pytest.mark.parametrize("kill_at", range(len(all_ops())))
    def test_any_kill_point_resumes_bit_identical(self, tmp_path,
                                                  kill_at,
                                                  reference_rounds):
        """For EVERY point in a multi-round session — between appends,
        mid-round, right after a resolve — abandoning the worker there
        and replaying the log on the standby yields outcomes, iteration
        counts, and carried smooth_rep bit-identical to the
        uninterrupted run."""
        ops = all_ops()
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        results = drive(session, ops[:kill_at])
        del session                      # the worker dies here
        standby = replay_session(tmp_path, "s")
        results += drive(standby, ops[kill_at:])
        assert len(results) == N_ROUNDS
        for got, ref in zip(results, reference_rounds):
            assert_same_bits(got, ref, f"kill_at={kill_at}")
        np.testing.assert_array_equal(
            standby.reputation,
            np.asarray(reference_rounds[-1]["smooth_rep"]))

    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_direct_backend_rounds_resume_bit_identical(self, tmp_path,
                                                        backend):
        """The non-incremental resolve path (explicit backend /
        multi-iteration kwargs) has the same failover contract on both
        backends."""
        kwargs = {"max_iterations": 2, "backend": backend}
        ref_session = MarketSession("ref", N_REPORTERS)
        ref = []
        for k in range(2):
            for j in range(BLOCKS_PER_ROUND):
                ref_session.append(make_block(k, j))
            ref.append(ref_session.resolve(**kwargs))

        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        for j in range(BLOCKS_PER_ROUND):
            session.append(make_block(0, j))
        got = [session.resolve(**kwargs)]
        session.append(make_block(1, 0))
        del session                      # killed mid-round 1
        standby = replay_session(tmp_path, "s")
        standby.append(make_block(1, 1))
        got.append(standby.resolve(**kwargs))
        for g, r in zip(got, ref):
            assert_same_bits(g, r, backend)

    def test_crash_before_commit_re_resolves_identically(self, tmp_path):
        """A kill between the round's resolve and its ledger commit
        leaves the previous checkpoint + full journal; the standby
        re-resolves the round from identical inputs to identical bits
        (no lost, no double-applied round)."""
        session = DurableSession.create(tmp_path / "a", "s", N_REPORTERS)
        for j in range(BLOCKS_PER_ROUND):
            session.append(make_block(0, j))
        # snapshot the durable state BEFORE the resolve commits
        shutil.copytree(tmp_path / "a", tmp_path / "b")
        ref = session.resolve()
        standby = replay_session(tmp_path / "b", "s")
        assert standby.ledger.round == 0
        assert len(standby._blocks) == BLOCKS_PER_ROUND
        assert_same_bits(standby.resolve(), ref)

    def test_refused_append_leaves_no_journal_record(self, tmp_path):
        """Validation runs BEFORE the journal write: an append the
        caller was told never happened must leave no record replay
        would fold — or crash on — during a takeover."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        session.append(make_block(0, 0))
        with pytest.raises(InputError):
            session.append(make_block(0, 1),
                           event_bounds=[(0.0, 1.0)] * 99)  # wrong len
        assert len(session.log.staged(session.ledger.round)) == 1
        standby = replay_session(tmp_path, "s")
        assert len(standby._blocks) == 1
        assert_same_bits(standby.resolve(), session.resolve())

    def test_replay_ignores_stale_closed_round_journal(self, tmp_path):
        """A crash between ledger commit and journal GC leaves stale
        staged files for an already-closed round — replay recognizes
        them by round index and the next round stays clean."""
        session = DurableSession.create(tmp_path, "s", N_REPORTERS)
        session.append(make_block(0, 0))
        log = session.log
        committed = log._block_path(0, 0).read_bytes()
        session.resolve()
        # resurrect the closed round's journal record (the GC the
        # crash skipped)
        log._block_path(0, 0).write_bytes(committed)
        standby = replay_session(tmp_path, "s")
        assert standby.ledger.round == 1
        assert len(standby._blocks) == 0


# -- the fleet router ------------------------------------------------------


class TestFleetRouting:
    def test_stateless_requests_bit_identical_to_oracle(self, tmp_path):
        rng = np.random.default_rng(5)
        m = rng.choice([0.0, 1.0], size=(10, 8))
        ref = Oracle(reports=m, backend="numpy").consensus()
        with small_fleet(tmp_path) as fleet:
            futs = [fleet.submit(reports=m, backend="numpy")
                    for _ in range(9)]
            for f in futs:
                got = f.result(timeout=60)
                np.testing.assert_array_equal(
                    got["events"]["outcomes_final"],
                    ref["events"]["outcomes_final"])
                np.testing.assert_array_equal(
                    got["agents"]["smooth_rep"],
                    ref["agents"]["smooth_rep"])

    def test_submit_rejects_reports_and_session(self, tmp_path):
        fleet = small_fleet(tmp_path)
        fleet.create_session("mkt", n_reporters=N_REPORTERS)
        with pytest.raises(InputError, match="exactly one"):
            fleet.submit(reports=np.ones((3, 3)), session="mkt")

    def test_session_requires_log_dir(self):
        fleet = ConsensusFleet(FleetConfig(
            n_workers=1, worker=ServeConfig(warmup=())))
        with pytest.raises(InputError, match="log_dir"):
            fleet.create_session("s", n_reporters=4)

    def test_unknown_session_and_worker(self, tmp_path):
        fleet = small_fleet(tmp_path)
        with pytest.raises(InputError, match="unknown fleet session"):
            fleet.submit(session="nope")
        with pytest.raises(PlacementError):
            fleet.kill_worker("w99")

    def test_all_workers_dead_is_placement_error(self, tmp_path):
        fleet = small_fleet(tmp_path, n=2)
        fleet.kill_worker("w0")
        fleet.kill_worker("w1")
        with pytest.raises(PlacementError) as ei:
            fleet.submit(reports=np.ones((3, 3)), backend="numpy")
        assert ei.value.error_code == "PYC503"

    def test_cluster_full_shed_quotes_scaled_retry(self, tmp_path):
        fleet = small_fleet(tmp_path, base_retry_s=0.2)

        def full(**kw):
            raise ServiceOverloadError("full", reason="queue_full")
        for w in fleet.workers.values():
            w.service.submit = full
        fleet.kill_worker("w2")          # 2/3 alive
        with pytest.raises(ServiceOverloadError) as ei:
            fleet.submit(reports=np.ones((3, 3)), backend="numpy")
        ctx = ei.value.context
        assert ctx["reason"] == "cluster_full"
        assert ctx["alive_workers"] == 2
        # honest hint: base * registered/alive = 0.2 * 3/2
        assert ctx["retry_after_s"] == pytest.approx(0.3, abs=1e-6)

    def test_rate_limit_not_spilled(self, tmp_path):
        """Spillover is for full queues; a tenant over its rate budget
        must not get n_workers times the configured rate."""
        fleet = small_fleet(tmp_path)
        calls = []

        def limited(**kw):
            calls.append(1)
            raise ServiceOverloadError("over rate", reason="rate_limited",
                                       retry_after_s=0.1)
        for w in fleet.workers.values():
            w.service.submit = limited
        with pytest.raises(ServiceOverloadError) as ei:
            fleet.submit(reports=np.ones((3, 3)))
        assert ei.value.context["reason"] == "rate_limited"
        assert len(calls) == 1


# -- failover through the fleet --------------------------------------------


class TestFleetFailover:
    def test_all_workers_dead_sheds_placement_not_retryable(
            self, tmp_path):
        """With every worker dead a session request must shed the
        NON-retryable PYC503 — not PYC501, which a polite client would
        retry against a fleet that can never serve — and repeated
        routing must not re-run (or re-count) takeovers that cannot
        land anywhere."""
        fleet = small_fleet(tmp_path, n=1).start(warmup=False)
        fleet.create_session("s", n_reporters=6)
        fleet.append("s", make_block(0, 0)[:6])
        fleet.submit(session="s").result(timeout=60)
        fleet.kill_worker("w0")
        failovers = obs.value("pyconsensus_failovers_total")
        for _ in range(3):
            with pytest.raises(PlacementError):
                fleet.submit(session="s")
        assert obs.value("pyconsensus_failovers_total") == failovers
        # the durable log survives the whole-fleet death: a fresh
        # adoption path still replays the session
        assert replay_session(fleet.config.log_dir, "s").ledger.round == 1
        fleet.close(drain=True)

    def test_migrated_session_leaves_dead_workers_store(self, tmp_path):
        """The live-session gauge counts every store in the process;
        a migrated session must live in exactly ONE of them."""
        fleet = small_fleet(tmp_path, n=2).start(warmup=False)
        before = obs.value("pyconsensus_serve_sessions") or 0
        fleet.create_session("s", n_reporters=6)
        assert obs.value("pyconsensus_serve_sessions") == before + 1
        victim = fleet.owner_of("s")
        fleet.kill_worker(victim)
        assert fleet.owner_of("s") != victim
        assert "s" not in fleet.workers[victim].service.sessions.names()
        assert obs.value("pyconsensus_serve_sessions") == before + 1
        fleet.close(drain=True)

    def test_graceful_drain_is_not_worker_loss(self, tmp_path):
        """A LIVE worker's shutdown drain must shed as PYC401
        (reason ``draining``), not PYC501 — no takeover is coming, so
        a polite client must not burn its retry budget waiting for
        one."""
        fleet = small_fleet(tmp_path).start(warmup=False)
        fleet.create_session("s", n_reporters=6)
        owner = fleet.owner_of("s")
        fleet.workers[owner].service.admission.start_drain()
        with pytest.raises(ServiceOverloadError) as ei:
            fleet.submit(session="s")
        assert ei.value.error_code == "PYC401"
        assert ei.value.context["reason"] == "draining"
        fleet.close(drain=True)

    def test_routing_discovery_takeover_fault_is_structured(
            self, tmp_path):
        """An injected ``fleet.takeover`` fault during the synchronous
        routing-time death declaration must reach the client as
        retryable PYC501 — never the raw injected error — and the
        stranded session must land on the survivor on the next routed
        request."""
        fleet = small_fleet(tmp_path, n=2).start(warmup=False)
        fleet.create_session("s", n_reporters=6)
        fleet.append("s", make_block(0, 0)[:6])
        fleet.submit(session="s").result(timeout=60)
        owner = fleet.owner_of("s")
        # fence the worker without declaring it (the monitor has not
        # scanned): the next routed request discovers the death
        fleet.workers[owner].hard_kill(0.1)
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "fleet.takeover", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            with pytest.raises(WorkerLostError) as ei:
                fleet.submit(session="s")
        assert plan.fired == [("fleet.takeover", 0, "raise")]
        assert ei.value.error_code == "PYC501"
        assert ei.value.context["retry_after_s"] > 0
        # the retried route runs the takeover for real this time
        fleet.append("s", make_block(1, 0)[:6])
        assert fleet.owner_of("s") != owner
        fleet.submit(session="s").result(timeout=60)
        fleet.close(drain=True)

    def test_only_dead_workers_sessions_move(self, tmp_path):
        fleet = small_fleet(tmp_path)
        names = [f"market-{i}" for i in range(8)]
        owners = {n: fleet.create_session(n, n_reporters=6)
                  for n in names}
        assert len(set(owners.values())) > 1       # actually spread
        victim = fleet.owner_of(names[0])
        before_migrated = obs.value("pyconsensus_sessions_migrated_total")
        info = fleet.kill_worker(victim)
        moved = {s for s, _ in info["sessions_migrated"]}
        assert moved == {n for n, o in owners.items() if o == victim}
        for n in names:
            if owners[n] != victim:
                assert fleet.owner_of(n) == owners[n]   # stability
            else:
                assert fleet.owner_of(n) != victim
        assert (obs.value("pyconsensus_sessions_migrated_total")
                - before_migrated) == len(moved)
        assert obs.value("pyconsensus_fleet_workers") == 2

    def test_queued_requests_shed_as_worker_lost(self, tmp_path):
        fleet = small_fleet(tmp_path)          # not started: no batcher
        w = fleet.workers["w0"]
        req = ResolveRequest(reports=np.ones((3, 3)))
        w.service.queue.put(req)
        info = fleet.kill_worker("w0")
        assert info["shed_queued"] == 1
        with pytest.raises(WorkerLostError) as ei:
            req.future.result(timeout=0)
        assert ei.value.error_code == "PYC501"
        assert ei.value.context["retry_after_s"] > 0
        assert ei.value.context["worker"] == "w0"

    def test_stale_session_object_is_fenced_at_takeover(self, tmp_path):
        """The acknowledged-append race: a client that resolved the
        owner just before the kill still holds the dead worker's
        session object. After the takeover that object is FENCED — a
        late append raises the retryable loss instead of journaling a
        block the standby never folds (and whose journal index the
        standby's next append would silently overwrite)."""
        fleet = small_fleet(tmp_path).start(warmup=False)
        owner = fleet.create_session("mkt", n_reporters=N_REPORTERS)
        fleet.append("mkt", make_block(0, 0))
        stale = fleet.workers[owner].service.sessions.get("mkt")
        fleet.kill_worker(owner)
        with pytest.raises(WorkerLostError) as ei:
            stale.append(make_block(0, 1))
        assert ei.value.error_code == "PYC501"
        assert ei.value.context["retry_after_s"] > 0
        with pytest.raises(WorkerLostError):
            stale.resolve()
        # the retrying client lands on the standby, and the session
        # carries exactly the acknowledged blocks — bit-identical to a
        # single box that saw the same appends
        fleet.append("mkt", make_block(0, 1))
        got = fleet.submit(session="mkt").result(timeout=60)
        ref = MarketSession("ref", N_REPORTERS)
        ref.append(make_block(0, 0))
        ref.append(make_block(0, 1))
        want = ref.resolve()
        np.testing.assert_array_equal(
            np.asarray(got["agents"]["smooth_rep"]),
            np.asarray(want["smooth_rep"]))
        np.testing.assert_array_equal(
            np.asarray(got["events"]["outcomes_final"]),
            np.asarray(want["outcomes_final"]))
        fleet.close(drain=True)

    def test_concurrent_death_declarations_single_takeover(self,
                                                           tmp_path):
        """kill_worker racing a second declaration of the same worker:
        the per-worker declare lock serializes them — exactly one
        takeover replays the session, the loser observes a no-op, and
        no InputError ('session already exists') escapes to a client."""
        fleet = small_fleet(tmp_path)
        owner = fleet.create_session("mkt", n_reporters=N_REPORTERS)
        fleet.append("mkt", make_block(0, 0))
        failovers0 = obs.value("pyconsensus_failovers_total") or 0
        migrated0 = obs.value("pyconsensus_sessions_migrated_total") or 0
        failures = []
        gate = threading.Barrier(2)

        def declare():
            gate.wait()
            try:
                fleet.kill_worker(owner)
            except Exception as exc:   # noqa: BLE001 — the assertion
                failures.append(exc)
        threads = [threading.Thread(target=declare) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures, failures
        assert fleet.owner_of("mkt") not in (None, owner)
        assert ((obs.value("pyconsensus_failovers_total") or 0)
                - failovers0) == 1
        assert ((obs.value("pyconsensus_sessions_migrated_total") or 0)
                - migrated0) == 1

    def test_takeover_window_surfaces_failover_in_progress(self,
                                                           tmp_path):
        fleet = small_fleet(tmp_path)
        fleet.create_session("s", n_reporters=4)
        fleet._migrating.add("s")
        fleet.capacity.begin_takeover(0.5)
        with pytest.raises(FailoverInProgressError) as ei:
            fleet.submit(session="s")
        assert ei.value.error_code == "PYC502"
        assert 0 < ei.value.context["retry_after_s"] <= 0.51

    def test_standby_never_adopts_corrupt_log(self, tmp_path):
        """Torn ledger replication: the takeover preflight refuses, the
        session answers its corruption error, and HEALTHY sessions on
        the same dead worker still migrate."""
        fleet = small_fleet(tmp_path)
        names = [f"m{i}" for i in range(6)]
        for n in names:
            fleet.create_session(n, n_reporters=6)
            fleet.append(n, make_block(0, 0)[:6])
            fleet.submit(session=n).result(timeout=60)
        victim_worker = fleet.owner_of(names[0])
        victims = [n for n in names
                   if fleet.owner_of(n) == victim_worker]
        torn = victims[0]
        path = ReplicationLog(fleet.config.log_dir, torn).ledger_path
        raw = path.read_bytes()
        path.write_bytes(raw[: len(raw) // 2])
        info = fleet.kill_worker(victim_worker)
        migrated = {s for s, _ in info["sessions_migrated"]}
        assert migrated == set(victims) - {torn}
        with pytest.raises(CheckpointCorruptionError):
            fleet.submit(session=torn)
        assert torn in fleet.status()["failed_sessions"]
        fleet.close(drain=True)

    def test_injected_torn_ledger_replay_site(self, tmp_path):
        """The seeded-FaultPlan spelling of the same contract: a
        ``torn_write`` rule at ``fleet.ledger_replay`` tears the
        replication log between death and adoption."""
        fleet = small_fleet(tmp_path, n=2)
        fleet.create_session("s", n_reporters=6)
        fleet.append("s", make_block(0, 0)[:6])
        fleet.submit(session="s").result(timeout=60)
        owner = fleet.owner_of("s")
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "fleet.ledger_replay", "kind": "torn_write",
             "occurrences": [0], "args": {"keep_bytes": 40}}])
        with faults.armed(plan):
            fleet.kill_worker(owner)
        assert plan.fired == [("fleet.ledger_replay", 0, "torn_write")]
        with pytest.raises(CheckpointCorruptionError):
            fleet.submit(session="s")
        fleet.close(drain=True)

    def test_route_site_injection(self, tmp_path):
        fleet = small_fleet(tmp_path)
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "fleet.route", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            with pytest.raises(OSError):
                fleet.submit(reports=np.ones((3, 3)), backend="numpy")

    def test_heartbeat_single_flap_is_tolerated(self, tmp_path):
        fleet = small_fleet(tmp_path, n=2, heartbeat_timeout_s=0.5)
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "fleet.heartbeat", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            assert fleet.check_workers() == []    # w0's beat lost...
            time.sleep(0.05)
            assert fleet.check_workers() == []    # ...but it recovers
        assert fleet.workers["w0"].alive

    def test_sustained_heartbeat_flap_triggers_failover(self, tmp_path):
        fleet = small_fleet(tmp_path, n=2, heartbeat_timeout_s=0.08)
        fleet.create_session("s", n_reporters=6)
        # force the session onto w0 so the flap visibly migrates it
        if fleet.owner_of("s") != "w0":
            with fleet._lock:
                owner = fleet._sessions["s"]
                sess = fleet.workers[owner].service.sessions.get("s")
                fleet.workers[owner].service.sessions.remove("s")
                fleet.workers["w0"].service.sessions.add(sess)
                fleet._sessions["s"] = "w0"
        # refresh both beats with an UNARMED scan first: construction +
        # create_session include fsync'd replication-log writes whose
        # latency spikes under a fully loaded suite can age w0's stamp
        # past the 80 ms window before the first armed scan even runs
        # (observed full-suite flake; disarmed scans consume no fault
        # occurrences, so the armed schedule below is unchanged)
        assert fleet.check_workers() == []
        # with 2 alive workers the scan order is w0, w1: occurrences
        # 0, 2, 4 are w0's beats — every one lost, w1 never touched
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "fleet.heartbeat", "kind": "raise",
             "occurrences": [0, 2, 4, 6], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            assert fleet.check_workers() == []
            time.sleep(0.1)
            dead = fleet.check_workers()
        assert dead == ["w0"]
        assert not fleet.workers["w0"].alive      # fenced (single writer)
        assert fleet.workers["w1"].alive
        assert fleet.owner_of("s") == "w1"
        # the migrated session still serves, from the replayed log
        fleet.append("s", make_block(0, 0)[:6])
        result = fleet.submit(session="s").result(timeout=60)
        assert np.isfinite(
            np.asarray(result["agents"]["smooth_rep"])).all()
        fleet.close(drain=True)

    def test_dead_owner_discovered_at_routing_fails_over(self, tmp_path):
        """A submit that races ahead of the monitor: the dead owner is
        discovered at routing time, takeover runs synchronously, and
        the caller lands on the standby — no error at all."""
        fleet = small_fleet(tmp_path, n=2)
        fleet.create_session("s", n_reporters=6)
        owner = fleet.owner_of("s")
        # fence without declaring (the monitor has not scanned yet)
        fleet.workers[owner].hard_kill(0.1)
        fleet.append("s", make_block(0, 0)[:6])
        result = fleet.submit(session="s").result(timeout=60)
        assert fleet.owner_of("s") != owner
        assert np.isfinite(
            np.asarray(result["agents"]["smooth_rep"])).all()
        fleet.close(drain=True)


# -- cluster capacity (fleet-aware admission) ------------------------------


class TestClusterCapacity:
    def test_alive_accounting_and_gauge(self):
        cap = ClusterCapacity(base_retry_s=0.2)
        for i in range(3):
            cap.register(f"w{i}", 16)
        assert cap.alive == 3
        assert cap.alive_slots() == 48
        assert obs.value("pyconsensus_fleet_workers") == 3
        cap.mark_dead("w1")
        assert cap.alive == 2
        assert cap.alive_slots() == 32
        assert obs.value("pyconsensus_fleet_workers") == 2

    def test_retry_hint_scales_with_survivors(self):
        cap = ClusterCapacity(base_retry_s=0.2)
        for i in range(4):
            cap.register(f"w{i}", 8)
        assert cap.shed_retry_after() == pytest.approx(0.2)
        cap.mark_dead("w0")
        cap.mark_dead("w1")
        assert cap.shed_retry_after() == pytest.approx(0.4)

    def test_takeover_window_folds_into_hint(self):
        cap = ClusterCapacity(base_retry_s=0.1)
        cap.register("w0", 8)
        cap.begin_takeover(5.0)
        assert cap.shed_retry_after() > 4.0
        assert cap.takeover_remaining() > 4.0
        cap.end_takeover()
        assert cap.takeover_remaining() == 0.0
        assert cap.shed_retry_after() == pytest.approx(0.1)

    def test_per_worker_queue_gauge(self, tmp_path):
        fleet = small_fleet(tmp_path, n=2)
        fleet.check_workers()
        assert obs.value("pyconsensus_fleet_worker_queue_depth",
                         worker="w0") == 0
        assert obs.value("pyconsensus_fleet_worker_queue_depth",
                         worker="w1") == 0


# -- loadgen retry (honest retry_after_s) ----------------------------------


class _ShedThenServe:
    """Sheds each request ``fails`` times with ``exc_factory()``, then
    serves it. Deterministic per request index (keyed by submit order)."""

    def __init__(self, fails, exc_factory):
        self.fails = fails
        self.exc_factory = exc_factory
        self.seen: dict = {}
        self.submits = 0

    def submit(self, reports=None, tenant="t", **kw):
        self.submits += 1
        key = self.submits          # attempt-unique; per-request count
        n = self.seen.get(id(reports), 0)
        self.seen[id(reports)] = n + 1
        if n < self.fails:
            raise self.exc_factory()
        fut = Future()
        fut.set_result({"ok": key})
        return fut


class TestLoadgenRetry:
    def test_retryable_codes_cover_fleet_taxonomy(self):
        assert set(RETRYABLE_CODES) == {"PYC401", "PYC501", "PYC502"}

    def test_retry_absorbs_bounded_sheds(self):
        svc = _ShedThenServe(2, lambda: WorkerLostError(
            "lost", retry_after_s=0.01))
        # distinct shapes -> distinct corpus matrices, so the fake
        # service counts sheds per request, not per matrix object
        gen = LoadGenerator(svc, shapes=((2, 2), (2, 3), (2, 4), (2, 5)),
                            max_retries=3, retry_cap_s=0.05)
        stats = gen.run_closed(n_requests=4, concurrency=1)
        assert stats["succeeded"] == 4 and stats["failed"] == 0
        assert stats["retried"] == 8          # 2 retries x 4 requests
        assert stats["abandoned"] == 0

    def test_exhausted_budget_counts_abandoned(self):
        svc = _ShedThenServe(99, lambda: ServiceOverloadError(
            "full", reason="queue_full", retry_after_s=0.01))
        gen = LoadGenerator(svc, shapes=((2, 2), (2, 3), (2, 4)),
                            max_retries=1, retry_cap_s=0.05)
        stats = gen.run_closed(n_requests=3, concurrency=1)
        assert stats["failed"] == 3
        assert stats["errors"] == {"PYC401": 3}
        assert stats["retried"] == 3
        assert stats["abandoned"] == 3

    def test_zero_budget_keeps_pre_fleet_semantics(self):
        svc = _ShedThenServe(99, lambda: ServiceOverloadError(
            "full", reason="queue_full", retry_after_s=0.01))
        gen = LoadGenerator(svc, shapes=((2, 2),))
        stats = gen.run_closed(n_requests=3, concurrency=1)
        assert stats["failed"] == 3
        assert stats["retried"] == 0 and stats["abandoned"] == 0

    def test_placement_error_not_retried(self):
        svc = _ShedThenServe(99, lambda: PlacementError("empty"))
        gen = LoadGenerator(svc, shapes=((2, 2),), max_retries=5)
        stats = gen.run_closed(n_requests=2, concurrency=1)
        assert stats["errors"] == {"PYC503": 2}
        assert stats["retried"] == 0 and stats["abandoned"] == 0

    def test_non_taxonomy_errors_not_retried(self):
        svc = _ShedThenServe(99, lambda: ValueError("bad"))
        gen = LoadGenerator(svc, shapes=((2, 2),), max_retries=5)
        stats = gen.run_closed(n_requests=2, concurrency=1)
        assert stats["errors"] == {"ValueError": 2}
        assert stats["retried"] == 0

    def test_open_loop_defers_retries_past_schedule(self):
        svc = _ShedThenServe(1, lambda: ServiceOverloadError(
            "full", reason="queue_full", retry_after_s=0.01))
        gen = LoadGenerator(svc, shapes=((2, 2), (2, 3), (2, 4), (2, 5)),
                            max_retries=2, retry_cap_s=0.05)
        stats = gen.run_open(n_requests=4, rate_rps=200.0)
        assert stats["succeeded"] == 4 and stats["failed"] == 0
        assert stats["retried"] == 4
        assert stats["abandoned"] == 0

    def test_summary_keys_stable(self):
        s = summarize([0.1], {"PYC401": 1}, 1.0, 2, retried=3,
                      abandoned=1)
        assert s["retried"] == 3 and s["abandoned"] == 1
        assert s["succeeded"] == 1 and s["failed"] == 1


# -- chaos: kill a worker mid-traffic --------------------------------------


class TestKillWorkerMidTraffic:
    def test_in_process_chaos_zero_client_visible_loss(self, tmp_path):
        """The acceptance criterion, in-process: concurrent stateless
        traffic + a session, one worker hard-killed mid-run. Every
        request either resolves bit-identical to the single-box
        reference or sheds with a PYC-coded structured error that a
        bounded retry absorbs — zero silent drops, zero abandoned."""
        rng = np.random.default_rng(9)
        m = rng.choice([0.0, 1.0], size=(10, 8))
        ref = Oracle(reports=m, backend="numpy").consensus()
        fleet = small_fleet(tmp_path).start(warmup=False)
        fleet.create_session("chaos", n_reporters=N_REPORTERS)

        results, errors = [], []
        lock = threading.Lock()
        barrier = threading.Event()

        def client(n):
            for i in range(n):
                if i == 3:
                    barrier.set()       # mid-traffic signal
                for attempt in range(6):
                    try:
                        r = fleet.submit(reports=m,
                                         backend="numpy").result(60)
                        with lock:
                            results.append(r)
                        break
                    except Exception as exc:  # noqa: BLE001
                        code = getattr(exc, "error_code", None)
                        with lock:
                            errors.append(exc)
                        if code not in ("PYC401", "PYC501", "PYC502"):
                            return
                        time.sleep(float(getattr(exc, "context", {})
                                         .get("retry_after_s", 0.05)))
                else:
                    pytest.fail("request abandoned after retries")

        threads = [threading.Thread(target=client, args=(8,))
                   for _ in range(4)]
        for t in threads:
            t.start()
        barrier.wait(timeout=60)
        victim = fleet.owner_of("chaos")
        fleet.kill_worker(victim)               # SIGKILL model, mid-run
        for t in threads:
            t.join(timeout=120)
        fleet.close(drain=True)
        assert len(results) == 32               # every request resolved
        for r in results:
            np.testing.assert_array_equal(
                r["events"]["outcomes_final"],
                ref["events"]["outcomes_final"])
            np.testing.assert_array_equal(
                r["agents"]["smooth_rep"], ref["agents"]["smooth_rep"])
        for exc in errors:                      # sheds all structured
            assert getattr(exc, "error_code", "").startswith("PYC"), exc
        assert fleet.owner_of("chaos") != victim

    def test_session_chaos_bit_identical_to_single_box(self, tmp_path):
        """Session traffic through the kill: the client retries PYC5xx
        sheds and the completed round sequence is bit-identical to the
        uninterrupted single-box run."""
        fleet = small_fleet(tmp_path).start(warmup=False)
        fleet.create_session("s", n_reporters=N_REPORTERS)
        got = []
        killed = False
        for k in range(N_ROUNDS):
            for j in range(BLOCKS_PER_ROUND):
                for _ in range(20):
                    try:
                        fleet.append("s", make_block(k, j))
                        break
                    except (WorkerLostError,
                            FailoverInProgressError) as exc:
                        time.sleep(exc.context.get("retry_after_s",
                                                   0.05))
                if k == 1 and j == 0 and not killed:
                    fleet.kill_worker(fleet.owner_of("s"))
                    killed = True
            for _ in range(20):
                try:
                    got.append(fleet.submit(session="s").result(60))
                    break
                except (WorkerLostError,
                        FailoverInProgressError) as exc:
                    time.sleep(exc.context.get("retry_after_s", 0.05))
        fleet.close(drain=True)
        ref_session = MarketSession("ref", N_REPORTERS)
        ref = drive(ref_session, all_ops())
        assert len(got) == N_ROUNDS
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(
                np.asarray(g["agents"]["smooth_rep"]),
                np.asarray(r["smooth_rep"]))
            np.testing.assert_array_equal(
                np.asarray(g["events"]["outcomes_final"]),
                np.asarray(r["outcomes_final"]))
            assert g["iterations"] == int(np.asarray(r["iterations"]))


class TestRealSigkill:
    def test_kill_minus_nine_mid_session_standby_resumes_bit_identical(
            self, tmp_path):
        """The acceptance criterion with a REAL ``kill -9``: a worker
        process drives a durable session; SIGKILLed mid-round, a
        standby (this process) adopts via verify + replay and finishes
        the rounds — final reputation and outcomes bit-identical to the
        never-killed run, no matter which instruction the kill hit."""
        log_root = tmp_path / "log"
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "fleet_worker.py")
        env = worker_env()
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, script, str(log_root), "mkt", "4", "0.1"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            deadline = time.monotonic() + 180
            seen = []
            # kill once the worker is INSIDE round 1 (mid-traffic, a
            # committed round behind it and a partial journal ahead)
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    pytest.fail("worker exited early:\n" + "".join(seen))
                seen.append(line)
                if line.startswith("APPEND 1"):
                    break
            else:
                pytest.fail("worker never reached round 1:\n"
                            + "".join(seen))
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == -signal.SIGKILL

        # the standby: verify-preflight + replay, then continue with
        # the same deterministic traffic to the same horizon
        standby = replay_session(log_root, "mkt")
        assert standby.ledger.round >= 1        # round 0 survived
        got = []
        for k in range(standby.ledger.round, 4):
            for j in range(len(standby._blocks), BLOCKS_PER_ROUND):
                standby.append(make_block(k, j))
            got.append(standby.resolve())

        ref_session = MarketSession("ref", N_REPORTERS)
        ref = []
        for k in range(4):
            for j in range(BLOCKS_PER_ROUND):
                ref_session.append(make_block(k, j))
            ref.append(ref_session.resolve())
        # every round the standby resolved matches the uninterrupted
        # run bit-for-bit, as does the carried reputation
        for g, r in zip(got, ref[-len(got):]):
            assert_same_bits(g, r)
        np.testing.assert_array_equal(
            standby.reputation, np.asarray(ref[-1]["smooth_rep"]))
        assert standby.ledger.round == 4
