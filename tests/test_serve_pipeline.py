"""Pipelined dispatch + donated bucket kernels (ISSUE 13 tentpole b/c).

Covers the donation bit-identity contract (donated executables match
the undonated reference across every padded bucket class), the
batcher's depth-N async dispatch ring (bit-identical to the
synchronous depth-1 loop, both backends, zero added retraces), the
reusable pad templates, the pipeline-depth autotuner, the roofline
model, and the CL306 compiled-HLO aliasing check's crafted
trigger/no-trigger pair.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import collusion_reports
from pyconsensus_tpu import obs
from pyconsensus_tpu.faults import InputError
from pyconsensus_tpu.models.pipeline import ConsensusParams
from pyconsensus_tpu.serve import ConsensusService, ServeConfig
from pyconsensus_tpu.serve import kernels as sk
from pyconsensus_tpu.serve import sharded as ss
from pyconsensus_tpu.parallel.mesh import make_mesh


@pytest.fixture(autouse=True)
def _under_lock_witness(lock_witness):
    yield


def serve_params(**kw):
    kw.setdefault("algorithm", "sztorc")
    kw.setdefault("pca_method", "power")
    kw.setdefault("has_na", True)
    kw.setdefault("any_scaled", False)
    kw.setdefault("n_scaled", 0)
    return ConsensusParams(**kw)


def fresh_args(seed, bucket=(16, 64), R=12, E=48, batch=1):
    """Freshly-built device lane arrays (donation consumes them)."""
    g = np.random.default_rng(seed)
    m, _ = collusion_reports(g, R, E, liars=4, na_frac=0.1)
    lane = sk.bucket_inputs(m, np.full(R, 1.0 / R), np.zeros(E, bool),
                            np.zeros(E), np.ones(E), bucket[0],
                            bucket[1], has_na=True)
    if batch > 1:
        return [jnp.asarray(np.stack([f] * batch)) for f in lane]
    return [jnp.asarray(f) for f in lane]


def assert_bitwise(a, b, msg=""):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"{msg}{k}")


class TestDonationParity:
    """Donated executables are bit-identical to the undonated
    reference — donation changes buffer lifetime, never results."""

    def test_xla_single(self):
        p = serve_params()
        ref = sk.make_bucket_executable(p)(*fresh_args(1), p)
        don = sk.make_bucket_executable(p, donate=True)(*fresh_args(1), p)
        assert_bitwise({k: v for k, v in don.items()},
                       {k: v for k, v in ref.items()})

    def test_xla_batched(self):
        p = serve_params()
        ref = sk.make_bucket_executable(p, batched=True)(
            *fresh_args(2, batch=4), p)
        don = sk.make_bucket_executable(p, batched=True, donate=True)(
            *fresh_args(2, batch=4), p)
        assert_bitwise(dict(don), dict(ref))

    def test_sharded_single(self):
        p = serve_params()
        mesh = make_mesh(batch=2, event=4)
        ref = ss.make_sharded_bucket_executable(p, mesh)(
            *fresh_args(3, bucket=(16, 128), E=100), p)
        don = ss.make_sharded_bucket_executable(p, mesh, donate=True)(
            *fresh_args(3, bucket=(16, 128), E=100), p)
        assert_bitwise(dict(don), dict(ref))

    def test_sharded_batched(self):
        p = serve_params()
        mesh = make_mesh(batch=2, event=4)
        ref = ss.make_sharded_bucket_executable(p, mesh, batched=True)(
            *fresh_args(4, bucket=(16, 128), E=100, batch=8), p)
        don = ss.make_sharded_bucket_executable(
            p, mesh, batched=True, donate=True)(
            *fresh_args(4, bucket=(16, 128), E=100, batch=8), p)
        assert_bitwise(dict(don), dict(ref))

    def test_scaled_donation_parity(self):
        """All four donated vectors live (rescale/unscale keep
        mins/maxs) — the serve-bucket-scaled-alias contract's class."""
        p = serve_params(any_scaled=True)
        g = np.random.default_rng(5)
        R, E = 10, 32
        m = g.random((R, E)) * 20.0 - 5.0
        lane = sk.bucket_inputs(m, np.full(R, 1.0 / R),
                                np.ones(E, bool), np.full(E, -5.0),
                                np.full(E, 15.0), 16, 32, has_na=False)

        def args():
            return [jnp.asarray(a) for a in lane]

        p2 = serve_params(any_scaled=True, has_na=False)
        ref = sk.make_bucket_executable(p2)(*args(), p2)
        don = sk.make_bucket_executable(p2, donate=True)(*args(), p2)
        assert_bitwise(dict(don), dict(ref))

    def test_donated_inputs_are_consumed(self):
        """The donation is real: donated arg buffers are invalidated
        after the call (the reuse hazard DONATED_ARGS documents)."""
        p = serve_params()
        fn = sk.make_bucket_executable(p, donate=True)
        args = fresh_args(6)
        fn(*args, p)
        assert args[1].is_deleted()          # reputation was donated
        assert not args[0].is_deleted()      # the matrix was not


class TestPadTemplates:
    def test_template_matches_bucket_inputs(self):
        t = sk.BucketTemplates(16, 64, 1)
        g = np.random.default_rng(0)
        m, _ = collusion_reports(g, 12, 48, liars=3, na_frac=0.1)
        rep = np.full(12, 1.0 / 12)
        t.fill_lane(0, m, rep, np.zeros(48, bool), np.zeros(48),
                    np.ones(48), has_na=True)
        ref = sk.bucket_inputs(m, rep, np.zeros(48, bool), np.zeros(48),
                               np.ones(48), 16, 64, has_na=True)
        for a, b in zip(t.arrays(), ref):
            np.testing.assert_array_equal(a, b)

    def test_reuse_after_larger_request_resets_pads(self):
        """A smaller refill after a larger one must equal a fresh
        fill — the dirty-extent reset discipline."""
        t = sk.BucketTemplates(16, 64, 1)
        g = np.random.default_rng(1)
        big, _ = collusion_reports(g, 16, 64, liars=3, na_frac=0.2)
        small, _ = collusion_reports(g, 6, 10, liars=2, na_frac=0.2)
        rep_b, rep_s = np.full(16, 1 / 16), np.full(6, 1 / 6)
        t.fill_lane(0, big, rep_b, np.zeros(64, bool), np.zeros(64),
                    np.ones(64), has_na=True)
        t.fill_lane(0, small, rep_s, np.zeros(10, bool), np.zeros(10),
                    np.ones(10), has_na=True)
        ref = sk.bucket_inputs(small, rep_s, np.zeros(10, bool),
                               np.zeros(10), np.ones(10), 16, 64,
                               has_na=True)
        for a, b in zip(t.arrays(), ref):
            np.testing.assert_array_equal(a, b)

    def test_batched_lanes_independent(self):
        t = sk.BucketTemplates(8, 16, 4)
        g = np.random.default_rng(2)
        m1, _ = collusion_reports(g, 6, 12, liars=2)
        m2, _ = collusion_reports(g, 8, 16, liars=2)
        t.fill_lane(0, m1, np.full(6, 1 / 6), np.zeros(12, bool),
                    np.zeros(12), np.ones(12), has_na=True)
        t.fill_lane(1, m2, np.full(8, 1 / 8), np.zeros(16, bool),
                    np.zeros(16), np.ones(16), has_na=True)
        ref1 = sk.bucket_inputs(m1, np.full(6, 1 / 6),
                                np.zeros(12, bool), np.zeros(12),
                                np.ones(12), 8, 16, has_na=True)
        np.testing.assert_array_equal(t.arrays()[0][0], ref1[0])
        # lane 2 untouched: still pad-default
        np.testing.assert_array_equal(t.arrays()[0][2],
                                      np.zeros((8, 16)))
        np.testing.assert_array_equal(t.arrays()[4][2], np.ones(16))

    def test_transfer_pin_makes_reuse_safe(self):
        """The reuse contract the batcher enforces: placement is a
        GUARANTEED copy (``place_bucket_operands`` — ``jnp.asarray``
        zero-copy-aliases a numpy buffer whose allocation happens to
        satisfy the CPU client's alignment, so the aliased template
        read back the pad-default after a reset; this test flaked on
        exactly that alignment luck) and the transfer is pinned
        complete before the template may be refilled, after which the
        placed data must be immune to lane resets and refills."""
        import jax

        t = sk.BucketTemplates(8, 16, 1)
        g = np.random.default_rng(3)
        m, _ = collusion_reports(g, 8, 16, liars=2)
        t.fill_lane(0, m, np.full(8, 1 / 8), np.zeros(16, bool),
                    np.zeros(16), np.ones(16), has_na=False)
        placed = sk.place_bucket_operands(t)
        jax.block_until_ready(placed)      # the batcher's transfer pin
        t.reset_lane(0)
        m2, _ = collusion_reports(g, 8, 16, liars=2)
        t.fill_lane(0, m2, np.full(8, 1 / 8), np.zeros(16, bool),
                    np.zeros(16), np.ones(16), has_na=False)
        np.testing.assert_array_equal(np.asarray(placed[0]), m)


def _flat(d, prefix=""):
    out = {}
    for k, v in d.items():
        if isinstance(v, dict):
            out.update(_flat(v, prefix + k + "."))
        else:
            out[prefix + k] = np.asarray(v)
    return out


class TestPipelinedService:
    """Depth-N pipelined dispatch is bit-identical to the synchronous
    depth-1 loop (the determinism contract) with zero added
    retraces."""

    def _traffic(self, seed, n=10):
        g = np.random.default_rng(seed)
        shapes = [(12, 48), (24, 96), (12, 48), (10, 40)]
        return [collusion_reports(g, *shapes[i % len(shapes)], liars=3,
                                  na_frac=0.1)[0] for i in range(n)]

    def _run(self, depth, panels, backend="jax", **cfg_kw):
        cfg_kw.setdefault("sharded_buckets", False)
        cfg = ServeConfig(warmup=((16, 64), (32, 128)),
                          batch_window_ms=1.0, pipeline_depth=depth,
                          pallas_buckets=False, **cfg_kw)
        with ConsensusService(cfg) as svc:
            futs = [svc.submit(reports=p, backend=backend)
                    for p in panels]
            return [f.result(timeout=120) for f in futs]

    @pytest.mark.parametrize("depth", [2, 4])
    def test_depth_bitwise_vs_sync(self, depth):
        panels = self._traffic(10)
        sync = self._run(1, panels)
        pipe = self._run(depth, panels)
        for i, (a, b) in enumerate(zip(sync, pipe)):
            fa, fb = _flat(a), _flat(b)
            assert fa.keys() == fb.keys()
            for k in fa:
                np.testing.assert_array_equal(fa[k], fb[k],
                                              err_msg=f"req {i}: {k}")

    def test_numpy_backend_unaffected(self):
        """Direct-path (numpy backend) requests bypass the ring and
        stay bit-identical under any depth."""
        panels = self._traffic(11, n=4)
        sync = self._run(1, panels, backend="numpy")
        pipe = self._run(3, panels, backend="numpy")
        for a, b in zip(sync, pipe):
            fa, fb = _flat(a), _flat(b)
            for k in fa:
                np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)

    def test_zero_added_retraces_and_ring_drains(self):
        obs.reset()
        panels = self._traffic(12, n=8)
        cfg = ServeConfig(warmup=((16, 64), (32, 128)),
                          batch_window_ms=1.0, pipeline_depth=3,
                          sharded_buckets=False, pallas_buckets=False)
        with ConsensusService(cfg) as svc:
            warmed = obs.value("pyconsensus_jit_retraces_total",
                               entry="serve_bucket")
            for p in panels:
                svc.submit(reports=p).result(timeout=120)
            assert obs.value("pyconsensus_jit_retraces_total",
                             entry="serve_bucket") == warmed
            assert svc.pipeline_depth == 3
        # after drain the ring is empty
        assert (obs.value("pyconsensus_serve_inflight_dispatches")
                or 0) == 0
        assert obs.value("pyconsensus_serve_pipeline_depth") == 3

    def test_sharded_buckets_pipeline(self):
        """The mesh bucket class rides the ring too (8 virtual
        devices)."""
        panels = self._traffic(13, n=6)
        sync = self._run(1, panels, sharded_buckets=True)
        pipe = self._run(3, panels, sharded_buckets=True)
        for a, b in zip(sync, pipe):
            fa, fb = _flat(a), _flat(b)
            for k in fa:
                np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)

    def test_ring_not_starved_by_direct_traffic(self, rng):
        """Non-ring dispatches are synchronization points: an older
        in-flight ring result retires BEFORE a later direct-path
        request is served — sustained direct/pallas/session traffic
        (which keeps the queue non-empty, so the idle-tick drain never
        fires) must not leave a finished bucket result undelivered on
        the ring."""
        m, _ = collusion_reports(rng, 12, 48, liars=3, na_frac=0.1)
        direct = collusion_reports(rng, 6, 12, liars=2)[0]
        cfg = ServeConfig(warmup=((16, 64),), batch_window_ms=1.0,
                          pipeline_depth=4, sharded_buckets=False,
                          pallas_buckets=False)
        with ConsensusService(cfg) as svc:
            bucket_fut = svc.submit(reports=m)
            # the direct request is dispatched AFTER the bucket one by
            # the single batcher thread; the sync-point rule guarantees
            # the bucket result was retired before it was served, so
            # the ordering assertion below is deterministic, not a race
            svc.submit(reports=direct, backend="numpy").result(60)
            assert bucket_fut.done(), (
                "ring result not retired before a later direct-path "
                "dispatch — non-ring traffic starves the ring")
            bucket_fut.result(1)

    def test_auto_depth_resolves(self):
        cfg = ServeConfig(pipeline_depth=0, sharded_buckets=False,
                          pallas_buckets=False)
        svc = ConsensusService(cfg)
        assert svc.pipeline_depth >= 1      # tuned winner or fallback 2

    def test_negative_depth_refused(self):
        with pytest.raises(InputError):
            ConsensusService(ServeConfig(pipeline_depth=-1))


class TestDepthAutotune:
    def test_deterministic_sweep_and_cache_hit(self, tmp_path):
        from pyconsensus_tpu.tune import (autotune_pipeline_depth,
                                          depth_candidates,
                                          tuned_pipeline_depth)

        path = tmp_path / "cache.json"
        entry = autotune_pipeline_depth(12, 32, deterministic=True,
                                        path=path, dispatches=3)
        assert entry["value"] in depth_candidates()
        assert entry["mode"] == "deterministic"
        before = obs.value("pyconsensus_autotune_sweeps_total",
                           kind="pipeline_depth") or 0
        again = autotune_pipeline_depth(12, 32, deterministic=True,
                                        path=path, dispatches=3)
        assert again == entry
        assert (obs.value("pyconsensus_autotune_sweeps_total",
                          kind="pipeline_depth") or 0) == before
        assert tuned_pipeline_depth(32, path=path) == entry["value"]

    def test_fallback_without_cache(self, tmp_path):
        from pyconsensus_tpu.tune import tuned_pipeline_depth

        assert tuned_pipeline_depth(4096,
                                    path=tmp_path / "none.json") == 2

    def test_sweep_is_deterministic(self, tmp_path):
        from pyconsensus_tpu.tune import autotune_pipeline_depth

        a = autotune_pipeline_depth(12, 32, deterministic=True,
                                    path=tmp_path / "a.json",
                                    dispatches=3)
        b = autotune_pipeline_depth(12, 32, deterministic=True,
                                    path=tmp_path / "b.json",
                                    dispatches=3)
        assert a == b


class TestRoofline:
    def test_traffic_model_monotone(self):
        from pyconsensus_tpu.tune import resolution_traffic_bytes

        base = resolution_traffic_bytes(100, 1000, 1, sweeps=4)
        assert resolution_traffic_bytes(100, 1000, 4, sweeps=4) > base
        assert resolution_traffic_bytes(100, 1000, 1, sweeps=8) > base
        assert resolution_traffic_bytes(200, 1000, 1, sweeps=4) > base

    def test_bound_and_regime(self):
        from pyconsensus_tpu.tune import (bound_resolutions_per_sec,
                                          classify_regime)

        bound = bound_resolutions_per_sec(1e9, 1e6)
        assert bound == pytest.approx(1e3)
        assert classify_regime(900.0, bound) == "bandwidth-bound"
        assert classify_regime(10.0, bound) == "host-bound"
        assert classify_regime(1.0, 0.0) == "unknown"

    def test_measured_bandwidth_positive(self):
        from pyconsensus_tpu.tune import stream_bandwidth_bytes_per_s

        bw = stream_bandwidth_bytes_per_s(mbytes=4, repeats=2)
        assert bw > 1e8          # any real machine streams > 100 MB/s


#: a compiled-HLO module header WITH the donation alias table (the
#: no-trigger form) and the same module without it (the trigger)
_ALIASED_HLO = (
    "HloModule jit_padded_consensus, is_scheduled=true, "
    "input_output_alias={ {0}: (3, {}, may-alias), {2}: (4, {}, "
    "may-alias), {3}: (7, {}, may-alias), {8}: (1, {}, may-alias) }, "
    "entry_computation_layout={(f32[16,128]{1,0})->(f32[128]{0})}\n"
    "ENTRY main { ... }\n")
_UNALIASED_HLO = (
    "HloModule jit_padded_consensus, is_scheduled=true, "
    "entry_computation_layout={(f32[16,128]{1,0})->(f32[128]{0})}\n"
    "ENTRY main { ... }\n")


class TestAliasContract:
    def test_parser_reads_alias_table(self):
        from pyconsensus_tpu.analysis.contracts import \
            input_output_aliases

        aliases = input_output_aliases(_ALIASED_HLO)
        assert aliases == [(0, 3), (2, 4), (3, 7), (8, 1)]
        assert input_output_aliases(_UNALIASED_HLO) == []

    def test_check_artifact_trigger_and_no_trigger(self):
        from pyconsensus_tpu.analysis.contracts import check_artifact

        spec = {"name": "crafted", "shape": {"R": 16, "E": 128},
                "min_donated_aliases": 4, "forbid_f64": False,
                "forbid_host_callbacks": False}
        assert check_artifact("crafted", _ALIASED_HLO, spec) == []
        findings = check_artifact("crafted", _UNALIASED_HLO, spec)
        assert len(findings) == 1
        assert findings[0].rule == "CL306"
        assert "0 donated input buffer" in findings[0].message

    def test_live_contracts_green(self):
        """The real donated serve-bucket contracts hold on the live
        tree (the compiled modules actually alias)."""
        from pyconsensus_tpu.analysis.contracts import run_contracts

        findings = run_contracts(names=["serve-bucket",
                                        "serve-bucket-scaled-alias"])
        assert findings == []

    def test_live_aliases_cover_donated_args(self):
        """The compiled donated executable's alias table references
        only DONATED_ARGS parameter positions."""
        import jax

        from pyconsensus_tpu.analysis.contracts import \
            input_output_aliases

        p = serve_params(any_scaled=True)
        fn = sk.make_bucket_executable(p, donate=True)
        dt = jnp.asarray(0.0).dtype
        R, E = 16, 32
        args = (jax.ShapeDtypeStruct((R, E), dt),
                jax.ShapeDtypeStruct((R,), dt),
                jax.ShapeDtypeStruct((E,), bool),
                jax.ShapeDtypeStruct((E,), dt),
                jax.ShapeDtypeStruct((E,), dt),
                jax.ShapeDtypeStruct((R,), bool),
                jax.ShapeDtypeStruct((E,), bool),
                jax.ShapeDtypeStruct((E,), dt))
        txt = fn.lower(*args, p).compile().as_text()
        aliases = input_output_aliases(txt)
        assert len(aliases) == 4
        assert {param for _, param in aliases} <= set(sk.DONATED_ARGS)
