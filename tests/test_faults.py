"""ISSUE 4 chaos suite: fault injection, structured errors, graceful
degradation, crash-safe checkpointing, and retry.

The hard acceptance criteria live here: a ``kill -9`` mid-sweep followed
by a resume is bit-identical to an uninterrupted run; a corrupted
checkpoint chunk is detected by checksum and transparently recomputed; a
seeded NaN-storm fault plan yields finite outcomes with quarantined rows
reported, and replaying the same plan reproduces the run exactly."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from pyconsensus_tpu import Oracle, faults
from pyconsensus_tpu.faults import (CheckpointCorruptionError,
                                    ConsensusError, ConvergenceError,
                                    FaultPlan, InputError, NumericsError,
                                    SimulatedCrash)

from conftest import worker_env


@pytest.fixture(autouse=True)
def _always_disarm():
    """No chaos test may leak an armed plan into the rest of the suite."""
    yield
    faults.disarm()


CANONICAL = np.array([
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 0.0, 0.0, 0.0],
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 1.0, 1.0, 0.0],
    [0.0, 0.0, 1.0, 1.0],
    [0.0, 0.0, 1.0, 1.0],
])


# -- taxonomy --------------------------------------------------------------


class TestErrorTaxonomy:
    def test_codes_are_stable(self):
        assert ConsensusError.error_code == "PYC000"
        assert InputError.error_code == "PYC101"
        assert NumericsError.error_code == "PYC201"
        assert ConvergenceError.error_code == "PYC202"
        assert CheckpointCorruptionError.error_code == "PYC301"
        assert faults.ERROR_CODES["PYC301"] is CheckpointCorruptionError

    def test_backward_compatible_bases(self):
        """The taxonomy narrows what is raised without widening what
        must be caught: every pre-taxonomy except clause keeps working."""
        assert issubclass(InputError, ValueError)
        assert issubclass(CheckpointCorruptionError, ValueError)
        assert issubclass(NumericsError, ArithmeticError)
        assert issubclass(ConvergenceError, NumericsError)

    def test_context_and_code_in_message(self):
        e = InputError("bad row", row=3, column=7)
        assert e.context == {"row": 3, "column": 7}
        assert "[PYC101]" in str(e) and "bad row" in str(e)

    def test_crash_is_not_an_exception(self):
        """SimulatedCrash must escape `except Exception` recovery code —
        that is the whole point of modeling a SIGKILL."""
        assert issubclass(SimulatedCrash, BaseException)
        assert not issubclass(SimulatedCrash, Exception)


# -- the injection core ----------------------------------------------------


class TestFaultPlan:
    def test_disarmed_hooks_are_identity(self):
        arr = np.ones((3, 3))
        assert faults.corrupt("any.site", arr) is arr
        faults.fire("any.site")              # no-op, no error
        assert faults.active_plan() is None

    def test_occurrence_indexing(self):
        plan = FaultPlan(seed=0, rules=[
            {"site": "s", "kind": "raise", "occurrences": [2],
             "args": {"error": "os_error"}}])
        with faults.armed(plan):
            faults.fire("s")
            faults.fire("s")
            with pytest.raises(OSError):
                faults.fire("s")
            faults.fire("s")                 # max_fires=0 (unlimited) but
        assert plan.fired == [("s", 2, "raise")]   # occurrence 3 not listed

    def test_site_patterns_and_max_fires(self):
        plan = FaultPlan(seed=0, rules=[
            {"site": "sweep.chunk.*", "kind": "raise",
             "occurrences": [0, 1], "max_fires": 1}])
        with faults.armed(plan):
            with pytest.raises(OSError):
                faults.fire("sweep.chunk.write")
            faults.fire("sweep.chunk.write")     # capped by max_fires
            faults.fire("sweep.chunk.pre_commit")  # occ counters per SITE
        assert len(plan.fired) == 1

    def test_probability_is_seeded_and_deterministic(self):
        def run(seed):
            plan = FaultPlan(seed=seed, rules=[
                {"site": "p", "kind": "nan_storm", "probability": 0.5,
                 "max_fires": 0, "args": {"fraction": 1.0}}])
            hits = []
            with faults.armed(plan):
                for _ in range(32):
                    out = faults.corrupt("p", np.ones(4))
                    hits.append(bool(np.isnan(out).any()))
            return hits

        a, b = run(7), run(7)
        assert a == b                        # same seed -> same activations
        assert run(8) != a                   # different seed -> different
        assert 0 < sum(a) < 32               # and actually probabilistic

    def test_payload_determinism_is_interleaving_independent(self):
        """The poisoned cells at (site, occurrence k) must not depend on
        how often OTHER sites were hit in between — the property that
        makes a replayed plan reproduce a run whose unrelated call order
        shifted."""
        rules = [{"site": "a", "kind": "nan_storm", "occurrences": [1],
                  "args": {"fraction": 0.3}},
                 {"site": "b", "kind": "nan_storm", "occurrences": [0],
                  "args": {"fraction": 0.3}}]
        arr = np.ones((8, 8))
        with faults.armed(FaultPlan(seed=1, rules=rules)):
            faults.corrupt("a", arr)
            r1 = faults.corrupt("a", arr)
        with faults.armed(FaultPlan(seed=1, rules=rules)):
            faults.corrupt("a", arr)
            faults.corrupt("b", arr)         # extra interleaved site
            r2 = faults.corrupt("a", arr)
        np.testing.assert_array_equal(np.isnan(r1), np.isnan(r2))

    def test_json_round_trip(self, tmp_path):
        plan = FaultPlan(seed=9, rules=[
            {"site": "x", "kind": "inf_storm", "occurrences": [0, 3],
             "args": {"fraction": 0.1}},
            {"site": "y.*", "kind": "torn_write", "probability": 0.25},
        ])
        path = plan.save(tmp_path / "plan.json")
        loaded = FaultPlan.load(path)
        assert loaded.to_dict() == plan.to_dict()

    def test_unknown_keys_rejected(self):
        with pytest.raises(ValueError, match="unknown fault-rule keys"):
            FaultPlan(rules=[{"site": "s", "kind": "raise", "bogus": 1}])
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultPlan(rules=[{"site": "s", "kind": "explode"}])

    def test_corrupt_never_mutates_input(self):
        arr = np.ones((4, 4))
        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "s", "kind": "nan_storm",
                 "args": {"fraction": 1.0}}])):
            out = faults.corrupt("s", arr)
        assert np.isnan(out).all()
        assert not np.isnan(arr).any()

    def test_drop_shard_nans_one_column_block(self):
        arr = np.ones((4, 16))
        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "s", "kind": "drop_shard",
                 "args": {"shard": 1, "n_shards": 4}}])):
            out = faults.corrupt("s", arr)
        assert np.isnan(out[:, 4:8]).all()
        assert np.isfinite(out[:, :4]).all()
        assert np.isfinite(out[:, 8:]).all()

    def test_dict_payload_poisons_floats_only(self):
        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "s", "kind": "nan_storm",
                 "args": {"fraction": 1.0}}])):
            out = faults.corrupt("s", {"x": np.ones(3),
                                       "n": np.arange(3),
                                       "flag": np.asarray(True)})
        assert np.isnan(out["x"]).all()
        np.testing.assert_array_equal(out["n"], np.arange(3))
        assert out["flag"] == np.asarray(True)


# -- retry -----------------------------------------------------------------


class TestRetry:
    def test_transient_failure_recovers(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert faults.retry_call(flaky, base_delay=0.001) == "ok"
        assert len(calls) == 3

    def test_exhaustion_reraises_last(self):
        def always():
            raise OSError("still down")

        with pytest.raises(OSError, match="still down"):
            faults.retry_call(always, retries=2, base_delay=0.001)

    def test_deadline_bounds_total_time(self):
        calls = []

        def always():
            calls.append(1)
            raise OSError("down")

        t0 = time.monotonic()
        with pytest.raises(OSError):
            faults.retry_call(always, retries=50, base_delay=0.2,
                              max_delay=0.2, deadline=0.3)
        assert time.monotonic() - t0 < 2.0
        assert len(calls) < 10               # deadline cut the budget

    def test_corruption_is_not_retried(self):
        """Checkpoint corruption does not become valid by retrying —
        the taxonomy is deliberately outside the default retry_on."""
        calls = []

        def corrupt():
            calls.append(1)
            raise CheckpointCorruptionError("bad chunk")

        with pytest.raises(CheckpointCorruptionError):
            faults.retry_call(corrupt, base_delay=0.001)
        assert len(calls) == 1

    def test_jitter_is_deterministic(self):
        from pyconsensus_tpu.faults.retry import _sleep_for

        a = [_sleep_for(k, 0.05, 2.0, 3, "w") for k in range(4)]
        b = [_sleep_for(k, 0.05, 2.0, 3, "w") for k in range(4)]
        assert a == b
        assert a != [_sleep_for(k, 0.05, 2.0, 4, "w") for k in range(4)]
        # exponential envelope with jitter in [0.5x, 1x]
        for k, d in enumerate(a):
            assert 0.5 * min(2.0, 0.05 * 2 ** k) <= d <= min(2.0,
                                                             0.05 * 2 ** k)

    def test_decorator_form(self):
        calls = []

        @faults.retry(retries=3, base_delay=0.001)
        def flaky(x):
            calls.append(1)
            if len(calls) < 2:
                raise OSError("once")
            return x + 1

        assert flaky(1) == 2


# -- io --------------------------------------------------------------------


class TestIOFaults:
    def test_truncated_csv_row_is_structured(self, tmp_path):
        from pyconsensus_tpu.io import load_reports

        p = tmp_path / "r.csv"
        p.write_text("1,0,1\n1,0\n")         # truncated second row
        with pytest.raises(InputError) as ei:
            load_reports(p)
        # row AND width context (native parser may not expose columns)
        assert ei.value.context.get("row") == 1 or "row 1" in str(ei.value)

    def test_bad_field_names_row_and_column(self, tmp_path):
        from pyconsensus_tpu.io import _parse_csv_row

        with pytest.raises(InputError) as ei:
            _parse_csv_row("1,spam,0", "f.csv", 4)
        assert ei.value.context == {"path": "f.csv", "row": 4, "column": 1}

    def test_csv_to_npy_leaves_no_partial_file(self, tmp_path):
        from pyconsensus_tpu.io import csv_to_npy

        src = tmp_path / "r.csv"
        src.write_text("1,0,1\n1,bogus,0\n")
        with pytest.raises(InputError):
            csv_to_npy(src)
        assert not (tmp_path / "r.npy").exists()
        assert not list(tmp_path.glob("*.tmp*"))

    def test_torn_npy_write_detected_on_read(self, tmp_path):
        from pyconsensus_tpu.io import load_reports, save_reports

        plan = FaultPlan(seed=0, rules=[
            {"site": "io.write", "kind": "torn_write", "occurrences": [0],
             "args": {"keep_bytes": 40}}])
        with faults.armed(plan):
            save_reports(tmp_path / "r.npy", CANONICAL)
        assert plan.fired
        with pytest.raises(InputError, match="unreadable .npy"):
            load_reports(tmp_path / "r.npy")

    def test_injected_write_error_leaves_no_file(self, tmp_path):
        from pyconsensus_tpu.io import save_reports

        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "io.write", "kind": "raise"}])):
            with pytest.raises(OSError):
                save_reports(tmp_path / "r.npy", CANONICAL)
        assert not (tmp_path / "r.npy").exists()
        assert not list(tmp_path.glob("*.tmp*"))

    def test_atomic_write_keeps_previous_on_crash(self, tmp_path):
        from pyconsensus_tpu.io import save_reports

        save_reports(tmp_path / "r.npy", CANONICAL)
        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "io.write", "kind": "crash"}])):
            with pytest.raises(SimulatedCrash):
                save_reports(tmp_path / "r.npy", np.zeros((2, 2)))
        from pyconsensus_tpu.io import load_reports

        np.testing.assert_array_equal(load_reports(tmp_path / "r.npy"),
                                      CANONICAL)


# -- ledger ----------------------------------------------------------------


class TestLedgerFaults:
    def _ledger(self):
        from pyconsensus_tpu import ReputationLedger

        led = ReputationLedger(n_reporters=6, max_iterations=2)
        led.resolve(CANONICAL)
        return led

    def test_round_trip_still_exact(self, tmp_path):
        from pyconsensus_tpu import ReputationLedger

        led = self._ledger()
        led.save(tmp_path / "state.npz")
        back = ReputationLedger.load(tmp_path / "state.npz")
        np.testing.assert_array_equal(back.reputation, led.reputation)
        assert back.round == led.round and back.history == led.history

    @pytest.mark.parametrize("field,mutate", [
        ("reputation", lambda d: d.pop("reputation")),
        ("round", lambda d: d.pop("round")),
        ("history", lambda d: d.pop("history")),
        ("oracle_kwargs", lambda d: d.pop("oracle_kwargs")),
        ("format_version", lambda d: d.pop("format_version")),
        ("reputation", lambda d: d.update(
            reputation=np.full(6, np.nan))),
        ("reputation", lambda d: d.update(
            reputation=np.ones((2, 3)))),
        ("reputation", lambda d: d.update(
            reputation=-np.ones(6))),
        ("round", lambda d: d.update(round=np.int64(-3))),
        ("history", lambda d: d.update(history=np.frombuffer(
            b"{not json", dtype=np.uint8))),
    ])
    def test_corrupt_field_named(self, tmp_path, field, mutate):
        from pyconsensus_tpu import ReputationLedger

        led = self._ledger()
        led.save(tmp_path / "state.npz")
        with np.load(tmp_path / "state.npz") as data:
            tree = {k: data[k] for k in data.files}
        mutate(tree)
        np.savez(tmp_path / "bad.npz", **tree)
        with pytest.raises(CheckpointCorruptionError) as ei:
            ReputationLedger.load(tmp_path / "bad.npz")
        assert f"'{field}'" in str(ei.value)
        assert ei.value.context.get("field") == field

    def test_torn_checkpoint_file(self, tmp_path):
        from pyconsensus_tpu import ReputationLedger

        led = self._ledger()
        led.save(tmp_path / "state.npz")
        raw = (tmp_path / "state.npz").read_bytes()
        (tmp_path / "state.npz").write_bytes(raw[:len(raw) // 2])
        with pytest.raises(CheckpointCorruptionError, match="unreadable"):
            ReputationLedger.load(tmp_path / "state.npz")

    def test_crash_mid_save_keeps_previous(self, tmp_path):
        from pyconsensus_tpu import ReputationLedger

        led = self._ledger()
        led.save(tmp_path / "state.npz")
        before = led.reputation.copy()
        led.resolve(CANONICAL)
        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "ledger.save", "kind": "crash"}])):
            with pytest.raises(SimulatedCrash):
                led.save(tmp_path / "state.npz")
        back = ReputationLedger.load(tmp_path / "state.npz")
        np.testing.assert_array_equal(back.reputation, before)
        assert back.round == 1


# -- checkpointed sweep ----------------------------------------------------


def _sweep(tmp_path, name="ck", trials_per_chunk=2):
    from pyconsensus_tpu.sim import CheckpointedSweep, CollusionSimulator

    sim = CollusionSimulator(n_reporters=6, n_events=4, max_iterations=2)
    return sim, CheckpointedSweep(sim, [0.0, 0.4], [0.1], 4, seed=11,
                                  checkpoint_dir=tmp_path / name,
                                  trials_per_chunk=trials_per_chunk)


class TestSweepCrashSafety:
    def test_corrupted_chunk_detected_and_recomputed_on_resume(
            self, tmp_path):
        sim, sweep = _sweep(tmp_path)
        assert sweep.run(host_id=0, n_hosts=1) == sweep.n_chunks
        mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
        # flip bytes inside chunk 1's payload
        victim = sweep._chunk_path(1)
        raw = bytearray(victim.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        victim.write_bytes(bytes(raw))
        _, resumed = _sweep(tmp_path)
        ran = resumed.run(host_id=0, n_hosts=1)
        assert ran == 1                      # exactly the scrubbed chunk
        got = resumed.gather()
        np.testing.assert_array_equal(got["correct_rate"],
                                      mono["correct_rate"])

    def test_gather_transparently_recomputes_torn_chunk(self, tmp_path):
        sim, sweep = _sweep(tmp_path)
        sweep.run(host_id=0, n_hosts=1)
        mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
        victim = sweep._chunk_path(0)
        with open(victim, "r+b") as f:       # torn write: truncated zip
            f.truncate(victim.stat().st_size // 2)
        got = sweep.gather()                 # detected + recomputed inline
        np.testing.assert_array_equal(got["correct_rate"],
                                      mono["correct_rate"])
        with pytest.raises(CheckpointCorruptionError):
            # strict mode surfaces instead of recomputing
            with open(victim, "r+b") as f:
                f.truncate(victim.stat().st_size // 2)
            sweep.gather(recompute=False)

    def test_injected_torn_chunk_write(self, tmp_path):
        plan = FaultPlan(seed=0, rules=[
            {"site": "sweep.chunk.write", "kind": "torn_write",
             "occurrences": [1], "args": {"keep_bytes": 64}}])
        sim, sweep = _sweep(tmp_path)
        with faults.armed(plan):
            sweep.run(host_id=0, n_hosts=1)
        assert plan.fired
        mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
        _, resumed = _sweep(tmp_path)
        assert resumed.run(host_id=0, n_hosts=1) == 1   # torn one redone
        got = resumed.gather()
        np.testing.assert_array_equal(got["correct_rate"],
                                      mono["correct_rate"])

    def test_crash_before_commit_resumes_bit_identical(self, tmp_path):
        plan = FaultPlan(seed=0, rules=[
            {"site": "sweep.chunk.pre_commit", "kind": "crash",
             "occurrences": [1]}])
        sim, sweep = _sweep(tmp_path)
        with faults.armed(plan):
            with pytest.raises(SimulatedCrash):
                sweep.run(host_id=0, n_hosts=1)
        done = sweep.n_chunks - len(sweep.pending())
        assert done == 1                     # crashed computing chunk 2
        _, resumed = _sweep(tmp_path)
        resumed.run(host_id=0, n_hosts=1)
        got = resumed.gather()
        mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
        for key in ("correct_rate", "liar_rep_share"):
            np.testing.assert_array_equal(got[key], mono[key], err_msg=key)

    def test_crash_after_commit_resume_skips_chunk(self, tmp_path):
        plan = FaultPlan(seed=0, rules=[
            {"site": "sweep.chunk.post_commit", "kind": "crash",
             "occurrences": [0]}])
        sim, sweep = _sweep(tmp_path)
        with faults.armed(plan):
            with pytest.raises(SimulatedCrash):
                sweep.run(host_id=0, n_hosts=1)
        assert sweep.n_chunks - len(sweep.pending()) == 1   # committed
        _, resumed = _sweep(tmp_path)
        assert resumed.run(host_id=0, n_hosts=1) == resumed.n_chunks - 1
        got = resumed.gather()
        mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
        np.testing.assert_array_equal(got["correct_rate"],
                                      mono["correct_rate"])

    def test_transient_write_error_is_retried(self, tmp_path):
        plan = FaultPlan(seed=0, rules=[
            {"site": "sweep.chunk.write", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        sim, sweep = _sweep(tmp_path)
        with faults.armed(plan):
            assert sweep.run(host_id=0, n_hosts=1) == sweep.n_chunks
        got = sweep.gather()
        mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
        np.testing.assert_array_equal(got["correct_rate"],
                                      mono["correct_rate"])


_KILL_WORKER = textwrap.dedent("""
    import sys, time
    import numpy as np
    from pyconsensus_tpu.sim import CheckpointedSweep, CollusionSimulator

    sim = CollusionSimulator(n_reporters=6, n_events=4, max_iterations=2)
    sweep = CheckpointedSweep(sim, [0.0, 0.4], [0.1], 4, seed=11,
                              checkpoint_dir=sys.argv[1],
                              trials_per_chunk=2)
    print("READY", flush=True)
    for c in sweep.pending():
        sweep._run_chunk(c)
        print("CHUNK", c, flush=True)
        time.sleep(0.5)
""")


class TestKillMinusNine:
    def test_sigkill_mid_sweep_then_resume_bit_identical(self, tmp_path):
        """The acceptance criterion verbatim: a worker process is
        SIGKILLed mid-sweep (a real kill -9 — no Python cleanup runs),
        a fresh process resumes against the same checkpoint dir, and
        the gathered result is bit-identical to an uninterrupted
        monolithic run."""
        ckdir = tmp_path / "ck"
        script = tmp_path / "worker.py"
        script.write_text(_KILL_WORKER)
        env = worker_env()
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, str(script), str(ckdir)],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            # wait for the first committed chunk, then kill -9
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if ckdir.exists() and list(ckdir.glob("chunk_*.npz")):
                    break
                if proc.poll() is not None:
                    pytest.fail("worker exited before first chunk:\n"
                                + (proc.stdout.read() or ""))
                time.sleep(0.05)
            else:
                pytest.fail("worker never committed a chunk")
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == -signal.SIGKILL
        sim, resumed = _sweep(tmp_path)
        assert len(resumed.pending()) >= 1   # killed mid-sweep
        resumed.run(host_id=0, n_hosts=1)
        got = resumed.gather()
        mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
        for key in ("correct_rate", "capture_rate", "liar_rep_share"):
            np.testing.assert_array_equal(got[key], mono[key], err_msg=key)


# -- quarantine + degradation ---------------------------------------------


class TestQuarantine:
    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_inf_rows_quarantined_not_poisoning(self, backend):
        poisoned = CANONICAL.copy()
        poisoned[1, 2] = np.inf
        poisoned[4, 0] = -np.inf
        r = Oracle(reports=poisoned, backend=backend,
                   max_iterations=2).consensus()
        np.testing.assert_array_equal(r["quarantined_rows"], [1, 4])
        assert np.isfinite(r["agents"]["smooth_rep"]).all()
        assert np.isfinite(r["events"]["outcomes_final"]).all()
        # equivalent to the same matrix with those rows fully absent
        nanned = CANONICAL.copy()
        nanned[[1, 4]] = np.nan
        ref = Oracle(reports=nanned, backend=backend,
                     max_iterations=2).consensus()
        np.testing.assert_array_equal(r["events"]["outcomes_final"],
                                      ref["events"]["outcomes_final"])
        np.testing.assert_array_equal(r["agents"]["smooth_rep"],
                                      ref["agents"]["smooth_rep"])

    def test_quarantine_counter_emitted(self):
        from pyconsensus_tpu import obs

        before = obs.value("pyconsensus_quarantined_rows_total") or 0
        poisoned = CANONICAL.copy()
        poisoned[0, 0] = np.inf
        Oracle(reports=poisoned).consensus()
        assert obs.value("pyconsensus_quarantined_rows_total") == before + 1

    def test_sharded_front_end_quarantines(self):
        from pyconsensus_tpu.parallel import make_mesh, sharded_consensus

        poisoned = CANONICAL.copy()
        poisoned[2, 1] = np.inf
        out = sharded_consensus(poisoned, mesh=make_mesh(batch=1))
        np.testing.assert_array_equal(out["quarantined_rows"], [2])
        assert np.isfinite(np.asarray(out["smooth_rep"])).all()
        assert np.isfinite(np.asarray(out["outcomes_final"])).all()

    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_all_nan_matrix_stays_finite(self, backend):
        r = Oracle(reports=np.full((4, 3), np.nan),
                   backend=backend).consensus()
        assert np.isfinite(r["agents"]["smooth_rep"]).all()
        assert np.isfinite(r["events"]["outcomes_final"]).all()
        assert r["participation"] == pytest.approx(0.0)

    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_all_inf_matrix_degrades_to_all_nan(self, backend):
        r = Oracle(reports=np.full((4, 3), np.inf),
                   backend=backend).consensus()
        assert np.isfinite(r["agents"]["smooth_rep"]).all()
        np.testing.assert_array_equal(r["quarantined_rows"], [0, 1, 2, 3])

    @pytest.mark.parametrize("shape", [(0, 4), (4, 0), (0, 0)])
    def test_empty_matrix_is_structured_input_error(self, shape):
        with pytest.raises(InputError, match="empty"):
            Oracle(reports=np.zeros(shape))

    def test_inf_reputation_is_structured_input_error(self):
        with pytest.raises(InputError, match="finite"):
            Oracle(reports=CANONICAL,
                   reputation=[1.0, np.inf, 1.0, 1.0, 1.0, 1.0])


class TestFallbackChain:
    def test_nonfinite_jax_result_falls_back_and_recovers(self):
        """An internal NaN storm (injected at the host fetch) walks
        power -> eigh-gram and returns a finite result, with the hop
        counted in pyconsensus_fallbacks_total{from,to,reason}."""
        from pyconsensus_tpu import obs

        before = obs.value("pyconsensus_fallbacks_total",
                           **{"from": "power", "to": "eigh-gram",
                              "reason": "nonfinite_result"}) or 0
        plan = FaultPlan(seed=0, rules=[
            {"site": "oracle.raw_result", "kind": "nan_storm",
             "occurrences": [0], "args": {"fraction": 1.0}}])
        with faults.armed(plan):
            r = Oracle(reports=CANONICAL, backend="jax",
                       pca_method="power").consensus()
        assert plan.fired
        assert np.isfinite(r["agents"]["smooth_rep"]).all()
        assert np.isfinite(r["events"]["outcomes_final"]).all()
        after = obs.value("pyconsensus_fallbacks_total",
                          **{"from": "power", "to": "eigh-gram",
                             "reason": "nonfinite_result"})
        assert after == before + 1
        # the recovered outcomes match an uninjected resolution
        clean = Oracle(reports=CANONICAL, backend="jax",
                       pca_method="eigh-gram").consensus()
        np.testing.assert_array_equal(r["events"]["outcomes_final"],
                                      clean["events"]["outcomes_final"])

    def test_exhausted_chain_raises_convergence_error(self, monkeypatch):
        oracle = Oracle(reports=CANONICAL, backend="jax",
                        pca_method="power")
        bad = {"smooth_rep": np.full(6, np.nan)}
        monkeypatch.setattr(Oracle, "_resolve_once",
                            lambda self, update: bad)
        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "oracle.raw_result", "kind": "nan_storm",
                 "occurrences": [0], "args": {"fraction": 1.0}}])):
            with pytest.raises(ConvergenceError) as ei:
                oracle.consensus()
        assert ei.value.error_code == "PYC202"

    def test_exhausted_chain_on_exact_method_is_numerics_error(
            self, monkeypatch):
        oracle = Oracle(reports=CANONICAL, backend="jax",
                        pca_method="eigh-gram")
        bad = {"smooth_rep": np.full(6, np.nan)}
        monkeypatch.setattr(Oracle, "_resolve_once",
                            lambda self, update: bad)
        with faults.armed(FaultPlan(seed=0, rules=[
                {"site": "oracle.raw_result", "kind": "nan_storm",
                 "occurrences": [0], "args": {"fraction": 1.0}}])):
            with pytest.raises(NumericsError) as ei:
                oracle.consensus()
        assert not isinstance(ei.value, ConvergenceError)


class TestStreamingPanelFaults:
    def test_nan_storm_panels_resolve_finite(self):
        """NaN poisoning of streamed panels is semantically MORE MISSING
        DATA — the out-of-core path must absorb it, finitely."""
        from pyconsensus_tpu.models.pipeline import ConsensusParams
        from pyconsensus_tpu.parallel import streaming_consensus

        rng = np.random.default_rng(0)
        reports = rng.choice([0.0, 1.0], size=(12, 32))
        with faults.armed(FaultPlan(seed=1, rules=[
                {"site": "streaming.panel", "kind": "nan_storm",
                 "max_fires": 0, "occurrences": [0, 1, 2, 3],
                 "args": {"fraction": 0.2}}])):
            out = streaming_consensus(reports, panel_events=8,
                                      params=ConsensusParams())
        assert np.isfinite(out["smooth_rep"]).all()
        assert np.isfinite(out["outcomes_final"]).all()

    def test_inf_storm_fails_loudly_not_silently(self):
        """±Inf reaching the accumulators must surface as non-finite
        outputs (the documented loud-failure contract of the streamed
        spectrum) — never as a silently wrong but finite answer."""
        from pyconsensus_tpu.models.pipeline import ConsensusParams
        from pyconsensus_tpu.parallel import streaming_consensus

        rng = np.random.default_rng(0)
        reports = rng.choice([0.0, 1.0], size=(12, 32))
        with faults.armed(FaultPlan(seed=1, rules=[
                {"site": "streaming.panel", "kind": "inf_storm",
                 "occurrences": [0], "args": {"fraction": 0.05}}])):
            out = streaming_consensus(reports, panel_events=8,
                                      params=ConsensusParams())
        assert not np.isfinite(out["smooth_rep"]).all()


# -- NaN-storm fuzz (the seeded chaos extension) ---------------------------


class TestNaNStormFuzz:
    """Satellite: seeded FaultPlan NaN/Inf storms through BOTH backends,
    asserting finite, quarantine-consistent outputs — and exact
    replayability of each plan."""

    @pytest.mark.parametrize("seed", range(6))
    def test_storm_is_finite_consistent_and_replayable(self, seed):
        rng = np.random.default_rng(100 + seed)
        reports = rng.choice([0.0, 0.5, 1.0], size=(10, 8))
        plan_dict = {"seed": seed, "rules": [
            {"site": "oracle.reports", "kind": "nan_storm",
             "occurrences": [0], "args": {"fraction": 0.15}},
            {"site": "oracle.reports", "kind": "inf_storm",
             "occurrences": [1], "args": {"fraction": 0.1}},
        ]}

        def resolve(backend, occurrence_shift=0):
            plan = FaultPlan.from_dict(plan_dict)
            with faults.armed(plan):
                if occurrence_shift:          # consume occurrence 0
                    faults.corrupt("oracle.reports", reports)
                return Oracle(reports=reports, backend=backend,
                              max_iterations=2).consensus(), plan

        for occ in (0, 1):                    # NaN storm, then Inf storm
            r_np, p_np = resolve("numpy", occ)
            r_jax, p_jax = resolve("jax", occ)
            for r in (r_np, r_jax):
                assert np.isfinite(r["agents"]["smooth_rep"]).all()
                assert np.isfinite(r["events"]["outcomes_final"]).all()
            # identical injection on both backends -> identical
            # quarantine decisions
            np.testing.assert_array_equal(r_np["quarantined_rows"],
                                          r_jax["quarantined_rows"])
            assert p_np.fired == p_jax.fired
            # replay: the same plan reproduces the numpy run exactly
            r_again, _ = resolve("numpy", occ)
            np.testing.assert_array_equal(
                r_np["events"]["outcomes_final"],
                r_again["events"]["outcomes_final"])
            np.testing.assert_array_equal(r_np["agents"]["smooth_rep"],
                                          r_again["agents"]["smooth_rep"])


# -- CLI -------------------------------------------------------------------


class TestCLIFaultPlan:
    def test_fault_plan_run_and_summary(self, tmp_path, capsys):
        from pyconsensus_tpu.cli import main

        plan_path = tmp_path / "plan.json"
        plan_path.write_text(json.dumps({"seed": 5, "rules": [
            {"site": "oracle.reports", "kind": "inf_storm",
             "occurrences": [0], "args": {"fraction": 0.1}}]}))
        assert main(["--example", "--fault-plan", str(plan_path)]) == 0
        out = capsys.readouterr().out
        assert "injected faults" in out
        assert "oracle.reports #0: inf_storm" in out
        assert faults.active_plan() is None   # disarmed on exit

    def test_bad_plan_file_errors_cleanly(self, tmp_path):
        from pyconsensus_tpu.cli import main

        bad = tmp_path / "plan.json"
        bad.write_text("{не json")
        with pytest.raises(SystemExit):
            main(["--example", "--fault-plan", str(bad)])
        assert faults.active_plan() is None
