"""Sweep-plot helpers (sim.plots): render to files, validate structure.
matplotlib is available in CI; the helpers must also import cleanly
without rendering anything at module import time."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")

from pyconsensus_tpu.sim import (plot_retention_curves, plot_sweep_heatmap,
                                 save_sweep_report)


@pytest.fixture(scope="module")
def result():
    lf = np.array([0.0, 0.2, 0.4])
    var = np.array([0.0, 0.1])
    rng = np.random.default_rng(0)
    mean = {
        "correct_rate": np.clip(1.0 - lf[:, None] - var[None, :], 0, 1),
        "capture_rate": np.clip(lf[:, None] * var[None, :] * 4, 0, 1),
        "liar_rep_share": np.tile(lf[:, None] / 2, (1, 2)),
    }
    full = {k: np.repeat(v[:, :, None], 5, axis=2) for k, v in mean.items()}
    full["mean"] = mean
    full["liar_fractions"] = lf
    full["variances"] = var
    return full


def test_heatmap_axes(result):
    ax = plot_sweep_heatmap(result, metric="correct_rate")
    assert ax.get_xlabel().startswith("honest-reporter")
    assert len(ax.get_images()) == 1
    img = ax.get_images()[0].get_array()
    assert img.shape[:2] == (3, 2)
    matplotlib.pyplot.close(ax.figure)


def test_heatmap_unknown_metric(result):
    with pytest.raises(ValueError, match="metric"):
        plot_sweep_heatmap(result, metric="nope")


def test_retention_curves(result):
    ax = plot_retention_curves(result)
    assert len(ax.get_lines()) == 2            # one per variance level
    assert ax.get_legend() is not None         # >= 2 series -> legend
    matplotlib.pyplot.close(ax.figure)


def test_retention_too_many_levels(result):
    r = dict(result)
    r["variances"] = np.linspace(0, 0.4, 9)
    r["mean"] = {"liar_rep_share": np.zeros((3, 9))}
    with pytest.raises(ValueError, match="categorical budget"):
        plot_retention_curves(r)


def test_save_report(result, tmp_path):
    p = tmp_path / "sweep.png"
    out = save_sweep_report(result, p)
    assert out == p and p.exists() and p.stat().st_size > 10_000


def test_cli_plot_flag(tmp_path, capsys):
    from pyconsensus_tpu.cli import main
    p = tmp_path / "cli_sweep.png"
    main(["--simulate", "--trials", "5", "--reporters", "10",
          "--events", "6", "--plot", str(p)])
    assert p.exists()
    assert "sweep report" in capsys.readouterr().out
