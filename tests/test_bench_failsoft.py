"""The benchmark's fail-soft contract (VERDICT r1 item 1): ``bench.py``
must print exactly one parseable JSON line and exit 0 under EVERY backend
condition — BENCH_r01.json was an unparseable crash record because the
wedged axon tunnel hung ``import jax`` inside the old single-process
bench. These tests drive the real script as the driver does (a fresh
``python bench.py`` process) with the probe forced to fail, and assert
the degraded artifact contract: headline metric name, zero value,
explicit error, CPU smoke evidence."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

BENCH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"


def _run(args, timeout=600):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})
    return subprocess.run([sys.executable, str(BENCH), *args],
                          capture_output=True, text=True, timeout=timeout,
                          env=env)


@pytest.mark.slow
def test_degraded_path_always_emits_json():
    """Probe forced to fail (1 ms timeout kills the probe subprocess
    before the interpreter even starts) -> the parent must still exit 0
    with one JSON line carrying the headline metric, an explicit error,
    and a successful CPU smoke result."""
    r = _run(["--probe-timeout", "0.001"])
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.strip()]
    payload = json.loads(lines[-1])
    assert payload["metric"] == "consensus_resolutions_per_sec_10000x100000"
    assert payload["value"] == 0.0
    assert payload["vs_baseline"] == 0.0
    assert "probe timed out" in payload["error"]
    smoke = payload["degraded_cpu_smoke"]
    assert smoke is not None, "CPU smoke should succeed on this host"
    assert smoke["backend"] == "cpu"
    assert smoke["value"] > 0.0
    assert smoke["metric"].startswith("consensus_resolutions_per_sec_256x")


@pytest.mark.slow
def test_child_runs_real_measurement_on_cpu():
    """With a healthy (CPU) backend the parent relays the child's real
    measurement line — tiny shape so the full pipeline actually runs."""
    r = _run(["--reporters", "64", "--events", "256", "--repeats", "2",
              "--batches", "2", "--storage-dtype", ""])
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(r.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "consensus_resolutions_per_sec_64x256"
    assert payload["value"] > 0.0
    assert "error" not in payload
    assert payload["backend"] == "cpu"
