"""The benchmark's fail-soft contract (VERDICT r1 item 1): ``bench.py``
must print exactly one parseable JSON line and exit 0 under EVERY backend
condition — BENCH_r01.json was an unparseable crash record because the
wedged axon tunnel hung ``import jax`` inside the old single-process
bench. These tests drive the real script as the driver does (a fresh
``python bench.py`` process) with the probe forced to fail, and assert
the degraded artifact contract: headline metric name, zero value,
explicit error, CPU smoke evidence."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

BENCH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"


def _run(args, timeout=600):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})
    # these tests probe the ladder/JSON contract; the (1000-session)
    # economy block and the (1024x8192-session) incremental block have
    # their own suites and CI stages
    args = [*args, "--no-econ", "--no-incremental"]
    return subprocess.run([sys.executable, str(BENCH), *args],
                          capture_output=True, text=True, timeout=timeout,
                          env=env)


@pytest.mark.slow
def test_degraded_path_always_emits_json():
    """Probe forced to fail (1 ms timeout kills the probe subprocess
    before the interpreter even starts) -> the parent must still exit 0
    with one JSON line carrying the headline metric, an explicit error,
    and a successful CPU smoke result."""
    r = _run(["--probe-timeout", "0.001"])
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.strip()]
    payload = json.loads(lines[-1])
    assert payload["metric"] == "consensus_resolutions_per_sec_10000x100000"
    assert payload["value"] == 0.0
    assert payload["vs_baseline"] == 0.0
    assert "probe timed out" in payload["error"]
    smoke = payload["degraded_cpu_smoke"]
    assert smoke is not None, "CPU smoke should succeed on this host"
    assert smoke["backend"] == "cpu"
    assert smoke["value"] > 0.0
    assert smoke["metric"].startswith("consensus_resolutions_per_sec_256x")
    # honesty contract (VERDICT r2 weak #6): a toy-shape smoke inside a
    # failed artifact must not carry a number that reads as a 97x win
    assert smoke["vs_baseline"] is None
    assert "note" in smoke


@pytest.mark.slow
def test_child_runs_real_measurement_on_cpu():
    """With a healthy (CPU) backend the parent relays the child's real
    measurement line — tiny shape so the full pipeline actually runs."""
    r = _run(["--reporters", "64", "--events", "256", "--repeats", "2",
              "--batches", "2", "--storage-dtype", ""])
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(r.stdout.strip().splitlines()[-1])
    # explicit f32 storage is suffixed out of the headline metric series
    assert payload["metric"] == "consensus_resolutions_per_sec_64x256_f32"
    assert payload["value"] > 0.0
    assert "error" not in payload
    assert payload["backend"] == "cpu"


@pytest.mark.slow
def test_ladder_degrades_within_backend_before_cpu_smoke():
    """Round-3 ladder contract: a rung-0 failure must retry WITHIN the
    device backend (f32 storage, then pure-XLA) instead of zeroing the
    artifact. Forced here with an int8 storage request the CPU backend's
    front-end rejects (the fused gate is closed off-TPU) — rung 1 strips
    the storage override and must succeed, and the JSON must carry the
    rung tag plus the rung-0 error."""
    r = _run(["--reporters", "64", "--events", "256", "--repeats", "2",
              "--batches", "2", "--storage-dtype", "int8"])
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(r.stdout.strip().splitlines()[-1])
    assert payload["value"] > 0.0, payload
    assert payload["rung"] == "storage-f32"
    assert len(payload["rung_errors"]) == 1
    assert "int8" in payload["rung_errors"][0]
    assert payload["backend"] == "cpu"


@pytest.mark.slow
def test_no_pallas_rung_runs_pure_xla():
    """--no-pallas must produce a working measurement with every Pallas
    gate closed (the ladder's last device rung)."""
    r = _run(["--reporters", "64", "--events", "256", "--repeats", "2",
              "--batches", "2", "--storage-dtype", "", "--no-pallas"])
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(r.stdout.strip().splitlines()[-1])
    assert payload["value"] > 0.0
    assert "error" not in payload


@pytest.mark.slow
def test_gate_decisions_logged_on_every_run():
    """BENCH-GATE lines must reach stderr so a driver-side failure is
    diagnosable (VERDICT r2 next-round #1)."""
    r = _run(["--reporters", "64", "--events", "256", "--repeats", "2",
              "--batches", "2"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "BENCH-GATE: storage_dtype auto ->" in r.stderr
    assert "BENCH-GATE: resolved storage_dtype=" in r.stderr
