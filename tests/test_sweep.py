"""Algorithm-variant sweep (sweep.compare_algorithms) — the EP-analogue
concurrent dispatch (SURVEY.md §2 parallelism table)."""

import numpy as np
import pytest

from pyconsensus_tpu import (ALGORITHMS, Oracle, compare_algorithms,
                             disagreement_matrix)


@pytest.fixture
def reports(rng):
    truth = rng.choice([0.0, 1.0], size=12)
    reports = np.tile(truth, (16, 1))
    flip = rng.random((12, 12)) < 0.1
    reports[:12] = np.abs(reports[:12] - flip)
    reports[12:] = 1.0 - truth
    return reports


def test_all_variants_match_serial(rng, reports):
    swept = compare_algorithms(reports, max_iterations=2)
    assert set(swept) == set(ALGORITHMS)
    for algo, res in swept.items():
        serial = Oracle(reports=reports, algorithm=algo, backend="jax",
                        max_iterations=2).consensus()
        np.testing.assert_array_equal(
            res["events"]["outcomes_final"],
            serial["events"]["outcomes_final"], err_msg=algo)
        np.testing.assert_allclose(res["agents"]["smooth_rep"],
                                   serial["agents"]["smooth_rep"],
                                   atol=1e-10, err_msg=algo)


def test_subset_and_order(rng, reports):
    swept = compare_algorithms(reports, algorithms=["k-means", "sztorc"])
    assert list(swept) == ["k-means", "sztorc"]


def test_disagreement_matrix(rng, reports):
    swept = compare_algorithms(reports, algorithms=["sztorc", "ica"])
    m = disagreement_matrix(swept)
    assert m.shape == (2, 2)
    assert m[0, 0] == 0 and m[1, 1] == 0
    assert m[0, 1] == m[1, 0]


def test_unknown_algorithm_rejected(reports):
    with pytest.raises(ValueError, match="unknown algorithm"):
        compare_algorithms(reports, algorithms=["pca2000"])


def test_kwargs_passthrough(rng, reports):
    reports = reports.copy()
    reports[0, 0] = np.nan
    swept = compare_algorithms(reports, algorithms=["sztorc"],
                               max_iterations=3, alpha=0.2)
    assert swept["sztorc"]["agents"]["smooth_rep"].shape == (16,)
