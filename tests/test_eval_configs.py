"""The five BASELINE.json eval configs, each at its stated shape
(BASELINE.md table; SURVEY.md §6). One test class per config, exercising
both backends where the config calls for parity."""

import numpy as np
import pytest

from conftest import collusion_reports as majority_matrix
from pyconsensus_tpu import Oracle
from pyconsensus_tpu.sim import CollusionSimulator


class TestConfig1PCA50x25:
    """Config 1: PCA, 50 reporters x 25 binary events, dense, uniform
    reputation — outcomes bit-identical across backends."""

    def test_dense_binary_parity(self, rng):
        reports, truth = majority_matrix(rng, R=50, E=25, liars=12)
        r_np = Oracle(reports=reports, backend="numpy").consensus()
        r_j = Oracle(reports=reports, backend="jax").consensus()
        np.testing.assert_array_equal(r_np["events"]["outcomes_final"],
                                      r_j["events"]["outcomes_final"])
        np.testing.assert_allclose(r_j["agents"]["smooth_rep"],
                                   r_np["agents"]["smooth_rep"], atol=1e-9)
        # the honest majority resolves the truth
        assert np.array_equal(r_np["events"]["outcomes_final"], truth)

    def test_uniform_reputation_default(self, rng):
        reports, _ = majority_matrix(rng, R=50, E=25, liars=12)
        r = Oracle(reports=reports).consensus()
        np.testing.assert_allclose(r["agents"]["old_rep"], 1.0 / 50)


class TestConfig2ScaledCategoricalNA:
    """Config 2: scaled + categorical events, event_bounds, NA
    interpolation, reputation-weighted resolution."""

    def test_mixed_matrix(self, rng):
        R = 12
        binary = rng.choice([0.0, 1.0], size=(R, 3))
        categorical = rng.choice([0.0, 0.5, 1.0], size=(R, 2))
        scaled = rng.uniform(100.0, 500.0, size=(R, 2))
        reports = np.concatenate([binary, categorical, scaled], axis=1)
        reports[rng.random(reports.shape) < 0.15] = np.nan
        bounds = [None] * 5 + [{"scaled": True, "min": 0.0, "max": 600.0}] * 2
        reputation = rng.random(R) + 0.2
        out = {}
        for backend in ("numpy", "jax"):
            r = Oracle(reports=reports, event_bounds=bounds,
                       reputation=reputation, backend=backend).consensus()
            filled = r["filled"]
            assert not np.isnan(np.asarray(filled, dtype=float)).any()
            final = np.asarray(r["events"]["outcomes_final"], dtype=float)
            # binary/categorical snap to {0, .5, 1}; scaled stay in bounds
            assert np.isin(final[:5], [0.0, 0.5, 1.0]).all()
            assert ((final[5:] >= 0.0) & (final[5:] <= 600.0)).all()
            out[backend] = final
        np.testing.assert_array_equal(out["numpy"][:5], out["jax"][:5])
        np.testing.assert_allclose(out["jax"][5:], out["numpy"][5:],
                                   rtol=1e-9)


class TestConfig3IterativeSztorc:
    """Config 3: iterative reputation redistribution to convergence
    (max_iterations > 1, smooth + catch)."""

    def test_converges_and_matches(self, rng):
        # the redistribution map's contraction factor approaches 1 near its
        # fixed point (per-step delta plateaus ~1e-3 on matrices like this),
        # so "to convergence" means a 1e-3 successive-change tolerance —
        # tighter tolerances may never trigger, for the reference's loop too
        reports, _ = majority_matrix(rng, R=30, E=15, liars=8)
        r_np = Oracle(reports=reports, backend="numpy", max_iterations=100,
                      convergence_tolerance=1e-3).consensus()
        r_j = Oracle(reports=reports, backend="jax", max_iterations=100,
                     convergence_tolerance=1e-3).consensus()
        assert r_np["convergence"] and bool(r_j["convergence"])
        assert r_np["iterations"] > 1
        assert int(r_j["iterations"]) == r_np["iterations"]
        np.testing.assert_array_equal(r_np["events"]["outcomes_final"],
                                      r_j["events"]["outcomes_final"])
        np.testing.assert_allclose(r_j["agents"]["smooth_rep"],
                                   r_np["agents"]["smooth_rep"], atol=1e-8)

    def test_iteration_sharpens_reputation(self, rng):
        reports, _ = majority_matrix(rng, R=30, E=15, liars=8)
        one = Oracle(reports=reports, max_iterations=1).consensus()
        many = Oracle(reports=reports, max_iterations=25).consensus()
        # iterating concentrates reputation on the honest majority
        assert (many["agents"]["smooth_rep"][:22].sum()
                >= one["agents"]["smooth_rep"][:22].sum())


class TestConfig4ClusteringVariants:
    """Config 4: clustering consensus variants — k-means / hierarchical /
    DBSCAN (hybrid + fully-jit) over reporter rows."""

    @pytest.mark.parametrize("algo,kwargs", [
        ("k-means", {"num_clusters": 2}),
        ("hierarchical", {"hierarchy_threshold": 1.5}),
        ("dbscan", {"dbscan_eps": 1.0, "dbscan_min_samples": 2}),
        ("dbscan-jit", {"dbscan_eps": 1.0, "dbscan_min_samples": 2}),
    ])
    def test_variant_detects_colluders(self, rng, algo, kwargs):
        reports, truth = majority_matrix(rng, R=24, E=12, liars=6)
        r = Oracle(reports=reports, algorithm=algo, backend="jax",
                   max_iterations=3, **kwargs).consensus()
        rep = r["agents"]["smooth_rep"]
        assert rep.sum() == pytest.approx(1.0)
        assert rep[:18].mean() > rep[18:].mean()
        out = np.asarray(r["events"]["outcomes_final"], dtype=float)
        # no event captured by the colluders; marginal events may land on
        # the 0.5 ambiguous band, everything else resolves to truth
        assert not np.any(out == 1.0 - truth)
        assert (out == truth).mean() >= 0.9


class TestHybridClusteringAtScale:
    """The hybrid host-clustering variants at a NON-toy reporter count
    (docs/API.md scale-envelope table; VERDICT r1 weak item 7): R=2000
    materializes a 2000x2000 host distance matrix and runs the native
    NN-chain / BFS loops on real workloads, not 24-row toys. Correctness
    bar matches config 4: colluders detected, no captured outcomes."""

    # cut distances scale with the matrix geometry: honest reporters with
    # 10% flip noise sit ~sqrt(2 * 0.1 * 0.9 * E) ~= 2.4 apart at E=32,
    # colluders (identical rows) at 0, honest-vs-liar at ~5 — the cut must
    # sit between 2.4 and 5 or the noisy honest majority shatters into
    # singletons while the tight liar block forms the one big cluster
    @pytest.mark.parametrize("algo,kwargs", [
        ("hierarchical", {"hierarchy_threshold": 3.5}),
        ("dbscan", {"dbscan_eps": 3.0, "dbscan_min_samples": 4}),
    ])
    def test_r2000(self, rng, algo, kwargs):
        R, E, liars = 2000, 32, 400
        reports, truth = majority_matrix(rng, R=R, E=E, liars=liars)
        r = Oracle(reports=reports, algorithm=algo, backend="jax",
                   **kwargs).consensus()
        rep = r["agents"]["smooth_rep"]
        assert rep.sum() == pytest.approx(1.0)
        honest = R - liars
        assert rep[:honest].mean() > rep[honest:].mean()
        out = np.asarray(r["events"]["outcomes_final"], dtype=float)
        assert not np.any(out == 1.0 - truth)
        assert (out == truth).mean() >= 0.9


class TestConfig5MonteCarlo10k:
    """Config 5: Monte-Carlo collusion sweep, vmap over
    (liar_fraction x variance x seed), 10k trials in one batched call."""

    def test_10k_trials_one_dispatch(self):
        sim = CollusionSimulator(n_reporters=12, n_events=6,
                                 max_iterations=1, power_iters=16)
        res = sim.run([0.0, 0.1, 0.2, 0.3, 0.4], [0.0, 0.1], 1000, seed=0)
        assert int(np.prod(res["correct_rate"].shape)) == 10_000
        assert np.isfinite(res["correct_rate"]).all()
        # no-liar cells resolve essentially everything correctly
        assert res["mean"]["correct_rate"][0].min() > 0.95
        # heavy collusion degrades capture resistance monotonically-ish
        assert (res["mean"]["liar_rep_share"][4] >=
                res["mean"]["liar_rep_share"][1]).all()

    @pytest.mark.parametrize("n_trials", [5, 16])
    def test_mesh_sweep_bit_identical(self, n_trials):
        """The trial axis sharded over the 8-device mesh (SURVEY §7
        replicate-and-vmap per chip) must reproduce the single-device
        sweep BIT-identically — including the padded non-divisible
        trial count (2 x 2 x 5 = 20 -> pad to 24)."""
        from pyconsensus_tpu.parallel import make_mesh

        kw = dict(n_reporters=10, n_events=6, max_iterations=2,
                  power_iters=16)
        lf, var = [0.0, 0.3], [0.0, 0.1]
        plain = CollusionSimulator(**kw).run(lf, var, n_trials, seed=3)
        meshed = CollusionSimulator(
            mesh=make_mesh(batch=8, event=1), **kw).run(
                lf, var, n_trials, seed=3)
        for k in ("correct_rate", "liar_rep_share", "capture_rate"):
            if k in plain:
                np.testing.assert_array_equal(plain[k], meshed[k])

    def test_mesh_rounds_sweep_bit_identical(self):
        """RoundsSimulator's per-round trajectory metrics (trailing
        axes) survive the trial-axis sharding + padding unchanged."""
        from pyconsensus_tpu.parallel import make_mesh
        from pyconsensus_tpu.sim import RoundsSimulator

        kw = dict(n_rounds=3, n_reporters=10, n_events=6,
                  max_iterations=1, power_iters=16)
        lf, var = [0.0, 0.3], [0.1]
        plain = RoundsSimulator(**kw).run(lf, var, 5, seed=1)
        meshed = RoundsSimulator(
            mesh=make_mesh(batch=8, event=1), **kw).run(lf, var, 5, seed=1)
        np.testing.assert_array_equal(plain["liar_rep_share"],
                                      meshed["liar_rep_share"])
        assert plain["liar_rep_share"].shape[-1] == 3    # rounds axis
