"""Drop-in compatibility surface of the ``pyconsensus`` package alias
(SURVEY.md §1 packaging layer; §2 #12 console entry; BASELINE.json symbol
list). A user of the reference should be able to switch imports and find
everything: the ``Oracle`` class, the module-level pipeline helpers, and
``python -m pyconsensus``."""

import runpy
import sys

import numpy as np
import pytest

import pyconsensus

CANONICAL = np.array([[1, 1, 0, 0],
                      [1, 0, 0, 0],
                      [1, 1, 0, 0],
                      [1, 1, 1, 0],
                      [0, 0, 1, 1],
                      [0, 0, 1, 1]], dtype=float)


class TestImportSurface:
    def test_oracle_resolves(self):
        result = pyconsensus.Oracle(reports=CANONICAL,
                                    max_iterations=5).consensus()
        np.testing.assert_array_equal(
            result["events"]["outcomes_final"], [1.0, 1.0, 0.0, 0.0])

    def test_reference_symbols_exported(self):
        # the BASELINE.json-anchored function surface, callable as the
        # reference exposed it
        for name in ("interpolate", "weighted_cov", "weighted_prin_comp",
                     "catch", "smooth", "row_reward_weighted",
                     "weighted_median", "normalize", "main",
                     "ALGORITHMS", "BACKENDS", "__version__"):
            assert hasattr(pyconsensus, name), name

    def test_helper_pipeline_matches_oracle(self):
        """Driving the module-level helpers by hand reproduces the Oracle's
        one-iteration resolution on the canonical matrix."""
        rep = np.full(6, 1.0 / 6.0)
        scaled = np.zeros(4, dtype=bool)
        filled = pyconsensus.interpolate(CANONICAL, rep, scaled, 0.1)
        np.testing.assert_array_equal(filled, CANONICAL)  # dense: identity
        cov, dev = pyconsensus.weighted_cov(filled, rep)
        assert cov.shape == (4, 4)
        loading, scores = pyconsensus.weighted_prin_comp(filled, rep)
        assert loading.shape == (4,) and scores.shape == (6,)
        from pyconsensus_tpu.ops.numpy_kernels import direction_fixed_scores
        adj = direction_fixed_scores(scores, filled, rep)
        this_rep = pyconsensus.row_reward_weighted(adj, rep)
        smooth_rep = pyconsensus.smooth(this_rep, rep, alpha=0.1)
        result = pyconsensus.Oracle(reports=CANONICAL, alpha=0.1).consensus()
        np.testing.assert_allclose(result["agents"]["smooth_rep"], smooth_rep,
                                   atol=1e-12)

    def test_catch_and_median(self):
        assert pyconsensus.catch(0.2, 0.1) == 0.0
        assert pyconsensus.catch(0.55, 0.1) == 0.5
        assert pyconsensus.weighted_median([1.0, 2.0, 3.0],
                                           [0.1, 0.1, 0.8]) == 3.0


class TestModuleEntry:
    def test_python_dash_m_pyconsensus(self, capsys, monkeypatch):
        """``python -m pyconsensus --example`` runs the reference's demo
        (exercised in-process via runpy; conftest already pinned the CPU
        platform)."""
        monkeypatch.setattr(sys, "argv", ["pyconsensus", "--example",
                                          "--backend", "numpy"])
        with pytest.raises(SystemExit) as exc:
            runpy.run_module("pyconsensus", run_name="__main__")
        assert exc.value.code == 0
        assert "Example (dense binary)" in capsys.readouterr().out
