"""Economy worker for the CI mid-economy SIGKILL stage (ISSUE 11) —
runs a fleet-backed adversarial economy on a given replication-log
directory, announcing round boundaries on stdout so the driver can
``kill -9`` it mid-round. The scenario lives HERE (``make_scenario``)
so the driver's uninterrupted reference run and the resumed run are
guaranteed the identical economy.

Usage: ``python tests/econ_worker.py <log_root>``
"""

import json
import sys

from pyconsensus_tpu.econ import MarketEconomy, build_scenario
from pyconsensus_tpu.serve import ServeConfig
from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig

ROUNDS = 3


def make_scenario():
    return build_scenario(seed=47, rounds=ROUNDS,
                          strategies=("camouflage", "slow_drip"),
                          markets_per_strategy=2, concurrency=4)


def make_fleet(log_root):
    return ConsensusFleet(FleetConfig(
        n_workers=2, log_dir=str(log_root),
        worker=ServeConfig(batch_window_ms=1.0))).start(warmup=False)


def main(log_root: str) -> int:
    fleet = make_fleet(log_root)
    econ = MarketEconomy(fleet, make_scenario())
    econ.start()
    for k in range(ROUNDS):
        print(f"ROUND {k}", flush=True)
        econ.run_round(k)
        print(f"ROUND {k} done", flush=True)
    result = econ.result()
    fleet.close(drain=True)
    print(json.dumps({"digest": result["mechanism_digest"]}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
