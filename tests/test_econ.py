"""Adversarial market economy (ISSUE 11): strategy determinism, panel
generation discipline, the multi-round harness against the live serve
tier, resume-from-log, the scoreboard, fault sites, plots, and the CLI.

The load-bearing contracts:

- every strategy schedule is bit-identical under replay from its
  ``(seed, strategy, round)`` keys, interleaving-independent across
  concurrent markets, and host-numpy (cross-backend identical) — the
  ``faults/plan.py`` payload-PRNG discipline;
- the WHOLE economy is bit-identical under the same scenario seed:
  across replays, across thread-pool widths, across the single-service
  vs fleet front doors, and across a kill/resume through the
  replication log;
- overload sheds delay resolutions but never change their bits.
"""

import json
import pathlib

import numpy as np
import pytest

from pyconsensus_tpu import faults, obs
from pyconsensus_tpu.econ import (STRATEGIES, MarketEconomy, MarketSpec,
                                  RoundPlan, Scenario, StrategyContext,
                                  build_scenario, make_strategy,
                                  mechanism_digest, round_panel,
                                  split_blocks, strategy_rng)
from pyconsensus_tpu.faults import InputError
from pyconsensus_tpu.serve import ConsensusService, ServeConfig
from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _under_lock_witness(lock_witness):
    """The economy drives the serve/fleet lock surface concurrently;
    every test here runs under the runtime lock witness (ISSUE 9)."""
    yield


@pytest.fixture(autouse=True)
def _under_digest_witness(digest_witness):
    """And under the runtime digest witness (ISSUE 17): every ledger
    round and mechanism digest the economy produces must replay
    bit-identical from the durable artifact / under reordered input —
    the dynamic mirror of Layer 6's bit-determinism proof."""
    yield


def _ctx(strategy="camouflage", market="m-0", round_idx=0, R=12,
         n_cartel=4, rep=None, seed=0):
    cartel = tuple(range(R - n_cartel, R))
    if rep is None:
        rep = np.full(R, 1.0 / R)
    return StrategyContext(seed=seed, market=market, round_idx=round_idx,
                           n_reporters=R, cartel=cartel,
                           reputation=np.asarray(rep, dtype=np.float64),
                           stake=n_cartel / R)


def _eroded(R=12, n_cartel=4, erosion=0.5):
    """A reputation vector whose cartel share sits at
    ``stake * (1 - erosion)``."""
    stake = n_cartel / R
    share = stake * (1.0 - erosion)
    rep = np.full(R, (1.0 - share) / (R - n_cartel))
    rep[R - n_cartel:] = share / n_cartel
    return rep


def _svc(**kwargs):
    kwargs.setdefault("batch_window_ms", 1.0)
    return ConsensusService(ServeConfig(**kwargs)).start(warmup=False)


SMALL = dict(strategies=("camouflage", "flash_crowd"),
             markets_per_strategy=2, rounds=2, concurrency=4)


def _run_service(scenario, **svc_kwargs):
    svc = _svc(**svc_kwargs)
    try:
        return MarketEconomy(svc, scenario).run()
    finally:
        svc.close(drain=True)


def _run_fleet(scenario, log_dir, n_workers=2):
    fleet = ConsensusFleet(FleetConfig(
        n_workers=n_workers, log_dir=str(log_dir),
        worker=ServeConfig(batch_window_ms=1.0, warmup=()))).start(
        warmup=False)
    try:
        return MarketEconomy(fleet, scenario).run()
    finally:
        fleet.close(drain=True)


# ------------------------------------------------------------ strategies


class TestStrategyDeterminism:
    def test_rng_keyed_and_stable(self):
        a = strategy_rng(3, "camouflage", "m-1", 2, "truth").random(8)
        b = strategy_rng(3, "camouflage", "m-1", 2, "truth").random(8)
        assert np.array_equal(a, b)

    @pytest.mark.parametrize("knob", ["seed", "strategy", "market",
                                      "round", "tag"])
    def test_rng_distinct_per_key_component(self, knob):
        base = dict(seed=3, strategy="camouflage", market="m-1",
                    round_idx=2, tag="truth")
        other = dict(base)
        other[{"seed": "seed", "strategy": "strategy",
               "market": "market", "round": "round_idx",
               "tag": "tag"}[knob]] = (4 if knob in ("seed", "round")
                                       else "other")
        a = strategy_rng(base["seed"], base["strategy"], base["market"],
                         base["round_idx"], base["tag"]).random(8)
        b = strategy_rng(other["seed"], other["strategy"],
                         other["market"], other["round_idx"],
                         other["tag"]).random(8)
        assert not np.array_equal(a, b)

    @pytest.mark.parametrize("name", sorted(STRATEGIES))
    def test_plan_replay_bit_identical(self, name):
        # two FRESH strategy objects, the same (seed, strategy, round)
        # key and ledger observation -> the identical plan, including
        # every array-valued field
        for rep in (None, _eroded(erosion=0.3), _eroded(erosion=0.9)):
            for k in range(4):
                ctx = _ctx(strategy=name, round_idx=k, rep=rep)
                p1 = make_strategy(name).plan_round(ctx)
                p2 = make_strategy(name).plan_round(ctx)
                assert p1 == p2

    @pytest.mark.parametrize("name", sorted(STRATEGIES))
    def test_plan_interleaving_independent(self, name):
        # planning market A then B gives A the same schedule as
        # planning B then A — no hidden shared state
        s = make_strategy(name)
        a1 = s.plan_round(_ctx(strategy=name, market="a"))
        b1 = s.plan_round(_ctx(strategy=name, market="b"))
        s2 = make_strategy(name)
        b2 = s2.plan_round(_ctx(strategy=name, market="b"))
        a2 = s2.plan_round(_ctx(strategy=name, market="a"))
        assert a1 == a2 and b1 == b2

    def test_unknown_strategy_and_params_fail_loudly(self):
        with pytest.raises(ValueError, match="unknown strategy"):
            make_strategy("nope")
        with pytest.raises(ValueError, match="unknown 'camouflage'"):
            make_strategy("camouflage", zeal=2)


class TestStrategyBehavior:
    def test_camouflage_backs_off_after_catch(self):
        fresh = make_strategy("camouflage").plan_round(_ctx())
        assert fresh.liars and fresh.lie_fraction > 0
        caught = make_strategy("camouflage").plan_round(
            _ctx(rep=_eroded(erosion=0.5)))
        assert caught.liars == () and caught.lie_fraction == 0.0
        assert "backoff" in caught.note

    def test_camouflage_lie_shrinks_with_erosion(self):
        mild = make_strategy("camouflage", backoff=0.9).plan_round(
            _ctx(rep=_eroded(erosion=0.05)))
        fresh = make_strategy("camouflage", backoff=0.9).plan_round(
            _ctx())
        assert mild.lie_fraction < fresh.lie_fraction

    def test_sybil_rotates_waves_and_parks_the_rest(self):
        s = make_strategy("sybil_split", waves=2)
        p0 = s.plan_round(_ctx(strategy="sybil_split", round_idx=0))
        p1 = s.plan_round(_ctx(strategy="sybil_split", round_idx=1))
        p2 = s.plan_round(_ctx(strategy="sybil_split", round_idx=2))
        assert set(p0.liars).isdisjoint(p1.liars)
        assert p0.liars == p2.liars            # the wave cycle
        for p in (p0, p1):
            assert set(p.liars) | set(p.abstain) == set(_ctx().cartel)
            assert set(p.liars).isdisjoint(p.abstain)

    def test_churn_exits_after_catch_and_reenters(self):
        s = make_strategy("reporter_churn")
        lying = s.plan_round(_ctx(strategy="reporter_churn"))
        assert lying.liars and not lying.abstain
        exited = s.plan_round(_ctx(strategy="reporter_churn",
                                   rep=_eroded(erosion=0.4)))
        assert exited.liars == ()
        assert set(exited.abstain) == set(_ctx().cartel)
        recovered = s.plan_round(_ctx(strategy="reporter_churn",
                                      rep=_eroded(erosion=0.01)))
        assert recovered.liars          # re-entered

    def test_flash_crowd_bursts_with_deadline_and_cools_down(self):
        s = make_strategy("flash_crowd")
        storm = s.plan_round(_ctx(strategy="flash_crowd"))
        assert storm.burst and storm.deadline_ms and storm.liars
        cool = s.plan_round(_ctx(strategy="flash_crowd",
                                 rep=_eroded(erosion=0.5)))
        assert cool.burst and cool.liars == ()   # storms honestly

    def test_slow_drip_streams_blocks_and_thins(self):
        s = make_strategy("slow_drip", blocks=6)
        fresh = s.plan_round(_ctx(strategy="slow_drip"))
        assert fresh.n_blocks == 6
        eroded = s.plan_round(_ctx(strategy="slow_drip",
                                   rep=_eroded(erosion=0.5)))
        assert 0 < eroded.lie_fraction < fresh.lie_fraction


# ----------------------------------------------------------------- panels


class TestRoundPanel:
    def _spec(self, **kwargs):
        kwargs.setdefault("name", "m-0")
        kwargs.setdefault("strategy", "camouflage")
        return MarketSpec(**kwargs)

    def test_replay_bit_identical_and_market_independent(self):
        spec_a = self._spec(name="a")
        spec_b = self._spec(name="b")
        plan = RoundPlan(liars=spec_a.cartel, lie_fraction=0.5)
        pa1 = round_panel(0, spec_a, 1, plan)[0]
        # interleave another market's generation between the replays
        round_panel(0, spec_b, 1, plan)
        pa2 = round_panel(0, spec_a, 1, plan)[0]
        assert np.array_equal(pa1, pa2, equal_nan=True)
        assert not np.array_equal(
            pa1, round_panel(0, spec_b, 1, plan)[0], equal_nan=True)

    def test_liars_report_shared_anti_truth_on_lie_mask(self):
        spec = self._spec(variance=0.0, na_frac=0.0)
        plan = RoundPlan(liars=spec.cartel, lie_fraction=1.0)
        panel, truth, lie_events, bounds = round_panel(0, spec, 0, plan)
        assert bounds is None and lie_events.all()
        honest = panel[:spec.n_reporters - spec.n_cartel]
        assert np.array_equal(honest, np.tile(truth, (honest.shape[0], 1)))
        liars = panel[list(spec.cartel)]
        assert np.array_equal(liars, np.tile(1.0 - truth,
                                             (spec.n_cartel, 1)))

    def test_abstain_rows_are_all_nan(self):
        spec = self._spec()
        plan = RoundPlan(liars=(), lie_fraction=0.0,
                         abstain=spec.cartel)
        panel = round_panel(0, spec, 0, plan)[0]
        assert np.isnan(panel[list(spec.cartel)]).all()
        assert not np.isnan(panel[0]).all()

    def test_scaled_tail_values_bounds_and_mirrored_lie(self):
        spec = self._spec(n_events=8, n_scaled=4, variance=0.0,
                          na_frac=0.0, scaled_min=-5.0, scaled_max=15.0)
        plan = RoundPlan(liars=spec.cartel, lie_fraction=1.0)
        panel, truth, _, bounds = round_panel(0, spec, 0, plan)
        assert bounds[:4] == [None] * 4
        assert all(b == {"scaled": True, "min": -5.0, "max": 15.0}
                   for b in bounds[4:])
        tail = panel[:, 4:]
        assert np.isin(tail, [-5.0, 15.0]).all()
        # the scaled lie is the mirrored value
        liar_tail = panel[list(spec.cartel), 4:]
        assert np.array_equal(liar_tail, np.tile(-5.0 + 15.0 - truth[4:],
                                                 (spec.n_cartel, 1)))

    def test_split_blocks_partitions_columns_with_bounds(self):
        spec = self._spec(n_events=10, n_scaled=2)
        plan = RoundPlan(liars=(), lie_fraction=0.0, n_blocks=3)
        panel, _, _, bounds = round_panel(0, spec, 0, plan)
        blocks = split_blocks(panel, bounds, plan.n_blocks)
        assert len(blocks) == 3
        assert np.array_equal(np.concatenate([b for b, _ in blocks],
                                             axis=1), panel,
                              equal_nan=True)
        assert [x for _, bb in blocks for x in bb] == bounds


# ----------------------------------------------------- scenario plumbing


class TestScenario:
    def test_build_scenario_shapes_and_json_round_trip(self):
        s = build_scenario(seed=5, rounds=4,
                           strategies=("camouflage", "slow_drip"),
                           markets_per_strategy=3)
        assert len(s.markets) == 6
        shapes = {(m.n_reporters, m.n_events) for m in s.markets}
        assert len(shapes) >= 3          # heterogeneous
        assert any(m.n_scaled for m in s.markets)     # mixed panels
        assert any(m.mirror for m in s.markets)
        s2 = Scenario.from_dict(json.loads(json.dumps(s.to_dict())))
        assert s2 == s

    def test_validation_fails_loudly(self):
        with pytest.raises(InputError, match="unknown strategy"):
            MarketSpec(name="x", strategy="nope")
        with pytest.raises(InputError, match="n_cartel"):
            MarketSpec(name="x", strategy="camouflage", n_reporters=4,
                       n_cartel=4)
        with pytest.raises(InputError, match="at least one market"):
            Scenario(markets=())
        m = MarketSpec(name="x", strategy="camouflage")
        with pytest.raises(InputError, match="unique"):
            Scenario(markets=(m, m))


# ------------------------------------------------------------ the economy


class TestEconomy:
    def test_result_shape_and_mechanism_outcomes(self):
        res = _run_service(build_scenario(seed=7, **SMALL))
        assert res["n_sessions"] == 4 and res["rounds"] == 2
        assert res["strategies"] == ["camouflage", "flash_crowd"]
        for s in res["strategies"]:
            block = res["per_strategy"][s]
            assert set(block) >= {"cartel_roi", "honest_yield",
                                  "time_to_catch_rounds",
                                  "caught_fraction", "stake"}
        traj = res["trajectories"]
        assert np.asarray(traj["cartel_roi"]).shape == (2, 2)
        assert res["service"]["requests"] > 0
        assert len(res["mechanism_digest"]) == 64

    def test_economy_grinds_cartels_down(self):
        # the paper's claim, end to end: a 1/3 cartel attacking through
        # the live serve tier loses value (ROI < 1) while the honest
        # majority's share never drops below its stake in any round —
        # strict per-round monotonicity is deliberately NOT claimed: a
        # caught cartel in honest back-off legitimately earns a little
        # reputation back, which is the mechanism working, not failing
        res = _run_service(build_scenario(
            seed=11, rounds=3, strategies=("camouflage",),
            markets_per_strategy=3, concurrency=4))
        block = res["per_strategy"]["camouflage"]
        assert block["cartel_roi"] < 1.0
        assert block["honest_yield"] > 1.0
        assert block["caught_fraction"] > 0
        yld = np.asarray(res["trajectories"]["honest_yield"])[0]
        assert (yld >= 1.0 - 1e-12).all()

    def test_replay_and_interleaving_bit_identical(self):
        scenario = build_scenario(seed=13, **SMALL)
        r1 = _run_service(scenario)
        r2 = _run_service(scenario)
        narrow = Scenario.from_dict(
            {**scenario.to_dict(), "concurrency": 1})
        r3 = _run_service(narrow)
        assert (r1["mechanism_digest"] == r2["mechanism_digest"]
                == r3["mechanism_digest"])
        assert r1["trajectories"] == r2["trajectories"] \
            == r3["trajectories"]

    def test_sheds_are_pyc_coded_and_do_not_change_bits(self):
        # a storm into a 2-slot queue sheds hard; every shed carries a
        # PYC code, retries absorb them, and the mechanism digest is
        # the one an uncontended run produces
        scenario = build_scenario(seed=17, rounds=2,
                                  strategies=("flash_crowd",),
                                  markets_per_strategy=4, concurrency=8)
        tight = _run_service(scenario, max_queue=2)
        roomy = _run_service(scenario, max_queue=256)
        assert tight["mechanism_digest"] == roomy["mechanism_digest"]
        assert all(code.startswith("PYC")
                   for code in tight["service"]["errors"])

    def test_metrics_emitted(self):
        obs.reset()
        res = _run_service(build_scenario(
            seed=19, rounds=2, strategies=("camouflage",),
            markets_per_strategy=2, concurrency=2))
        assert obs.value("pyconsensus_econ_rounds_total") == 2
        assert obs.value("pyconsensus_econ_markets") == 2
        assert obs.value("pyconsensus_econ_lies_total",
                         strategy="camouflage") > 0
        assert res["service"]["shed_rate"] >= 0.0

    def test_unstarted_service_session_not_found(self):
        svc = _svc()
        try:
            econ = MarketEconomy(svc, build_scenario(seed=1, rounds=1))
            econ.start()
            assert econ.start() is econ          # idempotent
            names = svc.sessions.names()
            assert len(names) == len(econ.scenario.markets)
        finally:
            svc.close(drain=True)


class TestEconomyFleet:
    def test_fleet_parity_and_resume_bit_identical(self, tmp_path):
        scenario = build_scenario(seed=23, **SMALL)
        ref = _run_service(scenario)

        full = _run_fleet(scenario, tmp_path / "a")
        assert full["mechanism_digest"] == ref["mechanism_digest"]

        # resume: play round 0 only, drop the fleet, adopt the logs
        # into a NEW fleet, finish — final state bit-identical
        log_b = tmp_path / "b"
        f1 = ConsensusFleet(FleetConfig(
            n_workers=2, log_dir=str(log_b),
            worker=ServeConfig(batch_window_ms=1.0))).start(warmup=False)
        e1 = MarketEconomy(f1, scenario)
        e1.start()
        e1.run_round(0)
        f1.close(drain=True)

        f2 = ConsensusFleet(FleetConfig(
            n_workers=2, log_dir=str(log_b),
            worker=ServeConfig(batch_window_ms=1.0))).start(warmup=False)
        resumed = MarketEconomy(f2, scenario).run()
        f2.close(drain=True)
        assert resumed["resumed_markets"] == 4
        assert resumed["mechanism_digest"] == ref["mechanism_digest"]

    def test_mid_round_resume_continues_at_staged_block(self, tmp_path):
        # kill mid-APPEND: stage only the first block of a market's
        # round through the fleet, drop it, resume — the economy must
        # append only the remaining blocks (no double-fold) and finish
        # bit-identical to the uninterrupted run
        scenario = build_scenario(
            seed=29, rounds=1, strategies=("slow_drip",),
            markets_per_strategy=1, concurrency=2)
        ref = _run_service(scenario)

        spec = scenario.markets[0]
        log = tmp_path / "log"
        f1 = ConsensusFleet(FleetConfig(
            n_workers=2, log_dir=str(log),
            worker=ServeConfig(batch_window_ms=1.0))).start(warmup=False)
        f1.create_session(spec.name, spec.n_reporters)
        plan = make_strategy(spec.strategy).plan_round(_ctx(
            strategy=spec.strategy, market=spec.name, round_idx=0,
            R=spec.n_reporters, n_cartel=spec.n_cartel))
        panel, _, _, bounds = round_panel(scenario.seed, spec, 0, plan)
        blocks = split_blocks(panel, bounds, plan.n_blocks)
        assert len(blocks) > 1
        f1.append(spec.name, blocks[0][0], blocks[0][1])
        f1.close(drain=True)

        f2 = ConsensusFleet(FleetConfig(
            n_workers=2, log_dir=str(log),
            worker=ServeConfig(batch_window_ms=1.0))).start(warmup=False)
        resumed = MarketEconomy(f2, scenario).run()
        f2.close(drain=True)
        assert resumed["mechanism_digest"] == ref["mechanism_digest"]

    def test_adopt_session_refuses_without_log_dir(self):
        fleet = ConsensusFleet(FleetConfig(n_workers=1))
        with pytest.raises(InputError, match="log_dir"):
            fleet.adopt_session("x")


# ------------------------------------------------------------ fault sites


class TestEconFaults:
    def _scenario(self):
        return build_scenario(seed=31, rounds=1,
                              strategies=("camouflage",),
                              markets_per_strategy=1, concurrency=2)

    def test_round_site_raises_injected_error(self):
        plan = faults.FaultPlan.from_dict({"seed": 0, "rules": [
            {"site": "econ.round", "kind": "raise",
             "occurrences": [0]}]})
        svc = _svc()
        try:
            with faults.armed(plan):
                with pytest.raises(OSError, match="injected fault"):
                    MarketEconomy(svc, self._scenario()).run()
        finally:
            svc.close(drain=True)
        assert ("econ.round", 0, "raise") in plan.fired

    def test_panel_storm_stays_finite_and_replayable(self):
        plan_dict = {"seed": 5, "rules": [
            {"site": "econ.panel", "kind": "nan_storm",
             "occurrences": [0], "args": {"fraction": 0.2}}]}

        def storm():
            svc = _svc()
            try:
                with faults.armed(
                        faults.FaultPlan.from_dict(plan_dict)):
                    return MarketEconomy(svc, self._scenario()).run()
            finally:
                svc.close(drain=True)

        r1, r2 = storm(), storm()
        # NaN is the legal non-report marker: the storm changes the
        # panel (more abstention), never the economy's health
        assert r1["mechanism_digest"] == r2["mechanism_digest"]
        clean = _run_service(self._scenario())
        assert r1["mechanism_digest"] != clean["mechanism_digest"]

    def test_submit_site_in_catalog_and_fires(self):
        assert {"econ.round", "econ.panel",
                "econ.submit"} <= set(faults.plan.FAULT_SITES)
        plan = faults.FaultPlan.from_dict({"seed": 0, "rules": [
            {"site": "econ.submit", "kind": "raise",
             "occurrences": [0]}]})
        svc = _svc()
        try:
            with faults.armed(plan):
                with pytest.raises(OSError, match="injected fault"):
                    MarketEconomy(svc, self._scenario()).run()
        finally:
            svc.close(drain=True)


# ------------------------------------------------------------------ plots


class TestEconPlots:
    @pytest.fixture(scope="class")
    def econ_result(self):
        return _run_service(build_scenario(
            seed=37, rounds=2, strategies=("camouflage", "sybil_split"),
            markets_per_strategy=1, concurrency=2))

    def test_cartel_roi_heatmap(self, econ_result):
        matplotlib = pytest.importorskip("matplotlib")
        matplotlib.use("Agg")
        from pyconsensus_tpu.sim import plot_cartel_roi_heatmap

        ax = plot_cartel_roi_heatmap(econ_result)
        assert ax.get_xlabel() == "round"
        assert [t.get_text() for t in ax.get_yticklabels()] \
            == econ_result["strategies"]
        matplotlib.pyplot.close(ax.figure)

    def test_honest_yield_curves(self, econ_result):
        matplotlib = pytest.importorskip("matplotlib")
        matplotlib.use("Agg")
        from pyconsensus_tpu.sim import plot_honest_yield_curves

        ax = plot_honest_yield_curves(econ_result)
        assert len(ax.get_lines()) >= 3      # 2 strategies + reference
        matplotlib.pyplot.close(ax.figure)

    def test_plots_reject_sweep_results(self):
        pytest.importorskip("matplotlib")
        from pyconsensus_tpu.sim import plot_cartel_roi_heatmap

        with pytest.raises((ValueError, KeyError, TypeError)):
            plot_cartel_roi_heatmap({"trajectories":
                                     {"cartel_roi": [1.0]}})


# -------------------------------------------------------------------- CLI


class TestEconCli:
    def test_quick_flags_and_json_out(self, tmp_path, capsys):
        from pyconsensus_tpu.econ.cli import main

        out = tmp_path / "econ.json"
        prom = tmp_path / "econ.prom"
        rc = main(["--strategies", "camouflage",
                   "--markets-per-strategy", "1", "--rounds", "1",
                   "--seed", "41", "--json-out", str(out),
                   "--metrics-out", str(prom)])
        assert rc == 0
        printed = json.loads(capsys.readouterr().out.strip()
                             .splitlines()[-1])
        saved = json.loads(out.read_text())
        assert printed["mechanism_digest"] == saved["mechanism_digest"]
        assert "pyconsensus_econ_rounds_total" in prom.read_text()

    def test_scenario_file_round_trip(self, tmp_path, capsys):
        from pyconsensus_tpu.econ.cli import main

        scenario = build_scenario(seed=43, rounds=1,
                                  strategies=("reporter_churn",),
                                  markets_per_strategy=1,
                                  concurrency=2)
        path = tmp_path / "scenario.json"
        path.write_text(json.dumps(scenario.to_dict()))
        assert main(["--scenario", str(path)]) == 0
        printed = json.loads(capsys.readouterr().out.strip()
                             .splitlines()[-1])
        assert printed["strategies"] == ["reporter_churn"]
        assert printed["seed"] == 43

    def test_fleet_flag_requires_log_dir(self, capsys):
        from pyconsensus_tpu.econ.cli import main

        assert main(["--fleet-workers", "2", "--rounds", "1"]) == 2
        assert "log-dir" in capsys.readouterr().err


# --------------------------------------------------------- session state


class TestSessionState:
    def test_state_snapshot_and_share(self, rng):
        from pyconsensus_tpu.serve import MarketSession

        s = MarketSession("m", 8)
        st = s.state()
        assert st["rounds_resolved"] == 0 and st["staged_blocks"] == 0
        assert np.allclose(st["reputation"], 1 / 8)
        s.append(rng.choice([0.0, 1.0], size=(8, 6)))
        assert s.state()["staged_blocks"] == 1
        assert s.state()["staged_events"] == 6
        assert s.reputation_share((6, 7)) == pytest.approx(0.25)
        # the snapshot is a copy, not a view
        st["reputation"][:] = 0.0
        assert s.state()["reputation"].sum() > 0
