"""consensus-lint's own tests: a fixture corpus of minimal snippets that
must (and must NOT) trigger each Layer-1 rule, text-level checks of the
Layer-2 contract machinery on crafted HLO, a trigger/no-trigger corpus
for the Layer-3a interprocedural taint rules (CL401-404), seeded-jaxpr
checks of the Layer-3b schedule rules (CL411-413), the CLI's
exit-code/baseline workflow, and the shipped-baseline-matches-tree
invariant."""

import json
import pathlib
import textwrap

import pytest

from pyconsensus_tpu.analysis import (Finding, analyze_paths, fingerprints,
                                      lint_paths, load_baseline,
                                      match_baseline)
from pyconsensus_tpu.analysis.baseline import save_baseline
from pyconsensus_tpu.analysis.cli import run as cli_run
from pyconsensus_tpu.analysis.contracts import (check_artifact,
                                                check_collective_budget,
                                                collective_inventory,
                                                collective_sizes, f64_ops,
                                                host_callbacks,
                                                load_contracts, run_contracts)
from pyconsensus_tpu.analysis.dataflow import DATAFLOW_RULES
from pyconsensus_tpu.analysis.rules import RULES, lint_file
from pyconsensus_tpu.analysis.schedule import (SCHEDULE_RULES, _check_perm,
                                               check_schedule,
                                               run_schedules)

# ---------------------------------------------------------------- Layer 1

#: per rule: (snippet that MUST trigger it, snippet that must NOT)
CORPUS = {
    "CL101": (
        """
        import jax, numpy as np
        @jax.jit
        def f(x):
            return np.asarray(x)
        """,
        """
        import numpy as np
        def host(x):
            return np.asarray(x)
        """,
    ),
    "CL102": (
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                return x
            return -x
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def g(x):
            if jnp.issubdtype(x.dtype, jnp.integer):
                return x
            return jnp.where(jnp.any(x > 0), x, -x)
        """,
    ),
    "CL103": (
        """
        import jax
        def bad(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
        """,
        """
        import jax
        def good(key):
            k1, k2 = jax.random.split(key)
            return jax.random.normal(k1, (3,)) + jax.random.uniform(k2, (3,))
        def loop(key):
            for _ in range(3):
                key, sub = jax.random.split(key)
                x = jax.random.normal(sub, (3,))
            return x
        """,
    ),
    "CL104": (
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return x.astype(jnp.float64)
        """,
        """
        import numpy as np
        def reference(x):
            return np.asarray(x, dtype=np.float64)
        """,
    ),
    "CL105": (
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return jnp.where(x > 0, 1.0, 0.5)
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def g(x):
            return jnp.where(x > 0, 1.0, jnp.asarray(0.5, x.dtype))
        """,
    ),
    "CL201": (
        "def f(a, b=[]):\n    return a\n",
        "def f(a, b=()):\n    return a\n",
    ),
    "CL202": (
        "def f(a):\n    try:\n        return a\n    except:\n        pass\n",
        "def f(a):\n    try:\n        return a\n    except ValueError:\n"
        "        pass\n",
    ),
    "CL203": (
        "import os\nX = 1\n",
        "import os\nX = os.sep\n",
    ),
    # ISSUE 3: telemetry is host-side only — obs emission in traced code
    # runs once per TRACE (not per execution) and span exit is a host sync
    "CL501": (
        """
        import jax
        from pyconsensus_tpu import obs
        @jax.jit
        def f(x):
            with obs.span("inner"):
                return x * 2
        """,
        """
        import jax
        from pyconsensus_tpu import obs
        def host(x):
            with obs.span("resolve"):
                return jax.jit(lambda y: y * 2)(x)
        """,
    ),
    "CL502": (
        """
        import time
        import jax
        @jax.jit
        def f(x):
            t0 = time.perf_counter()
            return x * 2, t0
        """,
        """
        import time
        import jax
        def host(x):
            t0 = time.perf_counter()
            return jax.jit(lambda y: y * 2)(x), t0
        """,
    ),
    # ISSUE 4: fault-injection sites are host-side only — in traced code
    # the armed-plan check bakes into the compiled graph as a constant
    # and the fault fires once per TRACE
    "CL601": (
        """
        import jax
        from pyconsensus_tpu import faults
        @jax.jit
        def f(x):
            faults.fire("kernel.site")
            return x * 2
        """,
        """
        import jax
        from pyconsensus_tpu import faults
        def host(x):
            faults.fire("host.site")
            return jax.jit(lambda y: y * 2)(x)
        """,
    ),
    # ISSUE 5: blocking waits / queue ops in traced code block once per
    # TRACE, never per execution — host coordination baked in as a
    # constant
    "CL701": (
        """
        import jax
        import queue
        @jax.jit
        def f(x):
            q = queue.Queue()
            q.put(x)
            return x * 2
        """,
        """
        import jax
        import queue
        def host(x):
            q = queue.Queue()
            q.put(x)
            return jax.jit(lambda y: y * 2)(x)
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(CORPUS))
def test_rule_triggers_and_stays_silent(rule, tmp_path):
    pos_src, neg_src = CORPUS[rule]
    pos = tmp_path / "pos.py"
    pos.write_text(textwrap.dedent(pos_src))
    neg = tmp_path / "neg.py"
    neg.write_text(textwrap.dedent(neg_src))
    assert rule in {f.rule for f in lint_file(pos, rel_path="pos.py")}, (
        f"{rule} did not fire on its positive snippet")
    assert rule not in {f.rule for f in lint_file(neg, rel_path="neg.py")}, (
        f"{rule} fired on its negative snippet")


def test_suppression_comment(tmp_path):
    p = tmp_path / "s.py"
    p.write_text(textwrap.dedent("""
        import jax, numpy as np
        @jax.jit
        def f(x):
            return np.asarray(x)  # consensus-lint: disable=CL101
        """))
    assert lint_file(p, rel_path="s.py") == []


def test_traced_module_pragma(tmp_path):
    p = tmp_path / "k.py"
    p.write_text(textwrap.dedent("""
        # consensus-lint: traced-module
        import numpy as np
        def plain_function(x):
            return np.asarray(x)
        def host_helper(x):  # consensus-lint: host
            return np.asarray(x)
        """))
    rules = [f.rule for f in lint_file(p, rel_path="k.py")]
    assert rules == ["CL101"], rules        # only the unmarked function


def test_composition_closure(tmp_path):
    """jax.jit(wrap(fn)) and lax.scan(step, ...) both mark fn traced."""
    p = tmp_path / "c.py"
    p.write_text(textwrap.dedent("""
        import jax, numpy as np
        from jax import lax
        def wrap(f):
            return f
        def core(x):
            return np.asarray(x)
        def step(carry, _):
            return np.asarray(carry), None
        core_jit = jax.jit(wrap(core))
        def driver(xs):
            return lax.scan(step, 0.0, xs)
        """))
    found = {f.message.split("'")[3] for f in lint_file(p, rel_path="c.py")
             if f.rule == "CL101"}
    assert found == {"core", "step"}


class TestObsInTracedRules:
    """CL501/CL502 beyond the basic corpus: alias forms, metric handles,
    shard_map bodies, PhaseTimer (ISSUE 3 satellite)."""

    def _rules(self, tmp_path, src):
        p = tmp_path / "t.py"
        p.write_text(textwrap.dedent(src))
        return [f.rule for f in lint_file(p, rel_path="t.py")]

    def test_from_import_alias_triggers(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu.obs import span as _sp
            @jax.jit
            def f(x):
                with _sp("inner"):
                    return x
            """)
        assert "CL501" in rules

    def test_metric_handle_method_triggers(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu import obs
            @jax.jit
            def f(x):
                h = obs.counter("c")
                h.inc()
                return x
            """)
        assert rules.count("CL501") == 2      # the build AND the .inc()

    def test_shard_map_body_triggers(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from jax.experimental.shard_map import shard_map
            from pyconsensus_tpu import obs
            def body(x):
                obs.counter("c").inc()
                return x
            f = shard_map(body, mesh=None, in_specs=None, out_specs=None)
            """)
        assert "CL501" in rules

    def test_host_metric_handle_silent(self, tmp_path):
        rules = self._rules(tmp_path, """
            from pyconsensus_tpu import obs
            def host():
                h = obs.counter("c")
                h.inc()
            """)
        assert "CL501" not in rules

    def test_phasetimer_in_traced_triggers(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu.utils import PhaseTimer
            @jax.jit
            def f(x):
                t = PhaseTimer()
                return x
            """)
        assert "CL502" in rules

    def test_suppression_works_for_cl50x(self, tmp_path):
        rules = self._rules(tmp_path, """
            import time
            import jax
            @jax.jit
            def f(x):
                t0 = time.perf_counter()  # consensus-lint: disable=CL502
                return x * 2, t0
            """)
        assert "CL502" not in rules

    def test_instrumented_package_is_cl50x_clean(self):
        """The package's OWN instrumentation (ISSUE 3 touched every
        layer) must never emit telemetry from traced code — the rule
        holds over the real tree, not just the corpus."""
        found = [f for f in lint_paths()
                 if f.rule in ("CL501", "CL502")]
        assert found == [], [(f.path, f.line, f.rule) for f in found]


class TestFaultsInTracedRule:
    """CL601 (ISSUE 4) beyond the basic corpus: alias/module-import
    forms, the corrupt hook, and the real injected package staying
    clean."""

    def _rules(self, tmp_path, src):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent(src))
        return [f.rule for f in lint_file(p, rel_path="m.py")]

    def test_plan_module_alias_form(self, tmp_path):
        # the package's own idiom: `from ..faults import plan as _faults`
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu.faults import plan as _faults
            @jax.jit
            def f(x):
                return _faults.corrupt("site", x)
            """)
        assert "CL601" in rules

    def test_direct_hook_import(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu.faults import fire
            @jax.jit
            def f(x):
                fire("site")
                return x
            """)
        assert "CL601" in rules

    def test_arming_in_traced_code_flagged(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu import faults
            @jax.jit
            def f(x):
                faults.arm(faults.FaultPlan())
                return x
            """)
        assert "CL601" in rules

    def test_errors_import_not_flagged(self, tmp_path):
        # taxonomy classes are trace-safe to RAISE (host-static gates)
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu.faults import InputError
            @jax.jit
            def f(x):
                if x.ndim != 2:
                    raise InputError("bad")
                return x
            """)
        assert "CL601" not in rules

    def test_suppression(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu import faults
            @jax.jit
            def f(x):
                faults.fire("site")  # consensus-lint: disable=CL601
                return x
            """)
        assert "CL601" not in rules

    def test_injected_package_is_cl601_clean(self):
        """ISSUE 4 threaded injection sites through io / ledger / runner
        / streaming / sharded / oracle — every one must be host-side
        over the real tree, not just the corpus."""
        found = [f for f in lint_paths() if f.rule == "CL601"]
        assert found == [], [(f.path, f.line, f.rule) for f in found]


class TestBlockingInTracedRule:
    """CL701 (ISSUE 5) beyond the basic corpus: sync-object handles,
    time.sleep, Future.result, benign-receiver immunity, and the real
    serve package staying clean."""

    def _rules(self, tmp_path, src):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent(src))
        return [f.rule for f in lint_file(p, rel_path="m.py")]

    def test_event_wait_handle(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            import threading
            @jax.jit
            def f(x):
                ev = threading.Event()
                ev.wait()
                return x
            """)
        assert "CL701" in rules

    def test_time_sleep(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            import time
            @jax.jit
            def f(x):
                time.sleep(0.1)
                return x
            """)
        assert "CL701" in rules

    def test_future_result_handle(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from concurrent.futures import Future
            @jax.jit
            def f(x):
                fut = Future()
                return fut.result(), x
            """)
        assert "CL701" in rules

    def test_serve_queue_ops(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            from pyconsensus_tpu.serve import RequestQueue
            @jax.jit
            def f(x):
                q = RequestQueue(4)
                q.take(timeout=1.0)
                return x
            """)
        assert "CL701" in rules

    def test_lock_acquire_handle(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            import threading
            @jax.jit
            def f(x):
                lock = threading.Lock()
                lock.acquire()
                return x
            """)
        assert "CL701" in rules

    def test_benign_receivers_not_flagged(self, tmp_path):
        # dict.get / str.join / untracked .result must stay silent —
        # only handles assigned from blocking constructors count
        rules = self._rules(tmp_path, """
            import jax
            @jax.jit
            def f(x, cfg):
                name = "-".join(["a", "b"])
                v = cfg.get("k", 0)
                return x * v, name
            """)
        assert "CL701" not in rules

    def test_host_side_not_flagged(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            import queue
            def host(x):
                q = queue.Queue()
                q.put(x)
                return jax.jit(lambda y: y * 2)(q.get())
            """)
        assert "CL701" not in rules

    def test_suppression(self, tmp_path):
        rules = self._rules(tmp_path, """
            import jax
            import time
            @jax.jit
            def f(x):
                time.sleep(0.0)  # consensus-lint: disable=CL701
                return x
            """)
        assert "CL701" not in rules

    def test_serve_package_is_cl701_clean(self):
        """The serving layer is built ON queues and waits — every one
        must live host-side, outside the traced kernel."""
        found = [f for f in lint_paths() if f.rule == "CL701"]
        assert found == [], [(f.path, f.line, f.rule) for f in found]


def test_fingerprints_stable_across_line_shifts(tmp_path):
    src = textwrap.dedent("""
        import jax, numpy as np
        @jax.jit
        def f(x):
            return np.asarray(x)
        """)
    p = tmp_path / "m.py"
    p.write_text(src)
    fp1 = fingerprints(lint_file(p, rel_path="m.py"))
    p.write_text("# a new comment line\n# another\n" + src)
    fp2 = fingerprints(lint_file(p, rel_path="m.py"))
    assert fp1 == fp2


def test_every_rule_has_corpus_coverage():
    assert set(CORPUS) == set(RULES)


# ----------------------------------------------- Layer 3a: taint corpus

#: per CL400-rule: (snippet that MUST trigger it, snippet that must NOT).
#: The no-trigger snippets pin the legitimacy carve-outs: raise-only
#: validation guards, per-host DATA selection feeding independent work,
#: and the multihost broadcast/allgather sanitizers.
TAINT_CORPUS = {
    "CL401": (
        """
        import time
        import jax
        @jax.jit
        def traced(x):
            if time.time() > 5:
                return x
            return -x
        """,
        """
        import jax
        from jax import lax
        def clean_roundrobin(chunks, n_hosts, run_chunk):
            host = jax.process_index()
            if not 0 <= host < n_hosts:
                raise ValueError("bad host")
            done = 0
            for c in chunks:
                if c % n_hosts == host:
                    run_chunk(c)
                    done += 1
            return done
        def sanitized(x, threshold):
            from jax.experimental.multihost_utils import broadcast_one_to_all
            import time
            seed = broadcast_one_to_all(time.time_ns())
            if seed > threshold:
                return lax.psum(x, "event")
            return x
        """,
    ),
    "CL402": (
        """
        import numpy as np
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        def spec_from_host(mesh, f, x):
            k = int(np.random.default_rng().integers(0, 2))
            specs = [P(None), P("event")][k]
            return shard_map(f, mesh=mesh, in_specs=specs,
                             out_specs=P())(x)
        """,
        """
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        def spec_static(mesh, f, x):
            return shard_map(f, mesh=mesh, in_specs=P(None, "event"),
                             out_specs=P())(x)
        """,
    ),
    "CL403": (
        """
        import os
        import numpy as np
        import jax
        from jax.sharding import Mesh
        def mesh_from_env():
            b = int(os.environ.get("NB", "1"))
            grid = np.array(jax.devices()).reshape(b, -1)
            return Mesh(grid, ("batch", "event"))
        """,
        """
        import numpy as np
        import jax
        from jax.sharding import Mesh
        def mesh_global(batch):
            grid = np.array(jax.devices()).reshape(batch, -1)
            return Mesh(grid, ("batch", "event"))
        """,
    ),
    "CL404": (
        """
        import jax
        from jax import lax
        def scaled_psum(x):
            n = jax.process_count()
            return lax.psum(x * n, "event")
        """,
        """
        import jax
        from jax import lax
        def plain_psum(x):
            return lax.psum(x, "event")
        def gathered(x):
            from jax.experimental.multihost_utils import process_allgather
            import time
            return process_allgather(time.monotonic() * x)
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(TAINT_CORPUS))
def test_taint_rule_triggers_and_stays_silent(rule, tmp_path):
    pos_src, neg_src = TAINT_CORPUS[rule]
    pos = tmp_path / "pos.py"
    pos.write_text(textwrap.dedent(pos_src))
    neg = tmp_path / "neg.py"
    neg.write_text(textwrap.dedent(neg_src))
    assert rule in {f.rule for f in analyze_paths([pos])}, (
        f"{rule} did not fire on its positive snippet")
    assert rule not in {f.rule for f in analyze_paths([neg])}, (
        f"{rule} fired on its negative snippet")


def test_every_taint_rule_has_corpus_coverage():
    assert set(TAINT_CORPUS) == set(DATAFLOW_RULES)


def test_taint_flows_interprocedurally(tmp_path):
    """The signature Layer-3a case PR 1 could not see: the source read,
    the propagating helper, and the sink live in three different
    functions across two modules."""
    (tmp_path / "ident.py").write_text(textwrap.dedent("""
        import jax
        def who_am_i():
            return jax.process_index()
        def offset(base):
            return base + who_am_i()
        """))
    sink = tmp_path / "sink.py"
    sink.write_text(textwrap.dedent("""
        from jax import lax
        from ident import offset
        def emit(x):
            return lax.ppermute(x, "event", [(0, offset(1))])
        """))
    found = analyze_paths([tmp_path])
    assert "CL404" in {f.rule for f in found}
    # the origin chain names the whole flow, three frames deep
    msg = next(f for f in found if f.rule == "CL404").message
    assert "offset()" in msg and "process_index" in msg
    # restricting the scan to the sink file alone drops the callee from
    # the call graph; an unresolved call with CLEAN arguments is clean
    # (the documented scope contract: the graph covers scanned files)
    assert analyze_paths([sink]) == []


def test_taint_sees_lambda_bodies(tmp_path):
    """Lambdas are the dominant idiom for cond arms — a sink inside one
    must fire (review catch: the first engine skipped lambda bodies),
    and the lambda's own params must not leak enclosing taint."""
    p = tmp_path / "lam.py"
    p.write_text(textwrap.dedent("""
        import jax
        from jax import lax
        def f(x):
            return lax.cond(x.sum() > 0,
                            lambda v: lax.psum(v * jax.process_count(),
                                               "event"),
                            lambda v: v, x)
        def clean(x):
            n = jax.process_count()
            g = lambda v: lax.psum(v, "event")   # n NOT captured
            return g(x)
        """))
    findings = analyze_paths([p])
    assert {f.rule for f in findings} == {"CL404"}
    assert all(f.line <= 9 for f in findings)    # none in clean()


def test_taint_flows_through_method_calls(tmp_path):
    """self.helper(tainted) must taint the parameter AFTER the implicit
    receiver (review catch: positional binding off by one landed the
    taint on 'self' and dropped the flow)."""
    p = tmp_path / "meth.py"
    p.write_text(textwrap.dedent("""
        import jax
        from jax import lax
        class Runner:
            def helper(self, x, idx):
                return lax.psum(x * idx, "event")
            def go(self, x):
                return self.helper(x, jax.process_index())
        """))
    assert {f.rule for f in analyze_paths([p])} == {"CL404"}


def test_taint_is_definition_order_independent(tmp_path):
    """Two review catches: (a) a param-pass-through chain whose CALLER
    is defined before its callee must still propagate (propagates_params
    now converges inside the fixpoint loop); (b) taint introduced by a
    walrus inside an `if` TEST must reach the summaries (the test is
    evaluated in every pass, not just the findings pass)."""
    p = tmp_path / "order.py"
    p.write_text(textwrap.dedent("""
        import jax
        from jax import lax
        def use(x):
            return lax.psum(outer(x, jax.process_index()), "event")
        def outer(v, i):
            return inner(v, i)
        def inner(v, i):
            return v * i
        """))
    assert {f.rule for f in analyze_paths([p])} == {"CL404"}
    q = tmp_path / "walrus.py"
    q.write_text(textwrap.dedent("""
        import jax
        from jax import lax
        def get():
            if (n := jax.process_index()) > 0:
                pass
            return n
        def use(x):
            return lax.psum(x * get(), "event")
        """))
    assert "CL404" in {f.rule for f in analyze_paths([q])}


def test_taint_marker_and_suppression(tmp_path):
    """`# consensus-lint: host-divergent` turns a function's return into
    a source; `# consensus-lint: disable=CL403` silences the sink line."""
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent("""
        import numpy as np
        import jax
        from jax.sharding import Mesh
        def topology_query(d):  # consensus-lint: host-divergent
            return getattr(d, "slice_index", 0)
        def build():
            devs = [d for d in jax.devices() if topology_query(d) == 0]
            return Mesh(np.array(devs), ("event",))
        """))
    assert {f.rule for f in analyze_paths([p])} == {"CL403"}
    src = p.read_text().replace(
        'return Mesh(np.array(devs), ("event",))',
        'return Mesh(np.array(devs), ("event",))'
        '  # consensus-lint: disable=CL403')
    p.write_text(src)
    assert analyze_paths([p]) == []


# ------------------------------------------- Layer 3b: schedule checks

@pytest.fixture(scope="module")
def mesh8():
    import jax

    from pyconsensus_tpu.parallel import make_mesh
    assert len(jax.devices()) == 8
    return make_mesh(batch=1, event=8)


def _sm_jaxpr(body, mesh, in_spec, out_spec):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from pyconsensus_tpu.parallel.ring import shard_map
    f = shard_map(body, mesh, in_spec or P(None, "event"),
                  out_spec or P(None, "event"))
    return jax.make_jaxpr(f)(jnp.ones((4, 8)))


def test_schedule_flags_unbalanced_cond(mesh8):
    import jax.numpy as jnp
    from jax import lax

    def unbal(x):
        return lax.cond(jnp.sum(x) > 0,
                        lambda v: lax.psum(v, "event"), lambda v: v, x)

    found = check_schedule("t", _sm_jaxpr(unbal, mesh8, None, None))
    assert [f.rule for f in found] == ["CL411"]
    assert "different collective sequences" in found[0].message

    def balanced(x):
        return lax.cond(jnp.sum(x) > 0,
                        lambda v: lax.psum(v, "event"),
                        lambda v: lax.psum(2.0 * v, "event"), x)

    assert check_schedule("t", _sm_jaxpr(balanced, mesh8, None, None)) == []


def test_schedule_flags_non_bijective_ppermute(mesh8):
    from jax import lax

    def partial_perm(x):                 # a dropped ring hop
        return lax.ppermute(x, "event", [(0, 1)])

    found = check_schedule("t", _sm_jaxpr(partial_perm, mesh8, None, None))
    assert [f.rule for f in found] == ["CL412"]

    def full_ring(x):
        return lax.ppermute(x, "event",
                            [(i, (i + 1) % 8) for i in range(8)])

    assert check_schedule("t", _sm_jaxpr(full_ring, mesh8, None, None)) == []


def test_check_perm_unit_cases():
    ring = [(i, (i + 1) % 8) for i in range(8)]
    assert _check_perm(ring, 8) is None
    assert "duplicate destination" in _check_perm([(0, 1), (1, 1)], 8)
    assert "duplicate source" in _check_perm([(0, 1), (0, 2)], 8)
    assert "out of range" in _check_perm([(0, 9)], 8)
    assert "covers" in _check_perm(ring[:-1], 8)
    assert _check_perm(ring, None) is None       # unknown axis size


def test_schedule_flags_unbound_axis():
    import jax
    from jax import lax

    jaxpr = jax.make_jaxpr(lambda x: lax.psum(x, "ghost"),
                           axis_env=[("ghost", 8)])(1.0)
    found = check_schedule("t", jaxpr, {"event": 8})
    assert [f.rule for f in found] == ["CL413"]
    assert "ghost" in found[0].message
    assert check_schedule("t", jaxpr, {"event": 8, "ghost": 8}) == []


def test_schedule_walks_while_loops(mesh8):
    """Collectives inside while bodies are part of the schedule: the
    bijection/binding checks reach them (a malformed perm in a ring
    LOOP is exactly the ring_allreduce bug class)."""
    import jax.numpy as jnp
    from jax import lax

    def looped(x):
        def body(c):
            i, v = c
            return i + 1, lax.ppermute(v, "event", [(0, 1)])
        _, out = lax.while_loop(lambda c: c[0] < 3, body,
                                (jnp.asarray(0), x))
        return out

    found = check_schedule("t", _sm_jaxpr(looped, mesh8, None, None))
    assert [f.rule for f in found] == ["CL412"]


def test_real_schedules_are_clean():
    """Every declared schedule target (ring primitives, fused shard_map
    executable, streaming panel, light pipeline) traces and passes —
    the live half of the CI gate, mirrored here so a deadlocking edit
    fails fast in pytest too."""
    assert run_schedules() == []


def test_ring_schedule_shape():
    """ring_gram's extracted schedule IS the documented two-phase ring:
    ppermute-only (reduce-scatter + all-gather loops), every hop on the
    event axis, no hidden psum fallback."""
    from pyconsensus_tpu.analysis.schedule import (SCHEDULES,
                                                   extract_schedule)

    jaxpr, env = SCHEDULES["ring-gram"]()
    msgs = []
    seq = extract_schedule(jaxpr.jaxpr, dict(env), msgs)
    assert msgs == []
    assert [op for op, _ in seq] == ["ppermute", "ppermute"]
    assert all(axes == ("event",) for _, axes in seq)


# ------------------------------------------------------- baseline workflow

def test_shipped_baseline_exactly_matches_tree():
    """The checked-in baseline accepts the CURRENT tree exactly: no new
    findings (CI would be red) and no stale static entries (the file
    rotted). Covers Layer 1 AND the Layer-3a taint pass; accepted
    ``contract:*`` / ``schedule:*`` entries are out of scope here — the
    traced layers don't run in this test; the full check is
    `consensus-lint --strict` in tools/ci_rehearsal.sh."""
    baseline = load_baseline()
    findings = lint_paths() + analyze_paths()
    new, matched, stale = match_baseline(findings, baseline)
    assert new == [], ("tree has non-baselined findings:\n"
                       + "\n".join(f.render() for f in new))
    traced_fps = {e["fingerprint"] for e in baseline.get("findings", [])
                  if e["path"].startswith(("contract:", "schedule:"))}
    stale = [fp for fp in stale if fp not in traced_fps]
    assert stale == [], f"baseline entries no longer match the tree: {stale}"


def test_baseline_roundtrip(tmp_path):
    f = Finding(rule="CL201", path="x.py", line=3, message="m",
                severity="warning", snippet="def f(a, b=[]):")
    bl = tmp_path / "bl.json"
    save_baseline([f], path=bl, reason="test rationale")
    doc = json.loads(bl.read_text())
    assert doc["findings"][0]["reason"] == "test rationale"
    new, matched, stale = match_baseline([f], load_baseline(bl))
    assert (new, len(matched), stale) == ([], 1, [])
    # a DIFFERENT finding is new; the old entry goes stale
    g = Finding(rule="CL202", path="x.py", line=9, message="m2",
                severity="warning", snippet="except:")
    new, matched, stale = match_baseline([g], load_baseline(bl))
    assert len(new) == 1 and matched == [] and len(stale) == 1


# -------------------------------------------------- Layer 2 text checkers

_SHARDED_BUDGET = {"require_all_reduce": True, "all_reduce_max": "4*R + 8",
                   "other_max": "E"}
_ENV = {"R": 32, "E": 2048, "n_dev": 8}


def test_collective_inventory_parses_tuples_and_dtypes():
    hlo = "\n".join([
        "  %ar = f32[32]{0} all-reduce(f32[32]{0} %p)",
        "  %t = (f32[32]{0}, f32[8]{0}) all-reduce(f32[32] %a, f32[8] %b)",
        "  %bits = u32[2048]{0} all-reduce(u32[2048]{0} %x)",
        "  %ag = f32[2048]{0} all-gather(f32[256]{0} %y)",
    ])
    inv = collective_inventory(hlo)
    assert (("all-reduce", frozenset({"f32"}), 32) in inv)
    assert (("all-reduce", frozenset({"f32"}), 40) in inv)   # tuple summed
    assert (("all-reduce", frozenset({"u32"}), 2048) in inv)
    assert collective_sizes(hlo)["all-gather"] == [2048]


def test_inventory_handles_fp8_and_annotation_tokens():
    """fp8 dtype names must be counted (a silent 0-element inventory
    would wave a matrix-sized collective through every budget), and
    digit-free annotation tokens like devices=[8] must NOT be."""
    hlo = ("  %ag = f8e4m3fn[32,2048]{1,0} all-gather("
           "f8e4m3fn[32,256]{1,0} %x), sharding={devices=[8]0,1,2,3,4,5,6,7}")
    inv = collective_inventory(hlo)
    assert inv == [("all-gather", frozenset({"f8e4m3fn"}), 32 * 2048)]
    out = check_collective_budget(inv, _SHARDED_BUDGET, _ENV)
    assert any("matrix-sized" in v or "all-gather" in v for v in out)


def test_budget_passes_the_contract_shape():
    hlo = ("  %ar = f32[32]{0} all-reduce(f32[32]{0} %p)\n"
           "  %bits = u32[2048]{0} all-reduce(u32[2048]{0} %x)\n"
           "  %ag = f32[2048]{0} all-gather(f32[256]{0} %y)")
    assert check_collective_budget(collective_inventory(hlo),
                                   _SHARDED_BUDGET, _ENV) == []


def test_budget_flags_seeded_violations():
    matrix = "  %ag = f32[32,2048]{1,0} all-gather(f32[32,256]{1,0} %x)"
    out = check_collective_budget(collective_inventory(matrix),
                                  dict(_SHARDED_BUDGET,
                                       require_all_reduce=False), _ENV)
    assert any("all-gather" in v for v in out)
    fat_ar = "  %ar = f32[2048]{0} all-reduce(f32[2048]{0} %p)"
    out = check_collective_budget(collective_inventory(fat_ar),
                                  _SHARDED_BUDGET, _ENV)
    assert any("float all-reduce" in v for v in out)
    out = check_collective_budget([], {"forbid_collectives": True}, _ENV)
    assert out == []
    out = check_collective_budget(
        collective_inventory(fat_ar), {"forbid_collectives": True}, _ENV)
    assert any("collective-free" in v for v in out)


def test_f64_and_callback_detectors():
    hlo = ("  %m = f64[32]{0} multiply(f64[32]{0} %a, f64[32]{0} %b)\n"
           "  %cc = f32[2]{0} custom-call(f32[2]{0} %x), "
           "custom_call_target=\"xla_python_cpu_callback\"\n"
           "  %ok = f32[2]{0} add(f32[2]{0} %x, f32[2]{0} %y)")
    assert len(f64_ops(hlo)) == 1
    assert len(host_callbacks(hlo)) == 1
    assert f64_ops("  %ok = f32[2] add(f32[2] %x, f32[2] %y)") == []


def test_bf16_compare_detector():
    """CL305 (ISSUE 7): bf16/i8-operand compares in compiled HLO — the
    lowered form Mosaic rejects in Pallas kernels (BENCH_r02's crash
    class). f32/pred compares and metadata-only mentions stay clean."""
    from pyconsensus_tpu.analysis.contracts import bf16_compare_ops

    bad = ("  %c = pred[8,128]{1,0} compare(bf16[8,128]{1,0} %a, "
           "bf16[8,128]{1,0} %b), direction=LT\n"
           "  %d = pred[32]{0} compare(s8[32]{0} %p, s8[32]{0} %q), "
           "direction=EQ\n"
           "  %ok = pred[32]{0} compare(f32[32]{0} %x, f32[32]{0} %y), "
           "direction=GE")
    hits = bf16_compare_ops(bad)
    assert len(hits) == 2
    assert bf16_compare_ops(
        "  %ok = pred[4]{0} compare(f32[4]{0} %x, f32[4]{0} %y)") == []
    # a bf16 mention only in metadata must not trigger
    assert bf16_compare_ops(
        "  %ok = pred[4]{0} compare(f32[4]{0} %x, f32[4]{0} %y), "
        "metadata={op_name=\"bf16[stuff]\"}") == []


def test_check_artifact_forbid_bf16_compares():
    spec = {"name": "t", "shape": {"R": 8, "E": 16},
            "forbid_bf16_compares": True}
    bad = ("  %c = pred[8]{0} compare(bf16[8]{0} %a, bf16[8]{0} %b), "
           "direction=LT")
    rules = {f.rule for f in check_artifact("t", bad, spec)}
    assert "CL305" in rules
    ok = ("  %c = pred[8]{0} compare(f32[8]{0} %a, f32[8]{0} %b), "
          "direction=LT")
    assert not {f.rule for f in check_artifact("t", ok, spec)} & {"CL305"}
    # without the spec flag the same HLO is not checked
    assert not {f.rule
                for f in check_artifact(
                    "t", bad, {"name": "t", "shape": {"R": 8, "E": 16}})
                } & {"CL305"}


def test_pallas_resolve_contract_holds_live():
    """The ISSUE 7 contract end-to-end in-process: the fused tier's
    compiled module is collective-free, f64-free, and carries no
    bf16/i8-operand compare (the full set runs under --strict in CI)."""
    assert run_contracts(names=["pallas-resolve"]) == []


def test_check_artifact_reports_findings():
    spec = {"name": "t", "shape": {"R": 32, "E": 2048},
            "mesh": {"batch": 1, "event": 8},
            "budget": dict(_SHARDED_BUDGET)}
    bad = "  %ar = f32[65536]{0} all-reduce(f32[65536]{0} %p)"
    rules = {f.rule for f in check_artifact("t", bad, spec)}
    assert "CL301" in rules
    cb = ("  %ar = f32[32]{0} all-reduce(f32[32]{0} %p)\n"
          "  %cc = f32[2]{0} custom-call(f32[2]{0} %x), "
          "custom_call_target=\"xla_python_cpu_callback\"")
    rules = {f.rule for f in check_artifact("t", cb, spec)}
    assert "CL303" in rules


# ------------------------------------------------------ Layer 2 live runs

def test_declared_contracts_are_wellformed():
    names = [c["name"] for c in load_contracts()]
    assert len(names) == len(set(names))
    from pyconsensus_tpu.analysis.contracts import BUILDERS
    for c in load_contracts():
        assert c["builder"] in BUILDERS, c["name"]


def test_single_device_contract_holds_live():
    """One cheap end-to-end contract run in-process (the full set runs in
    CI via `consensus-lint --strict`)."""
    assert run_contracts(names=["pipeline-single-device"]) == []


def test_retrace_contract_holds_live():
    assert run_contracts(names=["pipeline-retrace-budget"]) == []


# ---------------------------------------------------------------- CLI

def test_cli_exit_codes_and_baseline_update(tmp_path, capsys):
    src = tmp_path / "mod.py"
    src.write_text(textwrap.dedent("""
        import jax, numpy as np
        @jax.jit
        def f(x):
            return np.asarray(x)
        """))
    bl = tmp_path / "bl.json"
    # new finding, empty baseline -> exit 1
    assert cli_run([str(src), "--baseline", str(bl)]) == 1
    # accept it -> exit 0 afterwards
    assert cli_run([str(src), "--baseline", str(bl),
                    "--update-baseline"]) == 0
    assert cli_run([str(src), "--baseline", str(bl)]) == 0
    # fix the code -> stale entry fails only --strict (without contracts)
    src.write_text("X = 1\n")
    assert cli_run([str(src), "--baseline", str(bl)]) == 0
    assert cli_run([str(src), "--baseline", str(bl), "--strict",
                    "--no-contracts"]) == 1
    out = capsys.readouterr().out
    assert "stale baseline" in out


def test_update_baseline_preserves_out_of_scope_entries(tmp_path):
    """A path-restricted or contracts-off --update-baseline run must not
    delete accepted entries it could not have reproduced."""
    mod = tmp_path / "mod.py"
    mod.write_text("def f(a, b=[]):\n    return a\n")
    bl = tmp_path / "bl.json"
    # seed the baseline with an accepted contract finding + a finding in
    # ANOTHER file, each with a rationale
    bl.write_text(json.dumps({"version": 1, "findings": [
        {"fingerprint": "CL301:contract:x:deadbeef", "rule": "CL301",
         "path": "contract:x", "message": "m", "reason": "accepted: gram"},
        {"fingerprint": "CL202:other.py:cafebabe", "rule": "CL202",
         "path": "other.py", "message": "m", "reason": "accepted: legacy"},
    ]}))
    assert cli_run([str(mod), "--baseline", str(bl),
                    "--update-baseline"]) == 0
    kept = {e["fingerprint"]: e for e in json.loads(bl.read_text())["findings"]}
    assert "CL301:contract:x:deadbeef" in kept          # contracts didn't run
    assert "CL202:other.py:cafebabe" in kept            # file not in scope
    assert kept["CL301:contract:x:deadbeef"]["reason"] == "accepted: gram"
    assert any(e.startswith("CL201:mod.py:") for e in kept)  # new accept


def test_strict_stale_is_scoped_to_the_run(tmp_path):
    """Out-of-scope baseline entries (other files, contract findings when
    Layer 2 didn't run) are not 'stale' — only a run that could have
    reproduced an entry may fail on its absence."""
    mod = tmp_path / "mod.py"
    mod.write_text("X = 1\n")
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "findings": [
        {"fingerprint": "CL301:contract:x:deadbeef", "rule": "CL301",
         "path": "contract:x", "message": "m", "reason": "accepted"},
        {"fingerprint": "CL202:other.py:cafebabe", "rule": "CL202",
         "path": "other.py", "message": "m", "reason": "accepted"},
    ]}))
    assert cli_run([str(mod), "--baseline", str(bl), "--strict",
                    "--no-contracts"]) == 0


def test_cli_list_rules(capsys):
    assert cli_run(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in list(RULES) + ["CL300", "CL301", "CL302", "CL303", "CL304"]:
        assert rid in out


def test_cli_json_format(tmp_path, capsys):
    src = tmp_path / "mod.py"
    src.write_text("def f(a, b=[]):\n    return a\n")
    rc = cli_run([str(src), "--format", "json", "--no-baseline"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["new"][0]["rule"] == "CL201"
    assert "fingerprint" in payload["new"][0]


def test_cli_exit_codes_on_seeded_divergence(tmp_path, capsys):
    """The acceptance seed: a host-divergent value reaching a traced
    branch must fail the default run (Layer 3a rides every lint run),
    and --no-dataflow must wave the same file through. Seeded with an
    ENV read since ISSUE 3: the original clock seed is now also caught
    statically by Layer-1 CL502 (host timer in traced code), so a clock
    file no longer passes --no-dataflow — the env source is the
    divergence class only the taint engine sees."""
    src = tmp_path / "div.py"
    src.write_text(textwrap.dedent("""
        import os
        import jax
        @jax.jit
        def f(x):
            if os.environ.get("HOST_ONLY_FLAG"):
                return x
            return -x
        """))
    assert cli_run([str(src), "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "CL401" in out
    assert cli_run([str(src), "--no-baseline", "--no-dataflow"]) == 0
    # the clock form of the same defect is now a STATIC catch (CL502) —
    # dataflow off no longer waves it through
    clock = tmp_path / "clock.py"
    clock.write_text(textwrap.dedent("""
        import time
        import jax
        @jax.jit
        def f(x):
            if time.monotonic() > 0:
                return x
            return -x
        """))
    capsys.readouterr()
    assert cli_run([str(clock), "--no-baseline", "--no-dataflow"]) == 1
    assert "CL502" in capsys.readouterr().out


def test_cli_select_covers_taint_rules(tmp_path):
    src = tmp_path / "div.py"
    src.write_text(textwrap.dedent("""
        import jax
        from jax import lax
        def f(x):
            return lax.psum(x * jax.process_index(), "event")
        """))
    assert cli_run([str(src), "--no-baseline", "--select", "CL404"]) == 1
    assert cli_run([str(src), "--no-baseline", "--select", "CL401"]) == 0


def test_cli_list_rules_includes_layer3(capsys):
    assert cli_run(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in list(DATAFLOW_RULES) + list(SCHEDULE_RULES):
        assert rid in out
