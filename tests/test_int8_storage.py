"""int8 sentinel-threaded storage (``storage_dtype="int8"``).

Binary/categorical reports take values in {0, 0.5, 1} (+NaN for absence)
— exactly representable in the int8 encoding ``stored = round(2·value)``
with sentinel ``-1`` for NaN — so int8 storage halves the HBM traffic of
every O(R·E) phase vs bf16 with ZERO quantization error on binary
workloads. The contract mirrors the bf16 storage mode's: catch-snapped
outcomes bit-identical to the full-precision path, continuous outputs to
tight float tolerance. Scaled events are rejected (their [0,1]-rescaled values
are continuous; a half-unit quantization would change results), as is the
XLA (non-fused) path (it stores the interpolated fill values, which are
continuous weighted means).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                             _consensus_core,
                                             _consensus_core_fused)
from pyconsensus_tpu.ops.pallas_kernels import (apply_weighted_cov,
                                                resolve_certainty_fused,
                                                scores_dirfix_pass)

from conftest import collusion_reports


def make_reports(rng, R=24, E=12, na_frac=0.15):
    reports, _ = collusion_reports(rng, R, E, liars=max(2, R // 5),
                                   na_frac=na_frac)
    return reports


def encode_int8(reports):
    """The reference encoding the pipeline must match: 2·value in
    {0, 1, 2}, sentinel -1 for NaN."""
    r = np.asarray(reports, dtype=np.float64)
    return np.where(np.isnan(r), -1, np.round(np.clip(r, 0.0, 1.0) * 2)
                    ).astype(np.int8)


def fused_args(reports, rep):
    E = reports.shape[1]
    return (jnp.asarray(reports), jnp.asarray(rep),
            jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E))


BASE = ConsensusParams(algorithm="sztorc", pca_method="power",
                       power_iters=256, power_tol=-1.0, any_scaled=False,
                       has_na=True, fused_resolution=True)


class TestKernelDecode:
    """Each Pallas kernel must read int8 sentinel storage identically to
    NaN-threaded float storage of the same values (interpret mode)."""

    def _inputs(self, rng, R=24, E=12):
        reports = make_reports(rng, R=R, E=E)
        x_f = jnp.asarray(reports, dtype=jnp.float32)
        x_i = jnp.asarray(encode_int8(reports))
        rep = jnp.asarray(np.full(R, 1.0 / R), dtype=jnp.float32)
        fill = jnp.asarray(rng.choice([0.0, 0.5, 1.0], size=E),
                           dtype=jnp.float32)
        filled = jnp.where(jnp.isnan(x_f), fill[None, :], x_f)
        mu = rep @ filled
        return x_f, x_i, rep, fill, mu

    def test_apply_weighted_cov(self, rng):
        x_f, x_i, rep, fill, mu = self._inputs(rng)
        v = jnp.asarray(rng.standard_normal(x_f.shape[1]),
                        dtype=jnp.float32)
        y_f = np.asarray(apply_weighted_cov(x_f, mu, rep, v, fill=fill,
                                            interpret=True))
        y_i = np.asarray(apply_weighted_cov(x_i, mu, rep, v, fill=fill,
                                            interpret=True))
        # int8 takes the MXU branch whose compensated v-split carries a
        # ~2^-17 second-order residual vs the f32 VPU branch; a broken
        # decode shows up as O(1) mismatch, not 1e-5
        np.testing.assert_allclose(y_i, y_f, rtol=3e-5, atol=1e-6)

    def test_apply_weighted_cov_dense_int8(self, rng):
        """No-fill (dense) mode must decode int8 too."""
        x_f, x_i, rep, fill, mu = self._inputs(rng)
        dense_f = jnp.where(jnp.isnan(x_f), 0.5, x_f)
        dense_i = jnp.asarray(encode_int8(np.asarray(dense_f)))
        v = jnp.asarray(rng.standard_normal(x_f.shape[1]),
                        dtype=jnp.float32)
        mu_d = rep @ dense_f
        y_f = np.asarray(apply_weighted_cov(dense_f, mu_d, rep, v,
                                            interpret=True))
        y_i = np.asarray(apply_weighted_cov(dense_i, mu_d, rep, v,
                                            interpret=True))
        np.testing.assert_allclose(y_i, y_f, rtol=3e-5, atol=1e-6)

    def test_scores_dirfix_pass(self, rng):
        x_f, x_i, rep, fill, mu = self._inputs(rng)
        loading = jnp.asarray(rng.standard_normal(x_f.shape[1]),
                              dtype=jnp.float32)
        outs_f = scores_dirfix_pass(x_f, rep, loading, fill=fill,
                                    interpret=True)
        outs_i = scores_dirfix_pass(x_i, rep, loading, fill=fill,
                                    interpret=True)
        for a, b in zip(outs_f, outs_i):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("R", [24, 23])   # 23: row-padding path
    def test_resolve_certainty_fused(self, rng, R):
        x_f, x_i, rep, fill, mu = self._inputs(rng, R=R)
        total = jnp.sum(rep)
        outs_f = resolve_certainty_fused(x_f, rep, fill, total, 0.1,
                                         interpret=True)
        outs_i = resolve_certainty_fused(x_i, rep, fill, total, 0.1,
                                         interpret=True)
        # outcomes (catch-snapped) exact; accumulations to float tolerance
        np.testing.assert_array_equal(np.asarray(outs_i[1]),
                                      np.asarray(outs_f[1]))
        for a, b in zip(outs_f, outs_i):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6, atol=1e-7)


class TestFusedPipelineInt8:
    """storage_dtype='int8' through the whole fused pipeline must
    reproduce the full-precision fused path key-for-key — exactly on
    catch-snapped outputs, to float tolerance on accumulations."""

    @pytest.mark.parametrize("R,max_iterations", [(24, 1), (24, 4),
                                                  (23, 1)])
    def test_matches_full_precision(self, rng, R, max_iterations):
        reports = make_reports(rng, R=R, E=12)
        rep = np.full(R, 1.0 / R)
        args = fused_args(reports, rep)
        base = BASE._replace(max_iterations=max_iterations)
        ref = _consensus_core_fused(*args, base)
        out = _consensus_core_fused(*args,
                                    base._replace(storage_dtype="int8"))
        assert set(out) == set(ref)
        for key in ref:
            a, b = np.asarray(ref[key]), np.asarray(out[key])
            # catch-snapped outputs: bit-exact. outcomes_raw (the
            # unsnapped means) is continuous: the int8 and f32 paths take
            # different exact-level accumulation routes through the
            # covariance kernel (MXU compensated vs VPU), so it is held
            # to float tolerance like the other continuous outputs.
            if key in ("outcomes_adjusted", "outcomes_final",
                       "na_row", "iterations", "convergence"):
                np.testing.assert_array_equal(a, b, err_msg=key)
            elif key == "first_loading":
                np.testing.assert_allclose(np.abs(b), np.abs(a), atol=1e-5,
                                           err_msg=key)
            else:
                np.testing.assert_allclose(b, a, atol=1e-5, err_msg=key)

    def test_half_unit_quantization_contract(self, rng):
        """Off-lattice values are rounded to the nearest half unit — the
        documented int8 quantization contract (exact for standard binary/
        categorical reports, which are already on the lattice)."""
        reports = make_reports(rng, R=24, E=12)
        noisy = reports + np.where(np.isnan(reports), 0.0, 0.05)
        lattice = np.where(np.isnan(noisy), np.nan,
                           np.round(np.clip(noisy, 0, 1) * 2) / 2)
        rep = np.full(24, 1.0 / 24)
        base = BASE._replace(storage_dtype="int8")
        out_noisy = _consensus_core_fused(*fused_args(noisy, rep), base)
        out_lattice = _consensus_core_fused(*fused_args(lattice, rep), base)
        np.testing.assert_array_equal(
            np.asarray(out_noisy["outcomes_adjusted"]),
            np.asarray(out_lattice["outcomes_adjusted"]))

    def test_scaled_events_rejected(self, rng):
        reports = make_reports(rng, R=24, E=12)
        E = reports.shape[1]
        scaled = np.zeros(E, dtype=bool)
        scaled[3] = True
        rep = np.full(24, 1.0 / 24)
        args = (jnp.asarray(reports), jnp.asarray(rep), jnp.asarray(scaled),
                jnp.zeros(E), jnp.ones(E))
        base = BASE._replace(storage_dtype="int8", any_scaled=True,
                             n_scaled=1)
        with pytest.raises(ValueError, match="int8"):
            _consensus_core_fused(*args, base)

    def test_xla_path_rejected(self, rng):
        reports = make_reports(rng, R=24, E=12)
        E = reports.shape[1]
        rep = np.full(24, 1.0 / 24)
        args = fused_args(reports, rep)
        with pytest.raises(ValueError, match="int8"):
            _consensus_core(*args,
                            ConsensusParams(storage_dtype="int8",
                                            any_scaled=False, has_na=True))


class TestShardedFrontEndGate:
    def test_sharded_rejects_int8_off_fused_path(self, rng):
        """On the CPU test platform the fused gate is closed (it requires a
        single real TPU), so an explicit int8 request must fail loudly —
        never fall through to the XLA path's continuous-fill storage."""
        from pyconsensus_tpu.parallel import make_mesh, sharded_consensus

        reports = make_reports(rng, R=16, E=8)
        with pytest.raises(ValueError, match="int8"):
            sharded_consensus(
                jnp.asarray(reports), mesh=make_mesh(),
                params=ConsensusParams(storage_dtype="int8",
                                       any_scaled=False, has_na=True))


class TestHybridAndConstructionGates:
    """ADVICE r2 (medium): int8 used to fall through to the hybrid
    clustering path, truncating continuous interpolated fills with a bare
    astype — silently wrong outcomes. Both the Oracle constructor and the
    hybrid driver itself must refuse."""

    def test_oracle_rejects_int8_hybrid(self, rng):
        from pyconsensus_tpu.oracle import Oracle

        reports = make_reports(rng, R=12, E=6)
        for algo in ("hierarchical", "dbscan"):
            with pytest.raises(ValueError, match="int8"):
                Oracle(reports=reports, algorithm=algo, backend="jax",
                       storage_dtype="int8")

    def test_oracle_rejects_unknown_storage_dtype(self, rng):
        from pyconsensus_tpu.oracle import Oracle

        reports = make_reports(rng, R=12, E=6)
        with pytest.raises(ValueError, match="storage_dtype"):
            Oracle(reports=reports, storage_dtype="float16")

    def test_hybrid_driver_rejects_int8(self, rng):
        from pyconsensus_tpu.models.pipeline import _consensus_hybrid

        reports = make_reports(rng, R=12, E=6)
        args = fused_args(reports, np.full(12, 1.0 / 12))
        with pytest.raises(ValueError, match="int8"):
            _consensus_hybrid(*args,
                              ConsensusParams(algorithm="hierarchical",
                                              storage_dtype="int8"))


class TestAutoStorageResolver:
    """parallel.sharded.resolve_auto_storage is the ONE auto-storage rule
    (round 2 kept a drifting mirror in bench.py). Contract: whatever it
    returns must resolve through resolve_params without raising — 'auto'
    can never produce a configuration the front-end then rejects."""

    @pytest.mark.parametrize("R,E", [(16, 8), (64, 256), (4097, 128),
                                     (8192, 4096), (10000, 2048)])
    @pytest.mark.parametrize("algorithm", ["sztorc", "ica", "k-means"])
    @pytest.mark.parametrize("any_scaled", [False, True])
    def test_auto_choice_always_resolves(self, R, E, algorithm, any_scaled):
        from pyconsensus_tpu.parallel import (make_mesh,
                                              resolve_auto_storage,
                                              resolve_params)

        mesh = make_mesh()
        p = ConsensusParams(algorithm=algorithm, any_scaled=any_scaled,
                            n_scaled=2 if any_scaled else 0, has_na=True)
        storage, reason = resolve_auto_storage(p, R, E, mesh)
        assert storage in ("int8", "bfloat16")
        assert reason
        resolved = resolve_params(p._replace(storage_dtype=storage),
                                  R, E, mesh)
        if storage == "int8":
            assert resolved.fused_resolution
            assert not any_scaled
        # int8 must never be picked off the fused path — resolve_params
        # raising would have failed the test already

    def test_no_pallas_closes_every_fused_gate(self):
        from pyconsensus_tpu.parallel import make_mesh, resolve_params

        mesh = make_mesh()
        p = ConsensusParams(allow_fused=False, any_scaled=False, has_na=True)
        resolved = resolve_params(p, 10000, 4096, mesh)
        assert not resolved.fused_resolution
        assert resolved.pca_method != "power-fused"
