"""Out-of-process fleet transport tests (ISSUE 15): wire-protocol
round trips and refusals, handshake rejection, PYC error-marshalling
fidelity, RPC client/server + bounded reconnect, log shipping with
verify-before-adopt, the process supervisor, and the REAL ``kill -9``
of a worker process mid-traffic with bit-identical takeover."""

import hashlib
import os
import signal
import socket
import struct
import tempfile
import threading
import time

import numpy as np
import pytest

from pyconsensus_tpu import faults, obs
from pyconsensus_tpu.faults import (ERROR_CODES, CheckpointCorruptionError,
                                    FailoverInProgressError, HandshakeError,
                                    InputError, ServiceOverloadError,
                                    TransportError, WorkerLostError)
from pyconsensus_tpu.serve.transport import wire
from pyconsensus_tpu.serve.transport.rpc import RpcClient, RpcServer
from pyconsensus_tpu.serve.transport.shipping import (LogShipper,
                                                      ShippingReceiver,
                                                      adopt_shipped)


@pytest.fixture(autouse=True)
def _under_protocol_witness(protocol_witness):
    """Every transport test runs under the runtime protocol witness
    (ISSUE 16): the observed durability-event order of each replicated
    operation — journal/commit/ship, then ack — must be consistent
    with the static CL901 happens-before graph, or the test fails with
    the witness JSON dumped (the dynamic mirror of CL901, exactly as
    ``lock_witness`` mirrors CL801 in test_fleet.py)."""
    yield


def pair():
    a, b = socket.socketpair()
    a.settimeout(10.0)
    b.settimeout(10.0)
    return a, b


# ---------------------------------------------------------------------------
# wire frames


class TestWireFrames:
    @pytest.mark.parametrize("codec", ["native", "json"])
    @pytest.mark.parametrize("seed", range(4))
    def test_round_trip_property(self, codec, seed, monkeypatch):
        """Random nested payloads with arrays of every serving dtype
        survive a frame round trip BIT-IDENTICAL, under both the
        msgpack and the JSON fallback codec."""
        if codec == "json":
            monkeypatch.setattr(wire, "_msgpack", None)
        rng = np.random.default_rng(seed)
        arrays = {
            "f64": rng.random((rng.integers(1, 9), rng.integers(1, 9))),
            "f32": rng.random(5).astype(np.float32),
            "i8": rng.integers(-2, 3, size=(3, 4)).astype(np.int8),
            "i64": rng.integers(0, 100, size=7),
            "bool": rng.random(6) < 0.5,
            "nan": np.array([np.nan, np.inf, -np.inf, -0.0, 0.5]),
        }
        msg = {"arrays": arrays, "n": int(rng.integers(100)),
               "f": float(rng.random()), "s": "héllo",
               "b": bytes(rng.integers(0, 256, size=17, dtype=np.uint8)),
               "none": None, "flag": True,
               "nested": [1, {"deep": arrays["f64"][0]}, "x"]}
        a, b = pair()
        wire.send_msg(a, msg)
        out = wire.recv_msg(b)
        for key, arr in arrays.items():
            got = out["arrays"][key]
            assert got.dtype == arr.dtype, key
            np.testing.assert_array_equal(got, arr, err_msg=key)
        # -0.0 and NaN cross bit-exactly (the serving lattice cares)
        assert np.signbit(out["arrays"]["nan"][3])
        assert out["n"] == msg["n"] and out["f"] == msg["f"]
        assert out["s"] == msg["s"] and out["b"] == msg["b"]
        assert out["none"] is None and out["flag"] is True
        np.testing.assert_array_equal(out["nested"][1]["deep"],
                                      arrays["f64"][0])

    def test_clean_close_returns_none(self):
        a, b = pair()
        a.close()
        assert wire.recv_msg(b) is None

    def test_truncated_frame_refused(self):
        """A peer dying mid-send leaves a torn frame: refused PYC601
        naming the check, never a half-decoded message."""
        a, b = pair()
        payload = b"x" * 100
        header = struct.Struct(">4sBBL32s").pack(
            wire.MAGIC, wire.WIRE_PROTOCOL_VERSION, 0, 200,
            hashlib.sha256(payload).digest())
        a.sendall(header + payload)     # claims 200, sends 100
        a.close()
        with pytest.raises(TransportError) as ei:
            wire.recv_msg(b)
        assert ei.value.error_code == "PYC601"
        assert ei.value.context["reason"] == "truncated"

    def test_bit_flipped_frame_refused(self):
        """One flipped payload bit -> digest refusal."""
        a, b = pair()
        codec, payload = wire._pack({"v": list(range(32))})
        damaged = bytearray(payload)
        damaged[len(damaged) // 2] ^= 0x10
        header = struct.Struct(">4sBBL32s").pack(
            wire.MAGIC, wire.WIRE_PROTOCOL_VERSION, codec, len(damaged),
            hashlib.sha256(payload).digest())
        a.sendall(header + bytes(damaged))
        with pytest.raises(TransportError) as ei:
            wire.recv_msg(b)
        assert ei.value.context["reason"] == "digest"

    def test_foreign_magic_refused(self):
        a, b = pair()
        a.sendall(b"HTTP" + b"\x00" * 38)
        with pytest.raises(TransportError) as ei:
            wire.recv_msg(b)
        assert ei.value.context["reason"] == "magic"

    def test_foreign_version_refused(self):
        a, b = pair()
        payload = b"{}"
        a.sendall(struct.Struct(">4sBBL32s").pack(
            wire.MAGIC, wire.WIRE_PROTOCOL_VERSION + 9, 0, len(payload),
            hashlib.sha256(payload).digest()) + payload)
        with pytest.raises(TransportError) as ei:
            wire.recv_msg(b)
        assert ei.value.context["reason"] == "version"

    def test_oversized_frame_refused_before_read(self):
        """The bounded read refuses on the LENGTH FIELD — no payload
        byte of an oversized frame is ever read."""
        a, b = pair()
        a.sendall(struct.Struct(">4sBBL32s").pack(
            wire.MAGIC, wire.WIRE_PROTOCOL_VERSION, 0,
            wire.MAX_FRAME_BYTES + 1, b"\x00" * 32))
        with pytest.raises(TransportError) as ei:
            wire.recv_msg(b)
        assert ei.value.context["reason"] == "oversized"

    def test_oversized_boundary_exact_at_limit_accepted(self):
        """A declared length EXACTLY at max_bytes is legal (the check
        is ``length > max_bytes``, not ``>=``) — the previous test
        exercises the refusal only far past the bound; this pair pins
        the boundary itself (ISSUE 16 satellite)."""
        obj = {"k": "v" * 100}
        _, payload = wire._pack(obj)
        a, b = pair()
        wire.send_msg(a, obj)
        assert wire.recv_msg(b, max_bytes=len(payload)) == obj

    def test_oversized_boundary_limit_plus_one_refused_with_context(self):
        """One byte past the limit refuses, and the PYC601 context
        carries the offending declared length AND the limit — what an
        operator needs to tell a fat-but-legitimate frame (raise the
        limit) from a corrupt length field (don't)."""
        obj = {"k": "v" * 100}
        _, payload = wire._pack(obj)
        a, b = pair()
        wire.send_msg(a, obj)
        with pytest.raises(TransportError) as ei:
            wire.recv_msg(b, max_bytes=len(payload) - 1)
        assert ei.value.context["reason"] == "oversized"
        assert ei.value.context["length"] == len(payload)
        assert ei.value.context["limit"] == len(payload) - 1

    def test_refusals_counted(self):
        before = obs.value("pyconsensus_transport_refused_total",
                           reason="magic") or 0
        a, b = pair()
        a.sendall(b"XXXX" + b"\x00" * 38)
        with pytest.raises(TransportError):
            wire.recv_msg(b)
        assert obs.value("pyconsensus_transport_refused_total",
                         reason="magic") == before + 1


# ---------------------------------------------------------------------------
# error marshalling


class TestErrorMarshalling:
    @pytest.mark.parametrize("code", sorted(ERROR_CODES))
    def test_every_taxonomy_code_round_trips(self, code):
        """PYC-coded errors cross the wire as the SAME class with
        message, code, and context intact — the fidelity that keeps
        client retry policy transport-agnostic."""
        cls = ERROR_CODES[code]
        exc = cls("the message", worker="w1", retry_after_s=0.75,
                  reason="queue_full", rows=[1, 2])
        out = wire.unmarshal_error(wire.marshal_error(exc))
        assert type(out) is cls
        assert out.error_code == code
        assert "the message" in str(out)
        assert out.context["worker"] == "w1"
        assert out.context["retry_after_s"] == 0.75
        assert out.context["rows"] == [1, 2]

    def test_retryable_fleet_errors_cross_intact(self):
        """The exact three the router's clients key retries on."""
        for cls, code in ((WorkerLostError, "PYC501"),
                          (FailoverInProgressError, "PYC502"),
                          (ServiceOverloadError, "PYC401")):
            out = wire.unmarshal_error(wire.marshal_error(
                cls("x", retry_after_s=1.5)))
            assert type(out) is cls and out.error_code == code
            assert out.context["retry_after_s"] == 1.5

    def test_numpy_context_values_sanitized(self):
        exc = InputError("bad", shape=(np.int64(3), np.int64(4)),
                         arr=np.arange(3), weird=object())
        out = wire.unmarshal_error(wire.marshal_error(exc))
        assert out.context["shape"] == [3, 4]
        assert out.context["arr"] == [0, 1, 2]
        assert isinstance(out.context["weird"], str)

    def test_non_taxonomy_error_becomes_pyc601(self):
        out = wire.unmarshal_error(wire.marshal_error(
            KeyError("missing")))
        assert isinstance(out, TransportError)
        assert out.context["remote_type"] == "KeyError"


# ---------------------------------------------------------------------------
# handshake


class TestHandshake:
    def run_server(self, sock, fingerprint=None):
        out = {}

        def serve():
            try:
                out["hello"] = wire.server_handshake(sock, "w0",
                                                     fingerprint)
            except Exception as exc:    # noqa: BLE001 — test observer
                out["error"] = exc
        t = threading.Thread(target=serve)
        t.start()
        return t, out

    def test_matching_fingerprint_accepted(self):
        a, b = pair()
        t, out = self.run_server(b)
        hello = wire.client_hello(a)
        t.join(5)
        assert "error" not in out
        assert hello["worker"] == "w0"

    def test_wrong_jaxlib_worker_refused_at_connect(self):
        """The ISSUE's contract verbatim: a worker whose runtime
        fingerprint differs (wrong jaxlib here) is refused by the
        ROUTER at connect with PYC602 naming the field."""
        from pyconsensus_tpu.tune.fingerprint import runtime_fingerprint

        foreign = dict(runtime_fingerprint())
        foreign["jaxlib"] = "0.0.1-foreign"
        a, b = pair()
        t, out = self.run_server(b, fingerprint=foreign)
        with pytest.raises(HandshakeError) as ei:
            wire.client_hello(a)
        t.join(5)
        assert ei.value.error_code == "PYC602"
        assert ei.value.context["field"] == "jaxlib"
        assert ei.value.context["found"] == "0.0.1-foreign"

    @pytest.mark.parametrize("field", ["platform", "x64", "n_devices",
                                       "generation"])
    def test_every_fingerprint_field_participates(self, field):
        from pyconsensus_tpu.tune.fingerprint import runtime_fingerprint

        foreign = dict(runtime_fingerprint())
        foreign[field] = "flipped"
        a, b = pair()
        t, out = self.run_server(b, fingerprint=foreign)
        with pytest.raises(HandshakeError) as ei:
            wire.client_hello(a)
        t.join(5)
        assert ei.value.context["field"] == field

    def test_protocol_version_refused_by_worker(self):
        """A future-protocol client is refused by the WORKER — and the
        refusal itself crosses the wire as PYC602."""
        a, b = pair()
        t, out = self.run_server(b)
        wire.send_msg(a, {"hello": {
            "protocol": wire.WIRE_PROTOCOL_VERSION + 1,
            "fingerprint": {}}})
        reply = wire.recv_msg(a)
        t.join(5)
        assert "error" in reply
        exc = wire.unmarshal_error(reply["error"])
        assert isinstance(exc, HandshakeError)
        assert exc.context["field"] == "protocol"
        assert isinstance(out.get("error"), HandshakeError)


# ---------------------------------------------------------------------------
# rpc client/server


@pytest.fixture
def echo_server():
    def boom(params):
        raise ServiceOverloadError("shed", reason="queue_full",
                                   retry_after_s=0.25)

    server = RpcServer({
        "echo": lambda params: params,
        "ping": lambda params: {"ok": True, "queue_depth": 0},
        "boom": boom,
    }, name="echo").start()
    yield server
    server.close()


class TestRpc:
    def test_call_round_trip(self, echo_server):
        client = RpcClient("127.0.0.1", echo_server.port, label="echo")
        arr = np.arange(12.0).reshape(3, 4)
        out = client.call("echo", {"x": arr, "k": 5})
        np.testing.assert_array_equal(out["x"], arr)
        assert out["k"] == 5
        client.close()

    def test_taxonomy_error_crosses(self, echo_server):
        client = RpcClient("127.0.0.1", echo_server.port, label="echo")
        with pytest.raises(ServiceOverloadError) as ei:
            client.call("boom")
        assert ei.value.context["retry_after_s"] == 0.25
        client.close()

    def test_unknown_method_is_pyc601(self, echo_server):
        client = RpcClient("127.0.0.1", echo_server.port, label="echo")
        with pytest.raises(TransportError) as ei:
            client.call("no_such")
        assert ei.value.context["reason"] == "method"
        client.close()

    def test_concurrent_calls_use_the_pool(self, echo_server):
        client = RpcClient("127.0.0.1", echo_server.port, pool=4,
                           label="echo")
        results = []

        def one(i):
            results.append(client.call("echo", {"i": i})["i"])
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20)
        assert sorted(results) == list(range(12))
        client.close()

    def test_connect_bounded_reconnect(self):
        """The retry_call path: a worker still booting refuses the
        first dials; the client's bounded reconnect rides through and
        the retry counter records it."""
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            port = probe.getsockname()[1]
        before = obs.value("pyconsensus_retries_total",
                           label="transport.connect:late") or 0
        server_box = {}

        def start_late():
            time.sleep(0.35)
            server_box["server"] = RpcServer(
                {"ping": lambda p: {"ok": True}},
                name="late", port=port).start()
        t = threading.Thread(target=start_late)
        t.start()
        client = RpcClient("127.0.0.1", port, label="late",
                           connect_retries=8)
        assert client.call("ping")["ok"] is True
        t.join(10)
        assert (obs.value("pyconsensus_retries_total",
                          label="transport.connect:late") or 0) > before
        client.close()
        server_box["server"].close()

    def test_handshake_refusal_not_retried(self, echo_server):
        """PYC602 is a taxonomy refusal — retrying an identical
        fingerprint cannot succeed, so exactly ONE handshake runs."""
        from pyconsensus_tpu.tune.fingerprint import runtime_fingerprint

        wrong = dict(runtime_fingerprint())
        wrong["jaxlib"] = "elsewhere"
        client = RpcClient("127.0.0.1", echo_server.port,
                           label="wrongfp", expect_fingerprint=wrong)
        before = obs.value("pyconsensus_retries_total",
                           label="transport.connect:wrongfp") or 0
        with pytest.raises(HandshakeError):
            client.call("ping")
        assert (obs.value("pyconsensus_retries_total",
                          label="transport.connect:wrongfp") or 0) \
            == before
        client.close()

    def test_rpc_latency_histogram_observed(self, echo_server):
        client = RpcClient("127.0.0.1", echo_server.port, label="echo")
        client.call("ping")
        client.close()
        prom = obs.render_prom()
        assert "pyconsensus_transport_rpc_seconds" in prom
        assert 'method="ping"' in prom


# ---------------------------------------------------------------------------
# fault sites


class TestTransportFaultSites:
    def test_sites_cataloged(self):
        for site in ("transport.send", "transport.recv",
                     "transport.connect", "shipping.append"):
            assert site in faults.FAULT_SITES

    def test_send_site_fires(self):
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "transport.send", "kind": "raise",
             "occurrences": [0]}])
        a, b = pair()
        with faults.armed(plan):
            with pytest.raises(OSError):
                wire.send_msg(a, {"x": 1})
        assert ("transport.send", 0, "raise") in plan.fired

    def test_recv_site_fires(self):
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "transport.recv", "kind": "raise",
             "occurrences": [0]}])
        a, b = pair()
        wire.send_msg(a, {"x": 1})
        with faults.armed(plan):
            with pytest.raises(OSError):
                wire.recv_msg(b)

    def test_connect_site_fires(self, echo_server):
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "transport.connect", "kind": "raise",
             "args": {"error": "input_error"}}])
        client = RpcClient("127.0.0.1", echo_server.port, label="echo")
        with faults.armed(plan):
            with pytest.raises(InputError):
                client.call("ping")
        client.close()

    def test_transient_send_fault_is_oserror_for_retry(self):
        """The injected default (os_error) is exactly what the
        reconnect path retries — taxonomy errors are not."""
        plan = faults.FaultPlan(seed=1, rules=[
            {"site": "transport.send", "kind": "raise",
             "occurrences": [0]}])
        a, b = pair()
        with faults.armed(plan):
            try:
                wire.send_msg(a, {})
                raised = None
            except Exception as exc:    # noqa: BLE001 — classify
                raised = exc
        assert isinstance(raised, OSError)
        assert not isinstance(raised, faults.ConsensusError)


# ---------------------------------------------------------------------------
# shipping


@pytest.fixture
def receiver(tmp_path):
    rcv = ShippingReceiver(tmp_path / "shipped").start()
    yield rcv
    rcv.close()


class TestShipping:
    def make_log(self, root, name="m1", rounds=1, blocks=2):
        from pyconsensus_tpu.serve.failover import DurableSession

        rng = np.random.default_rng(3)
        session = DurableSession.create(root, name, 8)
        for k in range(rounds):
            for _ in range(blocks):
                session.append(rng.choice([0.0, 1.0], size=(8, 3)))
            session.resolve()
        # one staged (uncommitted) block so mid-round state ships too
        session.append(rng.choice([0.0, 1.0], size=(8, 3)))
        return session

    def ship_all(self, shipper, root, name):
        log_dir = root / name
        for path in sorted(log_dir.rglob("*")):
            if path.is_file():
                rel = str(path.relative_to(log_dir)).replace(os.sep, "/")
                shipper.ship_file(name, rel, path)

    def test_ship_and_adopt_bit_identical(self, tmp_path, receiver):
        """The cross-process takeover contract: ship every record,
        verify-adopt on a different root, and the replayed session's
        next resolve is BIT-IDENTICAL to the original's."""
        local = tmp_path / "primary"
        session = self.make_log(local)
        shipper = LogShipper(receiver.host, receiver.port)
        self.ship_all(shipper, local, "m1")
        shipper.close()

        adopted = adopt_shipped(tmp_path / "shipped",
                                tmp_path / "standby", "m1")
        assert adopted.ledger.round == session.ledger.round
        np.testing.assert_array_equal(adopted.ledger.reputation,
                                      session.ledger.reputation)
        a = adopted.resolve()
        b = session.resolve()
        np.testing.assert_array_equal(a["outcomes_adjusted"],
                                      b["outcomes_adjusted"])
        np.testing.assert_array_equal(a["smooth_rep"],
                                      b["smooth_rep"])

    def test_bit_flip_refused_by_receiver(self, tmp_path, receiver):
        local = tmp_path / "primary"
        self.make_log(local)
        client = RpcClient(receiver.host, receiver.port, label="ship")
        ledger = (local / "m1" / "ledger.npz").read_bytes()
        damaged = bytearray(ledger)
        damaged[len(damaged) // 2] ^= 1
        with pytest.raises(CheckpointCorruptionError):
            client.call("ship", {
                "session": "m1", "relpath": "ledger.npz",
                "data": bytes(damaged),
                "digest": hashlib.sha256(ledger).hexdigest()})
        client.close()

    def test_path_escape_refused(self, receiver):
        client = RpcClient(receiver.host, receiver.port, label="ship")
        data = b"owned"
        for sess, rel in ((".." , "meta.json"),
                          ("m1", "../evil.json"),
                          ("m1", "staged/../../evil.npz")):
            with pytest.raises(CheckpointCorruptionError):
                client.call("ship", {
                    "session": sess, "relpath": rel, "data": data,
                    "digest": hashlib.sha256(data).hexdigest()})
        client.close()

    def test_torn_shipped_log_refused_at_adopt(self, tmp_path, receiver):
        """verify-before-adopt over the shipped copy: a torn ledger in
        the shipped tree refuses the takeover with PYC301."""
        local = tmp_path / "primary"
        self.make_log(local)
        shipper = LogShipper(receiver.host, receiver.port)
        self.ship_all(shipper, local, "m1")
        shipper.close()
        shipped_ledger = tmp_path / "shipped" / "m1" / "ledger.npz"
        shipped_ledger.write_bytes(
            shipped_ledger.read_bytes()[:40])     # torn
        with pytest.raises(CheckpointCorruptionError):
            adopt_shipped(tmp_path / "shipped", tmp_path / "standby2",
                          "m1")

    def test_append_idempotency_token_survives_replay(self, tmp_path,
                                                      receiver):
        """The retry-ambiguity contract (ISSUE 15): an append whose
        ack was lost carries an idempotency token; after the standby
        replays the shipped journal, the SAME token acknowledges
        without folding a second copy — bits match the never-killed
        single-append run."""
        from pyconsensus_tpu.serve.failover import DurableSession

        rng = np.random.default_rng(5)
        block = rng.choice([0.0, 1.0], size=(8, 3))
        session = DurableSession.create(tmp_path / "primary", "idem", 8)
        n1 = session.append(block, append_id="tok-1")
        # same token again on the LIVE session: no-op acknowledge
        assert session.append(block, append_id="tok-1") == n1
        assert session.state()["staged_blocks"] == 1
        shipper = LogShipper(receiver.host, receiver.port)
        self.ship_all(shipper, tmp_path / "primary", "idem")
        shipper.close()
        adopted = adopt_shipped(tmp_path / "shipped",
                                tmp_path / "standby3", "idem")
        # the token rode the journal record: the standby's dedupe set
        # is seeded at replay, so the client's retry still no-ops
        assert adopted.append(block, append_id="tok-1") == n1
        assert adopted.state()["staged_blocks"] == 1
        a = adopted.resolve()
        b = session.resolve()
        np.testing.assert_array_equal(a["outcomes_adjusted"],
                                      b["outcomes_adjusted"])
        np.testing.assert_array_equal(a["smooth_rep"], b["smooth_rep"])

    def test_shipping_append_fault_retries_transient(self, tmp_path,
                                                     receiver):
        """A transient OSError on the ship path is absorbed by the
        bounded retry; the record still lands."""
        local = tmp_path / "primary"
        self.make_log(local)
        plan = faults.FaultPlan(seed=2, rules=[
            {"site": "shipping.append", "kind": "raise",
             "occurrences": [0]}])
        shipper = LogShipper(receiver.host, receiver.port)
        with faults.armed(plan):
            with pytest.raises(OSError):
                # the fault fires at the SITE (before the send) — the
                # caller (worker) is who wraps the site in retry_call;
                # here we assert the site is armed and transient-typed
                shipper.ship_file("m1", "meta.json",
                                  local / "m1" / "meta.json")
        shipper.ship_file("m1", "meta.json", local / "m1" / "meta.json")
        shipper.close()
        assert (tmp_path / "shipped" / "m1" / "meta.json").exists()


# ---------------------------------------------------------------------------
# supervisor + the real cross-process fleet


def make_block(round_idx: int, block_idx: int,
               n_reporters: int = 12) -> np.ndarray:
    """tests/fleet_worker.py's deterministic traffic (the parent
    regenerates identical blocks for the reference run)."""
    rng = np.random.default_rng([7, round_idx, block_idx])
    block = rng.choice([0.0, 1.0], size=(n_reporters, 5))
    block[rng.random(block.shape) < 0.1] = np.nan
    return block


@pytest.fixture(scope="module")
def socket_fleet():
    """One module-scoped 2-worker SOCKET fleet (worker processes are
    the expensive resource here — boot once, exercise many times)."""
    from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig
    from pyconsensus_tpu.serve.service import ServeConfig

    log_dir = tempfile.mkdtemp(prefix="transport-fleet-")
    fleet = ConsensusFleet(FleetConfig(
        n_workers=2, transport="socket", log_dir=log_dir,
        worker=ServeConfig(pallas_buckets=False))).start()
    yield fleet
    fleet.close(drain=False, timeout=10.0)


class TestSocketFleet:
    def test_worker_processes_are_real(self, socket_fleet):
        pids = {w.process.proc.pid
                for w in socket_fleet.workers.values()}
        assert len(pids) == 2 and os.getpid() not in pids
        for w in socket_fleet.workers.values():
            assert w.heartbeat()

    def test_stateless_parity_vs_oracle(self, socket_fleet, rng):
        """A resolution served across the process boundary is
        BIT-IDENTICAL to a direct in-process Oracle resolution."""
        from pyconsensus_tpu.oracle import Oracle

        reports = rng.choice([0.0, 1.0], size=(12, 16))
        reports[rng.random(reports.shape) < 0.08] = np.nan
        res = socket_fleet.submit(reports=reports).result(timeout=120)
        ref = Oracle(reports=reports, backend="jax").consensus()
        np.testing.assert_array_equal(
            res["events"]["outcomes_adjusted"],
            ref["events"]["outcomes_adjusted"])
        # the worker served the PADDED BUCKET kernel: catch-snapped
        # outcomes are bit-identical, continuous tails sit inside the
        # documented equivalence band (docs/SERVING.md) — the wire
        # itself adds nothing (bit-exact frames, pinned above)
        np.testing.assert_allclose(res["agents"]["smooth_rep"],
                                   ref["agents"]["smooth_rep"],
                                   atol=1e-7)
        assert res["iterations"] == ref["iterations"]

    def test_session_round_parity_vs_inprocess(self, socket_fleet,
                                               tmp_path):
        """The same session traffic through the socket fleet and a
        single in-process service resolves bit-identically — the
        transport is invisible to the bits."""
        from pyconsensus_tpu.serve.failover import DurableSession

        socket_fleet.create_session("parity", n_reporters=12)
        ref = DurableSession.create(tmp_path / "ref", "parity", 12)
        for k in range(2):
            for j in range(2):
                block = make_block(k, j)
                socket_fleet.append("parity", block)
                ref.append(block)
            got = socket_fleet.submit(session="parity").result(120)
            want = ref.resolve()
            np.testing.assert_array_equal(
                np.asarray(got["events"]["outcomes_adjusted"]),
                np.asarray(want["outcomes_adjusted"]))
            np.testing.assert_array_equal(
                np.asarray(got["agents"]["smooth_rep"]),
                np.asarray(want["smooth_rep"]))

    def test_taxonomy_crosses_fleet_wire(self, socket_fleet):
        with pytest.raises(InputError):
            socket_fleet.session_state("no-such-session-anywhere")

    def test_wrong_fingerprint_client_refused(self, socket_fleet):
        from pyconsensus_tpu.tune.fingerprint import runtime_fingerprint

        worker = next(iter(socket_fleet.workers.values()))
        wrong = dict(runtime_fingerprint())
        wrong["jax"] = "9.9.9"
        client = RpcClient("127.0.0.1", worker.process.port,
                           label="wrong", expect_fingerprint=wrong)
        with pytest.raises(HandshakeError) as ei:
            client.call("ping")
        assert ei.value.context["field"] == "jax"
        client.close()

    def test_transport_metrics_flow(self, socket_fleet):
        assert (obs.value("pyconsensus_transport_frames_total",
                          direction="sent") or 0) > 0
        assert (obs.value("pyconsensus_transport_bytes_total",
                          direction="received") or 0) > 0


@pytest.mark.slow
class TestCrossProcessChaos:
    def test_kill9_worker_process_mid_traffic_bit_identical(self,
                                                            tmp_path):
        """THE acceptance contract: a real ``SIGKILL`` of a worker
        PROCESS mid-traffic loses zero resolutions — the standby
        process replays the SHIPPED log and every subsequent round is
        bit-identical to the never-killed reference run. The monitor's
        socket heartbeats (not in-memory staleness) detect the death."""
        from pyconsensus_tpu.serve.failover import DurableSession
        from pyconsensus_tpu.serve.fleet import (ConsensusFleet,
                                                 FleetConfig)
        from pyconsensus_tpu.serve.service import ServeConfig

        fleet = ConsensusFleet(FleetConfig(
            n_workers=3, transport="socket", monitor=True,
            heartbeat_timeout_s=1.0, heartbeat_interval_s=0.25,
            log_dir=str(tmp_path / "fleet"),
            worker=ServeConfig(pallas_buckets=False))).start()
        try:
            owner = fleet.create_session("chaos", n_reporters=12)
            results = []
            # round 0 completes; round 1 is mid-flight (one block
            # journaled + shipped, one not yet appended) at the kill
            for j in range(2):
                fleet.append("chaos", make_block(0, j))
            results.append(fleet.submit(session="chaos").result(120))
            fleet.append("chaos", make_block(1, 0))

            # SIGKILL the owning PROCESS — no drain, no cooperation
            handle = fleet.workers[owner]
            os.kill(handle.process.proc.pid, signal.SIGKILL)
            handle.process.proc.wait(timeout=30)

            # keep driving traffic with the fleet's retry discipline:
            # the heartbeat monitor declares the death over the wire,
            # the standby adopts the shipped log, the session continues
            def retried(fn, attempts=40):
                last = None
                for _ in range(attempts):
                    try:
                        return fn()
                    except (WorkerLostError, FailoverInProgressError,
                            TransportError, OSError) as exc:
                        last = exc
                        hint = getattr(exc, "context", {})
                        time.sleep(float(
                            hint.get("retry_after_s", 0.25) or 0.25))
                raise last

            st = retried(lambda: fleet.session_state("chaos"))
            # the shipped journal carried the mid-round append
            assert st["rounds_resolved"] == 1
            assert st["staged_blocks"] == 1
            new_owner = fleet.owner_of("chaos")
            assert new_owner != owner
            # a retried append carries a STABLE idempotency token —
            # if any attempt lands-but-loses-its-ack, the next one
            # acknowledges instead of double-folding (ISSUE 15)
            retried(lambda: fleet.append("chaos", make_block(1, 1),
                                         append_id="chaos-r1b1"))
            # and replaying the SAME id against the standby is a no-op
            before = fleet.session_state("chaos")["staged_blocks"]
            total = fleet.append("chaos", make_block(1, 1),
                                 append_id="chaos-r1b1")
            after = fleet.session_state("chaos")["staged_blocks"]
            assert after == before and total == 10
            results.append(retried(
                lambda: fleet.submit(session="chaos").result(120)))

            # the never-killed reference: identical traffic, one box
            ref = DurableSession.create(tmp_path / "ref", "chaos", 12)
            for k in range(2):
                for j in range(2):
                    ref.append(make_block(k, j))
                want = ref.resolve()
                got = results[k]
                np.testing.assert_array_equal(
                    np.asarray(got["events"]["outcomes_adjusted"]),
                    np.asarray(want["outcomes_adjusted"]),
                    err_msg=f"round {k}")
                np.testing.assert_array_equal(
                    np.asarray(got["agents"]["smooth_rep"]),
                    np.asarray(want["smooth_rep"]),
                    err_msg=f"round {k}")
        finally:
            fleet.close(drain=False, timeout=10.0)

    def test_standby_adopts_aot_cache_zero_retraces(self, tmp_path):
        """The AOT cache dir is the cross-process warm-start medium: a
        worker process booting against a populated cache adopts every
        configured bucket with ZERO pipeline retraces."""
        from pyconsensus_tpu.serve.service import (ConsensusService,
                                                   ServeConfig)
        from pyconsensus_tpu.serve.transport.supervisor import (
            SocketTransport)
        from pyconsensus_tpu.serve.fleet import (ConsensusFleet,
                                                 FleetConfig)

        aot = tmp_path / "aot"
        cfg = ServeConfig(warmup=((8, 16),), pallas_buckets=False,
                          aot_cache_dir=str(aot))
        # populate: an in-process service warms + persists
        svc = ConsensusService(cfg)
        svc.warm_buckets()
        persisted = obs.value("pyconsensus_aot_persist_total",
                              outcome="written")
        assert persisted and persisted >= 1

        fleet = ConsensusFleet(FleetConfig(
            n_workers=1, transport="socket",
            log_dir=str(tmp_path / "fleet"), worker=cfg)).start()
        try:
            w = fleet.workers["w0"]
            retraces = w.call("metric", {
                "name": "pyconsensus_jit_retraces_total",
                "labels": {"entry": "serve_bucket"}})["value"]
            adopted = w.call("metric", {
                "name": "pyconsensus_aot_load_total",
                "labels": {"outcome": "loaded"}})["value"]
            assert (retraces or 0) == 0
            assert adopted and adopted >= 1
        finally:
            fleet.close(drain=False, timeout=10.0)
