"""CLI demo driver tests (SURVEY.md §2 #12)."""

import numpy as np
import pytest

from pyconsensus_tpu.cli import main


class TestCli:
    def test_default_runs_example(self, capsys):
        assert main([]) == 0
        out = capsys.readouterr().out
        assert "Example (dense binary)" in out
        assert "Reporters" in out and "Events" in out
        assert "participation" in out

    def test_all_demo_flags(self, capsys):
        assert main(["--example", "--missing", "--scaled",
                     "--backend", "numpy"]) == 0
        out = capsys.readouterr().out
        assert "missing reports" in out
        assert "scaled events" in out

    def test_short_flags(self, capsys):
        assert main(["-x", "-m", "-s", "--iterations", "2"]) == 0
        assert "scaled events" in capsys.readouterr().out

    def test_algorithm_selection(self, capsys):
        assert main(["--example", "--algorithm", "k-means"]) == 0
        capsys.readouterr()

    def test_simulate(self, capsys):
        assert main(["--simulate", "--trials", "5",
                     "--reporters", "10", "--events", "5"]) == 0
        out = capsys.readouterr().out
        assert "Correct-outcome rate" in out
        assert "Liar reputation share" in out

    def test_simulate_rounds(self, capsys, tmp_path):
        pytest.importorskip("matplotlib").use("Agg")
        path = str(tmp_path / "rounds.png")
        assert main(["--simulate", "--rounds", "3", "--trials", "4",
                     "--reporters", "10", "--events", "5",
                     "--plot", path]) == 0
        out = capsys.readouterr().out
        assert "repeated-game sweep" in out
        assert "first vs final round" in out
        assert (tmp_path / "rounds.png").exists()

    def test_rounds_validation(self):
        with pytest.raises(SystemExit):
            main(["--simulate", "--rounds", "0"])

    def test_stream_file(self, capsys, tmp_path, rng):
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=16, E=20, liars=4,
                                       na_frac=0.1)
        path = str(save_reports(tmp_path / "r.npy", reports))
        assert main(["--file", path, "--stream",
                     "--panel-events", "6"]) == 0
        out = capsys.readouterr().out
        assert "Streaming resolution" in out
        assert "outcomes 0/0.5/1" in out

    def test_stream_requires_file(self):
        with pytest.raises(SystemExit):
            main(["--stream"])

    def test_stream_rejects_incompatible_flags(self, tmp_path):
        with pytest.raises(SystemExit):
            main(["--file", "x.npy", "--stream", "--algorithm", "k-means"])

    def test_stream_iterations(self, capsys, tmp_path, rng):
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=12, E=10, liars=3)
        path = str(save_reports(tmp_path / "r.npy", reports))
        assert main(["--file", path, "--stream", "--iterations", "3",
                     "--panel-events", "4"]) == 0
        assert "3 iteration(s)" in capsys.readouterr().out

    def test_stream_bad_path_clean_error(self, capsys):
        with pytest.raises(SystemExit):
            main(["--file", "/nonexistent/x.npy", "--stream"])
        assert "--stream" in capsys.readouterr().err

    def test_bad_flag_exits_nonzero(self):
        with pytest.raises(SystemExit):
            main(["--algorithm", "nope"])

    def test_scaled_outcomes_unscaled_in_output(self, capsys):
        main(["--scaled", "--backend", "numpy"])
        out = capsys.readouterr().out
        # the 16027.59 weighted-median outcome appears un-rescaled
        assert "16027.59" in out
