"""CLI demo driver tests (SURVEY.md §2 #12)."""

import numpy as np
import pytest

from pyconsensus_tpu.cli import main
from pyconsensus_tpu.serve.transport.multihost import multihost_capability

_MULTIHOST_REASON = multihost_capability()


class TestCli:
    def test_default_runs_example(self, capsys):
        assert main([]) == 0
        out = capsys.readouterr().out
        assert "Example (dense binary)" in out
        assert "Reporters" in out and "Events" in out
        assert "participation" in out

    def test_all_demo_flags(self, capsys):
        assert main(["--example", "--missing", "--scaled",
                     "--backend", "numpy"]) == 0
        out = capsys.readouterr().out
        assert "missing reports" in out
        assert "scaled events" in out

    def test_short_flags(self, capsys):
        assert main(["-x", "-m", "-s", "--iterations", "2"]) == 0
        assert "scaled events" in capsys.readouterr().out

    def test_algorithm_selection(self, capsys):
        assert main(["--example", "--algorithm", "k-means"]) == 0
        capsys.readouterr()

    def test_simulate(self, capsys):
        assert main(["--simulate", "--trials", "5",
                     "--reporters", "10", "--events", "5"]) == 0
        out = capsys.readouterr().out
        assert "Correct-outcome rate" in out
        assert "Liar reputation share" in out

    def test_simulate_rounds(self, capsys, tmp_path):
        pytest.importorskip("matplotlib").use("Agg")
        path = str(tmp_path / "rounds.png")
        assert main(["--simulate", "--rounds", "3", "--trials", "4",
                     "--reporters", "10", "--events", "5",
                     "--plot", path]) == 0
        out = capsys.readouterr().out
        assert "repeated-game sweep" in out
        assert "first vs final round" in out
        assert (tmp_path / "rounds.png").exists()

    def test_rounds_validation(self):
        with pytest.raises(SystemExit):
            main(["--simulate", "--rounds", "0"])

    def test_stream_file(self, capsys, tmp_path, rng):
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=16, E=20, liars=4,
                                       na_frac=0.1)
        path = str(save_reports(tmp_path / "r.npy", reports))
        assert main(["--file", path, "--stream",
                     "--panel-events", "6"]) == 0
        out = capsys.readouterr().out
        assert "Streaming resolution" in out
        assert "outcomes 0/0.5/1" in out

    def test_stream_requires_file(self):
        with pytest.raises(SystemExit):
            main(["--stream"])

    def test_stream_rejects_incompatible_flags(self, tmp_path):
        with pytest.raises(SystemExit):
            main(["--file", "x.npy", "--stream", "--algorithm", "k-means"])

    def test_stream_iterations(self, capsys, tmp_path, rng):
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=12, E=10, liars=3)
        path = str(save_reports(tmp_path / "r.npy", reports))
        assert main(["--file", path, "--stream", "--iterations", "3",
                     "--panel-events", "4"]) == 0
        assert "3 iteration(s)" in capsys.readouterr().out

    def test_stream_bad_path_clean_error(self, capsys):
        with pytest.raises(SystemExit):
            main(["--file", "/nonexistent/x.npy", "--stream"])
        assert "--stream" in capsys.readouterr().err

    def test_shard_example(self, capsys):
        """--shard resolves the demo on the full local-device mesh and
        prints the identical tables to the single-device run (the tiny
        x64 example is far inside the %.6f print resolution)."""
        assert main(["--example"]) == 0
        plain = capsys.readouterr().out
        assert main(["--example", "--shard"]) == 0
        sharded = capsys.readouterr().out
        assert "sharded over 8 device(s)" in sharded

        def tables(text):  # everything from the Reporters table down
            lines = text.splitlines()
            return lines[lines.index("Reporters"):]

        assert tables(sharded) == tables(plain)

    def test_shard_stream(self, capsys, tmp_path, rng):
        """--shard composes with --stream: panels are event-sharded."""
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=12, E=20, liars=3,
                                       na_frac=0.1)
        path = str(save_reports(tmp_path / "r.npy", reports))
        assert main(["--file", path, "--stream", "--shard",
                     "--panel-events", "8"]) == 0
        out = capsys.readouterr().out
        assert "8 device(s)" in out
        assert "outcomes 0/0.5/1" in out

    def test_shard_validation(self):
        with pytest.raises(SystemExit):
            main(["--example", "--shard", "--backend", "numpy"])

    def test_shard_simulate(self, capsys):
        """--simulate --shard: the MC trial axis rides the local mesh."""
        assert main(["--simulate", "--shard", "--trials", "6",
                     "--reporters", "8", "--events", "5"]) == 0
        out = capsys.readouterr().out
        assert "trials over 8 device(s)" in out
        assert "Correct-outcome rate" in out

    def test_stream_multihost_flags_validation(self, tmp_path, rng):
        """--coordinator/--hosts/--host-id must come together, with
        --stream, hosts >= 2, and host-id in range."""
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=8, E=6, liars=2)
        path = str(save_reports(tmp_path / "r.npy", reports))
        base = ["--file", path, "--stream"]
        for bad in ([*base, "--hosts", "2"],
                    [*base, "--coordinator", "localhost:1"],
                    [*base, "--coordinator", "localhost:1", "--hosts", "2"],
                    ["--file", path, "--coordinator", "localhost:1",
                     "--hosts", "2", "--host-id", "0"],     # no --stream
                    [*base, "--coordinator", "localhost:1", "--hosts", "1",
                     "--host-id", "0"],
                    [*base, "--coordinator", "localhost:1", "--hosts", "2",
                     "--host-id", "2"]):
            with pytest.raises(SystemExit):
                main(bad)

    @pytest.mark.slow
    @pytest.mark.xfail(
        condition=_MULTIHOST_REASON is not None, strict=False,
        reason=f"environmental: {_MULTIHOST_REASON} (ISSUE 15 "
               f"re-triage: parallel.initialize selects the gloo CPU "
               f"collectives client where the jaxlib ships one, and "
               f"this test then runs for real — see "
               f"tests/test_distributed.py)")
    def test_stream_multihost_two_processes(self, tmp_path, rng):
        """The real CLI deployment story: the same command on two OS
        processes (each with its own --host-id) joins one distributed
        runtime via --coordinator, splits the panels, and both print the
        identical resolution — equal to a single-host --stream run.
        Compared NUMERICALLY (the snapped outcome counts exactly, the
        printed reputations at the cross-process tolerance the repo uses
        elsewhere), never as raw text — logging noise and sub-print-digit
        summation drift must not flake this."""
        import re
        import subprocess
        import sys

        from conftest import collusion_reports, free_port, worker_env
        from pyconsensus_tpu.io import save_reports

        reports, _ = collusion_reports(rng, R=14, E=21, liars=4,
                                       na_frac=0.1)
        path = str(save_reports(tmp_path / "r.npy", reports))
        port = free_port()
        env = worker_env()
        # --shard included: each host's LOCAL 2-device mesh shards its
        # own round-robin panels (the composition that must NOT build a
        # global multi-process mesh)
        cmd = [sys.executable, "-m", "pyconsensus_tpu", "--file", path,
               "--stream", "--panel-events", "6", "--iterations", "2",
               "--shard"]

        procs = [subprocess.Popen(
            cmd + ["--coordinator", f"localhost:{port}", "--hosts", "2",
                   "--host-id", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        outs = []
        try:
            for proc in procs:
                out, _ = proc.communicate(timeout=180)
                outs.append(out)
        finally:
            for proc in procs:       # never leak a peer blocked in a
                if proc.poll() is None:  # cross-process collective
                    proc.kill()
        for proc, out in zip(procs, outs):
            assert proc.returncode == 0, f"host failed:\n{out}"
        assert "host 0/2" in outs[0] and "host 1/2" in outs[1]

        single = subprocess.run(cmd, capture_output=True, text=True,
                                env=env, timeout=180)
        assert single.returncode == 0, single.stdout + single.stderr

        def summary(text):
            """(outcome-count line, {reporter: (smooth_rep, bonus)})."""
            counts = re.search(r"outcomes 0/0\.5/1: (\d+/\d+/\d+)", text)
            assert counts, text
            rows = {int(m[0]): (float(m[1]), float(m[2])) for m in
                    re.findall(r"^\s+(\d+)\s+([\d.e+-]+)\s+([\d.e+-]+)\s*$",
                               text, re.M)}
            assert len(rows) == 8, text          # the top-8 table
            return counts.group(1), rows

        c_single, rows_single = summary(single.stdout)
        for out in outs:
            c_host, rows_host = summary(out)
            assert c_host == c_single            # snapped outcomes: exact
            assert rows_host.keys() == rows_single.keys()
            for rid, (rep, bonus) in rows_host.items():
                np.testing.assert_allclose(
                    (rep, bonus), rows_single[rid], atol=1e-5)

    def test_stream_csv_file(self, capsys, tmp_path, rng):
        """--stream on a .csv source stages in row chunks and resolves."""
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=12, E=10, liars=3,
                                       na_frac=0.1)
        path = str(save_reports(tmp_path / "r.csv", reports))
        assert main(["--file", path, "--stream",
                     "--panel-events", "4"]) == 0
        assert "Streaming resolution" in capsys.readouterr().out
        assert [f for f in tmp_path.iterdir() if "stage" in f.name] == []

    def test_file_with_bounds(self, capsys, tmp_path, rng):
        """--bounds JSON sidecar: scaled outcomes come back un-rescaled."""
        import json
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=10, E=4, liars=3)
        reports[:, 3] = reports[:, 3] * 400.0 + 100.0     # into [100, 500]
        path = str(save_reports(tmp_path / "r.npy", reports))
        bounds = [None, None, None,
                  {"scaled": True, "min": 100.0, "max": 500.0}]
        bpath = tmp_path / "bounds.json"
        bpath.write_text(json.dumps(bounds))
        assert main(["--file", path, "--bounds", str(bpath)]) == 0
        out = capsys.readouterr().out
        # the scaled event's outcome is in original units, not [0, 1]
        last_event_line = [l for l in out.splitlines()
                          if l.strip().startswith("3 ")][-1]
        assert any(float(tok) > 1.0 for tok in last_event_line.split()[1:3])

    def test_stream_with_bounds(self, capsys, tmp_path, rng):
        import json
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=10, E=4, liars=3)
        reports[:, 3] = reports[:, 3] * 400.0 + 100.0
        path = str(save_reports(tmp_path / "r.npy", reports))
        bounds = [None, None, None,
                  {"scaled": True, "min": 100.0, "max": 500.0}]
        bpath = tmp_path / "bounds.json"
        bpath.write_text(json.dumps(bounds))
        assert main(["--file", path, "--stream", "--bounds", str(bpath),
                     "--panel-events", "2"]) == 0
        assert "(+1 scaled)" in capsys.readouterr().out

    def test_bounds_validation(self, capsys, tmp_path):
        import json
        with pytest.raises(SystemExit):
            main(["--bounds", "b.json"])          # requires --file
        bpath = tmp_path / "bounds.json"
        bpath.write_text(json.dumps({"not": "a list"}))
        with pytest.raises(SystemExit):
            main(["--file", "r.npy", "--bounds", str(bpath)])
        assert "JSON list" in capsys.readouterr().err
        # wrong entry count against a real file
        import numpy as np
        from pyconsensus_tpu.io import save_reports
        path = str(save_reports(tmp_path / "r.npy", np.eye(3)))
        bpath.write_text(json.dumps([None]))
        with pytest.raises(SystemExit):
            main(["--file", path, "--bounds", str(bpath)])
        assert "entries" in capsys.readouterr().err

    def test_profile_writes_trace(self, capsys, tmp_path):
        out = tmp_path / "trace"
        assert main(["--example", "--profile", str(out)]) == 0
        assert "profiler trace written" in capsys.readouterr().out
        assert any(out.rglob("*"))          # trace events on disk

    def test_profile_covers_stream_and_simulate(self, capsys, tmp_path,
                                                rng):
        from conftest import collusion_reports
        from pyconsensus_tpu.io import save_reports
        reports, _ = collusion_reports(rng, R=10, E=8, liars=3)
        path = str(save_reports(tmp_path / "r.npy", reports))
        out1 = tmp_path / "t1"
        assert main(["--file", path, "--stream", "--panel-events", "4",
                     "--profile", str(out1)]) == 0
        assert any(out1.rglob("*"))
        out2 = tmp_path / "t2"
        assert main(["--simulate", "--trials", "4", "--reporters", "8",
                     "--events", "5", "--profile", str(out2)]) == 0
        assert any(out2.rglob("*"))

    def test_verbose_flag(self, capsys):
        assert main(["--example", "--verbose", "--backend", "numpy"]) == 0
        out = capsys.readouterr().out
        # the Oracle's verbose summary (printed ONLY under --verbose)
        assert "pyconsensus_tpu Oracle" in out
        assert "smooth_rep:" in out
        main(["--example", "--backend", "numpy"])
        assert "pyconsensus_tpu Oracle" not in capsys.readouterr().out

    def test_bad_flag_exits_nonzero(self):
        with pytest.raises(SystemExit):
            main(["--algorithm", "nope"])

    def test_scaled_outcomes_unscaled_in_output(self, capsys):
        main(["--scaled", "--backend", "numpy"])
        out = capsys.readouterr().out
        # the 16027.59 weighted-median outcome appears un-rescaled
        assert "16027.59" in out
