"""Randomized invariant fuzzing (SURVEY.md §4's property-based tests,
implemented with plain seeded sampling — no hypothesis dependency).

Every sampled configuration must satisfy the structural invariants of the
consensus mechanism regardless of shape, NA pattern, event mix, algorithm,
or backend:

- reputation vectors live on the simplex (non-negative, sum 1);
- binary/categorical outcomes land exactly on {0, 0.5, 1};
- scaled outcomes stay inside their event bounds;
- participation and certainty are in [0, 1];
- numpy and jax backends agree bit-identically on snapped outcomes;
- resolutions are deterministic (same inputs -> same outputs).
"""

import numpy as np
import pytest

from pyconsensus_tpu import Oracle

N_CASES = 25


def _random_case(rng):
    R = int(rng.integers(3, 40))
    E = int(rng.integers(2, 30))
    n_scaled = int(rng.integers(0, max(1, E // 3) + 1))
    scaled_cols = rng.choice(E, size=n_scaled, replace=False)
    reports = rng.choice([0.0, 0.5, 1.0], size=(R, E))
    bounds = [None] * E
    for j in scaled_cols:
        lo = float(rng.uniform(-100.0, 100.0))
        hi = lo + float(rng.uniform(1.0, 500.0))
        bounds[j] = {"scaled": True, "min": lo, "max": hi}
        reports[:, j] = rng.uniform(lo, hi, size=R)
    # NA pattern, but never an all-NaN column (reference precondition)
    mask = rng.random((R, E)) < rng.uniform(0.0, 0.3)
    keep = rng.integers(0, R, size=E)
    mask[keep, np.arange(E)] = False
    reports[mask] = np.nan
    reputation = None
    if rng.random() < 0.5:
        reputation = rng.random(R) + 0.05
    kwargs = {
        "algorithm": str(rng.choice(["sztorc", "fixed-variance", "ica",
                                     "k-means", "dbscan-jit"])),
        "max_iterations": int(rng.integers(1, 6)),
        "alpha": float(rng.uniform(0.05, 0.5)),
        "catch_tolerance": float(rng.uniform(0.05, 0.3)),
    }
    if kwargs["algorithm"] == "sztorc":
        # at fuzz shapes "auto" always resolves to eigh-cov, which would
        # leave the matrix-free strategies — including the warm-started
        # iterative power loop (max_iterations > 1 + v_init threading) —
        # entirely unfuzzed against numpy's exact per-iteration eigh
        kwargs["pca_method"] = str(rng.choice(["auto", "eigh-gram",
                                               "power"]))
    return reports, bounds, reputation, kwargs, np.asarray(
        [b is not None for b in bounds])


def _check_invariants(reports, bounds, reputation, kwargs, scaled):
    """Resolve on both backends and assert the full invariant set — the
    single source of truth shared by the jit and hybrid fuzz sweeps:
    simplex reputation, snapped outcomes on {0, 0.5, 1}, scaled outcomes
    inside their bounds, participation/certainty ranges, bit-identical
    cross-backend snapped outcomes, smooth_rep within a tiered
    cross-backend tolerance — 5e-6 for every configuration except
    iterated ``pca_method="power"``, which gets only a coarse 8e-2
    divergence guard (see the rationale
    at the tolerance below; ICA stays at 5e-6 because its
    convergence-or-fallback contract in models/ica.py makes even its
    iterated nonlinear fixed point reproducible — chaotic cases fall
    back to the first whitened component instead of returning a
    wandering iterate), and jax determinism on re-resolution."""
    results = {}
    for backend in ("numpy", "jax"):
        r = Oracle(reports=reports, event_bounds=bounds,
                   reputation=reputation, backend=backend,
                   **kwargs).consensus()
        for key in ("old_rep", "this_rep", "smooth_rep"):
            v = np.asarray(r["agents"][key], dtype=float)
            assert (v >= -1e-9).all(), (backend, key)
            assert v.sum() == pytest.approx(1.0, abs=1e-6), (backend, key)
        final = np.asarray(r["events"]["outcomes_final"], dtype=float)
        assert np.isin(final[~scaled], [0.0, 0.5, 1.0]).all(), backend
        for j in np.flatnonzero(scaled):
            lo, hi = bounds[j]["min"], bounds[j]["max"]
            assert lo - 1e-6 <= final[j] <= hi + 1e-6, (backend, j)
        assert 0.0 <= r["participation"] <= 1.0 + 1e-9, backend
        assert 0.0 <= r["certainty"] <= 1.0 + 1e-9, backend
        cert = np.asarray(r["events"]["certainty"], dtype=float)
        assert ((cert >= -1e-9) & (cert <= 1.0 + 1e-6)).all(), backend
        results[backend] = r
    # cross-backend: snapped outcomes bit-identical
    np.testing.assert_array_equal(
        np.asarray(results["numpy"]["events"]["outcomes_final"])[~scaled],
        np.asarray(results["jax"]["events"]["outcomes_final"])[~scaled],
        err_msg=str(kwargs))
    # iterated power-vs-eigh has NO tight reputation contract: the numpy
    # anchor always scores with the exact eigendecomposition, while
    # pca_method="power" carries per-iteration truncation error that the
    # redistribution loop amplifies on unlucky eigengaps (documented in
    # models/sztorc.py). The round-4 1400-seed fuzz measured an unbounded
    # tail — 1.7e-4 (seed 1539), 1.76e-3 (1616), 1.09e-2 (1930) — with
    # snapped outcomes bit-identical in EVERY case, which is the hard
    # contract. So that configuration gets only a coarse guard against
    # wholesale divergence (a flipped direction decision shows ~0.5);
    # every other configuration is held to 5e-6.
    rep_atol = (8e-2 if (kwargs.get("pca_method") == "power"
                         and kwargs.get("max_iterations", 1) > 1)
                else 5e-6)
    np.testing.assert_allclose(
        np.asarray(results["jax"]["agents"]["smooth_rep"], dtype=float),
        np.asarray(results["numpy"]["agents"]["smooth_rep"], dtype=float),
        atol=rep_atol, err_msg=str(kwargs))
    # determinism: resolving again reproduces the jax result exactly
    again = Oracle(reports=reports, event_bounds=bounds,
                   reputation=reputation, backend="jax",
                   **kwargs).consensus()
    np.testing.assert_array_equal(
        np.asarray(again["events"]["outcomes_final"]),
        np.asarray(results["jax"]["events"]["outcomes_final"]))


@pytest.mark.parametrize("seed", range(N_CASES))
def test_invariants_hold(seed):
    rng = np.random.default_rng(1000 + seed)
    reports, bounds, reputation, kwargs, scaled = _random_case(rng)
    _check_invariants(reports, bounds, reputation, kwargs, scaled)


@pytest.mark.parametrize("seed", (1478, 1539, 1616, 1930))
def test_iterated_power_truncation_seeds(seed):
    """Round-4 1400-seed fuzz finds: iterated power-vs-eigh reputation
    drift on unlucky eigengaps (measured tail: 1.7e-4, 1.76e-3, 1.09e-2
    — see the tiered ``rep_atol`` in :func:`_check_invariants`).
    Snapped outcomes stayed bit-identical on every found seed; these
    replays pin that and the coarse divergence guard."""
    rng = np.random.default_rng(1000 + seed)
    reports, bounds, reputation, kwargs, scaled = _random_case(rng)
    assert kwargs["pca_method"] == "power" and kwargs["max_iterations"] > 1
    _check_invariants(reports, bounds, reputation, kwargs, scaled)


def test_dirfix_tie_sign_canonical_seed2989():
    """Round-4 fuzz seed 1989 (rng 2989): a symmetric 4x2 lattice matrix
    puts the two direction-fix orientations EXACTLY equidistant from the
    current consensus, where "pick set1" was not sign-invariant — numpy
    eigh-cov and the jax Gram path returned opposite eigenvector signs
    and resolved OPPOSITE outcomes (smooth_rep reversed by 0.58). Pinned
    by sign-canonicalizing scores before the banded tie
    (ops.numpy_kernels.DIRFIX_TIE_ATOL) at every decision site."""
    rng = np.random.default_rng(1000 + 1989)
    reports, bounds, reputation, kwargs, scaled = _random_case(rng)
    assert kwargs["pca_method"] == "eigh-gram"
    _check_invariants(reports, bounds, reputation, kwargs, scaled)


@pytest.mark.parametrize("algorithm", ("hierarchical", "dbscan"))
@pytest.mark.parametrize("seed", range(6))
def test_hybrid_invariants_hold(seed, algorithm):
    """The invariant sweep for the HYBRID algorithms, which
    :func:`_random_case` never samples (its draw covers the jit table
    only — the host clustering paths are orders slower, so they get a
    small dedicated seed set instead of a share of every fuzz case).
    The hybrid paths are the most plausible source of nondeterminism or
    bounds drift (host scipy linkage / native-or-sklearn DBSCAN), so
    they run the identical full invariant set."""
    rng = np.random.default_rng(4000 + seed)
    reports, bounds, reputation, kwargs, scaled = _random_case(rng)
    kwargs.pop("pca_method", None)
    kwargs["algorithm"] = algorithm
    _check_invariants(reports, bounds, reputation, kwargs, scaled)


def test_dbscan_eps_boundary_backend_parity():
    """Round-4 300-seed fuzz find (rng seed 2120): the {0, 0.5, 1} report
    lattice places reporter-pair distances EXACTLY on the default eps^2
    boundary (one flipped event at eps=0.5 -> d2 = 0.25), where the Gram
    expansion's inexact cancellation over shared NA-fill values let numpy
    BLAS and XLA disagree on neighborhood membership — whole clusters
    then diverged (max smooth_rep gap 0.021 before the fix). Pinned by
    the shared boundary band ``clustering.DBSCAN_D2_ATOL``; this replays
    the found case plus a minimal engineered boundary matrix."""
    rng = np.random.default_rng(2120)
    reports, bounds, reputation, kwargs, scaled = _random_case(rng)
    assert kwargs["algorithm"] == "dbscan-jit"  # the found configuration
    got = {}
    for backend in ("numpy", "jax"):
        got[backend] = Oracle(reports=reports, event_bounds=bounds,
                              reputation=reputation, backend=backend,
                              **kwargs).consensus()
    np.testing.assert_allclose(
        np.asarray(got["jax"]["agents"]["smooth_rep"], dtype=float),
        np.asarray(got["numpy"]["agents"]["smooth_rep"], dtype=float),
        atol=5e-6)
    # minimal construction: a non-dyadic shared fill (NA in both rows of
    # one column) plus exactly one half-step disagreement puts the pair's
    # true squared distance exactly on eps^2 = 0.25
    reports = np.array([[0.0, 1.0, np.nan, 1.0],
                        [0.5, 1.0, np.nan, 1.0],
                        [0.0, 1.0, 1.0, 1.0],
                        [0.0, 0.0, 0.0, 0.0],
                        [1.0, 1.0, 1.0, 0.5]])
    rep = np.array([0.3, 0.1, 0.35, 0.15, 0.1])
    got = {}
    for backend in ("numpy", "jax"):
        got[backend] = Oracle(reports=reports, reputation=rep,
                              algorithm="dbscan-jit",
                              backend=backend).consensus()
    np.testing.assert_allclose(
        np.asarray(got["jax"]["agents"]["smooth_rep"], dtype=float),
        np.asarray(got["numpy"]["agents"]["smooth_rep"], dtype=float),
        atol=5e-6)


def test_hierarchical_threshold_boundary_backend_parity():
    """Round-5 (VERDICT r4 item 7): the linkage-cut analogue of the DBSCAN
    boundary case above. The {0, 0.5, 1} lattice realizes merge heights
    exactly on round thresholds (one half-step disagreement -> first merge
    at height 0.5), and the two backends reach the cut through different
    arithmetic (device f32 Gram expansion vs host f64 direct distances), so
    an exact ``<= t`` comparison could resolve the boundary merge on
    opposite sides and diverge whole-cluster. Pinned by the shared
    ``clustering._linkage_threshold`` band; the engineered matrix reuses
    the DBSCAN case's non-dyadic shared-NA fill so the device and host
    distances genuinely differ at the last ulp."""
    from pyconsensus_tpu.models import clustering as cl

    reports = np.array([[0.0, 1.0, np.nan, 1.0],
                        [0.5, 1.0, np.nan, 1.0],
                        [0.0, 1.0, 1.0, 1.0],
                        [0.0, 0.0, 0.0, 0.0],
                        [1.0, 1.0, 1.0, 0.5]])
    rep = np.array([0.3, 0.1, 0.35, 0.15, 0.1])
    # the pair (0, 1) sits at exact height 0.5; the cut is exactly there
    got = {}
    for backend in ("numpy", "jax"):
        got[backend] = Oracle(reports=reports, reputation=rep,
                              algorithm="hierarchical",
                              hierarchy_threshold=0.5,
                              backend=backend).consensus()
    np.testing.assert_allclose(
        np.asarray(got["jax"]["agents"]["smooth_rep"], dtype=float),
        np.asarray(got["numpy"]["agents"]["smooth_rep"], dtype=float),
        atol=5e-6)
    # the band must actually admit the boundary merge: rows 0 and 1 share
    # one cluster (conformity mass 0.4), whichever backend computed d
    X = np.where(np.isnan(reports), 0.0, reports)
    conf = cl.hierarchical_conformity(X, rep, 0.5)
    assert conf[0] == conf[1] and conf[0] >= 0.4 - 1e-12


from pyconsensus_tpu.models.pipeline import JIT_ALGORITHMS  # noqa: E402

#: k-means excluded: its deterministic evenly-spaced-ROW centroid seeding
#: (models/clustering.py::_seed_indices) makes the clustering itself
#: depend on row order by design
_ROW_ORDER_FREE_ALGOS = tuple(a for a in JIT_ALGORITHMS if a != "k-means")


@pytest.mark.parametrize("algorithm", JIT_ALGORITHMS)
@pytest.mark.parametrize("seed", (0, 5))
def test_event_permutation_equivariance(seed, algorithm):
    """Permuting event columns (with their bounds) permutes the per-event
    outputs identically and leaves the reporter-side outputs unchanged —
    no event may influence another through ordering (SURVEY.md §4's
    property-test suggestion, extended from reporters to events).
    Parametrized over every jit algorithm explicitly — a random draw left
    some scorers untested."""
    rng = np.random.default_rng(2000 + seed)
    reports, bounds, reputation, kwargs, scaled = _random_case(rng)
    kwargs["algorithm"] = algorithm
    E = reports.shape[1]
    perm = rng.permutation(E)
    base = Oracle(reports=reports, event_bounds=bounds,
                  reputation=reputation, backend="jax", **kwargs).consensus()
    permed = Oracle(reports=reports[:, perm],
                    event_bounds=[bounds[j] for j in perm],
                    reputation=reputation, backend="jax",
                    **kwargs).consensus()
    for key in ("outcomes_final", "certainty", "participation_columns"):
        np.testing.assert_allclose(
            np.asarray(permed["events"][key], dtype=float),
            np.asarray(base["events"][key], dtype=float)[perm],
            atol=1e-9, err_msg=key)
    np.testing.assert_allclose(
        np.asarray(permed["agents"]["smooth_rep"], dtype=float),
        np.asarray(base["agents"]["smooth_rep"], dtype=float),
        atol=1e-9, err_msg=str(kwargs))


@pytest.mark.parametrize("algorithm", _ROW_ORDER_FREE_ALGOS)
@pytest.mark.parametrize("seed", (0, 5))
def test_reporter_permutation_equivariance(seed, algorithm):
    """Permuting reporter rows (with their reputation) permutes the
    reporter-side outputs and leaves the event-side outputs unchanged —
    for every scorer without row-order-dependent seeding (see
    _ROW_ORDER_FREE_ALGOS)."""
    rng = np.random.default_rng(3000 + seed)
    reports, bounds, reputation, kwargs, scaled = _random_case(rng)
    kwargs["algorithm"] = algorithm
    R = reports.shape[0]
    if reputation is None:
        reputation = np.full(R, 1.0 / R)
    perm = rng.permutation(R)
    base = Oracle(reports=reports, event_bounds=bounds,
                  reputation=reputation, backend="jax", **kwargs).consensus()
    permed = Oracle(reports=reports[perm], event_bounds=bounds,
                    reputation=reputation[perm], backend="jax",
                    **kwargs).consensus()
    for key in ("smooth_rep", "reporter_bonus", "participation_rows"):
        np.testing.assert_allclose(
            np.asarray(permed["agents"][key], dtype=float),
            np.asarray(base["agents"][key], dtype=float)[perm],
            atol=1e-9, err_msg=key)
    np.testing.assert_allclose(
        np.asarray(permed["events"]["outcomes_final"], dtype=float),
        np.asarray(base["events"]["outcomes_final"], dtype=float),
        atol=1e-9, err_msg=str(kwargs))
