"""Worker for the FOUR-process distributed test (round 5, VERDICT r4
item 8) — launched as ``python distributed_worker4.py <process_id> <port>``
by tests/test_distributed.py. Each of the four OS processes contributes 2
virtual CPU devices to one 8-device global mesh.

Lean phase set (the 2-process worker keeps the broad coverage; this one
targets what only appears at >2 hosts):

1. event-sharded resolution over the 8-device mesh — rendezvous and
   cross-process collectives at 4 processes;
2. multi-host out-of-core streaming with an ODD panel split (3 panels
   over 4 hosts) — host 3 owns ZERO panels and must still enter every
   all-reduce in lockstep;
3. multi-host streamed k-means — the (R, k) distance accumulator
   all-reduces once per Lloyd pass, including from the zero-panel host.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

process_id, port = int(sys.argv[1]), sys.argv[2]

from pyconsensus_tpu.parallel import initialize  # noqa: E402

initialize(coordinator_address=f"localhost:{port}", num_processes=4,
           process_id=process_id)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from pyconsensus_tpu.models.pipeline import (ConsensusParams,  # noqa: E402
                                             consensus_light_jit)
from pyconsensus_tpu.parallel import (make_mesh,  # noqa: E402
                                      streaming_consensus)

assert jax.process_count() == 4, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

# the same deterministic matrix on every process
rng = np.random.default_rng(0)
truth = rng.choice([0.0, 1.0], size=16)
reports = np.tile(truth, (12, 1))
reports[:9] = np.abs(reports[:9] - (rng.random((9, 16)) < 0.1))
reports[9:] = 1.0 - truth

mesh = make_mesh(batch=1, event=8)
x = jax.device_put(jnp.asarray(reports), NamedSharding(mesh, P(None, "event")))
rep = jax.device_put(jnp.full((12,), 1.0 / 12.0), NamedSharding(mesh, P()))
sc = jax.device_put(jnp.zeros((16,), bool), NamedSharding(mesh, P("event")))
mn = jax.device_put(jnp.zeros((16,)), NamedSharding(mesh, P("event")))
mx = jax.device_put(jnp.ones((16,)), NamedSharding(mesh, P("event")))
params = ConsensusParams(algorithm="sztorc", max_iterations=2,
                         pca_method="eigh-gram")
out = consensus_light_jit(x, rep, sc, mn, mx, params)

outcomes = multihost_utils.process_allgather(out["outcomes_adjusted"],
                                             tiled=True)
smooth = np.asarray(out["smooth_rep"])
print("RESULT", ",".join(f"{float(v):g}" for v in np.ravel(outcomes)),
      flush=True)
print("REP", ",".join(f"{float(v):.6f}" for v in smooth), flush=True)

# phase 2: odd split — ceil(16/6) = 3 panels round-robin over 4 hosts:
# hosts 0..2 stream one panel each, host 3 streams NONE and must still
# hit every per-iteration all-reduce (zero local statistics, full result)
s_out = streaming_consensus(
    reports, panel_events=6,
    params=ConsensusParams(algorithm="sztorc", max_iterations=2),
    n_hosts=4)
print("STREAM", ",".join(f"{float(v):g}"
                         for v in s_out["outcomes_adjusted"]), flush=True)
print("STREAMREP", ",".join(f"{float(v):.6f}"
                            for v in s_out["smooth_rep"]), flush=True)

# phase 3: streamed k-means on the same odd/zero-panel split — the
# (R, k) distance accumulator all-reduces once per Lloyd assignment
# pass; centroid slices stay event-local on their owning hosts
k_out = streaming_consensus(
    reports, panel_events=6,
    params=ConsensusParams(algorithm="k-means", num_clusters=3,
                           max_iterations=2),
    n_hosts=4)
print("KMEANS", ",".join(f"{float(v):g}"
                         for v in k_out["outcomes_adjusted"]), flush=True)
print("KMEANSREP", ",".join(f"{float(v):.6f}"
                            for v in k_out["smooth_rep"]), flush=True)
