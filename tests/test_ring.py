"""Explicit ring-collective tests (parallel.ring) on the simulated
8-device CPU mesh, plus the hybrid ICI x DCN mesh builder
(parallel.distributed). The ring results must match both plain numpy and
the GSPMD kernel path — same math, different (fixed) reduction order."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pyconsensus_tpu.ops import jax_kernels as jk
from pyconsensus_tpu.parallel import (make_hybrid_mesh, make_mesh, num_slices,
                                      ring_allreduce, ring_first_pc,
                                      ring_gram, ring_matvec)
from pyconsensus_tpu.parallel.ring import shard_map
from jax.sharding import PartitionSpec as P


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(batch=1, event=8)


class TestRingAllreduce:
    @pytest.mark.parametrize("shape", [(8, 5), (16, 3), (7, 4), (1, 9), (24,)])
    def test_matches_psum(self, rng, mesh8, shape):
        """Ring all-reduce of per-device partials == sum over the axis,
        including leading dims not divisible by the 8 devices (padding)."""
        parts = rng.standard_normal((8,) + shape)

        def local(x):
            return ring_allreduce(x[0], "event")

        f = shard_map(local, mesh8, in_specs=P("event"), out_specs=P())
        out = f(jnp.asarray(parts))
        np.testing.assert_allclose(np.asarray(out), parts.sum(axis=0),
                                   rtol=1e-12)

    def test_scalarish(self, mesh8):
        parts = np.arange(8.0).reshape(8, 1)
        f = shard_map(lambda x: ring_allreduce(x[0], "event"),
                      mesh8, in_specs=P("event"), out_specs=P())
        np.testing.assert_allclose(np.asarray(f(jnp.asarray(parts))), [28.0])

    def test_deterministic_order(self, rng, mesh8):
        """Same inputs -> bitwise-identical sums across calls (the ring's
        fixed neighbor order is the whole point)."""
        parts = rng.standard_normal((8, 13, 7)).astype(np.float32)
        f = jax.jit(shard_map(lambda x: ring_allreduce(x[0], "event"),
                              mesh8, in_specs=P("event"), out_specs=P()))
        a = np.asarray(f(jnp.asarray(parts)))
        b = np.asarray(f(jnp.asarray(parts)))
        np.testing.assert_array_equal(a, b)


class TestRingGramMatvec:
    def test_gram(self, rng, mesh8):
        X = rng.standard_normal((24, 64))
        G = ring_gram(jnp.asarray(X), mesh8)
        np.testing.assert_allclose(np.asarray(G), X @ X.T, rtol=1e-10)

    def test_gram_uneven_reporters(self, rng, mesh8):
        # R=13 not divisible by 8: exercises the padding path on (R, R)
        X = rng.standard_normal((13, 40))
        G = ring_gram(jnp.asarray(X), mesh8)
        np.testing.assert_allclose(np.asarray(G), X @ X.T, rtol=1e-10)

    def test_matvec(self, rng, mesh8):
        X = rng.standard_normal((24, 64))
        v = rng.standard_normal(64)
        t = ring_matvec(jnp.asarray(X), jnp.asarray(v), mesh8)
        np.testing.assert_allclose(np.asarray(t), X @ v, rtol=1e-10)


class TestRingFirstPC:
    def test_matches_gram_kernel(self, rng, mesh8):
        X = rng.random((24, 64))
        rep = np.full(24, 1.0 / 24)
        l_ref, s_ref = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                             method="eigh-gram")
        l, s = ring_first_pc(jnp.asarray(X), jnp.asarray(rep), mesh8)
        sign = np.sign(np.dot(np.asarray(l), np.asarray(l_ref)))
        np.testing.assert_allclose(sign * np.asarray(l), np.asarray(l_ref),
                                   atol=1e-9)
        np.testing.assert_allclose(sign * np.asarray(s), np.asarray(s_ref),
                                   atol=1e-9)

    def test_nonuniform_reputation(self, rng, mesh8):
        X = rng.random((16, 32))
        rep = rng.random(16)
        rep /= rep.sum()
        l_ref, s_ref = jk.weighted_prin_comp(jnp.asarray(X), jnp.asarray(rep),
                                             method="eigh-gram")
        l, s = ring_first_pc(jnp.asarray(X), jnp.asarray(rep), mesh8)
        sign = np.sign(np.dot(np.asarray(l), np.asarray(l_ref)))
        np.testing.assert_allclose(sign * np.asarray(s), np.asarray(s_ref),
                                   atol=1e-9)

    def test_jits(self, rng, mesh8):
        X = jnp.asarray(rng.random((16, 32)))
        rep = jnp.full((16,), 1.0 / 16)
        f = jax.jit(lambda x, r: ring_first_pc(x, r, mesh8))
        l, s = f(X, rep)
        assert l.shape == (32,) and s.shape == (16,)


class TestHybridMesh:
    def test_single_slice_falls_back(self):
        """CPU devices report no slice_index -> one slice -> flat mesh."""
        assert num_slices() == 1
        m = make_hybrid_mesh()
        assert m.shape == {"batch": 1, "event": 8}
        m = make_hybrid_mesh(batch=2)
        assert m.shape == {"batch": 2, "event": 4}

    def test_multi_slice_layout(self):
        """Fake a 2-slice x 4-chip topology: event neighbors must be
        same-slice (ICI), batch crosses slices (DCN)."""

        class FakeDev:
            def __init__(self, i, s):
                self.id, self.slice_index = i, s

            def __repr__(self):
                return f"d{self.id}s{self.slice_index}"

        devs = [FakeDev(i, i // 4) for i in range(8)]
        assert num_slices(devs) == 2
        import numpy as _np

        from pyconsensus_tpu.parallel.distributed import _slice_index
        from jax.sharding import Mesh
        m = make_hybrid_mesh(devices=devs)
        assert isinstance(m, Mesh)
        grid = _np.asarray(m.devices)
        assert grid.shape == (2, 4)
        for row in grid:           # each event row lives in exactly 1 slice
            assert len({_slice_index(d) for d in row}) == 1

    def test_multi_slice_subdivided_batch(self):
        class FakeDev:
            def __init__(self, i, s):
                self.id, self.slice_index = i, s

        devs = [FakeDev(i, i // 4) for i in range(8)]
        import numpy as _np
        m = make_hybrid_mesh(batch=4, devices=devs)
        grid = _np.asarray(m.devices)
        assert grid.shape == (4, 2)
        for row in grid:
            assert len({d.slice_index for d in row}) == 1

    def test_bad_batch_rejected(self):
        class FakeDev:
            def __init__(self, i, s):
                self.id, self.slice_index = i, s

        devs = [FakeDev(i, i // 4) for i in range(8)]
        with pytest.raises(ValueError, match="multiple of the slice"):
            make_hybrid_mesh(batch=3, devices=devs)
