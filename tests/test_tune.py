"""pyconsensus_tpu.tune — the Pallas block-shape autotuner (ISSUE 7
tentpole b): legal-candidate sweeps under the kernels' VMEM fit
predicates, deterministic interpret-mode winners, atomic persistence +
cache-hit reload, provider wiring into ``pallas_kernels`` with stale-
value re-validation, and the block-shapes-never-change-results
invariant."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from pyconsensus_tpu import obs
from pyconsensus_tpu.ops import pallas_kernels as pk
from pyconsensus_tpu.tune import (TuneCache, autotune_cov,
                                  autotune_resolve, default_provider,
                                  shape_class)


@pytest.fixture(autouse=True)
def _restore_provider():
    """Every test leaves the kernel module's provider state as it found
    it (other suites must keep seeing the heuristics)."""
    prev = pk._TUNE_PROVIDER
    prev_auto = pk._TUNE_AUTOLOAD
    yield
    pk._TUNE_PROVIDER = prev
    pk._TUNE_AUTOLOAD = prev_auto


class TestCandidates:
    def test_resolve_candidates_legal(self):
        for R in (64, 1000, 10_008):
            for itemsize in (1, 2, 4):
                for c in pk.resolve_block_candidates(R, itemsize):
                    assert c % 128 == 0
                    assert pk.resolve_block_fits(R, c, itemsize)

    def test_resolve_candidates_cover_heuristic(self):
        assert 128 in pk.resolve_block_candidates(10_008, 4)

    def test_cov_candidates_legal_and_cover_heuristic(self):
        for E in (128, 2048, 100_000):
            for itemsize in (1, 2, 4):
                cands = pk.cov_tile_candidates(E, itemsize, True)
                assert all(t % 8 == 0 for t in cands)
                heuristic = pk.matmat_tile_rows(E, itemsize, True)
                assert heuristic in cands

    @pytest.mark.parametrize("nan_fill", [True, False])
    def test_cov_candidates_all_pass_fit_model(self, nan_fill):
        """EVERY candidate must satisfy the sweep's own legality model —
        including the appended heuristic (at compact DENSE storage the
        hand-measured heuristic exceeds the conservative model and must
        then stay OUT of the sweep space; review finding, ISSUE 7)."""
        for E in (256, 1024, 4096, 100_000):
            for itemsize in (1, 2, 4):
                for t in pk.cov_tile_candidates(E, itemsize, nan_fill):
                    assert pk.cov_tile_fits(t, E, itemsize), \
                        (E, itemsize, nan_fill, t)

    def test_no_fit_no_candidates(self):
        # R=60k f32: no column block fits the 14 MB budget
        assert pk.resolve_block_candidates(60_000, 4) == []


class TestProviderWiring:
    def test_tile_override_and_validation(self):
        default = pk.matmat_tile_rows(2048, 1, True)
        pk.set_tune_provider(
            lambda kind, **ctx: 32 if kind == "cov_tile_rows" else None)
        assert pk.matmat_tile_rows(2048, 1, True) == 32
        # an ILLEGAL provider value (not mult-of-8 / VMEM misfit) is
        # ignored, never trusted
        pk.set_tune_provider(lambda kind, **ctx: 12)
        assert pk.matmat_tile_rows(2048, 1, True) == default
        pk.set_tune_provider(lambda kind, **ctx: 1 << 20)
        assert pk.matmat_tile_rows(2048, 1, True) == default
        pk.set_tune_provider(None)
        assert pk.matmat_tile_rows(2048, 1, True) == default

    def test_garbage_provider_values_degrade_to_heuristic(self, rng):
        """A hand-edited cache can put ANY JSON behind "value" — a
        provider returning a string/float/bool/negative, or raising,
        must yield the heuristic, never crash a kernel build (review
        finding, ISSUE 7)."""
        default = pk.matmat_tile_rows(2048, 1, True)
        for bad in ("fast", 16.5, True, -8, 0, None):
            pk.set_tune_provider(lambda kind, _b=bad, **ctx: _b)
            assert pk.matmat_tile_rows(2048, 1, True) == default, bad
        def boom(kind, **ctx):
            raise RuntimeError("corrupt provider")
        pk.set_tune_provider(boom)
        assert pk.matmat_tile_rows(2048, 1, True) == default
        # end to end through the resolve kernel's tuned-width lookup
        pk.set_tune_provider(lambda kind, **ctx: "fast")
        x = jnp.asarray(rng.choice([0.0, 1.0], size=(16, 64)),
                        jnp.float32)
        rep = jnp.full((16,), 1 / 16, jnp.float32)
        fill = jnp.full((64,), 0.5, jnp.float32)
        out = pk.resolve_certainty_fused(x, rep, fill, jnp.sum(rep), 0.1,
                                         interpret=True)
        assert np.isfinite(np.asarray(out[0])).all()
        # an integral float IS accepted (JSON round-trips ints as such)
        pk.set_tune_provider(lambda kind, **ctx: 32.0)
        assert pk.matmat_tile_rows(2048, 1, True) == 32

    def test_resolve_width_override_changes_nothing_numeric(self, rng):
        """A tuned column width must change the grid, not the results:
        the fused resolution kernel at two widths is bit-identical."""
        x = jnp.asarray(rng.choice([0.0, 0.5, 1.0, np.nan],
                                   size=(16, 300)), jnp.float32)
        rep = jnp.full((16,), 1 / 16, jnp.float32)
        fill = jnp.full((300,), 0.5, jnp.float32)
        outs = {}
        for C in (128, 256):
            outs[C] = [np.asarray(o) for o in pk.resolve_certainty_fused(
                x, rep, fill, jnp.sum(rep), 0.1, block_cols=C,
                interpret=True)]
        for a, b in zip(outs[128], outs[256]):
            np.testing.assert_array_equal(a, b)

    def test_default_provider_serves_persisted_winner(self, tmp_path):
        """An entry persisted under this host's generation is served by
        the default provider at kernel-build time; absent entries fall
        through to the fallback chain (None = in-kernel heuristic)."""
        from pyconsensus_tpu.tune.autotune import (_entry_key,
                                                   tpu_generation)

        path = tmp_path / "cache.json"
        cache = TuneCache(path)
        key = _entry_key("cov_tile_rows", tpu_generation(), 1,
                         shape_class(2048), nan_fill=True)
        cache.put(key, {"value": 48})
        provider = default_provider(path)
        assert provider("cov_tile_rows", n_events=2048, itemsize=1,
                        nan_fill=True) == 48
        # absent shape class -> fallback (None on this generation)
        assert provider("cov_tile_rows", n_events=65_536, itemsize=1,
                        nan_fill=True) is None
        # end to end: the kernel sizing picks the persisted winner
        pk.set_tune_provider(provider)
        assert pk.matmat_tile_rows(2048, 1, True) == 48


class TestSweeps:
    def test_interpret_sweep_deterministic_and_persisted(self, tmp_path):
        path = tmp_path / "cache.json"
        obs.reset()
        e1 = autotune_resolve(64, n_events=96, interpret=True, path=path)
        assert e1["mode"] == "interpret"
        assert e1["value"] in e1["candidates"]
        assert obs.value("pyconsensus_autotune_sweeps_total",
                         kind="resolve_block_cols") == 1
        # the persisted file is valid JSON with the entry installed
        raw = json.loads(path.read_text())
        assert raw["version"] == 1
        assert any(v["value"] == e1["value"]
                   for v in raw["entries"].values())
        # second call: served from cache — NO sweep, same winner
        e2 = autotune_resolve(64, n_events=96, interpret=True, path=path)
        assert e2["value"] == e1["value"]
        assert obs.value("pyconsensus_autotune_sweeps_total",
                         kind="resolve_block_cols") == 1
        assert obs.value("pyconsensus_autotune_cache_hits_total",
                         kind="resolve_block_cols") == 1
        # force re-sweeps and re-lands the same deterministic winner
        e3 = autotune_resolve(64, n_events=96, interpret=True, path=path,
                              force=True)
        assert e3["value"] == e1["value"]

    def test_cov_sweep_deterministic_and_persisted(self, tmp_path):
        path = tmp_path / "cache.json"
        obs.reset()
        e1 = autotune_cov(256, n_reporters=24, interpret=True, path=path)
        e2 = autotune_cov(256, n_reporters=24, interpret=True, path=path)
        assert e1["value"] == e2["value"]
        assert e1["value"] in e1["candidates"]
        assert obs.value("pyconsensus_autotune_sweeps_total",
                         kind="cov_tile_rows") == 1
        assert obs.value("pyconsensus_autotune_cache_hits_total",
                         kind="cov_tile_rows") == 1

    def test_cov_sweep_preserves_provider_autoload(self, tmp_path):
        """The cov sweep's scoped per-candidate override must not latch
        the lazy default-provider autoload off: a fresh process that
        tunes and then builds kernels must pick its own winner up
        (review finding, ISSUE 7)."""
        pk._TUNE_PROVIDER = None
        pk._TUNE_AUTOLOAD = True
        autotune_cov(256, n_reporters=24, interpret=True,
                     path=tmp_path / "cache.json")
        assert pk._TUNE_PROVIDER is None
        assert pk._TUNE_AUTOLOAD is True

    def test_storage_dtypes_key_separately(self, tmp_path):
        path = tmp_path / "cache.json"
        autotune_resolve(64, n_events=96, storage_dtype="int8",
                         interpret=True, path=path)
        autotune_resolve(64, n_events=96, storage_dtype="",
                         interpret=True, path=path)
        raw = json.loads(path.read_text())
        assert len(raw["entries"]) == 2

    def test_unfittable_shape_raises(self, tmp_path):
        with pytest.raises(ValueError, match="XLA path"):
            autotune_resolve(60_000, storage_dtype="float32",
                             interpret=True,
                             path=tmp_path / "cache.json")


class TestCacheDurability:
    def test_corrupt_cache_treated_as_empty(self, tmp_path, capsys):
        path = tmp_path / "cache.json"
        path.write_text("{torn")
        cache = TuneCache(path)
        assert cache.entries == {}
        assert "unreadable" in capsys.readouterr().err
        # a sweep then rewrites a clean file
        autotune_resolve(64, n_events=96, interpret=True, path=path)
        assert json.loads(path.read_text())["version"] == 1

    def test_foreign_version_ignored(self, tmp_path, capsys):
        path = tmp_path / "cache.json"
        path.write_text(json.dumps({"version": 99, "entries": {"k": 1}}))
        cache = TuneCache(path)
        assert cache.entries == {}
        assert "version" in capsys.readouterr().err

    def test_atomic_write_fault_site(self, tmp_path):
        """The persistence rides the faults machinery: a seeded raise at
        tune.cache_write surfaces, and the file keeps its previous
        content (atomic_write never tears)."""
        from pyconsensus_tpu.faults import plan as fplan

        path = tmp_path / "cache.json"
        cache = TuneCache(path)
        cache.put("a", {"value": 1})
        plan = fplan.FaultPlan(
            seed=3, rules=[fplan.FaultRule("tune.cache_write", "raise")])
        with fplan.armed(plan):
            with pytest.raises(Exception):
                cache.put("b", {"value": 2})
        assert json.loads(path.read_text())["entries"] == {"a": {"value": 1}}


class TestCLI:
    def test_module_cli_json_line(self, tmp_path, capsys):
        from pyconsensus_tpu.tune.__main__ import main

        main(["--reporters", "64", "--events", "128",
              "--probe-events", "96", "--probe-reporters", "24",
              "--interpret", "--cache", str(tmp_path / "c.json")])
        out = capsys.readouterr().out.strip().splitlines()[-1]
        d = json.loads(out)
        assert d["cov_tile_rows"]["value"] in \
            d["cov_tile_rows"]["candidates"]
        assert d["resolve_block_cols"]["value"] in \
            d["resolve_block_cols"]["candidates"]
