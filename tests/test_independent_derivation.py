"""Independent second derivation of the frozen golden vectors
(VERDICT r2, missing #2 / next-round #2).

The GOLDEN numbers in test_oracle.py were frozen from the package's own
numpy backend — they pin regressions, but a systematic reconstruction bug
would be frozen as "correct". This module re-implements the SURVEY.md
§3.5 consensus formulas **from scratch, naively**: explicit Python loops,
``math.isnan`` scalar tests, a dense E×E float64 covariance fed to
``np.linalg.eigh`` — sharing NOTHING with ``pyconsensus_tpu`` (no imports
from the package; the only shared assets are the fixture matrices and the
frozen numbers themselves, both plain data). Every frozen golden the
sztorc/fixed-variance (§3.5 PCA-chain) path covers is asserted against
this second derivation.

Clustering-variant goldens (k-means/dbscan/hierarchical) are NOT
re-derived here: their numbers hang off a partition, not the §3.5
formulas, and the partition is already pinned against an independent
implementation (sklearn) in test_native.py / test_plots.py parity tests.

Scope note: agreement of two independent implementations pins the
*reconstruction*, not the reference (the /root/reference mount has been
empty every round — see SURVEY.md header). If the mount ever populates,
SURVEY.md §8 step 6 supersedes both with R-derived vectors.
"""

import math

import numpy as np
import pytest

from test_oracle import (CANONICAL, GOLDEN, GOLDEN_VARIANTS, MISSING,
                         SCALED_BOUNDS, SCALED_REPORTS)

# ---------------------------------------------------------------------------
# The naive derivation. Formulas transcribed from SURVEY.md §3.4-§3.5 and
# §2 #5-#9 prose, deliberately in the dumbest possible style.
# ---------------------------------------------------------------------------


def _snap(x, tol):
    if x < 0.5 - tol:
        return 0.0
    if x > 0.5 + tol:
        return 1.0
    return 0.5


def _norm(v):
    t = sum(v)
    if t == 0.0:
        return list(v)
    return [x / t for x in v]


def _dirfix(scores, filled, rep):
    """nonconformity: pick the orientation whose implied outcomes sit
    closer to the current reputation-weighted outcomes; return it in
    non-negative form (SURVEY.md §2 #5). Ties follow the round-4 rule
    (SURVEY.md §8 item 9): scores are sign-canonicalized first (at an
    exact tie "pick set1" is not sign-invariant) and the comparison is
    banded by DIRFIX_TIE_ATOL — re-derived here from the spec, not
    shared with the implementation."""
    R, E = len(filled), len(filled[0])
    # canon_sign re-derived: flip so the largest-|value| entry (first
    # index on ties) is positive
    besti, bestv = 0, 0.0
    for i, s in enumerate(scores):
        if abs(s) > bestv:
            besti, bestv = i, abs(s)
    sgn = 1.0 if scores[besti] >= 0.0 else -1.0
    scores = [s * sgn for s in scores]
    set1 = [s + abs(min(scores)) for s in scores]
    set2 = [s - max(scores) for s in scores]
    old = [sum(rep[i] * filled[i][j] for i in range(R)) for j in range(E)]
    n1w, n2w = _norm(set1), _norm(set2)
    new1 = [sum(n1w[i] * filled[i][j] for i in range(R)) for j in range(E)]
    new2 = [sum(n2w[i] * filled[i][j] for i in range(R)) for j in range(E)]
    d1 = sum((new1[j] - old[j]) ** 2 for j in range(E))
    d2 = sum((new2[j] - old[j]) ** 2 for j in range(E))
    if d1 - d2 <= 1e-9 * (d1 + d2):
        return set1
    return [-s for s in set2]


def _weighted_pcs(filled, rep, k):
    """Weighted PCA by dense E×E covariance + eigh (SURVEY.md §3.5):
    mu = rep^T X, D = X - mu, cov = D^T diag(rep) D / (1 - sum rep²).
    Returns (scores per component desc-eigenvalue, explained fractions)."""
    R, E = len(filled), len(filled[0])
    mu = [sum(rep[i] * filled[i][j] for i in range(R)) for j in range(E)]
    dev = [[filled[i][j] - mu[j] for j in range(E)] for i in range(R)]
    denom = 1.0 - sum(r * r for r in rep)
    if denom == 0.0:
        denom = 1.0
    cov = np.zeros((E, E))
    for a in range(E):
        for b in range(E):
            cov[a, b] = sum(rep[i] * dev[i][a] * dev[i][b]
                            for i in range(R)) / denom
    eigvals, eigvecs = np.linalg.eigh(cov)
    order = np.argsort(eigvals)[::-1][:k]
    scores = []
    for c in order:
        vec = eigvecs[:, c]
        scores.append([sum(dev[i][j] * vec[j] for j in range(E))
                       for i in range(R)])
    pos = [max(float(eigvals[c]), 0.0) for c in order]
    total = float(np.clip(eigvals, 0.0, None).sum())
    explained = [p / total if total > 0 else 0.0 for p in pos]
    return scores, explained


def _scores(filled, rep, algorithm, variance_threshold, max_components):
    if algorithm == "sztorc":
        scores, _ = _weighted_pcs(filled, rep, 1)
        return _dirfix(scores[0], filled, rep)
    # fixed-variance: blend direction-fixed component scores weighted by
    # explained variance; include component c while the cumulative
    # explained variance BEFORE c is under the threshold (c=0 always)
    k = min(max_components, min(len(filled), len(filled[0])))
    scores, explained = _weighted_pcs(filled, rep, k)
    cum = 0.0
    w = []
    for c in range(k):
        w.append(explained[c] if (c == 0 or cum < variance_threshold)
                 else 0.0)
        cum += explained[c]
    wt = sum(w)
    w = ([x / wt for x in w] if wt > 0
         else [1.0 / sum(1 for x in w if x) if x else 0.0 for x in w])
    R = len(filled)
    adj = [0.0] * R
    for c in range(k):
        fixed = _dirfix(scores[c], filled, rep)
        for i in range(R):
            adj[i] += w[c] * fixed[i]
    return adj


def _weighted_median(pairs):
    """Sorted-cumulative-weight median with the lower/upper midpoint rule
    on an exact 0.5 hit — the shared MEDIAN_TIE_ATOL rule (round 4
    unified the kernels on this absolute epsilon; SURVEY.md §2 #8)."""
    pairs = sorted(pairs, key=lambda p: p[0])
    total = sum(w for _, w in pairs)
    cum = 0.0
    for idx, (v, w) in enumerate(pairs):
        cum += w / total
        if cum >= 0.5 - 1e-9:
            if abs(cum - 0.5) <= 1e-9 and idx + 1 < len(pairs):
                return 0.5 * (v + pairs[idx + 1][0])
            return v
    return pairs[-1][0]


def naive_consensus(reports, event_bounds=None, max_iterations=1,
                    algorithm="sztorc", alpha=0.1, tol=0.1, conv=1e-6,
                    variance_threshold=0.9, max_components=5):
    X = [list(map(float, row)) for row in np.asarray(reports, np.float64)]
    R, E = len(X), len(X[0])
    scaled = [False] * E
    mins, maxs = [0.0] * E, [1.0] * E
    if event_bounds:
        for j, b in enumerate(event_bounds):
            if b and b.get("scaled"):
                scaled[j] = True
                mins[j], maxs[j] = float(b["min"]), float(b["max"])
    for j in range(E):
        if scaled[j]:
            span = (maxs[j] - mins[j]) or 1.0
            for i in range(R):
                X[i][j] = (X[i][j] - mins[j]) / span

    rep = [1.0 / R] * R

    # interpolate: reputation-weighted column mean over reporters who did
    # report; binary fills snap through catch; empty column -> 0.5
    filled = [row[:] for row in X]
    for j in range(E):
        num = den = 0.0
        for i in range(R):
            if not math.isnan(X[i][j]):
                num += rep[i] * X[i][j]
                den += rep[i]
        f = num / den if den > 0.0 else 0.5
        if not scaled[j]:
            f = _snap(f, tol)
        for i in range(R):
            if math.isnan(X[i][j]):
                filled[i][j] = f

    this_rep = rep
    for _ in range(max(max_iterations, 1)):
        adj = _scores(filled, rep, algorithm, variance_threshold,
                      max_components)
        if max(abs(a) for a in adj) == 0.0:
            this_rep = list(rep)
        else:
            mean_rep = sum(rep) / R
            this_rep = _norm([adj[i] * rep[i] / mean_rep for i in range(R)])
        new_rep = [alpha * this_rep[i] + (1 - alpha) * rep[i]
                   for i in range(R)]
        delta = max(abs(new_rep[i] - rep[i]) for i in range(R))
        rep = new_rep
        if delta <= conv:
            break

    # outcomes: reputation restricted to actual reporters, weighted mean
    # (binary, catch-snapped) or weighted median (scaled); a column nobody
    # reported falls back to the full-rep mean of the filled column
    raw, adjusted, final = [0.0] * E, [0.0] * E, [0.0] * E
    for j in range(E):
        wsum = vsum = 0.0
        pairs = []
        for i in range(R):
            if not math.isnan(X[i][j]):
                wsum += rep[i]
                vsum += rep[i] * filled[i][j]
                pairs.append((filled[i][j], rep[i]))
        if wsum <= 0.0:
            raw[j] = (sum(rep[i] * filled[i][j] for i in range(R))
                      / sum(rep))
        elif scaled[j]:
            raw[j] = _weighted_median(pairs)
        else:
            raw[j] = vsum / wsum
        adjusted[j] = raw[j] if scaled[j] else _snap(raw[j], tol)
        final[j] = (adjusted[j] * (maxs[j] - mins[j]) + mins[j]
                    if scaled[j] else adjusted[j])

    certainty = []
    for j in range(E):
        c = 0.0
        for i in range(R):
            agree = (abs(filled[i][j] - adjusted[j]) <= tol if scaled[j]
                     else filled[i][j] == adjusted[j])
            if agree:
                c += rep[i]
        certainty.append(c)

    return {
        "this_rep": this_rep,
        "smooth_rep": rep,
        "outcomes_final": final,
        "event_certainty": certainty,
        "certainty": sum(certainty) / E,
    }


# ---------------------------------------------------------------------------
# Assertions: the naive derivation must land on the SAME frozen numbers.
# ---------------------------------------------------------------------------

_INPUTS = {
    "canonical": (CANONICAL, None),
    "missing": (MISSING, None),
    "scaled": (SCALED_REPORTS, SCALED_BOUNDS),
}


@pytest.mark.parametrize("fixture,max_iterations", sorted(GOLDEN))
def test_frozen_goldens_match_independent_derivation(fixture, max_iterations):
    reports, bounds = _INPUTS[fixture]
    g = GOLDEN[(fixture, max_iterations)]
    r = naive_consensus(reports, bounds, max_iterations)
    np.testing.assert_allclose(r["this_rep"], g["this_rep"],
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(r["smooth_rep"], g["smooth_rep"],
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(r["outcomes_final"], g["outcomes_final"],
                               rtol=1e-10)
    np.testing.assert_allclose(r["event_certainty"], g["event_certainty"],
                               rtol=1e-10, atol=1e-12)
    assert r["certainty"] == pytest.approx(g["certainty"], rel=1e-10)


def test_fixed_variance_golden_matches_independent_derivation():
    g = GOLDEN_VARIANTS["fixed-variance"]
    r = naive_consensus(CANONICAL, None, 1, algorithm="fixed-variance")
    np.testing.assert_allclose(r["smooth_rep"], g["smooth_rep"],
                               rtol=1e-10, atol=1e-12)
    assert r["certainty"] == pytest.approx(g["certainty"], rel=1e-10)
    np.testing.assert_array_equal(r["outcomes_final"], [1.0, 0.5, 0.5, 0.0])


def test_canonical_iterative_resolution():
    """The §3.5 lie-detector property, derived independently: iteration
    concentrates reputation on the PCA-coherent majority and resolves the
    3-vs-3 ties toward it (SURVEY.md §0)."""
    one = naive_consensus(CANONICAL, None, 1)
    five = naive_consensus(CANONICAL, None, 5)
    assert one["outcomes_final"] == [1.0, 0.5, 0.5, 0.0]
    assert five["outcomes_final"] == [1.0, 1.0, 0.0, 0.0]
    assert (sum(five["smooth_rep"][:4]) / 4
            > sum(five["smooth_rep"][4:]) / 2)


def test_catch_boundary_is_a_float_knife_edge():
    """Documents the finding that forced the round-3 missing-fixture
    re-freeze: a fill mean of mathematically-exactly 2/5 sits exactly ON
    the snap boundary ``0.5 - 0.1`` (the two are bit-equal in f64), where
    ``x < boundary`` is False and the fill snaps to 0.5 — but the same
    mean computed through a renormalized reputation vector
    (sum(6 * 1/6) = 1 - 1ulp) lands one ulp BELOW the boundary and snaps
    to 0.0. Golden fixtures must therefore keep fill statistics robustly
    off the {0.5-tol, 0.5+tol} boundaries; SURVEY.md §8 step 3 flags the
    reference's exact boundary rule as unverifiable until the mount
    populates."""
    tol = 0.1
    assert 0.5 - tol == 0.4                      # boundary bit-equal to 0.4
    assert _snap(0.4, tol) == 0.5                # on-boundary: not below
    rep = np.full(6, 1 / 6)
    rep = rep / rep.sum()                        # 1-ulp renormalization
    col = np.array([1.0, 0, np.nan, 1, 0, 0])
    present = ~np.isnan(col)
    mean = float((np.where(present, col, 0) * rep).sum()
                 / (present * rep).sum())
    assert mean < 0.5 - tol                      # now one ulp BELOW
    assert _snap(mean, tol) == 0.0
