"""Oracle API tests: golden examples, the numpy<->jax parity harness
(BASELINE.json north star — bit-identical binary outcomes), result-dict
contract, and validation (SURVEY.md §4)."""

import numpy as np
import pytest

from conftest import collusion_reports
from pyconsensus_tpu import ALGORITHMS, Oracle

# The canonical Truthcoin whitepaper-style example: 6 reporters × 4 binary
# events; reporters 0-3 form the honest majority, 4-5 answer inverted
# (SURVEY.md §4 "canonical example").
CANONICAL = np.array([
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 0.0, 0.0, 0.0],
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 1.0, 1.0, 0.0],
    [0.0, 0.0, 1.0, 1.0],
    [0.0, 0.0, 1.0, 1.0],
])

# NA fixture. Designed so every fill statistic sits ROBUSTLY off the
# catch boundaries: each NA column has 4 present reporters, so the
# uniform-reputation fill means are 0.8 / 0.5 / 0.5 / 0.25 — never the
# knife-edge 0.4 whose snap flips on 1-ulp reputation-normalization noise
# (0.4 vs the boundary 0.5-0.1 = 0.39999999999999997; the round-2 fixture
# had exactly that, and its frozen goldens encoded the ulp artifact —
# found by tests/test_independent_derivation.py's second derivation).
MISSING = np.array([
    [1.0, 1.0, 0.0, np.nan],
    [1.0, 0.0, np.nan, 0.0],
    [1.0, np.nan, np.nan, 0.0],
    [1.0, 1.0, 0.0, 0.0],
    [np.nan, 0.0, 1.0, 1.0],
    [0.0, np.nan, 1.0, np.nan],
])

SCALED_REPORTS = np.array([
    [1.0, 0.5, 0.0, 233.0, 16027.59],
    [1.0, 0.5, 0.0, 199.0, np.nan],
    [1.0, 1.0, 0.0, 233.0, 16027.59],
    [1.0, 0.5, 0.0, 250.0, 0.0],
    [0.0, 0.5, 1.0, 435.8, 8001.0],
    [0.0, 0.5, 1.0, 435.8, 19999.0],
])
SCALED_BOUNDS = [
    None,
    None,
    None,
    {"scaled": True, "min": 0.0, "max": 435.8},
    {"scaled": True, "min": 0.0, "max": 20000.0},
]


def make_majority(rng, R=50, E=25, liars=10):
    return collusion_reports(rng, R, E, liars)


class TestCanonical:
    def test_majority_outcomes(self):
        # events 1 and 2 are 3-vs-3 splits: a single redistribution pass
        # under near-uniform reputation leaves them ambiguous (0.5) ...
        result = Oracle(reports=CANONICAL).consensus()
        np.testing.assert_array_equal(result["events"]["outcomes_final"],
                                      [1.0, 0.5, 0.5, 0.0])
        # ... while iterative redistribution concentrates reputation on the
        # PCA-coherent honest cluster and resolves them (the Truthcoin
        # "lie detector" working as intended)
        result = Oracle(reports=CANONICAL, max_iterations=5).consensus()
        np.testing.assert_array_equal(result["events"]["outcomes_final"],
                                      [1.0, 1.0, 0.0, 0.0])

    def test_liars_lose_reputation(self):
        result = Oracle(reports=CANONICAL).consensus()
        rep = result["agents"]["smooth_rep"]
        assert rep.sum() == pytest.approx(1.0)
        assert rep[:4].mean() > rep[4:].mean()

    def test_reputation_simplex(self):
        result = Oracle(reports=CANONICAL).consensus()
        for key in ("old_rep", "this_rep", "smooth_rep"):
            v = result["agents"][key]
            assert (v >= -1e-12).all(), key
            assert v.sum() == pytest.approx(1.0), key

    def test_permutation_equivariance(self):
        perm = np.array([3, 1, 5, 0, 2, 4])
        base = Oracle(reports=CANONICAL).consensus()
        permed = Oracle(reports=CANONICAL[perm]).consensus()
        np.testing.assert_array_equal(base["events"]["outcomes_final"],
                                      permed["events"]["outcomes_final"])
        np.testing.assert_allclose(permed["agents"]["smooth_rep"],
                                   base["agents"]["smooth_rep"][perm],
                                   atol=1e-12)

    def test_result_dict_contract(self):
        result = Oracle(reports=CANONICAL).consensus()
        assert set(result) == {"original", "filled", "agents", "events",
                               "participation", "certainty", "convergence",
                               "iterations", "quarantined_rows"}
        # clean input: the quarantine field is present and empty (ISSUE 4
        # graceful-degradation contract)
        assert result["quarantined_rows"].size == 0
        assert set(result["agents"]) == {
            "old_rep", "this_rep", "smooth_rep", "na_row",
            "participation_rows", "relative_part", "reporter_bonus"}
        assert set(result["events"]) == {
            "outcomes_raw", "consensus_reward", "certainty",
            "participation_columns", "author_bonus", "outcomes_adjusted",
            "outcomes_final", "adj_first_loadings"}
        assert result["participation"] == pytest.approx(1.0)


# Golden vectors, frozen from the x64 numpy backend at full printed
# precision (canonical/scaled 2026-07-30; missing re-frozen 2026-07-31 on
# the boundary-robust fixture above). The reference mount was empty every
# round so far, so these are NOT reference-derived numbers — but since
# round 3 they are no longer merely self-referential either: every entry
# is independently re-derived by tests/test_independent_derivation.py
# (naive loops + dense E×E f64 eigh, zero shared code) and the two
# implementations agree to 1e-10 (VERDICT r2 item 2). If /root/reference/
# is ever populated, SURVEY.md §8 step 6 supersedes both with R-derived
# vectors.
GOLDEN = {
    ("canonical", 1): dict(
        this_rep=[0.28237569612767888, 0.21762430387232110,
                  0.28237569612767888, 0.21762430387232112, -0.0, -0.0],
        smooth_rep=[0.17823756961276790, 0.17176243038723213,
                    0.17823756961276790, 0.17176243038723213,
                    0.15000000000000002, 0.15000000000000002],
        outcomes_final=[1.0, 0.5, 0.5, 0.0],
        event_certainty=[0.7000000000000001, 0.0, 0.0, 0.7000000000000001],
        certainty=0.35000000000000003),
    ("canonical", 5): dict(
        this_rep=[0.30126300085578023, 0.19873699914421977,
                  0.30126300085578023, 0.19873699914421980, -0.0, -0.0],
        smooth_rep=[0.21837130847656355, 0.18321369152343653,
                    0.21837130847656355, 0.18321369152343650,
                    0.09841500000000003, 0.09841500000000003],
        outcomes_final=[1.0, 1.0, 0.0, 0.0],
        event_certainty=[0.8031700000000001, 0.6199563084765636,
                         0.6199563084765636, 0.8031700000000001],
        certainty=0.7115631542382819),
    ("missing", 1): dict(
        this_rep=[0.29309810234060385, 0.13276351070315356,
                  0.18481053841759568, 0.29309810234060385,
                  -0.0, 0.09622974619804311],
        smooth_rep=[0.17930981023406040, 0.16327635107031538,
                    0.16848105384175960, 0.17930981023406040,
                    0.15000000000000002, 0.15962297461980435],
        outcomes_final=[1.0, 0.5, 0.5, 0.0],
        event_certainty=[0.8403770253801958, 0.32810402846156395,
                         0.33175740491207495, 0.8500000000000001],
        certainty=0.5875596146884587),
    ("missing", 10): dict(
        this_rep=[0.39040227265917210, 0.06290019944832231,
                  0.12994922284926000, 0.39040227265917210,
                  -0.0, 0.02634603238407339],
        smooth_rep=[0.28996224886217276, 0.11570076472109293,
                    0.15760790152799994, 0.28996224886217276,
                    0.05811307335000003, 0.08865376267656183],
        outcomes_final=[1.0, 1.0, 0.0, 0.0],
        event_certainty=[0.9113462373234383, 0.5799244977243455,
                         0.5799244977243455, 0.9418869266500001],
        certainty=0.7532705398555324),
    ("scaled", 1): dict(
        this_rep=[0.24035512601552864, 0.24805623658902839,
                  0.24699855698679155, 0.25337041478453742,
                  0.01121966562411400, -0.0],
        smooth_rep=[0.17403551260155289, 0.17480562365890287,
                    0.17469985569867919, 0.17533704147845378,
                    0.15112196656241142, 0.15000000000000002],
        outcomes_final=[1.0, 0.5, 0.0, 232.99999999999997, 16027.59],
        event_certainty=[0.6988780334375887, 0.8253001443013209,
                         0.6988780334375887, 0.6988780334375887,
                         0.3487353683002321],
        certainty=0.6541339225828638),
}

_GOLDEN_INPUTS = {
    "canonical": (CANONICAL, None),
    "missing": (MISSING, None),
    "scaled": (SCALED_REPORTS, SCALED_BOUNDS),
}


@pytest.mark.parametrize("fixture,max_iterations", sorted(GOLDEN))
class TestGolden:
    """Frozen-number regression tests over every golden fixture: the numpy
    backend must reproduce the frozen vectors to float64 round-off, and the
    jax backend must land on the identical catch-snapped outcomes plus the
    same reputation to cross-backend tolerance."""

    def test_numpy_matches_frozen(self, fixture, max_iterations):
        reports, bounds = _GOLDEN_INPUTS[fixture]
        g = GOLDEN[(fixture, max_iterations)]
        r = Oracle(reports=reports, event_bounds=bounds, backend="numpy",
                   max_iterations=max_iterations).consensus()
        np.testing.assert_allclose(r["agents"]["this_rep"], g["this_rep"],
                                   rtol=1e-12, atol=1e-14)
        np.testing.assert_allclose(r["agents"]["smooth_rep"],
                                   g["smooth_rep"], rtol=1e-12, atol=1e-14)
        np.testing.assert_allclose(r["events"]["outcomes_final"],
                                   g["outcomes_final"], rtol=1e-12)
        np.testing.assert_allclose(r["events"]["certainty"],
                                   g["event_certainty"], rtol=1e-12,
                                   atol=1e-14)
        assert r["certainty"] == pytest.approx(g["certainty"], rel=1e-12)

    def test_jax_matches_frozen(self, fixture, max_iterations):
        reports, bounds = _GOLDEN_INPUTS[fixture]
        g = GOLDEN[(fixture, max_iterations)]
        r = Oracle(reports=reports, event_bounds=bounds, backend="jax",
                   max_iterations=max_iterations).consensus()
        out = np.asarray(r["events"]["outcomes_final"])
        binary = [i for i, b in enumerate(bounds or [None] * out.size)
                  if not (b and b.get("scaled"))]
        np.testing.assert_array_equal(
            out[binary], np.asarray(g["outcomes_final"])[binary])
        np.testing.assert_allclose(out, g["outcomes_final"], rtol=1e-6)
        np.testing.assert_allclose(r["agents"]["smooth_rep"],
                                   g["smooth_rep"], atol=5e-6)


# Per-variant provisional goldens on the canonical matrix (same freeze
# rationale as GOLDEN above): every algorithm's reconstruction is pinned,
# not just sztorc's. The four clustering variants coincide here by
# construction — the canonical 4-vs-2 split is the same partition under
# k-means(2), dbscan(eps=1), dbscan-jit, and hierarchical(1.5).
GOLDEN_VARIANTS = {
    # re-frozen round 4: the canonical matrix's SECOND component (17.6%
    # explained variance) is an EXACT direction-fix tie (relative margin
    # 3e-16) — the old golden encoded whichever sign LAPACK returned;
    # the sign-canonical banded rule (ops/numpy_kernels.DIRFIX_TIE_ATOL,
    # SURVEY §8 item 9) resolves it deterministically, swapping
    # reporters 1 and 3 in the blend (outcomes unchanged)
    "fixed-variance": dict(
        kwargs={},
        smooth_rep=[0.1768359560747499, 0.17316404392525017,
                    0.1768359560747499, 0.16912629065008247,
                    0.15201887663758387, 0.15201887663758387],
        certainty=0.3479811233624162),
    "ica": dict(
        kwargs={},
        smooth_rep=[0.17500002852460511, 0.17499997147539492,
                    0.17500002852460511, 0.17499997147539495,
                    0.15000000000000002, 0.15000000000000002],
        certainty=0.35000000000000003),
    "k-means": dict(
        kwargs={"num_clusters": 2},
        smooth_rep=[0.17000000000000001, 0.17000000000000001,
                    0.17000000000000001, 0.17000000000000001,
                    0.16000000000000003, 0.16000000000000003],
        certainty=0.34),
    "dbscan-jit": dict(
        kwargs={"dbscan_eps": 1.0, "dbscan_min_samples": 2},
        smooth_rep=[0.17000000000000001, 0.17000000000000001,
                    0.17000000000000001, 0.17000000000000001,
                    0.16000000000000003, 0.16000000000000003],
        certainty=0.34),
    "hierarchical": dict(
        kwargs={"hierarchy_threshold": 1.5},
        smooth_rep=[0.17000000000000001, 0.17000000000000001,
                    0.17000000000000001, 0.17000000000000001,
                    0.16000000000000003, 0.16000000000000003],
        certainty=0.34),
    "dbscan": dict(
        kwargs={"dbscan_eps": 1.0, "dbscan_min_samples": 2},
        smooth_rep=[0.17000000000000001, 0.17000000000000001,
                    0.17000000000000001, 0.17000000000000001,
                    0.16000000000000003, 0.16000000000000003],
        certainty=0.34),
}


@pytest.mark.parametrize("algo", sorted(GOLDEN_VARIANTS))
class TestGoldenVariants:
    def test_numpy_matches_frozen(self, algo):
        g = GOLDEN_VARIANTS[algo]
        r = Oracle(reports=CANONICAL, backend="numpy", algorithm=algo,
                   **g["kwargs"]).consensus()
        np.testing.assert_allclose(r["agents"]["smooth_rep"],
                                   g["smooth_rep"], rtol=1e-12, atol=1e-14)
        np.testing.assert_array_equal(r["events"]["outcomes_final"],
                                      [1.0, 0.5, 0.5, 0.0])
        assert r["certainty"] == pytest.approx(g["certainty"], rel=1e-12)

    def test_jax_matches_frozen(self, algo):
        g = GOLDEN_VARIANTS[algo]
        r = Oracle(reports=CANONICAL, backend="jax", algorithm=algo,
                   **g["kwargs"]).consensus()
        np.testing.assert_array_equal(
            np.asarray(r["events"]["outcomes_final"]), [1.0, 0.5, 0.5, 0.0])
        np.testing.assert_allclose(r["agents"]["smooth_rep"],
                                   g["smooth_rep"], atol=5e-6)


class TestMissing:
    def test_filled_no_nan(self):
        result = Oracle(reports=MISSING, max_iterations=10).consensus()
        assert not np.isnan(result["filled"]).any()
        np.testing.assert_array_equal(result["events"]["outcomes_final"],
                                      [1.0, 1.0, 0.0, 0.0])

    def test_participation_below_one(self):
        result = Oracle(reports=MISSING).consensus()
        assert result["participation"] < 1.0
        assert result["agents"]["na_row"].sum() == 5


class TestScaled:
    def test_outcomes_in_bounds(self):
        result = Oracle(reports=SCALED_REPORTS,
                        event_bounds=SCALED_BOUNDS).consensus()
        out = result["events"]["outcomes_final"]
        assert 0.0 <= out[3] <= 435.8
        assert 0.0 <= out[4] <= 20000.0
        # scaled outcome is the rep-weighted median of honest cluster
        np.testing.assert_array_equal(out[:3], [1.0, 0.5, 0.0])


@pytest.mark.parametrize("backend_algo", [
    ("sztorc", {}),
    ("fixed-variance", {}),
    ("ica", {}),
    ("k-means", {}),
    ("sztorc", {"max_iterations": 5}),
    ("sztorc", {"pca_method": "eigh-gram"}),
    ("sztorc", {"pca_method": "power"}),
    # iterative + power: the warm-started loop (v_init threading) must
    # stay within the uniform cross-backend tolerance vs numpy's exact
    # per-iteration eigh
    ("sztorc", {"max_iterations": 5, "pca_method": "power"}),
])
class TestBackendParity:
    """The north star: jax outcomes bit-identical to numpy on binary events
    (catch-snapped), reputation equal to float tolerance."""

    def _run(self, reports, algo, kwargs, backend, event_bounds=None):
        return Oracle(reports=reports, event_bounds=event_bounds,
                      algorithm=algo, backend=backend, **kwargs).consensus()

    def test_binary_dense(self, rng, backend_algo):
        algo, kwargs = backend_algo
        reports, _ = make_majority(rng)
        a = self._run(reports, algo, kwargs, "numpy")
        b = self._run(reports, algo, kwargs, "jax")
        np.testing.assert_array_equal(a["events"]["outcomes_final"],
                                      b["events"]["outcomes_final"])
        np.testing.assert_allclose(b["agents"]["smooth_rep"],
                                   a["agents"]["smooth_rep"], atol=1e-8)
        np.testing.assert_allclose(b["events"]["certainty"],
                                   a["events"]["certainty"], atol=1e-8)

    def test_missing_and_scaled(self, rng, backend_algo):
        algo, kwargs = backend_algo
        a = self._run(SCALED_REPORTS, algo, kwargs, "numpy", SCALED_BOUNDS)
        b = self._run(SCALED_REPORTS, algo, kwargs, "jax", SCALED_BOUNDS)
        scaled = np.array([bool(x and x.get("scaled")) for x in SCALED_BOUNDS])
        np.testing.assert_array_equal(
            a["events"]["outcomes_final"][~scaled],
            b["events"]["outcomes_final"][~scaled])
        np.testing.assert_allclose(b["events"]["outcomes_final"],
                                   a["events"]["outcomes_final"], rtol=1e-8)
        np.testing.assert_allclose(b["agents"]["smooth_rep"],
                                   a["agents"]["smooth_rep"], atol=1e-8)


class TestIcaConverged:
    """ica's chaotic-case fallback (first whitened component) must be
    observable: the result dict carries ``ica_converged`` on BOTH
    backends, True on a decisively-structured matrix, False when the
    FastICA loop cannot converge (forced here by a 1-sweep budget) —
    VERDICT r3 item 7."""

    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_flag_present_and_true_on_structure(self, rng, backend):
        reports, _ = make_majority(rng)
        r = Oracle(reports=reports, algorithm="ica",
                   backend=backend).consensus()
        assert r["ica_converged"] is True

    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_flag_false_when_fallback_fires(self, rng, backend,
                                            monkeypatch):
        import pyconsensus_tpu.models.ica as ica_mod
        from pyconsensus_tpu.models import pipeline as pl_mod

        monkeypatch.setattr(ica_mod, "ICA_ITERS", 1)
        # the jitted pipeline caches on (shape, params) — ICA_ITERS is a
        # module global invisible to the cache key, so trace fresh
        monkeypatch.setattr(
            pl_mod, "consensus_jit",
            pl_mod.jax.jit(
                pl_mod.jk.exact_matmuls(pl_mod._consensus_core),
                static_argnames=("p",)))
        reports, _ = make_majority(rng)
        r = Oracle(reports=reports, algorithm="ica",
                   backend=backend).consensus()
        assert r["ica_converged"] is False

    def test_other_algorithms_omit_flag(self, rng):
        reports, _ = make_majority(rng)
        for algo in ("sztorc", "fixed-variance", "k-means"):
            r = Oracle(reports=reports, algorithm=algo,
                       backend="jax").consensus()
            assert "ica_converged" not in r


class TestStorageDtype:
    """storage_dtype="bfloat16" keeps the filled matrix compact through the
    whole jax pipeline. Binary report values {0, 0.5, 1} and catch-snapped
    fills are bf16-exact and reductions accumulate in the reputation dtype,
    so catch-snapped outcomes must be IDENTICAL to the full-precision
    backend — the same honesty contract the bench asserts on TPU."""

    def test_binary_outcomes_identical(self, rng):
        reports, _ = make_majority(rng)
        full = Oracle(reports=reports, backend="jax",
                      max_iterations=3).consensus()
        compact = Oracle(reports=reports, backend="jax", max_iterations=3,
                         storage_dtype="bfloat16").consensus()
        np.testing.assert_array_equal(full["events"]["outcomes_final"],
                                      compact["events"]["outcomes_final"])
        # reputation is float-noisy at bf16 matrix precision but must
        # rank-order the liars identically
        np.testing.assert_allclose(compact["agents"]["smooth_rep"],
                                   full["agents"]["smooth_rep"], atol=5e-3)

    def test_with_missing_entries(self, rng):
        reports, _ = make_majority(rng)
        reports[rng.random(reports.shape) < 0.1] = np.nan
        full = Oracle(reports=reports, backend="jax").consensus()
        compact = Oracle(reports=reports, backend="jax",
                         storage_dtype="bfloat16").consensus()
        np.testing.assert_array_equal(full["events"]["outcomes_final"],
                                      compact["events"]["outcomes_final"])
        np.testing.assert_array_equal(full["agents"]["na_row"],
                                      compact["agents"]["na_row"])

    def test_power_path_storage(self, rng):
        reports, _ = make_majority(rng)
        full = Oracle(reports=reports, backend="jax",
                      pca_method="power").consensus()
        compact = Oracle(reports=reports, backend="jax", pca_method="power",
                         storage_dtype="bfloat16").consensus()
        np.testing.assert_array_equal(full["events"]["outcomes_final"],
                                      compact["events"]["outcomes_final"])

    @pytest.mark.parametrize("algo", sorted(ALGORITHMS))
    def test_every_algorithm_runs_compact(self, rng, algo):
        """storage_dtype must work (not crash, keep catch-snapped outcomes)
        under every algorithm= variant, including the k-means fori_loop
        (carry dtype stability) and the hybrid host-clustering paths."""
        reports, _ = make_majority(rng)
        full = Oracle(reports=reports, backend="jax", algorithm=algo,
                      max_iterations=2).consensus()
        compact = Oracle(reports=reports, backend="jax", algorithm=algo,
                         max_iterations=2,
                         storage_dtype="bfloat16").consensus()
        np.testing.assert_array_equal(full["events"]["outcomes_final"],
                                      compact["events"]["outcomes_final"],
                                      err_msg=algo)


class TestKmeansLowIterParity:
    def test_unconverged_lloyd_matches_across_backends(self):
        """Regression: labels must come from the *final* centroids in both
        backends even when Lloyd hasn't converged within n_iters."""
        import jax.numpy as jnp

        from pyconsensus_tpu.models import clustering as cl
        rng = np.random.default_rng(3)
        X = rng.random((12, 6))
        rep = np.full(12, 1 / 12)
        a = cl.kmeans_conformity_np(X, rep, 3, n_iters=2)
        b = np.asarray(cl.kmeans_conformity_jax(jnp.asarray(X),
                                                jnp.asarray(rep), 3, n_iters=2))
        np.testing.assert_allclose(b, a, atol=1e-12)


class TestLoadingParity:
    @pytest.mark.parametrize("algo", ["sztorc", "fixed-variance"])
    def test_loading_sign_canonical_across_backends(self, rng, algo):
        reports, _ = make_majority(rng)
        a = Oracle(reports=reports, algorithm=algo,
                   backend="numpy").consensus()
        b = Oracle(reports=reports, algorithm=algo, backend="jax").consensus()
        np.testing.assert_allclose(b["events"]["adj_first_loadings"],
                                   a["events"]["adj_first_loadings"],
                                   atol=1e-6)


class TestHybridAlgorithms:
    @pytest.mark.parametrize("algo", ["hierarchical", "dbscan"])
    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_runs_and_detects_liars(self, rng, algo, backend):
        reports, truth = make_majority(rng, R=20, E=10, liars=5)
        kwargs = {"dbscan_eps": 1.0, "dbscan_min_samples": 2,
                  "hierarchy_threshold": 1.5}
        result = Oracle(reports=reports, algorithm=algo, backend=backend,
                        **kwargs).consensus()
        rep = result["agents"]["smooth_rep"]
        assert rep.sum() == pytest.approx(1.0)
        assert rep[:15].mean() > rep[15:].mean()

    @pytest.mark.parametrize("algo", ["hierarchical", "dbscan"])
    def test_backend_parity(self, rng, algo):
        reports, _ = make_majority(rng, R=16, E=8, liars=4)
        kwargs = {"dbscan_eps": 1.0, "hierarchy_threshold": 1.5}
        a = Oracle(reports=reports, algorithm=algo, backend="numpy",
                   **kwargs).consensus()
        b = Oracle(reports=reports, algorithm=algo, backend="jax",
                   **kwargs).consensus()
        np.testing.assert_array_equal(a["events"]["outcomes_final"],
                                      b["events"]["outcomes_final"])
        np.testing.assert_allclose(b["agents"]["smooth_rep"],
                                   a["agents"]["smooth_rep"], atol=1e-8)


class TestDbscanJit:
    """The fully on-device DBSCAN variant (dbscan-jit): same clusters as
    classic DBSCAN via min-label propagation over the core graph, with a
    deterministic border tie-break; jit/vmap-compatible."""

    def test_partition_matches_sklearn(self, rng):
        from pyconsensus_tpu.models.clustering import (_dbscan_jit_labels_np,
                                                       _pairwise_sq_dists_np)
        sklearn = pytest.importorskip("sklearn.cluster")
        X = np.concatenate([rng.normal(0.0, 0.05, (8, 5)),
                            rng.normal(1.0, 0.05, (6, 5)),
                            np.full((1, 5), 10.0)])       # noise point
        ours = _dbscan_jit_labels_np(_pairwise_sq_dists_np(X), 0.6, 3)
        d = np.sqrt(_pairwise_sq_dists_np(X))
        ref = sklearn.DBSCAN(eps=0.6, min_samples=3,
                             metric="precomputed").fit(d).labels_
        # compare partitions up to relabeling (noise = singleton clusters)
        ref = ref.copy()
        nxt = ref.max() + 1
        for i, l in enumerate(ref):
            if l == -1:
                ref[i] = nxt
                nxt += 1
        same_ours = ours[:, None] == ours[None, :]
        same_ref = ref[:, None] == ref[None, :]
        np.testing.assert_array_equal(same_ours, same_ref)

    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_runs_and_detects_liars(self, rng, backend):
        reports, truth = make_majority(rng, R=20, E=10, liars=5)
        result = Oracle(reports=reports, algorithm="dbscan-jit",
                        backend=backend, dbscan_eps=1.0,
                        dbscan_min_samples=2).consensus()
        rep = result["agents"]["smooth_rep"]
        assert rep.sum() == pytest.approx(1.0)
        assert rep[:15].mean() > rep[15:].mean()

    def test_backend_parity(self, rng):
        reports, _ = make_majority(rng, R=16, E=8, liars=4)
        a = Oracle(reports=reports, algorithm="dbscan-jit", backend="numpy",
                   dbscan_eps=1.0).consensus()
        b = Oracle(reports=reports, algorithm="dbscan-jit", backend="jax",
                   dbscan_eps=1.0).consensus()
        np.testing.assert_array_equal(a["events"]["outcomes_final"],
                                      b["events"]["outcomes_final"])
        np.testing.assert_allclose(b["agents"]["smooth_rep"],
                                   a["agents"]["smooth_rep"], atol=1e-8)

    def test_vmappable_in_simulator(self):
        """The hybrid DBSCAN cannot batch; dbscan-jit can — whole sweep in
        one vmapped XLA call, with the DBSCAN knobs plumbed through."""
        from pyconsensus_tpu.sim import CollusionSimulator
        sim = CollusionSimulator(n_reporters=12, n_events=6,
                                 algorithm="dbscan-jit", max_iterations=1,
                                 dbscan_eps=1.5, dbscan_min_samples=2)
        assert sim.params.dbscan_eps == 1.5
        res = sim.run([0.0, 0.3], [0.05], 4, seed=0)
        assert res["correct_rate"].shape == (2, 1, 4)
        assert np.isfinite(res["correct_rate"]).all()
        # honest cells with a sane eps resolve essentially everything
        assert res["mean"]["correct_rate"][0, 0] > 0.9


class TestValidation:
    def test_requires_reports(self):
        with pytest.raises(ValueError, match="reports"):
            Oracle()

    def test_rejects_1d(self):
        with pytest.raises(ValueError, match="2-D"):
            Oracle(reports=np.ones(5))

    def test_rejects_bad_algorithm(self):
        with pytest.raises(ValueError, match="algorithm"):
            Oracle(reports=CANONICAL, algorithm="nope")

    def test_rejects_bad_backend(self):
        with pytest.raises(ValueError, match="backend"):
            Oracle(reports=CANONICAL, backend="torch")

    def test_rejects_bad_reputation(self):
        with pytest.raises(ValueError, match="reputation"):
            Oracle(reports=CANONICAL, reputation=np.ones(3))
        with pytest.raises(ValueError, match="non-negative"):
            Oracle(reports=CANONICAL, reputation=np.array([1, 1, 1, 1, 1, -1.0]))
        with pytest.raises(ValueError, match="NaN"):
            Oracle(reports=CANONICAL,
                   reputation=np.array([1, np.nan, 1, 1, 1, 1.0]))

    def test_rejects_bad_knobs(self):
        with pytest.raises(ValueError, match="num_clusters"):
            Oracle(reports=CANONICAL, algorithm="k-means", num_clusters=0)
        with pytest.raises(ValueError, match="alpha"):
            Oracle(reports=CANONICAL, alpha=1.5)
        with pytest.raises(ValueError, match="dbscan_eps"):
            Oracle(reports=CANONICAL, dbscan_eps=0.0)
        with pytest.raises(ValueError, match="max_iterations"):
            Oracle(reports=CANONICAL, max_iterations=0)

    def test_rejects_bad_bounds(self):
        bounds = [None, None, None, {"scaled": True, "min": 2.0, "max": 1.0}]
        with pytest.raises(ValueError, match="max must exceed"):
            Oracle(reports=CANONICAL, event_bounds=bounds)
        with pytest.raises(ValueError, match="entries"):
            Oracle(reports=CANONICAL, event_bounds=[None])

    def test_n_scaled_static_wiring(self):
        """Oracle carries the exact static scaled count whenever the
        gather-median path can fire (the shared gather_median_pays
        envelope, up to 90% scaled — round 4 opened the gate to
        majorities); near-all-scaled and all-binary carry 0 (a gather of
        ~the whole matrix buys nothing / is unused)."""
        bounds_minor = [None, None, None,
                        {"scaled": True, "min": 0.0, "max": 10.0}]
        o = Oracle(reports=CANONICAL, event_bounds=bounds_minor)
        assert o.params.n_scaled == 1
        bounds_major = [{"scaled": True, "min": 0.0, "max": 10.0}] * 3 \
            + [None]
        o = Oracle(reports=CANONICAL, event_bounds=bounds_major)
        assert o.params.n_scaled == 3          # majority: gather still wins
        bounds_all = [{"scaled": True, "min": 0.0, "max": 10.0}] * 4
        o = Oracle(reports=CANONICAL, event_bounds=bounds_all)
        assert o.params.n_scaled == 0          # all-scaled: nothing to skip
        assert Oracle(reports=CANONICAL).params.n_scaled == 0
        # above the 90% envelope (10 of 11): the gather would copy ~the
        # whole matrix and fragment the jit cache per count — full-width
        reports_11 = np.tile(CANONICAL[:, :1], (1, 11))
        bounds_tail = [{"scaled": True, "min": 0.0, "max": 10.0}] * 10 \
            + [None]
        o = Oracle(reports=reports_11, event_bounds=bounds_tail)
        assert o.params.n_scaled == 0

    def test_algorithm_aliases(self):
        o = Oracle(reports=CANONICAL, algorithm="kmeans")
        assert o.params.algorithm == "k-means"
        o = Oracle(reports=CANONICAL, algorithm="DBSCAN")
        assert o.params.algorithm == "dbscan"

    def test_nonuniform_reputation(self):
        rep = np.array([10.0, 1, 1, 1, 1, 1])
        result = Oracle(reports=CANONICAL, reputation=rep).consensus()
        assert result["agents"]["old_rep"][0] == pytest.approx(10.0 / 15.0)


class TestVerbose:
    def test_prints_summary(self, capsys):
        Oracle(reports=CANONICAL, verbose=True).consensus()
        out = capsys.readouterr().out
        assert "outcomes_final" in out
        assert "sztorc" in out


class TestConvergence:
    def test_iterative_converges(self):
        # reputation fully concentrates on the coherent cluster by ~240
        # iterations, after which the update is a fixed point
        result = Oracle(reports=CANONICAL, max_iterations=300).consensus()
        assert result["convergence"]
        assert 1 <= result["iterations"] < 300

    def test_unanimous_converges_immediately(self):
        reports = np.tile(np.array([1.0, 0.0, 1.0, 0.0]), (6, 1))
        result = Oracle(reports=reports, max_iterations=10).consensus()
        assert result["convergence"]
        assert result["iterations"] == 1
        np.testing.assert_array_equal(result["events"]["outcomes_final"],
                                      [1.0, 0.0, 1.0, 0.0])
        np.testing.assert_allclose(result["agents"]["smooth_rep"],
                                   np.full(6, 1 / 6), atol=1e-12)

    def test_single_iteration_no_convergence_claim(self):
        r1 = Oracle(reports=CANONICAL, max_iterations=1).consensus()
        assert r1["iterations"] == 1

    def test_iterations_match_across_backends(self):
        """What the long-trajectory cross-backend contract actually
        guarantees on the knife-edge CANONICAL matrix (docs/ROBUSTNESS.md
        parity ledger #8): iteration counts, convergence flags, snapped
        outcomes, and the reputation DISTRIBUTION (sorted values) agree —
        the per-reporter assignment within the symmetric near-tied pair
        does not (see the xfail'd strict test below)."""
        a = Oracle(reports=CANONICAL, max_iterations=50,
                   backend="numpy").consensus()
        b = Oracle(reports=CANONICAL, max_iterations=50,
                   backend="jax").consensus()
        assert a["iterations"] == b["iterations"]
        assert a["convergence"] == b["convergence"]
        np.testing.assert_array_equal(b["events"]["outcomes_final"],
                                      a["events"]["outcomes_final"])
        # the reputation MASS distribution is identical — only the
        # labeling within the symmetric pair is trajectory-chaotic
        np.testing.assert_allclose(np.sort(b["agents"]["smooth_rep"]),
                                   np.sort(a["agents"]["smooth_rep"]),
                                   atol=1e-8)

    @pytest.mark.xfail(
        strict=False,
        reason="cross-backend f64 trajectory identity on a symmetric "
               "knife-edge matrix (docs/ROBUSTNESS.md parity ledger #8): "
               "CANONICAL holds two reporters whose adjusted scores stay "
               "near-tied through the iterated redistribution; at "
               "iteration 29 backend reduction-order ulp noise resolves "
               "the tie OPPOSITELY and the pair's reputations swap "
               "(2.6e-2) while outcomes, iteration counts, convergence, "
               "and the sorted reputation distribution all still match "
               "(pinned by test_iterations_match_across_backends). "
               "Per-reporter trajectory identity through a chaotic "
               "symmetric tie is beyond any fixed reduction order's "
               "capability — it would need bit-identical arithmetic "
               "across numpy and XLA.")
    def test_trajectory_tail_identity_across_backends(self):
        a = Oracle(reports=CANONICAL, max_iterations=50,
                   backend="numpy").consensus()
        b = Oracle(reports=CANONICAL, max_iterations=50,
                   backend="jax").consensus()
        np.testing.assert_allclose(b["agents"]["smooth_rep"],
                                   a["agents"]["smooth_rep"], atol=1e-8)

    def test_more_iterations_pushes_liar_rep_down(self, rng):
        reports, _ = make_majority(rng, R=30, E=15, liars=8)
        r1 = Oracle(reports=reports, max_iterations=1).consensus()
        r20 = Oracle(reports=reports, max_iterations=20).consensus()
        liar1 = r1["agents"]["smooth_rep"][22:].sum()
        liar20 = r20["agents"]["smooth_rep"][22:].sum()
        assert liar20 < liar1
