"""Mesh-sharded serving hot path (ISSUE 6): the padded bucket kernel
under shard_map on the 8-fake-device CPU mesh.

Pins the tentpole's parity contract — sharded-vs-single-device bucket
dispatches agree on catch-snapped outcomes and iteration counts
BIT-IDENTICALLY (the tie bands make every snap reduction-order stable),
continuous tails within the documented GSPMD tiling band — plus
batch-composition determinism on the mesh (co-batched lanes never
change a request's bits), the topology-aware cache policy (wrong-
topology keys rejected, divisibility gate routing), and the serve-side
``pyconsensus_mesh_event_shards`` gauge emission.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from conftest import collusion_reports
from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.models.pipeline import ConsensusParams
from pyconsensus_tpu.parallel import make_mesh
from pyconsensus_tpu.serve import (BucketKey, ConsensusService,
                                   ExecutableCache, ServeConfig)
from pyconsensus_tpu.serve import kernels as sk
from pyconsensus_tpu.serve import sharded as ss

#: continuous tails across differently-reduced graphs (the fused_sharded
#: parity band — psum association vs one device's fused reduction)
SHARD_ATOL = 5e-6

#: result keys compared within the band (everything continuous)
_BAND_KEYS = ("old_rep", "this_rep", "smooth_rep", "certainty",
              "consensus_reward", "participation_rows",
              "participation_columns", "na_bonus_rows", "na_bonus_cols",
              "reporter_bonus", "author_bonus", "percent_na",
              "avg_certainty")


def serve_params(**kw):
    kw.setdefault("algorithm", "sztorc")
    kw.setdefault("pca_method", "power")
    kw.setdefault("has_na", True)
    kw.setdefault("any_scaled", False)
    kw.setdefault("n_scaled", 0)
    return ConsensusParams(**kw)


def bucket_args(reports, rep, scaled, mins, maxs, bucket, has_na=True):
    return [jnp.asarray(a) for a in sk.bucket_inputs(
        reports, rep, scaled, mins, maxs, bucket[0], bucket[1],
        has_na=has_na)]


def run_pair(args, p, mesh):
    """One unbatched dispatch through both kernel classes."""
    single = sk.make_bucket_executable(p)(*args, p)
    sharded = ss.make_sharded_bucket_executable(p, mesh,
                                                batched=False)(*args, p)
    return ({k: np.asarray(v) for k, v in sharded.items()},
            {k: np.asarray(v) for k, v in single.items()})


def assert_bucket_parity(sharded, single, scaled=None):
    binary = (slice(None) if scaled is None
              else ~np.asarray(scaled, dtype=bool))
    for key in ("outcomes_adjusted", "outcomes_final"):
        np.testing.assert_array_equal(sharded[key][binary],
                                      single[key][binary], err_msg=key)
    if scaled is not None:
        sc = np.asarray(scaled, dtype=bool)
        for key in ("outcomes_raw", "outcomes_adjusted", "outcomes_final"):
            np.testing.assert_allclose(sharded[key][sc], single[key][sc],
                                       atol=SHARD_ATOL, err_msg=key)
    assert sharded["iterations"] == single["iterations"]
    assert sharded["convergence"] == single["convergence"]
    np.testing.assert_array_equal(sharded["na_row"], single["na_row"])
    for key in _BAND_KEYS:
        np.testing.assert_allclose(sharded[key], single[key],
                                   atol=SHARD_ATOL, err_msg=key)


class TestShardedBucketParity:
    @pytest.mark.parametrize("bucket", [(16, 64), (32, 128), (8, 32)])
    @pytest.mark.parametrize("layout", [(1, 8), (2, 4)])
    def test_binary_na_across_buckets_and_layouts(self, rng, bucket,
                                                  layout):
        R, E = bucket[0] - 3, bucket[1] - 9
        reports, _ = collusion_reports(rng, R, E, liars=max(2, R // 4),
                                       na_frac=0.12)
        p = serve_params()
        args = bucket_args(reports, np.full(R, 1.0 / R),
                           np.zeros(E, bool), np.zeros(E), np.ones(E),
                           bucket)
        mesh = make_mesh(batch=layout[0], event=layout[1])
        sharded, single = run_pair(args, p, mesh)
        assert_bucket_parity(sharded, single)

    def test_scaled_bucket(self, rng):
        R, E, bucket = 13, 50, (16, 64)
        reports, _ = collusion_reports(rng, R, E, liars=4, na_frac=0.1)
        scaled = np.zeros(E, bool)
        scaled[[3, 20, 41]] = True
        mins = np.where(scaled, -5.0, 0.0)
        maxs = np.where(scaled, 15.0, 1.0)
        with np.errstate(invalid="ignore"):
            reports[:, scaled] = reports[:, scaled] * 20.0 - 5.0
        p = serve_params(any_scaled=True, n_scaled=3)
        args = bucket_args(reports, np.full(R, 1.0 / R), scaled, mins,
                           maxs, bucket)
        mesh = make_mesh(batch=2, event=4)
        sharded, single = run_pair(args, p, mesh)
        # the bucket-shaped scaled mask (padded with False)
        assert_bucket_parity(sharded, single, scaled=np.asarray(args[2]))

    def test_iterative_loop_iterations_pinned(self, rng):
        R, E, bucket = 12, 48, (16, 64)
        reports, _ = collusion_reports(rng, R, E, liars=4, na_frac=0.1)
        p = serve_params(max_iterations=5)
        args = bucket_args(reports, np.full(R, 1.0 / R),
                           np.zeros(E, bool), np.zeros(E), np.ones(E),
                           bucket)
        mesh = make_mesh(batch=2, event=4)
        sharded, single = run_pair(args, p, mesh)
        assert_bucket_parity(sharded, single)
        assert sharded["iterations"] >= 1

    def test_dense_exact_fit(self, rng):
        """has_na=False (dense request, exact-fit rows): the elided-fill
        arithmetic must shard identically."""
        R, E = 16, 64
        reports, _ = collusion_reports(rng, R, E, liars=4, na_frac=0.0)
        p = serve_params(has_na=False)
        args = bucket_args(reports, np.full(R, 1.0 / R),
                           np.zeros(E, bool), np.zeros(E), np.ones(E),
                           (R, E), has_na=False)
        mesh = make_mesh(batch=1, event=8)
        sharded, single = run_pair(args, p, mesh)
        assert_bucket_parity(sharded, single)
        assert sharded["percent_na"] == pytest.approx(0.0, abs=1e-12)

    def test_nonuniform_reputation(self, rng):
        R, E, bucket = 14, 40, (16, 64)
        reports, _ = collusion_reports(rng, R, E, liars=4, na_frac=0.15)
        rep = rng.random(R) + 0.05
        rep = rep / rep.sum()
        p = serve_params()
        args = bucket_args(reports, rep, np.zeros(E, bool), np.zeros(E),
                           np.ones(E), bucket)
        mesh = make_mesh(batch=2, event=4)
        sharded, single = run_pair(args, p, mesh)
        assert_bucket_parity(sharded, single)


class TestShardedBatchLanes:
    """Co-batched lanes on the mesh's batch axis: every lane must be a
    pure function of its own inputs — bit-identical to the unbatched
    single-device kernel on that lane's inputs, in any lane position,
    with any co-batched partners."""

    def _lanes(self, rng, n, R=12, E=48):
        out = []
        for i in range(n):
            r = np.random.default_rng(700 + i)
            m, _ = collusion_reports(r, R, E, liars=4, na_frac=0.1)
            out.append(m)
        return out

    def test_each_lane_matches_single_device(self, rng):
        B, bucket = 4, (16, 64)
        p = serve_params()
        mesh = make_mesh(batch=2, event=4)
        lanes = [bucket_args(m, np.full(12, 1.0 / 12), np.zeros(48, bool),
                             np.zeros(48), np.ones(48), bucket)
                 for m in self._lanes(rng, B)]
        stacked = [jnp.stack(field) for field in zip(*lanes)]
        batched = ss.make_sharded_bucket_executable(p, mesh, batched=True)(
            *stacked, p)
        batched = {k: np.asarray(v) for k, v in batched.items()}
        single_fn = sk.make_bucket_executable(p)
        for i, lane in enumerate(lanes):
            ref = {k: np.asarray(v) for k, v in single_fn(*lane, p).items()}
            np.testing.assert_array_equal(
                batched["outcomes_adjusted"][i], ref["outcomes_adjusted"],
                err_msg=f"lane {i}")
            assert batched["iterations"][i] == ref["iterations"]
            np.testing.assert_allclose(batched["smooth_rep"][i],
                                       ref["smooth_rep"], atol=SHARD_ATOL)

    def test_batch_composition_determinism_on_mesh(self, rng):
        """The service-level contract, on the mesh: the same request
        dispatched solo, co-batched, and in a fresh service produces
        bit-identical FULL results."""
        reports, _ = collusion_reports(rng, 12, 48, liars=4, na_frac=0.1)
        others = [collusion_reports(np.random.default_rng(80 + i), 12, 48,
                                    liars=4, na_frac=0.1)[0]
                  for i in range(5)]
        cfg = ServeConfig(batch_window_ms=20.0, max_batch=8,
                          sharded_buckets=True)
        outs = []
        with ConsensusService(cfg) as svc:
            assert svc.mesh is not None
            outs.append(svc.submit(reports=reports).result(timeout=120))
        with ConsensusService(cfg) as svc:
            futs = [svc.submit(reports=m) for m in [reports] + others]
            outs.append(futs[0].result(timeout=120))
        with ConsensusService(cfg) as svc:
            outs.append(svc.submit(reports=reports).result(timeout=120))
        first = outs[0]
        for other in outs[1:]:
            for section in ("agents", "events"):
                for key, v in first[section].items():
                    np.testing.assert_array_equal(
                        np.asarray(v), np.asarray(other[section][key]),
                        err_msg=f"{section}.{key}")
            assert other["certainty"] == first["certainty"]
            assert other["iterations"] == first["iterations"]


class TestServiceMeshPolicy:
    def test_eligibility_gate(self):
        mesh = make_mesh(batch=2, event=4)
        p = serve_params()
        assert ss.sharded_bucket_eligible(64, 8, p, mesh)
        # event width must divide over the event axis
        assert not ss.sharded_bucket_eligible(66, 8, p, mesh)
        # small E < n_event is the documented single-device class
        assert not ss.sharded_bucket_eligible(2, 8, p, mesh)
        # capacity must divide over the batch axis
        assert not ss.sharded_bucket_eligible(64, 3, p, mesh)
        # no mesh -> never
        assert not ss.sharded_bucket_eligible(64, 8, p, None)
        # int8 sentinel storage stays on the fused path
        assert not ss.sharded_bucket_eligible(
            64, 8, p._replace(storage_dtype="int8"), mesh)

    def test_service_routes_by_divisibility(self, rng):
        """An indivisible event bucket falls back to the single-device
        topology; a divisible one rides the mesh — from one service."""
        cfg = ServeConfig(event_buckets=(18, 64), row_buckets=(16,),
                          batch_window_ms=0.0, sharded_buckets=True)
        svc = ConsensusService(cfg)
        assert svc.mesh is not None
        key_div = svc._bucket_key((16, 64), has_na=True, any_scaled=False,
                                  n_scaled=0, oracle_kwargs={})
        key_odd = svc._bucket_key((16, 18), has_na=True, any_scaled=False,
                                  n_scaled=0, oracle_kwargs={})
        assert key_div.topology == ss.mesh_fingerprint(svc.mesh)
        assert key_odd.topology == ss.SINGLE_TOPOLOGY
        svc.close(drain=False)

    def test_auto_stays_single_device_off_tpu(self):
        """sharded_buckets='auto' (the default) must not engage the mesh
        on the CPU test platform — existing single-device serving
        contracts stay untouched."""
        svc = ConsensusService(ServeConfig(batch_window_ms=0.0))
        assert svc.mesh is None and svc.n_devices == 1
        key = svc._bucket_key((16, 64), has_na=True, any_scaled=False,
                              n_scaled=0, oracle_kwargs={})
        assert key.topology == ss.SINGLE_TOPOLOGY
        svc.close(drain=False)

    def test_serve_mesh_layouts(self):
        mesh = ss.serve_mesh(max_batch=8)
        assert dict(mesh.shape) == {"batch": 2, "event": 4}
        # odd capacity cannot split lanes over a batch axis
        mesh1 = ss.serve_mesh(max_batch=1)
        assert dict(mesh1.shape) == {"batch": 1, "event": 8}
        with pytest.raises(ValueError, match="mesh_batch"):
            ss.serve_mesh(max_batch=8, mesh_batch=3)
        assert ss.serve_mesh(max_batch=8, devices=[object()]) is None

    def test_topology_helpers(self):
        mesh = make_mesh(batch=2, event=4)
        fp = ss.mesh_fingerprint(mesh)
        assert fp.endswith(":2x4")
        assert ss.topology_event_shards(fp) == 4
        assert ss.topology_n_devices(fp) == 8
        assert ss.topology_event_shards(ss.SINGLE_TOPOLOGY) == 1
        assert ss.topology_n_devices(ss.SINGLE_TOPOLOGY) == 1


class TestWrongTopologyRejection:
    def _key(self, topology):
        return BucketKey.make(16, 64, 8, serve_params(), topology)

    def test_mesh_cache_rejects_foreign_topology(self):
        cache = ExecutableCache(4, mesh=make_mesh(batch=2, event=4))
        with pytest.raises(ValueError, match="wrong-topology"):
            cache.get(self._key("tpu-v5e:2x4"))
        with pytest.raises(ValueError, match="wrong-topology"):
            cache.get(self._key("cpu:1x8"))

    def test_meshless_cache_rejects_any_mesh_topology(self):
        cache = ExecutableCache(4)
        fp = ss.mesh_fingerprint(make_mesh(batch=2, event=4))
        with pytest.raises(ValueError, match="wrong-topology"):
            cache.get(self._key(fp))

    def test_matching_topologies_serve(self):
        mesh = make_mesh(batch=1, event=8)
        cache = ExecutableCache(4, mesh=mesh)
        assert cache.get(self._key(ss.SINGLE_TOPOLOGY)) is not None
        assert cache.get(self._key(ss.mesh_fingerprint(mesh))) is not None
        assert len(cache) == 2

    def test_bucket_key_topology_field(self):
        p = serve_params()
        assert BucketKey.make(16, 64, 8, p).topology == ss.SINGLE_TOPOLOGY
        key = BucketKey.make(16, 64, 8, p, "cpu:2x4")
        assert key.topology == "cpu:2x4"
        assert key != BucketKey.make(16, 64, 8, p)


class TestShardedServeEndToEnd:
    def test_parity_with_direct_oracle_and_gauge(self, rng):
        """One mesh-served request: outcomes bit-identical to a direct
        Oracle resolution, retraces land under serve_bucket_sharded, and
        the bucket dispatch emits the mesh-width gauge (ISSUE 6
        satellite: bench's missing-metric path sees serve traffic)."""
        obs.reset()
        reports, _ = collusion_reports(rng, 12, 48, liars=4, na_frac=0.1)
        cfg = ServeConfig(warmup=((16, 64),), batch_window_ms=1.0,
                          sharded_buckets=True)
        with ConsensusService(cfg) as svc:
            n_event = svc.mesh.shape["event"]
            got = svc.submit(reports=reports).result(timeout=120)
            got2 = svc.submit(reports=reports).result(timeout=120)
        ref = Oracle(reports=reports, backend="jax",
                     pca_method="power").consensus()
        np.testing.assert_array_equal(got["events"]["outcomes_final"],
                                      ref["events"]["outcomes_final"])
        np.testing.assert_array_equal(
            got["events"]["outcomes_adjusted"],
            ref["events"]["outcomes_adjusted"])
        assert got["iterations"] == ref["iterations"]
        np.testing.assert_allclose(got["agents"]["smooth_rep"],
                                   ref["agents"]["smooth_rep"],
                                   atol=SHARD_ATOL)
        # serving determinism on the mesh
        np.testing.assert_array_equal(
            got["events"]["outcomes_raw"], got2["events"]["outcomes_raw"])
        # warmup pinned the sharded retrace counter; traffic kept it there
        assert obs.value("pyconsensus_jit_retraces_total",
                         entry="serve_bucket_sharded") == 1
        assert not obs.value("pyconsensus_jit_retraces_total",
                             entry="serve_bucket")
        assert obs.value("pyconsensus_mesh_event_shards") == n_event
