"""Out-of-core streaming resolution (parallel/streaming.py): two passes
over host panels must reproduce the in-memory light pipeline."""

import numpy as np
import pytest

from conftest import collusion_reports
from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                             _consensus_core_light)
from pyconsensus_tpu.parallel import streaming_consensus


def reference_light(reports, bounds=None):
    import jax.numpy as jnp

    from pyconsensus_tpu.oracle import parse_event_bounds
    R, E = reports.shape
    scaled, mins, maxs = parse_event_bounds(bounds, E)
    p = ConsensusParams(algorithm="sztorc", max_iterations=1,
                        pca_method="eigh-gram",
                        any_scaled=bool(scaled.any()), has_na=True)
    out = _consensus_core_light(jnp.asarray(reports),
                                jnp.full((R,), 1.0 / R),
                                jnp.asarray(scaled), jnp.asarray(mins),
                                jnp.asarray(maxs), p)
    return {k: np.asarray(v) for k, v in out.items()}


class TestStreamingParity:
    @pytest.mark.parametrize("panel_events", [4, 7, 64])
    def test_matches_in_memory(self, rng, panel_events):
        """Panel width must not matter — including ragged last panels and
        panels wider than E."""
        reports, _ = collusion_reports(rng, R=18, E=23, liars=5,
                                       na_frac=0.1)
        ref = reference_light(reports)
        out = streaming_consensus(reports, panel_events=panel_events)
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      ref["outcomes_adjusted"])
        np.testing.assert_allclose(out["smooth_rep"], ref["smooth_rep"],
                                   atol=1e-9)
        np.testing.assert_allclose(out["certainty"], ref["certainty"],
                                   atol=1e-9)
        np.testing.assert_allclose(out["participation_rows"],
                                   ref["participation_rows"], atol=1e-9)
        np.testing.assert_allclose(out["participation_columns"],
                                   ref["participation_columns"], atol=1e-9)
        np.testing.assert_allclose(out["reporter_bonus"],
                                   ref["reporter_bonus"], atol=1e-9)
        np.testing.assert_array_equal(out["na_row"], ref["na_row"])
        np.testing.assert_allclose(
            np.abs(out["first_loading"]), np.abs(ref["first_loading"]),
            atol=1e-8)

    @pytest.mark.parametrize("algorithm", ["sztorc", "fixed-variance",
                                           "ica"])
    def test_orth_iter_spectrum_above_eigh_cap(self, rng, algorithm,
                                               monkeypatch):
        """Round-5 first-hardware-contact fix: above STREAM_EIGH_MAX_R
        the streamed spectrum comes from orthogonal iteration on the
        explicit Gram accumulator (QDWH eigh's temporaries OOM'd the v5e
        HBM at R=10000). Forcing the cap below R here exercises that
        route and requires the same snapped outcomes as the in-memory
        pipeline (loadings agree to orth-iter tolerance, outcomes snap
        exactly)."""
        import jax.numpy as jnp

        from pyconsensus_tpu.parallel import streaming as st
        monkeypatch.setattr(st, "STREAM_EIGH_MAX_R", 4)
        reports, _ = collusion_reports(rng, R=18, E=23, liars=5,
                                       na_frac=0.1)
        R, E = reports.shape
        p = ConsensusParams(algorithm=algorithm, max_iterations=1,
                            pca_method="eigh-gram", any_scaled=False,
                            has_na=True)
        ref = _consensus_core_light(jnp.asarray(reports),
                                    jnp.full((R,), 1.0 / R),
                                    jnp.zeros(E, bool), jnp.zeros(E),
                                    jnp.ones(E), p)
        out = streaming_consensus(reports, panel_events=7, params=p)
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))
        # ica amplifies the orth-iter's ~1e-7 subspace tolerance through
        # FastICA (the module-documented sensitivity); outcomes snap
        # exactly either way
        np.testing.assert_allclose(out["smooth_rep"],
                                   np.asarray(ref["smooth_rep"]),
                                   atol=5e-5 if algorithm == "ica"
                                   else 5e-6)

    def test_scaled_events(self, rng):
        reports, _ = collusion_reports(rng, R=12, E=10, liars=3)
        reports[:, 8:] = rng.uniform(0.0, 50.0, size=(12, 2))
        bounds = [None] * 8 + [{"scaled": True, "min": 0.0,
                                "max": 50.0}] * 2
        ref = reference_light(reports, bounds)
        out = streaming_consensus(reports, event_bounds=bounds,
                                  panel_events=3)
        np.testing.assert_allclose(out["outcomes_final"],
                                   ref["outcomes_final"], atol=1e-9)
        np.testing.assert_allclose(out["smooth_rep"], ref["smooth_rep"],
                                   atol=1e-9)

    def test_from_npy_path(self, rng, tmp_path):
        from pyconsensus_tpu.io import save_reports
        reports, truth = collusion_reports(rng, R=16, E=12, liars=4)
        path = save_reports(tmp_path / "big.npy", reports)
        out = streaming_consensus(path, panel_events=5)
        ref = reference_light(reports)
        np.testing.assert_array_equal(out["outcomes_final"],
                                      ref["outcomes_final"])
        # truth-or-ambiguous, never captured
        final = out["outcomes_final"]
        assert not np.any(final == 1.0 - truth)

    def test_csv_stages_beside_source(self, rng, tmp_path, monkeypatch):
        """CSV staging lands in the source's directory (NOT the system temp
        dir, which may be RAM-backed tmpfs) — or in an explicit
        ``staging_dir`` — and is removed after resolution."""
        from pyconsensus_tpu import io as io_mod
        from pyconsensus_tpu.io import save_reports

        reports, _ = collusion_reports(rng, R=16, E=12, liars=4)
        src = save_reports(tmp_path / "big.csv", reports)
        ref = reference_light(reports)
        staged_at = []
        real = io_mod.csv_to_npy

        def spy(src_p, dst_p, **kw):
            staged_at.append(dst_p)
            return real(src_p, dst_p, **kw)

        monkeypatch.setattr(io_mod, "csv_to_npy", spy)
        out = streaming_consensus(src, panel_events=5)
        np.testing.assert_array_equal(out["outcomes_final"],
                                      ref["outcomes_final"])
        assert staged_at[0].parent == tmp_path
        other = tmp_path / "elsewhere"
        other.mkdir()
        out = streaming_consensus(src, panel_events=5, staging_dir=other)
        np.testing.assert_array_equal(out["outcomes_final"],
                                      ref["outcomes_final"])
        assert staged_at[1].parent == other
        # staging files cleaned up in both cases
        assert list(tmp_path.glob("*-stage-*")) == []
        assert list(other.glob("*-stage-*")) == []

    def test_sym_topk_whole_block_nan_fallback(self, monkeypatch):
        """Regression (Layer-3 PR satellite): _sym_topk's degenerate-QR
        guard must fall back WHOLE-BLOCK — ``jnp.isfinite(Q).all()`` —
        like jax_kernels._top_pcs_orth_iter. The old elementwise
        ``where(isfinite(Q), Q, V)`` spliced finite Q entries into V's
        columns, handing a NON-orthonormal mixed block to the alignment
        exit. Simulated here by making every in-loop QR return one NaN
        column (the TPU rank-loss shape): the fallback must keep the
        block exactly orthonormal, which the mixed block is not."""
        import jax.numpy as jnp

        from pyconsensus_tpu.parallel import streaming as st

        real_qr = jnp.linalg.qr
        calls = []

        def poisoned_qr(a, *args, **kw):
            out = real_qr(a, *args, **kw)
            calls.append(1)
            if len(calls) == 1:          # the start-block QR stays clean
                return out
            q, r = out
            return q.at[:, -1].set(jnp.nan), r

        monkeypatch.setattr(jnp.linalg, "qr", poisoned_qr)
        rng = np.random.default_rng(11)
        u = rng.standard_normal(12)
        Gd = jnp.asarray(np.outer(u, u))             # rank-1 PSD
        lam, V = st._sym_topk(Gd, 3)
        lam, V = np.asarray(lam), np.asarray(V)
        assert np.isfinite(lam).all() and np.isfinite(V).all()
        # the whole-block guarantee: the returned block is orthonormal
        np.testing.assert_allclose(V.T @ V, np.eye(3), atol=1e-6)
        assert (lam >= 0).all()

    def test_sym_topk_matches_eigh_and_poisons_nonfinite(self):
        """Unmocked behavior: top-k eigenpairs of an explicit PSD matrix
        match eigh, and a non-finite accumulator poisons the outputs
        loudly instead of 'converging' on the random start block."""
        import jax.numpy as jnp

        from pyconsensus_tpu.parallel import streaming as st

        rng = np.random.default_rng(5)
        A = rng.standard_normal((10, 6))
        Gd = jnp.asarray(A @ A.T)                    # rank 6 PSD
        lam, V = st._sym_topk(Gd, 3)
        ref_vals = np.linalg.eigvalsh(np.asarray(Gd))[::-1][:3]
        np.testing.assert_allclose(np.asarray(lam), ref_vals,
                                   rtol=1e-5, atol=1e-8)
        GV = np.asarray(Gd) @ np.asarray(V)
        np.testing.assert_allclose(GV, np.asarray(V) * np.asarray(lam),
                                   atol=1e-4 * ref_vals[0])
        lam_bad, V_bad = st._sym_topk(Gd.at[0, 0].set(jnp.nan), 2)
        assert np.isnan(np.asarray(lam_bad)).all()
        assert np.isnan(np.asarray(V_bad)).all()

    def test_rejects_unsupported(self, rng):
        reports, _ = collusion_reports(rng, R=8, E=6, liars=2)
        with pytest.raises(ValueError, match="unknown algorithm"):
            streaming_consensus(
                reports, params=ConsensusParams(algorithm="nonsense"))
        with pytest.raises(ValueError, match="panel_events"):
            streaming_consensus(reports, panel_events=0)

    def test_dbscan_jit_sq_dists_parity(self, rng):
        """Both dbscan-jit backends must produce identical conformity
        whether they compute distances themselves or receive them
        precomputed (the streaming path's contract)."""
        import jax.numpy as jnp

        from pyconsensus_tpu.models import clustering as cl

        X = rng.random((12, 7))
        rep = np.full(12, 1.0 / 12)
        sq = cl._pairwise_sq_dists_np(X)
        direct_np = cl.dbscan_jit_conformity_np(X, rep, 0.8, 2)
        given_np = cl.dbscan_jit_conformity_np(np.empty((12, 0)), rep,
                                               0.8, 2, sq_dists=sq)
        np.testing.assert_array_equal(direct_np, given_np)
        direct_j = cl.dbscan_jit_conformity_jax(jnp.asarray(X),
                                                jnp.asarray(rep), 0.8, 2)
        given_j = cl.dbscan_jit_conformity_jax(
            jnp.zeros((12, 0)), jnp.asarray(rep), 0.8, 2,
            sq_dists=jnp.asarray(sq))
        np.testing.assert_allclose(np.asarray(direct_j),
                                   np.asarray(given_j), atol=1e-12)
        np.testing.assert_allclose(np.asarray(given_j), given_np,
                                   atol=1e-9)

    def test_dbscan_jit_matches_in_memory(self, rng):
        """dbscan-jit streams too (round 4 completed the table): the
        on-device clustering runs against the S-derived distances."""
        import jax.numpy as jnp
        reports, _ = collusion_reports(rng, R=14, E=19, liars=4,
                                       na_frac=0.1)
        R, E = reports.shape
        p = ConsensusParams(algorithm="dbscan-jit", dbscan_eps=1.0,
                            max_iterations=2, any_scaled=False,
                            has_na=True)
        ref = _consensus_core_light(
            jnp.asarray(reports), jnp.full((R,), 1.0 / R),
            jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E), p)
        out = streaming_consensus(reports, panel_events=6, params=p)
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))
        np.testing.assert_allclose(out["smooth_rep"],
                                   np.asarray(ref["smooth_rep"]),
                                   atol=1e-8)
        assert out["iterations"] == int(ref["iterations"])

    @pytest.mark.parametrize("algorithm", ["fixed-variance", "ica"])
    @pytest.mark.parametrize("panel_events,max_iterations",
                             [(5, 1), (64, 3)])
    def test_multi_component_matches_in_memory(self, rng, algorithm,
                                               panel_events,
                                               max_iterations):
        """Round 4 (VERDICT r3 item 4): ica / fixed-variance out-of-core
        — the top-k spectrum streamed off the Gram accumulator must
        reproduce the in-memory eigh-gram route (identical math, panel-
        accumulated; x64 makes the comparison tight)."""
        import jax.numpy as jnp
        reports, _ = collusion_reports(rng, R=18, E=23, liars=5,
                                       na_frac=0.1)
        R, E = reports.shape
        p = ConsensusParams(algorithm=algorithm, pca_method="eigh-gram",
                            max_iterations=max_iterations,
                            any_scaled=False, has_na=True)
        ref = _consensus_core_light(
            jnp.asarray(reports), jnp.full((R,), 1.0 / R),
            jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E), p)
        out = streaming_consensus(reports, panel_events=panel_events,
                                  params=p)
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))
        np.testing.assert_allclose(out["smooth_rep"],
                                   np.asarray(ref["smooth_rep"]),
                                   atol=1e-8)
        np.testing.assert_allclose(out["certainty"],
                                   np.asarray(ref["certainty"]), atol=1e-8)
        assert out["iterations"] == int(ref["iterations"])
        if algorithm == "ica":
            assert "ica_converged" in out
            assert "first_loading" not in out
        else:
            np.testing.assert_allclose(
                np.abs(out["first_loading"]),
                np.abs(np.asarray(ref["first_loading"])), atol=1e-7)

    @pytest.mark.parametrize("algorithm", ["hierarchical", "dbscan"])
    def test_hybrid_clustering_matches_in_memory(self, rng, algorithm):
        """Hybrid clustering out-of-core: the R x R distance matrix
        derived from the streamed S accumulator must reproduce the
        in-memory hybrid path (same host clustering, fill-pinned
        distances)."""
        import jax.numpy as jnp

        from pyconsensus_tpu.models.pipeline import _consensus_hybrid
        reports, _ = collusion_reports(rng, R=14, E=19, liars=4,
                                       na_frac=0.1)
        R, E = reports.shape
        p = ConsensusParams(algorithm=algorithm, max_iterations=2,
                            any_scaled=False, has_na=True)
        ref = _consensus_hybrid(
            jnp.asarray(reports), jnp.full((R,), 1.0 / R),
            jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E), p,
            light=True)
        out = streaming_consensus(reports, panel_events=6, params=p)
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))
        np.testing.assert_allclose(out["smooth_rep"],
                                   np.asarray(ref["smooth_rep"]),
                                   atol=1e-8)
        np.testing.assert_allclose(out["participation_rows"],
                                   np.asarray(ref["participation_rows"]),
                                   atol=1e-8)
        assert out["iterations"] == int(ref["iterations"])

    @pytest.mark.parametrize("panel_events", [4, 64])
    def test_kmeans_matches_in_memory(self, rng, panel_events):
        """Out-of-core Lloyd reproduces the in-memory k-means variant:
        identical labels -> identical conformity -> identical reputation
        and outcomes."""
        import jax.numpy as jnp
        reports, _ = collusion_reports(rng, R=18, E=23, liars=5,
                                       na_frac=0.1)
        R, E = reports.shape
        p = ConsensusParams(algorithm="k-means", num_clusters=3,
                            max_iterations=1, any_scaled=False, has_na=True)
        ref = _consensus_core_light(
            jnp.asarray(reports), jnp.full((R,), 1.0 / R),
            jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E), p)
        out = streaming_consensus(reports, panel_events=panel_events,
                                  params=p)
        assert "first_loading" not in out
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))
        np.testing.assert_allclose(out["smooth_rep"],
                                   np.asarray(ref["smooth_rep"]), atol=1e-9)
        np.testing.assert_allclose(out["certainty"],
                                   np.asarray(ref["certainty"]), atol=1e-9)

    @pytest.mark.parametrize("algorithm", ["sztorc", "k-means"])
    def test_mesh_sharded_panels_match_unsharded(self, rng, algorithm):
        """Out-of-core x multi-chip composition: panels placed
        event-sharded over the 8-device mesh must reproduce the
        single-device streaming result (the per-panel contractions reduce
        over the sharded axis; GSPMD all-reduces the R x R partials).
        panel_events=5 also exercises the round-up to a shardable
        width."""
        import jax
        from pyconsensus_tpu.parallel import make_mesh

        assert len(jax.devices()) == 8
        mesh = make_mesh(batch=1, event=8)
        reports, _ = collusion_reports(rng, R=18, E=21, liars=5,
                                       na_frac=0.1)
        p = ConsensusParams(algorithm=algorithm, max_iterations=2,
                            num_clusters=3)
        plain = streaming_consensus(reports, panel_events=5, params=p)
        sharded = streaming_consensus(reports, panel_events=5, params=p,
                                      mesh=mesh)
        np.testing.assert_array_equal(sharded["outcomes_adjusted"],
                                      plain["outcomes_adjusted"])
        np.testing.assert_allclose(sharded["smooth_rep"],
                                   plain["smooth_rep"], atol=1e-9)
        np.testing.assert_allclose(sharded["certainty"],
                                   plain["certainty"], atol=1e-9)

    @staticmethod
    def _run_multihost(reports, params, n_hosts, panel_events):
        """Resolve on ``n_hosts`` threads with a rendezvous-sum allreduce;
        returns ``{host_id: result}``. The barrier carries a timeout so a
        host that skips a collective (the regression these tests guard
        against) raises BrokenBarrierError into every peer instead of
        deadlocking the suite."""
        import threading

        bar = threading.Barrier(n_hosts, timeout=60)
        contrib = {}
        summed = {}

        def make_allreduce(i):
            def allreduce(x):
                contrib[i] = np.asarray(x)
                bar.wait()
                if i == 0:
                    summed["v"] = sum(contrib[j] for j in range(n_hosts))
                bar.wait()
                out = summed["v"]
                bar.wait()          # all read before the next round
                return out
            return allreduce

        results = {}
        errors = []

        def host(i):
            try:
                results[i] = streaming_consensus(
                    reports, panel_events=panel_events, params=params,
                    host_id=i, n_hosts=n_hosts,
                    allreduce=make_allreduce(i))
            except Exception as exc:       # surface thread failures
                errors.append(exc)
                bar.abort()

        threads = [threading.Thread(target=host, args=(i,), daemon=True)
                   for i in range(n_hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads), "host thread hung"
        return results

    @staticmethod
    def _assert_multihost_parity(results, plain):
        for res in results.values():
            np.testing.assert_array_equal(res["outcomes_adjusted"],
                                          plain["outcomes_adjusted"])
            np.testing.assert_allclose(res["smooth_rep"],
                                       plain["smooth_rep"], atol=1e-9)
            np.testing.assert_allclose(res["participation_rows"],
                                       plain["participation_rows"],
                                       atol=1e-9)
            assert res["iterations"] == plain["iterations"]

    @pytest.mark.parametrize("algorithm", ["sztorc", "ica",
                                           "fixed-variance",
                                           "hierarchical", "dbscan-jit",
                                           "k-means"])
    def test_multi_host_split_matches_single(self, rng, algorithm):
        """Two 'hosts' each stream half the panels; the reduced result
        must equal the single-host resolution bit-for-bit on snapped
        outcomes. The same wiring runs across real OS processes in
        test_distributed.py. Round 4: every algorithm multi-hosts — the
        R x R statistic variants via the stacked accumulator allreduce,
        k-means via its (R, k) distance allreduce with event-local
        centroids."""
        reports, _ = collusion_reports(rng, R=16, E=23, liars=4,
                                       na_frac=0.1)
        p = ConsensusParams(algorithm=algorithm, max_iterations=3)
        plain = streaming_consensus(reports, panel_events=4, params=p)
        results = self._run_multihost(reports, p, n_hosts=2,
                                      panel_events=4)
        self._assert_multihost_parity(results, plain)

    @pytest.mark.parametrize("algorithm", ["sztorc", "k-means"])
    def test_more_hosts_than_panels(self, rng, algorithm):
        """A host whose round-robin slice is EMPTY (3 hosts, 2 panels)
        must still join every collective in lock-step with zero
        contributions — the fragile case for any per-panel early-out."""
        reports, _ = collusion_reports(rng, R=12, E=23, liars=3,
                                       na_frac=0.1)
        p = ConsensusParams(algorithm=algorithm, max_iterations=2)
        plain = streaming_consensus(reports, panel_events=16, params=p)
        results = self._run_multihost(reports, p, n_hosts=3,
                                      panel_events=16)
        self._assert_multihost_parity(results, plain)

    def test_multi_host_validation(self, rng):
        reports, _ = collusion_reports(rng, R=8, E=6, liars=2)
        with pytest.raises(ValueError, match="host_id"):
            streaming_consensus(reports, host_id=5, n_hosts=2)
        # default allreduce requires n_hosts == jax.process_count()
        # (1 in-process): fewer deadlocks, more silently drops panels
        with pytest.raises(ValueError, match="process"):
            streaming_consensus(reports, host_id=0, n_hosts=2)
        # a custom allreduce without the host split is a silent no-op —
        # reject it
        with pytest.raises(ValueError, match="allreduce"):
            streaming_consensus(reports, allreduce=lambda x: x)

    def test_kmeans_multi_iteration_matches_in_memory(self, rng):
        """Iterative redistribution with k-means scoring: the fill-pinned
        seed reuse and per-iteration reputation threading must reproduce
        the in-memory scan."""
        import jax.numpy as jnp
        reports, _ = collusion_reports(rng, R=18, E=23, liars=5,
                                       na_frac=0.1)
        R, E = reports.shape
        p = ConsensusParams(algorithm="k-means", num_clusters=3,
                            max_iterations=4, any_scaled=False, has_na=True)
        ref = _consensus_core_light(
            jnp.asarray(reports), jnp.full((R,), 1.0 / R),
            jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E), p)
        out = streaming_consensus(reports, panel_events=6, params=p)
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))
        np.testing.assert_allclose(out["smooth_rep"],
                                   np.asarray(ref["smooth_rep"]), atol=1e-9)
        assert out["iterations"] == int(ref["iterations"])
        assert out["convergence"] == bool(ref["convergence"])

    @pytest.mark.parametrize("max_iterations", [3, 25])
    def test_multi_iteration_matches_in_memory(self, rng, max_iterations):
        """Iterative redistribution: one accumulation pass per executed
        iteration must reproduce the in-memory scan (same outcomes,
        reputation, iteration count, convergence flag)."""
        import jax.numpy as jnp
        reports, _ = collusion_reports(rng, R=20, E=17, liars=5,
                                       na_frac=0.08)
        R, E = reports.shape
        p = ConsensusParams(algorithm="sztorc",
                            max_iterations=max_iterations,
                            convergence_tolerance=1e-3,
                            pca_method="eigh-gram", any_scaled=False,
                            has_na=True)
        ref = _consensus_core_light(
            jnp.asarray(reports), jnp.full((R,), 1.0 / R),
            jnp.zeros(E, dtype=bool), jnp.zeros(E), jnp.ones(E), p)
        out = streaming_consensus(reports, panel_events=5, params=p)
        np.testing.assert_array_equal(out["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))
        np.testing.assert_allclose(out["smooth_rep"],
                                   np.asarray(ref["smooth_rep"]), atol=1e-9)
        assert out["iterations"] == int(ref["iterations"])
        assert out["convergence"] == bool(ref["convergence"])
