"""Direct unit tests for small dispatch/placement helpers that the suite
otherwise only exercises indirectly through the full pipeline — their
decision tables are load-bearing (PCA strategy routing, device placement
reuse, sweep-grid layout) and a silent change would surface far away from
its cause."""

import jax
import numpy as np
import pytest

from pyconsensus_tpu.ops import jax_kernels as jk
from pyconsensus_tpu.parallel import (batch_event_sharding, make_mesh,
                                      place_event_bounds)
from pyconsensus_tpu.sim import flat_grid


class TestResolvePcaMethod:
    """The auto/downgrade decision table (jax_kernels.resolve_pca_method):
    never E×E at scale, never the Pallas interpreter beyond toy sizes."""

    def test_auto_by_shape(self):
        assert jk.resolve_pca_method(10, 512, "auto") == "eigh-cov"
        assert jk.resolve_pca_method(100, 5000, "auto") == "eigh-gram"
        # big R and E: matrix-free (CPU test platform -> power, not the
        # Pallas interpreter)
        assert jk.resolve_pca_method(5000, 50_000, "auto") == "power"

    def test_explicit_methods_pass_through(self):
        for m in ("eigh-cov", "eigh-gram", "power"):
            assert jk.resolve_pca_method(100, 5000, m) == m

    def test_fused_downgrades_off_tpu_at_size(self):
        # tiny shapes may run the interpreter (tests); big ones must not
        assert jk.resolve_pca_method(10, 64, "power-fused") == "power-fused"
        assert jk.resolve_pca_method(5000, 50_000, "power-fused") == "power"


class TestPlacedBounds:
    def test_round_trip_and_counts(self):
        mesh = make_mesh(batch=1, event=8)
        E = 32
        bounds = [None] * 28 + [{"scaled": True, "min": -5.0,
                                 "max": 15.0}] * 4
        placed = place_event_bounds(bounds, E, mesh)
        assert placed.any_scaled is True
        assert placed.n_scaled == 4
        np.testing.assert_array_equal(np.asarray(placed.scaled),
                                      [False] * 28 + [True] * 4)
        assert np.asarray(placed.mins)[-1] == -5.0
        assert np.asarray(placed.maxs)[-1] == 15.0
        # resolving with PlacedBounds equals resolving with the raw list
        from pyconsensus_tpu.parallel import sharded_consensus

        rng = np.random.default_rng(0)
        reports = rng.choice([0.0, 1.0], size=(12, E))
        reports[:, -4:] = rng.uniform(-5.0, 15.0, size=(12, 4))
        a = sharded_consensus(reports, event_bounds=placed, mesh=mesh)
        b = sharded_consensus(reports, event_bounds=bounds, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(a["outcomes_final"]),
                                      np.asarray(b["outcomes_final"]))

    def test_all_binary(self):
        mesh = make_mesh(batch=1, event=2)
        placed = place_event_bounds(None, 16, mesh)
        assert placed.any_scaled is False
        assert placed.n_scaled == 0


class TestBatchEventSharding:
    def test_spec_axes(self):
        mesh = make_mesh(batch=2, event=4)
        sharding = batch_event_sharding(mesh)
        assert sharding.spec == jax.sharding.PartitionSpec(
            "batch", None, "event")
        # a (B, R, E) batch places without error and shards both axes
        x = jax.device_put(np.zeros((4, 6, 8)), sharding)
        assert x.sharding.is_equivalent_to(sharding, 3)


class TestFlatGrid:
    def test_layout_is_trial_major(self):
        lf, var, grid_lf, grid_var = flat_grid([0.1, 0.2], [0.5], 3)
        np.testing.assert_array_equal(grid_lf,
                                      [0.1, 0.1, 0.1, 0.2, 0.2, 0.2])
        np.testing.assert_array_equal(grid_var, [0.5] * 6)

    def test_rejects_empty(self):
        with pytest.raises(ValueError):
            flat_grid([], [0.1], 2)
        with pytest.raises(ValueError):
            flat_grid([0.1], [0.1], 0)
