"""The observability subsystem (ISSUE 3 tentpole): span tracer semantics
(nesting, exception safety, device blocking, threading), metrics registry
(counter/gauge/histogram, bucket edges, label hygiene), sinks (Prometheus
exposition golden test, JSONL round-trip + tree reconstruction), compile
observability (retrace counter on a deliberately re-specialized jit
function), and the real pipeline emission contract (convergence metrics
from a small Oracle.consensus run)."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.obs import MetricsRegistry, Tracer


@pytest.fixture
def registry():
    return MetricsRegistry()


@pytest.fixture
def tracer(registry):
    return Tracer(registry=registry)


# ------------------------------------------------------------- tracer


class TestTracer:
    def test_nesting_and_parent_ids(self, tracer):
        with tracer.span("root") as root:
            with tracer.span("child") as child:
                with tracer.span("grandchild") as grand:
                    assert tracer.current() is grand
                assert tracer.current() is child
        assert child.parent_id == root.span_id
        assert grand.parent_id == child.span_id
        assert root.parent_id == 0
        assert (root.depth, child.depth, grand.depth) == (0, 1, 2)
        # finish order: children before parents
        assert [s.name for s in tracer.spans()] == ["grandchild", "child",
                                                    "root"]

    def test_exception_safety(self, tracer):
        with pytest.raises(ValueError, match="boom"):
            with tracer.span("outer"):
                with tracer.span("failing"):
                    raise ValueError("boom")
        spans = {s.name: s for s in tracer.spans()}
        assert spans["failing"].status == "error"
        assert "boom" in spans["failing"].error
        assert spans["outer"].status == "error"   # propagated through
        assert tracer.current() is None           # stack fully unwound
        # the tracer still works after the exception
        with tracer.span("after"):
            pass
        assert tracer.spans()[-1].status == "ok"

    def test_observe_blocks_all_values(self, tracer):
        class Recorder:
            blocked = 0

            def block_until_ready(self):
                Recorder.blocked += 1
                return self

        with tracer.span("s") as sp:
            sp.observe(Recorder())
            sp.observe(Recorder())
        assert Recorder.blocked == 2

    def test_observe_without_span_passes_through(self, tracer):
        x = object()
        assert tracer.observe(x) is x

    def test_durations_feed_registry(self, tracer, registry):
        with tracer.span("timed"):
            pass
        hist = registry.get("pyconsensus_phase_seconds")
        assert hist.value(phase="timed")["count"] == 1

    def test_threads_get_independent_stacks(self, tracer):
        def worker():
            with tracer.span("worker_root"):
                pass

        with tracer.span("main_root"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        spans = {s.name: s for s in tracer.spans()}
        # the worker's span must NOT be parented under main's open span
        assert spans["worker_root"].parent_id == 0

    def test_report_tree_indents_children(self, tracer):
        with tracer.span("root"):
            with tracer.span("leaf"):
                pass
        rep = tracer.report()
        root_line = [ln for ln in rep.splitlines() if "root" in ln][0]
        leaf_line = [ln for ln in rep.splitlines() if "leaf" in ln][0]
        assert not root_line.startswith(" ")
        assert leaf_line.startswith("  ")

    def test_span_cap_drops_oldest(self, registry):
        t = Tracer(registry=registry, max_spans=5)
        for i in range(8):
            with t.span(f"s{i}"):
                pass
        assert len(t.spans()) == 5
        assert t.dropped() == 3
        assert t.spans()[0].name == "s3"

    def test_report_promotes_orphaned_children(self, tracer):
        """A finished child whose parent is missing from the ring (still
        open, or evicted) must appear in report() as a root — matching
        sinks.span_tree — not silently vanish."""
        with tracer.span("still_open"):
            with tracer.span("orphan_child"):
                pass
            rep = tracer.report()     # parent not finished yet
        assert "orphan_child" in rep, rep


# ------------------------------------------------------------ metrics


class TestMetrics:
    def test_counter_accumulates_per_label(self, registry):
        c = registry.counter("t_total", "help", labels=("k",))
        c.inc(k="a")
        c.inc(2.5, k="a")
        c.inc(k="b")
        assert c.value(k="a") == 3.5
        assert c.value(k="b") == 1.0
        assert c.value(k="never") == 0.0

    def test_counter_rejects_decrease_and_label_typos(self, registry):
        c = registry.counter("t_total", labels=("k",))
        with pytest.raises(ValueError, match="decrease"):
            c.inc(-1, k="a")
        with pytest.raises(ValueError, match="labels"):
            c.inc(wrong="a")

    def test_gauge_last_write_wins(self, registry):
        g = registry.gauge("g")
        assert g.value() is None
        g.set(3)
        g.set(7)
        assert g.value() == 7.0

    def test_histogram_bucket_edges_inclusive_upper(self, registry):
        """le is an INCLUSIVE upper bound (the Prometheus contract): a
        value exactly on an edge lands in that edge's bucket."""
        h = registry.histogram("h", buckets=(1.0, 2.0, 5.0))
        for v in (0.5, 1.0, 1.0001, 2.0, 5.0, 99.0):
            h.observe(v)
        text = registry.render_prom()
        assert 'h_bucket{le="1"} 2' in text        # 0.5, 1.0
        assert 'h_bucket{le="2"} 4' in text        # + 1.0001, 2.0
        assert 'h_bucket{le="5"} 5' in text        # + 5.0
        assert 'h_bucket{le="+Inf"} 6' in text     # + 99.0
        assert "h_count 6" in text
        assert f"h_sum {0.5 + 1.0 + 1.0001 + 2.0 + 5.0 + 99.0!r}" in text

    def test_histogram_rejects_unsorted_buckets(self, registry):
        with pytest.raises(ValueError, match="ascending"):
            registry.histogram("h", buckets=(2.0, 1.0))

    def test_reregistration_returns_same_metric(self, registry):
        a = registry.counter("x_total", labels=("k",))
        b = registry.counter("x_total", labels=("k",))
        assert a is b
        with pytest.raises(ValueError, match="conflicting"):
            registry.gauge("x_total")
        with pytest.raises(ValueError, match="conflicting"):
            registry.counter("x_total", labels=("other",))

    def test_histogram_bucket_conflict_raises(self, registry):
        h = registry.histogram("h", buckets=(1.0, 2.0))
        assert registry.histogram("h", buckets=(1.0, 2.0)) is h
        with pytest.raises(ValueError, match="buckets"):
            registry.histogram("h", buckets=(5.0, 10.0))

    def test_invalid_names_rejected(self, registry):
        with pytest.raises(ValueError, match="metric name"):
            registry.counter("bad-name")
        with pytest.raises(ValueError, match="label name"):
            registry.counter("ok", labels=("bad-label",))

    def test_value_lookup_fails_soft(self, registry):
        assert registry.value("never_registered") is None
        registry.counter("c_total", labels=("k",))
        assert registry.value("c_total", wrong_label="x") is None

    def test_thread_safety_under_contention(self, registry):
        c = registry.counter("n_total")
        h = registry.histogram("d", buckets=(0.5,))

        def hammer():
            for _ in range(1000):
                c.inc()
                h.observe(0.25)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 8000
        assert h.value()["count"] == 8000


# -------------------------------------------------------------- sinks


class TestSinks:
    def test_prometheus_exposition_golden(self, registry):
        """Golden test of the text exposition format v0.0.4: HELP/TYPE
        headers, label escaping, histogram expansion, trailing newline."""
        registry.counter("req_total", "requests served",
                         labels=("path",)).inc(3, path='a"b\\c\nd')
        registry.gauge("temp", "temperature").set(1.5)
        registry.histogram("lat_seconds", "latency", buckets=(0.1, 1.0)
                           ).observe(0.05)
        got = registry.render_prom()
        expected = (
            "# HELP lat_seconds latency\n"
            "# TYPE lat_seconds histogram\n"
            'lat_seconds_bucket{le="0.1"} 1\n'
            'lat_seconds_bucket{le="1"} 1\n'
            'lat_seconds_bucket{le="+Inf"} 1\n'
            "lat_seconds_sum 0.05\n"
            "lat_seconds_count 1\n"
            "# HELP req_total requests served\n"
            "# TYPE req_total counter\n"
            'req_total{path="a\\"b\\\\c\\nd"} 3\n'
            "# HELP temp temperature\n"
            "# TYPE temp gauge\n"
            "temp 1.5\n"
        )
        assert got == expected

    def test_empty_registry_renders_empty(self, registry):
        assert registry.render_prom() == ""
        # registered but never emitted -> no series, no headers
        registry.counter("silent_total", labels=("k",))
        assert registry.render_prom() == ""

    def test_jsonl_round_trip_and_tree(self, tracer, tmp_path):
        with tracer.span("root", algorithm="sztorc"):
            with tracer.span("fill"):
                pass
            with tracer.span("iterate", n=3):
                with tracer.span("scores"):
                    pass
        path = tmp_path / "trace.jsonl"
        n = obs.write_jsonl(path, tracer.events(), meta={"run": "test"})
        back = obs.read_jsonl(path)
        assert n == len(back) == 5                # meta + 4 spans
        assert back[0]["type"] == "meta" and back[0]["run"] == "test"
        # every record is plain JSON (the file is line-parseable)
        for line in path.read_text().splitlines():
            json.loads(line)
        tree = obs.span_tree(back)
        assert len(tree) == 1
        root = tree[0]
        assert root["name"] == "root"
        assert root["attrs"]["algorithm"] == "sztorc"
        assert [c["name"] for c in root["children"]] == ["fill", "iterate"]
        assert [c["name"] for c in root["children"][1]["children"]] == [
            "scores"]
        # attrs survive the round trip typed
        assert root["children"][1]["attrs"]["n"] == 3

    def test_span_tree_keys_per_process(self):
        """Merged fleet JSONL: every host numbers span_ids from 1, so
        tree reconstruction must key (process_index, span_id) — a host-0
        child must never attach under host 1's same-numbered span."""
        merged = []
        for proc in (0, 1):
            merged += [
                {"type": "span", "name": f"root_p{proc}", "span_id": 1,
                 "parent_id": 0, "process_index": proc, "start_s": 1.0},
                {"type": "span", "name": f"child_p{proc}", "span_id": 2,
                 "parent_id": 1, "process_index": proc, "start_s": 2.0},
            ]
        tree = obs.span_tree(merged)
        assert sorted(t["name"] for t in tree) == ["root_p0", "root_p1"]
        for root in tree:
            proc = root["process_index"]
            assert [c["name"] for c in root["children"]] == [
                f"child_p{proc}"]

    def test_async_failure_at_block_marks_span_error(self, tracer):
        """An observed value that fails ASYNCHRONOUSLY (raises at
        block_until_ready) must not leave a green span for the phase
        that crashed."""

        class Poisoned:
            def block_until_ready(self):
                raise RuntimeError("async XLA failure")

        with pytest.raises(RuntimeError, match="async XLA failure"):
            with tracer.span("crashing") as sp:
                sp.observe(Poisoned())
        recorded = tracer.spans()[-1]
        assert recorded.status == "error"
        assert "async XLA failure" in recorded.error
        assert recorded.duration_s is not None
        assert tracer.current() is None       # stack still unwound

    def test_span_tree_orphans_become_roots(self):
        events = [
            {"type": "span", "name": "orphan", "span_id": 7,
             "parent_id": 99, "start_s": 1.0},
            {"type": "meta"},
        ]
        tree = obs.span_tree(events)
        assert [t["name"] for t in tree] == ["orphan"]

    def test_write_prom_writes_file(self, registry, tmp_path):
        registry.counter("c_total").inc()
        text = obs.write_prom(tmp_path / "sub" / "m.prom", registry)
        assert (tmp_path / "sub" / "m.prom").read_text() == text
        assert "c_total 1" in text


# ----------------------------------------------- compile observability


class TestCompileObservability:
    def test_retrace_counter_on_respecialization(self, registry):
        """The acceptance invariant: identical re-calls keep the counter
        at 1; a deliberately re-specialized call (new shape -> new trace)
        increments it."""
        f = obs.instrument_jit(jax.jit(lambda x: x * 2), "t_entry",
                               registry=registry)
        f(jnp.ones(4))
        f(jnp.ones(4))
        f(jnp.ones(4))
        assert registry.value("pyconsensus_jit_retraces_total",
                              entry="t_entry") == 1
        f(jnp.ones(8))                         # re-specialize: new shape
        assert registry.value("pyconsensus_jit_retraces_total",
                              entry="t_entry") == 2
        assert registry.value("pyconsensus_jit_compile_seconds",
                              entry="t_entry") > 0

    def test_wrapper_forwards_jit_introspection(self, registry):
        f = obs.instrument_jit(jax.jit(lambda x: x + 1), "fwd",
                               registry=registry)
        f(jnp.ones(3))
        assert f._cache_size() == 1            # forwarded attribute
        lowered = f.lower(jnp.ones(3))         # contracts.py's usage
        assert "stablehlo" in lowered.as_text().lower() or lowered
        assert repr(f).startswith("InstrumentedJit(fwd")

    def test_wrapper_passthrough_for_plain_callables(self, registry):
        g = obs.instrument_jit(lambda x: x - 1, "plain", registry=registry)
        assert g(3) == 2                       # no _cache_size: no crash
        # never emitted -> fail-soft lookup (None), never a phantom count
        assert not registry.value("pyconsensus_jit_retraces_total",
                                  entry="plain")

    def test_wrapper_noops_under_trace(self, registry):
        inner = obs.instrument_jit(jax.jit(lambda x: x * 3), "inner_entry",
                                   registry=registry)
        outer = jax.jit(lambda x: inner(x))
        outer(jnp.ones(2))
        # the inner wrapper saw only tracers — no retrace recorded for it
        assert not registry.value("pyconsensus_jit_retraces_total",
                                  entry="inner_entry")


# -------------------------------------------- pipeline emission contract


REPORTS = np.array([
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 0.0, 0.0, 0.0],
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 1.0, 1.0, 0.0],
    [0.0, 0.0, 1.0, 1.0],
    [np.nan, 0.0, 1.0, 1.0],
])


class TestPipelineEmission:
    def test_oracle_consensus_emits_convergence_metrics(self):
        obs.reset()
        r = Oracle(reports=REPORTS, backend="numpy",
                   max_iterations=7).consensus()
        conv = str(bool(r["convergence"])).lower()
        assert obs.value("pyconsensus_consensus_total", algorithm="sztorc",
                         backend="numpy", converged=conv) == 1
        iters = obs.value("pyconsensus_consensus_iterations",
                          algorithm="sztorc", backend="numpy")
        assert iters["count"] == 1
        assert iters["sum"] == r["iterations"]
        # residual histogram saw one observation per executed iteration
        res = obs.value("pyconsensus_convergence_residual",
                        backend="numpy")
        assert res["count"] == r["iterations"]
        # redistribution mass: raw + smooth, both in [0, 1]
        mass = obs.REGISTRY.get("pyconsensus_redistribution_mass")
        for kind in ("raw", "smooth"):
            v = mass.value(kind=kind)
            assert v["count"] == 1
            assert 0.0 <= v["sum"] <= 1.0
        # the NaN cell was counted as a fill
        assert obs.value("pyconsensus_na_fills_total",
                         backend="numpy") == 1
        # span tree: oracle.consensus wraps the numpy phases
        names = [s.name for s in obs.TRACER.spans()]
        assert "oracle.consensus" in names
        assert {"np.fill", "np.iterate", "np.resolve"} <= set(names)

    def test_oracle_jax_backend_emits_and_counts_compiles(self):
        obs.reset()
        Oracle(reports=REPORTS, backend="jax", max_iterations=3).consensus()
        Oracle(reports=REPORTS, backend="jax", max_iterations=3).consensus()
        assert obs.value("pyconsensus_consensus_total", algorithm="sztorc",
                         backend="jax", converged="false") == 2
        # identical params + shape: the entry point compiled ONCE across
        # both resolutions (the acceptance-criterion invariant)
        assert obs.value("pyconsensus_jit_retraces_total",
                         entry="consensus_core") == 1

    def test_hybrid_emits_cluster_spans(self):
        obs.reset()
        Oracle(reports=REPORTS, algorithm="hierarchical", backend="jax",
               max_iterations=2).consensus()
        names = [s.name for s in obs.TRACER.spans()]
        assert "hybrid.device_prep" in names
        assert "hybrid.cluster" in names
        assert "clustering.hierarchical" in names
        res = obs.value("pyconsensus_convergence_residual",
                        backend="hybrid")
        assert res is not None and res["count"] >= 1

    def test_sharded_consensus_counts_paths(self):
        obs.reset()
        from pyconsensus_tpu.parallel import make_mesh, sharded_consensus

        mesh = make_mesh(batch=1)
        out = sharded_consensus(REPORTS, mesh=mesh)
        np.asarray(out["outcomes_adjusted"])
        snap = obs.REGISTRY.snapshot()[
            "pyconsensus_sharded_resolutions_total"]["series"]
        assert sum(snap.values()) == 1
        assert obs.value("pyconsensus_mesh_event_shards") is not None
