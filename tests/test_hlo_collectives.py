"""Compiled-HLO regression tests pinning the sharded path's collective
costs (docs/PERFORMANCE.md "Scaling design"; VERDICT r1 item 5).

The scaling claim is: on an event-sharded mesh, per-sweep all-reduces move
only (R,)-sized partials, and no collective ever carries an O(R x E) or
R x R operand. These tests compile the real jitted pipeline on the virtual
8-device CPU mesh and check the optimized (post-GSPMD-partitioning) HLO
against the SAME declared budgets the ``consensus-lint`` traced-contract
layer enforces in CI (``pyconsensus_tpu.analysis.contracts`` +
``contracts.json`` — the single source of truth for collective
inventories; this file's original private helpers became that module).
This caught a real one: the blocked weighted median's ``dynamic_slice``
over the sharded event axis made GSPMD all-gather the full (R, E) matrix
onto every device (fixed by ``median_block=0`` on multi-device meshes
plus take_along_axis indexing in the median block).
"""

import jax
import numpy as np
import pytest

from pyconsensus_tpu.analysis.contracts import (check_collective_budget,
                                                collective_inventory,
                                                collective_sizes,
                                                load_contracts)
from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                             consensus_light_jit)
from pyconsensus_tpu.oracle import parse_event_bounds
from pyconsensus_tpu.parallel import make_mesh
from pyconsensus_tpu.parallel.sharded import _place_inputs

R, E = 32, 2048
N_DEV = 8
N_SCALED = 256

#: the lint subsystem's declared budgets, keyed by contract name — the
#: tests below assert against THESE, so a budget edit and a pipeline
#: regression both surface here and in `consensus-lint --strict` alike
_CONTRACTS = {c["name"]: c for c in load_contracts()}


def _check(hlo_text, contract_name, R_=R, E_=E, n_dev=N_DEV):
    budget = _CONTRACTS[contract_name]["budget"]
    env = {"R": R_, "E": E_, "n_dev": n_dev}
    return check_collective_budget(collective_inventory(hlo_text), budget,
                                   env)


def compiled_hlo(reports, bounds, params):
    scaled, mins, maxs = parse_event_bounds(bounds, E)
    mesh = make_mesh(batch=1, event=N_DEV)
    placed = _place_inputs(mesh, reports, np.full(R, 1.0 / R), scaled,
                           mins, maxs)
    return consensus_light_jit.lower(*placed, params).compile().as_text()


@pytest.fixture(scope="module")
def binary_reports(request):
    rng = np.random.default_rng(0)
    return rng.choice([0.0, 1.0], size=(R, E))


class TestSharedHelpers:
    def test_sizes_view_matches_inventory(self, binary_reports):
        """collective_sizes is the dtype-blind projection of
        collective_inventory — same instructions, same element counts."""
        p = ConsensusParams(algorithm="sztorc", pca_method="power",
                            has_na=False, any_scaled=False, median_block=0)
        hlo = compiled_hlo(binary_reports, None, p)
        inv = collective_inventory(hlo)
        sizes = collective_sizes(hlo)
        assert sorted(n for _, _, n in inv) == sorted(
            n for ns in sizes.values() for n in ns)
        assert inv, "sharded compile must contain collectives"


class TestShardedCollectiveCosts:
    def test_binary_power_path(self, binary_reports):
        p = ConsensusParams(algorithm="sztorc", pca_method="power",
                            has_na=False, any_scaled=False, median_block=0)
        hlo = compiled_hlo(binary_reports, None, p)
        assert _check(hlo, "pipeline-binary-power-sharded") == []

    def test_scaled_power_path(self, binary_reports):
        """The scaled-event resolution (weighted median) must not change the
        collective footprint — before round 2's median_block=0 +
        take_along_axis fixes this compiled to a full (R, E) all-gather
        plus (E, 2) index gathers on every device."""
        reports = binary_reports.copy()
        rng = np.random.default_rng(1)
        reports[:, -N_SCALED:] = rng.uniform(0, 50, size=(R, N_SCALED))
        bounds = ([None] * (E - N_SCALED)
                  + [{"scaled": True, "min": 0.0, "max": 50.0}] * N_SCALED)
        p = ConsensusParams(algorithm="sztorc", pca_method="power",
                            has_na=False, any_scaled=True, median_block=0)
        hlo = compiled_hlo(reports, bounds, p)
        assert _check(hlo, "pipeline-scaled-power-sharded") == []
        # scaled resolution adds NO collectives beyond the binary path's
        sizes = collective_sizes(hlo)
        binary = collective_sizes(compiled_hlo(
            binary_reports, None,
            ConsensusParams(algorithm="sztorc", pca_method="power",
                            has_na=False, any_scaled=False, median_block=0)))
        assert sorted(sizes.keys()) == sorted(binary.keys())
        assert len(sizes["all-reduce"]) == len(binary["all-reduce"])

    def test_gram_path_one_rxr_allreduce(self, binary_reports):
        """The eigh-gram strategy (exact path; mandatory for the
        multi-component fixed-variance/ICA variants) legitimately
        all-reduces ONE R x R Gram matrix per outer iteration — an
        algorithmic cost, not a regression (SURVEY.md §7 route b; at the
        R<=4096 sizes auto picks it, that is <=64 MB over ICI). The
        declared gram contract pins it to exactly one R x R-sized
        all-reduce and nothing larger."""
        p = ConsensusParams(algorithm="sztorc", pca_method="eigh-gram",
                            has_na=False, any_scaled=False, median_block=0)
        hlo = compiled_hlo(binary_reports, None, p)
        assert _check(hlo, "pipeline-gram-sharded") == []

    def test_na_power_path(self, binary_reports):
        """NaN interpolation's column stats are event-sharded reductions
        over the replicated R axis — no extra large collectives."""
        reports = binary_reports.copy()
        rng = np.random.default_rng(2)
        reports[rng.random((R, E)) < 0.05] = np.nan
        p = ConsensusParams(algorithm="sztorc", pca_method="power",
                            has_na=True, any_scaled=False, median_block=0)
        hlo = compiled_hlo(reports, None, p)
        assert _check(hlo, "pipeline-na-power-sharded") == []

    def test_budget_rejects_matrix_collective(self, binary_reports):
        """The shared checker actually rejects a seeded violation: the
        binary budget must flag a crafted matrix-sized all-gather (the
        infrastructure is only trustworthy if it can fail)."""
        fake = f"  %ag = f32[{R},{E}]{{1,0}} all-gather(f32[{R},256] %x)"
        violations = _check(fake, "pipeline-binary-power-sharded")
        assert any("all-gather" in v for v in violations)


class TestEffectiveMedianBlock:
    def test_predicate_is_event_axis_extent(self):
        """Blocking must turn off exactly when the EVENT axis is sharded:
        a pure-batch mesh (batch=8, event=1) replicates events, so the
        blocked median is both partitionable and the only sort-temporary
        bound on each device — forcing 0 there would reintroduce the
        full-width (R, E) sort allocations that OOM at scale."""
        from pyconsensus_tpu.parallel.mesh import effective_median_block

        assert effective_median_block(1024, None) == 1024
        assert effective_median_block(
            1024, make_mesh(batch=1, event=N_DEV)) == 0
        assert effective_median_block(
            1024, make_mesh(batch=N_DEV, event=1)) == 1024
        assert effective_median_block(
            0, make_mesh(batch=N_DEV, event=1)) == 0


class TestMedianBlockParity:
    def test_unblocked_matches_blocked_bitwise(self):
        """block_cols is a memory/partitioning knob, never a numerics knob:
        each column's median is self-contained, so blocked and unblocked
        results must be bitwise identical."""
        from pyconsensus_tpu.ops import jax_kernels as jk

        rng = np.random.default_rng(3)
        vals = rng.uniform(0, 1, size=(17, 2500))
        vals[rng.random(vals.shape) < 0.1] = np.nan
        present = ~np.isnan(vals)
        filled = np.where(present, vals, np.inf)
        w = rng.uniform(0, 1, size=17)
        blocked = jk.weighted_median_cols(
            jax.numpy.asarray(filled), jax.numpy.asarray(w),
            jax.numpy.asarray(present), block_cols=1024)
        direct = jk.weighted_median_cols(
            jax.numpy.asarray(filled), jax.numpy.asarray(w),
            jax.numpy.asarray(present), block_cols=0)
        np.testing.assert_array_equal(np.asarray(blocked),
                                      np.asarray(direct))


class TestNorthStarShapeCollectiveCosts:
    """VERDICT r2 items 3/7: the toy-shape bounds above caught the round-1
    all-gather bug only after the fact — these compile the REAL north-star
    shape (10k x 100k over 8 event shards, compile-only, inputs as
    ShapeDtypeStructs so no 4 GB matrix is ever materialized) and pin the
    same invariants where they actually matter. GSPMD's partitioning
    choices are shape-dependent; a sane toy compile does not imply a sane
    100k-column compile. The BUDGETS are the lint subsystem's declared
    ones — only the (R, E) environment differs."""

    R_NS, E_NS = 10_000, 100_000

    def _compile(self, params, n_scaled=0):
        from pyconsensus_tpu.parallel import resolve_params
        from pyconsensus_tpu.parallel.mesh import (event_sharding,
                                                   replicated)

        mesh = make_mesh(batch=1, event=N_DEV)
        e_sh = jax.sharding.NamedSharding(mesh,
                                          jax.sharding.PartitionSpec("event"))
        f32 = np.float32
        args = (
            jax.ShapeDtypeStruct((self.R_NS, self.E_NS), f32,
                                 sharding=event_sharding(mesh)),
            jax.ShapeDtypeStruct((self.R_NS,), f32, sharding=replicated(mesh)),
            jax.ShapeDtypeStruct((self.E_NS,), bool, sharding=e_sh),
            jax.ShapeDtypeStruct((self.E_NS,), f32, sharding=e_sh),
            jax.ShapeDtypeStruct((self.E_NS,), f32, sharding=e_sh),
        )
        p = resolve_params(
            params._replace(any_scaled=n_scaled > 0, n_scaled=n_scaled),
            self.R_NS, self.E_NS, mesh)
        assert not p.fused_resolution          # multi-device: XLA path
        assert p.median_block == 0             # event-sharded: unblocked
        return consensus_light_jit.lower(*args, p).compile().as_text()

    def _assert_bounded_ns(self, hlo):
        assert _check(hlo, "pipeline-binary-power-sharded",
                      R_=self.R_NS, E_=self.E_NS) == []

    @pytest.mark.slow
    def test_binary_northstar_compile(self):
        p = ConsensusParams(algorithm="sztorc", pca_method="power",
                            has_na=True, storage_dtype="bfloat16")
        self._assert_bounded_ns(self._compile(p))

    @pytest.mark.slow
    def test_scaled16k_northstar_compile(self):
        """The 16k-scaled 8-chip sharded-median compile (VERDICT r2 item
        3): each shard medians its local 12.5k columns along the
        replicated R axis — the sort adds ZERO collectives, at the shape
        where the single-chip ladder was over budget."""
        p = ConsensusParams(algorithm="sztorc", pca_method="power",
                            has_na=True, storage_dtype="bfloat16")
        hlo = self._compile(p, n_scaled=16_000)
        self._assert_bounded_ns(hlo)
        sizes = collective_sizes(hlo)
        binary = collective_sizes(self._compile(p))
        assert sorted(sizes.keys()) == sorted(binary.keys())
        assert len(sizes["all-reduce"]) == len(binary["all-reduce"])
