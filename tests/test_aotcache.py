"""Zero-cold-start AOT executable cache (ISSUE 10).

Covers the persist/adopt round trip (bitwise parity between adopted and
freshly-compiled executables on real traffic, across all three bucket
classes), the verify-before-adopt corruption matrix (torn file, bit
flip, stale runtime fingerprint, wrong-BucketKey collision — each
refused with a structured PYC302 naming the reason, deleted, and
transparently recompiled), the shared tune/AOT fingerprint helper, the
``aot.cache_write`` / ``aot.cache_load`` fault sites, the fleet
takeover warm-from-disk hook, and a REAL kill-and-restart subprocess
run asserting ``pyconsensus_jit_retraces_total{entry="serve_bucket"}
== 0`` and bitwise parity with the pre-kill resolution.
"""

import json
import os
import signal
import struct
import subprocess
import sys

import numpy as np
import pytest

from pyconsensus_tpu import obs
from pyconsensus_tpu.faults import (ERROR_CODES, AotCacheCorruptionError,
                                    CheckpointCorruptionError, FaultPlan,
                                    armed)
from pyconsensus_tpu.models.pipeline import ConsensusParams
from pyconsensus_tpu.serve import (AotCache, AotExecutable, BucketKey,
                                   ConsensusService, ExecutableCache,
                                   ServeConfig, warm_inputs)
from pyconsensus_tpu.serve import kernels as sk
from pyconsensus_tpu.serve.aotcache import (AOT_MAGIC, entry_filename,
                                            key_fingerprint)
from pyconsensus_tpu.tune import autotune
from pyconsensus_tpu.tune.fingerprint import (device_generation,
                                              runtime_fingerprint)


def bucket_params(**kw):
    base = dict(algorithm="sztorc", pca_method="power", has_na=True,
                any_scaled=False, n_scaled=0)
    base.update(kw)
    return ConsensusParams(**base)


def xla_key(rows=16, events=32, batch=2, **kw):
    return BucketKey.make(rows, events, batch, bucket_params(**kw))


def traffic_lanes(key, rng, R=10, E=20):
    """Real request arrays padded up to ``key`` and stacked to its
    batch capacity — what the batcher actually dispatches."""
    import jax.numpy as jnp

    m = rng.choice([0.0, 1.0, np.nan], size=(R, E), p=[.45, .45, .1])
    lane = sk.bucket_inputs(m, np.full(R, 1.0 / R), np.zeros(E, bool),
                            np.zeros(E), np.ones(E), key.rows, key.events,
                            has_na=True)
    if key.batch > 1:
        return [jnp.asarray(np.stack([f] * key.batch)) for f in lane]
    return [jnp.asarray(f) for f in lane]


def assert_bitwise(a, b):
    for k in b:
        np.testing.assert_array_equal(
            np.asarray(a[k]), np.asarray(b[k]),
            err_msg=f"AOT-loaded executable output {k!r} is not "
                    f"bit-identical to the freshly-compiled one")


class TestSharedFingerprint:
    """ISSUE 10 satellite: ONE definition of the version/generation
    fingerprint, shared by the tune winner cache and the AOT keys."""

    def test_tune_cache_pins_to_shared_helper(self):
        # the winner cache's generation component IS the shared helper
        # (not a drifted copy) — the CATCH_TIE_ATOL unification rule
        assert autotune.tpu_generation is device_generation
        from pyconsensus_tpu import tune

        assert tune.tpu_generation is device_generation
        key = autotune._entry_key("resolve_block_cols",
                                  autotune.tpu_generation(), 4, "p128")
        assert key.startswith(device_generation() + "/")

    def test_aot_key_pins_to_shared_helper(self):
        fp = key_fingerprint(xla_key())
        assert fp["runtime"] == runtime_fingerprint()
        assert fp["runtime"]["generation"] == device_generation()

    def test_runtime_fingerprint_fields(self):
        import jax
        import jaxlib

        fp = runtime_fingerprint()
        assert fp["jax"] == jax.__version__
        assert fp["jaxlib"] == jaxlib.__version__
        assert fp["platform"] == "cpu"
        assert fp["x64"] is True        # conftest enables x64
        assert fp["n_devices"] == jax.device_count()

    def test_every_bucketkey_dimension_keys_the_file(self):
        base = xla_key()
        variants = [xla_key(rows=32), xla_key(events=64),
                    BucketKey.make(16, 32, 4, bucket_params()),
                    xla_key(alpha=0.3),
                    BucketKey.make(16, 32, 2, bucket_params(),
                                   "cpu:2x4"),
                    BucketKey.make(16, 32, 1,
                                   bucket_params(fused_resolution=True),
                                   kernel_path="pallas")]
        names = {entry_filename(key_fingerprint(k))
                 for k in [base] + variants}
        assert len(names) == len(variants) + 1

    def test_error_taxonomy(self):
        assert ERROR_CODES["PYC302"] is AotCacheCorruptionError
        exc = AotCacheCorruptionError("x", reason="digest")
        assert isinstance(exc, CheckpointCorruptionError)
        assert isinstance(exc, ValueError)
        assert exc.context["reason"] == "digest"
        assert str(exc).startswith("[PYC302]")


class TestRoundTrip:
    def test_persist_adopt_bitwise_parity(self, tmp_path, rng):
        key = xla_key()
        c1 = ExecutableCache(8, aot=AotCache(tmp_path))
        c1.warm(key)
        files = list(tmp_path.glob("*.aotx"))
        assert len(files) == 1
        assert files[0].read_bytes().startswith(AOT_MAGIC)
        lanes = traffic_lanes(key, np.random.default_rng(11))
        fresh = c1.get(key)(*lanes, key.params)

        c2 = ExecutableCache(8, aot=AotCache(tmp_path))
        before = obs.value("pyconsensus_jit_retraces_total",
                           entry="serve_bucket") or 0
        c2.warm(key)
        adopted = c2.get(key)
        assert isinstance(adopted, AotExecutable)
        # zero retraces of the consensus pipeline: the adopted entry
        # never touches the instrumented serve_bucket jit
        after = obs.value("pyconsensus_jit_retraces_total",
                          entry="serve_bucket") or 0
        assert after == before
        # cache-built executables DONATE their padded vector inputs
        # (ISSUE 13) — rebuild identical lanes for the adopted call
        lanes = traffic_lanes(key, np.random.default_rng(11))
        assert_bitwise(adopted(*lanes, key.params), fresh)

    def test_runtime_miss_adopts_from_disk(self, tmp_path, rng):
        key = xla_key(rows=8, events=32, batch=1)
        ExecutableCache(8, aot=AotCache(tmp_path)).warm(key)
        c2 = ExecutableCache(8, aot=AotCache(tmp_path))
        # a cold GET (traffic hitting an unwarmed bucket) consults the
        # disk tier before compiling
        assert isinstance(c2.get(key), AotExecutable)

    def test_cold_warm_counts_one_disk_miss(self, tmp_path):
        # warm of an unpersisted bucket consults the disk exactly once
        # (adopt-or-build lives in get(); a double consult would make
        # every loaded/(loaded+miss) adoption-rate dashboard read low)
        before = obs.value("pyconsensus_aot_load_total",
                           outcome="miss") or 0
        ExecutableCache(8, aot=AotCache(tmp_path)).warm(
            xla_key(rows=8, events=32, batch=1))
        assert obs.value("pyconsensus_aot_load_total",
                         outcome="miss") == before + 1

    def test_persist_idempotent(self, tmp_path):
        key = xla_key(rows=8, events=32, batch=1)
        cache = ExecutableCache(8, aot=AotCache(tmp_path))
        cache.warm(key)
        written = obs.value("pyconsensus_aot_persist_total",
                            outcome="written")
        cache.warm(key)
        assert obs.value("pyconsensus_aot_persist_total",
                         outcome="written") == written
        assert obs.value("pyconsensus_aot_persist_total",
                         outcome="exists") >= 1
        assert len(list(tmp_path.glob("*.aotx"))) == 1

    def test_params_mismatch_refused_at_call(self, tmp_path):
        key = xla_key(rows=8, events=32, batch=1)
        cache = ExecutableCache(8, aot=AotCache(tmp_path))
        cache.warm(key)
        adopted = ExecutableCache(8, aot=AotCache(tmp_path)).get(key)
        assert isinstance(adopted, AotExecutable)
        args = warm_inputs(key)
        with pytest.raises(ValueError, match="persisted for params"):
            adopted(*args, bucket_params(alpha=0.7))

    def test_without_aot_dir_unchanged(self, tmp_path):
        key = xla_key(rows=8, events=32, batch=1)
        cache = ExecutableCache(8)
        cache.warm(key)
        assert cache.aot is None
        assert not list(tmp_path.glob("*.aotx"))


class TestCorruptionMatrix:
    """Every damaged or incompatible entry: refused with a structured
    PYC302 naming the reason, deleted, transparently recompiled —
    NEVER deserialized."""

    def _persisted(self, tmp_path, key=None):
        key = key or xla_key(rows=8, events=32, batch=1)
        ExecutableCache(8, aot=AotCache(tmp_path)).warm(key)
        (path,) = tmp_path.glob("*.aotx")
        return key, path

    def _assert_refused(self, tmp_path, key, path, reason):
        aot = AotCache(tmp_path)
        with pytest.raises(AotCacheCorruptionError) as ei:
            aot.verify(key)
        assert ei.value.context["reason"] == reason
        assert ei.value.error_code == "PYC302"
        # transparent arm: adopt refuses, DELETES, returns None...
        assert aot.adopt(key) is None
        assert not path.exists()
        assert obs.value("pyconsensus_aot_reject_total",
                         reason=reason) >= 1
        # ...and warm recompiles + re-persists a clean entry
        cache = ExecutableCache(8, aot=AotCache(tmp_path))
        cache.warm(key)
        assert not isinstance(cache.get(key), AotExecutable)
        assert AotCache(tmp_path).verify(key) is not None

    def test_truncated_file(self, tmp_path):
        key, path = self._persisted(tmp_path)
        data = path.read_bytes()
        path.write_bytes(data[:int(len(data) * 0.6)])
        self._assert_refused(tmp_path, key, path, "torn")

    def test_bit_flipped_payload(self, tmp_path):
        key, path = self._persisted(tmp_path)
        data = bytearray(path.read_bytes())
        data[-50] ^= 0x01                      # one flipped payload bit
        path.write_bytes(bytes(data))
        self._assert_refused(tmp_path, key, path, "digest")

    def test_stale_runtime_fingerprint(self, tmp_path):
        # rewrite the header claiming a different jaxlib — what a cache
        # dir surviving a toolchain upgrade looks like. The digest is
        # kept CONSISTENT with the payload so only the fingerprint can
        # refuse (the check under test).
        key, path = self._persisted(tmp_path)
        data = path.read_bytes()
        (hdr_len,) = struct.unpack_from(">Q", data, len(AOT_MAGIC))
        body = len(AOT_MAGIC) + 8
        header = json.loads(data[body:body + hdr_len])
        header["fingerprint"]["runtime"]["jaxlib"] = "0.0.1-stale"
        hdr = json.dumps(header, sort_keys=True).encode()
        path.write_bytes(AOT_MAGIC + struct.pack(">Q", len(hdr)) + hdr
                         + data[body + hdr_len:])
        aot = AotCache(tmp_path)
        with pytest.raises(AotCacheCorruptionError) as ei:
            aot.verify(key)
        assert ei.value.context["reason"] == "fingerprint"
        assert "runtime" in ei.value.context["fields"]
        self._assert_refused(tmp_path, key, path, "fingerprint")

    def test_wrong_bucketkey_collision(self, tmp_path):
        # a valid entry for key A renamed under key B's file name (copy
        # mistake, digest collision fantasy): the header fingerprint is
        # verified on load, so it can never be adopted as B
        key_a, path_a = self._persisted(tmp_path)
        key_b = xla_key(rows=16, events=32, batch=1)
        aot = AotCache(tmp_path)
        path_b = aot.entry_path(key_b)
        path_b.write_bytes(path_a.read_bytes())
        with pytest.raises(AotCacheCorruptionError) as ei:
            aot.verify(key_b)
        assert ei.value.context["reason"] == "fingerprint"
        assert "rows" in ei.value.context["fields"]
        assert aot.adopt(key_b) is None
        assert not path_b.exists()
        # key A's own entry is untouched and still adopts
        assert isinstance(aot.adopt(key_a), AotExecutable)

    def test_foreign_magic(self, tmp_path):
        key, path = self._persisted(tmp_path)
        path.write_bytes(b"not an aot entry at all" + b"\0" * 64)
        self._assert_refused(tmp_path, key, path, "magic")

    def test_garbage_header(self, tmp_path):
        key, path = self._persisted(tmp_path)
        hdr = b"{definitely not json"
        path.write_bytes(AOT_MAGIC + struct.pack(">Q", len(hdr)) + hdr)
        self._assert_refused(tmp_path, key, path, "header")

    def test_non_dict_fingerprint_refused_not_crashed(self, tmp_path):
        # valid JSON header whose fingerprint is a STRING: must take the
        # structured fingerprint refusal, never an AttributeError escape
        key, path = self._persisted(tmp_path)
        hdr = json.dumps({"format": 1, "fingerprint": "xyz",
                          "payload_bytes": 0,
                          "payload_sha256": ""}).encode()
        path.write_bytes(AOT_MAGIC + struct.pack(">Q", len(hdr)) + hdr)
        self._assert_refused(tmp_path, key, path, "fingerprint")


class TestFaultSites:
    def test_cache_write_raise_is_failsoft(self, tmp_path, capsys):
        key = xla_key(rows=8, events=32, batch=1)
        cache = ExecutableCache(8, aot=AotCache(tmp_path))
        plan = FaultPlan(seed=1, rules=[
            {"site": "aot.cache_write", "kind": "raise"}])
        with armed(plan):
            cache.warm(key)                    # serving must not break
        assert plan.fired == [("aot.cache_write", 0, "raise")]
        assert obs.value("pyconsensus_aot_persist_total",
                         outcome="failed") >= 1
        assert "AOT persist" in capsys.readouterr().err

    def test_cache_write_torn_then_refused_on_load(self, tmp_path):
        key = xla_key(rows=8, events=32, batch=1)
        cache = ExecutableCache(8, aot=AotCache(tmp_path))
        plan = FaultPlan(seed=2, rules=[
            {"site": "aot.cache_write", "kind": "torn_write"}])
        with armed(plan):
            cache.warm(key)
        assert plan.fired == [("aot.cache_write", 0, "torn_write")]
        (path,) = tmp_path.glob("*.aotx")
        aot = AotCache(tmp_path)
        with pytest.raises(AotCacheCorruptionError):
            aot.verify(key)
        assert aot.adopt(key) is None          # refused + deleted
        assert not path.exists()

    def test_cache_load_error_degrades_without_delete(self, tmp_path,
                                                      capsys):
        key = xla_key(rows=8, events=32, batch=1)
        ExecutableCache(8, aot=AotCache(tmp_path)).warm(key)
        (path,) = tmp_path.glob("*.aotx")
        plan = FaultPlan(seed=3, rules=[
            {"site": "aot.cache_load", "kind": "raise"}])
        aot = AotCache(tmp_path)
        with armed(plan):
            assert aot.adopt(key) is None      # recompile this boot...
        assert path.exists()                   # ...but keep the file
        assert "unreadable" in capsys.readouterr().err
        assert isinstance(aot.adopt(key), AotExecutable)  # next boot ok


class TestServiceIntegration:
    CFG = dict(warmup=((16, 64),), sharded_buckets=False,
               pallas_buckets=False, batch_window_ms=1.0, max_batch=2)

    def _request(self, rng):
        return rng.choice([0.0, 1.0, np.nan], size=(12, 48),
                          p=[.45, .45, .1])

    def test_restart_parity_and_zero_retraces(self, tmp_path, rng):
        cfg = ServeConfig(aot_cache_dir=str(tmp_path), **self.CFG)
        m = self._request(rng)
        with ConsensusService(cfg) as svc:
            r1 = svc.submit(reports=m).result(120)
        assert len(list(tmp_path.glob("*.aotx"))) == 1

        before = obs.value("pyconsensus_jit_retraces_total",
                           entry="serve_bucket") or 0
        svc2 = ConsensusService(cfg)
        assert svc2.warm_buckets() == 1
        after = obs.value("pyconsensus_jit_retraces_total",
                          entry="serve_bucket") or 0
        assert after == before, "adopting from disk must not retrace"
        with svc2:
            r2 = svc2.submit(reports=m).result(120)
        for section in ("events", "agents"):
            for k, v in r1[section].items():
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(r2[section][k]),
                    err_msg=f"{section}.{k}")
        assert r1["iterations"] == r2["iterations"]

    def test_sharded_bucket_roundtrip(self, tmp_path, rng):
        cfg = ServeConfig(warmup=((16, 128),), sharded_buckets=True,
                          pallas_buckets=False, max_batch=8,
                          aot_cache_dir=str(tmp_path))
        svc = ConsensusService(cfg)
        svc.warm_buckets()
        (key,) = svc.cache.keys()
        assert key.topology != "single"
        lanes = traffic_lanes(key, np.random.default_rng(12), R=12, E=100)
        fresh = svc.cache.get(key)(*lanes, key.params)

        svc2 = ConsensusService(cfg)
        before = obs.value("pyconsensus_jit_retraces_total",
                           entry="serve_bucket_sharded") or 0
        svc2.warm_buckets()
        adopted = svc2.cache.get(key)
        assert isinstance(adopted, AotExecutable)
        assert (obs.value("pyconsensus_jit_retraces_total",
                          entry="serve_bucket_sharded") or 0) == before
        # donated inputs (ISSUE 13): rebuild identical lanes
        lanes = traffic_lanes(key, np.random.default_rng(12), R=12, E=100)
        assert_bitwise(adopted(*lanes, key.params), fresh)

    def test_pallas_bucket_roundtrip(self, tmp_path, rng):
        import jax.numpy as jnp

        cfg = ServeConfig(warmup=(), pallas_warmup=((12, 48),),
                          sharded_buckets=False, pallas_buckets=True,
                          aot_cache_dir=str(tmp_path))
        svc = ConsensusService(cfg)
        svc.warm_buckets()
        (key,) = svc.cache.keys()
        assert key.kernel_path == "pallas"
        acc = jnp.asarray(0.0).dtype
        m = self._request(rng)
        args = (jnp.asarray(m, acc),
                jnp.asarray(np.full(12, 1 / 12), acc),
                jnp.zeros(48, bool), jnp.zeros(48, acc),
                jnp.ones(48, acc))
        fresh = svc.cache.get(key)(*args, key.params)

        svc2 = ConsensusService(cfg)
        before = obs.value("pyconsensus_jit_retraces_total",
                           entry="serve_bucket_pallas") or 0
        svc2.warm_buckets()
        adopted = svc2.cache.get(key)
        assert isinstance(adopted, AotExecutable)
        assert (obs.value("pyconsensus_jit_retraces_total",
                          entry="serve_bucket_pallas") or 0) == before
        assert_bitwise(adopted(*args, key.params), fresh)

    def test_warm_from_disk_skips_unpersisted(self, tmp_path):
        cfg = ServeConfig(warmup=((8, 32), (16, 64)),
                          sharded_buckets=False, pallas_buckets=False,
                          aot_cache_dir=str(tmp_path))
        svc = ConsensusService(cfg)
        # persist only the FIRST bucket
        svc.cache.warm(svc.configured_keys()[0])
        svc2 = ConsensusService(cfg)
        assert svc2.warm_from_disk() == 1      # adopted, not compiled
        assert len(svc2.cache) == 1
        assert isinstance(
            svc2.cache.get(svc2.configured_keys()[0]), AotExecutable)

    def test_config_roundtrip(self, tmp_path):
        cfg = ServeConfig.from_dict({"aot_cache_dir": str(tmp_path),
                                     "warmup": [[8, 32]]})
        assert cfg.aot_cache_dir == str(tmp_path)
        assert ConsensusService(cfg).cache.aot is not None
        assert ConsensusService(ServeConfig()).cache.aot is None


class TestFleetTakeoverWarm:
    def test_standby_warms_from_disk_in_takeover(self, tmp_path):
        from pyconsensus_tpu.serve import ConsensusFleet, FleetConfig

        aot_dir = tmp_path / "aot"
        cfg = ServeConfig(warmup=((8, 32),), sharded_buckets=False,
                          pallas_buckets=False,
                          aot_cache_dir=str(aot_dir))
        # an earlier fleet member (or boot) persisted the bucket set
        ConsensusService(cfg).warm_buckets()
        assert len(list(aot_dir.glob("*.aotx"))) == 1

        fleet = ConsensusFleet(FleetConfig(
            n_workers=2, worker=cfg, log_dir=str(tmp_path / "log")))
        fleet.start(warmup=False)              # nobody compiles at boot
        try:
            owner = fleet.create_session("mkt", n_reporters=6)
            block = np.tile([1.0, 0.0, 1.0, 0.0], (6, 2))
            fleet.append("mkt", block[:, :8])
            standby = next(n for n in fleet.workers if n != owner)
            assert len(fleet.workers[standby].service.cache) == 0
            before = obs.value("pyconsensus_aot_takeover_warms_total") \
                or 0
            fleet.kill_worker(owner)
            # the standby adopted the persisted executable inside the
            # takeover window — zero compiles, zero pipeline retraces
            w = fleet.workers[standby].service
            assert len(w.cache) == 1
            assert isinstance(w.cache.get(w.configured_keys()[0]),
                              AotExecutable)
            assert obs.value("pyconsensus_aot_takeover_warms_total") \
                == before + 1
            assert fleet.owner_of("mkt") == standby
        finally:
            fleet.close(drain=True)


#: phase scripts of the real kill-and-restart run. Phase 1 warms +
#: persists + serves + SIGKILLs itself (the dump happens before the
#: kill); phase 2 is the restarted process: adopt from disk, assert the
#: zero-retrace contract, serve the same request.
_PHASE1 = r"""
import os, signal, sys
import numpy as np
from pyconsensus_tpu.serve import ConsensusService, ServeConfig

out, aot = sys.argv[1], sys.argv[2]
cfg = ServeConfig(warmup=((16, 64),), sharded_buckets=False,
                  pallas_buckets=False, aot_cache_dir=aot)
svc = ConsensusService(cfg)
svc.warm_buckets()
svc.start(warmup=False)
rng = np.random.default_rng(3)
m = rng.choice([0.0, 1.0, np.nan], size=(12, 48), p=[.45, .45, .1])
r = svc.submit(reports=m).result(300)
np.savez(out, outcomes=np.asarray(r["events"]["outcomes_final"]),
         smooth=np.asarray(r["agents"]["smooth_rep"]),
         iters=np.asarray(r["iterations"]))
os.kill(os.getpid(), signal.SIGKILL)
"""

_PHASE2 = r"""
import sys
import numpy as np
from pyconsensus_tpu import obs
from pyconsensus_tpu.serve import ConsensusService, ServeConfig

out, aot = sys.argv[1], sys.argv[2]
cfg = ServeConfig(warmup=((16, 64),), sharded_buckets=False,
                  pallas_buckets=False, aot_cache_dir=aot)
svc = ConsensusService(cfg)
svc.warm_buckets()
retr = obs.value("pyconsensus_jit_retraces_total",
                 entry="serve_bucket") or 0
assert retr == 0, f"restart retraced the pipeline {retr} time(s)"
assert obs.value("pyconsensus_aot_load_total", outcome="loaded") == 1
svc.start(warmup=False)
rng = np.random.default_rng(3)
m = rng.choice([0.0, 1.0, np.nan], size=(12, 48), p=[.45, .45, .1])
r = svc.submit(reports=m).result(300)
svc.close(drain=True)
np.savez(out, outcomes=np.asarray(r["events"]["outcomes_final"]),
         smooth=np.asarray(r["agents"]["smooth_rep"]),
         iters=np.asarray(r["iterations"]))
print("RESTART_OK")
"""


class TestKillAndRestart:
    def test_sigkill_restart_zero_retraces_bitwise(self, tmp_path):
        """The acceptance criterion, end to end with a REAL SIGKILL: a
        process warms + persists + serves, dies by kill -9, and its
        restart serves the first request with zero pipeline retraces
        and bits identical to the pre-kill run."""
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                    "JAX_ENABLE_X64": "1"})
        aot = str(tmp_path / "aot")
        out1, out2 = str(tmp_path / "pre.npz"), str(tmp_path / "post.npz")
        p1 = subprocess.run(
            [sys.executable, "-c", _PHASE1, out1, aot],
            capture_output=True, text=True, timeout=600, env=env)
        assert p1.returncode == -signal.SIGKILL, p1.stderr[-2000:]
        assert os.path.exists(out1), "phase 1 died before serving"
        import pathlib

        assert list(pathlib.Path(aot).glob("*.aotx")), \
            "phase 1 persisted nothing"
        p2 = subprocess.run(
            [sys.executable, "-c", _PHASE2, out2, aot],
            capture_output=True, text=True, timeout=600, env=env)
        assert p2.returncode == 0, p2.stderr[-2000:]
        assert "RESTART_OK" in p2.stdout
        pre, post = np.load(out1), np.load(out2)
        for k in ("outcomes", "smooth", "iters"):
            np.testing.assert_array_equal(
                pre[k], post[k],
                err_msg=f"restart changed {k} — the AOT executable is "
                        f"not bit-identical to the pre-kill compile")
