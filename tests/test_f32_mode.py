"""f32-precision smoke of the jax backend — the precision the REAL TPU
runs at. The whole CPU suite is pinned to f64 (conftest.py enables x64 so
numpy parity is tight), which left the chip's actual numeric mode with
zero coverage: an f32-only failure (dtype-promotion error, a
precision-sensitive tie-break, an out-of-range cast) would first surface
on scarce chip time. This test resolves the golden fixtures in a fresh
x64-OFF process and checks the catch-snap contract: snapped binary
outcomes must be IDENTICAL to the f64 results (the snap absorbs float
noise — the north star's own argument), reputation close at f32
tolerance."""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from pyconsensus_tpu import ALGORITHMS, Oracle

_WORKER = pathlib.Path(__file__).resolve().parent / "f32_worker.py"


@pytest.fixture(scope="module")
def f32_results():
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})
    env.pop("JAX_ENABLE_X64", None)
    r = subprocess.run([sys.executable, str(_WORKER)], capture_output=True,
                       text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-3000:]
    for line in r.stdout.splitlines():
        if line.startswith("F32RESULTS "):
            return json.loads(line.split(" ", 1)[1])
    raise AssertionError(f"no results line:\n{r.stdout}")


@pytest.mark.slow
@pytest.mark.parametrize("algo", sorted(ALGORITHMS))
def test_canonical_outcomes_match_f64(f32_results, algo):
    got = f32_results[f"canonical/{algo}"]
    ref = Oracle(reports=np.array([
        [1.0, 1.0, 0.0, 0.0], [1.0, 0.0, 0.0, 0.0], [1.0, 1.0, 0.0, 0.0],
        [1.0, 1.0, 1.0, 0.0], [0.0, 0.0, 1.0, 1.0], [0.0, 0.0, 1.0, 1.0],
    ]), backend="jax", algorithm=algo, max_iterations=2).consensus()
    np.testing.assert_array_equal(
        got["outcomes"], np.asarray(ref["events"]["outcomes_final"],
                                    dtype=float))
    if algo == "fixed-variance":
        # documented f32 caveat (models/sztorc.py): minor-component
        # orientation is float-noise-decided; reporters on opposite sides
        # of a near-degenerate component can swap reputations in f32 while
        # snapped outcomes stay identical. Assert the multiset instead.
        np.testing.assert_allclose(
            sorted(got["smooth_rep"]),
            sorted(np.asarray(ref["agents"]["smooth_rep"], dtype=float)),
            atol=2e-3)
    else:
        np.testing.assert_allclose(got["smooth_rep"],
                                   np.asarray(ref["agents"]["smooth_rep"],
                                              dtype=float), atol=2e-3)


@pytest.mark.slow
def test_missing_scaled_and_power_paths(f32_results):
    # iterative + NaN resolution converges to the same snapped outcomes
    assert f32_results["missing/sztorc"]["outcomes"] == [1.0, 1.0, 0.0, 0.0]
    # scaled outcomes carry f32 resolution; binary part exact
    sc = f32_results["scaled/sztorc"]["outcomes"]
    assert sc[:3] == [1.0, 0.5, 0.0]
    assert abs(sc[3] - 233.0) < 0.01
    assert abs(sc[4] - 16027.59) < 1.0
    # the exact gram path reproduces the f64 iterative trajectory in f32
    assert (f32_results["canonical-iter5/eigh-gram"]["outcomes"]
            == [1.0, 1.0, 0.0, 0.0])
    # documented f32 caveat (models/sztorc.py): the iterative POWER path's
    # O(sqrt(E)*eps_f32) per-sweep loading error, amplified by reputation
    # feedback, may leave a knife-edge 3-vs-3 event at the ambiguous 0.5 —
    # but must NEVER resolve any event to the opposite of the f64 answer
    f64_golden = [1.0, 1.0, 0.0, 0.0]
    power = f32_results["canonical-iter5/power"]["outcomes"]
    for got, want in zip(power, f64_golden):
        assert got in (want, 0.5), (power, f64_golden)
