"""CATCH_TIE_ATOL boundary-band parity across all three kernel families
(ISSUE 7 satellite): an exact-boundary weighted mean — landing ON
``0.5 ± tolerance`` — must snap to the ambiguous 0.5 identically through
the numpy reference (``numpy_kernels.catch``), the XLA kernels
(``jax_kernels.catch`` / ``resolve_outcomes``), and the Pallas fused
resolution kernel (``resolve_certainty_fused``, interpret mode on CPU),
for every storage encoding. The parity-ledger #1-7 root cause was
exactly this class: knife-edge fills snapping oppositely across XLA
reduce tilings; the band (now ONE definition —
``jax_kernels.catch_tie_atol``, threaded into the Pallas kernel) is the
fix, and this corpus pins it on the revived Pallas path.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from pyconsensus_tpu.ops import jax_kernels as jk
from pyconsensus_tpu.ops import numpy_kernels as nk
from pyconsensus_tpu.ops.pallas_kernels import resolve_certainty_fused

TOL = 0.1

#: per-column vote stacks engineering the present-weighted mean (uniform
#: reputation) to exact boundary / near-boundary values: (votes, mean)
_COLUMNS = [
    ([1, 1, 1, 0, 0], 0.6),       # exactly 0.5 + tol  -> band -> 0.5
    ([1, 1, 0, 0, 0], 0.4),       # exactly 0.5 - tol  -> band -> 0.5
    ([1, 1, 1, 1, 0], 0.8),       # clearly above      -> 1.0
    ([1, 0, 0, 0, 0], 0.2),       # clearly below      -> 0.0
    ([1, 1, 1, 0, 1], 0.8),       # above              -> 1.0
]

_EXPECTED = np.array([0.5, 0.5, 1.0, 0.0, 1.0])


def _matrix():
    """(5, 5) all-present vote matrix whose column means are _COLUMNS'."""
    return np.array([[c[0][r] for c in _COLUMNS]
                     for r in range(5)], dtype=np.float64)


def _encode(reports, dtype):
    if dtype == "int8":
        return jnp.asarray(
            np.where(np.isnan(reports), -1,
                     np.round(2 * reports)).astype(np.int8))
    return jnp.asarray(reports, dtype=dtype)


def test_catch_band_shared_definition():
    """The three families share ONE band definition: numpy's constant,
    jax's dtype-floored variant, and the value the Pallas kernel is
    built with (jax_kernels.catch_tie_atol — the unification this PR
    pins)."""
    assert jk.catch_tie_atol(jnp.float64) == nk.CATCH_TIE_ATOL
    f32_band = jk.catch_tie_atol(jnp.float32)
    assert f32_band == max(nk.CATCH_TIE_ATOL,
                           32.0 * float(jnp.finfo(jnp.float32).eps))
    assert f32_band > nk.CATCH_TIE_ATOL      # the f32 floor engages


@pytest.mark.parametrize("mean,expected", [
    (0.6, 0.5), (0.4, 0.5), (0.8, 1.0), (0.2, 0.0),
    # one ulp inside the f32 band still snaps to 0.5 on every family
    (0.6 - 1e-8, 0.5), (0.4 + 1e-8, 0.5),
    # outside the band resolves to the side
    (0.6 + 1e-3, 1.0), (0.4 - 1e-3, 0.0),
])
def test_catch_numpy_vs_jax_scalar(mean, expected):
    got_np = float(nk.catch(np.asarray([mean]), TOL)[0])
    got_jax = float(np.asarray(
        jk.catch(jnp.asarray([mean], jnp.float32), TOL))[0])
    assert got_np == got_jax == expected


@pytest.mark.parametrize("dtype", ["int8", "bfloat16", "float32"])
def test_resolve_kernel_snaps_boundary_identically(dtype):
    """The Pallas fused resolution kernel's catch snap on exact-boundary
    column means must match the numpy and XLA families bit-identically
    (interpret mode on CPU — the kernel arithmetic, not Mosaic, decides
    the snap)."""
    reports = _matrix()
    R, E = reports.shape
    rep = jnp.full((R,), 1.0 / R, jnp.float32)
    x = _encode(reports, dtype)
    fill = jnp.full((E,), 0.5, jnp.float32)   # no NaN: fill never used
    raw, adjusted, *_ = resolve_certainty_fused(
        x, rep, fill, jnp.sum(rep), TOL, interpret=True)
    np.testing.assert_array_equal(np.asarray(adjusted, np.float64),
                                  _EXPECTED)
    # the numpy family on the EXACT f64 means, and the jax family on
    # the kernel's own f32 means (each family snaps at ITS dtype's
    # floored band — that is the unification's whole point: the f32
    # kernel mean lands ~1e-7 off the knife edge and the f32-floored
    # band absorbs it, while the exact f64 mean sits inside the 1e-9
    # reference band)
    exact_means = np.array([m for _, m in _COLUMNS])
    np.testing.assert_array_equal(nk.catch(exact_means, TOL), _EXPECTED)
    np.testing.assert_array_equal(
        np.asarray(jk.catch(jnp.asarray(raw, jnp.float32), TOL),
                   np.float64), _EXPECTED)


@pytest.mark.parametrize("dtype", ["int8", "bfloat16", "float32"])
def test_boundary_fill_snaps_identically_with_na(dtype):
    """Exact-boundary FILLS: a column whose present-weighted mean sits
    on the boundary fills its NaN with the banded 0.5 on every family —
    the parity-ledger #1-7 scenario, replayed through the Pallas
    NaN-threaded storage (absent entries in-storage, fill vector from
    the interpolate semantics)."""
    reports = _matrix()
    reports = np.vstack([reports, np.full((1, reports.shape[1]),
                                          np.nan)])   # one NaN row
    R, E = reports.shape
    rep_np = np.full(R, 1.0 / R)
    # the interpolate fill (numpy reference): present-weighted means of
    # _COLUMNS — exactly the boundary values — then catch-snapped
    filled = nk.interpolate(reports, rep_np, np.zeros(E, bool), TOL)
    np.testing.assert_array_equal(filled[-1], _EXPECTED)
    # jax family
    filled_j, _ = jk.interpolate_masked(
        jnp.asarray(reports, jnp.float32),
        jnp.asarray(rep_np, jnp.float32), jnp.zeros(E, bool), TOL)
    np.testing.assert_array_equal(np.asarray(filled_j)[-1], _EXPECTED)
    # Pallas family: the resolve kernel consumes the fill vector and the
    # sentinel storage; its adjusted outcomes must agree with the
    # reference resolution of the FILLED matrix
    x = _encode(reports, dtype)
    rep = jnp.asarray(rep_np, jnp.float32)
    fill = jnp.asarray(filled[-1], jnp.float32)
    _, adjusted, *_ = resolve_certainty_fused(
        x, rep, fill, jnp.sum(rep), TOL, interpret=True)
    # present-weighted means are _COLUMNS' boundary values (the NaN row
    # carries no present weight) — the kernel must land the same snaps
    np.testing.assert_array_equal(np.asarray(adjusted, np.float64),
                                  _EXPECTED)


def test_full_pipeline_boundary_outcomes_numpy_vs_fused(rng):
    """Pipeline-level: a matrix carrying boundary-mean columns resolved
    through the numpy reference backend and through the fused Pallas
    pipeline (``_consensus_core_fused``, interpret mode on CPU — the
    graph the TPU fused gate and the serve ``bucket_pallas`` tier run)
    produces identical catch-snapped outcomes and iteration counts —
    the ISSUE 7 acceptance contract at the pipeline surface."""
    from pyconsensus_tpu import Oracle
    from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                                 _consensus_core_fused)

    reports = np.vstack([_matrix()] * 3)     # enough rows to score
    reports[rng.random(reports.shape) < 0.1] = np.nan
    R, E = reports.shape
    p = ConsensusParams(algorithm="sztorc", pca_method="power",
                        power_tol=0.0, catch_tolerance=TOL,
                        max_iterations=3, has_na=True, any_scaled=False,
                        n_scaled=0, fused_resolution=True)
    acc = jnp.asarray(0.0).dtype
    fused = _consensus_core_fused(
        jnp.asarray(reports, acc), jnp.full((R,), 1.0 / R, acc),
        jnp.zeros((E,), bool), jnp.zeros((E,), acc),
        jnp.ones((E,), acc), p)
    res_np = Oracle(reports=reports, backend="numpy",
                    catch_tolerance=TOL, max_iterations=3).consensus()
    np.testing.assert_array_equal(
        np.asarray(fused["outcomes_adjusted"], np.float64),
        np.asarray(res_np["events"]["outcomes_adjusted"]))
    assert int(np.asarray(fused["iterations"])) == res_np["iterations"]
