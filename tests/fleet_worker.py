"""Fleet chaos worker: one serve worker's session traffic as a REAL OS
process (ISSUE 8 chaos stage). Drives a durable market session —
deterministic per-(round, block) event blocks, two appends then a
resolve per round — against a shared replication log, printing progress
markers. The parent test (or tools/ci_rehearsal.sh) SIGKILLs this
process mid-traffic and a standby adopts the session by
``replay_session``: because every append is journaled before it is
acknowledged and every resolve commits the ledger before clearing its
journal, the standby resumes bit-identical no matter which instruction
the kill landed on.

Usage: fleet_worker.py LOG_ROOT SESSION N_ROUNDS [SLEEP_S] [REFRESH_K]

``REFRESH_K`` (optional, > 0) makes the session INCREMENTAL with that
exact-refresh cadence (ISSUE 12): warm marginal resolves between
anchors, the warm eigenstate committed with every round — so the
mid-round SIGKILL replay contract covers the ``bucket_incremental``
tier's warm trajectory too.

Restart-safe by design: if the session's log already exists the worker
replays it and continues from the durable position — the same recovery
discipline the standby uses.
"""

import sys
import time

import numpy as np

N_REPORTERS = 12
BLOCK_EVENTS = 5
BLOCKS_PER_ROUND = 2


def make_block(round_idx: int, block_idx: int) -> np.ndarray:
    """Deterministic event block for (round, block) — the parent
    regenerates the identical traffic to continue after the kill and to
    build the uninterrupted reference run."""
    rng = np.random.default_rng([7, round_idx, block_idx])
    block = rng.choice([0.0, 1.0], size=(N_REPORTERS, BLOCK_EVENTS))
    block[rng.random(block.shape) < 0.1] = np.nan
    return block


def main(argv) -> int:
    from pyconsensus_tpu.serve.failover import (DurableSession,
                                                ReplicationLog,
                                                replay_session)

    log_root, name = argv[1], argv[2]
    n_rounds = int(argv[3])
    sleep_s = float(argv[4]) if len(argv) > 4 else 0.15
    refresh_k = int(argv[5]) if len(argv) > 5 else 0

    if ReplicationLog(log_root, name).exists():
        # the incremental policy (and warm eigenstate) replay from the
        # log's meta + ledger aux — no flag needed on resume
        session = replay_session(log_root, name)
    elif refresh_k > 0:
        session = DurableSession.create(log_root, name, N_REPORTERS,
                                        incremental=True,
                                        refresh_every=refresh_k)
    else:
        session = DurableSession.create(log_root, name, N_REPORTERS)
    print(f"READY round={session.ledger.round} "
          f"staged={len(session._blocks)}", flush=True)
    for k in range(session.ledger.round, n_rounds):
        for j in range(len(session._blocks), BLOCKS_PER_ROUND):
            session.append(make_block(k, j))
            print(f"APPEND {k} {j}", flush=True)
            time.sleep(sleep_s)
        session.resolve()
        print(f"ROUND {k}", flush=True)
        time.sleep(sleep_s)
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
