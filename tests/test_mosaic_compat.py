"""Mosaic lowering compatibility guards.

BENCH_r02.json's TPU run died at compile time: ``arith.cmpf`` on
``vector<8x128x2xbf16>`` — "Target does not support this comparison".
Mosaic (the Pallas TPU compiler) rejects bf16 float comparisons outright;
the offender was ``_decode_filled_bf16``'s int8 sentinel test running in
bf16. CPU tests can't catch that (the interpreter happily compares bf16),
so this test enforces the invariant at the jaxpr level: **no comparison
primitive inside any Pallas kernel may take bf16 operands** — decode must
upcast to f32 before any compare.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pyconsensus_tpu.ops.pallas_kernels import (apply_weighted_cov,
                                                resolve_certainty_fused,
                                                scores_dirfix_pass)

#: comparison primitives (isnan lowers to ne; sign tests to lt/gt)
_CMP_PRIMS = {"lt", "le", "gt", "ge", "eq", "ne"}


def _iter_eqns(jaxpr):
    """Yield every eqn in ``jaxpr`` and, recursively, in any sub-jaxpr
    carried in eqn params (pallas_call kernels, scan/cond/while bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    core = jax.extend.core if hasattr(jax.extend, "core") else jax.core
    if isinstance(v, core.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, core.Jaxpr):
        yield v
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _sub_jaxprs(item)


#: operand dtypes whose comparisons Mosaic rejects ("Target does not
#: support this comparison"): bf16 cmpf (BENCH_r02's crash) and — probed
#: on v5e in round 4 — i8 cmpi as well; i32 cmpi and f32 cmpf are the
#: legal forms
_ILLEGAL_CMP_DTYPES = (jnp.bfloat16, jnp.int8)


def _assert_no_bf16_compare(closed_jaxpr, ctx):
    bad = []
    for eqn in _iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in _CMP_PRIMS:
            for invar in eqn.invars:
                aval = getattr(invar, "aval", None)
                dt = getattr(aval, "dtype", None)
                if dt is not None and any(dt == d
                                          for d in _ILLEGAL_CMP_DTYPES):
                    bad.append(f"{eqn.primitive.name} on {aval} in {ctx}")
    assert not bad, ("Mosaic rejects bf16 arith.cmpf and i8 cmpi; found "
                     "illegal comparisons:\n" + "\n".join(bad))


_R, _E = 16, 256


def _storage(dtype):
    rng = np.random.default_rng(0)
    vals = rng.choice([0.0, 0.5, 1.0, np.nan], size=(_R, _E))
    if dtype == "int8":
        enc = np.where(np.isnan(vals), -1, np.round(2 * vals)).astype(np.int8)
        return jnp.asarray(enc)
    return jnp.asarray(vals, dtype=dtype)   # NaN entries mark absence


@pytest.mark.parametrize("dtype", ["int8", "bfloat16", "float32"])
def test_no_bf16_compare_in_cov_kernel(dtype):
    x = _storage(dtype)
    mu = jnp.zeros((_E,), jnp.float32)
    rep = jnp.full((_R,), 1.0 / _R, jnp.float32)
    v = jnp.ones((_E,), jnp.float32)
    fill = jnp.full((_E,), 0.5, jnp.float32)
    fn = functools.partial(apply_weighted_cov, interpret=True)
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a[:4], fill=a[4]))(
        x, mu, rep, v, fill)
    _assert_no_bf16_compare(jaxpr, f"apply_weighted_cov[{dtype}]")


@pytest.mark.parametrize("dtype", ["int8", "bfloat16", "float32"])
def test_no_bf16_compare_in_dirfix_kernel(dtype):
    x = _storage(dtype)
    rep = jnp.full((_R,), 1.0 / _R, jnp.float32)
    loading = jnp.ones((_E,), jnp.float32)
    fill = jnp.full((_E,), 0.5, jnp.float32)
    fn = functools.partial(scores_dirfix_pass, interpret=True)
    jaxpr = jax.make_jaxpr(lambda *a: fn(a[0], a[1], a[2], fill=a[3]))(
        x, rep, loading, fill)
    _assert_no_bf16_compare(jaxpr, f"scores_dirfix_pass[{dtype}]")


@pytest.mark.parametrize("dtype", ["int8", "bfloat16", "float32"])
def test_no_illegal_compare_in_storage_kernels(dtype):
    """The separable storage kernels (mesh + multi-component paths) carry
    the same comparison-legality invariant — including the i8 cmpi class
    that first hit real hardware in round 4 (interpret-mode tests cannot
    see Mosaic rejections, so the jaxpr guard is the regression pin)."""
    from pyconsensus_tpu.ops.pallas_kernels import (storage_matmat,
                                                    storage_matvec,
                                                    storage_rows_matmat)

    x = _storage(dtype)
    fill = jnp.full((_E,), 0.5, jnp.float32)
    v = jnp.ones((_E,), jnp.float32)
    V = jnp.ones((_E, 3), jnp.float32)
    W = jnp.ones((4, _R), jnp.float32)
    for name, fn, args in (
            ("storage_matvec", storage_matvec, (x, v)),
            ("storage_matmat", storage_matmat, (x, V)),
            ("storage_rows_matmat", storage_rows_matmat, (x, W))):
        jaxpr = jax.make_jaxpr(
            functools.partial(fn, fill=fill, interpret=True))(*args)
        _assert_no_bf16_compare(jaxpr, f"{name}[{dtype}]")


@pytest.mark.parametrize("dtype", ["int8", "bfloat16", "float32"])
def test_no_bf16_compare_in_resolve_kernel(dtype):
    x = _storage(dtype)
    rep = jnp.full((_R,), 1.0 / _R, jnp.float32)
    fill = jnp.full((_E,), 0.5, jnp.float32)
    fn = functools.partial(resolve_certainty_fused, interpret=True)
    jaxpr = jax.make_jaxpr(
        lambda *a: fn(a[0], a[1], a[2], a[3], 0.1))(
        x, rep, fill, jnp.asarray(1.0, jnp.float32))
    _assert_no_bf16_compare(jaxpr, f"resolve_certainty_fused[{dtype}]")


def test_decode_filled_bf16_values_exact():
    """The post-fix decode (f32 compare, then bf16 cast) must produce the
    same filled bf16 panel as the storage contract: lattice values exact,
    absent entries replaced by the fill row."""
    from pyconsensus_tpu.ops.pallas_kernels import _decode_filled_bf16

    enc = jnp.asarray([[0, 1, 2, -1], [2, -1, 0, 1]], jnp.int8)
    fill = jnp.asarray([[0.5, 0.5, 1.0, 0.0]], jnp.bfloat16)
    out = _decode_filled_bf16(enc, fill, nan_fill=True)
    np.testing.assert_array_equal(
        np.asarray(out, np.float32),
        [[0.0, 0.5, 1.0, 0.0], [1.0, 0.5, 0.0, 0.5]])

    raw = jnp.asarray([[0.0, jnp.nan], [1.0, 0.5]], jnp.float32)
    out = _decode_filled_bf16(raw, fill[:, :2], nan_fill=True)
    np.testing.assert_array_equal(np.asarray(out, np.float32),
                                  [[0.0, 0.5], [1.0, 0.5]])


@pytest.mark.parametrize("dtype", ["int8", "bfloat16"])
def test_no_highest_precision_on_bf16_kernel_dots(dtype):
    """Second Mosaic rejection mode (16k-scaled BENCH rung-0, 2026-07-31):
    an ambient jax.default_matmul_precision('highest') — the XLA path's
    exact_matmuls wrapper — leaking into a Pallas kernel trace asks for an
    fp32-precision contract on bf16 operands, which Mosaic rejects ("Bad
    lhs type"). The compact-storage kernel dots are exact-by-compensation
    at DEFAULT and must pin it explicitly, immune to ambient settings."""
    x = _storage(dtype)
    mu = jnp.zeros((_E,), jnp.float32)
    rep = jnp.full((_R,), 1.0 / _R, jnp.float32)
    v = jnp.ones((_E,), jnp.float32)
    fill = jnp.full((_E,), 0.5, jnp.float32)
    with jax.default_matmul_precision("highest"):
        jaxpr = jax.make_jaxpr(
            lambda *a: apply_weighted_cov(*a[:4], fill=a[4], interpret=True))(
            x, mu, rep, v, fill)
    bad = []
    for eqn in _iter_eqns(jaxpr.jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        prec = eqn.params.get("precision")
        if prec is None:
            continue
        high = jax.lax.Precision.HIGHEST
        is_high = (prec == high or
                   (isinstance(prec, tuple) and high in prec))
        if is_high and any(
                getattr(getattr(iv, "aval", None), "dtype", None)
                == jnp.bfloat16 for iv in eqn.invars):
            bad.append(str(eqn.primitive))
    assert not bad, ("bf16 kernel dots traced at HIGHEST precision under "
                     "ambient default_matmul_precision — Mosaic rejects "
                     f"this at compile time: {bad}")


class TestCompensatedSplit:
    """Third backend hazard (found 2026-07-31 building the shard_map
    path): XLA's TPU simplifier folds the compensated-split convert chain
    ``bf16(v - f32(bf16(v)))`` to an ALL-ZERO vector under jit — eager
    gives the true residual — silently degrading every 'compensated' MXU
    dot whose operands were built inside a jitted wrapper to a plain
    bf16-head dot. pallas_kernels._compensated_split hides the head
    behind lax.optimization_barrier."""

    def test_jitted_residual_is_alive(self):
        from pyconsensus_tpu.ops.pallas_kernels import _compensated_split

        v = jnp.asarray(np.random.default_rng(0)
                        .standard_normal(512).astype(np.float32))
        vh, vl = jax.jit(_compensated_split)(v)
        vl = np.asarray(vl, np.float32)
        assert (vl != 0).mean() > 0.9, (
            "jitted compensated split lost its residual — the "
            "optimization_barrier guard is gone or ineffective")
        recon = np.asarray(vh, np.float32) + vl
        np.testing.assert_allclose(recon, np.asarray(v), rtol=2e-5)

    def test_split_keeps_its_barrier(self):
        from pyconsensus_tpu.ops.pallas_kernels import _compensated_split

        v = jnp.ones((16,), jnp.float32)
        prims = {e.primitive.name
                 for e in jax.make_jaxpr(_compensated_split)(v).eqns}
        assert "optimization_barrier" in prims
