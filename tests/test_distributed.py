"""True multi-process distributed execution: two OS processes, each with 2
virtual CPU devices, form ONE 4-device global mesh through
``parallel.initialize`` and resolve the same oracle with cross-process
collectives (gloo CPU backend). This is the multi-host validation story —
the same wiring a real ICI/DCN deployment uses, minus the hardware
(SURVEY.md §5 distributed-communication row)."""

import pathlib
import subprocess
import sys

import numpy as np
import pytest

from conftest import free_port, worker_env
from pyconsensus_tpu import Oracle

_WORKER = pathlib.Path(__file__).resolve().parent / "distributed_worker.py"
_WORKER4 = pathlib.Path(__file__).resolve().parent / "distributed_worker4.py"

#: ISSUE 15 re-triage: the "missing capability" of the ISSUE-3 triage
#: was ONE unset knob — ``parallel.initialize`` now selects the gloo
#: CPU collectives client before the backend initializes
#: (``jax_cpu_collectives_implementation``; the env-var spelling alone
#: never reached the XLA CpuClient on this jax line), so on any jaxlib
#: that SHIPS the client these tests run and pass. The xfail survives
#: only as a capability gate, naming the genuinely absent jaxlib
#: feature where one is absent (``transport.multihost``). Now that
#: they RUN (~60 s each: subprocess jax imports + five phases of
#: cross-process collectives), they carry the ``slow`` mark — the CI
#: rehearsal's unfiltered suite exercises them; the tier-1 wall-time
#: budget does not.
from pyconsensus_tpu.serve.transport.multihost import multihost_capability

_MULTIHOST_REASON = multihost_capability()
_MULTIPROC_XFAIL = pytest.mark.xfail(
    condition=_MULTIHOST_REASON is not None, strict=False,
    reason=f"environmental: {_MULTIHOST_REASON}")


@pytest.mark.slow
@_MULTIPROC_XFAIL
def test_four_process_global_mesh():
    """Round-5 (VERDICT r4 item 8): rendezvous, collective lockstep, and
    the streaming round-robin at FOUR processes — covering an odd panel
    split (3 panels over 4 hosts) with a zero-panel host, the bug class
    (non-adjacent rings, hosts with no local work entering collectives)
    that a 2-process mesh can never exhibit."""
    port = free_port()
    env = worker_env()
    procs = [subprocess.Popen([sys.executable, str(_WORKER4), str(i),
                               str(port)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(4)]
    outputs = []
    try:
        for proc in procs:
            out, _ = proc.communicate(timeout=360)
            outputs.append(out)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
    for proc, out in zip(procs, outputs):
        assert proc.returncode == 0, f"worker failed:\n{out}"

    def parse(tag, text):
        for line in text.splitlines():
            if line.startswith(tag + " "):
                return np.asarray([float(v) for v in
                                   line.split(" ", 1)[1].split(",")])
        raise AssertionError(f"no {tag} line in:\n{text}")

    # every process computed the identical global resolution
    for tag, atol in (("RESULT", 0), ("REP", 1e-6), ("STREAM", 0),
                      ("STREAMREP", 1e-6), ("KMEANS", 0),
                      ("KMEANSREP", 1e-6)):
        vals = [parse(tag, o) for o in outputs]
        for v in vals[1:]:
            if atol:
                np.testing.assert_allclose(v, vals[0], atol=atol,
                                           err_msg=tag)
            else:
                np.testing.assert_array_equal(v, vals[0], err_msg=tag)

    # and the mesh resolution matches a plain single-process oracle
    from conftest import collusion_reports
    reports, _ = collusion_reports(np.random.default_rng(0), 12, 16, liars=3)
    ref = Oracle(reports=reports, backend="jax", max_iterations=2,
                 pca_method="eigh-gram").consensus()
    np.testing.assert_array_equal(parse("RESULT", outputs[0]),
                                  ref["events"]["outcomes_adjusted"])
    np.testing.assert_allclose(parse("REP", outputs[0]),
                               ref["agents"]["smooth_rep"], atol=1e-5)

    # the streamed resolutions (odd split, zero-panel host) match a
    # single-process streaming run of the same matrix
    from pyconsensus_tpu.models.pipeline import ConsensusParams
    from pyconsensus_tpu.parallel import streaming_consensus
    local = streaming_consensus(
        reports, panel_events=6,
        params=ConsensusParams(algorithm="sztorc", max_iterations=2))
    np.testing.assert_array_equal(parse("STREAM", outputs[0]),
                                  local["outcomes_adjusted"])
    local_k = streaming_consensus(
        reports, panel_events=6,
        params=ConsensusParams(algorithm="k-means", num_clusters=3,
                               max_iterations=2))
    np.testing.assert_array_equal(parse("KMEANS", outputs[0]),
                                  local_k["outcomes_adjusted"])


@pytest.mark.slow
@_MULTIPROC_XFAIL
def test_two_process_global_mesh(tmp_path):
    port = free_port()
    env = worker_env()
    ckdir = tmp_path / "sweep-ck"
    procs = [subprocess.Popen([sys.executable, str(_WORKER), str(i),
                               str(port), str(ckdir)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outputs = []
    try:
        for proc in procs:
            out, _ = proc.communicate(timeout=240)
            outputs.append(out)
    finally:
        # a worker that failed or timed out leaves its peer blocked in a
        # cross-process collective — never leak it past the test
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
    for proc, out in zip(procs, outputs):
        assert proc.returncode == 0, f"worker failed:\n{out}"

    def parse(tag, text):
        for line in text.splitlines():
            if line.startswith(tag + " "):
                return np.asarray([float(v) for v in
                                   line.split(" ", 1)[1].split(",")])
        raise AssertionError(f"no {tag} line in:\n{text}")

    res0, res1 = (parse("RESULT", o) for o in outputs)
    rep0, rep1 = (parse("REP", o) for o in outputs)
    # both processes computed the identical global resolution
    np.testing.assert_array_equal(res0, res1)
    np.testing.assert_allclose(rep0, rep1, atol=1e-6)

    # and it matches a plain single-process resolution of the same matrix
    from conftest import collusion_reports
    reports, _ = collusion_reports(np.random.default_rng(0), 12, 16, liars=3)
    ref = Oracle(reports=reports, backend="jax", max_iterations=2,
                 pca_method="eigh-gram").consensus()
    np.testing.assert_array_equal(res0,
                                  ref["events"]["outcomes_adjusted"])
    np.testing.assert_allclose(rep0, ref["agents"]["smooth_rep"], atol=1e-5)

    # phase 2: the two processes split one CheckpointedSweep round-robin
    # (host_id from jax.process_index); the merged result must equal a
    # monolithic single-process run
    from pyconsensus_tpu.sim import CheckpointedSweep, CollusionSimulator
    counts = [int(parse("SWEEP", o)[0]) for o in outputs]
    sim = CollusionSimulator(n_reporters=8, n_events=5, max_iterations=1)
    sweep = CheckpointedSweep(sim, [0.0, 0.3], [0.1], 6, seed=2,
                              checkpoint_dir=ckdir, trials_per_chunk=4)
    assert sum(counts) == sweep.n_chunks
    assert sweep.pending() == []
    got = sweep.gather()
    mono = sim.run([0.0, 0.3], [0.1], 6, seed=2)
    np.testing.assert_array_equal(got["correct_rate"],
                                  mono["correct_rate"])

    # phase 3: multi-host out-of-core streaming — both processes must
    # return the identical full resolution, equal to a single-process
    # streaming run of the same matrix
    from pyconsensus_tpu.models.pipeline import ConsensusParams
    from pyconsensus_tpu.parallel import streaming_consensus
    s0, s1 = (parse("STREAM", o) for o in outputs)
    sr0, sr1 = (parse("STREAMREP", o) for o in outputs)
    np.testing.assert_array_equal(s0, s1)
    np.testing.assert_allclose(sr0, sr1, atol=1e-6)
    local = streaming_consensus(
        reports, panel_events=3,
        params=ConsensusParams(algorithm="sztorc", max_iterations=2))
    np.testing.assert_array_equal(s0, local["outcomes_adjusted"])
    np.testing.assert_allclose(sr0, local["smooth_rep"], atol=1e-5)

    # phase 4: scaled events + power PCA with cross-process collectives —
    # the unblocked sharded median (round 2) must agree across processes
    # and with a plain single-process resolution of the same matrix
    sc0, sc1 = (parse("SCALED", o) for o in outputs)
    np.testing.assert_array_equal(sc0, sc1)
    reports_sc = reports.copy()
    reports_sc[:, -2:] = np.random.default_rng(42).uniform(0.0, 10.0,
                                                           (12, 2))
    bounds = [None] * 14 + [{"scaled": True, "min": 0.0, "max": 10.0}] * 2
    ref_sc = Oracle(reports=reports_sc, event_bounds=bounds, backend="jax",
                    max_iterations=2, pca_method="power").consensus()
    # binary columns catch-snapped -> exact across process counts
    np.testing.assert_array_equal(
        sc0[:14], ref_sc["events"]["outcomes_adjusted"][:14])
    np.testing.assert_allclose(
        sc0[14:], ref_sc["events"]["outcomes_adjusted"][14:], atol=1e-6)

    # phase 5: the shard_map fused path (round 3) — int8 kernels per
    # event shard with explicit psums over REAL cross-process gloo
    # collectives; outcomes must agree across processes and bit-match the
    # single-device fused path on the same matrix
    import jax.numpy as jnp

    from pyconsensus_tpu.models.pipeline import _consensus_core_fused
    f0, f1 = (parse("FUSED", o) for o in outputs)
    fr0, fr1 = (parse("FUSEDREP", o) for o in outputs)
    np.testing.assert_array_equal(f0, f1)
    np.testing.assert_allclose(fr0, fr1, atol=1e-6)
    pf = ConsensusParams(algorithm="sztorc", pca_method="power",
                         power_iters=64, power_tol=0.0,
                         storage_dtype="int8", any_scaled=False,
                         has_na=True, fused_resolution=True)
    local_f = _consensus_core_fused(
        jnp.asarray(reports), jnp.full((12,), 1.0 / 12.0),
        jnp.zeros((16,), bool), jnp.zeros((16,)), jnp.ones((16,)), pf)
    np.testing.assert_array_equal(
        f0, np.asarray(local_f["outcomes_adjusted"]))
    np.testing.assert_allclose(fr0, np.asarray(local_f["smooth_rep"]),
                               atol=1e-5)

    # phase 6 (round 4): hybrid host-clustering on the multi-process
    # mesh — identical across processes (each clusters the same
    # replicated distance copy) and equal to the single-process hybrid
    h0, h1 = (parse("HYBRID", o) for o in outputs)
    hr0, hr1 = (parse("HYBRIDREP", o) for o in outputs)
    np.testing.assert_array_equal(h0, h1)
    np.testing.assert_allclose(hr0, hr1, atol=1e-6)
    ref_h = Oracle(reports=reports, backend="jax", max_iterations=2,
                   algorithm="hierarchical").consensus()
    np.testing.assert_array_equal(h0,
                                  ref_h["events"]["outcomes_adjusted"])
    np.testing.assert_allclose(hr0, ref_h["agents"]["smooth_rep"],
                               atol=1e-5)

    # phase 7 (round 4): multi-host streamed k-means — event-local
    # centroids with the (R, k) distance allreduce riding real gloo;
    # identical across processes and equal to a single-process streamed
    # run of the same matrix
    k0, k1 = (parse("KMEANS", o) for o in outputs)
    kr0, kr1 = (parse("KMEANSREP", o) for o in outputs)
    np.testing.assert_array_equal(k0, k1)
    np.testing.assert_allclose(kr0, kr1, atol=1e-6)
    local_k = streaming_consensus(
        reports, panel_events=3,
        params=ConsensusParams(algorithm="k-means", num_clusters=3,
                               max_iterations=2))
    np.testing.assert_array_equal(k0, local_k["outcomes_adjusted"])
    np.testing.assert_allclose(kr0, local_k["smooth_rep"], atol=1e-5)
