"""SLO-driven elastic fleet (ISSUE 19): the autoscaler control loop,
elastic fleet membership, and the drain-vs-death race.

The contract under test, end to end: sustained SLO violation grows the
fleet, sustained idleness shrinks it through a graceful drain that
LIVE-migrates every session (zero lost acknowledged rounds, bits
identical to a single-box run), a declared death is replaced by a FRESH
worker without double-firing against the heartbeat takeover, and a
SIGKILL landing mid-drain still moves every session exactly once — no
matter which migration step the kill interrupts.
"""

import threading

import numpy as np
import pytest

from fleet_worker import N_REPORTERS, make_block
from pyconsensus_tpu import faults, obs
from pyconsensus_tpu.faults import InputError, PlacementError
from pyconsensus_tpu.obs import SloMonitor
from pyconsensus_tpu.serve import (AutoScaler, AutoscaleConfig,
                                   ConsensusFleet, DurableSession,
                                   FleetConfig, MarketSession,
                                   ServeConfig)


@pytest.fixture(autouse=True)
def _under_lock_witness(lock_witness):
    """Every autoscale test runs under the runtime lock witness (ISSUE
    9): the autoscaler's lock is declared OUTERMOST of the fleet
    hierarchy, and the observed acquisition order across scaler /
    declare / router locks must stay acyclic."""
    yield


@pytest.fixture(autouse=True)
def _under_protocol_witness(protocol_witness):
    """And under the runtime protocol witness (ISSUE 16): a drain's
    live migration replays durable sessions, so every observed
    journal/commit/ship/ack order must match the CL901 graph."""
    yield


@pytest.fixture(autouse=True)
def _under_digest_witness(digest_witness):
    """And under the runtime digest witness (ISSUE 17): every digest a
    migration journals must replay bit-identical from the log."""
    yield


def mini_fleet(tmp_path, n=2, **cfg_kwargs):
    cfg = FleetConfig(
        n_workers=n, log_dir=str(tmp_path / "log"),
        worker=ServeConfig(warmup=(), batch_window_ms=1.0),
        **cfg_kwargs)
    return ConsensusFleet(cfg)


class StubMonitor:
    """The autoscaler consumes exactly ``targets`` + ``window()`` — a
    stub drives the control law with hand-built windowed views, the
    same way the SloMonitor tests drive the window math with hand-built
    snapshots."""

    def __init__(self, targets):
        self.targets = dict(targets)
        self.win = {}

    def window(self):
        return dict(self.win)


#: any observed signal above its target (p99 target 50ms)
BREACHED = {"p99_ms": 120.0}
#: every observed signal at/below half (down_headroom) of its target
IDLE = {"p99_ms": 10.0, "queue_depth": 1.0}
#: under the target but above the scale-down headroom — neither
#: breached nor idle; streaks must reset
MID_BAND = {"p99_ms": 40.0}


def make_scaler(fleet, targets=None, **cfg):
    mon = StubMonitor(targets or {"p99_ms": 50.0, "queue_depth": 8.0})
    defaults = dict(min_workers=1, max_workers=4, up_signals=2,
                    down_signals=3, cooldown_s=5.0, warmup=False)
    defaults.update(cfg)
    return AutoScaler(fleet, mon, AutoscaleConfig(**defaults)), mon


def decisions(action):
    return obs.value("pyconsensus_autoscale_decisions_total",
                     action=action) or 0


# -- config validation -------------------------------------------------------


class TestAutoscaleConfig:
    def test_min_workers_must_be_positive(self, tmp_path):
        fleet = mini_fleet(tmp_path)
        with pytest.raises(InputError, match="min_workers"):
            AutoScaler(fleet, StubMonitor({}),
                       AutoscaleConfig(min_workers=0))

    def test_max_must_cover_min(self, tmp_path):
        fleet = mini_fleet(tmp_path)
        with pytest.raises(InputError, match="max_workers"):
            AutoScaler(fleet, StubMonitor({}),
                       AutoscaleConfig(min_workers=3, max_workers=2))


# -- the control law ---------------------------------------------------------


class TestControlLaw:
    def test_first_evaluate_adopts_ring_size_as_target(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2)
        scaler, mon = make_scaler(fleet)
        d = scaler.evaluate(now=0.0)
        assert d["action"] == "hold"
        assert d["target"] == 2
        assert scaler.status()["target"] == 2
        # an empty window (no samples yet) is neither breached nor idle
        assert d["breached"] == []
        assert d["idle"] is False

    def test_single_breach_is_hysteresis_hold(self, tmp_path):
        """One bad sample never scales — up_signals are CONSECUTIVE."""
        fleet = mini_fleet(tmp_path, n=2)
        scaler, mon = make_scaler(fleet)
        mon.win = BREACHED
        d = scaler.evaluate(now=0.0)
        assert d["action"] == "hold"
        assert d["up_streak"] == 1
        assert len(fleet.ring.workers()) == 2

    def test_sustained_breach_scales_up(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2)
        scaler, mon = make_scaler(fleet)
        holds0, ups0 = decisions("hold"), decisions("scale_up")
        mon.win = BREACHED
        scaler.evaluate(now=0.0)
        d = scaler.evaluate(now=0.5)
        assert d["action"] == "scale_up"
        assert d["worker"] == "w2"          # monotonic fresh name
        assert d["breached"] == ["p99_ms"]
        assert sorted(fleet.ring.workers()) == ["w0", "w1", "w2"]
        assert d["target"] == 3
        assert decisions("hold") - holds0 == 1
        assert decisions("scale_up") - ups0 == 1
        assert obs.value("pyconsensus_autoscale_target_workers") == 3
        fleet.close(drain=False, timeout=10.0)

    def test_cooldown_blocks_back_to_back_changes(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=1)
        scaler, mon = make_scaler(fleet, cooldown_s=5.0)
        mon.win = BREACHED
        scaler.evaluate(now=0.0)
        assert scaler.evaluate(now=0.5)["action"] == "scale_up"
        # still breached, streak builds past up_signals — but the
        # cool-down quiet period holds the line
        for t in (1.0, 2.0, 4.0):
            assert scaler.evaluate(now=t)["action"] == "hold"
        assert len(fleet.ring.workers()) == 2
        assert scaler.evaluate(now=6.0)["action"] == "scale_up"
        assert len(fleet.ring.workers()) == 3
        fleet.close(drain=False, timeout=10.0)

    def test_max_workers_is_a_hard_ceiling(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2)
        scaler, mon = make_scaler(fleet, max_workers=2)
        mon.win = BREACHED
        for t in (0.0, 0.5, 1.0, 1.5):
            assert scaler.evaluate(now=t)["action"] == "hold"
        assert len(fleet.ring.workers()) == 2

    def test_mid_band_resets_both_streaks(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2)
        scaler, mon = make_scaler(fleet, up_signals=2)
        mon.win = BREACHED
        scaler.evaluate(now=0.0)                        # streak 1
        mon.win = MID_BAND
        d = scaler.evaluate(now=0.5)
        assert d["action"] == "hold"
        assert scaler.status()["up_streak"] == 0
        assert scaler.status()["down_streak"] == 0
        mon.win = BREACHED
        d = scaler.evaluate(now=1.0)                    # streak 1 again
        assert d["action"] == "hold"
        assert len(fleet.ring.workers()) == 2

    def test_sustained_idle_drains_one_worker(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=3).start(warmup=False)
        scaler, mon = make_scaler(fleet, down_signals=3)
        mon.win = IDLE
        assert scaler.evaluate(now=0.0)["action"] == "hold"
        assert scaler.evaluate(now=0.5)["action"] == "hold"
        d = scaler.evaluate(now=1.0)
        assert d["action"] == "scale_down"
        assert d["worker"] == "w2"      # newest on the 0-session tie
        assert d["drained"] is True
        assert d["target"] == 2
        assert sorted(fleet.ring.workers()) == ["w0", "w1"]
        fleet.close(drain=True, timeout=10.0)

    def test_min_workers_is_a_hard_floor(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=1).start(warmup=False)
        scaler, mon = make_scaler(fleet, down_signals=2)
        mon.win = IDLE
        for t in (0.0, 0.5, 1.0, 1.5):
            assert scaler.evaluate(now=t)["action"] == "hold"
        assert len(fleet.ring.workers()) == 1
        fleet.close(drain=True, timeout=10.0)

    def test_empty_window_is_not_idle(self, tmp_path):
        """No observed signals must never read as 'idle' — a monitor
        that has not sampled yet would otherwise drain the fleet."""
        fleet = mini_fleet(tmp_path, n=2)
        scaler, mon = make_scaler(fleet, down_signals=1)
        mon.win = {}
        d = scaler.evaluate(now=0.0)
        assert d["action"] == "hold"
        assert d["idle"] is False
        assert len(fleet.ring.workers()) == 2

    def test_victim_fewest_sessions_then_newest(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=3)
        scaler, _ = make_scaler(fleet)
        ring = tuple(fleet.ring.workers())
        # no sessions anywhere: a three-way tie — the NEWEST worker is
        # the victim (boot workers are the last to go)
        assert scaler._victim(ring) == "w2"
        # load w2 with a session: the tie is now w0/w1 — newest wins
        name = next(f"m{i}" for i in range(200)
                    if fleet.ring.owner(f"m{i}") == "w2")
        fleet.create_session(name, n_reporters=6)
        assert scaler._victim(ring) == "w1"


# -- replacement composes with the heartbeat declaration ---------------------


class TestReplacement:
    def test_dead_worker_replaced_without_streaks_or_cooldown(
            self, tmp_path):
        """A declared death is replaced on the very next evaluation —
        no streaks (serving below target IS the incident), no cool-down
        (a death is monotonic; it cannot flap) — and the replacement is
        a FRESH name, never the corpse's."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        scaler, mon = make_scaler(fleet, cooldown_s=60.0)
        scaler.evaluate(now=0.0)                # adopt target = 2
        fleet.kill_worker("w1")
        d = scaler.evaluate(now=0.1)            # single eval suffices
        assert d["action"] == "replace"
        assert d["worker"] == "w2"
        assert sorted(fleet.ring.workers()) == ["w0", "w2"]
        assert scaler.status()["target"] == 2
        # a second death INSIDE the cool-down window set by the first
        # replacement is still replaced immediately
        fleet.kill_worker("w2")
        d = scaler.evaluate(now=0.5)
        assert d["action"] == "replace"
        assert d["worker"] == "w3"
        # back at target: the loop settles, no double-fire
        assert scaler.evaluate(now=0.6)["action"] == "hold"
        fleet.close(drain=False, timeout=10.0)

    def test_refused_drain_restores_target_for_replacement(
            self, tmp_path):
        """The scale-down actuator lowers the target BEFORE draining
        (so the mid-drain ring shrink is not read as a death). A drain
        the fleet REFUSES — here: the only surviving peer is an
        undeclared corpse — must roll that back, or the lowered target
        would silently absorb the corpse's eventual declaration and no
        replacement would ever fire."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        scaler, mon = make_scaler(fleet, down_signals=1, cooldown_s=0.0)
        mon.win = IDLE
        fleet.workers["w0"].hard_kill(0.2)      # dead, NOT declared
        d = scaler.evaluate(now=0.0)            # drains w1 -> refused
        assert d["action"] == "error"
        assert "no surviving ring" in d["error"]
        assert scaler.status()["target"] == 2   # rolled back, not 1
        fleet.check_workers()                   # the declaration lands
        mon.win = MID_BAND
        d = scaler.evaluate(now=0.5)
        assert d["action"] == "replace"
        assert sorted(fleet.ring.workers()) == ["w1", "w2"]
        fleet.close(drain=False, timeout=10.0)

    def test_replacement_composes_with_takeover_bit_identical(
            self, tmp_path):
        """Chaos pin (a) in-process: SIGKILL a session's owner — the
        heartbeat declaration fails the session over (exactly one
        takeover), the autoscaler only ADDS capacity, and the session's
        resolved bits match a single box that saw the same appends —
        zero lost acknowledged rounds."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        scaler, mon = make_scaler(fleet)
        scaler.evaluate(now=0.0)
        owner = fleet.create_session("mkt", n_reporters=N_REPORTERS)
        fleet.append("mkt", make_block(0, 0))   # acknowledged
        failovers0 = obs.value("pyconsensus_failovers_total") or 0
        fleet.kill_worker(owner)                # declaration + takeover
        survivor = fleet.owner_of("mkt")
        assert survivor != owner
        d = scaler.evaluate(now=0.1)
        assert d["action"] == "replace"
        replacement = d["worker"]
        assert replacement not in (owner, survivor)
        # the replacement never re-ran the takeover: one failover, and
        # the session stayed where the declaration put it
        assert (obs.value("pyconsensus_failovers_total")
                - failovers0) == 1
        assert fleet.owner_of("mkt") == survivor
        # the acknowledged append survived the whole dance, bit for bit
        fleet.append("mkt", make_block(0, 1))
        got = fleet.submit(session="mkt").result(timeout=60)
        ref = MarketSession("ref", N_REPORTERS)
        ref.append(make_block(0, 0))
        ref.append(make_block(0, 1))
        want = ref.resolve()
        np.testing.assert_array_equal(
            np.asarray(got["agents"]["smooth_rep"]),
            np.asarray(want["smooth_rep"]))
        np.testing.assert_array_equal(
            np.asarray(got["events"]["outcomes_final"]),
            np.asarray(want["outcomes_final"]))
        fleet.close(drain=True, timeout=30.0)


# -- fault injection ---------------------------------------------------------


class TestAutoscaleFaults:
    def test_decide_fault_costs_one_period(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2)
        scaler, mon = make_scaler(fleet)
        errors0 = decisions("error")
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "autoscale.decide", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            d = scaler.evaluate(now=0.0)
        assert plan.fired == [("autoscale.decide", 0, "raise")]
        assert d["action"] == "error"
        assert "OSError" in d["error"]
        assert decisions("error") - errors0 == 1
        # the loop outlives the fault: the next period decides normally
        assert scaler.evaluate(now=0.5)["action"] == "hold"

    def test_spawn_fault_never_half_changes_membership(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=1)
        scaler, mon = make_scaler(fleet, up_signals=1, cooldown_s=0.0)
        mon.win = BREACHED
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "autoscale.spawn", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            d = scaler.evaluate(now=0.0)
        assert d["action"] == "error"
        assert len(fleet.ring.workers()) == 1   # nothing half-spawned
        # re-attempted from fresh signals the next period
        assert scaler.evaluate(now=0.5)["action"] == "scale_up"
        assert len(fleet.ring.workers()) == 2
        fleet.close(drain=False, timeout=10.0)

    def test_drain_fault_never_half_drains(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        scaler, mon = make_scaler(fleet, down_signals=1, cooldown_s=0.0)
        mon.win = IDLE
        plan = faults.FaultPlan(seed=0, rules=[
            {"site": "autoscale.drain", "kind": "raise",
             "occurrences": [0], "args": {"error": "os_error"}}])
        with faults.armed(plan):
            d = scaler.evaluate(now=0.0)
        assert d["action"] == "error"
        # an aborted decision, never a half-drained fleet
        assert sorted(fleet.ring.workers()) == ["w0", "w1"]
        assert scaler.evaluate(now=0.5)["action"] == "scale_down"
        assert list(fleet.ring.workers()) == ["w0"]
        fleet.close(drain=True, timeout=10.0)


# -- the production loop -----------------------------------------------------


class TestAutoscalerThread:
    def test_run_in_thread_is_idempotent_and_stops(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=1)
        scaler, mon = make_scaler(fleet)
        scaler.config = AutoscaleConfig(interval_s=0.02, warmup=False)
        assert scaler.run_in_thread() is scaler
        th = scaler._thread
        assert scaler.run_in_thread() is scaler     # idempotent
        assert scaler._thread is th
        deadline = 100
        while not scaler.status()["last_decision"] and deadline:
            deadline -= 1
            threading.Event().wait(0.02)
        assert scaler.status()["last_decision"]["action"] == "hold"
        scaler.stop()
        assert scaler._thread is None
        scaler.stop()                               # stop is idempotent
        # stopping the loop is not a scale-to-zero
        assert len(fleet.ring.workers()) == 1


# -- elastic membership ------------------------------------------------------


class TestElasticMembership:
    def test_worker_names_are_monotonic_never_reused(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        assert fleet.drain_worker("w1")["drained"] is True
        assert fleet.add_worker(warmup=False) == "w2"   # not "w1"
        fleet.kill_worker("w2")
        assert fleet.add_worker(warmup=False) == "w3"   # nor "w2"
        with pytest.raises(InputError, match="already exists"):
            fleet.add_worker(name="w0")
        fleet.close(drain=True, timeout=10.0)

    def test_drain_refuses_the_last_ring_worker(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=1)
        with pytest.raises(PlacementError, match="last worker"):
            fleet.drain_worker("w0")

    def test_drain_unknown_worker_is_placement_error(self, tmp_path):
        fleet = mini_fleet(tmp_path, n=2)
        with pytest.raises(PlacementError, match="unknown worker"):
            fleet.drain_worker("w99")

    def test_drain_migrates_every_live_session_bit_identical(
            self, tmp_path):
        """Chaos pin (b) in-process: scale-down live-migrates EVERY
        session off the victim with zero loss — the survivors' bits
        match a single box that saw the same appends, and the drained
        worker has left the fleet entirely."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        names = [f"m{i}" for i in range(5)]
        for n in names:
            fleet.create_session(n, n_reporters=6)
            fleet.append(n, make_block(0, 0)[:6])
            fleet.submit(session=n).result(timeout=60)  # acked round
        victim = fleet.owner_of(names[0])
        mine = sorted(n for n in names if fleet.owner_of(n) == victim)
        migrated0 = obs.value("pyconsensus_sessions_migrated_total") or 0
        res = fleet.drain_worker(victim)
        assert res["drained"] is True
        assert sorted(s for s, _ in res["sessions_migrated"]) == mine
        assert victim not in fleet.ring.workers()
        assert not fleet.workers[victim].alive
        assert ((obs.value("pyconsensus_sessions_migrated_total") or 0)
                - migrated0) == len(mine)
        # every session still serves, on the survivor, bit-identical to
        # the never-drained single box (a DurableSession on its own
        # log: the same journal-staged fold the fleet runs — the
        # migration contract is exactly "as if the drain never
        # happened", staging machinery included)
        survivor = fleet.ring.workers()[0]
        for n in names:
            assert fleet.owner_of(n) == survivor
            fleet.append(n, make_block(1, 0)[:6])
            got = fleet.submit(session=n).result(timeout=60)
            ref = DurableSession.create(tmp_path / "refs", n, 6)
            ref.append(make_block(0, 0)[:6])
            ref.resolve()
            ref.append(make_block(1, 0)[:6])
            want = ref.resolve()
            np.testing.assert_array_equal(
                np.asarray(got["agents"]["smooth_rep"]),
                np.asarray(want["smooth_rep"]))
            np.testing.assert_array_equal(
                np.asarray(got["events"]["outcomes_final"]),
                np.asarray(want["outcomes_final"]))
        # a second drain of the departed worker is a structured no-op
        again = fleet.drain_worker(victim)
        assert again["drained"] is False
        assert again["sessions_migrated"] == []
        fleet.close(drain=True, timeout=30.0)

    def test_killing_a_drained_worker_runs_no_takeover(self, tmp_path):
        """Death after departure: the drained worker owns nothing, so a
        late declaration (monitor scan, chaos kill) must not re-run a
        takeover or disturb the migrated sessions."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        fleet.create_session("s", n_reporters=6)
        victim = fleet.owner_of("s")
        fleet.drain_worker(victim)
        owner = fleet.owner_of("s")
        failovers0 = obs.value("pyconsensus_failovers_total") or 0
        info = fleet.kill_worker(victim)
        assert info["sessions_migrated"] == []
        assert (obs.value("pyconsensus_failovers_total") or 0) \
            == failovers0
        assert fleet.owner_of("s") == owner
        fleet.close(drain=True, timeout=10.0)


# -- the drain-vs-death race -------------------------------------------------


class TestDrainVsDeathRace:
    def test_death_before_drain_is_a_noop_drain(self, tmp_path):
        """The declaration wins outright: a worker killed BEFORE the
        drain starts has already handed its sessions to the takeover —
        the drain observes the corpse and does nothing."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        fleet.create_session("s", n_reporters=6)
        fleet.append("s", make_block(0, 0)[:6])
        victim = fleet.owner_of("s")
        fleet.kill_worker(victim)
        owner = fleet.owner_of("s")
        assert owner != victim
        res = fleet.drain_worker(victim)
        assert res["drained"] is False
        assert res["sessions_migrated"] == []
        assert fleet.owner_of("s") == owner
        fleet.close(drain=True, timeout=10.0)

    def test_drain_refuses_when_only_peer_is_an_undeclared_corpse(
            self, tmp_path):
        """Ring membership is not liveness: between a peer's death and
        its heartbeat-staleness declaration the ring still lists the
        corpse. A drain that counted it as surviving capacity would
        shut down the last LIVE worker and migrate its sessions onto a
        corpse — the drain must probe and refuse instead."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        fleet.workers["w0"].hard_kill(0.2)      # dead, NOT declared
        assert sorted(fleet.ring.workers()) == ["w0", "w1"]
        with pytest.raises(PlacementError, match="no surviving ring"):
            fleet.drain_worker("w1")
        # the refused drain left w1 untouched: on the ring, alive
        assert "w1" in fleet.ring.workers()
        assert fleet.workers["w1"].alive
        # once the monitor declares the corpse, w1 is the last ring
        # worker — still undrainable, by the last-worker rule
        fleet.check_workers()
        assert list(fleet.ring.workers()) == ["w1"]
        with pytest.raises(PlacementError, match="last worker"):
            fleet.drain_worker("w1")
        fleet.close(drain=False, timeout=10.0)

    @pytest.mark.parametrize("kill_point", [0, 1, 2])
    def test_sigkill_mid_drain_single_takeover_bit_identical(
            self, tmp_path, kill_point):
        """The satellite property test: SIGKILL the worker being
        gracefully drained, at every migration step the fence sequence
        exposes. Holding the victim's declare lock across the drain
        serializes the racing declaration — it blocks, then observes an
        off-ring worker with nothing left to move. Exactly ONE takeover
        runs, every session lands exactly once, and the resolved bits
        match a never-killed run."""
        fleet = mini_fleet(tmp_path, n=2).start(warmup=False)
        names = [f"m{i}" for i in range(5)]
        for n in names:
            fleet.create_session(n, n_reporters=6)
            fleet.append(n, make_block(0, 0)[:6])
            fleet.submit(session=n).result(timeout=60)  # acked round
        by_owner = {}
        for n in names:
            by_owner.setdefault(fleet.owner_of(n), []).append(n)
        # the majority owner has >= 3 of 5 sessions (pigeonhole), so
        # every parametrized kill point lands inside its fence sequence
        victim = max(by_owner, key=lambda w: len(by_owner[w]))
        mine = sorted(by_owner[victim])
        assert len(mine) > kill_point
        w = fleet.workers[victim]
        failovers0 = obs.value("pyconsensus_failovers_total") or 0
        migrated0 = obs.value("pyconsensus_sessions_migrated_total") or 0

        race = []
        killer = threading.Thread(
            target=lambda: race.append(fleet.kill_worker(victim)))
        orig_fence = w.fence_session
        calls = {"n": 0}

        def fence_and_die(name, exc):
            if calls["n"] == kill_point:
                # the in-process SIGKILL model lands mid-migration, and
                # a concurrent declaration races the rest of the drain
                w.hard_kill(0.2)
                killer.start()
            calls["n"] += 1
            return orig_fence(name, exc)

        w.fence_session = fence_and_die
        res = fleet.drain_worker(victim)
        killer.join(timeout=30.0)
        assert not killer.is_alive()
        # the drain completed: the log is the source of truth, so the
        # mid-drain death changes nothing about what migrates
        assert res["drained"] is True
        assert sorted(s for s, _ in res["sessions_migrated"]) == mine
        # the racing declaration blocked on the declare lock, then
        # observed nothing left to move: exactly one takeover ran and
        # each session landed exactly once
        assert race and race[0]["sessions_migrated"] == []
        assert ((obs.value("pyconsensus_failovers_total") or 0)
                - failovers0) == 1
        assert ((obs.value("pyconsensus_sessions_migrated_total") or 0)
                - migrated0) == len(mine)
        survivor = fleet.ring.workers()[0]
        assert survivor != victim
        assert set(fleet.sessions()) == set(names)
        assert set(fleet.sessions().values()) == {survivor}
        # bit-identity against the never-killed single box (a durable
        # session on its own log — the same journal-staged fold)
        for n in names:
            fleet.append(n, make_block(1, 0)[:6])
            got = fleet.submit(session=n).result(timeout=60)
            ref = DurableSession.create(tmp_path / "refs", n, 6)
            ref.append(make_block(0, 0)[:6])
            ref.resolve()
            ref.append(make_block(1, 0)[:6])
            want = ref.resolve()
            np.testing.assert_array_equal(
                np.asarray(got["agents"]["smooth_rep"]),
                np.asarray(want["smooth_rep"]))
            np.testing.assert_array_equal(
                np.asarray(got["events"]["outcomes_final"]),
                np.asarray(want["outcomes_final"]))
        fleet.close(drain=True, timeout=30.0)


# -- the SLO window under membership change ----------------------------------


def _member_snap(requests=None, counts=None, edges=(0.005, 0.05, 0.5)):
    """Hand-built MERGED-registry snapshot with per-worker series — the
    membership-change shape the fleet's merged cluster view produces."""
    snap = {}
    if requests is not None:
        snap["pyconsensus_serve_requests_total"] = {
            "kind": "counter", "labels": ["worker"],
            "series": {k: float(v) for k, v in requests.items()}}
    if counts is not None:
        snap["pyconsensus_serve_request_seconds"] = {
            "kind": "histogram", "labels": ["worker"],
            "edges": list(edges),
            "series": {k: {"sum": 0.0, "count": sum(v),
                           "counts": list(v)}
                       for k, v in counts.items()}}
    return snap


def _feed(monitor, timeline):
    feed = {"snap": {}}
    monitor._snapshot_fn = lambda: feed["snap"]
    for now, snap in timeline:
        feed["snap"] = snap
        monitor.sample(now=now)


class TestSloWindowMembership:
    def test_worker_born_inside_window_charges_window_local_counts(
            self):
        """A scale-up mid-window: the new worker's cumulative counters
        ARE window-local (they started at zero when it joined) — the
        cluster rate is the sum, not a phantom."""
        m = SloMonitor(window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _member_snap(requests={"w0": 100.0})),
                  (1.0, _member_snap(requests={"w0": 110.0,
                                               "w1": 5.0}))])
        assert m.window()["request_rate_rps"] == 15.0

    def test_drained_worker_vanishing_series_never_negative(self):
        """A scale-down mid-window: the departed worker's series
        vanishes from the merged snapshot — it contributes zero, never
        a negative delta that bends the cluster rate."""
        m = SloMonitor(window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _member_snap(requests={"w0": 100.0,
                                               "w1": 80.0})),
                  (1.0, _member_snap(requests={"w0": 110.0}))])
        assert m.window()["request_rate_rps"] == 10.0

    def test_histogram_membership_change_keeps_quantiles_honest(self):
        """Bucket deltas are taken per series THEN summed: the joining
        worker's window-local counts drive the quantile, the steady
        worker's unchanged cumulative counts contribute nothing."""
        m = SloMonitor(window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _member_snap(counts={"w0": [90, 9, 1, 0]})),
                  (1.0, _member_snap(counts={"w0": [90, 9, 1, 0],
                                             "w1": [10, 0, 0, 0]}))])
        assert m.window()["p50_ms"] == 5.0      # w1's 10 fast requests

    def test_real_scale_up_mid_window_keeps_rate_honest(self, tmp_path):
        """The REAL thing: sample the fleet's merged snapshot, grow the
        fleet mid-window, and the windowed request rate counts exactly
        the requests served — no double count, no negative bend."""
        fleet = mini_fleet(tmp_path, n=1).start(warmup=False)
        m = SloMonitor(window_s=60.0, snapshot_fn=fleet.merged_snapshot)

        def req_total(snap):
            series = snap.get("pyconsensus_serve_requests_total",
                              {}).get("series") or {}
            return sum(series.values())
        for _ in range(3):
            fleet.submit(reports=np.ones((3, 3)),
                         backend="numpy").result(timeout=60)
        before = req_total(fleet.merged_snapshot())
        m.sample(now=0.0)
        fleet.add_worker(warmup=False)          # membership change
        for _ in range(4):
            fleet.submit(reports=np.ones((3, 3)),
                         backend="numpy").result(timeout=60)
        after = req_total(fleet.merged_snapshot())
        win = m.sample(now=1.0)
        # on a pure scale-up no series vanishes, so the per-series
        # window delta must equal the plain cluster-total difference —
        # a double count (or the new worker's series read as phantom
        # history) would bend it
        assert win["request_rate_rps"] == pytest.approx(after - before)
        assert after - before > 0
        assert win["shed_ratio"] == 0.0         # nothing shed
        fleet.close(drain=True, timeout=30.0)
