"""Worker for the true multi-process distributed test (not collected by
pytest — launched as ``python distributed_worker.py <process_id> <port>``
by tests/test_distributed.py with a clean environment).

Each of the two OS processes contributes 2 virtual CPU devices, joins the
JAX distributed runtime through ``pyconsensus_tpu.parallel.initialize``,
and runs ONE event-sharded resolution over the resulting 4-device global
mesh — the collectives cross the process boundary via the gloo CPU
backend, which is how the multi-host claim is validated without a TPU
pod (SURVEY.md §4, §5 distributed rows)."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

process_id, port = int(sys.argv[1]), sys.argv[2]

from pyconsensus_tpu.parallel import initialize  # noqa: E402

initialize(coordinator_address=f"localhost:{port}", num_processes=2,
           process_id=process_id)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from pyconsensus_tpu.models.pipeline import (ConsensusParams,  # noqa: E402
                                             consensus_light_jit)
from pyconsensus_tpu.parallel import make_mesh  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, len(jax.devices())

# the same deterministic matrix on every process (the multi-process
# device_put contract for replicated-from-host inputs)
rng = np.random.default_rng(0)
truth = rng.choice([0.0, 1.0], size=16)
reports = np.tile(truth, (12, 1))
reports[:9] = np.abs(reports[:9] - (rng.random((9, 16)) < 0.1))
reports[9:] = 1.0 - truth

mesh = make_mesh(batch=1, event=4)
x = jax.device_put(jnp.asarray(reports), NamedSharding(mesh, P(None, "event")))
rep = jax.device_put(jnp.full((12,), 1.0 / 12.0), NamedSharding(mesh, P()))
sc = jax.device_put(jnp.zeros((16,), bool), NamedSharding(mesh, P("event")))
mn = jax.device_put(jnp.zeros((16,)), NamedSharding(mesh, P("event")))
mx = jax.device_put(jnp.ones((16,)), NamedSharding(mesh, P("event")))
params = ConsensusParams(algorithm="sztorc", max_iterations=2,
                         pca_method="eigh-gram")
out = consensus_light_jit(x, rep, sc, mn, mx, params)

outcomes = multihost_utils.process_allgather(out["outcomes_adjusted"],
                                             tiled=True)
smooth = np.asarray(out["smooth_rep"])          # replicated -> addressable
print("RESULT", ",".join(f"{float(v):g}" for v in np.ravel(outcomes)),
      flush=True)
print("REP", ",".join(f"{float(v):.6f}" for v in smooth), flush=True)

# optional phase 2: each process computes ITS round-robin share of one
# checkpointed sweep into a shared directory (host_id/n_hosts default to
# jax.process_index/process_count) — the real multi-host story for
# sim.CheckpointedSweep, chunks crossing no process boundary at all
if len(sys.argv) > 3:
    from pyconsensus_tpu.sim import (CheckpointedSweep,  # noqa: E402
                                     CollusionSimulator)

    sim = CollusionSimulator(n_reporters=8, n_events=5, max_iterations=1)
    sweep = CheckpointedSweep(sim, [0.0, 0.3], [0.1], 6, seed=2,
                              checkpoint_dir=sys.argv[3],
                              trials_per_chunk=4)
    print("SWEEP", sweep.run(), flush=True)

    # phase 3: multi-host OUT-OF-CORE streaming — each process streams
    # its round-robin half of the event panels, the R x R sufficient
    # statistics all-reduce across the two processes every iteration,
    # and both return the identical full resolution
    from pyconsensus_tpu.parallel import streaming_consensus  # noqa: E402

    s_out = streaming_consensus(
        reports, panel_events=3,
        params=ConsensusParams(algorithm="sztorc", max_iterations=2),
        n_hosts=2)
    print("STREAM", ",".join(f"{float(v):g}"
                             for v in s_out["outcomes_adjusted"]),
          flush=True)
    print("STREAMREP", ",".join(f"{float(v):.6f}"
                                for v in s_out["smooth_rep"]), flush=True)

    # phase 4: scaled events + power-iteration PCA across processes — the
    # round-2 sharded-median path (effective_median_block forces the
    # unblocked, shard-local median; tests/test_hlo_collectives.py bounds
    # its collectives) running with REAL cross-process gloo collectives,
    # through the sharded_consensus front-end that applies the gating
    from pyconsensus_tpu.parallel import sharded_consensus  # noqa: E402

    reports_sc = reports.copy()
    reports_sc[:, -2:] = np.random.default_rng(42).uniform(0.0, 10.0,
                                                           (12, 2))
    bounds = [None] * 14 + [{"scaled": True, "min": 0.0, "max": 10.0}] * 2
    out_sc = sharded_consensus(
        reports_sc, event_bounds=bounds, mesh=mesh,
        params=ConsensusParams(algorithm="sztorc", max_iterations=2,
                               pca_method="power"))
    sc_all = multihost_utils.process_allgather(out_sc["outcomes_adjusted"],
                                               tiled=True)
    print("SCALED", ",".join(f"{float(v):.10g}" for v in np.ravel(sc_all)),
          flush=True)

    # phase 5: the shard_map fused-kernel path (round 3) with REAL
    # cross-process collectives — int8 sentinel storage decoded in-register
    # per shard, the explicit (R,)/scalar psums crossing the gloo backend
    from pyconsensus_tpu.parallel.fused_sharded import (  # noqa: E402
        fused_sharded_consensus)

    params_f = ConsensusParams(algorithm="sztorc", pca_method="power",
                               power_iters=64, power_tol=0.0,
                               storage_dtype="int8", any_scaled=False,
                               has_na=True, fused_resolution=True)
    out_f = fused_sharded_consensus(x, rep, mesh, params_f)
    f_all = multihost_utils.process_allgather(out_f["outcomes_adjusted"],
                                              tiled=True)
    print("FUSED", ",".join(f"{float(v):g}" for v in np.ravel(f_all)),
          flush=True)
    print("FUSEDREP", ",".join(f"{float(v):.6f}"
                               for v in np.asarray(out_f["smooth_rep"])),
          flush=True)

    # phase 6 (round 4): the hybrid host-clustering path on a
    # MULTI-PROCESS mesh — jitted device phases, the R x R distances
    # replicated across both controllers, each clustering the identical
    # local copy (no broadcast needed; pipeline._consensus_hybrid)
    out_h = sharded_consensus(
        reports, mesh=mesh,
        params=ConsensusParams(algorithm="hierarchical",
                               max_iterations=2))
    h_all = multihost_utils.process_allgather(out_h["outcomes_adjusted"],
                                              tiled=True)
    print("HYBRID", ",".join(f"{float(v):g}" for v in np.ravel(h_all)),
          flush=True)
    print("HYBRIDREP", ",".join(f"{float(v):.6f}"
                                for v in np.asarray(
                                    out_h["smooth_rep"].addressable_data(0))),
          flush=True)

    # phase 7 (round 4): multi-host out-of-core k-means — the one
    # streaming variant whose cross-host state is NOT an R x R statistic:
    # centroid slices stay event-local on the owning host, and the (R, k)
    # distance accumulator all-reduces once per Lloyd assignment pass
    # over the real gloo backend
    k_out = streaming_consensus(
        reports, panel_events=3,
        params=ConsensusParams(algorithm="k-means", num_clusters=3,
                               max_iterations=2),
        n_hosts=2)
    print("KMEANS", ",".join(f"{float(v):g}"
                             for v in k_out["outcomes_adjusted"]),
          flush=True)
    print("KMEANSREP", ",".join(f"{float(v):.6f}"
                                for v in k_out["smooth_rep"]), flush=True)
