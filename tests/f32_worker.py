"""Worker for the f32-mode test (not collected by pytest — launched in a
fresh process by tests/test_f32_mode.py WITHOUT x64 enabled, the precision
the real TPU runs at; the main test session is pinned to f64 by
conftest.py and cannot change precision after jax initializes)."""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64

import numpy as np  # noqa: E402

from pyconsensus_tpu import ALGORITHMS, Oracle  # noqa: E402

CANONICAL = np.array([
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 0.0, 0.0, 0.0],
    [1.0, 1.0, 0.0, 0.0],
    [1.0, 1.0, 1.0, 0.0],
    [0.0, 0.0, 1.0, 1.0],
    [0.0, 0.0, 1.0, 1.0],
])
MISSING = CANONICAL.copy()
MISSING[0, 3] = np.nan
MISSING[4, 0] = np.nan
SCALED = np.array([
    [1.0, 0.5, 0.0, 233.0, 16027.59],
    [1.0, 0.5, 0.0, 199.0, np.nan],
    [1.0, 1.0, 0.0, 233.0, 16027.59],
    [1.0, 0.5, 0.0, 250.0, 0.0],
    [0.0, 0.5, 1.0, 435.8, 8001.0],
    [0.0, 0.5, 1.0, 435.8, 19999.0],
])
BOUNDS = [None, None, None,
          {"scaled": True, "min": 0.0, "max": 435.8},
          {"scaled": True, "min": 0.0, "max": 20000.0}]

out = {}
for algo in ALGORITHMS:
    r = Oracle(reports=CANONICAL, backend="jax", algorithm=algo,
               max_iterations=2).consensus()
    out[f"canonical/{algo}"] = {
        "outcomes": np.asarray(r["events"]["outcomes_final"],
                               dtype=float).tolist(),
        "smooth_rep": np.asarray(r["agents"]["smooth_rep"],
                                 dtype=float).tolist(),
    }
r = Oracle(reports=MISSING, backend="jax", max_iterations=5).consensus()
out["missing/sztorc"] = {
    "outcomes": np.asarray(r["events"]["outcomes_final"],
                           dtype=float).tolist(),
    "smooth_rep": np.asarray(r["agents"]["smooth_rep"],
                             dtype=float).tolist(),
}
r = Oracle(reports=SCALED, event_bounds=BOUNDS, backend="jax").consensus()
out["scaled/sztorc"] = {
    "outcomes": np.asarray(r["events"]["outcomes_final"],
                           dtype=float).tolist(),
    "smooth_rep": np.asarray(r["agents"]["smooth_rep"],
                             dtype=float).tolist(),
}
for pca in ("eigh-gram", "power"):
    r = Oracle(reports=CANONICAL, backend="jax", max_iterations=5,
               pca_method=pca).consensus()
    out[f"canonical-iter5/{pca}"] = {
        "outcomes": np.asarray(r["events"]["outcomes_final"],
                               dtype=float).tolist(),
        "smooth_rep": np.asarray(r["agents"]["smooth_rep"],
                                 dtype=float).tolist(),
    }
print("F32RESULTS " + json.dumps(out))
