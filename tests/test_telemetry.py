"""The fleet-wide telemetry plane (ISSUE 18): cross-process metric
aggregation (``merge_snapshot`` label algebra, the fleet's merged
cluster view, the ``/metrics`` exposition endpoint), wire-propagated
tracing (one span forest across a REAL 2-worker socket fleet's process
boundaries), the windowed SLO monitor's math pinned against hand-built
snapshot fixtures, and the flight recorder's postmortem artifacts —
including the ones a real ``kill -9`` leaves behind."""

import json
import os
import pathlib
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pyconsensus_tpu import obs
from pyconsensus_tpu.obs import (FlightRecorder, MetricsRegistry,
                                 SloMonitor, read_flight_dir,
                                 targets_from_config)

REPO = pathlib.Path(__file__).resolve().parents[1]


# ----------------------------------------------- merged registry algebra


class TestMergedRegistry:
    def test_worker_label_added_and_counters_sum(self):
        """Two per-worker snapshots fold into one registry, every
        series widened by ``worker=<name>``; the merged total is the
        arithmetic sum."""
        merged = MetricsRegistry()
        for name, n in (("w0", 3), ("w1", 5)):
            src = MetricsRegistry()
            src.counter("pyconsensus_serve_requests_total",
                        "requests", labels=("path",)).inc(n, path="resolve")
            merged.merge_snapshot(src.snapshot(), worker=name)
        entry = merged.snapshot()["pyconsensus_serve_requests_total"]
        assert sorted(entry["labels"]) == ["path", "worker"]
        by_worker = {json.loads(k)["worker"]: v
                     for k, v in entry["series"].items()}
        assert by_worker == {"w0": 3.0, "w1": 5.0}
        text = merged.render_prom()
        assert 'worker="w0"' in text and 'worker="w1"' in text

    def test_metric_already_carrying_the_label_keeps_its_own(self):
        """The collision rule: a metric that already has a ``worker``
        label (the router's own per-worker heartbeat histogram) must
        NOT have its series collapsed onto the collector's
        ``worker="router"`` — the series' own label wins."""
        src = MetricsRegistry()
        h = src.histogram("pyconsensus_fleet_heartbeat_seconds",
                          "hb", labels=("worker",),
                          buckets=(0.01, 0.1))
        h.observe(0.002, worker="w0")
        h.observe(0.002, worker="w1")
        merged = MetricsRegistry()
        merged.merge_snapshot(src.snapshot(), worker="router")
        entry = merged.snapshot()["pyconsensus_fleet_heartbeat_seconds"]
        workers = {json.loads(k)["worker"] for k in entry["series"]}
        assert workers == {"w0", "w1"}          # not {"router"}

    def test_histogram_counts_and_gauge_semantics(self):
        """Histograms absorb bucket counts (re-renderable cluster-wide
        quantiles); gauges take the snapshot value."""
        src = MetricsRegistry()
        src.histogram("pyconsensus_serve_request_seconds", "lat",
                      buckets=(0.1, 1.0)).observe(0.05)
        src.gauge("pyconsensus_serve_queue_depth", "depth").set(7)
        merged = MetricsRegistry()
        merged.merge_snapshot(src.snapshot(), worker="w0")
        merged.merge_snapshot(src.snapshot(), worker="w0")  # idempotent kind,
        snap = merged.snapshot()                            # additive counts
        hist = snap["pyconsensus_serve_request_seconds"]
        skey = json.dumps({"worker": "w0"}, sort_keys=True)
        assert hist["series"][skey]["count"] == 2
        assert hist["series"][skey]["counts"][0] == 2
        assert hist["edges"] == [0.1, 1.0]
        assert snap["pyconsensus_serve_queue_depth"]["series"][skey] == 7.0

    def test_metrics_endpoint_golden_scrape(self):
        """`/metrics` over real HTTP: 200 + Prometheus exposition
        content type + HELP/TYPE/sample lines; anything else 404."""
        reg = MetricsRegistry()
        reg.counter("pyconsensus_serve_requests_total", "requests served",
                    labels=("worker",)).inc(4, worker="w0")
        srv = obs.start_metrics_server(0, reg.render_prom)
        assert srv is not None
        try:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            with urllib.request.urlopen(url, timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
                body = resp.read().decode("utf-8")
            assert body == reg.render_prom()    # golden: scrape == render
            assert "# HELP pyconsensus_serve_requests_total" in body
            assert "# TYPE pyconsensus_serve_requests_total counter" in body
            assert 'pyconsensus_serve_requests_total{worker="w0"} 4' in body
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/other", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.close()


# ----------------------------------------------------- SLO window math


def _snap(requests=0.0, shed=0.0, queue=None, counts=None,
          edges=(0.005, 0.05, 0.5)):
    """Hand-built registry snapshot — the monitor reads snapshots, not
    live metrics, exactly so these fixtures drive the real math."""
    snap = {
        "pyconsensus_serve_requests_total": {
            "kind": "counter", "labels": [],
            "series": {"": float(requests)}},
        "pyconsensus_serve_shed_total": {
            "kind": "counter", "labels": [],
            "series": {"": float(shed)}},
    }
    if queue is not None:
        snap["pyconsensus_serve_queue_depth"] = {
            "kind": "gauge", "labels": [], "series": {"": float(queue)}}
    if counts is not None:
        snap["pyconsensus_serve_request_seconds"] = {
            "kind": "histogram", "labels": [], "edges": list(edges),
            "series": {"": {"sum": 0.0, "count": sum(counts),
                            "counts": list(counts)}}}
    return snap


def _feed(monitor, timeline):
    """Drive ``monitor`` through ``[(now, snapshot), ...]`` with an
    explicit deterministic clock."""
    feed = {"snap": {}}
    monitor._snapshot_fn = lambda: feed["snap"]
    for now, snap in timeline:
        feed["snap"] = snap
        monitor.sample(now=now)


class TestSloWindow:
    def test_rate_and_shed_ratio_from_counter_deltas(self):
        m = SloMonitor(window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _snap(requests=0, shed=0, queue=2)),
                  (1.0, _snap(requests=10, shed=1, queue=2))])
        win = m.window()
        assert win["request_rate_rps"] == 10.0
        assert win["shed_ratio"] == 0.1
        assert win["queue_depth"] == 2.0
        assert win["window_s"] == 1.0

    def test_quantiles_from_bucket_count_deltas(self):
        """p50/p99 come from the WINDOW's bucket deltas, hand-checked:
        100 window requests split 90/9/1 over edges 5ms/50ms/500ms →
        nearest-rank p50 = 5ms, p99 = 50ms."""
        m = SloMonitor(window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _snap(counts=[0, 0, 0, 0])),
                  (1.0, _snap(counts=[90, 9, 1, 0]))])
        win = m.window()
        assert win["p50_ms"] == 5.0
        assert win["p99_ms"] == 50.0

    def test_overflow_bucket_reports_overflow(self):
        m = SloMonitor(window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _snap(counts=[0, 0, 0, 0])),
                  (1.0, _snap(counts=[0, 0, 0, 5]))])
        assert m.summary()["p99_ms"] == "overflow"

    def test_metric_born_inside_window_still_quantiles(self):
        """The earliest window sample predates the latency metric's
        first observation — the cumulative distribution IS the window
        and must not be discarded."""
        m = SloMonitor(window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _snap()),                       # no histogram yet
                  (1.0, _snap(counts=[0, 2, 0, 0]))])
        assert m.window()["p50_ms"] == 50.0

    def test_samples_age_out_of_the_window(self):
        m = SloMonitor(window_s=10.0, snapshot_fn=dict)
        _feed(m, [(0.0, _snap(requests=0)),
                  (5.0, _snap(requests=100)),
                  (12.0, _snap(requests=110))])
        win = m.window()
        # the t=0 sample fell out: rate is over [5, 12] only
        assert win["samples"] == 3
        assert win["request_rate_rps"] == round(10 / 7, 3)

    def test_violation_seconds_accumulate_per_target(self):
        """Every second the window spends past a target is charged to
        that target's label — 2s sample gap in violation → 2s."""
        m = SloMonitor(targets={"p99_ms": 10.0}, window_s=60.0,
                       snapshot_fn=dict)
        _feed(m, [(0.0, _snap(counts=[0, 0, 0, 0])),
                  (2.0, _snap(counts=[0, 0, 10, 0]))])  # p99 = 500ms
        s = m.summary()
        assert s["p99_ms"] == 500.0
        assert s["targets"] == {"p99_ms": 10.0}
        assert s["violation_s"]["p99_ms"] == pytest.approx(2.0)
        # the accounting counter is the autoscaler-facing mirror
        assert (obs.value("pyconsensus_slo_violation_seconds",
                          slo="p99_ms") or 0) >= 2.0

    def test_within_target_charges_nothing(self):
        m = SloMonitor(targets={"p99_ms": 1000.0, "shed_ratio": 0.5},
                       window_s=60.0, snapshot_fn=dict)
        _feed(m, [(0.0, _snap(requests=0, counts=[0, 0, 0, 0])),
                  (1.0, _snap(requests=10, counts=[10, 0, 0, 0]))])
        assert m.summary()["violation_s"] == {}

    def test_unknown_target_refused(self):
        with pytest.raises(ValueError, match="p95_ms"):
            SloMonitor(targets={"p95_ms": 1.0})

    def test_targets_from_serve_config(self):
        from pyconsensus_tpu.serve import ServeConfig

        assert targets_from_config(ServeConfig()) == {}
        got = targets_from_config(
            ServeConfig(slo_p99_ms=50.0, slo_shed_ratio=0.01))
        assert got == {"p99_ms": 50.0, "shed_ratio": 0.01}


# ------------------------------------- the real cross-process plane


@pytest.fixture
def router_source():
    old = obs.TRACER.source
    obs.TRACER.source = "router"
    yield
    obs.TRACER.source = old


def test_cross_process_aggregation_and_tracing(tmp_path, router_source):
    """The tentpole end to end over a REAL 2-worker socket fleet: the
    merged cluster view carries worker-labeled series summing to the
    client-observed totals, the merged endpoint scrapes it over HTTP,
    and after shutdown the shipped span files reconstruct ONE forest
    whose router-rooted traces descend into worker processes."""
    from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig
    from pyconsensus_tpu.serve.service import ServeConfig

    log_dir = tmp_path / "fleet"
    fleet = ConsensusFleet(FleetConfig(
        n_workers=2, transport="socket", log_dir=str(log_dir),
        worker=ServeConfig(warmup=(), pallas_buckets=False))).start(
            warmup=False)
    try:
        rng = np.random.default_rng(7)
        matrix = rng.choice([0.0, 1.0], size=(12, 8))
        futs = [fleet.submit(reports=matrix, backend="numpy",
                             tenant="telem") for _ in range(4)]
        for f in futs:
            f.result(timeout=120)
        fleet.check_workers()           # land the heartbeat histogram

        # (a) aggregation: worker-labeled sums match the client's view
        merged = fleet.merged_snapshot()
        req = merged["pyconsensus_serve_requests_total"]["series"]
        worker_sum = sum(
            int(v) for k, v in req.items()
            if (json.loads(k) if k else {}).get("worker", "")
            .startswith("w"))
        assert worker_sum == 4
        hb = merged["pyconsensus_fleet_heartbeat_seconds"]["series"]
        assert {json.loads(k)["worker"]
                for k in hb} >= {"w0", "w1"}

        text = fleet.render_metrics()
        assert "# TYPE pyconsensus_serve_requests_total counter" in text
        assert 'worker="w0"' in text and 'worker="w1"' in text

        # the merged endpoint, scraped over real HTTP mid-run
        srv = obs.start_metrics_server(0, fleet.render_metrics)
        assert srv is not None
        try:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            with urllib.request.urlopen(url, timeout=10) as resp:
                assert resp.status == 200
                body = resp.read().decode("utf-8")
            assert 'worker="w0"' in body and 'worker="w1"' in body
        finally:
            srv.close()
    finally:
        # graceful close: workers write trace-<name>.jsonl on the way out
        fleet.close(drain=True, timeout=60.0)

    # (b) tracing: merge every process's spans into one forest
    trace_files = sorted(str(p) for p in
                         log_dir.glob("*/trace-*.jsonl"))
    assert len(trace_files) == 2
    events = obs.merge_jsonl(trace_files) + list(obs.events())
    forest = obs.trace_forest(events)

    def crosses(node, root_src):
        if node.get("source") != root_src:
            return True
        return any(crosses(c, root_src) for c in node["children"])

    def walk(node):
        yield node
        for c in node["children"]:
            yield from walk(c)

    mine = [r for tid, roots in forest.items()
            for r in roots
            if isinstance(tid, str) and tid.startswith("~telem:")]
    assert len(mine) == 4
    for root in mine:
        assert root["name"] == "fleet.submit"
        assert root["source"] == "router"
        assert crosses(root, "router"), \
            "trace never descended into a worker process"
        spans = list(walk(root))
        # the RPC hop crossed with parentage intact: a worker-side
        # rpc.* dispatch span sits under the router's root
        assert any(s["name"].startswith("rpc.")
                   and s["source"] in ("w0", "w1") for s in spans)


# ------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_ring_deltas_and_dump_tool(self, tmp_path):
        """Artifacts land in the ring with metric DELTAS between dumps;
        the pretty-printer renders them and exits 0."""
        c = obs.counter("pyconsensus_telemetry_probe_total",
                        "test-only counter (never shipped)")
        rec = FlightRecorder(tmp_path / "fr", source="t0")
        with obs.TRACER.span("flightrec.probe"):
            c.inc(3)
        rec.dump("boot")
        c.inc(2)
        rec.dump("shutdown")

        recs = read_flight_dir(tmp_path / "fr")
        assert [r["reason"] for r in recs] == ["boot", "shutdown"]
        assert all(r["format"] == "pyconsensus-flightrec-v1"
                   for r in recs)
        delta = recs[1]["metric_deltas"][
            "pyconsensus_telemetry_probe_total"]
        assert delta["series"][""] == 2.0       # NOT the cumulative 5
        assert any(sp["name"] == "flightrec.probe"
                   for sp in recs[0]["spans"])

        out = subprocess.run(
            [sys.executable, str(REPO / "tools" / "flightrec_dump.py"),
             str(tmp_path / "fr")],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "reason=shutdown" in out.stdout
        assert "pyconsensus_telemetry_probe_total" in out.stdout

    @pytest.mark.slow
    def test_kill9_leaves_postmortem_artifacts(self, tmp_path):
        """A real ``SIGKILL`` mid-fleet: the victim's boot artifact is
        already on disk, and the router's monitor dumps a ``takeover``
        artifact when it declares the death — the black box survives
        the crash it instruments."""
        from pyconsensus_tpu.serve.fleet import (ConsensusFleet,
                                                 FleetConfig)
        from pyconsensus_tpu.serve.service import ServeConfig

        frd = tmp_path / "flightrec"
        fleet = ConsensusFleet(FleetConfig(
            n_workers=3, transport="socket", monitor=True,
            heartbeat_timeout_s=1.0, heartbeat_interval_s=0.25,
            log_dir=str(tmp_path / "fleet"),
            worker=ServeConfig(warmup=(), pallas_buckets=False,
                               flightrec_dir=str(frd)))).start(
                                   warmup=False)
        try:
            owner = fleet.create_session("chaos", n_reporters=12)
            handle = fleet.workers[owner]
            os.kill(handle.process.proc.pid, signal.SIGKILL)
            handle.process.proc.wait(timeout=30)

            deadline = time.monotonic() + 30.0
            takeovers = []
            while time.monotonic() < deadline and not takeovers:
                takeovers = [r for r in read_flight_dir(frd / "router")
                             if r["reason"] == "takeover"]
                time.sleep(0.25)
            assert takeovers, "monitor never dumped a takeover artifact"
            assert takeovers[-1]["source"] == "router"

            boots = [r for r in read_flight_dir(frd / owner)
                     if r["reason"] == "boot"]
            assert boots and boots[0]["source"] == owner

            out = subprocess.run(
                [sys.executable,
                 str(REPO / "tools" / "flightrec_dump.py"),
                 str(frd), "--all"],
                capture_output=True, text=True, timeout=60)
            assert out.returncode == 0, out.stderr
            assert "reason=takeover" in out.stdout
            assert "reason=boot" in out.stdout
        finally:
            fleet.close(drain=False, timeout=10.0)


# ------------------------------------------------------- bench_diff


class TestBenchDiff:
    def _tool(self):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import bench_diff
        finally:
            sys.path.pop(0)
        return bench_diff

    def test_digest_mismatch_always_fails(self, tmp_path, capsys):
        bd = self._tool()
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(
            {"pipeline": {"digest_match": "aaa", "rps": 100.0}}))
        b.write_text(json.dumps(
            {"pipeline": {"digest_match": "bbb", "rps": 100.0}}))
        assert bd.main([str(a), str(b)]) == 1
        assert "DIGEST MISMATCH" in capsys.readouterr().out

    def test_numeric_drift_tolerated_unless_gated(self, tmp_path):
        bd = self._tool()
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({"serve": {"rps": 100.0, "d": "x"}}))
        b.write_text(json.dumps({"serve": {"rps": 350.0, "d": "x"}}))
        assert bd.main([str(a), str(b)]) == 0           # rtol 0.5 default
        assert bd.main([str(a), str(b), "--fail-on-drift"]) == 1
        assert bd.main([str(a), str(b), "--rtol", "5.0",
                        "--fail-on-drift"]) == 0

    def test_bench_wrapper_unwrapped_and_blocks_filter(self, tmp_path):
        bd = self._tool()
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({"parsed": {
            "economy": {"mechanism_digest": "m1"},
            "serve": {"rps": 1.0}}}))
        b.write_text(json.dumps({
            "economy": {"mechanism_digest": "m2"},
            "serve": {"rps": 1.0}}))
        assert bd.main([str(a), str(b)]) == 1           # digest differs
        assert bd.main([str(a), str(b), "--blocks", "serve"]) == 0
