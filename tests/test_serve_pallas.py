"""The ``bucket_pallas`` low-latency serve tier (ISSUE 7 tentpole c).

The fused NaN-threaded pipeline as a cached serve executable class:
kernel-path-keyed executables that can never collide with the padded
XLA buckets, eligibility gated by the fused kernels' VMEM fit
predicates and the small-E class bound, catch-snapped outcomes and
iteration counts bit-identical to a direct Oracle resolution (the tier
runs the Oracle's own fused graph — CPU tests drive the kernels through
the Pallas interpreter with ``pallas_buckets=True``), and the
steady-state retrace pin for the ``serve_bucket_pallas`` entry.
"""

import numpy as np
import pytest

from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.models.pipeline import ConsensusParams
from pyconsensus_tpu.serve import (BucketKey, ConsensusService,
                                   ExecutableCache, PALLAS_KERNEL_PATH,
                                   XLA_KERNEL_PATH, ServeConfig,
                                   pallas_bucket_eligible)
from pyconsensus_tpu.serve.pallas import pallas_bucket_params

#: fused-vs-direct continuous-tail band: the Pallas kernels decode and
#: accumulate in f32 while the x64 test stack's direct Oracle runs f64
#: end to end, so per-reporter scores drift at the f32-kernel class
#: (the sharding suite's 2e-3, plus margin for the f64 reference);
#: outcomes/iterations are bitwise — that is the tier's contract
FUSED_ATOL = 5e-3

_CONT_KEYS = (("agents", "smooth_rep"), ("agents", "this_rep"),
              ("agents", "reporter_bonus"),
              ("events", "certainty"), ("events", "consensus_reward"),
              ("events", "participation_columns"))


def _reports(rng, R=14, E=44, na_frac=0.1):
    reports = rng.choice([0.0, 1.0], size=(R, E))
    reports[rng.random((R, E)) < na_frac] = np.nan
    return reports


def _pallas_cfg(**kw):
    kw.setdefault("pallas_buckets", True)
    return ServeConfig(**kw)


class TestEligibility:
    def test_gate_modes(self):
        import jax

        args = dict(algorithm="sztorc", pca_method="auto",
                    any_scaled=False, storage_dtype="", max_events=4096)
        assert pallas_bucket_eligible(16, 64, mode=True, **args)
        assert not pallas_bucket_eligible(16, 64, mode=False, **args)
        # "auto" requires a TPU backend — this suite runs on CPU
        assert jax.default_backend() != "tpu"
        assert not pallas_bucket_eligible(16, 64, mode="auto", **args)
        with pytest.raises(ValueError):
            pallas_bucket_eligible(16, 64, mode="yes", **args)

    def test_gate_scope(self):
        base = dict(algorithm="sztorc", pca_method="power",
                    any_scaled=False, storage_dtype="", mode=True,
                    max_events=4096)
        assert pallas_bucket_eligible(16, 64, **base)
        # scaled events take the XLA/bucket tiers (the serve tier does
        # not ride the gather-and-fix arm)
        assert not pallas_bucket_eligible(
            16, 64, **{**base, "any_scaled": True})
        # beyond the small-E class bound
        assert not pallas_bucket_eligible(
            16, 8192, **base)
        # non-sztorc / non-power algorithms stay off the fused tier
        assert not pallas_bucket_eligible(
            16, 64, **{**base, "algorithm": "k-means"})
        assert not pallas_bucket_eligible(
            16, 64, **{**base, "pca_method": "eigh"})
        # VMEM misfit at huge padded R (resolve_kernel_fits' bound)
        assert not pallas_bucket_eligible(60_000, 64, **base)

    def test_default_config_off_tpu_stays_xla(self, rng):
        """``pallas_buckets="auto"`` on a CPU host must not change any
        pre-existing routing: the request lands on the padded XLA
        bucket path exactly as before ISSUE 7."""
        obs.reset()
        reports = _reports(rng)
        with ConsensusService(ServeConfig()) as svc:
            res = svc.submit(reports=reports).result(120)
        assert res["iterations"] >= 1
        snap = obs.REGISTRY.snapshot().get(
            "pyconsensus_serve_requests_total", {}).get("series", {})
        assert not any("bucket_pallas" in k for k in snap)


class TestBucketKey:
    def test_kernel_path_dimension(self):
        p = ConsensusParams(algorithm="sztorc", pca_method="power")
        xla = BucketKey.make(16, 64, 8, p)
        pal = BucketKey.make(16, 64, 8, p, kernel_path=PALLAS_KERNEL_PATH)
        assert xla.kernel_path == XLA_KERNEL_PATH
        assert pal.kernel_path == PALLAS_KERNEL_PATH
        assert xla != pal          # same shape+params, distinct entries

    def test_cache_never_collides_across_kernel_paths(self):
        cache = ExecutableCache(capacity=4)
        p_xla = ConsensusParams(algorithm="sztorc", pca_method="power",
                                has_na=True, any_scaled=False)
        p_pal = pallas_bucket_params(True, {}, ())
        k_xla = BucketKey.make(8, 16, 1, p_xla)
        k_pal = BucketKey.make(8, 16, 1, p_pal,
                               kernel_path=PALLAS_KERNEL_PATH)
        e1, e2 = cache.get(k_xla), cache.get(k_pal)
        assert e1 is not e2
        assert len(cache) == 2
        assert cache.get(k_pal) is e2       # hit, not a rebuild

    def test_pallas_key_rejects_mesh_topology(self):
        cache = ExecutableCache(capacity=4)
        p_pal = pallas_bucket_params(True, {}, ())
        bad = BucketKey.make(8, 16, 1, p_pal, topology="TPU-v5e:2x4",
                             kernel_path=PALLAS_KERNEL_PATH)
        with pytest.raises(ValueError, match="single-topology"):
            cache.get(bad)

    def test_unknown_kernel_path_rejected(self):
        cache = ExecutableCache(capacity=4)
        p = ConsensusParams(algorithm="sztorc", pca_method="power")
        bad = BucketKey.make(8, 16, 1, p, kernel_path="mosaic2")
        with pytest.raises(ValueError, match="unknown bucket kernel"):
            cache.get(bad)

    def test_pallas_executable_requires_fused_params(self):
        from pyconsensus_tpu.serve import make_pallas_bucket_executable

        p = ConsensusParams(algorithm="sztorc", pca_method="power")
        with pytest.raises(ValueError, match="fused_resolution"):
            make_pallas_bucket_executable(p)


class TestPallasTierParity:
    @pytest.mark.parametrize("max_iterations", [1, 3])
    def test_outcomes_bitwise_vs_direct_oracle(self, rng, max_iterations):
        """The tier's contract (ISSUE 7 acceptance): catch-snapped
        outcomes and iteration counts bit-identical to a direct Oracle
        resolution; continuous tails in the documented fused-vs-XLA
        band."""
        reports = _reports(rng)
        with ConsensusService(_pallas_cfg()) as svc:
            got = svc.submit(reports=reports,
                             max_iterations=max_iterations).result(120)
        ref = Oracle(reports=reports,
                     max_iterations=max_iterations).consensus()
        np.testing.assert_array_equal(
            np.asarray(got["events"]["outcomes_adjusted"]),
            np.asarray(ref["events"]["outcomes_adjusted"]))
        np.testing.assert_array_equal(
            np.asarray(got["events"]["outcomes_final"]),
            np.asarray(ref["events"]["outcomes_final"]))
        assert got["iterations"] == ref["iterations"]
        assert got["convergence"] == ref["convergence"]
        for section, key in _CONT_KEYS:
            np.testing.assert_allclose(
                np.asarray(got[section][key]),
                np.asarray(ref[section][key]), atol=FUSED_ATOL,
                err_msg=f"{section}.{key}")

    def test_dense_request(self, rng):
        reports = _reports(rng, na_frac=0.0)
        with ConsensusService(_pallas_cfg()) as svc:
            got = svc.submit(reports=reports).result(120)
        ref = Oracle(reports=reports).consensus()
        np.testing.assert_array_equal(
            np.asarray(got["events"]["outcomes_adjusted"]),
            np.asarray(ref["events"]["outcomes_adjusted"]))
        assert got["iterations"] == ref["iterations"]

    def test_repeat_dispatch_bitwise_and_retrace_pinned(self, rng):
        """Serving determinism + the runtime CL304 pin: the same request
        twice is bit-identical everywhere, and the second dispatch rides
        the cached executable (serve_bucket_pallas retraces stay at the
        number of cached Pallas executables)."""
        obs.reset()
        reports = _reports(rng)
        with ConsensusService(_pallas_cfg()) as svc:
            a = svc.submit(reports=reports).result(120)
            b = svc.submit(reports=reports).result(120)
            cached = len(svc.cache)
        for section in ("agents", "events"):
            for key in a[section]:
                np.testing.assert_array_equal(
                    np.asarray(a[section][key]),
                    np.asarray(b[section][key]),
                    err_msg=f"{section}.{key}")
        assert cached == 1
        assert obs.value("pyconsensus_jit_retraces_total",
                         entry="serve_bucket_pallas") == 1

    def test_kernel_path_counter_and_request_labels(self, rng):
        obs.reset()
        reports = _reports(rng)
        with ConsensusService(_pallas_cfg()) as svc:
            svc.submit(reports=reports).result(120)
        assert obs.value("pyconsensus_kernel_path_total",
                         path="pallas") >= 1
        assert obs.value("pyconsensus_serve_requests_total",
                         path="bucket_pallas", outcome="ok") == 1

    def test_two_shapes_two_executables(self, rng):
        """Exact-shape keying: two request shapes are two cache entries
        (the documented latency-tier trade), both served."""
        with ConsensusService(_pallas_cfg()) as svc:
            svc.submit(reports=_reports(rng, R=10, E=24)).result(120)
            svc.submit(reports=_reports(rng, R=12, E=32)).result(120)
            assert len(svc.cache) == 2

    def test_int8_storage_request_rides_pallas(self, rng):
        """int8 sentinel storage is the fused tier's native encoding —
        a binary request asking for it must ride bucket_pallas (the
        padded XLA bucket refuses int8), with outcomes equal to the
        f32 Oracle."""
        obs.reset()
        reports = _reports(rng)
        with ConsensusService(_pallas_cfg()) as svc:
            got = svc.submit(reports=reports,
                             storage_dtype="int8").result(120)
        ref = Oracle(reports=reports).consensus()
        np.testing.assert_array_equal(
            np.asarray(got["events"]["outcomes_adjusted"]),
            np.asarray(ref["events"]["outcomes_adjusted"]))
        assert obs.value("pyconsensus_serve_requests_total",
                         path="bucket_pallas", outcome="ok") >= 1

    def test_scaled_request_falls_back(self, rng):
        """A scaled-event request must NOT ride the fused tier (binary
        scope) — it lands on another path and still resolves."""
        obs.reset()
        reports = _reports(rng, R=10, E=16, na_frac=0.0)
        bounds = [None] * 15 + [{"scaled": True, "min": 0.0, "max": 10.0}]
        reports[:, -1] = np.round(reports[:, -1] * 10)
        with ConsensusService(_pallas_cfg()) as svc:
            got = svc.submit(reports=reports,
                             event_bounds=bounds).result(120)
        snap = obs.REGISTRY.snapshot().get(
            "pyconsensus_serve_requests_total", {}).get("series", {})
        assert not any("bucket_pallas" in k for k in snap)
        assert got["iterations"] >= 1


class TestGroupFailure:
    def test_dispatch_pallas_resolves_every_waiter_on_failure(self):
        """A dispatch failure must resolve EVERY waiter in the group —
        the tail after the failing request must not hang to its
        timeouts (the _dispatch_bucket rule; review finding, ISSUE 7).
        batch=1 keys make multi-request groups unreachable today, but
        the handler claims to tolerate them defensively."""
        from pyconsensus_tpu.serve.batcher import Microbatcher
        from pyconsensus_tpu.serve.queue import ResolveRequest

        class BoomCache:
            def get(self, key):
                raise RuntimeError("compile exploded")

        p = pallas_bucket_params(True, {}, ())
        key = BucketKey.make(4, 8, 1, p, kernel_path=PALLAS_KERNEL_PATH)
        reqs = []
        for _ in range(2):
            r = ResolveRequest(reports=np.zeros((4, 8)))
            r.reputation = np.full(4, 0.25)
            r.scaled = np.zeros(8, bool)
            r.mins, r.maxs = np.zeros(8), np.ones(8)
            r.batch_key = key
            reqs.append(r)
        mb = Microbatcher(queue=None, cache=BoomCache(), config=None,
                          sessions=None, admission=None)
        with pytest.raises(RuntimeError, match="compile exploded"):
            mb._dispatch_pallas(key, reqs)
        for r in reqs:
            assert r.future.done()
            with pytest.raises(RuntimeError, match="compile exploded"):
                r.future.result(timeout=0)


class TestWarmupAndConfig:
    def test_pallas_warmup_preflight(self):
        obs.reset()
        cfg = _pallas_cfg(pallas_warmup=((12, 24),), warmup=())
        svc = ConsensusService(cfg)
        n = svc.warm_buckets()
        assert n == 1
        assert len(svc.cache) == 1
        key = svc.cache.keys()[0]
        assert key.kernel_path == PALLAS_KERNEL_PATH
        assert (key.rows, key.events, key.batch) == (12, 24, 1)
        assert obs.value("pyconsensus_jit_retraces_total",
                         entry="serve_bucket_pallas") == 1

    def test_config_json_round_trip(self, tmp_path):
        import json

        path = tmp_path / "serve.json"
        path.write_text(json.dumps({
            "pallas_buckets": True, "pallas_max_events": 512,
            "pallas_warmup": [[12, 24]]}))
        cfg = ServeConfig.load(path)
        assert cfg.pallas_buckets is True
        assert cfg.pallas_max_events == 512
        assert cfg.pallas_warmup == ((12, 24),)

    def test_bad_mode_raises_at_submit(self, rng):
        with ConsensusService(ServeConfig(pallas_buckets="never")) as svc:
            with pytest.raises(Exception):
                svc.submit(reports=_reports(rng)).result(120)
