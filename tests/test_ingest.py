"""Device-resident ingestion (ISSUE 13 tentpole a).

Covers the device/host encoder bit-identity contract
(``encode_reports_device`` vs ``encode_reports_host`` — the pin the
tentpole names), the ``lattice_exact`` staging gate, the event-sharded
``load_reports_encoded`` loader, and the market session's encoded
device-resident staging (resolves bit-identical to float staging for
lattice panels; off-lattice blocks keep the float path).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import collusion_reports
from pyconsensus_tpu import io as pio
from pyconsensus_tpu import obs
from pyconsensus_tpu.models.pipeline import (decode_reports,
                                             encode_reports,
                                             encode_reports_device,
                                             encode_reports_host,
                                             lattice_exact)
from pyconsensus_tpu.serve.session import MarketSession


def lattice_panel(rng, R=24, E=64, na_frac=0.1):
    m = rng.choice([0.0, 0.5, 1.0], size=(R, E))
    m[rng.random((R, E)) < na_frac] = np.nan
    return m


class TestEncoderParity:
    """The tentpole's pin: device and host encoders are bit-identical
    on the same-dtype input."""

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_lattice_parity(self, rng, dtype):
        panel = lattice_panel(rng).astype(dtype)
        host = encode_reports_host(panel)
        dev = np.asarray(encode_reports_device(jnp.asarray(panel)))
        np.testing.assert_array_equal(host, dev)
        assert host.dtype == np.int8

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_off_lattice_rounding_parity(self, rng, dtype):
        """Off-lattice values round onto the lattice identically on
        both paths (clip + round-half-to-even, per input dtype)."""
        panel = (rng.random((32, 48)) * 1.6 - 0.3).astype(dtype)
        panel[rng.random((32, 48)) < 0.1] = np.nan
        host = encode_reports_host(panel)
        dev = np.asarray(encode_reports_device(jnp.asarray(panel)))
        np.testing.assert_array_equal(host, dev)

    def test_parity_with_traceable_core(self, rng):
        """Both front doors agree with the raw traceable encode the
        fused pipeline already uses."""
        panel = lattice_panel(rng)
        np.testing.assert_array_equal(
            encode_reports_host(panel),
            np.asarray(encode_reports(jnp.asarray(panel))))

    def test_decode_round_trip_exact(self, rng):
        panel = lattice_panel(rng)
        dec = decode_reports(encode_reports_host(panel))
        np.testing.assert_array_equal(dec, panel)

    def test_ingest_metrics_emitted(self, rng):
        before_d = obs.value("pyconsensus_ingest_encoded_bytes_total",
                             path="device") or 0
        before_h = obs.value("pyconsensus_ingest_encodes_total",
                             path="host") or 0
        panel = lattice_panel(rng, R=8, E=16)
        encode_reports_device(jnp.asarray(panel))
        encode_reports_host(panel)
        after_d = obs.value("pyconsensus_ingest_encoded_bytes_total",
                            path="device") or 0
        assert after_d - before_d == panel.size
        assert (obs.value("pyconsensus_ingest_encodes_total",
                          path="host") or 0) == before_h + 1

    def test_retrace_pinned_across_same_shape_encodes(self, rng):
        """The shared jitted encode entry compiles once per shape —
        repeated ingests at one shape must not grow the retrace
        counter."""
        panel = lattice_panel(rng, R=16, E=32)
        encode_reports_device(jnp.asarray(panel))
        before = obs.value("pyconsensus_jit_retraces_total",
                           entry="encode_reports") or 0
        for _ in range(3):
            encode_reports_device(jnp.asarray(lattice_panel(
                np.random.default_rng(7), R=16, E=32)))
        after = obs.value("pyconsensus_jit_retraces_total",
                          entry="encode_reports") or 0
        assert after == before


class TestLatticeGate:
    def test_lattice_values_pass(self):
        assert lattice_exact(np.array([[0.0, 0.5, 1.0, np.nan]]))

    @pytest.mark.parametrize("bad", [0.25, -0.5, 2.0, np.inf, -np.inf,
                                     1.0 + 1e-12])
    def test_off_lattice_refused(self, bad):
        assert not lattice_exact(np.array([[0.0, bad]]))

    def test_negative_zero_refused(self):
        """-0.0 is observably different downstream (sign of zero
        products) and the lattice only carries +0.0."""
        assert not lattice_exact(np.array([[-0.0, 1.0]]))

    def test_empty_is_exact(self):
        assert lattice_exact(np.zeros((0, 4)))


class TestEncodedLoader:
    def test_loader_matches_host_encode(self, rng, tmp_path):
        panel = lattice_panel(rng, R=16, E=64)
        path = tmp_path / "reports.npy"
        pio.save_reports(path, panel)
        enc = pio.load_reports_encoded(path)
        assert np.asarray(enc).dtype == np.int8
        np.testing.assert_array_equal(
            np.asarray(enc), encode_reports_host(panel))

    def test_loader_keeps_event_sharding(self, rng, tmp_path):
        import jax

        from pyconsensus_tpu.parallel.mesh import make_mesh

        n = len(jax.devices())
        mesh = make_mesh(batch=1, event=n)
        panel = lattice_panel(rng, R=8, E=8 * n)
        path = tmp_path / "reports.npy"
        pio.save_reports(path, panel)
        enc = pio.load_reports_encoded(path, mesh=mesh)
        assert enc.shape == panel.shape
        # the encode is elementwise: the event axis stays sharded
        assert len(enc.sharding.device_set) == n
        np.testing.assert_array_equal(
            np.asarray(enc), encode_reports_host(panel))


class TestSessionEncodedStaging:
    """ISSUE 13: lattice-exact appended blocks stage as device-resident
    int8; resolves are bit-identical to the float-staged session."""

    def _rounds(self, seed, R=12, widths=(16, 8, 24)):
        g = np.random.default_rng(seed)
        return [lattice_panel(g, R=R, E=w) for w in widths]

    def _run(self, blocks, **kw):
        s = MarketSession("m", blocks[0].shape[0], **kw)
        results = []
        for b in blocks:
            s.append(b)
            results.append(s.resolve())
        return s, results

    def test_staging_forms(self, rng):
        s = MarketSession("m", 8)
        s.append(lattice_panel(rng, R=8, E=8))
        assert s._blocks[0].dtype == np.int8        # device-resident
        s.append(rng.random((8, 4)))                # off-lattice
        assert s._blocks[1].dtype == np.float64     # float staging
        s2 = MarketSession("m2", 8, encoded_staging=False)
        s2.append(lattice_panel(rng, R=8, E=8))
        assert s2._blocks[0].dtype == np.float64

    def test_stats_resolve_bitwise_vs_float_staging(self):
        blocks = self._rounds(3)
        _, enc = self._run(blocks)
        _, flo = self._run(blocks, encoded_staging=False)
        for a, b in zip(enc, flo):
            assert a.keys() == b.keys()
            for k in a:
                np.testing.assert_array_equal(
                    np.asarray(a[k]), np.asarray(b[k]), err_msg=k)

    def test_direct_resolve_bitwise_vs_float_staging(self):
        """The assembled/direct path decodes staged blocks back to the
        exact host float panel."""
        blocks = self._rounds(4, widths=(12, 12))
        s_enc = MarketSession("a", 12)
        s_flo = MarketSession("b", 12, encoded_staging=False)
        for b in blocks:
            s_enc.append(b)
            s_flo.append(b)
        r_enc = s_enc.resolve(max_iterations=3)     # direct Oracle path
        r_flo = s_flo.resolve(max_iterations=3)
        for k in r_enc:
            np.testing.assert_array_equal(
                np.asarray(r_enc[k]), np.asarray(r_flo[k]), err_msg=k)

    def test_peek_resolve_on_encoded_staging(self):
        blocks = self._rounds(5, widths=(16,))
        s = MarketSession("m", 12)
        s.append(blocks[0])
        peek = s.peek_resolve()
        res = s.resolve()
        for k in ("outcomes_adjusted", "smooth_rep", "certainty"):
            np.testing.assert_array_equal(np.asarray(peek[k]),
                                          np.asarray(res[k]))

    def test_incremental_session_rides_encoded_staging(self):
        """The warm tier and encoded staging compose: warm resolves on
        encoded-staged rounds match the float-staged session's bits."""
        blocks = self._rounds(6, widths=(16, 16, 16, 16))
        _, enc = self._run(blocks, incremental=True, refresh_every=3)
        _, flo = self._run(blocks, incremental=True, refresh_every=3,
                           encoded_staging=False)
        for a, b in zip(enc, flo):
            for k in a:
                np.testing.assert_array_equal(
                    np.asarray(a[k]), np.asarray(b[k]), err_msg=k)

    def test_service_session_bitwise_vs_oracle(self, rng):
        """End to end through the service: an encoded-staged session
        resolve matches a direct streaming-equivalent resolution (the
        existing session contract, now over int8-staged blocks)."""
        from pyconsensus_tpu.parallel.streaming import streaming_consensus

        R = 10
        b1 = lattice_panel(rng, R=R, E=12)
        b2 = lattice_panel(rng, R=R, E=20)
        s = MarketSession("m", R)
        s.append(b1)
        s.append(b2)
        assert all(b.dtype == np.int8 for b in s._blocks)
        res = s.resolve()
        ref = streaming_consensus(np.concatenate([b1, b2], axis=1),
                                  panel_events=12)
        np.testing.assert_array_equal(res["outcomes_adjusted"],
                                      np.asarray(ref["outcomes_adjusted"]))

    def test_mixed_staging_round(self, rng):
        """A round mixing encoded and float-staged blocks resolves
        bit-identically to the all-float session."""
        R = 8
        lat = lattice_panel(rng, R=R, E=8)
        off = rng.random((R, 6)) * 0.9
        a = MarketSession("a", R)
        b = MarketSession("b", R, encoded_staging=False)
        for s in (a, b):
            s.append(lat)
            s.append(off)
        assert a._blocks[0].dtype == np.int8
        assert a._blocks[1].dtype == np.float64
        ra, rb = a.resolve(), b.resolve()
        for k in ra:
            np.testing.assert_array_equal(np.asarray(ra[k]),
                                          np.asarray(rb[k]), err_msg=k)


class TestCollusionPanelStaging:
    def test_collusion_panel_is_lattice_exact(self, rng):
        reports, _ = collusion_reports(rng, 16, 32, liars=4, na_frac=0.1)
        assert lattice_exact(reports)
