"""``bucket_incremental`` — O(update) marginal resolves (ISSUE 12).

Covers the warm-eigenpair algebra (``gram_warm_pc`` /
``gram_top_components``'s warm-start + delta forms), the staleness-bound
contract (property sweep over appended-block size × refresh cadence:
catch-snapped outcomes + iteration counts bit-identical at every exact
refresh — against the non-incremental session AND against direct Oracle
on both backends — with continuous drift ≤ the documented band between
refreshes), the serve-tier integration (``bucket_incremental`` dispatch
path, kernel-path counter, ``serve_bucket_incremental`` retrace pin,
PYC101 cadence validation, CLI opt-outs), and durability (warm
eigenstate through ``state()``/ledger aux, replication-log replay
bit-identical after a real mid-round SIGKILL, fleet takeover of a warm
session).
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from conftest import collusion_reports, worker_env
from fleet_worker import BLOCKS_PER_ROUND, N_REPORTERS, make_block
from pyconsensus_tpu import Oracle, ReputationLedger, obs
from pyconsensus_tpu.faults import CheckpointCorruptionError, InputError
from pyconsensus_tpu.serve import (ConsensusFleet, ConsensusService,
                                   DurableSession, FleetConfig,
                                   MarketSession, ServeConfig,
                                   replay_session)
from pyconsensus_tpu.serve.incremental import (INCREMENTAL_KERNEL_PATH,
                                               incremental_drift_band,
                                               incremental_params)


@pytest.fixture(autouse=True)
def _under_lock_witness(lock_witness):
    """Incremental-tier tests run under the runtime lock witness
    (ISSUE 9), like the rest of the serve/fleet suites."""
    yield


#: continuous result keys the drift band covers
CONT_KEYS = ("smooth_rep", "this_rep", "certainty", "consensus_reward",
             "reporter_bonus", "author_bonus", "first_loading")


def band():
    import jax.numpy as jnp

    return incremental_drift_band(jnp.asarray(0.0).dtype)


def blk(R, e, seed, na_frac=0.1):
    r = np.random.default_rng(seed)
    b = r.choice([0.0, 1.0], size=(R, e)).astype(np.float64)
    if na_frac:
        b[r.random((R, e)) < na_frac] = np.nan
    return b


def drift_between(a, b):
    return max(float(np.max(np.abs(np.asarray(a[k]) - np.asarray(b[k]))))
               for k in CONT_KEYS)


# -- the warm-eigenpair algebra (parallel.streaming) -----------------------


class TestWarmAlgebra:
    def _stats(self, rng, R=16, E=64):
        import jax.numpy as jnp

        from pyconsensus_tpu.parallel.streaming import _pass1_panel

        reports, _ = collusion_reports(rng, R, E, liars=4, na_frac=0.1)
        rep = jnp.full((R,), 1.0 / R)
        G, M, S = _pass1_panel(
            jnp.asarray(reports), rep, rep, jnp.zeros(E, bool),
            jnp.zeros(E), jnp.ones(E), jnp.ones(E, bool), 0.1, True)
        return G, M, S, rep

    def test_delta_form_equals_materialized_update(self, rng):
        """gram_top_components(delta=(dG, dM)) == the solve over G+dG,
        M+dM — the appended-block low-rank form is pure restructuring."""
        from pyconsensus_tpu.parallel.streaming import gram_top_components

        G, M, _, rep = self._stats(rng)
        dG, dM, _, _ = self._stats(np.random.default_rng(7))
        a = gram_top_components(G + dG, M + dM, rep, 2)
        b = gram_top_components(G, M, rep, 2, delta=(dG, dM))
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_warm_start_converges_to_eigh_direction(self, rng):
        """The warm-started power path lands on the eigh dominant
        eigenvector (up to sign) well inside the drift band's scale,
        even from a deliberately stale start."""
        from pyconsensus_tpu.parallel.streaming import (gram_top_components,
                                                        gram_warm_pc)

        G, M, _, rep = self._stats(rng)
        _, _, U, _ = gram_top_components(G, M, rep, 1)
        exact = np.asarray(U[:, 0])
        stale = exact + 0.05 * rng.standard_normal(exact.shape)
        import jax.numpy as jnp

        u, sweeps = gram_warm_pc(G, rep, jnp.asarray(stale),
                                 n_iters=incremental_params(
                                     0.1, 0.1, 1e-6).power_iters)
        align = abs(float(np.asarray(u) @ exact))
        assert align >= 1.0 - 1e-9
        assert int(sweeps) > 0

    def test_warm_scores_match_eigh_scores_closely(self, rng):
        from pyconsensus_tpu.parallel.streaming import gram_top_components

        G, M, _, rep = self._stats(rng)
        s_exact, _, U, _ = gram_top_components(G, M, rep, 1)
        s_warm, _, Uw, _ = gram_top_components(
            G, M, rep, 1, warm_u=U[:, 0],
            warm_iters=incremental_params(0.1, 0.1, 1e-6).power_iters)
        # canonical signs may differ; compare up to sign
        a, b = np.asarray(s_exact[:, 0]), np.asarray(s_warm[:, 0])
        if float(a @ b) < 0:
            b = -b
        np.testing.assert_allclose(a, b, atol=band(), rtol=0)

    def test_warm_start_requires_k1(self, rng):
        from pyconsensus_tpu.parallel.streaming import gram_top_components

        G, M, _, rep = self._stats(rng)
        with pytest.raises(ValueError, match="k=1"):
            gram_top_components(G, M, rep, 2, warm_u=G[:, 0])


# -- the staleness-bound contract ------------------------------------------


class TestStalenessContract:
    def test_refresh_every_one_is_bitwise_the_plain_session(self, rng):
        """K=1 never engages the warm kernel: every resolve is the
        exact anchor, bit-identical to a non-incremental session —
        injecting the tier's machinery must not move a single bit."""
        R = 12
        plain = MarketSession("p", R)
        inc = MarketSession("i", R, incremental=True, refresh_every=1)
        for k in range(3):
            b = blk(R, 10, 100 + k)
            plain.append(b)
            inc.append(b)
            a, c = plain.resolve(), inc.resolve()
            for key in ("smooth_rep", "outcomes_adjusted",
                        "outcomes_final", "certainty", "iterations"):
                np.testing.assert_array_equal(np.asarray(a[key]),
                                              np.asarray(c[key]))
            assert inc.last_resolve_path == "incremental_exact"

    @pytest.mark.parametrize("block_events", [1, 6, 24])
    @pytest.mark.parametrize("refresh_every", [2, 3, 5])
    def test_drift_band_and_refresh_bitwise(self, rng, block_events,
                                            refresh_every):
        """The contract property sweep (appended-block size × cadence):
        warm rounds stay within the documented band of the exact
        resolve of the SAME statistics (``peek_resolve``) with snapped
        outcomes + iteration counts identical; exact-refresh rounds run
        the exact arithmetic bit-identically (the carried reputation
        diverges from a never-warm twin only within the band, which is
        precisely what the contract bounds — cross-trajectory bitwise
        equality is the K=1 case, pinned separately)."""
        R = 14
        inc = MarketSession("inc", R, incremental=True,
                            refresh_every=refresh_every)
        saw_warm = saw_refresh = False
        for k in range(2 * refresh_every + 1):
            b = blk(R, block_events, 31 * block_events + k)
            inc.append(b)
            exact_same_stats = inc.peek_resolve()
            got = inc.resolve()
            if inc.last_resolve_path == "incremental":
                saw_warm = True
                assert drift_between(got, exact_same_stats) <= band()
                np.testing.assert_array_equal(
                    got["outcomes_adjusted"],
                    exact_same_stats["outcomes_adjusted"])
                assert got["iterations"] == \
                    exact_same_stats["iterations"] == 1
            else:
                saw_refresh = True
                assert inc.last_resolve_path == "incremental_exact"
                # an anchor round runs the exact arithmetic on its own
                # statistics: identical to the peek of the same stats
                for key in ("smooth_rep", "outcomes_adjusted",
                            "certainty", "iterations"):
                    np.testing.assert_array_equal(
                        np.asarray(got[key]),
                        np.asarray(exact_same_stats[key]))
        assert saw_refresh
        assert saw_warm == (refresh_every > 1)

    def test_exact_refresh_bitwise_vs_oracle_both_backends(self, rng):
        """At every exact-refresh round the incremental session's
        catch-snapped outcomes + iteration count equal a direct Oracle
        resolution of the staged round under the carried reputation —
        on BOTH backends (the repo's cross-backend snap-parity class)."""
        R = 12
        sess = MarketSession("m", R, incremental=True, refresh_every=2)
        for k in range(4):
            b = blk(R, 16, 900 + k)
            rep_in = sess.reputation.copy()
            sess.append(b)
            got = sess.resolve()
            if sess.last_resolve_path != "incremental_exact":
                continue
            for backend in ("jax", "numpy"):
                ref = Oracle(reports=b, reputation=rep_in,
                             backend=backend).consensus()
                np.testing.assert_array_equal(
                    got["outcomes_adjusted"],
                    np.asarray(ref["events"]["outcomes_adjusted"]),
                    err_msg=f"round {k} backend {backend}")
                assert got["iterations"] == ref["iterations"]

    def test_cadence_state_and_counters(self, rng):
        R = 10
        before_w = obs.value("pyconsensus_incremental_resolves_total",
                             mode="warm") or 0
        before_e = obs.value("pyconsensus_incremental_resolves_total",
                             mode="exact") or 0
        before_k = obs.value("pyconsensus_kernel_path_total",
                             path=INCREMENTAL_KERNEL_PATH) or 0
        sess = MarketSession("m", R, incremental=True, refresh_every=3)
        expect = ["incremental_exact", "incremental", "incremental",
                  "incremental_exact", "incremental"]
        ages = [0, 1, 2, 0, 1]
        for k, (path, age) in enumerate(zip(expect, ages)):
            sess.append(blk(R, 8, 50 + k))
            sess.resolve()
            assert sess.last_resolve_path == path
            st = sess.state()["incremental"]
            assert st["enabled"] and st["refresh_every"] == 3
            assert st["rounds_since_exact"] == age
            assert st["has_warm_start"]
            assert st["warm_u"].shape == (R,)
        warm = (obs.value("pyconsensus_incremental_resolves_total",
                          mode="warm") or 0) - before_w
        exact = (obs.value("pyconsensus_incremental_resolves_total",
                           mode="exact") or 0) - before_e
        kp = (obs.value("pyconsensus_kernel_path_total",
                        path=INCREMENTAL_KERNEL_PATH) or 0) - before_k
        assert (warm, exact) == (3, 2)
        assert kp == 3
        assert obs.value("pyconsensus_incremental_drift") is not None

    def test_direct_resolve_invalidates_warm_state(self, rng):
        """A non-stats resolve (full Oracle fallback) leaves no valid
        eigenstate: the next stats resolve must be an exact anchor."""
        R = 10
        sess = MarketSession("m", R, incremental=True, refresh_every=4)
        sess.append(blk(R, 8, 1))
        sess.resolve()
        sess.append(blk(R, 8, 2))
        sess.resolve(max_iterations=3)          # direct path
        assert sess.last_resolve_path == "direct"
        assert not sess.state()["incremental"]["has_warm_start"]
        sess.append(blk(R, 8, 3))
        sess.resolve()
        assert sess.last_resolve_path == "incremental_exact"

    def test_peek_resolve_mutates_nothing(self, rng):
        R = 10
        sess = MarketSession("m", R, incremental=True, refresh_every=4)
        sess.append(blk(R, 8, 1))
        st0 = sess.state()
        first = sess.peek_resolve()
        again = sess.peek_resolve()
        st1 = sess.state()
        assert st0["rounds_resolved"] == st1["rounds_resolved"] == 0
        assert st0["staged_blocks"] == st1["staged_blocks"] == 1
        assert sess.last_resolve_path is None
        np.testing.assert_array_equal(first["smooth_rep"],
                                      again["smooth_rep"])


# -- serve-tier integration ------------------------------------------------


class TestServiceTier:
    def test_sessions_ride_bucket_incremental(self, rng):
        R = 12
        base_req = obs.value("pyconsensus_serve_requests_total",
                             path="bucket_incremental",
                             outcome="ok") or 0
        base_re = obs.value("pyconsensus_jit_retraces_total",
                            entry="serve_bucket_incremental") or 0
        svc = ConsensusService(ServeConfig(
            incremental_sessions=True, incremental_refresh_every=3,
            batch_window_ms=1.0)).start(warmup=False)
        svc.create_session("m", n_reporters=R)
        paths = []
        for k in range(4):
            svc.append("m", blk(R, 8, 70 + k))
            svc.submit(session="m").result(timeout=120)
            paths.append(svc.sessions.get("m").last_resolve_path)
        svc.close(drain=True)
        assert paths == ["incremental_exact", "incremental",
                         "incremental", "incremental_exact"]
        got = (obs.value("pyconsensus_serve_requests_total",
                         path="bucket_incremental",
                         outcome="ok") or 0) - base_req
        assert got == 4
        # steady-state retrace pin: ONE compile for the (roster,
        # params) key, flat across every subsequent marginal resolve
        retraces = (obs.value("pyconsensus_jit_retraces_total",
                              entry="serve_bucket_incremental") or 0) \
            - base_re
        assert retraces == 1

    def test_incremental_executables_live_in_the_cache(self, rng):
        from pyconsensus_tpu.serve import BucketKey
        from pyconsensus_tpu.serve.sharded import SINGLE_TOPOLOGY

        svc = ConsensusService(ServeConfig(
            incremental_sessions=True, batch_window_ms=1.0))
        svc.create_session("m", n_reporters=10)
        svc.append("m", blk(10, 8, 3))
        svc.start(warmup=False)
        svc.submit(session="m").result(timeout=120)
        svc.append("m", blk(10, 8, 4))
        svc.submit(session="m").result(timeout=120)   # warm round
        svc.close(drain=True)
        p = incremental_params(0.1, 0.1, 1e-6)
        key = BucketKey.make(10, 0, 1, p, SINGLE_TOPOLOGY,
                             kernel_path=INCREMENTAL_KERNEL_PATH)
        assert key in svc.cache.keys()

    def test_plain_sessions_keep_the_session_path(self, rng):
        R = 10
        base = obs.value("pyconsensus_serve_requests_total",
                         path="session", outcome="ok") or 0
        svc = ConsensusService(ServeConfig(batch_window_ms=1.0)).start(
            warmup=False)
        svc.create_session("m", n_reporters=R)
        svc.append("m", blk(R, 8, 5))
        svc.submit(session="m").result(timeout=120)
        assert svc.sessions.get("m").last_resolve_path == "stats"
        svc.close(drain=True)
        got = (obs.value("pyconsensus_serve_requests_total",
                         path="session", outcome="ok") or 0) - base
        assert got == 1

    def test_refresh_cadence_zero_refused_pyc101(self):
        with pytest.raises(InputError) as ei:
            ConsensusService(ServeConfig(incremental_refresh_every=0))
        assert ei.value.error_code == "PYC101"
        with pytest.raises(InputError):
            ConsensusService(ServeConfig(incremental_refresh_every=-3))
        with pytest.raises(InputError) as ei:
            MarketSession("m", 8, incremental=True, refresh_every=0)
        assert ei.value.error_code == "PYC101"

    def test_config_round_trip(self, tmp_path):
        import json

        cfg = ServeConfig(incremental_sessions=True,
                          incremental_refresh_every=7)
        path = tmp_path / "serve.json"
        path.write_text(json.dumps({
            "incremental_sessions": True,
            "incremental_refresh_every": 7}))
        loaded = ServeConfig.load(path)
        assert loaded.incremental_sessions == cfg.incremental_sessions
        assert (loaded.incremental_refresh_every
                == cfg.incremental_refresh_every)

    def test_cli_flags_parse(self, tmp_path, capsys):
        """--incremental / --no-incremental / --refresh-every thread
        through the serve CLI like the other --no-* flags."""
        from pyconsensus_tpu.serve.cli import main

        rc = main(["--warmup-only", "--shapes", "8x16",
                   "--incremental", "--refresh-every", "5"])
        assert rc == 0
        rc = main(["--warmup-only", "--shapes", "8x16",
                   "--no-incremental"])
        assert rc == 0

    def test_bench_flag_is_known(self):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        try:
            import bench
        finally:
            sys.path.pop(0)
        args = bench.build_parser().parse_args(
            ["--no-incremental", "--incremental-shape", "32x64",
             "--incremental-append-sizes", "2,4",
             "--incremental-samples", "2"])
        assert bench._incremental_block(args) is None


# -- durability: ledger aux, replay, fleet takeover ------------------------


class TestDurability:
    def test_ledger_aux_round_trips_warm_state(self, rng, tmp_path):
        R = 8
        ledger = ReputationLedger(n_reporters=R)
        sess = MarketSession("m", R, ledger=ledger, incremental=True,
                             refresh_every=5)
        for k in range(3):
            sess.append(blk(R, 8, 20 + k))
            sess.resolve()
        assert "incremental_warm_u" in ledger.aux
        ledger.save(tmp_path / "state.npz")
        resumed_ledger = ReputationLedger.load(tmp_path / "state.npz")
        resumed = MarketSession("m", R, ledger=resumed_ledger,
                                incremental=True, refresh_every=5)
        np.testing.assert_array_equal(resumed._warm_u, sess._warm_u)
        assert resumed._rounds_since_exact == sess._rounds_since_exact
        # and the next round is bit-identical to the uninterrupted one
        b = blk(R, 8, 99)
        sess.append(b)
        resumed.append(b)
        a, c = sess.resolve(), resumed.resolve()
        np.testing.assert_array_equal(a["smooth_rep"], c["smooth_rep"])
        assert sess.last_resolve_path == resumed.last_resolve_path \
            == "incremental"

    def test_plain_session_writes_no_aux(self, rng, tmp_path):
        R = 8
        ledger = ReputationLedger(n_reporters=R)
        sess = MarketSession("m", R, ledger=ledger)
        sess.append(blk(R, 8, 1))
        sess.resolve()
        assert ledger.aux == {}
        ledger.save(tmp_path / "s.npz")
        assert ReputationLedger.load(tmp_path / "s.npz").aux == {}

    def test_corrupt_warm_aux_refused(self, rng, tmp_path):
        R = 8
        ledger = ReputationLedger(n_reporters=R)
        ledger.aux["incremental_warm_u"] = np.zeros(R + 3)  # wrong roster
        ledger.save(tmp_path / "s.npz")
        bad = ReputationLedger.load(tmp_path / "s.npz")
        with pytest.raises(CheckpointCorruptionError):
            MarketSession("m", R, ledger=bad, incremental=True)

    def test_nonfinite_aux_refused_at_load(self, rng, tmp_path):
        R = 8
        ledger = ReputationLedger(n_reporters=R)
        ledger.aux["incremental_warm_u"] = np.full(R, np.nan)
        ledger.save(tmp_path / "s.npz")
        with pytest.raises(CheckpointCorruptionError):
            ReputationLedger.load(tmp_path / "s.npz")

    def test_replay_continues_warm_trajectory(self, rng, tmp_path):
        R = 10
        a = DurableSession.create(str(tmp_path / "a"), "m", R,
                                  incremental=True, refresh_every=5)
        twin = DurableSession.create(str(tmp_path / "b"), "m", R,
                                     incremental=True, refresh_every=5)
        for k in range(3):
            b = blk(R, 8, 300 + k)
            a.append(b)
            twin.append(b)
            np.testing.assert_array_equal(
                a.resolve()["smooth_rep"],
                twin.resolve()["smooth_rep"])
        replayed = replay_session(str(tmp_path / "a"), "m")
        assert replayed.incremental and replayed.refresh_every == 5
        np.testing.assert_array_equal(replayed._warm_u, twin._warm_u)
        assert replayed._rounds_since_exact == twin._rounds_since_exact
        for k in range(3, 6):
            b = blk(R, 8, 300 + k)
            replayed.append(b)
            twin.append(b)
            got, ref = replayed.resolve(), twin.resolve()
            assert replayed.last_resolve_path == twin.last_resolve_path
            np.testing.assert_array_equal(got["smooth_rep"],
                                          ref["smooth_rep"])
            np.testing.assert_array_equal(got["outcomes_adjusted"],
                                          ref["outcomes_adjusted"])

    def test_midround_sigkill_replay_bit_identical(self, tmp_path):
        """The satellite's chaos leg, on the fleet_worker harness: an
        INCREMENTAL durable session SIGKILLed mid-round replays onto a
        standby and finishes with bits identical to the never-killed
        run — warm rounds included (the warm eigenstate rides the
        ledger aux checkpoint)."""
        log_root = tmp_path / "log"
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "fleet_worker.py")
        env = worker_env()
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, script, str(log_root), "mkt", "4", "0.1",
             "3"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            deadline = time.monotonic() + 180
            seen = []
            # kill inside round 2: a WARM round (round 1 was warm, the
            # eigenstate is live) with a partial journal ahead
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    pytest.fail("worker exited early:\n" + "".join(seen))
                seen.append(line)
                if line.startswith("APPEND 2"):
                    break
            else:
                pytest.fail("worker never reached round 2:\n"
                            + "".join(seen))
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == -signal.SIGKILL

        standby = replay_session(log_root, "mkt")
        assert standby.incremental and standby.refresh_every == 3
        got = []
        for k in range(standby.ledger.round, 4):
            for j in range(len(standby._blocks), BLOCKS_PER_ROUND):
                standby.append(make_block(k, j))
            got.append(standby.resolve())

        ref_session = MarketSession("ref", N_REPORTERS, incremental=True,
                                    refresh_every=3)
        ref = []
        for k in range(4):
            for j in range(BLOCKS_PER_ROUND):
                ref_session.append(make_block(k, j))
            ref.append(ref_session.resolve())
        for g, r in zip(got, ref[-len(got):]):
            np.testing.assert_array_equal(
                np.asarray(g["smooth_rep"]), np.asarray(r["smooth_rep"]))
            np.testing.assert_array_equal(
                np.asarray(g["outcomes_adjusted"]),
                np.asarray(r["outcomes_adjusted"]))
            assert int(np.asarray(g["iterations"])) == int(
                np.asarray(r["iterations"]))
        np.testing.assert_array_equal(
            standby.reputation, np.asarray(ref[-1]["smooth_rep"]))
        assert standby.last_resolve_path == ref_session.last_resolve_path


class TestFleetTakeover:
    def test_takeover_resumes_warm_session_bit_identical(self, rng,
                                                         tmp_path):
        """Kill the worker owning a WARM incremental session
        mid-trajectory: the standby adopts via verify+replay and every
        remaining round is bit-identical to a never-killed durable twin
        (warm path labels included)."""
        fleet = ConsensusFleet(FleetConfig(
            n_workers=3, log_dir=str(tmp_path / "log"),
            worker=ServeConfig(warmup=(), batch_window_ms=1.0,
                               incremental_sessions=True,
                               incremental_refresh_every=4))).start(
            warmup=False)
        twin = DurableSession.create(str(tmp_path / "twin"), "mkt", 12,
                                     incremental=True, refresh_every=4)
        try:
            fleet.create_session("mkt", n_reporters=12)
            for k in range(2):
                b = blk(12, 8, 600 + k)
                fleet.append("mkt", b)
                twin.append(b)
                got = fleet.submit(session="mkt").result(timeout=120)
                ref = twin.resolve()
                np.testing.assert_array_equal(
                    np.asarray(got["agents"]["smooth_rep"]),
                    np.asarray(ref["smooth_rep"]))
            owner = fleet.owner_of("mkt")
            fleet.kill_worker(owner)
            for k in range(2, 5):
                b = blk(12, 8, 600 + k)
                fleet.append("mkt", b)
                twin.append(b)
                got = fleet.submit(session="mkt").result(timeout=120)
                ref = twin.resolve()
                np.testing.assert_array_equal(
                    np.asarray(got["agents"]["smooth_rep"]),
                    np.asarray(ref["smooth_rep"]))
                np.testing.assert_array_equal(
                    np.asarray(got["events"]["outcomes_adjusted"]),
                    np.asarray(ref["outcomes_adjusted"]))
            new_owner = fleet.owner_of("mkt")
            assert new_owner != owner
            live = fleet.workers[new_owner].service.sessions.get("mkt")
            assert live.last_resolve_path == twin.last_resolve_path
        finally:
            fleet.close(drain=True)
