#!/usr/bin/env python
"""Compare two bench artifacts block by block (ISSUE 18 satellite).

Bench JSON (``bench.py`` stdout, or a ``BENCH_r*.json`` wrapper whose
payload sits under ``"parsed"``) is a tree of probe blocks. Two runs of
the same commit should agree on every DIGEST exactly (bit-determinism
is the repo's contract — a digest drift is a correctness regression,
never noise) and on every NUMERIC leaf within an honest tolerance
(throughput numbers wobble; digests do not). This tool encodes that
split:

- **digest keys** (any key containing ``digest`` — e.g. the pipeline
  block's ``digest_match``, the economy block's ``mechanism_digest``)
  must match EXACTLY: any mismatch exits 1 regardless of flags.
- **numeric leaves** drift within ``--rtol``/``--atol``; out-of-band
  drift is reported, and fails the run only with ``--fail-on-drift``.
- **structure** (a block present in one artifact only, a string that
  changed) is reported as a note — growth PRs add blocks; that is not
  a regression.

Usage::

    python tools/bench_diff.py BENCH_r07.json BENCH_r08.json
    python tools/bench_diff.py a.json b.json --rtol 0.5 --fail-on-drift
    python tools/bench_diff.py a.json b.json --blocks pipeline,serve

Exit code: 0 = digests match (and drift within band, with
``--fail-on-drift``); 1 = digest mismatch or gated drift; 2 = unusable
input.
"""

from __future__ import annotations

import argparse
import json
import math
import sys

__all__ = ["diff_blocks", "main"]


def _load(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: top level is not a JSON object")
    # BENCH_r*.json wraps the bench stdout under "parsed"
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def diff_blocks(a, b, rtol: float, atol: float, path: str = "") -> list:
    """Recursive aligned walk; returns findings as dicts with ``kind``
    in {"digest", "drift", "changed", "only_a", "only_b"}. Iteration is
    sorted throughout — the report is a serialized artifact and must
    not depend on dict order."""
    out: list = []
    if isinstance(a, dict) and isinstance(b, dict):
        for k in sorted(set(a) | set(b)):
            sub = f"{path}/{k}"
            if k not in a:
                out.append({"kind": "only_b", "path": sub})
            elif k not in b:
                out.append({"kind": "only_a", "path": sub})
            else:
                out.extend(diff_blocks(a[k], b[k], rtol, atol, sub))
        return out
    if isinstance(a, list) and isinstance(b, list):
        for i in range(max(len(a), len(b))):
            sub = f"{path}[{i}]"
            if i >= len(a):
                out.append({"kind": "only_b", "path": sub})
            elif i >= len(b):
                out.append({"kind": "only_a", "path": sub})
            else:
                out.extend(diff_blocks(a[i], b[i], rtol, atol, sub))
        return out
    # leaves -----------------------------------------------------------
    key = path.rsplit("/", 1)[-1]
    if "digest" in key:
        if a != b:
            out.append({"kind": "digest", "path": path,
                        "a": a, "b": b})
        return out
    if _is_number(a) and _is_number(b):
        fa, fb = float(a), float(b)
        if math.isnan(fa) and math.isnan(fb):
            return out
        if abs(fa - fb) > atol + rtol * max(abs(fa), abs(fb)):
            rel = (abs(fa - fb) / max(abs(fa), abs(fb))
                   if max(abs(fa), abs(fb)) > 0 else math.inf)
            out.append({"kind": "drift", "path": path, "a": a, "b": b,
                        "rel": round(rel, 4)})
        return out
    if a != b:
        out.append({"kind": "changed", "path": path, "a": a, "b": b})
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two bench JSON artifacts block by block: "
                    "digests must match exactly, numerics within "
                    "tolerance (ISSUE 18 satellite)")
    ap.add_argument("a", help="first bench artifact (baseline)")
    ap.add_argument("b", help="second bench artifact (candidate)")
    ap.add_argument("--rtol", type=float, default=0.5,
                    help="relative tolerance for numeric leaves "
                         "(default 0.5 — throughput wobbles; tighten "
                         "for controlled environments)")
    ap.add_argument("--atol", type=float, default=1e-9,
                    help="absolute tolerance floor for numeric leaves")
    ap.add_argument("--blocks", default=None,
                    help="comma-separated top-level blocks to compare "
                         "(default: every block present in either)")
    ap.add_argument("--fail-on-drift", action="store_true",
                    help="numeric drift beyond tolerance also exits 1 "
                         "(digest mismatches always do)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the findings as JSON instead of text")
    args = ap.parse_args(argv)

    try:
        a, b = _load(args.a), _load(args.b)
    except (OSError, ValueError) as exc:
        print(f"ERROR: {exc}", file=sys.stderr)
        return 2

    if args.blocks:
        keep = [s.strip() for s in args.blocks.split(",") if s.strip()]
        a = {k: a[k] for k in keep if k in a}
        b = {k: b[k] for k in keep if k in b}

    findings = diff_blocks(a, b, args.rtol, args.atol)
    digests = [f for f in findings if f["kind"] == "digest"]
    drifts = [f for f in findings if f["kind"] == "drift"]
    notes = [f for f in findings if f["kind"] in ("changed", "only_a",
                                                  "only_b")]
    if args.as_json:
        print(json.dumps({"digest_mismatches": digests,
                          "drift": drifts, "notes": notes,
                          "rtol": args.rtol, "atol": args.atol},
                         indent=2, sort_keys=True))
    else:
        for f in digests:
            print(f"DIGEST MISMATCH {f['path']}: "
                  f"{f['a']!r} != {f['b']!r}")
        for f in drifts:
            print(f"drift {f['path']}: {f['a']} -> {f['b']} "
                  f"(rel {f['rel']})")
        for f in notes:
            if f["kind"] == "changed":
                print(f"note {f['path']}: {f['a']!r} -> {f['b']!r}")
            else:
                which = "first" if f["kind"] == "only_a" else "second"
                print(f"note {f['path']}: only in {which} artifact")
        print(f"{len(digests)} digest mismatch(es), {len(drifts)} "
              f"numeric drift(s) beyond rtol={args.rtol}, "
              f"{len(notes)} structural note(s)")
    if digests:
        return 1
    if drifts and args.fail_on_drift:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
