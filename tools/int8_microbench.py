"""Micro-A/B for int8 vs bf16 NaN-threaded storage in the covariance sweep.

The power-iteration sweep is the pipeline's dominant phase and is purely
HBM-bandwidth-bound (docs/PERFORMANCE.md "Where the time goes"), so storage
bytes/entry set its speed. Binary/categorical reports take values in
{0, 0.5, 1} (+NaN for absence) — exactly representable in an int8 encoding
``stored = round(2 * value)`` with sentinel ``-1`` for NaN — so an int8
storage mode halves the sweep's traffic vs bf16 with ZERO quantization
error on the workload the headline benchmark runs.

This tool times ``apply_weighted_cov`` (the per-sweep kernel) on the same
matrix in bf16-NaN-threaded vs int8-sentinel storage and checks the
results agree to f32 accumulation noise. Run it on a quiet chip BEFORE
wiring int8 into the pipeline — if the kernel doesn't beat bf16 here,
nothing downstream is worth the complexity.

Usage: python tools/int8_microbench.py [--reporters 10000] [--events 100000]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reporters", type=int, default=10_000)
    ap.add_argument("--events", type=int, default=100_000)
    ap.add_argument("--na-frac", type=float, default=0.02)
    ap.add_argument("--iters", type=int, default=30,
                    help="sweeps per timed run (differential timing: "
                    "(t(iters) - t(1)) / (iters - 1) cancels dispatch/fetch)")
    args = ap.parse_args()
    if args.iters < 2:
        ap.error("--iters must be >= 2 (differential timing needs two "
                 "run lengths)")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pyconsensus_tpu.ops.pallas_kernels import apply_weighted_cov

    R, E = args.reporters, args.events
    interp = jax.default_backend() != "tpu"

    @jax.jit
    def gen(key):
        # both encodings built in ONE jit so the f32 intermediates are
        # freed at return — holding reports/vals/bf16/int8 live at once
        # OOMed a 16 GB chip at the default shape
        k1, k2 = jax.random.split(key)
        codes = jax.random.randint(k1, (R, E), 0, 3).astype(jnp.int8)
        na = jax.random.bernoulli(k2, args.na_frac, (R, E))
        x_int8 = jnp.where(na, jnp.int8(-1), codes)
        x_bf16 = jnp.where(na, jnp.nan,
                           codes.astype(jnp.bfloat16) * 0.5)
        return x_bf16, x_int8

    x_bf16, x_int8 = gen(jax.random.key(0))
    rep = jnp.full((R,), 1.0 / R, dtype=jnp.float32)

    # fill vector + mu as the pipeline computes them (values don't matter
    # for timing; correctness cross-check uses the same ones for both paths)
    fill = jnp.full((E,), 0.5, dtype=jnp.float32)
    filled_mu = jnp.nanmean(x_bf16.astype(jnp.float32), axis=0)
    v = jnp.ones((E,), dtype=jnp.float32)

    @jax.jit
    def sweep_n(x, n):
        def body(i, vv):
            y = apply_weighted_cov(x, filled_mu, rep, vv, fill=fill,
                                   interpret=interp)
            return y / jnp.linalg.norm(y)
        return jax.lax.fori_loop(0, n, body, v)

    def timed(x, n):
        out = sweep_n(x, n)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = sweep_n(x, n)
        # honest completion barrier through the tunnel: fetch a scalar
        float(np.asarray(out[0]))
        return time.perf_counter() - t0

    results = {}
    for name, x in (("bf16", x_bf16), ("int8", x_int8)):
        try:
            t1 = timed(x, 1)
            tn = timed(x, args.iters)
            per_sweep_ms = (tn - t1) / (args.iters - 1) * 1e3
            y = np.asarray(sweep_n(x, 4))
            results[name] = {"per_sweep_ms": round(per_sweep_ms, 3),
                             "loading_head": [float(f) for f in y[:3]]}
        except Exception as e:  # compile failure is a result, not a crash
            results[name] = {"error": f"{type(e).__name__}: {e}"[:300]}

    if "error" not in results.get("bf16", {}) and \
       "error" not in results.get("int8", {}):
        a = np.asarray(sweep_n(x_bf16, 4))
        b = np.asarray(sweep_n(x_int8, 4))
        diff = float(np.max(np.abs(a - b)))
        results["max_loading_diff"] = diff
        if diff <= 1e-5:
            results["speedup"] = round(
                results["bf16"]["per_sweep_ms"]
                / max(results["int8"]["per_sweep_ms"], 1e-9), 3)
        else:
            # never bank a speedup for a kernel that computes the wrong
            # thing — a large diff means the int8 decode is broken
            results["error"] = (f"int8 loading disagrees with bf16 by "
                                f"{diff:.3e} (> 1e-5) — decode broken; "
                                f"speedup withheld")
    print(json.dumps(results))
    if "error" in results or any(
            isinstance(v, dict) and "error" in v for v in results.values()):
        sys.exit(1)


if __name__ == "__main__":
    main()
