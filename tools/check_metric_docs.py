#!/usr/bin/env python
"""Metric-name drift check: code vs docs/OBSERVABILITY.md (ISSUE 9).

Every metric the package emits through the obs registry
(``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` with a literal
``pyconsensus_*`` name) must have a row in docs/OBSERVABILITY.md's
catalog tables, and every cataloged row must correspond to a metric the
code can actually emit. PRs 3-8 each grew both sides by hand; this
script is what CI trusts instead (tools/ci_rehearsal.sh runs it, and
tests/test_concurrency.py pins the live tree clean).

Zero dependencies; importable — :func:`check` returns the drift lists
so the test suite can assert on them directly.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = pathlib.Path(__file__).resolve().parents[1]
PACKAGE = REPO / "pyconsensus_tpu"
CATALOG = REPO / "docs" / "OBSERVABILITY.md"

#: obs registration entry points whose first literal argument names a
#: metric (module functions and Registry methods share these names)
_REGISTER_CALLS = {"counter", "gauge", "histogram"}

#: full backticked metric names inside a catalog table row — a row may
#: catalog several related metrics in one cell (``...hits_total`` /
#: ``...misses_total``), but each must be spelled out in full: the
#: whole point is that a grep for the emitted name finds its row
_NAME_RE = re.compile(r"`(pyconsensus_\w+)`")


def collect_emitted(package: pathlib.Path = PACKAGE
                    ) -> Dict[str, List[str]]:
    """{metric name: [registration sites]} for every literal
    ``pyconsensus_*`` name passed to a counter/gauge/histogram call
    anywhere in the package source."""
    out: Dict[str, List[str]] = {}
    for path in sorted(package.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"),
                             filename=str(path))
        except SyntaxError:
            continue
        try:
            rel = path.relative_to(REPO).as_posix()
        except ValueError:
            rel = path.name
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name not in _REGISTER_CALLS or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and arg.value.startswith("pyconsensus_"):
                out.setdefault(arg.value, []).append(f"{rel}:{node.lineno}")
    return out


def collect_documented(catalog: pathlib.Path = CATALOG) -> Set[str]:
    """Metric names appearing (backticked, in full) in catalog table
    rows of docs/OBSERVABILITY.md."""
    names: Set[str] = set()
    for line in catalog.read_text(encoding="utf-8").splitlines():
        if line.strip().startswith("|"):
            names.update(_NAME_RE.findall(line))
    return names


def check() -> Tuple[List[str], List[str], Dict[str, List[str]]]:
    """(undocumented, unemitted, emitted-sites). Empty lists = green."""
    emitted = collect_emitted()
    documented = collect_documented()
    undocumented = sorted(set(emitted) - documented)
    unemitted = sorted(documented - set(emitted))
    return undocumented, unemitted, emitted


def main() -> int:
    undocumented, unemitted, emitted = check()
    for name in undocumented:
        print(f"DRIFT: metric {name!r} is registered at "
              f"{', '.join(emitted[name])} but has no row in "
              f"{CATALOG.relative_to(REPO)}")
    for name in unemitted:
        print(f"DRIFT: {CATALOG.relative_to(REPO)} catalogs {name!r} "
              f"but no obs registration in the package emits it")
    if undocumented or unemitted:
        return 1
    print(f"metric docs in sync: {len(emitted)} emitted metric(s) all "
          f"cataloged, no dead catalog rows")
    return 0


if __name__ == "__main__":
    sys.exit(main())
