"""Round-5 (VERDICT r4 item 1): first on-chip evidence for the streaming /
out-of-core subsystem — the flagship long-context analogue had bit-parity
tests on CPU but had never once run on real TPU hardware.

Three stages, each banked to ``--out`` (docs/MEASUREMENTS_r05.json) as it
completes, riskiest last per the wedge post-mortem:

1. ``parity``  — reduced shape (10k x 50k, in-memory): streaming outcomes
   vs the in-memory sharded resolution, on chip.
2. ``bench``   — streaming at the bench shape (10k x 100k) from an
   in-memory host array: wall latency + panel count, for sztorc.
3. ``beyond``  — the beyond-HBM shape (default 10k x 500k f32 = 20 GB >
   the chip's 16 GB HBM), staged once as an ``.npy`` and memory-mapped;
   resolved for sztorc + fixed-variance + dbscan-jit.

Every stage runs in THIS process (the shapes are deliberate, no fail-soft
ladder): run AFTER the round's bench numbers are banked — a wedged tunnel
afterwards costs probing time, not artifacts.

Usage: python tools/streaming_tpu.py [--stage parity,bench,beyond]
           [--rows 10000] [--cols 500000] [--panel 8192]
           [--out docs/MEASUREMENTS_r05.json] [--keep-npy]
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

import numpy as np  # noqa: E402


def _bank(out_path: pathlib.Path, entry: dict) -> None:
    """Upsert one measurement into the bank (tools/tpu_measurements.py's
    keyed-on-_name convention)."""
    results = []
    if out_path.exists():
        try:
            results = [m for m in json.loads(out_path.read_text())
                       if isinstance(m, dict)]
        except ValueError:
            results = []
    for i, m in enumerate(results):
        if m.get("_name") == entry["_name"]:
            results[i] = entry
            break
    else:
        results.append(entry)
    out_path.write_text(json.dumps(results, indent=1) + "\n")
    print(f"banked {entry['_name']} -> {out_path}", flush=True)


def _gen_host(rng, R, E, na_frac=0.02):
    """Binary-lattice synthetic reports, generated host-side in one shot
    (used for the in-memory stages)."""
    r = rng.random((R, E), dtype=np.float32)
    reports = np.where(r < 0.45, 0.0, np.where(r < 0.95, 1.0, 0.5)
                       ).astype(np.float32)
    reports[rng.random((R, E)) < na_frac] = np.nan
    return reports


def _write_big_npy(path, R, E, chunk_cols=16384, na_frac=0.02):
    """Stage the beyond-HBM matrix to disk column-chunk-wise — peak host
    memory stays one (R, chunk) block."""
    rng = np.random.default_rng(0)
    mm = np.lib.format.open_memmap(path, mode="w+", dtype=np.float32,
                                   shape=(R, E))
    t0 = time.time()
    for start in range(0, E, chunk_cols):
        stop = min(start + chunk_cols, E)
        mm[:, start:stop] = _gen_host(rng, R, stop - start, na_frac)
    mm.flush()
    del mm
    print(f"staged {path} ({R}x{E} f32, "
          f"{R * E * 4 / 1e9:.1f} GB) in {time.time() - t0:.0f}s",
          flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", default="parity,bench,beyond")
    ap.add_argument("--rows", type=int, default=10_000)
    ap.add_argument("--cols", type=int, default=500_000)
    ap.add_argument("--panel", type=int, default=8192)
    ap.add_argument("--out", default=str(ROOT / "docs/MEASUREMENTS_r05.json"))
    ap.add_argument("--npy", default=str(ROOT / "bench_data_beyond_hbm.npy"))
    ap.add_argument("--keep-npy", action="store_true")
    args = ap.parse_args()
    stages = set(args.stage.split(","))
    out_path = pathlib.Path(args.out)

    import jax

    from pyconsensus_tpu.models.pipeline import ConsensusParams
    from pyconsensus_tpu.parallel import (make_mesh, sharded_consensus,
                                          streaming_consensus)

    backend = jax.default_backend()
    print(f"backend: {backend} ({jax.devices()})", flush=True)
    R = args.rows

    if "parity" in stages:
        E = 50_000
        reports = _gen_host(np.random.default_rng(1), R, E)
        p = ConsensusParams(algorithm="sztorc", has_na=True)
        mesh = make_mesh(batch=1, event=len(jax.devices()))
        t0 = time.time()
        mem = sharded_consensus(reports, mesh=mesh, params=p)
        mem_out = np.asarray(mem["outcomes_adjusted"])
        t_mem = time.time() - t0
        t0 = time.time()
        stream = streaming_consensus(reports, panel_events=args.panel,
                                     params=p)
        t_stream = time.time() - t0
        flips = int((np.asarray(stream["outcomes_adjusted"])
                     != mem_out).sum())
        rep_gap = float(np.max(np.abs(
            np.asarray(stream["smooth_rep"], dtype=float)
            - np.asarray(mem["smooth_rep"], dtype=float))))
        _bank(out_path, {
            "_name": "streaming_parity_onchip",
            "backend": backend, "shape": [R, E],
            "panel_events": args.panel,
            "outcome_flips_vs_inmemory": flips,
            "max_smooth_rep_gap": rep_gap,
            "in_memory_s": round(t_mem, 3),
            "streaming_s": round(t_stream, 3),
            "_note": "streaming vs in-memory sharded resolution on the "
                     "real chip at a reduced shape (both include "
                     "compile+ingest; parity is the point here)"})
        assert flips == 0, f"{flips} outcome flips vs in-memory"

    if "bench" in stages:
        E = 100_000
        reports = _gen_host(np.random.default_rng(2), R, E)
        p = ConsensusParams(algorithm="sztorc", has_na=True)
        # warm (compile) once, then measure the steady resolution
        streaming_consensus(reports, panel_events=args.panel, params=p)
        t0 = time.time()
        out = streaming_consensus(reports, panel_events=args.panel,
                                  params=p)
        t1 = time.time() - t0
        _bank(out_path, {
            "_name": "streaming_bench_shape_onchip",
            "backend": backend, "shape": [R, E],
            "panel_events": args.panel,
            "n_panels_per_pass": -(-E // args.panel),
            "latency_s": round(t1, 3),
            "avg_certainty": float(np.asarray(out["avg_certainty"])),
            "_note": "streaming sztorc at the bench shape from a host "
                     "array (warm; includes per-panel host->device "
                     "ingest through the tunnel every pass — the price "
                     "of out-of-core)"})

    if "beyond" in stages:
        E = args.cols
        npy = pathlib.Path(args.npy)
        if not npy.exists():
            _write_big_npy(npy, R, E)
        try:
            for algo in ("sztorc", "fixed-variance", "dbscan-jit"):
                p = ConsensusParams(algorithm=algo, has_na=True)
                t0 = time.time()
                out = streaming_consensus(str(npy), panel_events=args.panel,
                                          params=p)
                t1 = time.time() - t0
                outc = np.asarray(out["outcomes_adjusted"])
                ok = bool(np.isin(outc, [0.0, 0.5, 1.0]).all())
                _bank(out_path, {
                    "_name": f"streaming_beyond_hbm_{algo}",
                    "backend": backend, "shape": [R, E],
                    "panel_events": args.panel,
                    "matrix_gb": round(R * E * 4 / 1e9, 1),
                    "latency_s": round(t1, 3),
                    "outcomes_snapped": ok,
                    "avg_certainty": float(np.asarray(out["avg_certainty"])),
                    "_note": "BEYOND-HBM out-of-core resolution on the "
                             "real chip (matrix > 16 GB HBM), npy "
                             "memory-mapped, cold (includes compile + "
                             "full disk read + tunnel ingest)"})
                assert ok, f"{algo}: unsnapped binary outcomes"
        finally:
            if not args.keep_npy:
                npy.unlink(missing_ok=True)


if __name__ == "__main__":
    main()
