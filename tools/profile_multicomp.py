"""Round-5 (VERDICT r4 item 2): where does multi-component time go at the
north-star width?

Differential chain timing (docs/PERFORMANCE.md methodology) of the
fixed-variance storage path at 10k x 100k int8 pre-encoded: the orth-iter
at a FORCED sweep count vs the production Ritz-exit loop pins both the
per-sweep cost and the effective sweep count; the full pipeline row says
what everything around the spectrum costs. Each per-sweep row prints
next to its HBM byte roofline AND its VPU-compute estimate — the one-pass
block kernel does ~2(k+1) fused mul-adds per element, so at k ~ 6 the
sweep is compute-bound, not bandwidth-bound, and the roofline argument
for the sztorc gap does not transfer.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                             _consensus_core_fused,
                                             _fill_stats, encode_reports)
from pyconsensus_tpu.models.sztorc import fixed_variance_scores_storage
from pyconsensus_tpu.ops.jax_kernels import _top_pcs_orth_iter
from bench import generate_reports_device

R, E = 10_000, 100_000
HBM_GBPS = 819e9

gen = jax.jit(generate_reports_device, static_argnums=(1, 2))
reports_f32 = gen(jax.random.key(0), R, E, 0.02, 0.1, 0.05)
enc = jax.jit(encode_reports)(reports_f32)
jax.block_until_ready(enc)
rep0 = jnp.full((R,), 1.0 / R)
scaled = jnp.zeros((E,), bool)
zeros = jnp.zeros((E,))
ones = jnp.ones((E,))

prep = jax.jit(lambda x, r: _fill_stats(x, r, 0.1, "int8"))
x_s, fill_s, tw_s, numer_s = prep(enc, rep0)
mu1 = numer_s + (1.0 - tw_s) * fill_s
denom = 1.0 - jnp.sum(rep0 ** 2)
jax.block_until_ready(x_s)

from pyconsensus_tpu.models.sztorc import fixed_variance_k  # noqa: E402

k = fixed_variance_k(R, E, 5)
print(f"shape {R}x{E}, int8 pre-encoded, fixed-variance k={k}", flush=True)


def timeit(fn, *args, n=8, pick=None):
    pick = pick or (lambda o: o)
    float(np.asarray(pick(fn(*args))))
    t0 = time.perf_counter()
    float(np.asarray(pick(fn(*args))))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [pick(fn(*args)) for _ in range(n + 1)]
    float(np.asarray(jnp.stack(outs).sum()))
    tN = time.perf_counter() - t0
    return (tN - t1) / n


def orth_at(n_iters):
    @jax.jit
    def f(x, mu, dn, rep, fill):
        loadings, eig, total, scores = _top_pcs_orth_iter(
            x, mu, dn, rep, k, n_iters=n_iters, fill=fill)
        out = jnp.sum(loadings) + jnp.sum(eig)
        if scores is not None:
            out = out + jnp.sum(scores)
        return out
    return f


# NOTE on estimator validity (code-review r5): the loop's Ritz/alignment
# early exit applies at ANY n_iters cap, so a marginal between two caps
# is only a true per-sweep cost when BOTH caps sit below the natural
# exit point (~16 here); tiny caps (1-4) also compile pathologically in
# isolation (the stats-chain effect — docs/PERFORMANCE.md r5). Hence
# (t12 - t8)/4: both forced, both real-sized.
t8 = timeit(orth_at(8), x_s, mu1, denom, rep0, fill_s)
t12 = timeit(orth_at(12), x_s, mu1, denom, rep0, fill_s)
t_full_orth = timeit(orth_at(96), x_s, mu1, denom, rep0, fill_s)
per_sweep = (t12 - t8) / 4
n_sweeps = 8 + (t_full_orth - t8) / per_sweep if per_sweep > 0 else float(
    "nan")

roof_ms = R * E / HBM_GBPS * 1e3
print(f"orth-iter n=8/12:   {t8 * 1e3:8.2f} / {t12 * 1e3:.2f} ms "
      f"(both below the exit point: forced sweeps)", flush=True)
print(f"per sweep (12-8)/4: {per_sweep * 1e3:8.2f} ms  "
      f"(HBM roofline {roof_ms:.2f} ms; ~{2 * (k + 1)} VPU mul-adds/elem)",
      flush=True)
print(f"ritz-exit loop:     {t_full_orth * 1e3:8.2f} ms  "
      f"(~{n_sweeps:.1f} effective sweeps of the 96 budget)", flush=True)

# does the budget buy SUBSPACE convergence (not just per-column churn
# inside the statistically-interchangeable bulk — code-review r5)?
# Compare an 8-sweep cap against the production exit by principal
# angles between the spans, and by the explained-variance vector.
cap8 = jax.jit(lambda x, mu, dn, rep, fill: _top_pcs_orth_iter(
    x, mu, dn, rep, k, n_iters=8, fill=fill)[:2])
prod = jax.jit(lambda x, mu, dn, rep, fill: _top_pcs_orth_iter(
    x, mu, dn, rep, k, fill=fill)[:2])
l8, e8 = (np.asarray(v) for v in cap8(x_s, mu1, denom, rep0, fill_s))
lp, ep = (np.asarray(v) for v in prod(x_s, mu1, denom, rep0, fill_s))
cosines = np.clip(np.linalg.svd(l8.T @ lp, compute_uv=False), -1.0, 1.0)
max_angle = float(np.degrees(np.arccos(cosines.min())))
print(f"8-cap vs production: max principal angle {max_angle:.3f} deg, "
      f"eigval max rel gap "
      f"{np.max(np.abs(e8 - ep)) / max(np.max(np.abs(ep)), 1e-30):.2e}, "
      f"per-column |loading| gap "
      f"{np.max(np.abs(np.abs(l8) - np.abs(lp))):.2e}", flush=True)


@jax.jit
def fv_scores(x, fill, mu, rep):
    adj, loadings = fixed_variance_scores_storage(x, fill, mu, rep, 0.9, 5)
    return jnp.sum(adj) + jnp.sum(loadings)


t_scores = timeit(fv_scores, x_s, fill_s, mu1, rep0)
print(f"fv scores total:    {t_scores * 1e3:8.2f} ms  "
      f"(spectrum + variance combination + multi-dirfix)", flush=True)

P = ConsensusParams(algorithm="fixed-variance", max_iterations=1,
                    pca_method="power", storage_dtype="int8",
                    any_scaled=False, has_na=True, fused_resolution=True)


@jax.jit
def fv_full(x, rep, scaled, zeros, ones):
    return _consensus_core_fused(x, rep, scaled, zeros, ones, P)


t_full = timeit(fv_full, enc, rep0, scaled, zeros, ones,
                pick=lambda o: o["avg_certainty"])
print(f"FULL fixed-variance:{t_full * 1e3:8.2f} ms  "
      f"(back half = {1e3 * (t_full - t_scores):.2f} ms beyond scores)",
      flush=True)
