"""On-chip content-fuzz of the production compact-storage paths.

Complements the CPU-mesh test suite (which interpret-mode Pallas cannot
protect — see tests/test_mosaic_compat.py for why) by running MANY random
matrices at a FIXED shape per config on the real TPU: one compile each,
then every resolution is a warm fast call.

Two contracts, matching what the framework actually promises:

1. **Storage parity (hard)** — ``storage_dtype`` in {bfloat16, int8} must
   add NOTHING on top of the plain f32 pipeline: snapped outcomes
   bit-identical to the same-strategy f32 resolution and smooth_rep
   within kernel noise. (int8 rides the fused power path, so its f32
   comparator pins ``pca_method="power"`` — the residual is the measured
   ~2e-6 fused-kernel-vs-XLA relative error, not storage.)

2. **Cross-precision envelope (statistical)** — f32 chip resolutions vs
   the numpy f64 reference. Iterated redistribution amplifies f32 noise
   (~1e-3/iteration in this_rep at small R; a near-tie decision can
   multiply it 30x — measured on seed 46, 2026-08-01, eigengap healthy
   so NOT a conditioning pathology), so snapped outcomes may differ
   near catch edges. The hard assertions mirror
   tests/test_f32_mode.py's documented f32 contract: a mismatch must
   never be an OPPOSITE flip (0<->1 — only adjacent 0/1<->0.5 drift),
   and smooth_rep must stay inside a coarse envelope. Mismatch counts
   are reported for trend-watching, not failed.

At north-star scale (large R) the raw statistics concentrate away from
catch edges and the bench's every-run bit-parity assert holds
empirically; this tool documents the small-R behavior honestly instead
of overclaiming (SURVEY.md §7 "bit-identical parity" hard part:
"guard with a tolerance audit in the parity harness" — this is that
audit).

Usage (real chip): ``python tools/onchip_fuzz.py [--seeds N] [--quick]``
Writes one summary JSON line to stdout; exits 1 on any hard failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from pyconsensus_tpu import Oracle
from pyconsensus_tpu.parallel.sharded import ShardedOracle

#: storage contract: snapped outcomes bit-identical to same-strategy f32;
#: smooth_rep within this of the f32 run. Measured 2026-08-01: bf16
#: exactly 0.0 (sztorc/k-means/dbscan-jit, 48x256); int8 7.8e-5 (sztorc)
#: and 1.44e-4 (ica at 4160x2048 — the nonlinear FastICA iteration
#: amplifies the ~1e-5 storage-kernel orth-iter residual pinned by
#: tests); bound sized ~3.5x the worst measurement
STORAGE_REP_ATOL = 5e-4
#: cross-precision envelope: coarse bound on |f32 - f64| smooth_rep after
#: iterated amplification (worst measured 5e-3 at 48x256, x20 headroom)
F32_REP_ENVELOPE = 1e-1


def _gen(rng, R, E):
    reports = rng.choice([0.0, 0.5, 1.0], size=(R, E))
    mask = rng.random((R, E)) < 0.15
    keep = rng.integers(0, R, size=E)
    mask[keep, np.arange(E)] = False
    reports[mask] = np.nan
    reputation = rng.random(R) + 0.05 if rng.random() < 0.5 else None
    return reports, reputation


def run_config(algo, storage, R, E, seeds):
    hard_fails = 0
    f32_mismatch_seeds = 0
    worst_storage_gap = 0.0
    worst_f32_gap = 0.0
    t0 = time.time()
    for seed in range(seeds):
        rng = np.random.default_rng(777000 + seed)
        reports, reputation = _gen(rng, R, E)
        kw = dict(algorithm=algo, max_iterations=3)
        # the three resolutions: storage-dtype jax, same-strategy f32 jax,
        # numpy f64 reference
        if storage == "int8":
            rs = ShardedOracle(reports=reports, reputation=reputation,
                               backend="jax", storage_dtype="int8",
                               pca_method="power-fused", **kw).consensus()
            rf = Oracle(reports=reports, reputation=reputation,
                        backend="jax", pca_method="power", **kw).consensus()
        else:
            rs = Oracle(reports=reports, reputation=reputation,
                        backend="jax", storage_dtype=storage,
                        **kw).consensus()
            rf = Oracle(reports=reports, reputation=reputation,
                        backend="jax", **kw).consensus()
        rn = Oracle(reports=reports, reputation=reputation,
                    backend="numpy", **kw).consensus()

        def arr(r, key, sec="events"):
            return np.asarray(r[sec][key], float)

        bad = False
        # contract 1: storage adds nothing on top of f32
        gap_s = float(np.abs(arr(rs, "smooth_rep", "agents")
                             - arr(rf, "smooth_rep", "agents")).max())
        worst_storage_gap = max(worst_storage_gap, gap_s)
        snap_s = int((arr(rs, "outcomes_final")
                      != arr(rf, "outcomes_final")).sum())
        if snap_s or gap_s > STORAGE_REP_ATOL:
            bad = True
            print(f"  STORAGE-FAIL {algo}/{storage} seed={seed}: "
                  f"{snap_s} snap diffs vs f32, rep gap {gap_s:.2e}",
                  file=sys.stderr)
        # contract 2: f32 vs f64 envelope — no opposite flips
        fn, ff = arr(rn, "outcomes_final"), arr(rf, "outcomes_final")
        gap_f = float(np.abs(arr(rf, "smooth_rep", "agents")
                             - arr(rn, "smooth_rep", "agents")).max())
        worst_f32_gap = max(worst_f32_gap, gap_f)
        diffs = np.flatnonzero(fn != ff)
        if diffs.size:
            f32_mismatch_seeds += 1
        opposite = int((np.abs(fn[diffs] - ff[diffs]) == 1.0).sum())
        if opposite or gap_f > F32_REP_ENVELOPE:
            bad = True
            print(f"  F32-FAIL {algo}/{storage} seed={seed}: "
                  f"{opposite} opposite flips, rep gap {gap_f:.2e}",
                  file=sys.stderr)
        hard_fails += bad
    r = {"algo": algo, "storage": storage, "R": R, "E": E,
         "seeds": seeds, "hard_fails": int(hard_fails),
         "f32_mismatch_seeds": int(f32_mismatch_seeds),
         "worst_storage_rep_gap": worst_storage_gap,
         "worst_f32_rep_gap": worst_f32_gap,
         "seconds": round(time.time() - t0, 1)}
    print(f"{r['algo']:>15s}/{r['storage']:<9s} {r['R']}x{r['E']}: "
          f"{r['seeds']} seeds, {r['hard_fails']} hard fails, "
          f"{r['f32_mismatch_seeds']} f32-knife-edge seeds, storage gap "
          f"{r['worst_storage_rep_gap']:.2e}, f32 gap "
          f"{r['worst_f32_rep_gap']:.2e} ({r['seconds']}s)",
          file=sys.stderr, flush=True)
    return r


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=60,
                    help="seeds per small-shape config (large shapes run "
                         "seeds//5)")
    ap.add_argument("--quick", action="store_true",
                    help="small-shape configs only")
    ap.add_argument("--only", default=None,
                    help="run a single config, e.g. 'ica/int8'")
    args = ap.parse_args(argv)
    small, large = args.seeds, max(1, args.seeds // 5)
    configs = [("sztorc", "int8", 48, 256, small),
               ("sztorc", "bfloat16", 48, 256, small),
               ("k-means", "bfloat16", 48, 256, small),
               ("dbscan-jit", "bfloat16", 48, 256, small)]
    if not args.quick:
        # multi-component int8 engages only at R>_GRAM_EIGH_MAX_R, E>1024
        configs += [("ica", "int8", 4160, 2048, large),
                    ("fixed-variance", "int8", 4160, 2048, large)]
    if args.only:
        configs = [c for c in configs if f"{c[0]}/{c[1]}" == args.only]
        if not configs:
            ap.error(f"no config named {args.only!r}")
    results = [run_config(*c) for c in configs]
    total = sum(r["hard_fails"] for r in results)
    print(json.dumps({"onchip_fuzz": results, "total_hard_fails": total}))
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
