"""Same-session cross-commit A/B of the headline benchmark.

Chip throughput drifts ~15% between sessions (docs/PERFORMANCE.md
methodology: "only same-session A/Bs are meaningful"), which left the
round-2 -> round-3 headline delta (27.2 -> 23.1 res/s) unresolved: drift
or regression? This harness settles such questions the only valid way —
running the pinned commit and HEAD **interleaved in one session on the
same chip**, so drift hits both sides equally and the ratio isolates the
code change (VERDICT r3 item 3).

Mechanics: a detached ``git worktree`` of the base commit under
``.ab/<sha>`` (inside the repo, gitignored); bench.py invoked
alternately base/HEAD/base/HEAD... with identical arguments (each
invocation is bench.py's own fail-soft parent — killable probe, bounded
children, always one JSON line); medians + the HEAD/base ratio are
printed and appended to the measurements file.

Usage:
    python tools/ab_commits.py --base <commit> [--pairs 2] \
        [--out docs/MEASUREMENTS_r04.json] [-- <bench.py args...>]

Interpretation: the chip also drifts *within* a session on the minutes
scale, so treat ratios within ~5% as parity; the interleaving exists so
a real regression shows up as a CONSISTENT per-pair gap, which the
per-pair ratios printed below make visible.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--base", required=True,
                    help="commit-ish to A/B against HEAD (e.g. the prior "
                         "round's bench commit)")
    ap.add_argument("--pairs", type=int, default=2,
                    help="interleaved (base, head) bench pairs")
    ap.add_argument("--out", default="docs/MEASUREMENTS_r04.json",
                    help="measurements JSON to append the A/B entries to")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-bench --bench-timeout (the hard cap per "
                         "invocation is 3x this + 500 s, matching "
                         "tools/tpu_measurements.py's ladder math)")
    ap.add_argument("--keep-worktree", action="store_true",
                    help="leave .ab/<sha> in place for inspection")
    ap.add_argument("bench_args", nargs="*",
                    help="extra bench.py arguments (after --)")
    return ap.parse_args(argv)


def _git(*args: str) -> str:
    return subprocess.run(["git", *args], cwd=ROOT, check=True,
                          capture_output=True, text=True).stdout.strip()


def make_worktree(commit: str) -> pathlib.Path:
    sha = _git("rev-parse", "--short", commit)
    path = ROOT / ".ab" / sha
    if not path.exists():
        path.parent.mkdir(exist_ok=True)
        _git("worktree", "add", "--detach", str(path), commit)
    return path


def drop_worktree(path: pathlib.Path) -> None:
    subprocess.run(["git", "worktree", "remove", "--force", str(path)],
                   cwd=ROOT, capture_output=True, text=True)


def run_bench(tree: pathlib.Path, bench_args: list, timeout: float) -> dict:
    """One bench.py invocation from ``tree``; returns its JSON line (or an
    error dict — bench.py's fail-soft parent always prints one)."""
    cmd = [sys.executable, str(tree / "bench.py"),
           "--bench-timeout", str(timeout), *bench_args]
    hard_cap = 3 * timeout + 500
    t0 = time.time()
    try:
        r = subprocess.run(cmd, cwd=tree, capture_output=True, text=True,
                           timeout=hard_cap)
    except subprocess.TimeoutExpired:
        return {"value": 0.0, "error": f"hard cap {hard_cap:.0f}s expired"}
    line = next((ln for ln in reversed(r.stdout.splitlines())
                 if ln.lstrip().startswith("{")), None)
    if line is None:
        return {"value": 0.0, "error": f"no JSON line (rc={r.returncode}); "
                                       f"stderr tail: {r.stderr[-400:]}"}
    out = json.loads(line)
    out["_wall_s"] = round(time.time() - t0, 1)
    return out


def main(argv=None) -> int:
    args = parse_args(argv)
    head_sha = _git("rev-parse", "--short", "HEAD")
    base_sha = _git("rev-parse", "--short", args.base)
    if _git("status", "--porcelain"):
        print("note: working tree dirty — HEAD side includes uncommitted "
              "changes", file=sys.stderr)
    tree = make_worktree(args.base)
    print(f"A/B: base={base_sha} (worktree {tree.relative_to(ROOT)}) vs "
          f"HEAD={head_sha} + working tree, {args.pairs} interleaved pairs",
          flush=True)
    results = {"base": [], "head": []}
    try:
        for i in range(args.pairs):
            for side, t in (("base", tree), ("head", ROOT)):
                r = run_bench(t, args.bench_args, args.timeout)
                results[side].append(r)
                print(f"pair {i + 1} {side}: value={r.get('value')} "
                      f"({r.get('error', 'ok')}, wall {r.get('_wall_s')}s)",
                      flush=True)
    finally:
        if not args.keep_worktree:
            drop_worktree(tree)

    base_vals = [r["value"] for r in results["base"] if r.get("value")]
    head_vals = [r["value"] for r in results["head"] if r.get("value")]

    def med(xs):
        if not xs:
            return 0.0
        s, n = sorted(xs), len(xs)
        return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
    ratio = (med(head_vals) / med(base_vals)) if base_vals and head_vals \
        and med(base_vals) > 0 else None
    per_pair = [round(h["value"] / b["value"], 4)
                for b, h in zip(results["base"], results["head"])
                if b.get("value") and h.get("value")]
    verdict = {
        "_name": f"ab_{base_sha}_vs_{head_sha}",
        "base_commit": base_sha,
        "head_commit": head_sha,
        "bench_args": args.bench_args,
        "base_values": base_vals,
        "head_values": head_vals,
        "median_base": med(base_vals),
        "median_head": med(head_vals),
        "head_over_base": round(ratio, 4) if ratio else None,
        "per_pair_ratios": per_pair,
        "runs": results,
    }
    print(json.dumps({k: v for k, v in verdict.items() if k != "runs"},
                     indent=1))
    out_path = ROOT / args.out
    existing = json.loads(out_path.read_text()) if out_path.exists() else []
    existing.append(verdict)
    out_path.parent.mkdir(exist_ok=True)
    out_path.write_text(json.dumps(existing, indent=1) + "\n")
    print(f"appended to {args.out}")
    return 0 if ratio else 1


if __name__ == "__main__":
    sys.exit(main())
