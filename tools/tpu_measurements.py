"""Run the queued on-chip measurement suite and bank the results.

docs/ROADMAP.md lists the measurements that have been waiting on a live
TPU tunnel (it wedges for hours after any OOM/aborted run — see
docs/PERFORMANCE.md methodology). This script exists so that the moment
the tunnel responds, ONE command banks everything in the right order
(parity/perf first, the OOM-risky scaled-heavy shape LAST, per the
wedge post-mortem), writing machine-readable results as it goes — a
mid-suite wedge still leaves everything banked up to that point.

Usage:  python tools/tpu_measurements.py [--out docs/MEASUREMENTS_r02.json]

Each measurement is one fail-soft ``bench.py`` invocation (its parent
process never imports jax and always emits a JSON line); this runner just
sequences them — NEVER concurrently, concurrent TPU jobs plus one OOM is
the documented wedge trigger — and aggregates the JSON.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# bench.py's parent half never imports jax, so importing from it is safe
# even with a wedged tunnel — and keeps the wedge-critical probe logic
# (killable subprocess, last-line parse past libtpu banners) in ONE place
from bench import _probe_backend  # noqa: E402

#: (name, bench.py argv, timeout_s) — ordered: parity/perf first, the
#: HBM-pressure scaled-heavy shape last (docs/ROADMAP.md items a-d)
MEASUREMENTS = [
    # (d) re-confirm the headline after the round-1 late commits + round-2
    # median/indexing changes
    ("headline", [], 900),
    # (a) the explicit-fused series (the power-mono A/B ran 2026-07-31:
    # mono measured 36% slower and was deleted — docs/PERFORMANCE.md)
    ("power_fused", ["--pca-method", "power-fused"], 900),
    # (c) the multi-component variants on-chip (matrix-free orthogonal
    # iteration spectrum path; fixed-variance added round 3 — VERDICT r2
    # item 5 flagged it as never measured on chip)
    ("ica", ["--algorithm", "ica"], 1200),
    ("fixed_variance", ["--algorithm", "fixed-variance"], 1200),
    # the pure-XLA recovery rung (bench --no-pallas): the rate the ladder
    # falls back to if Mosaic ever rejects every kernel again
    ("no_pallas_xla", ["--no-pallas", "--storage-dtype", ""], 1200),
    # round 5 (VERDICT r4 item 4): eval config 4's jit clustering
    # variants on chip at the bench shape (hierarchical and the MC sweep
    # are in tools/eval45_tpu.py — hybrid/host phases don't fit bench.py)
    ("kmeans", ["--algorithm", "k-means"], 1500),
    ("dbscan_jit", ["--algorithm", "dbscan-jit"], 1500),
    # (b) blocked median at increasing scaled fractions; the >E/8 shape
    # (XLA path, biggest sort temporaries) is the OOM-riskiest → last
    ("scaled_1k", ["--scaled", "1000"], 1200),
    ("scaled_4k", ["--scaled", "4000"], 1500),
    ("scaled_16k", ["--scaled", "16000"], 1800),
    # round 5 (VERDICT r4 item 5): the scaled-MAJORITY ladder through and
    # past the 90% gather_median_pays cap — 80k rides the gather, 95k is
    # the first measurement of the full-width fallback the cap reverts
    # to. Biggest sort temporaries of the whole suite → very last.
    ("scaled_60k", ["--scaled", "60000"], 1800),
    ("scaled_80k", ["--scaled", "80000"], 1800),
    ("scaled_95k", ["--scaled", "95000"], 2400),
]


def probe(timeout: float = 90.0) -> bool:
    backend, info = _probe_backend(timeout)
    if backend is None:
        print(f"probe: {info}")
    return backend is not None and backend != "cpu"


def run_one(name: str, extra_argv: list, timeout: float) -> dict:
    cmd = [sys.executable, str(ROOT / "bench.py"),
           "--bench-timeout", str(timeout), *extra_argv]
    t0 = time.time()
    # the fail-soft parent's worst case since the round-3 ladder is
    # probe (90 s) + up to THREE bounded rung children + CPU smoke
    # (300 s); the cap must exceed that or a wedged rung 0 gets the
    # parent killed mid-ladder before it can emit its fail-soft JSON —
    # the exact zeroed-artifact outcome the ladder exists to prevent
    hard_cap = 3 * timeout + 500
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=hard_cap)
    except subprocess.TimeoutExpired:
        return {"_name": name, "_wall_s": round(time.time() - t0, 1),
                "error": f"bench.py parent exceeded {hard_cap:.0f}s "
                         f"hard cap (should be impossible — fail-soft "
                         f"parent is bounded)"}
    parsed = None
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            candidate = json.loads(line)
        except ValueError:
            continue
        if isinstance(candidate, dict):
            parsed = candidate
            break
    if parsed is None:
        parsed = {"error": f"no JSON from bench.py (rc={r.returncode})"}
    parsed["_name"] = name
    parsed["_wall_s"] = round(time.time() - t0, 1)
    if r.stderr:
        tail = r.stderr.strip().splitlines()[-2:]
        parsed["_stderr_tail"] = " | ".join(tail)
    return parsed


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(ROOT / "docs/MEASUREMENTS_r02.json"))
    ap.add_argument("--only", default="",
                    help="comma-separated subset of measurement names")
    ap.add_argument("--force", action="store_true",
                    help="skip the TPU probe and run on whatever backend "
                         "comes up (testing the orchestration on CPU)")
    ap.add_argument("--shape", nargs=2, type=int, metavar=("R", "E"),
                    help="override reporters/events for every measurement "
                         "(testing; scaled counts are clamped to E)")
    args = ap.parse_args()
    out_path = pathlib.Path(args.out)

    only = {s for s in args.only.split(",") if s}
    known = {n for n, _, _ in MEASUREMENTS}
    if only - known:
        # fail fast on a typo BEFORE burning the probe / any chip time
        print(f"unknown measurement name(s) {sorted(only - known)}; "
              f"known: {sorted(known)}")
        sys.exit(2)

    if not args.force:
        if not probe():
            print("TPU tunnel not responding — nothing measured (probe "
                  "rc!=0 or timeout; see docs/PERFORMANCE.md wedge notes)")
            sys.exit(1)
        print("TPU alive — running suite (sequential; OOM-risky shapes "
              "last)")

    # upsert into any existing bank (keyed on _name) rather than replacing
    # the file wholesale: a --only subset re-run must refresh just its own
    # entries — round 3 nearly lost six banked measurements to a partial
    # re-run truncating the file
    results = []
    if out_path.exists():
        try:
            results = [m for m in json.loads(out_path.read_text())
                       if isinstance(m, dict)]
        except ValueError:
            results = []

    def upsert(res):
        for i, m in enumerate(results):
            if m.get("_name") == res["_name"]:
                results[i] = res
                return
        results.append(res)

    measured = 0
    for name, argv, timeout in MEASUREMENTS:
        if only and name not in only:
            continue
        if args.shape:
            R, E = args.shape
            argv = list(argv)
            if "--scaled" in argv:
                i = argv.index("--scaled") + 1
                argv[i] = str(min(int(argv[i]), E))
            argv += ["--reporters", str(R), "--events", str(E),
                     "--repeats", "2", "--batches", "2"]
        print(f"--- {name}: bench.py {' '.join(argv)}", flush=True)
        res = run_one(name, argv, timeout)
        upsert(res)
        measured += 1
        # bank after EVERY measurement — a wedge mid-suite keeps the rest
        out_path.write_text(json.dumps(results, indent=1) + "\n")
        err = res.get("error")
        line = (f"    {res.get('metric')}: value={res.get('value')} "
                f"latency={res.get('latency_s')}s wall={res['_wall_s']}s")
        print(line + (f" ERROR={err}" if err else ""), flush=True)
        if err and "unavailable" in str(err):
            print("tunnel lost mid-suite — stopping (results banked)")
            break
    if not measured:
        known = ", ".join(n for n, _, _ in MEASUREMENTS)
        print(f"nothing measured — no measurement matched {args.only!r} "
              f"(known: {known}); {out_path} NOT written")
        sys.exit(1)
    print(f"wrote {out_path} ({measured} measured this run, "
          f"{len(results)} banked)")


if __name__ == "__main__":
    main()
